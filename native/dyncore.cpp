// Native hot-path kernels for the dynamo-tpu runtime: chained block hashing.
//
// Role parity: the reference keeps its per-request hash/identity hot paths in
// native code (lib/tokens is Rust; block_copy.cu is CUDA). Here the chained
// xxh3 sequence-hash loop — run for every block of every request on both the
// router and the engine — is one C call over the whole token array instead
// of a Python loop with per-block bytes assembly.
//
// Hash contract (must match dynamo_tpu/tokens.py exactly):
//   root block:  xxh3_64(tokens_le4, seed=salt)
//   child block: xxh3_64(parent_hash_le8 || tokens_le4, seed=salt)
//
// XXH3 comes from the xxhash single-header library already shipped in this
// image (vendored by pyarrow); XXH_INLINE_ALL keeps us dependency-free.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#define XXH_INLINE_ALL
#include "xxhash.h"

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// One block's payload buffer: 8-byte parent + tokens. Reused across blocks.
PyObject *block_hashes(PyObject *, PyObject *args, PyObject *kwargs) {
    static const char *kwlist[] = {"tokens", "block_size", "salt", "parent", nullptr};
    Py_buffer buf;
    Py_ssize_t block_size;
    unsigned long long salt;
    PyObject *parent_obj = Py_None;
    if (!PyArg_ParseTupleAndKeywords(
            args, kwargs, "y*nK|O", const_cast<char **>(kwlist),
            &buf, &block_size, &salt, &parent_obj)) {
        return nullptr;
    }
    if (block_size <= 0) {
        PyBuffer_Release(&buf);
        PyErr_SetString(PyExc_ValueError, "block_size must be positive");
        return nullptr;
    }
    if (buf.len % 4 != 0) {
        PyBuffer_Release(&buf);
        PyErr_SetString(PyExc_ValueError, "tokens buffer must be little-endian int32");
        return nullptr;
    }
    const Py_ssize_t n_tokens = buf.len / 4;
    const Py_ssize_t n_blocks = n_tokens / block_size;
    const Py_ssize_t block_bytes = block_size * 4;
    const uint8_t *tok = static_cast<const uint8_t *>(buf.buf);

    bool has_parent = parent_obj != Py_None;
    uint64_t parent = 0;
    if (has_parent) {
        parent = PyLong_AsUnsignedLongLong(parent_obj);
        if (PyErr_Occurred()) {
            PyBuffer_Release(&buf);
            return nullptr;
        }
    }

    std::vector<uint64_t> out(static_cast<size_t>(n_blocks));
    {
        // Pure C loop: release the GIL for long prompts.
        std::vector<uint8_t> payload(8 + static_cast<size_t>(block_bytes));
        Py_BEGIN_ALLOW_THREADS
        for (Py_ssize_t i = 0; i < n_blocks; i++) {
            const uint8_t *block = tok + i * block_bytes;
            uint64_t h;
            if (!has_parent && i == 0) {
                h = XXH3_64bits_withSeed(block, block_bytes, salt);
            } else {
                std::memcpy(payload.data(), &parent, 8);  // little-endian hosts
                std::memcpy(payload.data() + 8, block, block_bytes);
                h = XXH3_64bits_withSeed(payload.data(), payload.size(), salt);
            }
            out[static_cast<size_t>(i)] = h;
            parent = h;
            has_parent = true;
        }
        Py_END_ALLOW_THREADS
    }
    PyBuffer_Release(&buf);

    PyObject *list = PyList_New(n_blocks);
    if (!list) return nullptr;
    for (Py_ssize_t i = 0; i < n_blocks; i++) {
        PyObject *v = PyLong_FromUnsignedLongLong(out[static_cast<size_t>(i)]);
        if (!v) {
            Py_DECREF(list);
            return nullptr;
        }
        PyList_SET_ITEM(list, i, v);
    }
    return list;
}

PyObject *hash_bytes(PyObject *, PyObject *args) {
    Py_buffer buf;
    unsigned long long seed;
    if (!PyArg_ParseTuple(args, "y*K", &buf, &seed)) return nullptr;
    uint64_t h = XXH3_64bits_withSeed(buf.buf, static_cast<size_t>(buf.len), seed);
    PyBuffer_Release(&buf);
    return PyLong_FromUnsignedLongLong(h);
}

PyMethodDef methods[] = {
    {"block_hashes", reinterpret_cast<PyCFunction>(block_hashes),
     METH_VARARGS | METH_KEYWORDS,
     "Chained xxh3 sequence hashes for every complete block of a le-i32 token buffer."},
    {"hash_bytes", hash_bytes, METH_VARARGS, "xxh3_64 of a buffer with a seed."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_dyncore",
    "Native runtime kernels (chained block hashing).", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__dyncore(void) { return PyModule_Create(&moduledef); }
