"""Mosaic-compiled kernel parity on the real chip.

The interpret-mode tests in ``tests/test_pallas_*.py`` pin the math; this
tier pins the *lowering*: scoped-VMEM fit, DMA semantics, the per-KV-head
tuple carry, lane-strip slicing at head_dim 64 and 128 — everything that
only exists once Mosaic compiles the kernel for hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.ops.attention import paged_attention_reference
from dynamo_tpu.ops.pallas_paged import paged_decode_attention
from dynamo_tpu.ops.pallas_prefill import paged_prefill_attention


def _case(rng, *, b, t, n_heads, n_kv, head_dim, page_size, pages_per_seq, starts):
    width = n_kv * head_dim
    num_pages = b * pages_per_seq + 1
    k = jnp.asarray(rng.standard_normal((num_pages, page_size, width)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((num_pages, page_size, width)), jnp.bfloat16)
    q = jnp.asarray(rng.standard_normal((b, t, n_heads, head_dim)), jnp.bfloat16)
    tables = jnp.asarray(
        1 + rng.permutation(num_pages - 1)[: b * pages_per_seq].reshape(b, pages_per_seq),
        jnp.int32,
    )
    positions = jnp.asarray(np.asarray(starts)[:, None] + np.arange(t)[None, :], jnp.int32)
    return q, k, v, tables, positions


@pytest.mark.parametrize(
    "n_heads,n_kv,head_dim",
    [(32, 8, 64), (32, 8, 128), (16, 16, 128)],  # 1B GQA, 8B GQA, MHA
)
def test_prefill_kernel_on_device(n_heads, n_kv, head_dim):
    rng = np.random.default_rng(0)
    q, k, v, tables, positions = _case(
        rng, b=2, t=256, n_heads=n_heads, n_kv=n_kv, head_dim=head_dim,
        page_size=128, pages_per_seq=6, starts=[256, 128],
    )
    scale = head_dim**-0.5
    want = paged_attention_reference(q, k, v, tables, positions, scale=scale)
    got = paged_prefill_attention(q, k, v, tables, positions, scale=scale)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=5e-2, rtol=5e-2
    )


@pytest.mark.parametrize("head_dim", [64, 128])
def test_decode_kernel_on_device(head_dim):
    rng = np.random.default_rng(1)
    q, k, v, tables, positions = _case(
        rng, b=8, t=1, n_heads=32, n_kv=8, head_dim=head_dim,
        page_size=128, pages_per_seq=8, starts=[int(x) for x in rng.integers(0, 1000, 8)],
    )
    scale = head_dim**-0.5
    want = paged_attention_reference(q, k, v, tables, positions, scale=scale)
    got = paged_decode_attention(q, k, v, tables, positions, scale=scale)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=5e-2, rtol=5e-2
    )


def test_prefill_faster_than_reference_long_context():
    """The kernel must beat the gather formulation at ISL >= 1024 (the
    VERDICT r2 'done' bar for the prefill path)."""
    import time

    rng = np.random.default_rng(2)
    q, k, v, tables, positions = _case(
        rng, b=4, t=2048, n_heads=32, n_kv=8, head_dim=128,
        page_size=128, pages_per_seq=17, starts=[0, 0, 0, 0],
    )
    scale = 128**-0.5
    ref = jax.jit(lambda *a: paged_attention_reference(*a, scale=scale))
    ker = jax.jit(lambda *a: paged_prefill_attention(*a, scale=scale))

    def bench(f):
        f(q, k, v, tables, positions).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(5):
            r = f(q, k, v, tables, positions)
        r.block_until_ready()
        return (time.perf_counter() - t0) / 5

    t_ref, t_ker = bench(ref), bench(ker)
    assert t_ker < t_ref, f"kernel {t_ker*1e3:.1f} ms !< reference {t_ref*1e3:.1f} ms"


def test_mla_decode_kernel_on_device():
    """MLA decode kernel at DeepSeek-V3 geometry (r_kv 512, rope 64 padded
    to a 128-lane tile), Mosaic-compiled, vs the gather formulation."""
    from dynamo_tpu.ops.pallas_mla import mla_paged_decode

    rng = np.random.default_rng(7)
    b, page_size, pages_per_seq = 8, 128, 5
    r_kv, r_width, dr = 512, 128, 64
    n_heads = 32
    num_pages = 1 + b * pages_per_seq
    c_cache = jnp.asarray(rng.standard_normal((num_pages, page_size, r_kv)) * 0.3, jnp.bfloat16)
    r_host = np.zeros((num_pages, page_size, r_width), np.float32)
    r_host[..., :dr] = rng.standard_normal((num_pages, page_size, dr)) * 0.3
    r_cache = jnp.asarray(r_host, jnp.bfloat16)
    tables = jnp.asarray(
        1 + rng.permutation(num_pages - 1).reshape(b, pages_per_seq), jnp.int32
    )
    lengths = rng.integers(100, page_size * pages_per_seq, size=b)
    positions = jnp.asarray(lengths[:, None] - 1, jnp.int32)
    q_lat = jnp.asarray(rng.standard_normal((b, n_heads, r_kv)) * 0.2, jnp.bfloat16)
    q_rope_host = np.zeros((b, n_heads, r_width), np.float32)
    q_rope_host[..., :dr] = rng.standard_normal((b, n_heads, dr)) * 0.2
    q_rope = jnp.asarray(q_rope_host, jnp.bfloat16)
    scale = (128 + 64) ** -0.5

    got = np.asarray(mla_paged_decode(
        q_lat, q_rope, c_cache, r_cache, tables, positions, scale=scale
    ))

    s = pages_per_seq * page_size
    c_pages = c_cache[tables.reshape(-1)].reshape(b, s, r_kv).astype(jnp.float32)
    r_pages = r_cache[tables.reshape(-1)].reshape(b, s, r_width).astype(jnp.float32)
    logits = (
        jnp.einsum("bhr,bsr->bhs", q_lat.astype(jnp.float32), c_pages)
        + jnp.einsum("bhr,bsr->bhs", q_rope.astype(jnp.float32), r_pages)
    ) * scale
    key_pos = jnp.arange(s)[None, None, :]
    logits = jnp.where(key_pos <= positions[:, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    want = np.asarray(jnp.einsum("bhs,bsr->bhr", probs, c_pages))
    np.testing.assert_allclose(got, want, rtol=3e-2, atol=3e-2)
