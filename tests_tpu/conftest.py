"""On-device (real TPU) test tier.

Unlike ``tests/`` (which forces an 8-device virtual CPU mesh), this suite
runs on whatever accelerator JAX finds and skips itself entirely when that
is not a TPU. Run explicitly: ``python -m pytest tests_tpu/ -q`` — it is
NOT in pyproject's default testpaths, because CI sandboxes have no chip.
"""

import jax
import pytest


def pytest_collection_modifyitems(config, items):
    if jax.default_backend() != "tpu":
        skip = pytest.mark.skip(reason="no TPU backend; on-device tier requires a chip")
        for item in items:
            item.add_marker(skip)
