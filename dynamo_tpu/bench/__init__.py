"""Benchmark harness: synthetic workloads + concurrency sweeps + pareto.

Parity with the reference benchmark stack (`benchmarks/llm/perf.sh`
concurrency sweep, `plot_pareto.py`, `data_generator/synthesizer.py`
prefix-structured workloads) rebuilt as a first-party harness that drives
the OpenAI HTTP surface of any topology this framework can serve.

- :mod:`dynamo_tpu.bench.synthesizer` — prefix-tree workload generator.
- :mod:`dynamo_tpu.bench.harness` — closed-loop sweep, TTFT/ITL percentiles.
- ``python -m dynamo_tpu.bench`` — one command, N topologies, pareto JSON.
"""

from dynamo_tpu.bench.harness import LevelStats, sweep_http
from dynamo_tpu.bench.synthesizer import SyntheticConfig, WorkloadRequest, synthesize

__all__ = ["LevelStats", "sweep_http", "SyntheticConfig", "WorkloadRequest", "synthesize"]
