"""Cross-process KV-wire bandwidth probe: the DCN-path number on hardware.

Measures the packed-bytes TCP fallback — the prefill->decode transfer path
that runs anywhere (`disagg/transfer.py`), unlike the PJRT transfer engine
(unsupported by the axon plugin) — between the CHIP-holding process and a
second, CPU-mesh receiver process on the same host:

  sender (this process, real TPU): prefill commits page chains ->
  `send_blocks_chunked` (wire v3: chunks striped round-robin over
  DYN_KV_WIRE_STREAMS duplex TCP connections, raw blob frames, deferred
  acks; ``streams=0`` pins the single-stream msgpack v2 baseline) ->
  receiver (child OS process, CPU): per chunk crc-verify -> reassemble in
  seq order -> allocate -> write_pages -> incremental commit -> ack.

``sweep_cross_process`` runs a stream-count x chunk-size grid (one receiver
child per combo) and reports the headline ``kv_wire_gbps`` /
``kv_wire_overlap_frac`` / ``speedup_vs_v2`` keys that bench.py promotes to
the stable top level of the bench document.

Each iteration ships a DISTINCT hash chain (a repeat would dedup against
the receiver's prefix cache and measure nothing). Iteration 0 is reported
as "cold" (includes both sides' jit compiles and connection setup); the
rest average into "amortized" — the two numbers BENCH r4 left unreconciled
for the in-process probe (VERDICT r4 weak #5 / item 3a).

The transferred KV uses a wide-cache geometry (`wire_config`) so a few
thousand prefill tokens move hundreds of MB: the point is to saturate the
WIRE, not the model.

Parity: the reference measures NIXL RDMA block-descriptor transfers
(`lib/llm/src/block_manager/block/transfer/nixl.rs:86`); this is the
TCP/DCN-class equivalent, reported by bench.py under
``detail.kv_wire_cross_process`` (the in-process gather stays in
``detail.kv_pull``).

Child entrypoint: ``python -m dynamo_tpu.bench.kv_wire`` (CPU platform,
prints ``ADDR <kv_transfer addr>`` once serving, exits on stdin EOF).
"""

from __future__ import annotations

import dataclasses
import time

from dynamo_tpu.models.config import ModelConfig

PAGE_SIZE = 128


def wire_config(num_layers: int = 4, num_kv_heads: int = 32, head_dim: int = 128) -> ModelConfig:
    """Wide-KV / tiny-weights geometry: 8 MiB per 128-token page at the
    defaults (4L * 2(K,V) * 32kv * 128hd * 2B * 128 tokens), ~50 MB of
    weights — the default 8-page chain moves ~64 MB per iteration."""
    return ModelConfig(
        name="kv-wire-proxy", vocab_size=512, hidden_size=512,
        num_layers=num_layers, num_heads=num_kv_heads, num_kv_heads=num_kv_heads,
        head_dim=head_dim, intermediate_size=1024, rope_theta=10000.0,
        max_position=16384, tie_embeddings=True,
    )


def _build_core(cfg: ModelConfig, num_pages: int, page_size: int, prefill_tokens: int):
    from dynamo_tpu.engine.core import EngineConfig, EngineCore
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama

    params = llama.init_params(cfg, 0)
    runner = ModelRunner(
        cfg, params, num_pages=num_pages, page_size=page_size,
        max_batch_size=2, prefill_bucket=max(prefill_tokens, 64),
    )
    return EngineCore(runner, EngineConfig(
        num_pages=num_pages, page_size=page_size, max_batch_size=2,
        max_prefill_tokens=prefill_tokens + page_size,
        max_seq_len=prefill_tokens + page_size,
    ))


def _prefill_chain(core, tokens: list[int], request_id: str) -> list[int]:
    """Run a 1-token generation so the prompt's full pages commit to the
    prefix cache (what a prefill worker does before shipping KV); returns
    the committed chain's hashes."""
    from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.tokens import compute_block_hashes

    core.add_request(PreprocessedRequest(
        token_ids=tokens, sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=1, ignore_eos=True), request_id=request_id,
    ), Context())
    for _ in range(200):
        if not core.has_work:
            break
        core.step()
    return compute_block_hashes(tokens, core.config.page_size, salt=core.config.salt)


async def measure_cross_process(
    *,
    pages_per_chain: int = 8,
    iters: int = 5,
    cfg: ModelConfig | None = None,
    page_size: int = PAGE_SIZE,
    child_cmd: list[str] | None = None,
    chunk_pages: int | None = None,
    streams: int | None = None,
    _core=None,
    _seed: int = 0,
) -> dict:
    """Parent side. Spawns the CPU receiver child, ships ``iters`` distinct
    chains over the chunked stream (``send_blocks_chunked``: gather, pack
    and wire pipelined; v3 striped over ``streams`` duplex connections,
    ``streams=0`` pins the v2 single-stream baseline), returns the labeled
    measurement dict. Per-iter phase sums exceeding ``total_s`` is the
    direct overlap signal. ``_core``/``_seed`` let sweep_cross_process reuse
    one compiled parent core across combos with distinct chains each."""
    import subprocess
    import sys

    import numpy as np

    from dynamo_tpu.disagg.transfer import send_blocks_chunked
    from dynamo_tpu.runtime.tcp import TcpTransport

    cfg = cfg or wire_config()
    chain_tokens = pages_per_chain * page_size
    cmd = child_cmd or [
        sys.executable, "-m", "dynamo_tpu.bench.kv_wire",
        str(cfg.num_layers), str(cfg.num_kv_heads), str(cfg.head_dim),
        str(page_size), str(pages_per_chain * iters + 4),
        str(chain_tokens),
    ]
    import asyncio

    proc = subprocess.Popen(
        cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    try:
        def _await_addr() -> str:
            tail: list[str] = []
            for line in proc.stdout:
                if line.startswith("ADDR "):
                    return line.split()[1]
                tail.append(line)
            raise RuntimeError(
                f"kv_wire child exited without ADDR (rc={proc.wait()}): "
                + "".join(tail[-5:])
            )

        # Bounded + off the event loop: a child hung before ADDR (plugin
        # import, port bind) must not wedge the bench with no diagnostic.
        kv_addr = await asyncio.wait_for(
            asyncio.get_running_loop().run_in_executor(None, _await_addr),
            timeout=180,
        )
        # Keep draining the merged stdout/stderr afterwards: a chatty child
        # filling the 64 KiB pipe would block mid-write and deadlock the
        # un-timed send_blocks round trips.
        import threading

        threading.Thread(
            target=lambda: [None for _ in proc.stdout], daemon=True,
            name="kv-wire-child-drain",
        ).start()

        core = _core or _build_core(cfg, pages_per_chain * iters + 4, page_size, chain_tokens)
        transport = TcpTransport(host="127.0.0.1")
        # >= 4 chunks per chain by default, so the double buffer has room to
        # overlap (one chunk can't pipeline with itself).
        chunk = chunk_pages or max(1, pages_per_chain // 4)
        try:
            rng = np.random.default_rng(_seed)
            per_iter = []
            protocol = "v2"
            n_streams = 0
            for i in range(iters):
                tokens = rng.integers(1, cfg.vocab_size - 1, size=chain_tokens).tolist()
                hashes = _prefill_chain(core, tokens, f"wire-{_seed}-{i}")
                t0 = time.perf_counter()
                resp = await send_blocks_chunked(
                    transport, kv_addr, f"wire-{_seed}-{i}", core, hashes,
                    chunk_pages=chunk, streams=streams,
                )
                t1 = time.perf_counter()
                protocol = resp.get("protocol", "v2")
                n_streams = resp.get("streams", 0)
                if resp.get("injected") != len(hashes):
                    raise RuntimeError(f"iter {i}: injected {resp.get('injected')} != {len(hashes)}")
                ph = resp["phases"]
                scatter = (resp.get("stats") or {}).get("scatter_s", 0.0)
                per_iter.append({
                    "bytes": resp["bytes"],
                    "gather_s": ph["gather_s"],   # dispatch -> host buffers landed
                    "pack_s": ph["pack_s"],       # msgpack framing (tobytes)
                    "wire_s": ph["wire_s"],       # TCP round trips + receiver ingest
                    "scatter_s_cum": round(scatter, 6),  # receiver-side, cumulative
                    "total_s": round(t1 - t0, 4),
                    "overlap_s": round(ph["gather_s"] + ph["pack_s"] + ph["wire_s"] - (t1 - t0), 4),
                })
            # scatter_s per iter = delta of the receiver's cumulative counter.
            prev = 0.0
            for p in per_iter:
                p["scatter_s"] = round(p.pop("scatter_s_cum") - prev, 6)
                prev += p["scatter_s"]
            amortized = per_iter[1:] or per_iter
            phase_sum = sum(
                p["gather_s"] + p["pack_s"] + p["wire_s"] for p in amortized)
            overlap_s = sum(p["overlap_s"] for p in amortized)
            return {
                "wire": "tcp_cross_process",
                "receiver": "separate OS process, cpu mesh",
                "definition": (
                    "cold = iter 0 (both sides' compiles + connection setup); "
                    f"amortized = mean of the rest. Chunked {protocol} stream "
                    f"({chunk} pages/chunk, {n_streams or 1} stream(s)): "
                    "gather_s = device gather -> host DMA span (crosses the "
                    "tunnel link when the chip is axon-remote), pack_s = "
                    "framing (v3: zero-copy blob views; v2: msgpack), wire_s "
                    "= per-stream-attributed TCP + receiver ingest wall time, "
                    "scatter_s = receiver write_pages. Phases overlap, so sum "
                    "of phases > total_s measures the pipeline win directly "
                    "(overlap_s; overlap_frac = overlap_s / sum of phases)"
                ),
                "protocol": protocol,
                "streams": n_streams,
                "chain_mb": round(per_iter[0]["bytes"] / 1e6, 1),
                "iters": iters,
                "chunk_pages": chunk,
                "cold_gbytes_per_sec": round(
                    per_iter[0]["bytes"] / per_iter[0]["total_s"] / 1e9, 6),
                "amortized_gbytes_per_sec": round(
                    sum(p["bytes"] for p in amortized)
                    / max(sum(p["total_s"] for p in amortized), 1e-9) / 1e9, 6),
                "amortized_wire_only_gbytes_per_sec": round(
                    sum(p["bytes"] for p in amortized)
                    / max(sum(p["wire_s"] for p in amortized), 1e-9) / 1e9, 6),
                "amortized_overlap_s": round(overlap_s / max(len(amortized), 1), 4),
                "overlap_frac": round(
                    min(1.0, max(0.0, overlap_s / phase_sum)) if phase_sum > 0 else 0.0,
                    4),
                "per_iter": per_iter,
            }
        finally:
            await transport.close()
    finally:
        try:
            proc.stdin.close()
            proc.wait(timeout=20)
        except Exception:
            proc.kill()


async def sweep_cross_process(
    *,
    pages_per_chain: int = 8,
    iters: int = 5,
    cfg: ModelConfig | None = None,
    page_size: int = PAGE_SIZE,
    child_cmd: list[str] | None = None,
    stream_counts: tuple[int, ...] = (0, 1, 2, 4, 8),
    chunk_pages_list: tuple[int, ...] = (0,),
) -> dict:
    """Stream-count x chunk-size grid over the cross-process wire.

    One receiver child per combo (fresh page pool, no prefix-cache dedup);
    the PARENT core — whose jit compiles dominate probe setup on hardware —
    is built once and reused, with a distinct chain seed per combo.

    ``stream_counts`` entry 0 is the v2 single-stream msgpack baseline; the
    headline ``speedup_vs_v2`` compares the best striped combo against the
    v2 run *at the same chunk size* (the acceptance comparison). Headline
    keys:

    - ``kv_wire_gbps``: best amortized end-to-end GB/s across the grid;
    - ``kv_wire_overlap_frac``: overlap fraction of that best combo
      (sum-of-phases time hidden by pipelining, 0..1);
    - ``speedup_vs_v2``: best-combo GB/s over same-chunk v2 GB/s.
    """
    cfg = cfg or wire_config()
    chunks = tuple(c or max(1, pages_per_chain // 4) for c in chunk_pages_list)
    chain_tokens = pages_per_chain * page_size
    core = _build_core(cfg, pages_per_chain * iters + 4, page_size, chain_tokens)
    combos = []
    seed = 0
    for chunk in chunks:
        for streams in stream_counts:
            seed += 1
            out = await measure_cross_process(
                pages_per_chain=pages_per_chain, iters=iters, cfg=cfg,
                page_size=page_size, child_cmd=child_cmd, chunk_pages=chunk,
                streams=streams, _core=core, _seed=seed,
            )
            combos.append({
                "streams_requested": streams,
                "streams": out["streams"],
                "protocol": out["protocol"],
                "chunk_pages": out["chunk_pages"],
                "chain_mb": out["chain_mb"],
                "amortized_gbytes_per_sec": out["amortized_gbytes_per_sec"],
                "amortized_wire_only_gbytes_per_sec":
                    out["amortized_wire_only_gbytes_per_sec"],
                "cold_gbytes_per_sec": out["cold_gbytes_per_sec"],
                "overlap_frac": out["overlap_frac"],
                "amortized_overlap_s": out["amortized_overlap_s"],
            })
    best = max(combos, key=lambda c: c["amortized_gbytes_per_sec"])
    v2_same_chunk = next(
        (c for c in combos
         if c["protocol"] == "v2" and c["chunk_pages"] == best["chunk_pages"]),
        None,
    )
    speedup = 0.0
    if v2_same_chunk and v2_same_chunk["amortized_gbytes_per_sec"] > 0:
        speedup = round(
            best["amortized_gbytes_per_sec"]
            / v2_same_chunk["amortized_gbytes_per_sec"], 3)
    return {
        "wire": "tcp_cross_process_sweep",
        "grid": {"stream_counts": list(stream_counts), "chunk_pages": list(chunks)},
        "iters": iters,
        "pages_per_chain": pages_per_chain,
        "chain_mb": combos[0]["chain_mb"],
        "kv_wire_gbps": best["amortized_gbytes_per_sec"],
        "kv_wire_overlap_frac": best["overlap_frac"],
        "speedup_vs_v2": speedup,
        "best": best,
        "v2_baseline": v2_same_chunk,
        "sweep": combos,
    }


def child_main(argv: list[str]) -> None:
    """Receiver: CPU platform, real engine core + KvTransferService on TCP."""
    import asyncio
    import sys

    import jax

    jax.config.update("jax_platforms", "cpu")  # env alone loses to hw plugins

    num_layers, num_kv_heads, head_dim, page_size, num_pages, chain_tokens = (
        int(a) for a in argv
    )
    cfg = wire_config(num_layers, num_kv_heads, head_dim)

    async def main() -> None:
        from dynamo_tpu.disagg.transfer import KV_TRANSFER_ENDPOINT, KvTransferService
        from dynamo_tpu.runtime.tcp import TcpTransport

        core = _build_core(cfg, num_pages, page_size, chain_tokens)
        svc = KvTransferService(core)
        transport = TcpTransport(host="127.0.0.1")
        await transport.register_engine(KV_TRANSFER_ENDPOINT, svc)
        print("ADDR", transport.address_of(KV_TRANSFER_ENDPOINT), flush=True)
        await asyncio.get_running_loop().run_in_executor(None, sys.stdin.read)
        await transport.close()

    asyncio.run(main())


if __name__ == "__main__":
    import sys

    child_main(sys.argv[1:])
