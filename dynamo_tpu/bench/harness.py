"""Closed-loop concurrency sweep over the OpenAI HTTP surface.

Each level keeps exactly C requests in flight (closed loop, like the
reference's genai-perf runs at concurrency 1..256, `perf.sh:18-29`),
streaming so TTFT and inter-token latency are measured per token. The
output rows are the pareto data the reference plots: throughput vs
TTFT/ITL percentiles per concurrency.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time

import aiohttp
import numpy as np

from dynamo_tpu.bench.synthesizer import WorkloadRequest


@dataclasses.dataclass
class RequestResult:
    ttft: float
    gaps: list[float]
    output_tokens: int
    ok: bool


@dataclasses.dataclass
class LevelStats:
    concurrency: int
    requests: int
    errors: int
    wall_seconds: float
    output_tokens: int
    output_tok_per_sec: float
    ttft_p50: float
    ttft_p90: float
    ttft_p99: float
    itl_p50: float
    itl_p90: float
    itl_p99: float

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _pct(xs: list[float], q: float) -> float:
    return float(np.percentile(xs, q)) if xs else 0.0


async def _one_request(
    session: aiohttp.ClientSession, base: str, model: str, req: WorkloadRequest
) -> RequestResult:
    import json as _json

    body = {
        "model": model,
        "prompt": req.token_ids,
        "max_tokens": req.max_tokens,
        "temperature": 0,
        "stream": True,
        # Authoritative token count: one SSE chunk may carry a multi-token
        # decode burst (decode_steps > 1), so counting chunks undercounts.
        "stream_options": {"include_usage": True},
    }
    t0 = time.monotonic()
    ttft = 0.0
    gaps: list[float] = []
    chunks = 0
    usage_tokens = None
    prev = None
    try:
        async with session.post(f"{base}/v1/completions", json=body) as resp:
            if resp.status != 200:
                return RequestResult(0.0, [], 0, ok=False)
            async for line in resp.content:
                if not line.startswith(b"data:"):
                    continue
                payload = line[5:].strip()
                if payload == b"[DONE]":
                    continue
                now = time.monotonic()
                try:
                    obj = _json.loads(payload)
                except Exception:
                    continue
                usage = obj.get("usage")
                if usage and usage.get("completion_tokens"):
                    usage_tokens = usage["completion_tokens"]
                if prev is None:
                    ttft = now - t0
                else:
                    gaps.append(now - prev)
                prev = now
                chunks += 1
    except Exception:
        return RequestResult(0.0, [], 0, ok=False)
    tokens = usage_tokens if usage_tokens is not None else chunks
    if chunks > 1 and tokens > chunks:
        # Burst streaming: each chunk gap spans ~tokens/chunks tokens —
        # normalize so ITL stays per-token across decode_steps configs.
        gaps = [g * chunks / tokens for g in gaps]
    return RequestResult(ttft, gaps, tokens, ok=True)


async def run_level(
    base: str, model: str, workload: list[WorkloadRequest], *, concurrency: int
) -> LevelStats:
    """Closed loop: C workers drain the workload queue."""
    queue: asyncio.Queue[WorkloadRequest] = asyncio.Queue()
    for r in workload:
        queue.put_nowait(r)
    results: list[RequestResult] = []

    async with aiohttp.ClientSession(timeout=aiohttp.ClientTimeout(total=600)) as session:

        async def worker() -> None:
            while True:
                try:
                    r = queue.get_nowait()
                except asyncio.QueueEmpty:
                    return
                results.append(await _one_request(session, base, model, r))

        t0 = time.monotonic()
        await asyncio.gather(*(worker() for _ in range(concurrency)))
        wall = time.monotonic() - t0

    good = [r for r in results if r.ok]
    gaps = [g for r in good for g in r.gaps]
    tokens = sum(r.output_tokens for r in good)
    return LevelStats(
        concurrency=concurrency,
        requests=len(results),
        errors=len(results) - len(good),
        wall_seconds=round(wall, 3),
        output_tokens=tokens,
        output_tok_per_sec=round(tokens / wall, 2) if wall > 0 else 0.0,
        ttft_p50=round(_pct([r.ttft for r in good], 50), 4),
        ttft_p90=round(_pct([r.ttft for r in good], 90), 4),
        ttft_p99=round(_pct([r.ttft for r in good], 99), 4),
        itl_p50=round(_pct(gaps, 50), 5),
        itl_p90=round(_pct(gaps, 90), 5),
        itl_p99=round(_pct(gaps, 99), 5),
    )


async def sweep_http(
    base: str, model: str, workloads, *, levels: list[int]
) -> list[LevelStats]:
    """One pareto sweep across concurrency levels.

    ``workloads``: one list of WorkloadRequest per level (fresh prompts per
    level — replaying identical prompts against a warm server would measure
    prefix-cache lookups, not prefill), or a single list replayed at every
    level when cross-level caching is knowingly acceptable (mock engines,
    caching disabled).
    """
    if workloads and isinstance(workloads[0], WorkloadRequest):
        workloads = [workloads] * len(levels)
    if len(workloads) != len(levels):
        raise ValueError(f"need one workload per level: {len(workloads)} != {len(levels)}")
    out = []
    for c, w in zip(levels, workloads):
        out.append(await run_level(base, model, w, concurrency=c))
    return out
