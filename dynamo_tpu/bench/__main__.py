"""One-command pareto comparison across serving topologies.

``python -m dynamo_tpu.bench --topologies agg,disagg --levels 1,4,16``
brings each topology up in-process (run_local), replays the same
prefix-structured synthetic workload at every concurrency level, and emits
one JSON document with the pareto rows per topology — the agg-vs-disagg
comparison the reference publishes as its headline result
(`docs/architecture/architecture.md:75`, `examples/llm/benchmarks/`).

Runs on whatever jax platform is active: the real chip under axon, or
CPU/mock for CI (``--mock``).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import sys

from dynamo_tpu.bench.harness import sweep_http
from dynamo_tpu.bench.synthesizer import SyntheticConfig, sharing_ratio, synthesize

logger = logging.getLogger(__name__)

TOPOLOGIES = {
    # name -> run_local kwargs beyond the shared ones
    "agg": {},
    "agg_router": {"router_mode": "kv"},
    "disagg": {"prefill": True},
}


async def bench_topology(
    name: str, args: argparse.Namespace, workload, levels: list[int]
) -> list[dict]:
    from dynamo_tpu.disagg.router import DisaggConfig
    from dynamo_tpu.launch import run_local

    topo = dict(TOPOLOGIES[name])
    kw: dict = {
        "num_pages": args.num_pages,
        "max_batch_size": args.max_batch_size,
        "mock": args.mock,
        "router_mode": topo.get("router_mode", "round_robin"),
        "num_workers": args.workers,
    }
    if args.page_size:
        kw["page_size"] = args.page_size
    if args.max_seq_len:
        kw["max_seq_len"] = args.max_seq_len
    if args.max_prefill_tokens:
        kw["max_prefill_tokens"] = args.max_prefill_tokens
    if args.decode_steps:
        kw["decode_steps"] = args.decode_steps
    if args.quantize:
        kw["quantize"] = args.quantize
    if topo.get("prefill"):
        kw["num_prefill_workers"] = max(1, args.prefill_workers)
        kw["disagg"] = DisaggConfig(
            max_local_prefill_length=args.disagg_threshold, min_remote_prefill_blocks=1
        )
    handles = await run_local(args.model, port=0, **kw)
    base = f"http://127.0.0.1:{handles['port']}"
    try:
        stats = await sweep_http(base, args.model, workload, levels=levels)
        return [s.to_dict() for s in stats]
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()


async def _amain(args: argparse.Namespace) -> None:
    import dataclasses

    levels = [int(x) for x in args.levels.split(",")]
    cfg = SyntheticConfig(
        num_requests=args.num_requests,
        shared_prefix_len=args.shared_prefix,
        num_groups=args.groups,
        group_prefix_len=args.group_prefix,
        unique_len=args.unique_len,
        osl_mean=args.osl,
        seed=args.seed,
    )
    # Fresh prompts per level: a replayed workload would be fully
    # prefix-cached after the first level and measure lookups, not prefill.
    workload = [
        synthesize(dataclasses.replace(cfg, seed=cfg.seed + 1000 * i))
        for i in range(len(levels))
    ]
    import jax

    report: dict = {
        # The model/engine config lives INSIDE the artifact: an unlabeled
        # pareto row is unreproducible (VERDICT r4 weak #2).
        "model": args.model,
        "quantize": args.quantize or "bf16",
        "backend": jax.default_backend(),
        "engine": {
            "workers": args.workers,
            "prefill_workers": args.prefill_workers,
            "num_pages": args.num_pages,
            "max_batch_size": args.max_batch_size,
            "page_size": args.page_size or "default",
            "max_seq_len": args.max_seq_len or "default",
            "max_prefill_tokens": args.max_prefill_tokens or "default",
            "decode_steps": args.decode_steps or "default",
            "disagg_threshold": args.disagg_threshold,
            "mock": args.mock,
        },
        "workload": {
            "num_requests": cfg.num_requests,
            "isl": cfg.shared_prefix_len + cfg.group_prefix_len + cfg.unique_len,
            "osl_mean": cfg.osl_mean,
            "prefix_sharing_ratio": round(sharing_ratio(cfg), 3),
        },
        "levels": levels,
        "topologies": {},
    }
    for name in args.topologies.split(","):
        if name not in TOPOLOGIES:
            raise SystemExit(f"unknown topology {name!r} (have: {', '.join(TOPOLOGIES)})")
        logger.info("benchmarking topology %s", name)
        report["topologies"][name] = await bench_topology(name, args, workload, levels)

    print(json.dumps(report))
    # Human-readable pareto table on stderr (stdout stays machine-parseable).
    for name, rows in report["topologies"].items():
        print(f"\n== {name} ==", file=sys.stderr)
        print(f"{'conc':>5} {'tok/s':>9} {'ttft_p50':>9} {'ttft_p90':>9} {'itl_p50':>8} {'itl_p90':>8} {'err':>4}", file=sys.stderr)
        for r in rows:
            print(
                f"{r['concurrency']:>5} {r['output_tok_per_sec']:>9.1f} "
                f"{r['ttft_p50']:>9.3f} {r['ttft_p90']:>9.3f} "
                f"{r['itl_p50']:>8.4f} {r['itl_p90']:>8.4f} {r['errors']:>4}",
                file=sys.stderr,
            )


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description="dynamo-tpu pareto benchmark")
    p.add_argument("--model", default="test-tiny")
    p.add_argument("--topologies", default="agg,disagg")
    p.add_argument("--levels", default="1,4,16", help="concurrency sweep (reference: 1..256)")
    p.add_argument("--num-requests", type=int, default=64)
    p.add_argument("--workers", type=int, default=1)
    p.add_argument("--prefill-workers", type=int, default=1)
    p.add_argument("--disagg-threshold", type=int, default=64)
    p.add_argument("--shared-prefix", type=int, default=64)
    p.add_argument("--groups", type=int, default=4)
    p.add_argument("--group-prefix", type=int, default=64)
    p.add_argument("--unique-len", type=int, default=64)
    p.add_argument("--osl", type=int, default=48)
    p.add_argument("--num-pages", type=int, default=2048)
    p.add_argument("--max-batch-size", type=int, default=64)
    p.add_argument("--page-size", type=int, default=0, help="0 = engine default (serving on TPU: use 128)")
    p.add_argument("--max-seq-len", type=int, default=0, help="0 = engine default")
    p.add_argument("--max-prefill-tokens", type=int, default=0, help="chunked-prefill budget per step; 0 = engine default")
    p.add_argument("--decode-steps", type=int, default=0, help="fused decode burst length; 0 = engine default")
    p.add_argument("--quantize", default="", help="weight-only quantization (int8)")
    p.add_argument("--mock", action="store_true", help="timing-model engine (CI)")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s %(levelname)s %(message)s")
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()
