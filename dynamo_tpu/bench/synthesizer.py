"""Synthetic workload generator with controlled prefix structure.

Real serving traffic shares prompt prefixes (system prompts, few-shot
preambles, multi-turn history). The generator builds a two-level prefix
tree — one corpus-wide shared prefix, G group prefixes under it, and a
unique per-request suffix — so KV-router hit rates and prefix-cache
behavior can be exercised and measured, not just raw decode.

Parity: reference `benchmarks/data_generator/synthesizer.py:34-303`
(prefix-tree synthesis from traces) — here parameterized directly instead
of fitted, which is what its own tests do too.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class SyntheticConfig:
    num_requests: int = 64
    shared_prefix_len: int = 64  # corpus-wide (system prompt)
    num_groups: int = 4  # second-level prefixes (few-shot variants)
    group_prefix_len: int = 64
    unique_len: int = 64  # per-request tail
    osl_mean: int = 64
    osl_cv: float = 0.3  # coefficient of variation of output lengths
    vocab: int = 250  # keep ids small: works with every test tokenizer
    seed: int = 0


@dataclasses.dataclass
class WorkloadRequest:
    token_ids: list[int]
    max_tokens: int
    group: int


def synthesize(cfg: SyntheticConfig) -> list[WorkloadRequest]:
    rng = np.random.default_rng(cfg.seed)
    shared = rng.integers(5, cfg.vocab, cfg.shared_prefix_len).tolist()
    groups = [rng.integers(5, cfg.vocab, cfg.group_prefix_len).tolist() for _ in range(max(cfg.num_groups, 1))]
    out: list[WorkloadRequest] = []
    for i in range(cfg.num_requests):
        g = int(rng.integers(0, len(groups)))
        unique = rng.integers(5, cfg.vocab, cfg.unique_len).tolist()
        sigma = max(cfg.osl_mean * cfg.osl_cv, 1e-6)
        osl = int(np.clip(rng.normal(cfg.osl_mean, sigma), 1, cfg.osl_mean * 4))
        out.append(WorkloadRequest(token_ids=shared + groups[g] + unique, max_tokens=osl, group=g))
    rng.shuffle(out)  # interleave groups like real arrival order
    return out


def sharing_ratio(cfg: SyntheticConfig) -> float:
    """Fraction of prompt tokens that are shared with at least one other
    request (the theoretical ceiling for prefix-cache hit rate)."""
    total = cfg.shared_prefix_len + cfg.group_prefix_len + cfg.unique_len
    return (cfg.shared_prefix_len + cfg.group_prefix_len) / max(total, 1)
