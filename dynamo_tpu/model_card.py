"""Model Deployment Card (MDC): everything a frontend needs to serve a model.

Workers publish their card into the discovery store under ``models/{name}``;
the frontend's ModelWatcher builds the client pipeline (preprocessor ->
backend -> router) from it. Cards carry *specs* (tokenizer path/kind,
template text) rather than live objects so they serialize cleanly.

Parity: reference `lib/llm/src/model_card/model.rs:37-128` (MDC) +
`ModelEntry` (`discovery/model_entry.rs:21`). Artifact distribution differs:
the reference ships tokenizer files through the NATS object store; here the
card inlines the chat template and names a tokenizer source (shared path or
"byte"), since TPU pods mount shared filesystems.
"""

from __future__ import annotations

import json
import logging
import pathlib
from dataclasses import dataclass, field
from typing import Any

logger = logging.getLogger(__name__)

MODEL_PREFIX = "models"


@dataclass
class ModelDeploymentCard:
    name: str
    tokenizer: str = "byte"  # "byte" | path to tokenizer.json / model dir
    chat_template: str | None = None
    context_length: int = 4096
    kv_page_size: int = 16
    eos_token_ids: list[int] = field(default_factory=list)
    bos_token_id: int | None = None
    model_type: str = "chat+completions"  # which endpoints to expose
    # Endpoint the workers serve, as (namespace, component, endpoint).
    endpoint: tuple[str, str, str] = ("dynamo", "backend", "generate")
    router_mode: str = "round_robin"  # round_robin | random | kv
    extra: dict[str, Any] = field(default_factory=dict)

    def instance_key(self, lease_id: int) -> str:
        """Discovery key for one serving instance's card record.

        Cards are published per-instance (``models/{name}/{lease_id:x}``) and
        bound to that instance's lease, so a model disappears from frontends
        only when its *last* worker is gone — one process dying must not
        unregister a model other healthy workers still serve.
        """
        return f"{MODEL_PREFIX}/{self.name}/{lease_id:x}"

    @staticmethod
    def name_of_key(key: str) -> str:
        """models/{name}/{lease_hex} -> name (name itself may contain '/')."""
        inner = key[len(MODEL_PREFIX) + 1 :]
        return inner.rsplit("/", 1)[0]

    @property
    def supports_chat(self) -> bool:
        return "chat" in self.model_type

    @property
    def supports_completions(self) -> bool:
        return "completions" in self.model_type

    def to_bytes(self) -> bytes:
        d = dict(self.__dict__)
        d["endpoint"] = list(self.endpoint)
        return json.dumps(d).encode()

    @classmethod
    def from_bytes(cls, data: bytes) -> "ModelDeploymentCard":
        d = json.loads(data)
        d["endpoint"] = tuple(d.get("endpoint", ("dynamo", "backend", "generate")))
        return cls(**d)

    @classmethod
    def from_model_dir(cls, name: str, path: str | pathlib.Path, **overrides: Any) -> "ModelDeploymentCard":
        """Build a card from an HF-style model directory (config/tokenizer files)."""
        p = pathlib.Path(path)
        kw: dict[str, Any] = {"name": name}
        cfg_file = p / "config.json"
        if cfg_file.exists():
            cfg = json.loads(cfg_file.read_text())
            kw["context_length"] = cfg.get("max_position_embeddings", 4096)
            eos = cfg.get("eos_token_id")
            if isinstance(eos, int):
                kw["eos_token_ids"] = [eos]
            elif isinstance(eos, list):
                kw["eos_token_ids"] = list(eos)
            if isinstance(cfg.get("bos_token_id"), int):
                kw["bos_token_id"] = cfg["bos_token_id"]
        if (p / "tokenizer.json").exists():
            kw["tokenizer"] = str(p / "tokenizer.json")
        elif (p / "tokenizer.model").exists():  # SentencePiece-only checkpoint
            kw["tokenizer"] = str(p / "tokenizer.model")
        tc_file = p / "tokenizer_config.json"
        if tc_file.exists():
            tc = json.loads(tc_file.read_text())
            if tc.get("chat_template"):
                kw["chat_template"] = tc["chat_template"]
        kw.update(overrides)
        return cls(**kw)

    async def move_to_store(self, objects: Any) -> "ModelDeploymentCard":
        """Upload file artifacts to the object store, rewriting paths to
        ``object://`` URLs — after this the card is fully portable: any
        worker joined to the deployment store can serve it.

        Parity: reference ``move_to_nats`` (`model_card/model.rs:230-326`).
        """
        tok = self.tokenizer
        if tok and tok not in ("byte",) and not str(tok).startswith("object://"):
            p = pathlib.Path(tok)
            if p.is_dir():
                # A model dir: ship the tokenizer artifact, not the weights.
                for candidate in ("tokenizer.json", "tokenizer.model"):
                    if (p / candidate).exists():
                        p = p / candidate
                        break
            if p.is_file() and p.suffix != ".gguf":
                self.tokenizer = await objects.put_file(f"cards/{self.name}/{p.name}", p)
            elif p.suffix == ".gguf":
                # The GGUF *is* the checkpoint — workers resolve it from the
                # model path (shared storage), not the artifact plane.
                logger.debug("card %s: leaving GGUF tokenizer path as-is", self.name)
        return self

    async def resolve_from_store(self, objects: Any, cache_dir: str | pathlib.Path) -> "ModelDeploymentCard":
        """Materialize ``object://`` artifacts into ``cache_dir`` and point
        the card back at local files (worker-side ``move_from_nats``)."""
        from dynamo_tpu.runtime.objects import is_object_url, object_name

        if is_object_url(self.tokenizer):
            name = object_name(self.tokenizer)
            local = pathlib.Path(cache_dir) / name
            await objects.get_to_file(name, local)
            self.tokenizer = str(local)
        return self

    @classmethod
    def from_gguf(cls, name: str, path: str | pathlib.Path, *, reader: Any | None = None, **overrides: Any) -> "ModelDeploymentCard":
        """Build a card from a GGUF file's metadata (embedded tokenizer,
        context length, special token ids, chat template). Pass an open
        ``reader`` to reuse an already-parsed header (the caller keeps
        ownership and closes it).

        Parity: reference `model_card/create.rs` + `model.rs:583` (card from
        GGUF vs HF repo)."""
        from dynamo_tpu.models.gguf import GGUFReader

        owned = reader is None
        reader = reader or GGUFReader(path)
        try:
            md = reader.metadata
            arch = md.get("general.architecture", "llama")
            kw: dict[str, Any] = {
                "name": name,
                "tokenizer": str(path),  # load_tokenizer understands .gguf
                "context_length": int(md.get(f"{arch}.context_length", 4096)),
            }
            if "tokenizer.ggml.eos_token_id" in md:
                kw["eos_token_ids"] = [int(md["tokenizer.ggml.eos_token_id"])]
            if "tokenizer.ggml.bos_token_id" in md:
                kw["bos_token_id"] = int(md["tokenizer.ggml.bos_token_id"])
            if md.get("tokenizer.chat_template"):
                kw["chat_template"] = md["tokenizer.chat_template"]
            kw.update(overrides)
            return cls(**kw)
        finally:
            if owned:
                reader.close()
