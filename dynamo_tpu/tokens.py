"""Token sequences and chained block hashing — the canonical prefix-cache identity.

Every KV-cache block in the system (engine paged cache, block manager tiers,
router radix index) is identified by a *sequence hash*: a chained xxh3-64 over
the block's tokens and the parent block's sequence hash. Two workers that have
processed the same prefix therefore derive the same block identities with no
coordination, which is what makes global KV-aware routing and cross-worker KV
reuse possible.

Capability parity: reference `lib/tokens/src/lib.rs:50-369` (Tokens,
TokenBlock, TokenBlockSequence, chained SequenceHash = xxh3 w/ salt) and
`lib/llm/src/kv_router/indexer.rs:122` (compute_block_hash_for_seq). The
design here is fresh: a flat numpy-backed sequence with incremental
append/commit, since the Python/JAX engine works in numpy token arrays.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Sequence

import numpy as np
import xxhash

try:  # native chained-hash kernel (build: `make -C native`); pure-Python fallback below
    from dynamo_tpu import _dyncore
except ImportError:  # pragma: no cover - image without the built extension
    _dyncore = None

# Salt mixed into every block hash so sequence hashes are namespaced to this
# framework's cache-identity scheme (mirrors the reference's hash salt).
DEFAULT_SALT: int = 0xD1A2_0001


def mm_salt_fold(mm_inputs) -> int:
    """Content hash folded into block-hash salts for multimodal requests.

    Identical prompts with different images must have different prefix-cache
    identities; the engine AND the KV router must fold the same value or the
    router's overlap lookups never match the worker's published hashes."""
    if not mm_inputs or not isinstance(mm_inputs, dict):
        return 0
    import hashlib

    payload = str(mm_inputs.get("embeds_b64") or "").encode()
    return int.from_bytes(hashlib.blake2b(payload, digest_size=8).digest(), "big")

_U64 = np.dtype("<u8")
_I32 = np.dtype("<i4")


def _hash_bytes(data: bytes, seed: int) -> int:
    if _dyncore is not None:
        return _dyncore.hash_bytes(data, seed)
    return xxhash.xxh3_64_intdigest(data, seed=seed)


def hash_token_block(tokens: Sequence[int] | np.ndarray, parent_hash: int | None, *, salt: int = DEFAULT_SALT) -> int:
    """Chained hash of one block: xxh3(parent_hash_le8 || tokens_le4, seed=salt).

    ``parent_hash=None`` marks the root block (no parent bytes are mixed in,
    so a sequence's first block hash depends only on its tokens + salt).
    """
    arr = np.ascontiguousarray(np.asarray(tokens, dtype=np.uint32), dtype=_I32)
    if parent_hash is None:
        payload = arr.tobytes()
    else:
        payload = np.uint64(parent_hash).astype(_U64).tobytes() + arr.tobytes()
    return _hash_bytes(payload, seed=salt)


def compute_block_hashes(
    tokens: Sequence[int] | np.ndarray,
    block_size: int,
    *,
    salt: int = DEFAULT_SALT,
) -> list[int]:
    """Sequence hashes for every *complete* block of ``tokens``.

    The trailing partial block (``len(tokens) % block_size`` tokens) has no
    identity yet and is excluded — identical to how the engine only publishes
    KV events for full blocks.
    """
    if block_size <= 0:
        raise ValueError(f"block_size must be positive, got {block_size}")
    arr = np.asarray(tokens, dtype=np.uint32)
    n_full = len(arr) // block_size
    if _dyncore is not None:
        # Native chained-hash loop (native/dyncore.cpp): one C call for the
        # whole prompt instead of per-block Python bytes assembly — this
        # runs for every request on both the router and the engine. The C
        # side drops the partial tail itself; pass the buffer, not a copy
        # (u4 and <i4 bytes are identical on little-endian hosts).
        return _dyncore.block_hashes(memoryview(np.ascontiguousarray(arr)), block_size, salt)
    hashes: list[int] = []
    parent: int | None = None
    for i in range(n_full):
        h = hash_token_block(arr[i * block_size : (i + 1) * block_size], parent, salt=salt)
        hashes.append(h)
        parent = h
    return hashes


@dataclass(frozen=True)
class TokenBlock:
    """An immutable, complete block of ``block_size`` tokens with its chained identity."""

    tokens: tuple[int, ...]
    block_hash: int
    parent_hash: int | None
    position: int  # block index within the sequence

    @property
    def block_size(self) -> int:
        return len(self.tokens)


class TokenBlockSequence:
    """A token stream chunked into hash-chained blocks, supporting incremental append.

    Used by the engine scheduler to derive block identities as a request's
    sequence grows during decode: each time the partial tail fills a block, the
    block is committed, gains a sequence hash, and (at the engine layer) a KV
    "stored" event is emitted for it.
    """

    def __init__(
        self,
        tokens: Sequence[int] | np.ndarray | None = None,
        *,
        block_size: int,
        salt: int = DEFAULT_SALT,
    ) -> None:
        if block_size <= 0:
            raise ValueError(f"block_size must be positive, got {block_size}")
        self.block_size = block_size
        self.salt = salt
        self._blocks: list[TokenBlock] = []
        self._partial: list[int] = []
        if tokens is not None:
            self.extend(tokens)

    # -- growth ------------------------------------------------------------

    def append(self, token: int) -> TokenBlock | None:
        """Append one token; returns the newly-committed block if the tail filled."""
        self._partial.append(int(token))
        if len(self._partial) == self.block_size:
            return self._commit_partial()
        return None

    def extend(self, tokens: Iterable[int]) -> list[TokenBlock]:
        """Append many tokens; returns all blocks committed as a result."""
        committed: list[TokenBlock] = []
        for t in tokens:
            blk = self.append(int(t))
            if blk is not None:
                committed.append(blk)
        return committed

    def _commit_partial(self) -> TokenBlock:
        parent = self._blocks[-1].block_hash if self._blocks else None
        h = hash_token_block(self._partial, parent, salt=self.salt)
        blk = TokenBlock(
            tokens=tuple(self._partial),
            block_hash=h,
            parent_hash=parent,
            position=len(self._blocks),
        )
        self._blocks.append(blk)
        self._partial = []
        return blk

    # -- truncation (sequence rewind, e.g. on preemption/restart) ----------

    def truncate(self, num_tokens: int) -> None:
        """Rewind the sequence to its first ``num_tokens`` tokens."""
        if num_tokens > len(self):
            raise ValueError(f"cannot truncate to {num_tokens}, sequence has {len(self)}")
        all_tokens = self.tokens
        self._blocks = []
        self._partial = []
        self.extend(all_tokens[:num_tokens])

    # -- views -------------------------------------------------------------

    @property
    def blocks(self) -> list[TokenBlock]:
        return list(self._blocks)

    @property
    def block_hashes(self) -> list[int]:
        return [b.block_hash for b in self._blocks]

    @property
    def partial_tokens(self) -> list[int]:
        return list(self._partial)

    @property
    def tokens(self) -> np.ndarray:
        full = [t for b in self._blocks for t in b.tokens]
        return np.asarray(full + self._partial, dtype=np.int32)

    def __len__(self) -> int:
        return len(self._blocks) * self.block_size + len(self._partial)

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"TokenBlockSequence(len={len(self)}, blocks={len(self._blocks)}, "
            f"partial={len(self._partial)}, block_size={self.block_size})"
        )


@dataclass(frozen=True)
class SaltedPrefix:
    """Optional per-model/per-lora salt prefix for cache identity separation.

    Two deployments serving different weights must never share block
    identities; mixing a model-unique value into the salt guarantees it.
    """

    model_id: str
    base_salt: int = DEFAULT_SALT

    @property
    def salt(self) -> int:
        return _hash_bytes(self.model_id.encode(), seed=self.base_salt) & 0xFFFF_FFFF_FFFF_FFFF
