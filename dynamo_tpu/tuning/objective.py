"""Trial scoring: bench keys joined with the lost-time vocabulary.

A trial is scored from what the probe measured — throughput (tok/s), tail
latency (ITL p99, TTFT p50) — *and* where its wall clock went, per the
pinned attribution vocabulary (:data:`LOSS_CAUSES`). Raw throughput alone
would happily trade a 2x ITL tail for 5% more tok/s; latency targets alone
would pin every knob at its most conservative rung. The join optimizes
goodput while explicitly driving the *burnable* loss causes — the host
``gap`` plus every overlap-barrier reason — toward the burn-down target
ROADMAP item 3 sets: under 5% of wall.

Scores are comparable only within one probe configuration (same preset,
workload shape, platform); the search never mixes rungs of different probe
lengths into one argmax without re-measuring.
"""

from __future__ import annotations

from dynamo_tpu.engine.core import BARRIER_REASONS

#: The burn-down target: gap + barrier:* may consume at most this fraction
#: of step wall time before the objective starts discounting the trial.
BURN_DOWN_TARGET = 0.05

#: Loss causes the tuner can actually burn down with the knobs it sweeps —
#: the host gap between dispatches and every overlap-barrier reason.
#: Pre-admission waits (queue/admission) price load, not knob settings.
BURNABLE_CAUSES = tuple(BARRIER_REASONS) + ("gap", "onboard_stall", "recompile")


def burn_down(loss: dict) -> dict:
    """Per-cause fractions of step wall time, from a loss snapshot (delta).

    ``loss`` is an ``EngineCore.loss_snapshot()``-shaped dict (typically the
    measured pass's delta). Returns stable keys:

    - ``frac_by_cause``: each charged cause as a fraction of ``wall + gap``
      (the full serving timeline the step loop owned).
    - ``burnable_frac``: the sum over :data:`BURNABLE_CAUSES` — the number
      the burn-down target bounds.
    - ``target`` / ``met``: :data:`BURN_DOWN_TARGET` and whether this trial
      is under it.
    """
    step = loss.get("step_time_ms", {})
    wall = float(step.get("wall", 0.0)) + float(step.get("gap", 0.0))
    lost = loss.get("lost_time_ms", {})
    frac = {
        cause: (float(ms) / wall if wall > 0.0 else 0.0)
        for cause, ms in sorted(lost.items())
    }
    burnable = sum(f for cause, f in frac.items() if cause in BURNABLE_CAUSES)
    return {
        "frac_by_cause": frac,
        "burnable_frac": burnable,
        "target": BURN_DOWN_TARGET,
        "met": burnable <= BURN_DOWN_TARGET,
    }


def score_trial(
    metrics: dict,
    *,
    itl_p99_target_ms: float = 50.0,
    ttft_p50_target_ms: float = 500.0,
) -> tuple[float, dict]:
    """Score one trial; higher is better.

    ``metrics`` carries the probe's bench keys (``tok_per_sec``,
    ``itl_p99_ms``, ``ttft_p50_ms``) and ``loss`` (the measured pass's
    loss-snapshot delta). The score is throughput discounted by three
    multiplicative factors, each 1.0 when its budget is respected:

    - ``itl_factor`` / ``ttft_factor``: ``target / actual`` once the tail
      overshoots its SLO target — goodput, not raw throughput.
    - ``burn_factor``: ``1 - (burnable_frac - target)`` once the burnable
      lost-time fraction exceeds the burn-down target, so two trials with
      equal goodput rank by how little serving time they waste.

    Returns ``(score, breakdown)``; the breakdown lands in the trial
    journal so a report can explain every ranking.
    """
    tok = float(metrics.get("tok_per_sec", 0.0))
    itl = float(metrics.get("itl_p99_ms", 0.0))
    ttft = float(metrics.get("ttft_p50_ms", 0.0))
    itl_factor = min(1.0, itl_p99_target_ms / itl) if itl > itl_p99_target_ms else 1.0
    ttft_factor = (
        min(1.0, ttft_p50_target_ms / ttft) if ttft > ttft_p50_target_ms else 1.0
    )
    burn = burn_down(metrics.get("loss", {}))
    burn_factor = max(0.0, 1.0 - max(0.0, burn["burnable_frac"] - burn["target"]))
    score = tok * itl_factor * ttft_factor * burn_factor
    return score, {
        "tok_per_sec": tok,
        "itl_p99_ms": itl,
        "itl_factor": round(itl_factor, 4),
        "ttft_p50_ms": ttft,
        "ttft_factor": round(ttft_factor, 4),
        "burnable_frac": round(burn["burnable_frac"], 4),
        "burn_target": burn["target"],
        "burn_factor": round(burn_factor, 4),
        "frac_by_cause": {c: round(f, 4) for c, f in burn["frac_by_cause"].items()},
        "score": round(score, 4),
    }
