"""Closed-loop auto-tuner: search the performance knob space against the
bench keys *joined with* the lost-time vocabulary, and converge on a
per-(model, batch-shape, platform) knob profile.

ROADMAP item 3's "the loop itself": PR 15 built the instrument (the pinned
16-cause lost-time ledger + critical-path budgets) and PR 16 the kernels
(packed int4, vectorized masks); this package closes the loop the way the
reference's planner closes observed-load -> resource decisions — but aimed
at per-host kernel/scheduler knobs instead of fleet sizing.

Layout:

- :mod:`~dynamo_tpu.tuning.space` — the knob registry: typed, bounded,
  sweepable knobs, each mapped to the config-cascade env name
  ``tools/check_env_knobs.py`` already enforces.
- :mod:`~dynamo_tpu.tuning.objective` — trial scoring: goodput from the
  probe's bench keys (tok/s, ITL p99, TTFT p50) discounted by the
  burnable lost-time fraction (``gap`` + barrier causes vs. the
  <5%-of-wall burn-down target).
- :mod:`~dynamo_tpu.tuning.probe` — the trial evaluator: one seeded
  mixed workload on a real ``EngineCore`` (CPU mock proxy or a real JAX
  preset), dry-run-then-measure like every bench probe, returning bench
  keys + the ``loss_snapshot()`` delta of the measured pass.
- :mod:`~dynamo_tpu.tuning.search` — coordinate descent with
  successive halving, resumable JSONL trial journals under
  ``bench/results/tune/``, and a plateau early-stop rule.
- :mod:`~dynamo_tpu.tuning.profile` — the winning-profile JSON artifact
  ``launch.py --tune-profile`` loads (explicit env/CLI still wins).
- :mod:`~dynamo_tpu.tuning.metrics` — ``dynamo_tuner_trials_total`` /
  ``dynamo_tuner_best_score``.

Entry points: ``python -m dynamo_tpu.tuning`` and ``bench.py --tune``.
"""

from dynamo_tpu.tuning.objective import BURN_DOWN_TARGET, burn_down, score_trial
from dynamo_tpu.tuning.profile import apply_profile, load_profile, make_profile, save_profile
from dynamo_tpu.tuning.search import Tuner
from dynamo_tpu.tuning.space import KNOBS, Knob, default_assignment, get_knob, select_knobs

__all__ = [
    "BURN_DOWN_TARGET",
    "KNOBS",
    "Knob",
    "Tuner",
    "apply_profile",
    "burn_down",
    "default_assignment",
    "get_knob",
    "load_profile",
    "make_profile",
    "save_profile",
    "score_trial",
    "select_knobs",
]
