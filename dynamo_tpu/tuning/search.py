"""The search driver: coordinate descent + successive halving + journal.

The space is small and discrete (each knob an explicit ladder), trials are
seconds-scale, and knob interactions are mostly separable — so the search
is deliberately simple and *auditable* rather than clever:

- **Coordinate descent**: sweep one knob at a time in registry order,
  holding the incumbent assignment for the rest; accept a move only when
  its full-length probe beats the incumbent by more than ``plateau_eps``.
- **Successive halving** per coordinate: every candidate first runs a
  short probe (``rung_frac`` of the full request count); only the top
  half graduates to full-length probes. Short probes never rank against
  full probes — the argmax is always taken within one rung.
- **Plateau early-stop**: a full round with no accepted move counts as a
  plateau; ``plateau_rounds`` consecutive plateaus (or the round budget,
  or ``max_trials``) ends the search.

Every probe lands in a resumable JSONL journal keyed by (assignment,
probe length): re-running the same search replays completed trials from
the journal instead of re-measuring, so an interrupted session continues
where it stopped and a finished one is fully deterministic to re-audit.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import os
import time
from typing import Callable

from dynamo_tpu.config import TuneSettings
from dynamo_tpu.tuning.objective import burn_down, score_trial
from dynamo_tpu.tuning.space import Knob, default_assignment, select_knobs

logger = logging.getLogger(__name__)


class BudgetExhausted(Exception):
    """Raised internally when ``max_trials`` measured probes are spent."""


class TrialJournal:
    """Append-only JSONL trial log; the resume cache is its replay."""

    def __init__(self, path: str | os.PathLike) -> None:
        self.path = str(path)
        self._cache: dict[str, dict] = {}
        if os.path.exists(self.path):
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    entry = json.loads(line)
                    self._cache[entry["key"]] = entry
        self.loaded = len(self._cache)

    @staticmethod
    def key(assignment: dict[str, int], requests: int) -> str:
        return json.dumps(
            {"assignment": dict(sorted(assignment.items())), "requests": requests},
            sort_keys=True, separators=(",", ":"),
        )

    def lookup(self, assignment: dict[str, int], requests: int) -> dict | None:
        return self._cache.get(self.key(assignment, requests))

    def record(self, entry: dict) -> None:
        self._cache[entry["key"]] = entry
        os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(entry, sort_keys=True) + "\n")


class Tuner:
    """Closed-loop knob search for one (preset, workload-shape, platform).

    ``probe_fn(assignment, requests) -> metrics`` defaults to the real
    engine probe; tests inject synthetic objectives through it.
    """

    def __init__(
        self,
        settings: TuneSettings | None = None,
        *,
        probe_fn: Callable[[dict, int], dict] | None = None,
        knobs: tuple[Knob, ...] | None = None,
        metrics=None,
    ) -> None:
        self.settings = settings or TuneSettings()
        s = self.settings
        if probe_fn is None:
            from dynamo_tpu.tuning.probe import run_probe

            probe_fn = lambda assignment, requests: run_probe(  # noqa: E731
                assignment, s, requests=requests
            )
        self.probe_fn = probe_fn
        self.knobs = knobs if knobs is not None else select_knobs(
            s.knobs, hardware=(s.mode != "mock")
        )
        if not self.knobs:
            raise ValueError("tuner has no knobs to sweep")
        self.journal = TrialJournal(os.path.join(s.out_dir, "journal.jsonl"))
        self.metrics = metrics
        self.trials_measured = 0
        self.trials_cached = 0

    # -- trial evaluation --------------------------------------------------

    def evaluate(self, assignment: dict[str, int], requests: int) -> dict:
        key = TrialJournal.key(assignment, requests)
        cached = self.journal.lookup(assignment, requests)
        if cached is not None:
            self.trials_cached += 1
            return cached
        s = self.settings
        if s.max_trials and self.trials_measured >= s.max_trials:
            raise BudgetExhausted(f"max_trials={s.max_trials} measured probes spent")
        t0 = time.perf_counter()
        metrics = self.probe_fn(assignment, requests)
        score, breakdown = score_trial(metrics)
        self.trials_measured += 1
        entry = {
            "key": key,
            "trial": self.trials_measured,
            "assignment": dict(sorted(assignment.items())),
            "requests": requests,
            "metrics": metrics,
            "score": round(score, 4),
            "breakdown": breakdown,
            "probe_wall_s": round(time.perf_counter() - t0, 3),
        }
        self.journal.record(entry)
        if self.metrics is not None:
            self.metrics.observe_trial(s.preset, s.mode)
        return entry

    # -- the loop ----------------------------------------------------------

    def _sweep_knob(self, knob: Knob, current: dict[str, int], best: dict) -> tuple[dict[str, int], dict, bool]:
        """One coordinate: halve candidates on short probes, settle on full."""
        s = self.settings
        short = max(2, int(math.ceil(s.requests * s.rung_frac)))
        rung0 = [
            (value, self.evaluate({**current, knob.name: value}, short))
            for value in knob.candidates
        ]
        keep = max(1, math.ceil(len(rung0) / 2))
        survivors = sorted(rung0, key=lambda r: -r[1]["score"])[:keep]
        # Settle survivors at full length, in ladder order (deterministic).
        finalists = [
            (value, self.evaluate({**current, knob.name: value}, s.requests))
            for value, _ in sorted(survivors, key=lambda r: knob.candidates.index(r[0]))
        ]
        value, entry = max(finalists, key=lambda r: r[1]["score"])
        if value != current[knob.name] and entry["score"] > best["score"] * (1.0 + s.plateau_eps):
            logger.info(
                "tuner: %s %s -> %s (score %.2f -> %.2f)",
                knob.name, current[knob.name], value, best["score"], entry["score"],
            )
            return {**current, knob.name: value}, entry, True
        return current, best, False

    def run(self) -> dict:
        """Run the search to convergence; write profile + report; return the
        report document."""
        s = self.settings
        current = default_assignment(self.knobs)
        stopped = "rounds"
        history: list[dict] = []
        try:
            baseline = self.evaluate(current, s.requests)
            best = baseline
            plateaus = 0
            for round_no in range(1, s.rounds + 1):
                moved = False
                for knob in self.knobs:
                    current, best, accepted = self._sweep_knob(knob, current, best)
                    if accepted:
                        moved = True
                        history.append({
                            "round": round_no, "knob": knob.name,
                            "value": current[knob.name],
                            "score": best["score"],
                        })
                        if self.metrics is not None:
                            self.metrics.set_best(s.preset, s.mode, best["score"])
                if not moved:
                    plateaus += 1
                    if plateaus >= s.plateau_rounds:
                        stopped = "plateau"
                        break
                else:
                    plateaus = 0
        except BudgetExhausted as exc:
            logger.info("tuner: %s", exc)
            stopped = "budget"
        return self._finalize(current, baseline, best, history, stopped)

    # -- artifacts ---------------------------------------------------------

    def _finalize(
        self, assignment: dict[str, int], baseline: dict, best: dict,
        history: list[dict], stopped: str,
    ) -> dict:
        from dynamo_tpu.tuning.profile import make_profile, save_profile

        s = self.settings
        try:
            import jax

            platform = jax.default_backend()
        except Exception:
            platform = "unknown"
        profile = make_profile(
            assignment,
            preset=s.preset, mode=s.mode, platform=platform,
            score=best["score"], baseline_score=baseline["score"],
            meta={
                "requests": s.requests, "isl": s.isl, "osl": s.osl,
                "seed": s.seed, "stopped": stopped,
                "trials_measured": self.trials_measured,
            },
        )
        base_burn = burn_down(baseline["metrics"].get("loss", {}))
        best_burn = burn_down(best["metrics"].get("loss", {}))
        causes = sorted(
            set(base_burn["frac_by_cause"]) | set(best_burn["frac_by_cause"])
        )
        report = {
            "settings": dataclasses.asdict(s),
            "platform": platform,
            "knobs_swept": [k.name for k in self.knobs],
            "baseline": baseline,
            "best": best,
            "gain": round(best["score"] / baseline["score"], 4)
            if baseline["score"] else 0.0,
            "stopped": stopped,
            "trials_measured": self.trials_measured,
            "trials_cached": self.trials_cached,
            "history": history,
            # The per-cause burn-down story: where the winning profile's
            # wall-time went vs. the untuned default's, as fractions of
            # each run's own serving timeline.
            "burn_down": {
                "target": base_burn["target"],
                "baseline_burnable_frac": round(base_burn["burnable_frac"], 4),
                "best_burnable_frac": round(best_burn["burnable_frac"], 4),
                "baseline_met": base_burn["met"],
                "best_met": best_burn["met"],
                "frac_by_cause": {
                    cause: {
                        "baseline": round(base_burn["frac_by_cause"].get(cause, 0.0), 4),
                        "best": round(best_burn["frac_by_cause"].get(cause, 0.0), 4),
                    }
                    for cause in causes
                },
            },
        }
        os.makedirs(s.out_dir, exist_ok=True)
        profile_path = os.path.join(s.out_dir, "profile.json")
        report_path = os.path.join(s.out_dir, "report.json")
        save_profile(profile_path, profile)
        with open(report_path, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        report["profile_path"] = profile_path
        report["report_path"] = report_path
        report["journal_path"] = self.journal.path
        return report
