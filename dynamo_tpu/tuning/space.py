"""The knob-space registry: every performance knob the auto-tuner may sweep.

Each :class:`Knob` is typed, bounded (an explicit ordered candidate ladder —
no unbounded numeric search), and mapped to the config-cascade env name that
``tools/check_env_knobs.py`` already enforces, so a tuned profile is just a
set of documented env assignments any deployment already understands.

Knobs that the probe can apply directly on :class:`EngineConfig` carry an
``engine_field``; the rest are applied as a scoped env overlay around the
trial (their readers resolve the env at trace/connect time). Knobs whose
effect only exists on real hardware (``hardware_only``) are skipped by the
CPU mock proxy unless explicitly requested — sweeping them there would just
fit timing noise — but sweep normally under the ``jax`` probe on a chip.
"""

from __future__ import annotations

import dataclasses

_MIB = 1024 * 1024


@dataclasses.dataclass(frozen=True)
class Knob:
    """One sweepable performance knob.

    ``candidates`` is the full ordered ladder INCLUDING ``default`` — the
    search compares every rung against the incumbent, so the default must
    be reachable (and re-winnable) like any other value.
    """

    name: str  # tuner-facing short name (journal / profile keys)
    env: str  # config-cascade env name (check_env_knobs-enforced)
    candidates: tuple[int, ...]  # ordered sweep ladder
    default: int  # untuned default (mirrors the reader's own default)
    layer: str  # scheduler | engine | kernel | quant | wire | tiers
    doc: str
    engine_field: str | None = None  # EngineConfig field, when one exists
    hardware_only: bool = False  # no observable effect on the CPU proxy

    def __post_init__(self) -> None:
        if self.default not in self.candidates:
            raise ValueError(
                f"knob {self.name}: default {self.default} not in candidates"
            )


#: The registry. Order is the coordinate-descent sweep order: scheduler-level
#: knobs first (largest, most portable effects), hardware-bound knobs last.
KNOBS: tuple[Knob, ...] = (
    Knob(
        name="chunk_prefill_tokens",
        env="DYN_WORKER_CHUNK_PREFILL_TOKENS",
        candidates=(128, 256, 512, 1024),
        default=512,
        layer="scheduler",
        doc="Per-step prefill chunk budget fused with decodes; smaller "
        "bounds decode stalls (ITL), larger finishes prefills (TTFT).",
        engine_field="chunk_prefill_tokens",
    ),
    Knob(
        name="decode_steps",
        env="DYN_WORKER_DECODE_STEPS",
        candidates=(1, 2, 4, 8),
        default=1,
        layer="engine",
        doc="Fused decode steps per device dispatch; amortizes dispatch "
        "and device->host copies at the cost of coarser token delivery.",
        engine_field="decode_steps",
    ),
    Knob(
        name="spec_k",
        env="DYN_WORKER_SPEC_K",
        candidates=(0, 2, 4),
        default=0,
        layer="engine",
        doc="Speculative-decoding draft length (lossless n-gram "
        "self-drafting); pays verify overhead for multi-token steps.",
        engine_field="spec_k",
    ),
    Knob(
        name="decode_splits",
        env="DYN_DECODE_SPLITS",
        candidates=(0, 2, 4, 8),
        default=0,
        layer="kernel",
        doc="Split-K factor of the paged-attention decode kernel "
        "(0 = shape heuristic); resolved at trace time.",
        hardware_only=True,
    ),
    Knob(
        name="quant_group_size",
        env="DYN_QUANT_GROUP_SIZE",
        candidates=(32, 64, 128, 256),
        default=128,
        layer="quant",
        doc="int4 weight-quantization group width along the contraction "
        "axis; trades scale-stream bytes against dequant granularity.",
        hardware_only=True,
    ),
    Knob(
        name="kv_wire_inflight",
        env="DYN_KV_WIRE_INFLIGHT",
        candidates=(64 * _MIB, 128 * _MIB, 256 * _MIB, 512 * _MIB),
        default=256 * _MIB,
        layer="wire",
        doc="KV-wire in-flight byte budget across sessions (the DMA-depth "
        "analog): deeper hides RTT, shallower bounds receiver staging.",
        hardware_only=True,
    ),
    Knob(
        name="onboard_pool_width",
        env="DYN_ONBOARD_POOL_WIDTH",
        candidates=(1, 2, 4, 8),
        default=2,
        layer="tiers",
        doc="KV-tier onboard fetch pool width; wider overlaps more tier "
        "reads with the forward pass but contends for HBM bandwidth.",
        hardware_only=True,
    ),
)

_BY_NAME = {k.name: k for k in KNOBS}


def get_knob(name: str) -> Knob:
    try:
        return _BY_NAME[name]
    except KeyError:
        raise KeyError(
            f"unknown knob {name!r}; known: {sorted(_BY_NAME)}"
        ) from None


def select_knobs(names: str | list[str] | None = None, *, hardware: bool = True) -> tuple[Knob, ...]:
    """The knobs a search sweeps.

    ``names`` (comma string or list) restricts to an explicit subset — and
    overrides the hardware filter, so a CPU run can still force-sweep a
    hardware knob for loop testing. Otherwise ``hardware=False`` (the mock
    proxy) drops ``hardware_only`` knobs.
    """
    if names:
        if isinstance(names, str):
            names = [n.strip() for n in names.split(",") if n.strip()]
        return tuple(get_knob(n) for n in names)
    return tuple(k for k in KNOBS if hardware or not k.hardware_only)


def default_assignment(knobs: tuple[Knob, ...] = KNOBS) -> dict[str, int]:
    """The untuned baseline point of the space."""
    return {k.name: k.default for k in knobs}


def assignment_env(assignment: dict[str, int]) -> dict[str, str]:
    """An assignment as the env overlay its readers resolve."""
    return {get_knob(name).env: str(value) for name, value in assignment.items()}


def validate_assignment(assignment: dict[str, int]) -> None:
    for name, value in assignment.items():
        knob = get_knob(name)
        if value not in knob.candidates:
            raise ValueError(
                f"knob {name}: value {value} not on its ladder {knob.candidates}"
            )
