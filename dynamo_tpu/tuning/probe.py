"""The trial evaluator: one seeded mixed workload on a real ``EngineCore``.

Every trial runs the *identical* scenario — N seeded prompts, half admitted
up front and the rest dripped in to force mixed prefill+decode steps — and
reports the bench keys the objective consumes (tok/s, ITL p50/p99, TTFT
p50) joined with the measured pass's ``loss_snapshot()`` delta. Two probe
backends share the scenario:

- ``mock`` — the CPU proxy: ``MockRunner`` realtime timing (the fleetsim
  engine), CI-scale seconds per trial. Engine/scheduler knobs move real
  scheduling decisions; kernel-layer knobs are inert here (the space marks
  them ``hardware_only``).
- ``jax`` — a real model preset through ``ModelRunner``; the same code
  path scales unchanged to a chip (swap the preset, keep the discipline).

Trials are comparable because each one follows the bench suite's warm-up
rule: the scenario runs TWICE on one engine and only the second pass is
measured — the step-bucket lattice is data-dependent, so the only warm-up
that provably compiles (or warms) every shape the measurement hits is an
identical dry run. Knobs without an ``EngineConfig`` field are applied as
a scoped env overlay restored after the trial.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Iterator

import numpy as np

from dynamo_tpu.config import TuneSettings
from dynamo_tpu.tuning.space import get_knob, validate_assignment


def _pct(xs: list[float], p: float) -> float:
    return xs[min(len(xs) - 1, int(p * len(xs)))] if xs else 0.0


def _delta(after: dict, before: dict) -> dict:
    """Elementwise numeric delta of two loss snapshots (nested dicts)."""
    out: dict = {}
    for key, a in after.items():
        b = before.get(key)
        if isinstance(a, dict):
            out[key] = _delta(a, b if isinstance(b, dict) else {})
        elif isinstance(a, (int, float)):
            out[key] = a - (b if isinstance(b, (int, float)) else 0)
        else:
            out[key] = a
    return out


@contextlib.contextmanager
def env_overlay(assignment: dict[str, int]) -> Iterator[None]:
    """Apply the env-mapped knobs of ``assignment`` for the trial's scope.

    Every knob is exported (engine-field knobs too — their env readers are
    the source of truth for subsystems the probe does not construct
    directly), and the prior environment is restored exactly on exit so
    trials cannot leak settings into each other or the caller.
    """
    saved: dict[str, str | None] = {}
    try:
        for name, value in assignment.items():
            env_name = get_knob(name).env
            saved[env_name] = os.environ.get(env_name)
            os.environ[env_name] = str(value)
        yield
    finally:
        for env_name, prior in saved.items():
            if prior is None:
                os.environ.pop(env_name, None)
            else:
                os.environ[env_name] = prior


def _build_core(assignment: dict[str, int], settings: TuneSettings, requests: int):
    from dynamo_tpu.engine.core import EngineConfig

    isl, osl = settings.isl, settings.osl
    page_size = 16 if settings.mode == "mock" else 64
    num_pages = requests * ((isl + osl) // page_size + 2) + 16
    cfg = EngineConfig(
        num_pages=num_pages,
        page_size=page_size,
        max_batch_size=requests + 2,
        max_prefill_tokens=max(isl * requests, isl),
        max_seq_len=isl + osl + 8,
        enable_prefix_caching=False,
        chunk_prefill_tokens=int(assignment.get("chunk_prefill_tokens", 512)),
        decode_steps=int(assignment.get("decode_steps", 1)),
        spec_k=int(assignment.get("spec_k", 0)),
    )
    if settings.mode == "mock":
        from dynamo_tpu.mocker import build_mock_core

        return build_mock_core(cfg, seed=settings.seed, d2h_us=200.0), 32000
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS

    model_cfg = PRESETS[settings.preset]
    params = llama.init_params(model_cfg, 0)
    runner = ModelRunner(
        model_cfg, params, num_pages=num_pages, page_size=page_size,
        max_batch_size=requests + 2, prefill_bucket=max(isl, 64),
    )
    from dynamo_tpu.engine.core import EngineCore

    return EngineCore(runner, cfg), model_cfg.vocab_size


def _prompts(rng: np.random.Generator, requests: int, isl: int, vocab: int) -> list[list[int]]:
    """Seeded prompts, half patterned so the n-gram drafter has structure
    (the regime spec_k targets; uniform-random text pins acceptance at 0)."""
    pattern = rng.integers(1, vocab - 1, size=16).tolist()
    out = []
    for i in range(requests):
        if i % 2 == 0:
            reps = isl // len(pattern) + 1
            out.append((pattern * reps)[:isl])
        else:
            out.append(rng.integers(1, vocab - 1, size=isl).tolist())
    return out


def run_probe(
    assignment: dict[str, int],
    settings: TuneSettings,
    *,
    requests: int | None = None,
) -> dict:
    """Evaluate one knob assignment; returns the objective's metric dict.

    Keys: ``tok_per_sec``, ``itl_p50_ms``, ``itl_p99_ms``, ``ttft_p50_ms``,
    ``generated_tokens``, ``steps``, ``elapsed_s``, and ``loss`` — the
    measured pass's ``EngineCore.loss_snapshot()`` delta.
    """
    from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions

    validate_assignment(assignment)
    requests = requests or settings.requests
    with env_overlay(assignment):
        core, vocab = _build_core(assignment, settings, requests)
        rng = np.random.default_rng(settings.seed)
        prompts = _prompts(rng, requests, settings.isl, vocab)

        def scenario() -> dict:
            def submit(tokens: list[int]):
                return core.add_request(PreprocessedRequest(
                    token_ids=list(tokens),
                    sampling=SamplingOptions(temperature=0.0),
                    stop=StopConditions(max_tokens=settings.osl, ignore_eos=True),
                ))

            t0 = time.perf_counter()
            submitted: dict[int, float] = {}
            emits: dict[int, list[float]] = {}
            first: dict[int, float] = {}
            # Half the load up front, the rest dripped one per step: forces
            # the mixed prefill+decode regime every knob here is about.
            pending = list(prompts)
            for _ in range(max(1, requests // 2)):
                seq = submit(pending.pop(0))
                submitted[seq.seq_id] = time.perf_counter()
                emits[seq.seq_id] = []
            steps = 0
            generated = 0
            last_emit = t0
            while core.has_work or pending:
                if pending and steps % 2 == 0:
                    seq = submit(pending.pop(0))
                    submitted[seq.seq_id] = time.perf_counter()
                    emits[seq.seq_id] = []
                outputs = core.step()
                now = time.perf_counter()
                steps += 1
                for seq, out in outputs:
                    n = len(out.token_ids)
                    if not n:
                        continue
                    generated += n
                    last_emit = now
                    first.setdefault(seq.seq_id, now)
                    emits[seq.seq_id].append(now)
            elapsed = max(last_emit - t0, 1e-9)
            itls = sorted(
                (b - a) * 1e3
                for ts in emits.values()
                for a, b in zip(ts, ts[1:])
            )
            ttfts = sorted(
                (first[sid] - submitted[sid]) * 1e3
                for sid in first
            )
            return {
                "tok_per_sec": round(generated / elapsed, 2),
                "itl_p50_ms": round(_pct(itls, 0.50), 3),
                "itl_p99_ms": round(_pct(itls, 0.99), 3),
                "ttft_p50_ms": round(_pct(ttfts, 0.50), 3),
                "generated_tokens": generated,
                "steps": steps,
                "elapsed_s": round(elapsed, 4),
            }

        scenario()  # dry run: warms every step-bucket shape the pass hits
        before = core.loss_snapshot()
        metrics = scenario()
        metrics["loss"] = _delta(core.loss_snapshot(), before)
        return metrics
