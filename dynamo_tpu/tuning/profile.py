"""The winning-profile artifact: a tuned knob assignment as a JSON file.

A profile is deliberately *just documented env assignments* — the same
config-cascade names ``tools/check_env_knobs.py`` enforces — so applying
one needs no new plumbing anywhere: ``launch.py --tune-profile p.json``
exports each assignment into the environment the existing readers already
resolve. Precedence is explicit-wins: a knob the operator set via env or
CLI is never overridden by a profile (env > CLI > profile).
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Mapping, MutableMapping

from dynamo_tpu.tuning.space import assignment_env, validate_assignment

PROFILE_VERSION = 1


def make_profile(
    assignment: dict[str, int],
    *,
    preset: str,
    mode: str,
    platform: str,
    score: float,
    baseline_score: float,
    meta: dict | None = None,
) -> dict:
    """A profile document from a winning assignment.

    ``env`` is the applicable payload; everything else is provenance so a
    reviewer can tell where (and how well) the profile was won.
    """
    validate_assignment(assignment)
    return {
        "version": PROFILE_VERSION,
        "preset": preset,
        "mode": mode,
        "platform": platform,
        "assignment": dict(sorted(assignment.items())),
        "env": dict(sorted(assignment_env(assignment).items())),
        "score": round(float(score), 4),
        "baseline_score": round(float(baseline_score), 4),
        "gain": round(float(score) / baseline_score, 4) if baseline_score else 0.0,
        "meta": meta or {},
    }


def save_profile(path: str | os.PathLike, profile: dict) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        json.dump(profile, f, indent=2, sort_keys=True)
        f.write("\n")


def load_profile(path: str | os.PathLike) -> dict:
    with open(path) as f:
        profile = json.load(f)
    version = profile.get("version")
    if version != PROFILE_VERSION:
        raise ValueError(f"{path}: unsupported profile version {version!r}")
    if not isinstance(profile.get("env"), dict):
        raise ValueError(f"{path}: profile has no 'env' assignment map")
    return profile


def apply_profile(
    profile: Mapping,
    *,
    env: MutableMapping[str, str] | None = None,
    cli_set: Iterable[str] = (),
) -> dict[str, str]:
    """Export a profile's knobs into ``env``; explicit settings win.

    ``cli_set`` names the env keys whose values the CLI set explicitly
    (the launcher derives it from non-default flags). A profile entry is
    applied only when the operator expressed *no* opinion: the key is
    absent from ``env`` (env wins) and not in ``cli_set`` (CLI wins).
    Returns the entries actually applied.
    """
    env = os.environ if env is None else env
    cli_set = set(cli_set)
    applied: dict[str, str] = {}
    for key, value in profile["env"].items():
        if key in env or key in cli_set:
            continue
        env[key] = str(value)
        applied[key] = str(value)
    return applied
