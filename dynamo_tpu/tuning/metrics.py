"""Tuner telemetry registry.

Tiny on purpose: the search's own record of truth is the trial journal
(resumable JSONL under ``bench/results/tune/``); these families exist so a
long-running tuning session is observable like every other plane —
``dynamo_tuner_trials_total`` rates trial progress, and
``dynamo_tuner_best_score`` tracks convergence. Registered with
``tools/check_metric_names.py`` alongside the frontend/engine/fleet
registries.
"""

from __future__ import annotations

from prometheus_client import CollectorRegistry, Counter, Gauge, generate_latest


class TunerMetrics:
    """Registry for one auto-tuner session."""

    def __init__(self, registry: CollectorRegistry | None = None) -> None:
        self.registry = registry or CollectorRegistry()
        self._trials = Counter(
            "dynamo_tuner_trials",
            "Measured auto-tuner trials (journal cache hits do not count)",
            ["preset", "mode"], registry=self.registry,
        )
        self._best = Gauge(
            "dynamo_tuner_best_score",
            "Best objective score the search has accepted so far",
            ["preset", "mode"], registry=self.registry,
        )

    def observe_trial(self, preset: str, mode: str) -> None:
        self._trials.labels(preset, mode).inc()

    def set_best(self, preset: str, mode: str, score: float) -> None:
        self._best.labels(preset, mode).set(score)

    def render(self) -> bytes:
        return generate_latest(self.registry)
