"""CLI: ``python -m dynamo_tpu.tuning`` (also reachable as ``bench.py --tune``).

Runs the closed-loop knob search and writes the trial journal, winning
profile, and gain report under the output directory (default
``bench/results/tune/``). Flags seed from the ``DYN_TUNE_*`` config
cascade, so a TOML ``[tune]`` section or env set the same defaults.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv: list[str] | None = None) -> int:
    from dynamo_tpu.config import load_tune_settings
    from dynamo_tpu.tuning.metrics import TunerMetrics
    from dynamo_tpu.tuning.search import Tuner

    ts = load_tune_settings()
    parser = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.tuning",
        description="closed-loop performance knob auto-tuner",
    )
    parser.add_argument("--preset", default=ts.preset, help="model preset to tune for")
    parser.add_argument("--mode", default=ts.mode, choices=["mock", "jax"],
                        help="probe backend: mock (CPU proxy) or jax (real model)")
    parser.add_argument("--seed", type=int, default=ts.seed)
    parser.add_argument("--rounds", type=int, default=ts.rounds,
                        help="max coordinate-descent rounds")
    parser.add_argument("--requests", type=int, default=ts.requests,
                        help="requests per full-length probe")
    parser.add_argument("--isl", type=int, default=ts.isl)
    parser.add_argument("--osl", type=int, default=ts.osl)
    parser.add_argument("--max-trials", type=int, default=ts.max_trials,
                        help="hard cap on measured probes (0 = unlimited)")
    parser.add_argument("--out-dir", default=ts.out_dir,
                        help="journal/profile/report directory")
    parser.add_argument("--knobs", default=ts.knobs,
                        help="comma list restricting swept knobs")
    args = parser.parse_args(argv)
    settings = type(ts)(
        preset=args.preset, mode=args.mode, seed=args.seed,
        rounds=args.rounds, requests=args.requests, isl=args.isl,
        osl=args.osl, rung_frac=ts.rung_frac, plateau_eps=ts.plateau_eps,
        plateau_rounds=ts.plateau_rounds, max_trials=args.max_trials,
        out_dir=args.out_dir, knobs=args.knobs,
    )
    tuner = Tuner(settings, metrics=TunerMetrics())
    report = tuner.run()
    print(json.dumps({
        "best_assignment": report["best"]["assignment"],
        "baseline_score": report["baseline"]["score"],
        "best_score": report["best"]["score"],
        "gain": report["gain"],
        "stopped": report["stopped"],
        "trials_measured": report["trials_measured"],
        "trials_cached": report["trials_cached"],
        "burnable_frac": report["burn_down"]["best_burnable_frac"],
        "profile": report["profile_path"],
        "report": report["report_path"],
        "journal": report["journal_path"],
    }, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
