"""Operator: watch GraphDeployment objects, reconcile fleets to match.

The kubebuilder-controller shape (reference
`deploy/cloud/operator/internal/controller/dynamographdeployment_controller.go`)
on this framework's primitives: a store-prefix watch delivers spec changes,
`reconcile()` diffs desired vs actual and actuates through a pluggable
:class:`WorkloadBackend`, then writes status back to the object. Status
writes echo through the watch; the generation/observed_generation pair makes
reconciliation idempotent, so the echo converges instead of looping.

Backends:

- :class:`ProcessBackend` — each deployment becomes a supervised
  ``sdk.serving.ServeFleet`` (one process per service replica). The
  single-host "cluster".
- k8s — render manifests with `deploy/manifests.py` and apply them with any
  cluster tooling; the reconciler logic is identical, only the backend
  differs (this image has no cluster to drive).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Protocol

from dynamo_tpu.deploy.objects import STORE_PREFIX, DeploymentPhase, GraphDeployment
from dynamo_tpu.runtime.discovery import KeyValueStore, WatchEventType

logger = logging.getLogger(__name__)


class WorkloadBackend(Protocol):
    async def apply(self, dep: GraphDeployment) -> dict[str, int]:
        """Bring the deployment's workloads to spec; return service->replicas."""
        ...

    async def delete(self, name: str) -> None: ...

    async def close(self) -> None: ...


class ProcessBackend:
    """One supervised ServeFleet per deployment (the local-cluster backend)."""

    def __init__(self, *, host: str = "127.0.0.1", base_store_port: int = 0) -> None:
        self.host = host
        self.base_store_port = base_store_port
        self.fleets: dict[str, Any] = {}
        self._cfg_files: dict[str, str] = {}

    async def apply(self, dep: GraphDeployment) -> dict[str, int]:
        from dynamo_tpu.sdk.graph import load_graph
        from dynamo_tpu.sdk.serving import ServeFleet, _section_for

        existing = self.fleets.pop(dep.name, None)
        if existing is not None:  # spec change: replace wholesale
            await existing.close()
            self._drop_cfg(dep.name)
        graph = load_graph(dep.graph)
        import json
        import tempfile

        # ServeFleet subprocesses read config from a file; materialize the
        # deployment's config dict for them.
        cfg_file = None
        if dep.config:
            cfg_file = tempfile.NamedTemporaryFile(
                "w", suffix=".json", prefix=f"dep-{dep.name}-", delete=False
            )
            json.dump(dep.config, cfg_file)
            cfg_file.close()
            self._cfg_files[dep.name] = cfg_file.name
        fleet = ServeFleet(
            dep.graph,
            config_path=cfg_file.name if cfg_file else None,
            store_port=self.base_store_port,
            host=self.host,
        )
        await fleet.start(graph, dep.config)
        self.fleets[dep.name] = fleet
        counts: dict[str, int] = {}
        for spec in graph.services:
            counts[spec.name] = int(_section_for(dep.config, spec).get("replicas", spec.replicas))
        return counts

    def has(self, name: str) -> bool:
        """Whether this backend currently holds the deployment's workload
        (lets a restarted operator detect RUNNING records with no fleet)."""
        return name in self.fleets

    def _drop_cfg(self, name: str) -> None:
        import os

        path = self._cfg_files.pop(name, None)
        if path is not None:
            try:
                os.unlink(path)
            except OSError:
                pass

    async def delete(self, name: str) -> None:
        fleet = self.fleets.pop(name, None)
        if fleet is not None:
            await fleet.close()
        self._drop_cfg(name)

    async def close(self) -> None:
        for name in list(self.fleets):
            await self.delete(name)


class Operator:
    def __init__(
        self,
        store: KeyValueStore,
        backend: WorkloadBackend,
        *,
        resync_seconds: float = 30.0,
    ) -> None:
        self.store = store
        self.backend = backend
        self.resync_seconds = resync_seconds
        self._task: asyncio.Task | None = None
        self._resync_task: asyncio.Task | None = None
        self.reconciled = asyncio.Event()  # pulses after each reconcile (tests)

    # -- control loop ------------------------------------------------------

    async def start(self) -> "Operator":
        await self.resync()
        self._task = asyncio.create_task(self._watch_loop())
        self._resync_task = asyncio.create_task(self._resync_loop())
        return self

    async def _watch_loop(self) -> None:
        try:
            async for event in self.store.watch_prefix(STORE_PREFIX):
                if event.type is WatchEventType.PUT and event.value is not None:
                    dep = GraphDeployment.from_bytes(event.value)
                    await self.reconcile(dep)
                # DELETE events need no action: deletion goes through the
                # DELETING phase first, where the backend is torn down.
        except asyncio.CancelledError:
            pass
        except Exception:
            logger.exception("operator watch loop died")

    async def _resync_loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.resync_seconds)
                await self.resync()
        except asyncio.CancelledError:
            pass

    async def resync(self) -> None:
        """Level-triggered pass over every object (missed-event safety net,
        and the retry path for failed deployments)."""
        for value in (await self.store.get_prefix(STORE_PREFIX)).values():
            await self.reconcile(GraphDeployment.from_bytes(value), force=True)

    # -- reconciliation ----------------------------------------------------

    async def reconcile(self, dep: GraphDeployment, *, force: bool = False) -> None:
        try:
            if dep.phase == DeploymentPhase.DELETING.value:
                await self.backend.delete(dep.name)
                await self.store.delete(dep.key)
                logger.info("deployment %s finalized", dep.name)
                self.reconciled.set()
                return
            has = getattr(self.backend, "has", None)
            workload_live = has(dep.name) if has is not None else True
            if (
                dep.observed_generation == dep.generation
                and dep.phase == DeploymentPhase.RUNNING.value
                and (workload_live or not force)
            ):
                # Status echo / converged resync. On a *forced* pass a
                # RUNNING record whose workload the backend doesn't hold
                # (operator restart) falls through and re-creates it.
                self.reconciled.set()
                return
            if (
                dep.observed_generation == dep.generation
                and dep.phase == DeploymentPhase.FAILED.value
                and not force
            ):
                # Don't hot-loop a failing spec off our own status write;
                # failed objects retry on the level-triggered resync.
                self.reconciled.set()
                return
            counts = await self.backend.apply(dep)
            dep.phase = DeploymentPhase.RUNNING.value
            dep.message = ""
            dep.services_ready = counts
        except Exception as exc:
            logger.exception("reconcile %s failed", dep.name)
            dep.phase = DeploymentPhase.FAILED.value
            dep.message = f"{type(exc).__name__}: {exc}"
        dep.observed_generation = dep.generation
        await self.store.put(dep.key, dep.to_bytes())
        self.reconciled.set()

    async def close(self) -> None:
        for task in (self._task, self._resync_task):
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
        await self.backend.close()
