"""api-store: REST CRUD for graph deployments, backed by the KeyValueStore.

Routes (all JSON):

- ``POST   /api/v1/deployments``        — create (409 on duplicate)
- ``GET    /api/v1/deployments``        — list (optional ``?label=k=v``)
- ``GET    /api/v1/deployments/{name}`` — fetch one
- ``PUT    /api/v1/deployments/{name}`` — update spec (bumps generation)
- ``DELETE /api/v1/deployments/{name}`` — mark deleting (operator finalizes)
- ``GET    /healthz``

Writing to the same store the operator watches makes the API the single
source of truth: a POST here is immediately visible to the reconciler as a
watch event — the kubectl→apiserver→controller loop in one hop.

Parity: reference `deploy/cloud/api-store` (REST store for packaged
graphs/deployments).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any

from aiohttp import web

from dynamo_tpu.deploy.objects import STORE_PREFIX, DeploymentPhase, GraphDeployment
from dynamo_tpu.runtime.discovery import KeyValueStore

logger = logging.getLogger(__name__)


class ApiStore:
    def __init__(self, store: KeyValueStore, *, host: str = "127.0.0.1", port: int = 0) -> None:
        self.store = store
        self.host = host
        self.port = port
        self._runner: web.AppRunner | None = None
        # Serializes read-modify-write mutations so a PUT interleaving a
        # DELETE can't overwrite the DELETING phase with a stale copy.
        self._mutate = asyncio.Lock()

    # -- handlers ----------------------------------------------------------

    async def create(self, request: web.Request) -> web.Response:
        body = await self._json(request)
        if body is None or "name" not in body or "graph" not in body:
            return web.json_response({"error": "body must have name + graph"}, status=400)
        dep = GraphDeployment(
            name=str(body["name"]),
            graph=str(body["graph"]),
            config=dict(body.get("config", {})),
            labels={str(k): str(v) for k, v in dict(body.get("labels", {})).items()},
        )
        async with self._mutate:
            if await self.store.get(dep.key) is not None:
                return web.json_response({"error": f"deployment {dep.name!r} exists"}, status=409)
            await self.store.put(dep.key, dep.to_bytes())
        logger.info("created deployment %s -> %s", dep.name, dep.graph)
        return web.json_response(self._view(dep), status=201)

    async def list_all(self, request: web.Request) -> web.Response:
        label = request.query.get("label")
        want: tuple[str, str] | None = None
        if label:
            k, _, v = label.partition("=")
            want = (k, v)
        items = []
        for value in (await self.store.get_prefix(STORE_PREFIX)).values():
            dep = GraphDeployment.from_bytes(value)
            if want and dep.labels.get(want[0]) != want[1]:
                continue
            items.append(self._view(dep))
        return web.json_response({"items": sorted(items, key=lambda d: d["name"])})

    async def get_one(self, request: web.Request) -> web.Response:
        dep = await self._load(request.match_info["name"])
        if dep is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(self._view(dep))

    async def update(self, request: web.Request) -> web.Response:
        body = await self._json(request)
        if body is None:
            return web.json_response({"error": "invalid JSON body"}, status=400)
        async with self._mutate:
            dep = await self._load(request.match_info["name"])
            if dep is None:
                return web.json_response({"error": "not found"}, status=404)
            if dep.phase == DeploymentPhase.DELETING.value:
                # A PUT must not cancel/resurrect an acknowledged deletion.
                return web.json_response({"error": "deployment is being deleted"}, status=409)
            changed = False
            if "graph" in body and body["graph"] != dep.graph:
                dep.graph = str(body["graph"])
                changed = True
            if "config" in body and body["config"] != dep.config:
                dep.config = dict(body["config"])
                changed = True
            if "labels" in body:
                dep.labels = {str(k): str(v) for k, v in dict(body["labels"]).items()}
            if changed:
                dep.generation += 1
                dep.phase = DeploymentPhase.PENDING.value
            # The operator may finalize a delete outside this lock: re-check
            # so we don't resurrect a removed record.
            if await self.store.get(dep.key) is None:
                return web.json_response({"error": "not found"}, status=404)
            await self.store.put(dep.key, dep.to_bytes())
        return web.json_response(self._view(dep))

    async def delete(self, request: web.Request) -> web.Response:
        async with self._mutate:
            dep = await self._load(request.match_info["name"])
            if dep is None:
                return web.json_response({"error": "not found"}, status=404)
            # Two-phase delete: the operator tears the fleet down, then
            # removes the record (the finalizer pattern).
            dep.phase = DeploymentPhase.DELETING.value
            await self.store.put(dep.key, dep.to_bytes())
        return web.json_response({"status": "deleting"}, status=202)

    async def healthz(self, _request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    # -- helpers -----------------------------------------------------------

    @staticmethod
    async def _json(request: web.Request) -> dict[str, Any] | None:
        try:
            body = await request.json()
        except Exception:
            return None
        return body if isinstance(body, dict) else None

    async def _load(self, name: str) -> GraphDeployment | None:
        raw = await self.store.get(STORE_PREFIX + name)
        return GraphDeployment.from_bytes(raw) if raw is not None else None

    @staticmethod
    def _view(dep: GraphDeployment) -> dict[str, Any]:
        import dataclasses

        return dataclasses.asdict(dep)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> "ApiStore":
        app = web.Application()
        app.router.add_post("/api/v1/deployments", self.create)
        app.router.add_get("/api/v1/deployments", self.list_all)
        app.router.add_get("/api/v1/deployments/{name}", self.get_one)
        app.router.add_put("/api/v1/deployments/{name}", self.update)
        app.router.add_delete("/api/v1/deployments/{name}", self.delete)
        app.router.add_get("/healthz", self.healthz)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self._runner.addresses:
            self.port = self._runner.addresses[0][1]
        logger.info("api-store on http://%s:%d", self.host, self.port)
        return self

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
