"""Helm chart + Gateway API asset rendering for graph deployments.

``python -m dynamo_tpu.deploy helm graphs.agg:Frontend -o chart/`` writes a
self-contained Helm chart whose templates are generated FROM the same
manifest renderer the operator applies (`deploy/manifests.py`) — the chart
can never drift from what the reconciler would produce. Tunables (the
image and per-service replicas) are lifted into ``values.yaml``; ports and
commands stay baked into the templates, as in the rendered manifests.

``render_gateway`` emits the Gateway API ingress assets: a Gateway, an
HTTPRoute to the frontend Service, and an InferencePool/InferenceModel
pair (Gateway API Inference Extension). The reference deploys a separate
endpoint-picker service (EPP) for model-aware routing
(`deploy/inference-gateway/example/resources/`); here the KV-aware router
is first-party inside the frontend, so the route points straight at it and
the pool documents that distinction.

Parity: reference `deploy/helm/chart/{Chart,values}.yaml` + templates and
`deploy/inference-gateway/example/` (VERDICT r4 missing #6).
"""

from __future__ import annotations

import re
from typing import Any

import yaml

from dynamo_tpu.deploy.manifests import DEFAULT_IMAGE, render_deployment
from dynamo_tpu.deploy.objects import GraphDeployment
from dynamo_tpu.sdk.graph import Graph

CHART_VERSION = "0.1.0"

# Sentinel -> Go-template expression. Sentinels survive yaml.safe_dump
# (plain strings); the post-pass swaps them in UNQUOTED so numeric fields
# render as numbers, which a naive "quote the template" approach breaks.
# The tag is deliberately improbable: user config is embedded verbatim in
# the ConfigMap, so a generic marker (e.g. '@@x@@') could collide with
# config content and corrupt it.
_TAG = "dyntpl-c4a91b"


def _t(expr: str) -> str:
    return f"@@{_TAG}:{expr}@@"


def _untemplate(text: str) -> str:
    # Quoted-whole-scalar form first (strip the dumper's quotes), then bare.
    text = re.sub(rf"'@@{_TAG}:(.+?)@@'", r"{{ \1 }}", text)
    return re.sub(rf"@@{_TAG}:(.+?)@@", r"{{ \1 }}", text)


def _values_key(name: str) -> str:
    """Service/component name -> a valid Go-template map key."""
    return re.sub(r"[^a-zA-Z0-9_]", "_", name)


def render_helm_chart(
    dep: GraphDeployment,
    graph: Graph,
    *,
    image: str = DEFAULT_IMAGE,
) -> dict[str, str]:
    """-> {relative path: file content} for a complete chart."""
    docs = render_deployment(dep, graph, image=image)
    values: dict[str, Any] = {"image": image, "services": {}}

    templates: dict[str, list[dict]] = {}
    for doc in docs:
        kind = doc["kind"]
        name = doc["metadata"]["name"]
        # Lift tunables into values, replacing them with sentinels.
        if kind == "Deployment":
            key = _values_key(name.removeprefix(f"{dep.name}-"))
            if key in values["services"]:  # '-'/'_' or store-name collisions
                key = _values_key(name)
            n = 2
            while key in values["services"]:
                key = f"{key}_{n}"
                n += 1
            values["services"][key] = {"replicas": doc["spec"]["replicas"]}
            doc["spec"]["replicas"] = _t(f"int .Values.services.{key}.replicas")
            for c in doc["spec"]["template"]["spec"]["containers"]:
                c["image"] = _t(".Values.image")
        fname = f"{kind.lower()}s.yaml"
        templates.setdefault(fname, []).append(doc)

    chart = {
        "apiVersion": "v2",
        "name": dep.name,
        "description": f"dynamo-tpu graph deployment {dep.graph}",
        "type": "application",
        "version": CHART_VERSION,
        "appVersion": CHART_VERSION,
    }
    files = {
        "Chart.yaml": yaml.safe_dump(chart, sort_keys=False),
        "values.yaml": yaml.safe_dump(values, sort_keys=False),
        ".helmignore": "*.tgz\n",
    }
    for fname, docs_ in templates.items():
        files[f"templates/{fname}"] = _untemplate(
            "---\n".join(yaml.safe_dump(d, sort_keys=False) for d in docs_)
        )
    return files


def write_chart(files: dict[str, str], out_dir: str) -> None:
    import pathlib

    root = pathlib.Path(out_dir)
    for rel, content in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(content)


def render_gateway(
    dep: GraphDeployment,
    graph: Graph,
    *,
    gateway_class: str = "istio",
    models: list[str] | None = None,
) -> list[dict[str, Any]]:
    """Gateway API ingress for the deployment's frontend service."""
    from dynamo_tpu.sdk.serving import _section_for

    frontend = None
    port = 0
    for spec in graph.services:
        section = _section_for(dep.config, spec)
        p = int(section.get("http_port", 0))
        if p:
            frontend, port = f"{dep.name}-{spec.component}", p
            break
    if frontend is None:
        raise ValueError("graph has no service with an http_port (no frontend to route to)")
    labels = {"dynamo.tpu/deployment": dep.name}
    docs: list[dict[str, Any]] = [
        {
            "apiVersion": "gateway.networking.k8s.io/v1",
            "kind": "Gateway",
            "metadata": {"name": f"{dep.name}-gateway", "labels": labels},
            "spec": {
                "gatewayClassName": gateway_class,
                "listeners": [
                    {"name": "http", "protocol": "HTTP", "port": 80,
                     "allowedRoutes": {"namespaces": {"from": "Same"}}}
                ],
            },
        },
        {
            "apiVersion": "gateway.networking.k8s.io/v1",
            "kind": "HTTPRoute",
            "metadata": {"name": f"{dep.name}-route", "labels": labels},
            "spec": {
                "parentRefs": [{"name": f"{dep.name}-gateway"}],
                "rules": [
                    {
                        "matches": [{"path": {"type": "PathPrefix", "value": "/v1"}}],
                        "backendRefs": [{"name": frontend, "port": port}],
                    }
                ],
            },
        },
        # Inference Extension pool: model-aware endpoint picking is done by
        # the FRONTEND's first-party KV router (router/scheduler.py), not an
        # external EPP sidecar — the pool targets the frontend pods and the
        # extensionRef is intentionally absent (reference: dynamo-epp.yaml).
        {
            "apiVersion": "inference.networking.x-k8s.io/v1alpha2",
            "kind": "InferencePool",
            "metadata": {"name": f"{dep.name}-pool", "labels": labels},
            "spec": {
                "targetPortNumber": port,
                "selector": {"app": frontend},
            },
        },
    ]
    for model in models or []:
        docs.append({
            "apiVersion": "inference.networking.x-k8s.io/v1alpha2",
            "kind": "InferenceModel",
            "metadata": {
                "name": re.sub(r"[^a-z0-9.-]", "-", model.lower())[:253],
                "labels": labels,
            },
            "spec": {
                "modelName": model,
                "criticality": "Critical",
                "poolRef": {"name": f"{dep.name}-pool"},
            },
        })
    return docs


def render_gateway_bundle(dep: GraphDeployment, graph: Graph, **kw: Any) -> str:
    return "---\n".join(
        yaml.safe_dump(d, sort_keys=False) for d in render_gateway(dep, graph, **kw)
    )


def simulate_helm_template(files: dict[str, str]) -> list[dict[str, Any]]:
    """Minimal `helm template` stand-in for tests (no helm binary in the
    image): substitutes ``{{ [int] .Values.x.y }}`` from values.yaml and
    parses every template document."""
    values = yaml.safe_load(files["values.yaml"])

    def resolve(m: re.Match) -> str:
        expr = m.group(1).strip()
        expr = expr.removeprefix("int ").strip()
        node: Any = values
        assert expr.startswith(".Values."), expr
        for part in expr[len(".Values."):].split("."):
            node = node[part]
        return str(node)

    docs: list[dict[str, Any]] = []
    for rel, content in files.items():
        if not rel.startswith("templates/"):
            continue
        rendered = re.sub(r"\{\{(.+?)\}\}", resolve, content)
        docs.extend(d for d in yaml.safe_load_all(rendered) if d)
    return docs
