"""Deployment plane: api-store, operator-style reconciler, manifest renderer,
fleet-wide metrics service.

The reference splits this across a Go kubebuilder operator
(`deploy/cloud/operator`), a Python REST api-store (`deploy/cloud/api-store`),
and a Grafana/Prometheus metrics stack (`deploy/metrics`). Here the same
control loop — declarative GraphDeployment objects, a watch-driven
reconciler, rendered per-service workloads — runs over this framework's own
KeyValueStore and process supervision, with the k8s YAML renderer producing
the manifests a cluster deployment would apply.
"""

from dynamo_tpu.deploy.objects import DeploymentPhase, GraphDeployment

__all__ = ["DeploymentPhase", "GraphDeployment"]
