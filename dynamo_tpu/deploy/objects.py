"""Declarative deployment objects (the CRD shapes).

``GraphDeployment`` is the DynamoGraphDeployment equivalent: a named desire
for "this service graph, with these per-service overrides, running". The
api-store persists them; the operator reconciles them; the manifest renderer
turns them into k8s YAML.

Parity: reference `deploy/cloud/operator/api/v1alpha1/dynamocomponent_types.go:42-104`
(CRD spec/status split), api-store deployment records.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import time
from typing import Any


class DeploymentPhase(str, enum.Enum):
    PENDING = "pending"
    RUNNING = "running"
    FAILED = "failed"
    DELETING = "deleting"


STORE_PREFIX = "deployments/"


@dataclasses.dataclass
class GraphDeployment:
    """Spec + status of one deployed service graph."""

    name: str
    graph: str  # module:Service ref
    config: dict[str, dict[str, Any]] = dataclasses.field(default_factory=dict)
    # spec
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    created_at: float = 0.0
    generation: int = 1
    # status (written by the operator)
    phase: str = DeploymentPhase.PENDING.value
    message: str = ""
    observed_generation: int = 0
    services_ready: dict[str, int] = dataclasses.field(default_factory=dict)

    def __post_init__(self) -> None:
        if not self.created_at:
            self.created_at = time.time()

    @property
    def key(self) -> str:
        return STORE_PREFIX + self.name

    def to_bytes(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()

    @classmethod
    def from_bytes(cls, data: bytes) -> "GraphDeployment":
        return cls(**json.loads(data))

    def spec_equals(self, other: "GraphDeployment") -> bool:
        return (self.graph, self.config) == (other.graph, other.config)
