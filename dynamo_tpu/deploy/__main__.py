"""Deployment-plane CLI.

- ``python -m dynamo_tpu.deploy api-store --port 8088`` — REST deployment
  store (in-memory store, or ``--store tcp://...`` to join a cluster store).
- ``python -m dynamo_tpu.deploy operator --store tcp://...`` — reconciler
  with the local process backend.
- ``python -m dynamo_tpu.deploy controller --port 8088`` — api-store +
  operator sharing one in-process store: the single-host control plane.
- ``python -m dynamo_tpu.deploy metrics --store tcp://...`` — fleet
  Prometheus exporter.
- ``python -m dynamo_tpu.deploy manifests mod:Svc --name d1 [-f cfg]`` —
  print the k8s bundle; ``--crd`` prints the CRD.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal


async def _wait_for_signal() -> None:
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()


def _store_from(args: argparse.Namespace):
    from dynamo_tpu.runtime.discovery import MemoryStore
    from dynamo_tpu.runtime.store_server import StoreClient

    if getattr(args, "store", None):
        return StoreClient.from_url(args.store)
    return MemoryStore()


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(prog="python -m dynamo_tpu.deploy")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_api = sub.add_parser("api-store")
    p_api.add_argument("--host", default="127.0.0.1")
    p_api.add_argument("--port", type=int, default=8088)
    p_api.add_argument("--store", default=None, help="tcp://host:port cluster store (default in-memory)")

    p_op = sub.add_parser("operator")
    p_op.add_argument("--store", required=True, help="tcp://host:port store with deployment objects")
    p_op.add_argument("--resync-seconds", type=float, default=30.0)

    p_ctl = sub.add_parser("controller", help="api-store + operator in one process")
    p_ctl.add_argument("--host", default="127.0.0.1")
    p_ctl.add_argument("--port", type=int, default=8088)
    p_ctl.add_argument("--resync-seconds", type=float, default=30.0)

    p_met = sub.add_parser("metrics")
    p_met.add_argument("--store", required=True)
    p_met.add_argument("--host", default="127.0.0.1")
    p_met.add_argument("--port", type=int, default=9090)
    p_met.add_argument("--namespace", default="dynamo")
    p_met.add_argument("--component", default="backend")

    p_man = sub.add_parser("manifests")
    p_man.add_argument("graph", nargs="?", help="module:Service ref")
    p_man.add_argument("--name", default="dynamo")
    p_man.add_argument("-f", "--config", default=None)
    p_man.add_argument("--image", default=None)
    p_man.add_argument("--crd", action="store_true", help="print the CRD instead")

    p_helm = sub.add_parser("helm", help="write a Helm chart for a graph")
    p_helm.add_argument("graph", help="module:Service ref")
    p_helm.add_argument("--name", default="dynamo")
    p_helm.add_argument("-f", "--config", default=None)
    p_helm.add_argument("--image", default=None)
    p_helm.add_argument("-o", "--out", required=True, help="chart output directory")

    p_gw = sub.add_parser("gateway", help="print Gateway API ingress assets")
    p_gw.add_argument("graph", help="module:Service ref")
    p_gw.add_argument("--name", default="dynamo")
    p_gw.add_argument("-f", "--config", default=None)
    p_gw.add_argument("--gateway-class", default="istio")
    p_gw.add_argument("--model", action="append", default=[], help="InferenceModel entries")

    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    if args.cmd == "manifests":
        from dynamo_tpu.deploy.manifests import DEFAULT_IMAGE, render_bundle, render_crd
        from dynamo_tpu.deploy.objects import GraphDeployment
        from dynamo_tpu.sdk.graph import load_graph
        from dynamo_tpu.sdk.serving import load_service_config

        if args.crd:
            print(render_crd())
            return
        if not args.graph:
            raise SystemExit("manifests requires a module:Service graph ref (or --crd)")
        dep = GraphDeployment(
            name=args.name, graph=args.graph, config=load_service_config(args.config)
        )
        print(render_bundle(dep, load_graph(args.graph), image=args.image or DEFAULT_IMAGE))
        return
    if args.cmd == "helm":
        from dynamo_tpu.deploy.helm import render_helm_chart, write_chart
        from dynamo_tpu.deploy.manifests import DEFAULT_IMAGE
        from dynamo_tpu.deploy.objects import GraphDeployment
        from dynamo_tpu.sdk.graph import load_graph
        from dynamo_tpu.sdk.serving import load_service_config

        dep = GraphDeployment(
            name=args.name, graph=args.graph, config=load_service_config(args.config)
        )
        files = render_helm_chart(
            dep, load_graph(args.graph), image=args.image or DEFAULT_IMAGE
        )
        write_chart(files, args.out)
        print(f"wrote {len(files)} chart files to {args.out}")
        return
    if args.cmd == "gateway":
        from dynamo_tpu.deploy.helm import render_gateway_bundle
        from dynamo_tpu.deploy.objects import GraphDeployment
        from dynamo_tpu.sdk.graph import load_graph
        from dynamo_tpu.sdk.serving import load_service_config

        dep = GraphDeployment(
            name=args.name, graph=args.graph, config=load_service_config(args.config)
        )
        print(render_gateway_bundle(
            dep, load_graph(args.graph),
            gateway_class=args.gateway_class, models=args.model or None,
        ))
        return

    async def run() -> None:
        closers = []
        if args.cmd == "api-store":
            from dynamo_tpu.deploy.api_store import ApiStore

            svc = await ApiStore(_store_from(args), host=args.host, port=args.port).start()
            closers.append(svc)
            print(f"API-STORE http://{args.host}:{svc.port}", flush=True)
        elif args.cmd == "operator":
            from dynamo_tpu.deploy.operator import Operator, ProcessBackend

            op = await Operator(
                _store_from(args), ProcessBackend(), resync_seconds=args.resync_seconds
            ).start()
            closers.append(op)
            print("OPERATOR UP", flush=True)
        elif args.cmd == "controller":
            from dynamo_tpu.deploy.api_store import ApiStore
            from dynamo_tpu.deploy.operator import Operator, ProcessBackend
            from dynamo_tpu.runtime.discovery import MemoryStore

            store = MemoryStore()
            svc = await ApiStore(store, host=args.host, port=args.port).start()
            op = await Operator(
                store, ProcessBackend(), resync_seconds=args.resync_seconds
            ).start()
            closers += [op, svc]
            print(f"CONTROLLER http://{args.host}:{svc.port}", flush=True)
        elif args.cmd == "metrics":
            from dynamo_tpu.deploy.metrics_service import MetricsService
            from dynamo_tpu.runtime.component import DistributedRuntime
            from dynamo_tpu.runtime.transport import InMemoryTransport

            runtime = DistributedRuntime(_store_from(args), InMemoryTransport())
            svc = await MetricsService(
                runtime,
                namespace=args.namespace,
                component=args.component,
                host=args.host,
                port=args.port,
            ).start()
            closers.append(svc)
            print(f"METRICS http://{args.host}:{svc.port}/metrics", flush=True)
        try:
            await _wait_for_signal()
        finally:
            for c in closers:
                await c.close()

    asyncio.run(run())


if __name__ == "__main__":
    main()
