"""Render k8s manifests for a graph deployment (the operator's k8s half).

Pure functions: GraphDeployment + Graph -> YAML documents. The layout
mirrors what the reference operator's controllers materialize from a
DynamoGraphDeployment (per-service Deployments + Services + a ConfigMap,
`dynamographdeployment_controller.go`), adapted to TPU scheduling:
``resources: {tpu: N}`` becomes a ``google.com/tpu`` limit plus the
TPU-topology node selectors.

``python -m dynamo_tpu.deploy manifests graphs.agg:Frontend -f cfg.yaml``
prints the full bundle; apply with any cluster tooling.
"""

from __future__ import annotations

import json
from typing import Any

import yaml

from dynamo_tpu.deploy.objects import GraphDeployment
from dynamo_tpu.sdk.graph import Graph
from dynamo_tpu.sdk.serving import _section_for

DEFAULT_IMAGE = "dynamo-tpu:latest"
STORE_PORT = 7411


def render_crd() -> str:
    """The GraphDeployment custom-resource definition."""
    crd = {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": "graphdeployments.dynamo.tpu"},
        "spec": {
            "group": "dynamo.tpu",
            "names": {
                "kind": "GraphDeployment",
                "plural": "graphdeployments",
                "singular": "graphdeployment",
                "shortNames": ["gdep"],
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": "v1alpha1",
                    "served": True,
                    "storage": True,
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "properties": {
                                "spec": {
                                    "type": "object",
                                    "required": ["graph"],
                                    "properties": {
                                        "graph": {"type": "string"},
                                        "config": {
                                            "type": "object",
                                            "x-kubernetes-preserve-unknown-fields": True,
                                        },
                                    },
                                },
                                "status": {
                                    "type": "object",
                                    "x-kubernetes-preserve-unknown-fields": True,
                                },
                            },
                        }
                    },
                    "subresources": {"status": {}},
                }
            ],
        },
    }
    return yaml.safe_dump(crd, sort_keys=False)


def _store_manifests(dep: GraphDeployment, image: str) -> list[dict[str, Any]]:
    name = f"{dep.name}-store"
    labels = {"app": name, "dynamo.tpu/deployment": dep.name}
    return [
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": name, "labels": labels},
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": labels},
                "template": {
                    "metadata": {"labels": labels},
                    "spec": {
                        "containers": [
                            {
                                "name": "store",
                                "image": image,
                                "command": [
                                    "python", "-m", "dynamo_tpu.launch",
                                    "--role", "store",
                                    "--serve-store-port", str(STORE_PORT),
                                    "--host", "0.0.0.0",
                                ],
                                "ports": [{"containerPort": STORE_PORT}],
                            }
                        ]
                    },
                },
            },
        },
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": name, "labels": labels},
            "spec": {
                "selector": labels,
                "ports": [{"port": STORE_PORT, "targetPort": STORE_PORT}],
            },
        },
    ]


def render_deployment(
    dep: GraphDeployment,
    graph: Graph,
    *,
    image: str = DEFAULT_IMAGE,
) -> list[dict[str, Any]]:
    """ConfigMap + store + one Deployment/Service per graph service."""
    cm_name = f"{dep.name}-config"
    store_addr = f"tcp://{dep.name}-store:{STORE_PORT}"
    out: list[dict[str, Any]] = [
        {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": cm_name, "labels": {"dynamo.tpu/deployment": dep.name}},
            "data": {"services.json": json.dumps(dep.config, indent=2, sort_keys=True)},
        },
        *_store_manifests(dep, image),
    ]
    for spec in graph.services:
        section = _section_for(dep.config, spec)
        replicas = int(section.get("replicas", spec.replicas))
        svc_name = f"{dep.name}-{spec.component}"
        labels = {
            "app": svc_name,
            "dynamo.tpu/deployment": dep.name,
            "dynamo.tpu/service": spec.name,
        }
        container: dict[str, Any] = {
            "name": spec.component,
            "image": image,
            "command": [
                "python", "-m", "dynamo_tpu.sdk.serve_entry",
                dep.graph, "--service", spec.name,
                "--store", store_addr,
                "--host", "0.0.0.0",  # cross-pod: bind + advertise non-loopback
                "-f", "/etc/dynamo/services.json",
            ],
            "volumeMounts": [{"name": "config", "mountPath": "/etc/dynamo"}],
        }
        pod: dict[str, Any] = {
            "containers": [container],
            "volumes": [{"name": "config", "configMap": {"name": cm_name}}],
        }
        tpus = int(spec.resources.get("tpu", 0))
        if tpus:
            container["resources"] = {"limits": {"google.com/tpu": tpus}}
            pod["nodeSelector"] = {"cloud.google.com/gke-tpu-accelerator": "tpu-v5e"}
        http_port = int(section.get("http_port", 0))
        if http_port:
            container["ports"] = [{"containerPort": http_port}]
        out.append(
            {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {"name": svc_name, "labels": labels},
                "spec": {
                    "replicas": replicas,
                    "selector": {"matchLabels": labels},
                    "template": {"metadata": {"labels": labels}, "spec": pod},
                },
            }
        )
        if http_port:
            out.append(
                {
                    "apiVersion": "v1",
                    "kind": "Service",
                    "metadata": {"name": svc_name, "labels": labels},
                    "spec": {
                        "selector": labels,
                        "ports": [{"port": http_port, "targetPort": http_port}],
                    },
                }
            )
    return out


def render_bundle(dep: GraphDeployment, graph: Graph, *, image: str = DEFAULT_IMAGE) -> str:
    """Multi-document YAML: everything `kubectl apply -f -` needs."""
    docs = render_deployment(dep, graph, image=image)
    return "---\n".join(yaml.safe_dump(d, sort_keys=False) for d in docs)
