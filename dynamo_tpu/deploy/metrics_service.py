"""Standalone fleet metrics exporter: worker KV plane -> Prometheus.

A deployment-wide ``/metrics`` endpoint that any Prometheus can scrape
without touching the serving path: it watches the same store-backed metrics
plane the KV router reads (`router/metrics.py`) and re-exposes every
worker's load snapshot as labelled gauges/counters.

Run: ``python -m dynamo_tpu.deploy metrics --store tcp://host:7411 --port 9090``
Dashboards: ``deploy/grafana-dashboard.json`` charts these series plus the
frontend's request metrics (`frontend/metrics.py`).

Parity: reference `components/metrics` binary (standalone aggregation
service feeding the Grafana stack, SURVEY §2 row 41).
"""

from __future__ import annotations

import logging

from aiohttp import web

from dynamo_tpu.router.metrics import KvMetricsAggregator
from dynamo_tpu.runtime.component import DistributedRuntime

logger = logging.getLogger(__name__)

_GAUGES = (
    ("kv_active_blocks", "KV blocks in use"),
    ("kv_total_blocks", "KV blocks total"),
    ("num_requests_waiting", "Requests queued"),
    ("num_requests_running", "Requests running"),
    ("request_total_slots", "Max batch slots"),
    ("cache_hit_rate", "Prefix cache hit rate"),
)
_COUNTERS = (
    ("prompt_tokens_total", "Prompt tokens processed"),
    ("generated_tokens_total", "Tokens generated"),
    ("moe_choices_total", "MoE (token, choice) pairs routed through the capacity dispatch (incl. bucket padding)"),
    ("moe_dropped_total", "MoE choices dropped for over-capacity (dispatch-level, incl. bucket padding)"),
)


class MetricsService:
    def __init__(
        self,
        runtime: DistributedRuntime,
        *,
        namespace: str = "dynamo",
        component: str = "backend",
        host: str = "127.0.0.1",
        port: int = 0,
    ) -> None:
        self.runtime = runtime
        self.aggregator = KvMetricsAggregator(runtime, namespace, component)
        self.host = host
        self.port = port
        self._runner: web.AppRunner | None = None

    def render(self) -> str:
        """Prometheus text format, one labelled series per worker."""
        snapshot = self.aggregator.snapshot()
        lines: list[str] = []
        ns = "dynamo_worker"
        for field, help_text in _GAUGES + _COUNTERS:
            kind = "counter" if field.endswith("_total") and field not in ("kv_total_blocks",) else "gauge"
            lines.append(f"# HELP {ns}_{field} {help_text}")
            lines.append(f"# TYPE {ns}_{field} {kind}")
            for wid, m in sorted(snapshot.items()):
                lines.append(f'{ns}_{field}{{worker_id="{wid:x}"}} {getattr(m, field)}')
        lines.append(f"# HELP {ns}_cache_usage KV utilization 0..1")
        lines.append(f"# TYPE {ns}_cache_usage gauge")
        for wid, m in sorted(snapshot.items()):
            lines.append(f'{ns}_cache_usage{{worker_id="{wid:x}"}} {m.cache_usage:.6f}')
        lines.append(f"# HELP {ns}_up Workers publishing metrics")
        lines.append(f"# TYPE {ns}_up gauge")
        lines.append(f"{ns}_up {len(snapshot)}")
        return "\n".join(lines) + "\n"

    async def _metrics(self, _request: web.Request) -> web.Response:
        return web.Response(text=self.render(), content_type="text/plain")

    async def _healthz(self, _request: web.Request) -> web.Response:
        return web.json_response({"status": "ok", "workers": len(self.aggregator.snapshot())})

    async def start(self) -> "MetricsService":
        await self.aggregator.start()
        app = web.Application()
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/healthz", self._healthz)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self._runner.addresses:
            self.port = self._runner.addresses[0][1]
        logger.info("metrics service on http://%s:%d/metrics", self.host, self.port)
        return self

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
        await self.aggregator.close()
