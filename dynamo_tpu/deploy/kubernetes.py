"""Kubernetes WorkloadBackend: the operator's cluster half.

Drives the k8s REST API directly over HTTP (no client library in the
image): rendered manifests (`deploy/manifests.py`) are schema-validated
and then server-side-applied (`PATCH` with
``application/apply-patch+yaml`` and a fieldManager) — one idempotent verb
for create and update, which is exactly what a reconciler wants. Deletion
is a labeled ``deletecollection`` per resource type using the
``dynamo.tpu/deployment`` label every rendered object carries.

Scaling composes end to end with the control plane: the planner's
``DeploymentConnector`` bumps ``replicas`` in the GraphDeployment record,
the operator's watch re-renders, and the server-side apply patches
``spec.replicas`` on the affected Deployment (test:
``tests/test_kubernetes_backend.py``).

Reference parity: the kubebuilder controller's materialization of a
DynamoGraphDeployment into per-service Deployments/Services
(`deploy/cloud/operator/internal/controller/dynamographdeployment_controller.go:33-72`)
and its scale path. VERDICT r3 item 6 / round-2 item 7.

Auth: in-cluster pattern — a bearer token (service-account token file) and
CA-verified TLS, or plain HTTP against a local apiserver proxy
(``kubectl proxy``) / test server.
"""

from __future__ import annotations

import json
import logging
import re
from typing import Any

import aiohttp

from dynamo_tpu.deploy.objects import GraphDeployment

logger = logging.getLogger(__name__)

FIELD_MANAGER = "dynamo-tpu-operator"
DEPLOYMENT_LABEL = "dynamo.tpu/deployment"

# kind -> (api prefix, plural). Everything the renderer emits.
_API = {
    "Deployment": ("/apis/apps/v1", "deployments"),
    "Service": ("/api/v1", "services"),
    "ConfigMap": ("/api/v1", "configmaps"),
}


class ManifestError(ValueError):
    """A rendered manifest violates the shape the API server would reject."""


def validate_manifest(doc: dict[str, Any]) -> None:
    """Pre-flight the invariants the API server enforces, so a rendering bug
    fails the reconcile loudly instead of as an opaque 422."""
    for key in ("apiVersion", "kind", "metadata"):
        if key not in doc:
            raise ManifestError(f"manifest missing {key!r}: {json.dumps(doc)[:120]}")
    kind = doc["kind"]
    if kind not in _API:
        raise ManifestError(f"unsupported kind {kind!r}")
    name = doc["metadata"].get("name", "")
    # DNS-1123 subdomain rule, per dot-separated label: alphanumeric ends,
    # label <= 63 chars (a strip()-based check accepted '-svc' / 'svc.' /
    # 'a..b', which the API server rejects, ADVICE r4).
    label = r"[a-z0-9]([-a-z0-9]*[a-z0-9])?"
    if (not name or len(name) > 253
            or not re.fullmatch(rf"{label}(\.{label})*", name)
            or any(len(part) > 63 for part in name.split("."))):
        raise ManifestError(f"{kind}: invalid DNS-1123 name {name!r}")
    if doc["metadata"].get("labels", {}).get(DEPLOYMENT_LABEL) is None:
        raise ManifestError(f"{kind}/{name}: missing {DEPLOYMENT_LABEL} label (deletion selector)")
    if kind == "Deployment":
        spec = doc.get("spec", {})
        match = spec.get("selector", {}).get("matchLabels", {})
        tmpl_labels = spec.get("template", {}).get("metadata", {}).get("labels", {})
        if not match:
            raise ManifestError(f"Deployment/{name}: empty spec.selector.matchLabels")
        for k, v in match.items():
            if tmpl_labels.get(k) != v:
                raise ManifestError(
                    f"Deployment/{name}: selector {k}={v} not matched by template labels"
                )
        if int(spec.get("replicas", 0)) < 0:
            raise ManifestError(f"Deployment/{name}: negative replicas")
        containers = spec.get("template", {}).get("spec", {}).get("containers", [])
        if not containers:
            raise ManifestError(f"Deployment/{name}: no containers")
        for c in containers:
            if not c.get("name") or not c.get("image"):
                raise ManifestError(f"Deployment/{name}: container missing name/image")
    if kind == "Service":
        spec = doc.get("spec", {})
        if not spec.get("ports"):
            raise ManifestError(f"Service/{name}: no ports")
        for p in spec["ports"]:
            port = int(p.get("port", 0))
            if not 0 < port < 65536:
                raise ManifestError(f"Service/{name}: invalid port {port}")


class KubernetesBackend:
    """WorkloadBackend against the k8s REST API (server-side apply)."""

    def __init__(
        self,
        base_url: str,
        *,
        namespace: str = "default",
        token: str | None = None,
        image: str | None = None,
        verify_ssl: bool = True,
        session: aiohttp.ClientSession | None = None,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.namespace = namespace
        self.image = image
        self._headers = {"Authorization": f"Bearer {token}"} if token else {}
        self._verify_ssl = verify_ssl
        self._session = session
        self._owns_session = session is None

    async def _http(self) -> aiohttp.ClientSession:
        if self._session is None:
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(ssl=self._verify_ssl or False),
            )
        return self._session

    def _req_headers(self, extra: dict[str, str] | None = None) -> dict[str, str]:
        # Attached per request (not per session) so a caller-injected shared
        # session still authenticates.
        return {**self._headers, **(extra or {})}

    def _path(self, kind: str, name: str | None = None) -> str:
        prefix, plural = _API[kind]
        base = f"{self.base_url}{prefix}/namespaces/{self.namespace}/{plural}"
        return f"{base}/{name}" if name else base

    # -- WorkloadBackend ---------------------------------------------------

    async def apply(self, dep: GraphDeployment) -> dict[str, int]:
        from dynamo_tpu.deploy.manifests import DEFAULT_IMAGE, render_deployment
        from dynamo_tpu.sdk.graph import load_graph

        graph = load_graph(dep.graph)
        docs = render_deployment(dep, graph, image=self.image or DEFAULT_IMAGE)
        for doc in docs:
            validate_manifest(doc)
        session = await self._http()
        counts: dict[str, int] = {}
        for doc in docs:
            name = doc["metadata"]["name"]
            # Server-side apply: one idempotent verb for create-or-update,
            # no resourceVersion bookkeeping in the reconciler.
            async with session.patch(
                self._path(doc["kind"], name),
                params={"fieldManager": FIELD_MANAGER, "force": "true"},
                headers=self._req_headers({"Content-Type": "application/apply-patch+yaml"}),
                data=json.dumps(doc),
            ) as resp:
                if resp.status >= 400:
                    raise RuntimeError(
                        f"apply {doc['kind']}/{name}: HTTP {resp.status}: "
                        f"{(await resp.text())[:300]}"
                    )
            svc = doc["metadata"].get("labels", {}).get("dynamo.tpu/service")
            if doc["kind"] == "Deployment" and svc:
                counts[svc] = int(doc["spec"].get("replicas", 0))
        return counts

    async def delete(self, name: str) -> None:
        session = await self._http()
        selector = f"{DEPLOYMENT_LABEL}={name}"
        for kind in _API:
            async with session.delete(
                self._path(kind), params={"labelSelector": selector},
                headers=self._req_headers(),
            ) as resp:
                if resp.status >= 400 and resp.status != 404:
                    raise RuntimeError(
                        f"delete {kind} ({selector}): HTTP {resp.status}: "
                        f"{(await resp.text())[:300]}"
                    )

    async def replicas(self, deployment_name: str) -> dict[str, int]:
        """Observed spec.replicas per rendered Deployment (status probe)."""
        session = await self._http()
        out: dict[str, int] = {}
        async with session.get(
            self._path("Deployment"),
            params={"labelSelector": f"{DEPLOYMENT_LABEL}={deployment_name}"},
            headers=self._req_headers(),
        ) as resp:
            resp.raise_for_status()
            for item in (await resp.json()).get("items", []):
                svc = item["metadata"].get("labels", {}).get("dynamo.tpu/service")
                if svc:
                    out[svc] = int(item["spec"].get("replicas", 0))
        return out

    async def close(self) -> None:
        if self._session is not None and self._owns_session:
            await self._session.close()
            self._session = None
