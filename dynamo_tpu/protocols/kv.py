"""KV event + worker load-metrics plane protocol.

Parity: reference `lib/llm/src/kv_router/protocols.rs` — `KvCacheEvent`
(block stored/removed/cleared, tagged with the emitting worker) feeding the
router's radix index, and `ForwardPassMetrics` (the per-worker load snapshot
the scheduler's cost function consumes).

In the TPU build the engine is in-process, so events are emitted directly on
the runtime's event bus (no ZMQ hop as in the reference, SURVEY.md §2 row 25).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass(frozen=True)
class BlockStored:
    block_hash: int
    parent_hash: int | None
    token_ids: tuple[int, ...] = ()


@dataclass(frozen=True)
class BlockRemoved:
    block_hash: int


@dataclass
class KvCacheEvent:
    """One batch of cache mutations from a worker (ordering is meaningful:
    parents are always stored before children)."""

    stored: list[BlockStored] = field(default_factory=list)
    removed: list[BlockRemoved] = field(default_factory=list)
    cleared: bool = False

    def is_empty(self) -> bool:
        return not self.stored and not self.removed and not self.cleared

    def to_dict(self) -> dict[str, Any]:
        return {
            "stored": [
                {"block_hash": s.block_hash, "parent_hash": s.parent_hash, "token_ids": list(s.token_ids)}
                for s in self.stored
            ],
            "removed": [{"block_hash": r.block_hash} for r in self.removed],
            "cleared": self.cleared,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "KvCacheEvent":
        return cls(
            stored=[
                BlockStored(s["block_hash"], s.get("parent_hash"), tuple(s.get("token_ids", ())))
                for s in d.get("stored", [])
            ],
            removed=[BlockRemoved(r["block_hash"]) for r in d.get("removed", [])],
            cleared=d.get("cleared", False),
        )


@dataclass
class RouterEvent:
    """A KvCacheEvent tagged with its source worker (instance/lease id)."""

    worker_id: int
    event: KvCacheEvent

    def to_dict(self) -> dict[str, Any]:
        return {"worker_id": self.worker_id, "event": self.event.to_dict()}

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "RouterEvent":
        return cls(worker_id=d["worker_id"], event=KvCacheEvent.from_dict(d["event"]))


@dataclass
class ForwardPassMetrics:
    """Per-worker load snapshot published on the metrics plane.

    Parity: `kv_router/protocols.rs:43` ForwardPassMetrics.
    """

    worker_id: int = 0
    kv_active_blocks: int = 0
    kv_total_blocks: int = 1
    num_requests_waiting: int = 0
    num_requests_running: int = 0
    request_total_slots: int = 1
    cache_hit_rate: float = 0.0
    # Cumulative counters for throughput accounting.
    prompt_tokens_total: int = 0
    generated_tokens_total: int = 0
    # MoE capacity-dispatch routing: cumulative (token, choice) pairs seen
    # and dropped for over-capacity (parallel/moe.py DROP_COUNTER). Zero for
    # dense models and for the dropless/dense dispatches.
    moe_choices_total: int = 0
    moe_dropped_total: int = 0

    @property
    def cache_usage(self) -> float:
        return self.kv_active_blocks / max(self.kv_total_blocks, 1)

    def to_dict(self) -> dict[str, Any]:
        return {
            "worker_id": self.worker_id,
            "kv_active_blocks": self.kv_active_blocks,
            "kv_total_blocks": self.kv_total_blocks,
            "num_requests_waiting": self.num_requests_waiting,
            "num_requests_running": self.num_requests_running,
            "request_total_slots": self.request_total_slots,
            "cache_hit_rate": self.cache_hit_rate,
            "prompt_tokens_total": self.prompt_tokens_total,
            "generated_tokens_total": self.generated_tokens_total,
            "moe_choices_total": self.moe_choices_total,
            "moe_dropped_total": self.moe_dropped_total,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ForwardPassMetrics":
        return cls(**{k: d[k] for k in cls().__dict__ if k in d})
