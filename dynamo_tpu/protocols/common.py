"""Internal inter-stage protocol: preprocessed requests and engine outputs.

Parity: reference `lib/llm/src/protocols/common/*` — `PreprocessedRequest`
(token_ids + sampling + stop conditions, produced by the preprocessor and
consumed by router/engine) and `BackendOutput`/`LLMEngineOutput` (token deltas
flowing back). Everything is a plain dataclass serializable to/from dicts so
it crosses the stream transport as msgpack/JSON without bespoke codecs.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from enum import Enum
from typing import Any


class FinishReason(str, Enum):
    STOP = "stop"  # stop condition (eos / stop token / stop string)
    LENGTH = "length"  # max_tokens or context window reached
    CANCELLED = "cancelled"  # client stopped/killed the request
    ERROR = "error"


@dataclass
class SamplingOptions:
    temperature: float = 0.0  # 0 => greedy
    top_k: int = 0  # <=0 => disabled
    top_p: float = 1.0  # >=1 => disabled
    seed: int | None = None
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    # OpenAI logprobs: 0 = off; N > 0 = enabled with N-1 top alternatives
    # per generated token (the +1 encoding lets "chosen token only, zero
    # alternatives" — chat top_logprobs: 0 / completions logprobs: 0 —
    # stay distinct from off). The reference leaves this a TODO
    # (`completions.rs:262`); first-party here.
    logprobs: int = 0
    # OpenAI response_format {"type": "json_object"}: constrain sampling so
    # the output is always a valid JSON prefix and force-close before the
    # token budget runs out (dynamo_tpu/constrained.py).
    json_mode: bool = False

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "SamplingOptions":
        return cls(**{k: v for k, v in d.items() if k in {f.name for f in dataclasses.fields(cls)}})


@dataclass
class StopConditions:
    max_tokens: int = 512
    stop_token_ids: list[int] = field(default_factory=list)
    stop_strings: list[str] = field(default_factory=list)
    ignore_eos: bool = False
    min_tokens: int = 0

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "StopConditions":
        return cls(**{k: v for k, v in d.items() if k in {f.name for f in dataclasses.fields(cls)}})


@dataclass
class PreprocessedRequest:
    """Tokenized request: what the router schedules and the engine executes."""

    token_ids: list[int]
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    stop: StopConditions = field(default_factory=StopConditions)
    model: str | None = None
    request_id: str | None = None
    annotations: dict[str, Any] = field(default_factory=dict)
    # Multimodal embeddings handle (filled by encode workers; see models/vision).
    mm_inputs: dict[str, Any] | None = None
    # Multi-tenant admission control (dynamo_tpu/sched): tenant identity from
    # the frontend's x-dynamo-tenant header (None = the shared default
    # tenant) and priority tier (0 = most latency-sensitive; each higher tier
    # stretches the EDF deadline budget — relaxed, never starved).
    tenant_id: str | None = None
    priority: int = 0

    def to_dict(self) -> dict[str, Any]:
        return {
            "token_ids": list(self.token_ids),
            "sampling": self.sampling.to_dict(),
            "stop": self.stop.to_dict(),
            "model": self.model,
            "request_id": self.request_id,
            "annotations": self.annotations,
            "mm_inputs": self.mm_inputs,
            "tenant_id": self.tenant_id,
            "priority": self.priority,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PreprocessedRequest":
        return cls(
            token_ids=list(d["token_ids"]),
            sampling=SamplingOptions.from_dict(d.get("sampling", {})),
            stop=StopConditions.from_dict(d.get("stop", {})),
            model=d.get("model"),
            request_id=d.get("request_id"),
            annotations=d.get("annotations", {}) or {},
            mm_inputs=d.get("mm_inputs"),
            tenant_id=d.get("tenant_id"),
            priority=int(d.get("priority") or 0),
        )


@dataclass
class BackendOutput:
    """Detokenized delta leaving the backend (postprocessor) stage."""

    text: str = ""
    token_ids: list[int] = field(default_factory=list)
    finish_reason: FinishReason | None = None
    cumulative_tokens: int = 0
    prompt_tokens: int | None = None
    cached_tokens: int | None = None
    embedding: list[float] | None = None  # /v1/embeddings result (no tokens stream)
    # Per generated token: {"id", "token", "bytes", "logprob",
    # "top": [[id, lp, token], ...]} (wire order: id, logprob, token).
    logprobs: list[dict] | None = None
    # Engine admission wait (add_request -> first scheduling), reported once
    # on the request's first delta; None on later deltas.
    admission_wait_ms: float | None = None

    def to_dict(self) -> dict[str, Any]:
        return {
            "text": self.text,
            "token_ids": list(self.token_ids),
            "finish_reason": self.finish_reason.value if self.finish_reason else None,
            "cumulative_tokens": self.cumulative_tokens,
            "prompt_tokens": self.prompt_tokens,
            "cached_tokens": self.cached_tokens,
            "embedding": self.embedding,
            "logprobs": self.logprobs,
            "admission_wait_ms": self.admission_wait_ms,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "BackendOutput":
        fr = d.get("finish_reason")
        return cls(
            text=d.get("text", ""),
            token_ids=list(d.get("token_ids", [])),
            finish_reason=FinishReason(fr) if fr else None,
            cumulative_tokens=d.get("cumulative_tokens", 0),
            prompt_tokens=d.get("prompt_tokens"),
            cached_tokens=d.get("cached_tokens"),
            embedding=d.get("embedding"),
            logprobs=d.get("logprobs"),
            admission_wait_ms=d.get("admission_wait_ms"),
        )


@dataclass
class EngineOutput:
    """One streamed delta from the engine: newly generated token ids."""

    token_ids: list[int]
    finish_reason: FinishReason | None = None
    cumulative_tokens: int = 0
    # Usage metadata on the final delta.
    prompt_tokens: int | None = None
    cached_tokens: int | None = None
    embedding: list[float] | None = None  # /v1/embeddings result (no tokens stream)
    # Per token in token_ids: {"id", "logprob", "top": [[id, lp], ...]};
    # None when the request didn't ask (SamplingOptions.logprobs == 0).
    logprobs: list[dict] | None = None
    # Engine admission wait (add_request -> first scheduling), attached to
    # the sequence's first delta only (frontend RequestTracker observes it).
    admission_wait_ms: float | None = None

    def to_dict(self) -> dict[str, Any]:
        return {
            "token_ids": list(self.token_ids),
            "finish_reason": self.finish_reason.value if self.finish_reason else None,
            "cumulative_tokens": self.cumulative_tokens,
            "prompt_tokens": self.prompt_tokens,
            "cached_tokens": self.cached_tokens,
            "embedding": self.embedding,
            "logprobs": self.logprobs,
            "admission_wait_ms": self.admission_wait_ms,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "EngineOutput":
        fr = d.get("finish_reason")
        return cls(
            token_ids=list(d.get("token_ids", [])),
            finish_reason=FinishReason(fr) if fr else None,
            cumulative_tokens=d.get("cumulative_tokens", 0),
            prompt_tokens=d.get("prompt_tokens"),
            cached_tokens=d.get("cached_tokens"),
            embedding=d.get("embedding"),
            logprobs=d.get("logprobs"),
            admission_wait_ms=d.get("admission_wait_ms"),
        )
