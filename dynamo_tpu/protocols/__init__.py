"""Wire and inter-stage protocol types.

Mirrors the reference's `lib/llm/src/protocols` split: OpenAI-compatible HTTP
schemas (:mod:`dynamo_tpu.protocols.openai`), the internal preprocessed
request / engine output shapes every pipeline stage speaks
(:mod:`dynamo_tpu.protocols.common`), and the KV event + worker metrics plane
(:mod:`dynamo_tpu.protocols.kv`).
"""
