"""Tokenizer abstraction: HF `tokenizers` backend + a hermetic byte tokenizer.

Parity: reference `lib/llm/src/tokenizers.rs` (HF + SentencePiece wrappers
behind one `Encoding` interface). The byte tokenizer serves the role the
reference's test fixtures play — fully deterministic, no artifacts, no
network — and is also the fallback for models shipping no tokenizer.
"""

from __future__ import annotations

import abc
import pathlib


class BaseTokenizer(abc.ABC):
    eos_token_ids: frozenset[int] = frozenset()
    bos_token_id: int | None = None

    @abc.abstractmethod
    def encode(self, text: str, *, add_bos: bool = False) -> list[int]: ...

    @abc.abstractmethod
    def decode(self, ids: list[int], *, skip_special_tokens: bool = True) -> str: ...

    @property
    @abc.abstractmethod
    def vocab_size(self) -> int: ...


class ByteTokenizer(BaseTokenizer):
    """UTF-8 bytes as tokens 0..255; BOS=256, EOS=257, PAD=258.

    Hermetic: any text round-trips with no artifacts. Used by CI and the echo/
    debug engines.
    """

    BOS, EOS, PAD = 256, 257, 258

    def __init__(self) -> None:
        self.eos_token_ids = frozenset({self.EOS})
        self.bos_token_id = self.BOS

    def encode(self, text: str, *, add_bos: bool = False) -> list[int]:
        ids = list(text.encode("utf-8"))
        return [self.BOS] + ids if add_bos else ids

    def decode(self, ids: list[int], *, skip_special_tokens: bool = True) -> str:
        data = bytes(i for i in ids if i < 256)
        return data.decode("utf-8", errors="replace")

    @property
    def vocab_size(self) -> int:
        return 259


class HfTokenizer(BaseTokenizer):
    """Wrapper over a `tokenizers.Tokenizer` (tokenizer.json)."""

    def __init__(self, tokenizer, *, eos_token_ids: set[int] | None = None, bos_token_id: int | None = None) -> None:
        self._tok = tokenizer
        self.eos_token_ids = frozenset(eos_token_ids or self._infer_eos())
        self.bos_token_id = bos_token_id

    @classmethod
    def from_file(cls, path: str | pathlib.Path, **kw) -> "HfTokenizer":
        from tokenizers import Tokenizer

        return cls(Tokenizer.from_file(str(path)), **kw)

    def _infer_eos(self) -> set[int]:
        out = set()
        for name in ("</s>", "<|end_of_text|>", "<|eot_id|>", "<|endoftext|>", "<|im_end|>", "<eos>"):
            tid = self._tok.token_to_id(name)
            if tid is not None:
                out.add(tid)
        return out

    def encode(self, text: str, *, add_bos: bool = False) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False).ids
        if add_bos and self.bos_token_id is not None:
            ids = [self.bos_token_id] + ids
        return ids

    def decode(self, ids: list[int], *, skip_special_tokens: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()


def load_tokenizer(spec: str | pathlib.Path) -> BaseTokenizer:
    """Load by spec: "byte" or a path to tokenizer.json / a model directory."""
    if str(spec) == "byte":
        return ByteTokenizer()
    p = pathlib.Path(spec)
    if p.is_dir():
        # Prefer the fast-tokenizer artifact; fall back to SentencePiece.
        if (p / "tokenizer.json").exists():
            p = p / "tokenizer.json"
        elif (p / "tokenizer.model").exists():
            p = p / "tokenizer.model"
        else:
            p = p / "tokenizer.json"
    if p.suffix == ".model" and p.exists():
        from dynamo_tpu.sentencepiece import load_sentencepiece

        return load_sentencepiece(p)
    if p.suffix == ".gguf" and p.exists():
        from dynamo_tpu.models.gguf import shared_reader, tokenizer_from_gguf

        return tokenizer_from_gguf(shared_reader(p))
    if p.exists():
        return HfTokenizer.from_file(p)
    raise FileNotFoundError(f"no tokenizer at {spec}")


class IncrementalDetokenizer:
    """Streams text deltas from a growing token sequence.

    Tokenizers are not prefix-stable (multi-byte codepoints, merge effects),
    so naive per-token decode corrupts output. Standard two-offset algorithm:
    keep a window [prefix_offset, read_offset) of already-emitted tokens and
    emit only the text that extends a re-decode of that window; hold back
    while the tail decodes to a dangling replacement character.
    """

    def __init__(self, tokenizer: BaseTokenizer, *, skip_special_tokens: bool = True) -> None:
        self._tok = tokenizer
        self._ids: list[int] = []
        self._prefix_offset = 0
        self._read_offset = 0
        self._skip_special = skip_special_tokens

    def push(self, token_ids: list[int]) -> str:
        """Add tokens; return newly-stable text (possibly empty)."""
        self._ids.extend(token_ids)
        prefix = self._tok.decode(self._ids[self._prefix_offset : self._read_offset],
                                  skip_special_tokens=self._skip_special)
        full = self._tok.decode(self._ids[self._prefix_offset :],
                                skip_special_tokens=self._skip_special)
        if len(full) <= len(prefix) or full.endswith("�"):
            return ""
        delta = full[len(prefix) :]
        self._prefix_offset = self._read_offset
        self._read_offset = len(self._ids)
        return delta

    @property
    def token_count(self) -> int:
        return len(self._ids)
