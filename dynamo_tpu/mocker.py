"""Mocker: a simulated engine worker for router/planner testing at scale.

The reference ships a full vLLM-like simulator (`lib/llm/src/mocker/*`,
SURVEY.md §2 row 35) so KV routing, metrics, and autoscaling logic can be
exercised without GPUs. Here the real ``EngineCore`` *is* the scheduler —
the mocker is just a runner with a timing model instead of a TPU: scheduling,
paging, prefix cache, preemption, KV events and metrics are all the
production code paths, so what the router/planner sees is exactly what a
real fleet emits, at simulated speed.

Timing model: prefill costs ``prefill_us_per_token * new_tokens``; a decode
step costs ``decode_us_base + decode_us_per_seq * batch``. Generated tokens
are deterministic per (seed, position) so tests can assert streams.
"""

from __future__ import annotations

import time

import numpy as np

from dynamo_tpu.engine.core import EngineConfig, EngineCore
from dynamo_tpu.engine.runner import StepBatch
from dynamo_tpu.engine.service import JaxEngineService


class MockRunner:
    """Drop-in for ModelRunner: no device, simulated latency."""

    def __init__(
        self,
        *,
        num_pages: int,
        page_size: int,
        vocab_size: int = 32000,
        prefill_us_per_token: float = 50.0,
        decode_us_base: float = 2000.0,
        decode_us_per_seq: float = 100.0,
        seed: int = 0,
        realtime: bool = True,
    ) -> None:
        self.num_pages = num_pages
        self.page_size = page_size
        self.vocab_size = vocab_size
        self.prefill_us_per_token = prefill_us_per_token
        self.decode_us_base = decode_us_base
        self.decode_us_per_seq = decode_us_per_seq
        self.seed = seed
        self.realtime = realtime
        self.simulated_us = 0.0
        self._layers, self._kv, self._hd = 1, 1, 8  # page payload shape stub

    def _sleep_us(self, us: float) -> None:
        self.simulated_us += us
        if self.realtime and us > 0:
            time.sleep(us / 1e6)

    def _tokens_for(self, positions: np.ndarray, row_tokens: np.ndarray) -> np.ndarray:
        # Deterministic pseudo-generation: next token = f(seed, pos, last token).
        return ((row_tokens.astype(np.int64) * 1103515245 + positions + self.seed) % (self.vocab_size - 2) + 1).astype(
            np.int32
        )

    def step(self, batch: StepBatch, lp_k: int = 0):
        b, t = batch.tokens.shape
        if t > 1:  # prefill
            new_tokens = int((batch.last_token_index + 1).sum())
            self._sleep_us(self.prefill_us_per_token * new_tokens)
        else:
            self._sleep_us(self.decode_us_base + self.decode_us_per_seq * b)
        last_tok = batch.tokens[np.arange(b), batch.last_token_index]
        last_pos = batch.positions[np.arange(b), batch.last_token_index]
        toks = self._tokens_for(last_pos, last_tok)
        if lp_k:
            # Synthetic but schema-complete logprobs (mock fleets exercise
            # the full API surface): chosen "probability" 0.5, alternatives
            # decaying deterministically.
            lps = np.full(b, np.log(0.5), np.float32)
            top_ids = (toks[:, None] + np.arange(lp_k)[None, :]) % self.vocab_size
            top_lps = np.log(0.5) - 0.5 * np.arange(1, lp_k + 1, dtype=np.float32)
            top_lps = np.broadcast_to(top_lps, (b, lp_k)).copy()
            top_lps[:, 0] = np.log(0.5)
            top_ids[:, 0] = toks
            return toks, {"logprob": lps, "top_ids": top_ids.astype(np.int32), "top_lps": top_lps}
        return toks

    def multi_step(self, batch: StepBatch, num_steps: int) -> np.ndarray:
        b = batch.tokens.shape[0]
        out = np.zeros((b, num_steps), np.int32)
        tok = batch.tokens[:, 0]
        pos = batch.positions[:, 0]
        for i in range(num_steps):
            self._sleep_us(self.decode_us_base + self.decode_us_per_seq * b)
            tok = self._tokens_for(pos, tok)
            out[:, i] = tok
            pos = pos + 1
        return out

    # Tier hooks: payload-free stubs (pair with NullStorage tiers).
    def read_page(self, page_id: int):
        shape = (self._layers, self._kv, self.page_size, self._hd)
        return np.zeros(shape, np.float32), np.zeros(shape, np.float32)

    def write_page(self, page_id: int, k, v) -> None:
        pass

    def read_pages(self, page_ids):
        return [self.read_page(p) for p in page_ids]

    def write_pages(self, page_ids, ks, vs) -> None:
        pass

    def cache_memory_bytes(self) -> int:
        return 0


def build_mock_core(
    config: EngineConfig | None = None,
    *,
    on_kv_event=None,
    **runner_kw,
) -> EngineCore:
    config = config or EngineConfig(num_pages=1024, page_size=16, max_batch_size=256, max_seq_len=32768)
    runner = MockRunner(num_pages=config.num_pages, page_size=config.page_size, **runner_kw)
    return EngineCore(runner, config, on_kv_event=on_kv_event)


async def build_mock_service(config: EngineConfig | None = None, **runner_kw) -> JaxEngineService:
    return await JaxEngineService(build_mock_core(config, **runner_kw)).start()
