"""Mocker: a simulated engine worker for router/planner testing at scale.

The reference ships a full vLLM-like simulator (`lib/llm/src/mocker/*`,
SURVEY.md §2 row 35) so KV routing, metrics, and autoscaling logic can be
exercised without GPUs. Here the real ``EngineCore`` *is* the scheduler —
the mocker is just a runner with a timing model instead of a TPU: scheduling,
paging, prefix cache, preemption, KV events and metrics are all the
production code paths, so what the router/planner sees is exactly what a
real fleet emits, at simulated speed.

Timing model: prefill costs ``prefill_us_per_token * new_tokens``; a decode
step costs ``decode_us_base + decode_us_per_seq * batch``. Generated tokens
are deterministic per (seed, position) so tests can assert streams.

Fleet fidelity (the fleetsim harness exposed these): ``jitter`` multiplies
every step's compute by deterministic lognormal noise (heteroscedastic —
absolute variance grows with the step cost, like real steps), and
``warmup_s``/``warmup_factor`` ramp a fresh worker from ``warmup_factor``×
compute down to 1× over its first ``warmup_s`` of stepping, so planner
scale-ups see realistic cold-start TTFT instead of instant capacity. Both
default off and leave the timing model bit-identical. Per-worker values
arrive via the ``DYN_MOCK_*`` env overlay (see :func:`build_mock_core`),
which is how the fleet plane gives each worker subprocess its own profile.
"""

from __future__ import annotations

import os
import time
from types import SimpleNamespace

import numpy as np

from dynamo_tpu.engine.core import EngineConfig, EngineCore
from dynamo_tpu.engine.runner import StepBatch
from dynamo_tpu.engine.service import JaxEngineService


class MockRunner:
    """Drop-in for ModelRunner: no device, simulated latency."""

    def __init__(
        self,
        *,
        num_pages: int,
        page_size: int,
        vocab_size: int = 32000,
        prefill_us_per_token: float = 50.0,
        decode_us_base: float = 2000.0,
        decode_us_per_seq: float = 100.0,
        seed: int = 0,
        realtime: bool = True,
        d2h_us: float = 0.0,
        jitter: float = 0.0,
        warmup_s: float = 0.0,
        warmup_factor: float = 1.0,
    ) -> None:
        self.num_pages = num_pages
        self.page_size = page_size
        self.vocab_size = vocab_size
        # Constrained (JSON-mode) decode reads ``runner.cfg.vocab_size``
        # when sizing token-mask caches and lookahead banks; this minimal
        # model-config shim keeps the mock API-compatible there.
        self.cfg = SimpleNamespace(vocab_size=vocab_size)
        self.prefill_us_per_token = prefill_us_per_token
        self.decode_us_base = decode_us_base
        self.decode_us_per_seq = decode_us_per_seq
        self.seed = seed
        self.realtime = realtime
        # Heteroscedastic step noise: lognormal(0, jitter) multiplier on
        # compute. A separate rng keeps token generation untouched.
        self.jitter = jitter
        self._jitter_rng = np.random.default_rng(seed ^ 0x5EED)
        # Cold-start ramp: warmup_factor x compute at the first step,
        # decaying linearly to 1.0 over warmup_s of wall time. The clock
        # starts lazily at the first step, so a worker that sat idle after
        # spawn still shows its ramp to the first requests routed at it.
        self.warmup_s = warmup_s
        self.warmup_factor = warmup_factor
        self._warm_t0: float | None = None
        # Device->host result-transfer latency per step: the synchronous loop
        # pays it inline (step() blocks on compute + copy); the overlapped
        # loop (step_async) pays it only at harvest, where it hides under the
        # next step's compute. 0 keeps legacy timing for existing tests.
        self.d2h_us = d2h_us
        # Device-cost plane: mock fleets light the same roofline surfaces
        # (flight hbm_bytes, /debug/cost, metrics) the real runner does —
        # there is no XLA program to extract from, so the synthetic
        # estimate IS the cost record (source stays "estimate").
        from dynamo_tpu.observability.cost import CostRegistry, cost_plane_enabled

        self.cost_registry = CostRegistry() if cost_plane_enabled() else None
        self.simulated_us = 0.0
        # Device-busy accounting for the overlap bench probe: cumulative
        # compute time vs. wall elapsed gives device_idle_frac.
        self.busy_us = 0.0
        self._busy_until = 0.0  # wall timestamp the simulated device frees up
        self._chain_host: np.ndarray | None = None  # last step_async samples
        self._layers, self._kv, self._hd = 1, 1, 8  # page payload shape stub

    def _sleep_us(self, us: float) -> None:
        self.simulated_us += us
        if self.realtime and us > 0:
            time.sleep(us / 1e6)

    def _timing_scale(self) -> float:
        """Per-step compute multiplier: warm-up ramp x jitter noise.

        Exactly 1.0 (and the jitter rng untouched) at the defaults, keeping
        legacy timing bit-identical.
        """
        scale = 1.0
        if self.warmup_s > 0.0 and self.warmup_factor > 1.0:
            if self._warm_t0 is None:
                self._warm_t0 = time.monotonic()
            frac = min(1.0, (time.monotonic() - self._warm_t0) / self.warmup_s)
            scale *= self.warmup_factor - (self.warmup_factor - 1.0) * frac
        if self.jitter > 0.0:
            scale *= float(self._jitter_rng.lognormal(0.0, self.jitter))
        return scale

    def _tokens_for(self, positions: np.ndarray, row_tokens: np.ndarray) -> np.ndarray:
        # Deterministic pseudo-generation: next token = f(seed, pos, last token).
        return ((row_tokens.astype(np.int64) * 1103515245 + positions + self.seed) % (self.vocab_size - 2) + 1).astype(
            np.int32
        )

    def _lp_aux(self, toks: np.ndarray, lp_k: int) -> dict:
        # Synthetic but schema-complete logprobs (mock fleets exercise
        # the full API surface): chosen "probability" 0.5, alternatives
        # decaying deterministically.
        b = toks.shape[0]
        lps = np.full(b, np.log(0.5), np.float32)
        top_ids = (toks[:, None] + np.arange(lp_k)[None, :]) % self.vocab_size
        top_lps = np.log(0.5) - 0.5 * np.arange(1, lp_k + 1, dtype=np.float32)
        top_lps = np.broadcast_to(top_lps, (b, lp_k)).copy()
        top_lps[:, 0] = np.log(0.5)
        top_ids[:, 0] = toks
        return {"logprob": lps, "top_ids": top_ids.astype(np.int32), "top_lps": top_lps}

    #: synthetic weight-stream bytes each processed token "moves" — scales
    #: the mock cost records without pretending to model a real chip.
    _MOCK_BYTES_PER_TOKEN = 65536

    def _observe_cost(self, batch: StepBatch, compute_us: float, *, spec: bool = False) -> None:
        reg = self.cost_registry
        if reg is None:
            return
        b, t = batch.tokens.shape
        if spec:
            kind = "spec_verify"
        elif t == 1:
            kind = "decode"
        elif batch.num_new is not None and bool((np.asarray(batch.num_new) == 1).any()):
            kind = "mixed"
        else:
            kind = "prefill"
        key = (b, t)
        if not reg.seen("mock_step", key):
            tokens = b * t
            reg.submit(
                "mock_step", key, kind,
                estimate={
                    "bytes": self._MOCK_BYTES_PER_TOKEN * tokens,
                    "flops": 2 * self._MOCK_BYTES_PER_TOKEN * tokens,
                },
            )
        reg.observe("mock_step", key, compute_us / 1e6, kind)

    def step(self, batch: StepBatch, lp_k: int = 0):
        b, t = batch.tokens.shape
        if t > 1:  # prefill
            new_tokens = int((batch.last_token_index + 1).sum())
            compute = self.prefill_us_per_token * new_tokens * self._timing_scale()
            self.busy_us += compute
            self._sleep_us(compute)
        else:
            compute = (self.decode_us_base + self.decode_us_per_seq * b) * self._timing_scale()
            self.busy_us += compute
            # The synchronous loop blocks on compute AND the result copy.
            self._sleep_us(compute + self.d2h_us)
        self._observe_cost(batch, compute)
        last_tok = batch.tokens[np.arange(b), batch.last_token_index]
        last_pos = batch.positions[np.arange(b), batch.last_token_index]
        toks = self._tokens_for(last_pos, last_tok)
        if lp_k:
            return toks, self._lp_aux(toks, lp_k)
        return toks

    def _mixed_compute_us(self, batch: StepBatch) -> float:
        """Timing for a (possibly mixed) step: every row pays the decode
        per-seq cost, extra real columns (prefill-chunk tokens) pay the
        per-token prefill cost on top."""
        b, t = batch.tokens.shape
        if batch.num_new is not None:
            total_new = int(np.asarray(batch.num_new).sum())
        else:
            total_new = int((batch.last_token_index + 1).sum()) if t > 1 else b
        return (
            self.decode_us_base
            + self.decode_us_per_seq * b
            + self.prefill_us_per_token * max(0, total_new - b)
        ) * self._timing_scale()

    def _chain_col0(self, batch: StepBatch, chain: bool, chain_src) -> np.ndarray:
        """Column-0 input token per row, with per-row chain sourcing from the
        flat host-side sample buffer (mirrors runner._apply_chain)."""
        tok0 = batch.tokens[:, 0].copy()
        if not chain:
            return tok0
        assert self._chain_host is not None, "chained step requires a previous async step"
        b = tok0.shape[0]
        src = np.arange(b, dtype=np.int32) if chain_src is None else np.asarray(chain_src, np.int32)
        sel = src >= 0
        assert not sel.any() or int(src.max()) < self._chain_host.shape[0], (
            "chain_src points past the sample buffer"
        )
        tok0[sel] = self._chain_host[src[sel]]
        return tok0

    def step_async(self, batch: StepBatch, lp_k: int = 0, *, chain: bool = False,
                   chain_src=None):
        """Mock of ModelRunner.step_async: returns a handle whose ``result()``
        blocks until the simulated device finishes this step's compute plus
        the d2h copy. Dispatch itself never blocks — consecutive chained
        dispatches queue on ``_busy_until``, so wall time per token in the
        overlapped loop is ~max(compute, d2h) instead of compute + d2h.
        Mixed batches (T > 1) and per-row ``chain_src`` sourcing mirror the
        real runner's contract."""
        b = batch.tokens.shape[0]
        compute = self._mixed_compute_us(batch)
        self.busy_us += compute
        self.simulated_us += compute + self.d2h_us
        self._observe_cost(batch, compute)
        now = time.monotonic()
        start = max(now, self._busy_until)
        self._busy_until = start + compute / 1e6
        ready_at = self._busy_until + self.d2h_us / 1e6
        tokens = batch.tokens.copy()
        tokens[:, 0] = self._chain_col0(batch, chain, chain_src)
        last_tok = tokens[np.arange(b), batch.last_token_index]
        last_pos = batch.positions[np.arange(b), batch.last_token_index]
        toks = self._tokens_for(last_pos, last_tok)
        self._chain_host = toks
        aux = self._lp_aux(toks, lp_k) if lp_k else None
        return MockStepTokens(self, toks, aux, ready_at)

    def _spec_targets(self, batch: StepBatch, verify_width: int,
                      tokens: np.ndarray) -> np.ndarray:
        """Exact-replay verify targets: column j's target is the token the
        sequential mock would generate from column j's input at its position
        (clamped to the row's last real column, like the device kernel)."""
        b = batch.tokens.shape[0]
        start = (batch.spec_start if batch.spec_start is not None
                 else np.zeros(b, np.int32))
        vi = np.minimum(
            start[:, None] + np.arange(verify_width, dtype=np.int32)[None, :],
            batch.last_token_index[:, None],
        )
        rows = np.arange(b)[:, None]
        return self._tokens_for(batch.positions[rows, vi], tokens[rows, vi])

    def spec_step(self, batch: StepBatch, verify_width: int, lp_k: int = 0):
        """Mock speculative verify (spec_k support for mock fleets)."""
        compute = self._mixed_compute_us(batch)
        self.busy_us += compute
        self._sleep_us(compute + self.d2h_us)
        self._observe_cost(batch, compute, spec=True)
        targets = self._spec_targets(batch, verify_width, batch.tokens)
        if lp_k:
            return targets, self._spec_lp_aux(targets, lp_k)
        return targets

    def _spec_lp_aux(self, targets: np.ndarray, lp_k: int) -> dict:
        base = self._lp_aux(targets[:, 0], lp_k)
        aux = {
            "logprob": np.broadcast_to(base["logprob"][:, None], targets.shape).copy(),
            "top_ids": np.broadcast_to(base["top_ids"][:, None, :], (*targets.shape, lp_k)).copy(),
            "top_lps": np.broadcast_to(base["top_lps"][:, None, :], (*targets.shape, lp_k)).copy(),
        }
        aux["top_ids"][..., 0] = targets
        return aux

    def spec_step_async(self, batch: StepBatch, verify_width: int, lp_k: int = 0, *,
                        chain_src=None):
        """Mock of ModelRunner.spec_step_async: verify as the pipeline's
        lookahead; targets become the flat chain buffer [B*V]."""
        compute = self._mixed_compute_us(batch)
        self.busy_us += compute
        self.simulated_us += compute + self.d2h_us
        self._observe_cost(batch, compute, spec=True)
        start = max(time.monotonic(), self._busy_until)
        self._busy_until = start + compute / 1e6
        ready_at = self._busy_until + self.d2h_us / 1e6
        tokens = batch.tokens.copy()
        tokens[:, 0] = self._chain_col0(batch, chain_src is not None, chain_src)
        targets = self._spec_targets(batch, verify_width, tokens)
        self._chain_host = targets.reshape(-1)
        aux = self._spec_lp_aux(targets, lp_k) if lp_k else None
        return MockSpecTokens(self, targets, aux, ready_at)

    def can_chain(self, batch_size: int) -> bool:
        return self._chain_host is not None and self._chain_host.shape[0] == batch_size

    def chain_len(self) -> int:
        return 0 if self._chain_host is None else int(self._chain_host.shape[0])

    def reset_chain(self) -> None:
        self._chain_host = None

    def multi_step(self, batch: StepBatch, num_steps: int) -> np.ndarray:
        b = batch.tokens.shape[0]
        out = np.zeros((b, num_steps), np.int32)
        tok = batch.tokens[:, 0]
        pos = batch.positions[:, 0]
        for i in range(num_steps):
            self._sleep_us((self.decode_us_base + self.decode_us_per_seq * b) * self._timing_scale())
            tok = self._tokens_for(pos, tok)
            out[:, i] = tok
            pos = pos + 1
        return out

    # Tier hooks: payload-free stubs (pair with NullStorage tiers).
    def read_page(self, page_id: int):
        shape = (self._layers, self._kv, self.page_size, self._hd)
        return np.zeros(shape, np.float32), np.zeros(shape, np.float32)

    def write_page(self, page_id: int, k, v) -> None:
        pass

    def read_pages(self, page_ids):
        return [self.read_page(p) for p in page_ids]

    def write_pages(self, page_ids, ks, vs) -> None:
        pass

    def cache_memory_bytes(self) -> int:
        return 0


class MockStepTokens:
    """Handle to a MockRunner.step_async dispatch (mirrors DeviceStepTokens)."""

    def __init__(self, runner: MockRunner, toks: np.ndarray, aux, ready_at: float) -> None:
        self._runner = runner
        self._toks = toks
        self._aux = aux
        self._ready_at = ready_at

    def result(self):
        if self._runner.realtime:
            wait = self._ready_at - time.monotonic()
            if wait > 0:
                time.sleep(wait)
        return self._toks[:, None], self._aux


class MockSpecTokens:
    """Handle to a MockRunner.spec_step_async dispatch (mirrors
    DeviceSpecTokens)."""

    def __init__(self, runner: MockRunner, targets: np.ndarray, aux, ready_at: float) -> None:
        self._runner = runner
        self._targets = targets
        self._aux = aux
        self._ready_at = ready_at

    def result(self):
        if self._runner.realtime:
            wait = self._ready_at - time.monotonic()
            if wait > 0:
                time.sleep(wait)
        return self._targets, self._aux


#: Env -> MockRunner kwarg overlay: how a fleet gives each worker
#: subprocess its own timing profile (fleetsim WorkerTimingProfile.to_env).
_ENV_RUNNER_KW = (
    ("DYN_MOCK_PREFILL_US_PER_TOKEN", "prefill_us_per_token", float),
    ("DYN_MOCK_DECODE_US_BASE", "decode_us_base", float),
    ("DYN_MOCK_DECODE_US_PER_SEQ", "decode_us_per_seq", float),
    ("DYN_MOCK_JITTER", "jitter", float),
    ("DYN_MOCK_WARMUP_S", "warmup_s", float),
    ("DYN_MOCK_WARMUP_FACTOR", "warmup_factor", float),
    ("DYN_MOCK_SEED", "seed", int),
)


def mock_runner_env_kw(env=None) -> dict:
    """MockRunner kwargs taken from ``DYN_MOCK_*`` environment variables."""
    env = os.environ if env is None else env
    out = {}
    for key, name, cast in _ENV_RUNNER_KW:
        if key in env:
            out[name] = cast(env[key])
    return out


def build_mock_core(
    config: EngineConfig | None = None,
    *,
    on_kv_event=None,
    **runner_kw,
) -> EngineCore:
    config = config or EngineConfig(num_pages=1024, page_size=16, max_batch_size=256, max_seq_len=32768)
    runner_kw = {**mock_runner_env_kw(), **runner_kw}  # explicit kwargs win
    runner = MockRunner(num_pages=config.num_pages, page_size=config.page_size, **runner_kw)
    return EngineCore(runner, config, on_kv_event=on_kv_event)


async def build_mock_service(config: EngineConfig | None = None, **runner_kw) -> JaxEngineService:
    return await JaxEngineService(build_mock_core(config, **runner_kw)).start()
