"""Tracing & profiling: JAX device traces (XPlane) + request-level spans.

Two complementary planes, mirroring the reference's tracing stack
(`logging.rs` tracing-subscriber spans + per-engine profilers):

- **Device**: :func:`device_trace` wraps `jax.profiler.start_trace` — dumps
  an XPlane/TensorBoard trace of everything the chip executed (XLA op
  timeline, HBM transfers, fusion view). ``annotate()`` adds named host-side
  regions (engine phases) to the same timeline via TraceAnnotation.
  Enable on any process with ``DYN_TRACE_DIR=/tmp/trace`` (traces the first
  ``DYN_TRACE_SECONDS``, default 5), or on demand over HTTP:
  ``POST /engine/profile {"seconds": 3}`` on the frontend.
- **Request spans**: :class:`Span` measures one phase of one request and
  logs it as a structured JSONL record (``runtime/logging.py`` flattens the
  fields), giving grep-able per-request latency breakdowns without a
  collector service. Every finished span also lands in the per-process
  :class:`SpanBuffer` ring (:data:`SPANS`), queryable by request or trace id
  — the storage behind ``GET /debug/traces/{request_id}``.
- **Distributed trace identity**: :class:`TraceContext` carries a W3C
  ``traceparent``-compatible (trace_id, span_id) pair across process hops.
  The frontend mints (or ingests) it, the runtime transport forwards it on
  the wire (``runtime/codec.py`` REQUEST frames, optional ``trace`` field),
  and the disagg prefill queue/KV-transfer path rides it too — so spans
  emitted on the frontend, the router, the decode engine, and a remote
  prefill worker all share one ``trace_id`` and parent/child links.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import re
import secrets
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Iterator

logger = logging.getLogger("dynamo.trace")

_lock = threading.Lock()
_active_dir: str | None = None


def trace_running() -> bool:
    return _active_dir is not None


def start_device_trace(log_dir: str) -> bool:
    """Begin an XPlane trace (idempotent; one at a time per process)."""
    global _active_dir
    import jax

    with _lock:
        if _active_dir is not None:
            return False
        jax.profiler.start_trace(log_dir)
        _active_dir = log_dir
    logger.info("device trace started -> %s", log_dir)
    return True


def stop_device_trace() -> str | None:
    global _active_dir
    import jax

    with _lock:
        if _active_dir is None:
            return None
        jax.profiler.stop_trace()
        path, _active_dir = _active_dir, None
    logger.info("device trace written -> %s", path)
    return path


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    started = start_device_trace(log_dir)
    try:
        yield
    finally:
        if started:
            stop_device_trace()


def annotate(name: str):
    """Named region on the profiler timeline.

    A no-op context when no trace is active — callers can sit on hot paths
    (the engine step loop) without paying TraceAnnotation construction."""
    if _active_dir is None:
        return contextlib.nullcontext()
    import jax

    return jax.profiler.TraceAnnotation(name)


async def profile_for(seconds: float, log_dir: str) -> str | None:
    """Trace the next ``seconds`` of device work (the HTTP hook's body)."""
    import asyncio

    if not start_device_trace(log_dir):
        return None
    try:
        await asyncio.sleep(seconds)
    finally:
        path = stop_device_trace()  # stop even on cancellation, then propagate
    return path


def maybe_trace_from_env() -> None:
    """Start a bounded trace when DYN_TRACE_DIR is set (worker bring-up)."""
    log_dir = os.environ.get("DYN_TRACE_DIR")
    if not log_dir:
        return
    try:
        seconds = float(os.environ.get("DYN_TRACE_SECONDS", "5"))
    except ValueError:
        logger.warning("ignoring malformed DYN_TRACE_SECONDS=%r", os.environ["DYN_TRACE_SECONDS"])
        seconds = 5.0
    try:
        if not start_device_trace(log_dir):
            return
    except Exception:
        # Observability must never take the serving worker down.
        logger.exception("could not start device trace in %s", log_dir)
        return

    def stop_later() -> None:
        time.sleep(seconds)
        stop_device_trace()

    threading.Thread(target=stop_later, name="dyn-trace-stop", daemon=True).start()


# -- distributed trace identity ---------------------------------------------

_TRACEPARENT_RE = re.compile(r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")


def _new_trace_id() -> str:
    return secrets.token_hex(16)


def _new_span_id() -> str:
    return secrets.token_hex(8)


@dataclass(frozen=True)
class TraceContext:
    """A W3C-trace-context-compatible (trace_id, span_id) pair.

    ``trace_id`` names the whole distributed request; ``span_id`` names the
    *current* span — a child span created under this context records it as
    ``parent_id``. The dict form (plain strings) is what rides msgpack/JSON
    hops: codec REQUEST frames, disagg queue tasks, KV-transfer chunks.
    """

    trace_id: str
    span_id: str

    @classmethod
    def new(cls) -> "TraceContext":
        return cls(trace_id=_new_trace_id(), span_id=_new_span_id())

    @classmethod
    def from_traceparent(cls, header: str | None) -> "TraceContext | None":
        if not header:
            return None
        m = _TRACEPARENT_RE.match(header.strip().lower())
        if m is None:
            return None
        return cls(trace_id=m.group(1), span_id=m.group(2))

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def to_dict(self) -> dict[str, str]:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_dict(cls, obj: Any) -> "TraceContext | None":
        if not isinstance(obj, dict) or "trace_id" not in obj:
            return None
        return cls(trace_id=str(obj["trace_id"]), span_id=str(obj.get("span_id", "")))


# -- span collection ----------------------------------------------------------


class SpanBuffer:
    """Bounded per-process ring of finished spans (thread-safe).

    Spans are plain dicts (see :meth:`Span._record`): name, trace/span/parent
    ids, request_id, wall + monotonic start, duration, status ok|error and
    the exception type on failure. ``GET /debug/traces/{request_id}`` fans
    out to every worker's buffer and assembles one timeline from the union.
    """

    def __init__(self, capacity: int = 4096) -> None:
        self._spans: deque[dict] = deque(maxlen=max(1, capacity))
        self._lock = threading.Lock()

    def record(self, span: dict) -> None:
        with self._lock:
            self._spans.append(span)

    def query(self, *, request_id: str | None = None, trace_id: str | None = None) -> list[dict]:
        with self._lock:
            spans = list(self._spans)
        if request_id is not None:
            spans = [s for s in spans if s.get("request_id") == request_id]
        if trace_id is not None:
            spans = [s for s in spans if s.get("trace_id") == trace_id]
        return spans

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


def _buffer_capacity() -> int:
    try:
        return int(os.environ.get("DYN_SPAN_BUFFER", "4096"))
    except ValueError:
        return 4096


#: The per-process span ring every finished Span records into.
SPANS = SpanBuffer(_buffer_capacity())

#: The span currently open in this task/thread (contextvar: async-safe).
_CURRENT_SPAN: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "dynamo_current_span", default=None
)


def current_span() -> "Span | None":
    """The innermost open Span in the current task/thread, if any.

    Lets unrelated code — notably ``runtime/logging.py``'s log-record filter —
    stamp trace_id/span_id onto whatever happens inside a span without the
    span being threaded through call signatures.
    """
    return _CURRENT_SPAN.get()


class Span:
    """One timed phase of one request, logged as structured JSONL.

    >>> with Span("prefill", trace=ctx, request_id=rid, tokens=len(ids)):
    ...     ...

    Logs ``{"span": "prefill", "duration_ms": 12.3, "trace_id": ...,
    "span_id": ..., "parent_id": ..., "status": "ok", ...}`` at DEBUG (set
    ``DYN_LOG_LEVEL=DEBUG`` + ``DYN_LOGGING_JSONL=1`` to collect). A raise
    inside the block still records the span — ``status="error"`` with the
    exception type under ``error`` — and propagates. Every exit also lands
    the span in :data:`SPANS`.

    ``trace`` threads the distributed identity: the span's ``parent_id`` is
    the incoming context's span_id, and :attr:`context` is what downstream
    hops should receive (same trace_id, this span as parent).
    """

    __slots__ = (
        "name", "fields", "t0", "t_wall",
        "trace_id", "span_id", "parent_id", "status", "error_type",
        "_cv_token",
    )

    def __init__(self, name: str, *, trace: TraceContext | None = None, **fields: Any) -> None:
        self.name = name
        self.fields = fields
        if trace is not None:
            self.trace_id = trace.trace_id
            self.parent_id = trace.span_id or None
        else:
            self.trace_id = _new_trace_id()  # root of a fresh trace
            self.parent_id = None
        self.span_id = _new_span_id()
        self.status = "ok"
        self.error_type: str | None = None
        self.t0 = 0.0
        self.t_wall = 0.0
        self._cv_token: contextvars.Token | None = None

    @property
    def context(self) -> TraceContext:
        """The context downstream hops should inherit (this span as parent)."""
        return TraceContext(trace_id=self.trace_id, span_id=self.span_id)

    def __enter__(self) -> "Span":
        self.t0 = time.perf_counter()
        self.t_wall = time.time()
        self._cv_token = _CURRENT_SPAN.set(self)
        return self

    def __exit__(self, exc_type, _exc, _tb) -> None:
        if self._cv_token is not None:
            try:
                _CURRENT_SPAN.reset(self._cv_token)
            except ValueError:
                # Exited in a different context than entered (the engine
                # service holds spans open across awaits); just clear.
                _CURRENT_SPAN.set(None)
            self._cv_token = None
        ms = (time.perf_counter() - self.t0) * 1e3
        if exc_type is not None:
            self.status = "error"
            self.error_type = exc_type.__name__
        extra = {
            "span": self.name, "duration_ms": round(ms, 3),
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "status": self.status,
            **self.fields,
        }
        if self.error_type is not None:
            extra["error"] = self.error_type
            logger.warning("span %s failed after %.1fms", self.name, ms, extra=extra)
        else:
            logger.debug("span %s %.1fms", self.name, ms, extra=extra)
        self._record(ms)

    def _record(self, duration_ms: float) -> None:
        doc: dict[str, Any] = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ts": self.t_wall,
            "start_mono": self.t0,
            "duration_ms": round(duration_ms, 3),
            "status": self.status,
        }
        if self.error_type is not None:
            doc["error"] = self.error_type
        for k, v in self.fields.items():
            doc.setdefault(k, v)
        SPANS.record(doc)


def record_span(
    name: str,
    duration_ms: float,
    *,
    trace: TraceContext | None = None,
    start_ts: float | None = None,
    status: str = "ok",
    **fields: Any,
) -> dict:
    """Record an already-measured phase as a finished span.

    For durations captured by existing instrumentation (the KV-wire
    gather/pack/wire phase clocks, queue-wait gaps computed from enqueue
    stamps) where wrapping the work in a ``with Span(...)`` block is not
    possible after the fact. Returns the recorded span dict.
    """
    span = Span(name, trace=trace, **fields)
    span.t_wall = start_ts if start_ts is not None else time.time() - duration_ms / 1e3
    span.t0 = time.perf_counter() - duration_ms / 1e3
    span.status = status
    logger.debug(
        "span %s %.1fms", name, duration_ms,
        extra={
            "span": name, "duration_ms": round(duration_ms, 3),
            "trace_id": span.trace_id, "span_id": span.span_id,
            "parent_id": span.parent_id, "status": status, **fields,
        },
    )
    span._record(duration_ms)
    return {
        "name": name, "trace_id": span.trace_id, "span_id": span.span_id,
        "parent_id": span.parent_id, "duration_ms": round(duration_ms, 3),
        "status": status, **fields,
    }


def trace_of(context: Any) -> TraceContext | None:
    """The TraceContext riding a runtime ``Context`` (or None)."""
    return TraceContext.from_dict(getattr(context, "trace", None))
