"""Tracing & profiling: JAX device traces (XPlane) + request-level spans.

Two complementary planes, mirroring the reference's tracing stack
(`logging.rs` tracing-subscriber spans + per-engine profilers):

- **Device**: :func:`device_trace` wraps `jax.profiler.start_trace` — dumps
  an XPlane/TensorBoard trace of everything the chip executed (XLA op
  timeline, HBM transfers, fusion view). ``annotate()`` adds named host-side
  regions (engine phases) to the same timeline via TraceAnnotation.
  Enable on any process with ``DYN_TRACE_DIR=/tmp/trace`` (traces the first
  ``DYN_TRACE_SECONDS``, default 5), or on demand over HTTP:
  ``POST /engine/profile {"seconds": 3}`` on the frontend.
- **Request spans**: :class:`Span` measures one phase of one request and
  logs it as a structured JSONL record (``runtime/logging.py`` flattens the
  fields), giving grep-able per-request latency breakdowns without a
  collector service.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from typing import Any, Iterator

logger = logging.getLogger("dynamo.trace")

_lock = threading.Lock()
_active_dir: str | None = None


def trace_running() -> bool:
    return _active_dir is not None


def start_device_trace(log_dir: str) -> bool:
    """Begin an XPlane trace (idempotent; one at a time per process)."""
    global _active_dir
    import jax

    with _lock:
        if _active_dir is not None:
            return False
        jax.profiler.start_trace(log_dir)
        _active_dir = log_dir
    logger.info("device trace started -> %s", log_dir)
    return True


def stop_device_trace() -> str | None:
    global _active_dir
    import jax

    with _lock:
        if _active_dir is None:
            return None
        jax.profiler.stop_trace()
        path, _active_dir = _active_dir, None
    logger.info("device trace written -> %s", path)
    return path


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    started = start_device_trace(log_dir)
    try:
        yield
    finally:
        if started:
            stop_device_trace()


def annotate(name: str):
    """Named region on the profiler timeline.

    A no-op context when no trace is active — callers can sit on hot paths
    (the engine step loop) without paying TraceAnnotation construction."""
    if _active_dir is None:
        return contextlib.nullcontext()
    import jax

    return jax.profiler.TraceAnnotation(name)


async def profile_for(seconds: float, log_dir: str) -> str | None:
    """Trace the next ``seconds`` of device work (the HTTP hook's body)."""
    import asyncio

    if not start_device_trace(log_dir):
        return None
    try:
        await asyncio.sleep(seconds)
    finally:
        path = stop_device_trace()  # stop even on cancellation, then propagate
    return path


def maybe_trace_from_env() -> None:
    """Start a bounded trace when DYN_TRACE_DIR is set (worker bring-up)."""
    log_dir = os.environ.get("DYN_TRACE_DIR")
    if not log_dir:
        return
    try:
        seconds = float(os.environ.get("DYN_TRACE_SECONDS", "5"))
    except ValueError:
        logger.warning("ignoring malformed DYN_TRACE_SECONDS=%r", os.environ["DYN_TRACE_SECONDS"])
        seconds = 5.0
    try:
        if not start_device_trace(log_dir):
            return
    except Exception:
        # Observability must never take the serving worker down.
        logger.exception("could not start device trace in %s", log_dir)
        return

    def stop_later() -> None:
        time.sleep(seconds)
        stop_device_trace()

    threading.Thread(target=stop_later, name="dyn-trace-stop", daemon=True).start()


class Span:
    """One timed phase of one request, logged as structured JSONL.

    >>> with Span("prefill", request_id=rid, tokens=len(ids)):
    ...     ...

    Logs ``{"span": "prefill", "duration_ms": 12.3, "request_id": ..., ...}``
    at DEBUG (set ``DYN_LOG_LEVEL=DEBUG`` + ``DYN_LOGGING_JSONL=1`` to
    collect); exceptions mark the span failed and propagate.
    """

    __slots__ = ("name", "fields", "t0")

    def __init__(self, name: str, **fields: Any) -> None:
        self.name = name
        self.fields = fields

    def __enter__(self) -> "Span":
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, _exc, _tb) -> None:
        ms = (time.perf_counter() - self.t0) * 1e3
        extra = {"span": self.name, "duration_ms": round(ms, 3), **self.fields}
        if exc_type is not None:
            extra["error"] = exc_type.__name__
            logger.warning("span %s failed after %.1fms", self.name, ms, extra=extra)
        else:
            logger.debug("span %s %.1fms", self.name, ms, extra=extra)
