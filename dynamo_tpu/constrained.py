"""Constrained decoding: OpenAI ``response_format: {"type": "json_object"}``.

Guarantees every generated token keeps the output a valid JSON *prefix*,
and (unlike OpenAI's "may truncate at max_tokens" caveat) force-closes
open structures when the remaining token budget runs low, so finished
responses parse. The reference has no counterpart (vLLM-level feature the
wrapped engines provide; first-party here).

Design, sized for a 128k-vocab TPU serving path:

- **Char-level JSON pushdown machine** (:class:`JsonMachine`): mode +
  container stack; accepts exactly the prefixes of JSON values (strings
  with escapes, numbers, literals, arrays, objects).
- **Token masks cached by machine summary** (:class:`TokenMaskCache`):
  the set of allowed next TOKENS depends only on a bounded summary of the
  machine (mode, pending literal, top few stack symbols) — a few dozen
  distinct summaries in practice. Computing a mask walks every vocab
  piece through the machine once per NEW summary (~O(vocab) chars) and
  is cached forever after; steady-state per-step cost is a dict lookup.
  Pieces that would close deeper than the summary records are
  conservatively disallowed (the output stays valid JSON; the model just
  closes one level per token in >3-deep nests).
- The engine applies the mask on-device (logits + ``where(mask, x,
  -inf)``) on the single-step sync path, and advances the machine on the
  host with each accepted token (`engine/core.py`).

Token text comes from ``tokenizer.decode([id])`` per piece; tokenizers
whose single-token decode is lossy (partial UTF-8 fragments render as
replacement chars) get those tokens conservatively disallowed inside
strings only when they decode to the replacement char.
"""

from __future__ import annotations

import dataclasses
import os
import time

import numpy as np

# Modes.
VALUE = "V"        # expecting a value start
IN_STRING = "S"    # inside a string
STR_ESCAPE = "E"   # after backslash in a string
STR_HEX = "U"      # inside \uXXXX: literal = key-marker + one 'h' per digit left
IN_NUMBER = "N"    # inside a number (last char was part of a number)
AFTER_VALUE = "A"  # a value just completed; expect , } ] or end
EXPECT_KEY = "K"   # inside an object, expecting a key string or }
AFTER_KEY = "C"    # key string done, expecting :
LITERAL = "L"      # partway through true/false/null
REJECT = "X"

_WS = " \t\n\r"
_LITERALS = {"t": "rue", "f": "alse", "n": "ull"}
# RFC 8259 string escapes: exactly these after a backslash; \u is handled
# as its own pending-hex state so it consumes exactly 4 hex digits ('\u12',
# '\uZZZZ' or a bare '\q' must not be accepted — json.loads rejects them).
_ESCAPABLE = set('"\\/bfnrt')
_HEX = set("0123456789abcdefABCDEF")


@dataclasses.dataclass(frozen=True)
class MachineState:
    mode: str = VALUE
    literal: str = ""          # remaining chars of a pending literal
    stack: tuple = ()          # container stack, innermost last: '{' / '['
    # IN_NUMBER only: the number is terminable (has digits, doesn't end in
    # '.', 'e', '+', '-' — "-" or "1e+" must not count as complete).
    num_ok: bool = False
    # VALUE/EXPECT_KEY reached via ',': an immediate closer would produce a
    # trailing comma ('[1,]' / '{"a":1,}'), which is not JSON.
    no_close: bool = False

    @property
    def depth(self) -> int:
        return len(self.stack)

    def summary(self) -> tuple:
        """Bounded cache key: masks computed from equal summaries are equal
        for every piece that closes at most len(kept stack) levels."""
        # min(depth, 4): depth <= 3 states carry their FULL stack (every
        # piece verdict is determined) and must never share a key with
        # deeper states whose 4th-from-top symbol is unrecorded.
        return (self.mode, self.literal, self.stack[-3:], min(self.depth, 4),
                self.num_ok, self.no_close)

    def complete(self) -> bool:
        """The text so far is a COMPLETE JSON value."""
        if self.depth != 0:
            return False
        return self.mode == AFTER_VALUE or (self.mode == IN_NUMBER and self.num_ok)


def advance(state: MachineState, ch: str) -> MachineState:
    """One character step; returns a REJECT-mode state on invalid input."""
    mode, lit, stack = state.mode, state.literal, state.stack

    def st(m, l="", s=stack):
        return MachineState(m, l, s)

    bad = MachineState(REJECT)
    if mode == REJECT:
        return bad
    if mode == IN_STRING:
        if ch == '"':
            # Key strings finish to AFTER_KEY; value strings to AFTER_VALUE.
            return st(AFTER_KEY if lit == "k" else AFTER_VALUE)
        if ch == "\\":
            return st(STR_ESCAPE, lit)
        # RFC 8259: control characters U+0000..U+001F must be escaped.
        return bad if ord(ch) < 0x20 else st(IN_STRING, lit)
    if mode == STR_ESCAPE:
        if ch == "u":
            return st(STR_HEX, lit + "hhhh")
        return st(IN_STRING, lit) if ch in _ESCAPABLE else bad
    if mode == STR_HEX:
        if ch not in _HEX:
            return bad
        rest = lit[:-1]  # one pending hex digit consumed
        return st(STR_HEX, rest) if rest.endswith("h") else st(IN_STRING, rest)
    if mode == LITERAL:
        if lit and ch == lit[0]:
            return st(AFTER_VALUE) if len(lit) == 1 else st(LITERAL, lit[1:])
        return bad
    if mode == IN_NUMBER:
        # Full JSON number grammar; phase rides in ``literal``:
        # sign -> (zero | int) -> [frac0 -> frac] -> [exp0 -> exp1? -> exp]
        ph = lit

        def num(phase, ok):
            return MachineState(IN_NUMBER, phase, stack, num_ok=ok)

        if ph == "sign":
            if ch == "0":
                return num("zero", True)
            return num("int", True) if ch.isdigit() else bad
        if ph in ("zero", "int", "frac", "exp"):
            if ch.isdigit():
                if ph == "zero":
                    return bad  # leading-zero rule: "01" is not JSON
                return num(ph, True)
            if ch == "." and ph in ("zero", "int"):
                return num("frac0", False)
            if ch in "eE" and ph in ("zero", "int", "frac"):
                return num("exp0", False)
            # Delimiter ends a terminable number (reinterpreted from
            # AFTER_VALUE); "-," / "1e+," are not JSON.
            return advance(st(AFTER_VALUE), ch) if state.num_ok else bad
        if ph == "frac0":
            return num("frac", True) if ch.isdigit() else bad
        if ph == "exp0":
            if ch in "+-":
                return num("exp1", False)
            return num("exp", True) if ch.isdigit() else bad
        if ph == "exp1":
            return num("exp", True) if ch.isdigit() else bad
        return bad
    if mode == VALUE:
        if ch in _WS:
            return state
        if ch == '"':
            return st(IN_STRING)
        if ch == "-":
            return MachineState(IN_NUMBER, "sign", stack, num_ok=False)
        if ch == "0":
            return MachineState(IN_NUMBER, "zero", stack, num_ok=True)
        if ch in "123456789":
            return MachineState(IN_NUMBER, "int", stack, num_ok=True)
        if ch in _LITERALS:
            return st(LITERAL, _LITERALS[ch])
        if ch == "{":
            return MachineState(EXPECT_KEY, "", stack + ("{",))
        if ch == "[":
            return MachineState(VALUE, "", stack + ("[",))
        if ch == "]" and stack and stack[-1] == "[" and not state.no_close:
            # Empty array closes straight from VALUE (but not right after a
            # comma — '[1,]' is not JSON).
            return MachineState(AFTER_VALUE, "", stack[:-1])
        return bad
    if mode == EXPECT_KEY:
        if ch in _WS:
            return state
        if ch == '"':
            return st(IN_STRING, "k")
        if ch == "}" and stack and stack[-1] == "{" and not state.no_close:
            return MachineState(AFTER_VALUE, "", stack[:-1])
        return bad
    if mode == AFTER_KEY:
        if ch in _WS:
            return state
        return st(VALUE) if ch == ":" else bad
    if mode == AFTER_VALUE:
        if ch in _WS:
            return state
        if ch == "," and stack:
            return MachineState(
                EXPECT_KEY if stack[-1] == "{" else VALUE, "", stack, no_close=True
            )
        if ch == "}" and stack and stack[-1] == "{":
            return MachineState(AFTER_VALUE, "", stack[:-1])
        if ch == "]" and stack and stack[-1] == "[":
            return MachineState(AFTER_VALUE, "", stack[:-1])
        return bad
    return bad


def advance_text(state: MachineState, text: str) -> MachineState:
    for ch in text:
        state = advance(state, ch)
        if state.mode == REJECT:
            return state
    return state


def advance_text_tracked(state: MachineState, text: str) -> tuple[MachineState, int]:
    """Like :func:`advance_text`, also returning the MINIMUM stack depth
    touched — a piece whose simulation dips below the depths the summary
    records consulted stack symbols the cache key doesn't know about, so
    its verdict must not be cached for that summary."""
    min_depth = state.depth
    for ch in text:
        state = advance(state, ch)
        if state.mode == REJECT:
            return state, min_depth
        min_depth = min(min_depth, state.depth)
    return state, min_depth


#: A piece per closing token used by force-close (one level per step).
_CLOSERS = {"{": "}", "[": "]"}


# ---------------------------------------------------------------------------
# Vectorized mask builder
#
# The pure-Python builder walks every vocab piece through ``advance`` char by
# char — ~0.4 s per cold summary at a 128k vocab, which caps chained
# JSON-mode traffic at exactly the vocab sizes production models use. The
# machine's per-piece state is finite and small (mode enum, one of ~27
# literal/phase strings, a relative stack depth, a handful of flags), so the
# whole vocab can be simulated COLUMN-WISE as numpy array ops: one pass over
# ``max_piece_len`` columns updates every piece's machine in lockstep. The
# result (mask, close budgets, transition descriptors) is bitwise identical
# to the Python builder's; DYN_CONSTRAINT_VECTOR_MASKS=0 falls back.
# ---------------------------------------------------------------------------

# Mode ids (np.int8 enum mirroring the single-char mode constants).
_M_V, _M_S, _M_E, _M_U, _M_N, _M_A, _M_K, _M_C, _M_L, _M_X = range(10)
_MODE_ID = {VALUE: _M_V, IN_STRING: _M_S, STR_ESCAPE: _M_E, STR_HEX: _M_U,
            IN_NUMBER: _M_N, AFTER_VALUE: _M_A, EXPECT_KEY: _M_K,
            AFTER_KEY: _M_C, LITERAL: _M_L, REJECT: _M_X}
_MODE_STR = {v: k for k, v in _MODE_ID.items()}

# Every value MachineState.literal can hold: the empty/key markers, literal
# tails, pending-hex chains (value and key strings), and number phases.
_LIT_STRINGS = (
    "", "k",
    "rue", "ue", "e", "alse", "lse", "se", "ull", "ll", "l",
    "hhhh", "hhh", "hh", "h", "khhhh", "khhh", "khh", "kh",
    "sign", "zero", "int", "frac0", "frac", "exp0", "exp1", "exp",
)
_LIT_ID = {s: i for i, s in enumerate(_LIT_STRINGS)}
_NLIT = len(_LIT_STRINGS)

# LITERAL mode: expected next char code and successor lit id (-1 = literal
# complete -> AFTER_VALUE).
_LIT_EXPECT = np.zeros(_NLIT, np.int32)
_LIT_NEXT = np.full(_NLIT, -1, np.int8)
for _s in ("rue", "ue", "e", "alse", "lse", "se", "ull", "ll", "l"):
    _LIT_EXPECT[_LIT_ID[_s]] = ord(_s[0])
    _LIT_NEXT[_LIT_ID[_s]] = _LIT_ID.get(_s[1:], -1) if len(_s) > 1 else -1
# STR_HEX chains: one hex digit consumed -> lit[:-1]; stay in STR_HEX while
# the rest still ends in 'h', else back to IN_STRING with "" / "k".
_HEX_NEXT = np.zeros(_NLIT, np.int8)
_HEX_STAY = np.zeros(_NLIT, bool)
for _s in ("hhhh", "hhh", "hh", "h", "khhhh", "khhh", "khh", "kh"):
    _rest = _s[:-1]
    _HEX_NEXT[_LIT_ID[_s]] = _LIT_ID[_rest]
    _HEX_STAY[_LIT_ID[_s]] = _rest.endswith("h")
# STR_ESCAPE '\u': lit ("" or "k") gains four pending hex digits.
_ESC_U_NEXT = np.zeros(_NLIT, np.int8)
_ESC_U_NEXT[_LIT_ID[""]] = _LIT_ID["hhhh"]
_ESC_U_NEXT[_LIT_ID["k"]] = _LIT_ID["khhhh"]
# budget_to_close lookups: literal length, pending hex digits, key marker.
_LIT_LEN = np.array([len(s) for s in _LIT_STRINGS], np.int16)
_LIT_HCOUNT = np.array([s.count("h") for s in _LIT_STRINGS], np.int16)
_LIT_ISKEY = np.array([s.startswith("k") for s in _LIT_STRINGS], bool)

# Char property bits. ASCII from the table below; non-ASCII chars carry
# only _P_UDIG (``str.isdigit()`` — the Python machine's number phases use
# it, so e.g. Arabic-Indic digits advance IN_NUMBER exactly as they do
# there; everything else is plain string content matching no structural
# char).
_P_WS, _P_DIG19, _P_ZERO, _P_HEX, _P_ESC, _P_CTRL, _P_UDIG = 1, 2, 4, 8, 16, 32, 64
_PROPS = np.zeros(128, np.uint8)
for _c in _WS:
    _PROPS[ord(_c)] |= _P_WS
for _c in "123456789":
    _PROPS[ord(_c)] |= _P_DIG19
_PROPS[ord("0")] |= _P_ZERO
for _c in "0123456789abcdefABCDEF":
    _PROPS[ord(_c)] |= _P_HEX
for _c in _ESCAPABLE:
    _PROPS[ord(_c)] |= _P_ESC
_PROPS[:0x20] |= _P_CTRL
for _c in "0123456789":
    _PROPS[ord(_c)] |= _P_UDIG

_SYM_ID = {"{": 1, "[": 2}
_SYM_STR = {1: "{", 2: "["}

# Number phase lit-id groups (IN_NUMBER rides its phase in ``literal``).
_PH_SIGN, _PH_ZERO, _PH_INT = _LIT_ID["sign"], _LIT_ID["zero"], _LIT_ID["int"]
_PH_FRAC0, _PH_FRAC = _LIT_ID["frac0"], _LIT_ID["frac"]
_PH_EXP0, _PH_EXP1, _PH_EXP = _LIT_ID["exp0"], _LIT_ID["exp1"], _LIT_ID["exp"]
_PH_CORE = np.zeros(_NLIT, bool)  # zero|int|frac|exp: digit-extensible
for _p in (_PH_ZERO, _PH_INT, _PH_FRAC, _PH_EXP):
    _PH_CORE[_p] = True
_PH_DOT_OK = np.zeros(_NLIT, bool)  # '.' legal: zero|int
_PH_DOT_OK[_PH_ZERO] = _PH_DOT_OK[_PH_INT] = True
_PH_EXP_OK = np.zeros(_NLIT, bool)  # e|E legal: zero|int|frac
_PH_EXP_OK[_PH_ZERO] = _PH_EXP_OK[_PH_INT] = _PH_EXP_OK[_PH_FRAC] = True

# Mode-keyed budget_to_close extras (IN_NUMBER/STR_HEX/LITERAL handled
# separately — they depend on lit/num_ok).
_MODE_EXTRA = np.zeros(10, np.int16)
for _m, _x in ((_M_S, 1), (_M_E, 2), (_M_C, 2), (_M_V, 1), (_M_K, 1)):
    _MODE_EXTRA[_m] = _x


class _VocabTable:
    """Per-tokenizer piece descriptors for the vectorized builder: a padded
    char-code matrix plus per-char ASCII property bits, built once."""

    def __init__(self, pieces: list[str]) -> None:
        V = len(pieces)
        lens = np.fromiter((len(p) for p in pieces), np.int32, count=V)
        maxlen = int(lens.max()) if V else 0
        codes = np.full((V, max(maxlen, 1)), -1, np.int32)
        for t, p in enumerate(pieces):
            if p:
                codes[t, : len(p)] = np.frombuffer(p.encode("utf-32-le"), "<u4")
        props = np.zeros_like(codes, np.uint8)
        ascii_mask = (codes >= 0) & (codes < 128)
        props[ascii_mask] = _PROPS[codes[ascii_mask]]
        hi = codes >= 128
        if hi.any():
            uniq = np.unique(codes[hi])
            udig = np.fromiter(
                (chr(int(u)).isdigit() for u in uniq), bool, count=uniq.size
            )
            props[hi] |= np.where(
                udig[np.searchsorted(uniq, codes[hi])], _P_UDIG, 0
            ).astype(np.uint8)
        self.lens = lens
        self.codes = codes
        self.props = props
        self.maxlen = maxlen
        self.empty = lens == 0
        self.has_replacement = (codes == 0xFFFD).any(axis=1)


def _simulate_vocab(state: MachineState, tab: _VocabTable):
    """Run every vocab piece through the machine in lockstep.

    Returns ``(mode, lit, rel, minrel, num_ok, no_close, buf)`` final
    arrays; ``buf[t, s]`` is piece ``t``'s current stack symbol at relative
    depth ``s - 3`` (slots 0-2 pre-seeded with the summary's recorded
    ``stack[-3:]``, 0 = no symbol), so ``buf[t, minrel+3 : rel+3]`` is
    exactly ``ns.stack[min_depth:]``. Pieces are REJECTed (mode X) exactly
    when the Python machine rejects them, plus — for depth > 3 states —
    when they dip below the recorded stack suffix (the soundness floor
    would disallow them anyway, and early kill keeps slot indices valid).
    """
    V, width = tab.codes.shape
    depth0 = state.depth
    deep = depth0 > 3
    mode = np.full(V, _MODE_ID[state.mode], np.int8)
    lit = np.full(V, _LIT_ID[state.literal], np.int8)
    rel = np.zeros(V, np.int16)
    minrel = np.zeros(V, np.int16)
    num_ok = np.full(V, state.num_ok, bool)
    no_close = np.full(V, state.no_close, bool)
    buf = np.zeros((V, width + 3), np.uint8)
    base = state.stack[-3:]
    for i, sym in enumerate(base):
        buf[:, 3 - len(base) + i] = _SYM_ID[sym]

    Q, BSL, LB, RB, LK, RK = ord('"'), ord("\\"), ord("{"), ord("}"), ord("["), ord("]")
    COMMA, COLON, MINUS, PLUS, DOT = ord(","), ord(":"), ord("-"), ord("+"), ord(".")
    ZERO, LE, UE, LU = ord("0"), ord("e"), ord("E"), ord("u")
    LT, LF, LN = ord("t"), ord("f"), ord("n")

    # The column loop runs COMPACTED: ``idx`` holds the still-live row ids
    # (piece long enough, not REJECTed) and every block operates on arrays
    # of ``idx.size``. Rejections shrink the working set fast (a cold build
    # in a structural mode kills most of a random vocab in the first one
    # or two columns), so later columns cost almost nothing. ``m``/``l``/
    # ``nk``/``nc`` are the compact views, scattered back each column;
    # ``rel``/``minrel``/``buf`` are touched by few rows (pushes/pops) and
    # stay full-width, indexed through ``idx``.
    live = np.flatnonzero(tab.lens > 0)
    for j in range(tab.maxlen):
        if j:
            live = live[(tab.lens[live] > j) & (mode[live] != _M_X)]
        if live.size == 0:
            break
        idx = live
        m = mode[idx]
        l = lit[idx]
        nk = num_ok[idx]
        nc = no_close[idx]
        c = tab.codes[idx, j]
        p = tab.props[idx, j]
        ws = (p & _P_WS) != 0
        todo = np.ones(idx.size, bool)

        def pop_rows(rows):
            """Pop one level for compact positions ``rows`` (top already
            verified) -> AFTER_VALUE with default flags."""
            g = idx[rows]
            rel[g] -= 1
            minrel[g] = np.minimum(minrel[g], rel[g])
            m[rows] = _M_A
            l[rows] = 0
            nk[rows] = False
            nc[rows] = False
            if deep:
                m[rows[rel[g] < -2]] = _M_X  # below the recorded suffix

        def top_of(rows):
            """Current stack-top symbol per compact position (0 = empty)."""
            g = idx[rows]
            r = rel[g]
            t = buf[g, np.maximum(r + 2, 0)]
            return np.where(depth0 + r > 0, t, 0)

        # IN_STRING: '"' ends (key -> AFTER_KEY), '\' escapes, control
        # dies; every step is a fresh st(...) so both flags reset.
        sel = todo & (m == _M_S)
        if sel.any():
            q = sel & (c == Q)
            m[q] = np.where(l[q] == _LIT_ID["k"], _M_C, _M_A).astype(np.int8)
            l[q] = 0
            m[sel & (c == BSL)] = _M_E
            m[sel & ((p & _P_CTRL) != 0)] = _M_X
            nk[sel] = False
            nc[sel] = False
            todo[sel] = False

        # STR_ESCAPE: 'u' starts a hex run, escapables return to the string.
        sel = todo & (m == _M_E)
        if sel.any():
            u = sel & (c == LU)
            l[u] = _ESC_U_NEXT[l[u]]
            m[u] = _M_U
            m[sel & ~u & ((p & _P_ESC) != 0)] = _M_S
            m[sel & ~u & ((p & _P_ESC) == 0)] = _M_X
            nk[sel] = False
            nc[sel] = False
            todo[sel] = False

        # STR_HEX: consume one pending digit; non-hex dies.
        sel = todo & (m == _M_U)
        if sel.any():
            hx = sel & ((p & _P_HEX) != 0)
            stay = _HEX_STAY[l[hx]]
            nxt = _HEX_NEXT[l[hx]]
            m[hx] = np.where(stay, _M_U, _M_S).astype(np.int8)
            l[hx] = nxt
            m[sel & ~hx] = _M_X
            nk[sel] = False
            nc[sel] = False
            todo[sel] = False

        # LITERAL: exact-char chain; completion -> AFTER_VALUE.
        sel = todo & (m == _M_L)
        if sel.any():
            exp = _LIT_EXPECT[l]
            hit = sel & (c == exp) & (exp != 0)  # exp 0: empty lit, no match
            nxt = _LIT_NEXT[l[hit]]
            m[hit] = np.where(nxt < 0, _M_A, _M_L).astype(np.int8)
            l[hit] = np.maximum(nxt, 0)
            m[sel & ~hit] = _M_X
            nk[sel] = False
            nc[sel] = False
            todo[sel] = False

        # IN_NUMBER: phase grammar; a delimiter on a terminable number
        # re-dispatches through AFTER_VALUE *in this same column* (the rows
        # stay on the todo list and the AFTER_VALUE block below picks them
        # up), matching advance()'s recursive re-interpretation.
        sel = todo & (m == _M_N)
        if sel.any():
            # Every legacy num() construction leaves no_close at its default.
            nc[sel] = False
            isdig = (p & (_P_DIG19 | _P_ZERO | _P_UDIG)) != 0
            s_sign = sel & (l == _PH_SIGN)
            if s_sign.any():
                z = s_sign & (c == ZERO)
                l[z] = _PH_ZERO
                nk[z] = True
                d = s_sign & isdig & (c != ZERO)
                l[d] = _PH_INT
                nk[d] = True
                m[s_sign & ~isdig] = _M_X
                todo[s_sign] = False
            core = sel & _PH_CORE[l] & todo
            if core.any():
                m[core & isdig & (l == _PH_ZERO)] = _M_X  # "01" is not JSON
                nk[core & isdig & (l != _PH_ZERO)] = True
                dot = core & (c == DOT) & _PH_DOT_OK[l]
                l[dot] = _PH_FRAC0
                nk[dot] = False
                ee = core & ((c == LE) | (c == UE)) & _PH_EXP_OK[l]
                l[ee] = _PH_EXP0
                nk[ee] = False
                delim = core & ~isdig & ~dot & ~ee
                m[delim & ~nk] = _M_X
                redo = delim & nk
                m[redo] = _M_A
                l[redo] = 0
                nk[redo] = False
                nc[redo] = False
                todo[core] = False
                todo[redo] = True  # AFTER_VALUE reprocesses this char below
            f0 = sel & (l == _PH_FRAC0) & todo
            if f0.any():
                d = f0 & isdig
                l[d] = _PH_FRAC
                nk[d] = True
                m[f0 & ~isdig] = _M_X
                todo[f0] = False
            e0 = sel & (l == _PH_EXP0) & todo
            if e0.any():
                pm = e0 & ((c == PLUS) | (c == MINUS))
                l[pm] = _PH_EXP1
                nk[pm] = False
                d = e0 & isdig
                l[d] = _PH_EXP
                nk[d] = True
                m[e0 & ~isdig & ~pm] = _M_X
                todo[e0] = False
            e1 = sel & (l == _PH_EXP1) & todo
            if e1.any():
                d = e1 & isdig
                l[d] = _PH_EXP
                nk[d] = True
                m[e1 & ~isdig] = _M_X
                todo[e1] = False

        # AFTER_VALUE: WS stays (state untouched), ',' reopens (no_close
        # set), closers pop.
        sel = todo & (m == _M_A)
        if sel.any():
            todo[sel & ws] = False
            sel &= ~ws
            if sel.any():
                rows = np.flatnonzero(sel)
                top = top_of(rows)
                ch = c[rows]
                comma = (ch == COMMA) & (top != 0)
                cr = rows[comma]
                m[cr] = np.where(top[comma] == 1, _M_K, _M_V).astype(np.int8)
                l[cr] = 0
                nk[cr] = False
                nc[cr] = True
                popm = ((ch == RB) & (top == 1)) | ((ch == RK) & (top == 2))
                pop_rows(rows[popm])
                m[rows[~comma & ~popm]] = _M_X
                todo[sel] = False

        # VALUE: value starts, '[' / '{' pushes, ']' closes an empty array.
        sel = todo & (m == _M_V)
        if sel.any():
            todo[sel & ws] = False
            sel &= ~ws
            if sel.any():
                q = sel & (c == Q)
                m[q] = _M_S
                l[q] = 0
                nk[q] = False
                nc[q] = False
                mi = sel & (c == MINUS)
                m[mi] = _M_N
                l[mi] = _PH_SIGN
                nk[mi] = False
                nc[mi] = False
                z = sel & (c == ZERO)
                m[z] = _M_N
                l[z] = _PH_ZERO
                nk[z] = True
                nc[z] = False
                d = sel & ((p & _P_DIG19) != 0)
                m[d] = _M_N
                l[d] = _PH_INT
                nk[d] = True
                nc[d] = False
                handled = q | mi | z | d
                for code, tail in ((LT, "rue"), (LF, "alse"), (LN, "ull")):
                    li = sel & (c == code)
                    m[li] = _M_L
                    l[li] = _LIT_ID[tail]
                    nk[li] = False
                    nc[li] = False
                    handled |= li
                for code, tgt, sym in ((LB, _M_K, 1), (LK, _M_V, 2)):
                    op = sel & (c == code)
                    if op.any():
                        rows = np.flatnonzero(op)
                        g = idx[rows]
                        buf[g, rel[g] + 3] = sym
                        rel[g] += 1
                        m[rows] = tgt
                        l[rows] = 0
                        nk[rows] = False
                        nc[rows] = False
                    handled |= op
                cl = sel & (c == RK) & ~nc
                if cl.any():
                    rows = np.flatnonzero(cl)
                    okt = top_of(rows) == 2
                    pop_rows(rows[okt])
                    m[rows[~okt]] = _M_X
                handled |= cl
                m[sel & ~handled] = _M_X  # incl. ']' right after a comma
                todo[sel] = False

        # EXPECT_KEY: key string or '}' (unless just after a comma).
        sel = todo & (m == _M_K)
        if sel.any():
            todo[sel & ws] = False
            sel &= ~ws
            if sel.any():
                q = sel & (c == Q)
                m[q] = _M_S
                l[q] = _LIT_ID["k"]
                nk[q] = False
                nc[q] = False
                cl = sel & (c == RB) & ~nc
                if cl.any():
                    rows = np.flatnonzero(cl)
                    okt = top_of(rows) == 1
                    pop_rows(rows[okt])
                    m[rows[~okt]] = _M_X
                m[sel & ~q & ~cl] = _M_X  # incl. '}' right after a comma
                todo[sel] = False

        # AFTER_KEY: only ':'.
        sel = todo & (m == _M_C)
        if sel.any():
            todo[sel & ws] = False
            sel &= ~ws
            if sel.any():
                col = sel & (c == COLON)
                m[col] = _M_V
                l[col] = 0
                nk[col] = False
                nc[col] = False
                m[sel & ~col] = _M_X
                todo[sel] = False

        mode[idx] = m
        lit[idx] = l
        num_ok[idx] = nk
        no_close[idx] = nc

    return mode, lit, rel, minrel, num_ok, no_close, buf


def _vector_masks_enabled() -> bool:
    """DYN_CONSTRAINT_VECTOR_MASKS=0 falls back to the pure-Python builder
    (escape hatch; outputs are bitwise identical, only build time differs)."""
    return os.environ.get("DYN_CONSTRAINT_VECTOR_MASKS", "1") != "0"


class TokenMaskCache:
    """Per-tokenizer vocab masks keyed by machine summary."""

    def __init__(self, tokenizer, vocab_size: int, eos_ids: tuple[int, ...]) -> None:
        import threading

        self.vocab_size = vocab_size
        self.eos_ids = tuple(eos_ids)
        self._pieces: list[str] | None = None
        self._tok = tokenizer
        self._masks: dict[tuple, np.ndarray] = {}
        # Per-summary transition table built alongside each mask: for every
        # admitted piece, a small descriptor of the machine's state change
        # (summary -> (desc_id i32[vocab], [descriptor, ...])). Lets the
        # overlapped engine reconstruct the EXACT successor state of any
        # allowed token without re-walking its piece — the one-step-lookahead
        # mask precompute groups candidate tokens by descriptor.
        self._descs: dict[tuple, tuple[np.ndarray, list]] = {}
        self._close_ids: dict[str, int | None] = {}
        # Cache-lookup counters (mirrored to the metrics plane as
        # dynamo_engine_constraint_mask_cache_{hits,misses}_total): a miss is
        # a lookup the cache could not answer warm — a cold mask build, or a
        # peek/lookahead that had to decline (the overlapped engine then
        # barriers with reason constraint_miss and the sync fallback warms
        # the summary).
        self.hits = 0
        self.misses = 0
        # Per-tokenizer piece descriptor arrays for the vectorized builder,
        # computed lazily on the first cold build.
        self._table: _VocabTable | None = None
        # Wall-clock seconds of each cold mask build since the last drain;
        # the metrics plane observes these into the
        # dynamo_engine_constraint_mask_build_seconds histogram.
        self._build_seconds: list[float] = []
        # Serializes the seconds-long cold builds (piece table, per-summary
        # vocab walks): the warm-up thread and a racing request must not
        # duplicate them, and the second comer blocks instead of recomputing.
        self._build_lock = threading.Lock()

    def _ensure_pieces(self) -> list[str]:
        if self._pieces is None:
            with self._build_lock:
                if self._pieces is None:
                    dec = self._tok.decode
                    self._pieces = [
                        dec([t], skip_special_tokens=False) for t in range(self.vocab_size)
                    ]
        return self._pieces

    def mask_for(self, state: MachineState, *, force_close: bool = False,
                 remaining: int | None = None) -> np.ndarray:
        """bool[vocab]: tokens that keep the output a valid JSON prefix.

        ``force_close``: remaining budget is nearly exhausted — restrict to
        tokens that strictly make progress toward closing (closers, the
        string terminator, escapes' completion), so the response parses
        when it finishes.

        ``remaining``: token budget left — pieces whose resulting state
        cannot be closed within it are excluded (a single BPE token like
        '[[[[' opens four levels; admitting it just above the force-close
        threshold would make the close unaffordable and truncate mid-JSON).
        """
        if force_close:
            return self._force_close_mask(state)
        allowed, close_rel = self._base_mask(state)
        if remaining is not None:
            allowed = allowed & (close_rel + state.depth <= max(remaining - 1, 1))
            if not allowed.any():
                return self._force_close_mask(state)
        return self._finalize(allowed, state)

    def _base_mask(self, state: MachineState) -> tuple[np.ndarray, np.ndarray]:
        """(allowed bool[vocab], budget_to_close after each piece i16[vocab])
        for a machine summary. Sound under the bounded summary: a piece
        whose simulation dips below the recorded stack suffix (min depth <
        depth - 3) is conservatively disallowed — its verdict would depend
        on symbols the cache key doesn't carry."""
        key = state.summary()
        cached = self._masks.get(key)
        if cached is not None:
            self.hits += 1
            return cached
        pieces = self._ensure_pieces()
        with self._build_lock:
            cached = self._masks.get(key)  # built while we waited?
            if cached is not None:
                self.hits += 1
                return cached
            self.misses += 1
            return self._build_mask(state, key, pieces)

    def _build_mask(self, state: MachineState, key: tuple, pieces) -> tuple[np.ndarray, np.ndarray]:
        """Cold build (caller holds ``_build_lock``): dispatch to the
        vectorized builder, timing the build for the metrics histogram."""
        t0 = time.perf_counter()
        if _vector_masks_enabled():
            out = self._build_mask_vectorized(state, key, pieces)
        else:
            out = self._build_mask_python(state, key, pieces)
        self._build_seconds.append(time.perf_counter() - t0)
        return out

    def drain_build_seconds(self) -> list[float]:
        """Cold-build durations since the last drain (metrics scrape path)."""
        out, self._build_seconds = self._build_seconds, []
        return out

    def _vocab_table(self) -> _VocabTable:
        # Only reached from _build_mask (under _build_lock) AFTER _base_mask
        # materialized the pieces, so _ensure_pieces returns without trying
        # to re-take the (non-reentrant) lock.
        if self._table is None:
            self._table = _VocabTable(self._ensure_pieces())
        return self._table

    def _build_mask_vectorized(
        self, state: MachineState, key: tuple, pieces
    ) -> tuple[np.ndarray, np.ndarray]:
        """Column-wise numpy simulation of the whole vocab; outputs are
        bitwise identical to :meth:`_build_mask_python` (the parity suite in
        tests/test_constrained.py checks masks, close budgets, descriptor
        ids AND decoded descriptors across a summary corpus)."""
        tab = self._vocab_table()
        mode, lit, rel, minrel, num_ok, no_close, buf = _simulate_vocab(state, tab)
        # Admission: the simulation already REJECTs every piece the Python
        # machine rejects, and (deep states) every piece dipping below the
        # recorded stack suffix — exactly the soundness floor. Empty pieces
        # and lossy-decode pieces mirror the Python builder's skips.
        allowed = (mode != _M_X) & ~tab.empty
        if state.mode in (IN_STRING, STR_ESCAPE, STR_HEX, VALUE, EXPECT_KEY):
            allowed &= ~tab.has_replacement
        # budget_to_close(ns) - state.depth, computed in the same override
        # order as the scalar method (mode extra -> hex -> key-string ->
        # unterminable number -> post-comma EXPECT_KEY).
        extra = _MODE_EXTRA[mode].astype(np.int32)
        isL = mode == _M_L
        extra[isL] = _LIT_LEN[lit[isL]]
        isU = mode == _M_U
        extra[isU] = _LIT_HCOUNT[lit[isU]] + 1
        keystr = ((mode == _M_S) | (mode == _M_E) | (mode == _M_U)) & _LIT_ISKEY[lit]
        extra[keystr] += 2
        badnum = (mode == _M_N) & ~num_ok
        extra[badnum] = 1
        kc = (mode == _M_K) & no_close
        extra[kc] = 5
        close = np.minimum(rel.astype(np.int32) + extra + 1, 2**14)
        close_after = np.where(allowed, close, 0).astype(np.int16)
        # Transition descriptors: dedup admitted pieces on fixed-width byte
        # records of (mode, literal, min rel depth, flags, stack slice), with
        # ids assigned in FIRST-OCCURRENCE (= token) order so they match the
        # Python builder's incremental numbering exactly.
        desc_ids = np.full(self.vocab_size, -1, np.int32)
        descs: list[tuple] = []
        tok = np.flatnonzero(allowed)
        if tok.size:
            width = buf.shape[1]
            slot = np.arange(width)[None, :]
            mb = buf[tok]
            keep = (slot >= minrel[tok, None] + 3) & (slot < rel[tok, None] + 3)
            mb = np.where(keep, mb, 0)
            rec = np.empty((tok.size, 5 + width), np.uint8)
            rec[:, 0] = mode[tok]
            rec[:, 1] = lit[tok]
            rec[:, 2] = (minrel[tok] + 3).astype(np.uint8)
            rec[:, 3] = num_ok[tok]
            rec[:, 4] = no_close[tok]
            rec[:, 5:] = mb
            rec = np.ascontiguousarray(rec)
            flat = rec.view(np.dtype((np.void, rec.shape[1]))).ravel()
            _, first_idx, inv = np.unique(flat, return_index=True, return_inverse=True)
            order = np.argsort(first_idx, kind="stable")
            rank = np.empty(order.size, np.int32)
            rank[order] = np.arange(order.size, dtype=np.int32)
            desc_ids[tok] = rank[inv]
            for g in range(order.size):
                r = rec[first_idx[order[g]]]
                body = r[5:]
                pushed = tuple(_SYM_STR[int(s)] for s in body[body != 0])
                descs.append((
                    _MODE_STR[int(r[0])], _LIT_STRINGS[int(r[1])],
                    int(r[2]) - 3, pushed, bool(r[3]), bool(r[4]),
                ))
        self._masks[key] = (allowed, close_after)
        self._descs[key] = (desc_ids, descs)
        return allowed, close_after

    def _build_mask_python(self, state: MachineState, key: tuple, pieces) -> tuple[np.ndarray, np.ndarray]:
        allowed = np.zeros(self.vocab_size, bool)
        close_after = np.zeros(self.vocab_size, np.int16)
        desc_ids = np.full(self.vocab_size, -1, np.int32)
        descs: list[tuple] = []
        desc_index: dict[tuple, int] = {}
        # Soundness floor: with depth <= 3 the summary records the WHOLE
        # stack, so the machine's own verdict is exact. Deeper states may
        # only admit pieces whose every stack consult (pop / ',' / closer
        # match, each reading the top at sim depth s = index s-1) touches a
        # recorded symbol: indices >= depth-3, i.e. sim depth stays
        # >= depth-2 throughout.
        floor = 0 if state.depth <= 3 else state.depth - 2
        for t, piece in enumerate(pieces):
            if not piece:
                continue
            if "�" in piece and state.mode in (IN_STRING, STR_ESCAPE, STR_HEX, VALUE, EXPECT_KEY):
                continue  # lossy single-token decode: keep strings clean
            ns, min_depth = advance_text_tracked(state, piece)
            if ns.mode != REJECT and min_depth >= floor:
                allowed[t] = True
                # Depth-RELATIVE: states deeper than the summary cap share
                # this entry; the caller adds its own depth back.
                close_after[t] = min(self.budget_to_close(ns) - state.depth, 2**14)
                # Transition descriptor, depth-relative like close_after.
                # The floor guarantees the simulation only consulted
                # recorded stack symbols, so any state sharing this summary
                # reaches the same (rel, pushed) — its successor stack is
                # stack[: depth + rel] + pushed exactly.
                d = (ns.mode, ns.literal, min_depth - state.depth,
                     ns.stack[min_depth:], ns.num_ok, ns.no_close)
                g = desc_index.get(d)
                if g is None:
                    g = desc_index[d] = len(descs)
                    descs.append(d)
                desc_ids[t] = g
        self._masks[key] = (allowed, close_after)
        self._descs[key] = (desc_ids, descs)
        return allowed, close_after

    def _finalize(self, base: np.ndarray, state: MachineState) -> np.ndarray:
        out = base.copy()
        complete = state.complete()
        for e in self.eos_ids:
            if 0 <= e < self.vocab_size:
                out[e] = complete  # EOS exactly when the JSON is complete
        return out

    def _closer_token(self, piece: str) -> int | None:
        if piece not in self._close_ids:
            pieces = self._ensure_pieces()
            self._close_ids[piece] = next(
                (t for t, p in enumerate(pieces) if p == piece), None
            )
        return self._close_ids[piece]

    def _force_close_mask(self, state: MachineState) -> np.ndarray:
        out = np.zeros(self.vocab_size, bool)
        if state.complete():
            for e in self.eos_ids:
                if 0 <= e < self.vocab_size:
                    out[e] = True
            if not out.any():
                # No EOS in this vocab: nothing to force — the ENGINE ends
                # completed json_mode sequences itself (a zero-allowed mask
                # would send the sampler into arbitrary tokens).
                return self.mask_for(state)
            return out
        want: str | None = None
        if state.mode in (IN_STRING, STR_ESCAPE, STR_HEX):
            # IN_STRING: terminate; STR_ESCAPE: finish the escape minimally;
            # STR_HEX: feed hex digits until the 4 are consumed.
            want = {IN_STRING: '"', STR_ESCAPE: "n", STR_HEX: "0"}[state.mode]
        elif state.mode == AFTER_KEY:
            want = ":"
        elif state.mode == VALUE:
            # Close an empty array where legal; otherwise produce a value.
            if state.stack and state.stack[-1] == "[" and not state.no_close:
                want = "]"
            else:
                want = "0"
        elif state.mode == LITERAL:
            want = state.literal[0] if state.literal else None
        elif state.mode == EXPECT_KEY:
            want = '"' if state.no_close else "}"
        elif state.mode == IN_NUMBER and not state.num_ok:
            want = "0"
        elif state.mode in (AFTER_VALUE, IN_NUMBER) and state.stack:
            want = _CLOSERS[state.stack[-1]]
        if want is not None:
            tid = self._closer_token(want)
            if tid is not None:
                out[tid] = True
        if not out.any():
            # No single-char closing token in this vocab: fall back to the
            # unconstrained-valid mask rather than deadlocking the sampler.
            return self.mask_for(state)
        return out

    def budget_to_close(self, state: MachineState) -> int:
        """Upper bound on tokens needed to reach a complete JSON value by
        single-char force-close steps."""
        extra = {IN_STRING: 1, STR_ESCAPE: 2, AFTER_KEY: 2, VALUE: 1,
                 EXPECT_KEY: 1, LITERAL: len(state.literal)}.get(state.mode, 0)
        if state.mode == STR_HEX:
            extra = state.literal.count("h") + 1  # pending hex digits + '"'
        if state.mode in (IN_STRING, STR_ESCAPE, STR_HEX) and state.literal.startswith("k"):
            extra += 2  # key string: the closing '"' lands in AFTER_KEY, so
            #             ':' + a one-char value must still fit
        if state.mode == IN_NUMBER and not state.num_ok:
            extra = 1  # one digit terminates any incomplete number phase
        if state.mode == EXPECT_KEY and state.no_close:
            extra = 5  # '"' + '"' + ':' + value before the '}' can come
        return state.depth + extra + 1  # +1 for EOS

    # ---- one-step lookahead (overlapped engine) ------------------------
    #
    # The overlapped pipeline composes step N+1 while step N's token is
    # still on device. These peek-only entry points let the engine (a)
    # recompute the mask the in-flight step samples under and (b) group
    # every candidate token it can emit by exact successor state — WITHOUT
    # ever paying a cold O(vocab) build on the dispatch path. A cold
    # summary returns None; the engine barriers (reason constraint_miss),
    # the sync fallback builds the mask, and the next step chains warm.

    def peek_mask(self, state: MachineState, remaining: int) -> np.ndarray | None:
        """:meth:`JsonConstraint.mask` replicated warm-only: None when the
        state's summary has no cached base mask."""
        if state.summary() not in self._masks:
            self.misses += 1
            return None
        force = remaining <= self.budget_to_close(state) + 2
        return self.mask_for(state, force_close=force, remaining=remaining)

    def lookahead_groups(
        self, state: MachineState, allowed: np.ndarray, cap: int
    ) -> tuple[list[MachineState], np.ndarray] | None:
        """Group the candidate next tokens by exact successor state.

        ``allowed`` is the mask the in-flight step samples under. Returns
        ``(states, group_of)`` with ``group_of`` int32[vocab]: candidate
        tokens map to an index into ``states``, everything else (including
        EOS, whose sample the engine discards at harvest) maps to -1.
        Returns None — the caller barriers — when the answer would need a
        cold build or more than ``cap`` distinct successor states.
        """
        if not allowed.any():
            # Pathological (closer-less vocab fallback masks): the sampled
            # token is unconstrained, so no finite group table covers it.
            self.misses += 1
            return None
        cands = np.flatnonzero(allowed)
        if self.eos_ids:
            cands = cands[~np.isin(cands, np.asarray(self.eos_ids))]
        group_of = np.full(self.vocab_size, -1, np.int32)
        states: list[MachineState] = []
        if cands.size == 0:
            return states, group_of  # EOS-only: the row finishes at harvest
        if cands.size <= cap:
            # Few candidates: advance each piece directly (exact, cheap).
            pieces = self._ensure_pieces()
            index: dict[MachineState, int] = {}
            for t in cands.tolist():
                ns = advance_text(state, pieces[t])
                g = index.get(ns)
                if g is None:
                    if len(states) >= cap:
                        self.misses += 1
                        return None
                    g = index[ns] = len(states)
                    states.append(ns)
                group_of[t] = g
            self.hits += 1
            return states, group_of
        # Wide masks (e.g. IN_STRING admits most of the vocab): use the
        # transition table recorded when the summary's mask was built.
        table = self._descs.get(state.summary())
        if table is None:
            self.misses += 1
            return None
        desc_ids, descs = table
        ids = desc_ids[cands]
        if (ids < 0).any():
            # An allowed token outside the recorded table (force-close /
            # clamp edge): decline rather than guess.
            self.misses += 1
            return None
        uniq, inv = np.unique(ids, return_inverse=True)
        if uniq.size > cap:
            self.misses += 1
            return None
        for d in uniq.tolist():
            mode, literal, rel, pushed, num_ok, no_close = descs[d]
            states.append(MachineState(
                mode, literal, state.stack[: state.depth + rel] + pushed,
                num_ok, no_close,
            ))
        group_of[cands] = inv.astype(np.int32)
        self.hits += 1
        return states, group_of


@dataclasses.dataclass
class JsonConstraint:
    """Per-request constrained-decoding state (lives on the Sequence)."""

    cache: TokenMaskCache
    state: MachineState = dataclasses.field(default_factory=MachineState)

    def mask(self, remaining_tokens: int) -> np.ndarray:
        force = remaining_tokens <= self.cache.budget_to_close(self.state) + 2
        return self.cache.mask_for(
            self.state, force_close=force, remaining=remaining_tokens
        )

    def accept(self, token_id: int) -> None:
        piece = self.cache._ensure_pieces()[token_id] if token_id < self.cache.vocab_size else ""
        if token_id in self.cache.eos_ids:
            return
        self.state = advance_text(self.state, piece)
