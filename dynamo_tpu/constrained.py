"""Constrained decoding: OpenAI ``response_format: {"type": "json_object"}``.

Guarantees every generated token keeps the output a valid JSON *prefix*,
and (unlike OpenAI's "may truncate at max_tokens" caveat) force-closes
open structures when the remaining token budget runs low, so finished
responses parse. The reference has no counterpart (vLLM-level feature the
wrapped engines provide; first-party here).

Design, sized for a 128k-vocab TPU serving path:

- **Char-level JSON pushdown machine** (:class:`JsonMachine`): mode +
  container stack; accepts exactly the prefixes of JSON values (strings
  with escapes, numbers, literals, arrays, objects).
- **Token masks cached by machine summary** (:class:`TokenMaskCache`):
  the set of allowed next TOKENS depends only on a bounded summary of the
  machine (mode, pending literal, top few stack symbols) — a few dozen
  distinct summaries in practice. Computing a mask walks every vocab
  piece through the machine once per NEW summary (~O(vocab) chars) and
  is cached forever after; steady-state per-step cost is a dict lookup.
  Pieces that would close deeper than the summary records are
  conservatively disallowed (the output stays valid JSON; the model just
  closes one level per token in >3-deep nests).
- The engine applies the mask on-device (logits + ``where(mask, x,
  -inf)``) on the single-step sync path, and advances the machine on the
  host with each accepted token (`engine/core.py`).

Token text comes from ``tokenizer.decode([id])`` per piece; tokenizers
whose single-token decode is lossy (partial UTF-8 fragments render as
replacement chars) get those tokens conservatively disallowed inside
strings only when they decode to the replacement char.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Modes.
VALUE = "V"        # expecting a value start
IN_STRING = "S"    # inside a string
STR_ESCAPE = "E"   # after backslash in a string
STR_HEX = "U"      # inside \uXXXX: literal = key-marker + one 'h' per digit left
IN_NUMBER = "N"    # inside a number (last char was part of a number)
AFTER_VALUE = "A"  # a value just completed; expect , } ] or end
EXPECT_KEY = "K"   # inside an object, expecting a key string or }
AFTER_KEY = "C"    # key string done, expecting :
LITERAL = "L"      # partway through true/false/null
REJECT = "X"

_WS = " \t\n\r"
_LITERALS = {"t": "rue", "f": "alse", "n": "ull"}
# RFC 8259 string escapes: exactly these after a backslash; \u is handled
# as its own pending-hex state so it consumes exactly 4 hex digits ('\u12',
# '\uZZZZ' or a bare '\q' must not be accepted — json.loads rejects them).
_ESCAPABLE = set('"\\/bfnrt')
_HEX = set("0123456789abcdefABCDEF")


@dataclasses.dataclass(frozen=True)
class MachineState:
    mode: str = VALUE
    literal: str = ""          # remaining chars of a pending literal
    stack: tuple = ()          # container stack, innermost last: '{' / '['
    # IN_NUMBER only: the number is terminable (has digits, doesn't end in
    # '.', 'e', '+', '-' — "-" or "1e+" must not count as complete).
    num_ok: bool = False
    # VALUE/EXPECT_KEY reached via ',': an immediate closer would produce a
    # trailing comma ('[1,]' / '{"a":1,}'), which is not JSON.
    no_close: bool = False

    @property
    def depth(self) -> int:
        return len(self.stack)

    def summary(self) -> tuple:
        """Bounded cache key: masks computed from equal summaries are equal
        for every piece that closes at most len(kept stack) levels."""
        # min(depth, 4): depth <= 3 states carry their FULL stack (every
        # piece verdict is determined) and must never share a key with
        # deeper states whose 4th-from-top symbol is unrecorded.
        return (self.mode, self.literal, self.stack[-3:], min(self.depth, 4),
                self.num_ok, self.no_close)

    def complete(self) -> bool:
        """The text so far is a COMPLETE JSON value."""
        if self.depth != 0:
            return False
        return self.mode == AFTER_VALUE or (self.mode == IN_NUMBER and self.num_ok)


def advance(state: MachineState, ch: str) -> MachineState:
    """One character step; returns a REJECT-mode state on invalid input."""
    mode, lit, stack = state.mode, state.literal, state.stack

    def st(m, l="", s=stack):
        return MachineState(m, l, s)

    bad = MachineState(REJECT)
    if mode == REJECT:
        return bad
    if mode == IN_STRING:
        if ch == '"':
            # Key strings finish to AFTER_KEY; value strings to AFTER_VALUE.
            return st(AFTER_KEY if lit == "k" else AFTER_VALUE)
        if ch == "\\":
            return st(STR_ESCAPE, lit)
        # RFC 8259: control characters U+0000..U+001F must be escaped.
        return bad if ord(ch) < 0x20 else st(IN_STRING, lit)
    if mode == STR_ESCAPE:
        if ch == "u":
            return st(STR_HEX, lit + "hhhh")
        return st(IN_STRING, lit) if ch in _ESCAPABLE else bad
    if mode == STR_HEX:
        if ch not in _HEX:
            return bad
        rest = lit[:-1]  # one pending hex digit consumed
        return st(STR_HEX, rest) if rest.endswith("h") else st(IN_STRING, rest)
    if mode == LITERAL:
        if lit and ch == lit[0]:
            return st(AFTER_VALUE) if len(lit) == 1 else st(LITERAL, lit[1:])
        return bad
    if mode == IN_NUMBER:
        # Full JSON number grammar; phase rides in ``literal``:
        # sign -> (zero | int) -> [frac0 -> frac] -> [exp0 -> exp1? -> exp]
        ph = lit

        def num(phase, ok):
            return MachineState(IN_NUMBER, phase, stack, num_ok=ok)

        if ph == "sign":
            if ch == "0":
                return num("zero", True)
            return num("int", True) if ch.isdigit() else bad
        if ph in ("zero", "int", "frac", "exp"):
            if ch.isdigit():
                if ph == "zero":
                    return bad  # leading-zero rule: "01" is not JSON
                return num(ph, True)
            if ch == "." and ph in ("zero", "int"):
                return num("frac0", False)
            if ch in "eE" and ph in ("zero", "int", "frac"):
                return num("exp0", False)
            # Delimiter ends a terminable number (reinterpreted from
            # AFTER_VALUE); "-," / "1e+," are not JSON.
            return advance(st(AFTER_VALUE), ch) if state.num_ok else bad
        if ph == "frac0":
            return num("frac", True) if ch.isdigit() else bad
        if ph == "exp0":
            if ch in "+-":
                return num("exp1", False)
            return num("exp", True) if ch.isdigit() else bad
        if ph == "exp1":
            return num("exp", True) if ch.isdigit() else bad
        return bad
    if mode == VALUE:
        if ch in _WS:
            return state
        if ch == '"':
            return st(IN_STRING)
        if ch == "-":
            return MachineState(IN_NUMBER, "sign", stack, num_ok=False)
        if ch == "0":
            return MachineState(IN_NUMBER, "zero", stack, num_ok=True)
        if ch in "123456789":
            return MachineState(IN_NUMBER, "int", stack, num_ok=True)
        if ch in _LITERALS:
            return st(LITERAL, _LITERALS[ch])
        if ch == "{":
            return MachineState(EXPECT_KEY, "", stack + ("{",))
        if ch == "[":
            return MachineState(VALUE, "", stack + ("[",))
        if ch == "]" and stack and stack[-1] == "[" and not state.no_close:
            # Empty array closes straight from VALUE (but not right after a
            # comma — '[1,]' is not JSON).
            return MachineState(AFTER_VALUE, "", stack[:-1])
        return bad
    if mode == EXPECT_KEY:
        if ch in _WS:
            return state
        if ch == '"':
            return st(IN_STRING, "k")
        if ch == "}" and stack and stack[-1] == "{" and not state.no_close:
            return MachineState(AFTER_VALUE, "", stack[:-1])
        return bad
    if mode == AFTER_KEY:
        if ch in _WS:
            return state
        return st(VALUE) if ch == ":" else bad
    if mode == AFTER_VALUE:
        if ch in _WS:
            return state
        if ch == "," and stack:
            return MachineState(
                EXPECT_KEY if stack[-1] == "{" else VALUE, "", stack, no_close=True
            )
        if ch == "}" and stack and stack[-1] == "{":
            return MachineState(AFTER_VALUE, "", stack[:-1])
        if ch == "]" and stack and stack[-1] == "[":
            return MachineState(AFTER_VALUE, "", stack[:-1])
        return bad
    return bad


def advance_text(state: MachineState, text: str) -> MachineState:
    for ch in text:
        state = advance(state, ch)
        if state.mode == REJECT:
            return state
    return state


def advance_text_tracked(state: MachineState, text: str) -> tuple[MachineState, int]:
    """Like :func:`advance_text`, also returning the MINIMUM stack depth
    touched — a piece whose simulation dips below the depths the summary
    records consulted stack symbols the cache key doesn't know about, so
    its verdict must not be cached for that summary."""
    min_depth = state.depth
    for ch in text:
        state = advance(state, ch)
        if state.mode == REJECT:
            return state, min_depth
        min_depth = min(min_depth, state.depth)
    return state, min_depth


#: A piece per closing token used by force-close (one level per step).
_CLOSERS = {"{": "}", "[": "]"}


class TokenMaskCache:
    """Per-tokenizer vocab masks keyed by machine summary."""

    def __init__(self, tokenizer, vocab_size: int, eos_ids: tuple[int, ...]) -> None:
        import threading

        self.vocab_size = vocab_size
        self.eos_ids = tuple(eos_ids)
        self._pieces: list[str] | None = None
        self._tok = tokenizer
        self._masks: dict[tuple, np.ndarray] = {}
        # Per-summary transition table built alongside each mask: for every
        # admitted piece, a small descriptor of the machine's state change
        # (summary -> (desc_id i32[vocab], [descriptor, ...])). Lets the
        # overlapped engine reconstruct the EXACT successor state of any
        # allowed token without re-walking its piece — the one-step-lookahead
        # mask precompute groups candidate tokens by descriptor.
        self._descs: dict[tuple, tuple[np.ndarray, list]] = {}
        self._close_ids: dict[str, int | None] = {}
        # Cache-lookup counters (mirrored to the metrics plane as
        # dynamo_engine_constraint_mask_cache_{hits,misses}_total): a miss is
        # a lookup the cache could not answer warm — a cold mask build, or a
        # peek/lookahead that had to decline (the overlapped engine then
        # barriers with reason constraint_miss and the sync fallback warms
        # the summary).
        self.hits = 0
        self.misses = 0
        # Serializes the seconds-long cold builds (piece table, per-summary
        # vocab walks): the warm-up thread and a racing request must not
        # duplicate them, and the second comer blocks instead of recomputing.
        self._build_lock = threading.Lock()

    def _ensure_pieces(self) -> list[str]:
        if self._pieces is None:
            with self._build_lock:
                if self._pieces is None:
                    dec = self._tok.decode
                    self._pieces = [
                        dec([t], skip_special_tokens=False) for t in range(self.vocab_size)
                    ]
        return self._pieces

    def mask_for(self, state: MachineState, *, force_close: bool = False,
                 remaining: int | None = None) -> np.ndarray:
        """bool[vocab]: tokens that keep the output a valid JSON prefix.

        ``force_close``: remaining budget is nearly exhausted — restrict to
        tokens that strictly make progress toward closing (closers, the
        string terminator, escapes' completion), so the response parses
        when it finishes.

        ``remaining``: token budget left — pieces whose resulting state
        cannot be closed within it are excluded (a single BPE token like
        '[[[[' opens four levels; admitting it just above the force-close
        threshold would make the close unaffordable and truncate mid-JSON).
        """
        if force_close:
            return self._force_close_mask(state)
        allowed, close_rel = self._base_mask(state)
        if remaining is not None:
            allowed = allowed & (close_rel + state.depth <= max(remaining - 1, 1))
            if not allowed.any():
                return self._force_close_mask(state)
        return self._finalize(allowed, state)

    def _base_mask(self, state: MachineState) -> tuple[np.ndarray, np.ndarray]:
        """(allowed bool[vocab], budget_to_close after each piece i16[vocab])
        for a machine summary. Sound under the bounded summary: a piece
        whose simulation dips below the recorded stack suffix (min depth <
        depth - 3) is conservatively disallowed — its verdict would depend
        on symbols the cache key doesn't carry."""
        key = state.summary()
        cached = self._masks.get(key)
        if cached is not None:
            self.hits += 1
            return cached
        pieces = self._ensure_pieces()
        with self._build_lock:
            cached = self._masks.get(key)  # built while we waited?
            if cached is not None:
                self.hits += 1
                return cached
            self.misses += 1
            return self._build_mask(state, key, pieces)

    def _build_mask(self, state: MachineState, key: tuple, pieces) -> tuple[np.ndarray, np.ndarray]:
        allowed = np.zeros(self.vocab_size, bool)
        close_after = np.zeros(self.vocab_size, np.int16)
        desc_ids = np.full(self.vocab_size, -1, np.int32)
        descs: list[tuple] = []
        desc_index: dict[tuple, int] = {}
        # Soundness floor: with depth <= 3 the summary records the WHOLE
        # stack, so the machine's own verdict is exact. Deeper states may
        # only admit pieces whose every stack consult (pop / ',' / closer
        # match, each reading the top at sim depth s = index s-1) touches a
        # recorded symbol: indices >= depth-3, i.e. sim depth stays
        # >= depth-2 throughout.
        floor = 0 if state.depth <= 3 else state.depth - 2
        for t, piece in enumerate(pieces):
            if not piece:
                continue
            if "�" in piece and state.mode in (IN_STRING, STR_ESCAPE, STR_HEX, VALUE, EXPECT_KEY):
                continue  # lossy single-token decode: keep strings clean
            ns, min_depth = advance_text_tracked(state, piece)
            if ns.mode != REJECT and min_depth >= floor:
                allowed[t] = True
                # Depth-RELATIVE: states deeper than the summary cap share
                # this entry; the caller adds its own depth back.
                close_after[t] = min(self.budget_to_close(ns) - state.depth, 2**14)
                # Transition descriptor, depth-relative like close_after.
                # The floor guarantees the simulation only consulted
                # recorded stack symbols, so any state sharing this summary
                # reaches the same (rel, pushed) — its successor stack is
                # stack[: depth + rel] + pushed exactly.
                d = (ns.mode, ns.literal, min_depth - state.depth,
                     ns.stack[min_depth:], ns.num_ok, ns.no_close)
                g = desc_index.get(d)
                if g is None:
                    g = desc_index[d] = len(descs)
                    descs.append(d)
                desc_ids[t] = g
        self._masks[key] = (allowed, close_after)
        self._descs[key] = (desc_ids, descs)
        return allowed, close_after

    def _finalize(self, base: np.ndarray, state: MachineState) -> np.ndarray:
        out = base.copy()
        complete = state.complete()
        for e in self.eos_ids:
            if 0 <= e < self.vocab_size:
                out[e] = complete  # EOS exactly when the JSON is complete
        return out

    def _closer_token(self, piece: str) -> int | None:
        if piece not in self._close_ids:
            pieces = self._ensure_pieces()
            self._close_ids[piece] = next(
                (t for t, p in enumerate(pieces) if p == piece), None
            )
        return self._close_ids[piece]

    def _force_close_mask(self, state: MachineState) -> np.ndarray:
        out = np.zeros(self.vocab_size, bool)
        if state.complete():
            for e in self.eos_ids:
                if 0 <= e < self.vocab_size:
                    out[e] = True
            if not out.any():
                # No EOS in this vocab: nothing to force — the ENGINE ends
                # completed json_mode sequences itself (a zero-allowed mask
                # would send the sampler into arbitrary tokens).
                return self.mask_for(state)
            return out
        want: str | None = None
        if state.mode in (IN_STRING, STR_ESCAPE, STR_HEX):
            # IN_STRING: terminate; STR_ESCAPE: finish the escape minimally;
            # STR_HEX: feed hex digits until the 4 are consumed.
            want = {IN_STRING: '"', STR_ESCAPE: "n", STR_HEX: "0"}[state.mode]
        elif state.mode == AFTER_KEY:
            want = ":"
        elif state.mode == VALUE:
            # Close an empty array where legal; otherwise produce a value.
            if state.stack and state.stack[-1] == "[" and not state.no_close:
                want = "]"
            else:
                want = "0"
        elif state.mode == LITERAL:
            want = state.literal[0] if state.literal else None
        elif state.mode == EXPECT_KEY:
            want = '"' if state.no_close else "}"
        elif state.mode == IN_NUMBER and not state.num_ok:
            want = "0"
        elif state.mode in (AFTER_VALUE, IN_NUMBER) and state.stack:
            want = _CLOSERS[state.stack[-1]]
        if want is not None:
            tid = self._closer_token(want)
            if tid is not None:
                out[tid] = True
        if not out.any():
            # No single-char closing token in this vocab: fall back to the
            # unconstrained-valid mask rather than deadlocking the sampler.
            return self.mask_for(state)
        return out

    def budget_to_close(self, state: MachineState) -> int:
        """Upper bound on tokens needed to reach a complete JSON value by
        single-char force-close steps."""
        extra = {IN_STRING: 1, STR_ESCAPE: 2, AFTER_KEY: 2, VALUE: 1,
                 EXPECT_KEY: 1, LITERAL: len(state.literal)}.get(state.mode, 0)
        if state.mode == STR_HEX:
            extra = state.literal.count("h") + 1  # pending hex digits + '"'
        if state.mode in (IN_STRING, STR_ESCAPE, STR_HEX) and state.literal.startswith("k"):
            extra += 2  # key string: the closing '"' lands in AFTER_KEY, so
            #             ':' + a one-char value must still fit
        if state.mode == IN_NUMBER and not state.num_ok:
            extra = 1  # one digit terminates any incomplete number phase
        if state.mode == EXPECT_KEY and state.no_close:
            extra = 5  # '"' + '"' + ':' + value before the '}' can come
        return state.depth + extra + 1  # +1 for EOS

    # ---- one-step lookahead (overlapped engine) ------------------------
    #
    # The overlapped pipeline composes step N+1 while step N's token is
    # still on device. These peek-only entry points let the engine (a)
    # recompute the mask the in-flight step samples under and (b) group
    # every candidate token it can emit by exact successor state — WITHOUT
    # ever paying a cold O(vocab) build on the dispatch path. A cold
    # summary returns None; the engine barriers (reason constraint_miss),
    # the sync fallback builds the mask, and the next step chains warm.

    def peek_mask(self, state: MachineState, remaining: int) -> np.ndarray | None:
        """:meth:`JsonConstraint.mask` replicated warm-only: None when the
        state's summary has no cached base mask."""
        if state.summary() not in self._masks:
            self.misses += 1
            return None
        force = remaining <= self.budget_to_close(state) + 2
        return self.mask_for(state, force_close=force, remaining=remaining)

    def lookahead_groups(
        self, state: MachineState, allowed: np.ndarray, cap: int
    ) -> tuple[list[MachineState], np.ndarray] | None:
        """Group the candidate next tokens by exact successor state.

        ``allowed`` is the mask the in-flight step samples under. Returns
        ``(states, group_of)`` with ``group_of`` int32[vocab]: candidate
        tokens map to an index into ``states``, everything else (including
        EOS, whose sample the engine discards at harvest) maps to -1.
        Returns None — the caller barriers — when the answer would need a
        cold build or more than ``cap`` distinct successor states.
        """
        if not allowed.any():
            # Pathological (closer-less vocab fallback masks): the sampled
            # token is unconstrained, so no finite group table covers it.
            self.misses += 1
            return None
        cands = np.flatnonzero(allowed)
        if self.eos_ids:
            cands = cands[~np.isin(cands, np.asarray(self.eos_ids))]
        group_of = np.full(self.vocab_size, -1, np.int32)
        states: list[MachineState] = []
        if cands.size == 0:
            return states, group_of  # EOS-only: the row finishes at harvest
        if cands.size <= cap:
            # Few candidates: advance each piece directly (exact, cheap).
            pieces = self._ensure_pieces()
            index: dict[MachineState, int] = {}
            for t in cands.tolist():
                ns = advance_text(state, pieces[t])
                g = index.get(ns)
                if g is None:
                    if len(states) >= cap:
                        self.misses += 1
                        return None
                    g = index[ns] = len(states)
                    states.append(ns)
                group_of[t] = g
            self.hits += 1
            return states, group_of
        # Wide masks (e.g. IN_STRING admits most of the vocab): use the
        # transition table recorded when the summary's mask was built.
        table = self._descs.get(state.summary())
        if table is None:
            self.misses += 1
            return None
        desc_ids, descs = table
        ids = desc_ids[cands]
        if (ids < 0).any():
            # An allowed token outside the recorded table (force-close /
            # clamp edge): decline rather than guess.
            self.misses += 1
            return None
        uniq, inv = np.unique(ids, return_inverse=True)
        if uniq.size > cap:
            self.misses += 1
            return None
        for d in uniq.tolist():
            mode, literal, rel, pushed, num_ok, no_close = descs[d]
            states.append(MachineState(
                mode, literal, state.stack[: state.depth + rel] + pushed,
                num_ok, no_close,
            ))
        group_of[cands] = inv.astype(np.int32)
        self.hits += 1
        return states, group_of


@dataclasses.dataclass
class JsonConstraint:
    """Per-request constrained-decoding state (lives on the Sequence)."""

    cache: TokenMaskCache
    state: MachineState = dataclasses.field(default_factory=MachineState)

    def mask(self, remaining_tokens: int) -> np.ndarray:
        force = remaining_tokens <= self.cache.budget_to_close(self.state) + 2
        return self.cache.mask_for(
            self.state, force_close=force, remaining=remaining_tokens
        )

    def accept(self, token_id: int) -> None:
        piece = self.cache._ensure_pieces()[token_id] if token_id < self.cache.vocab_size else ""
        if token_id in self.cache.eos_ids:
            return
        self.state = advance_text(self.state, piece)
