"""llmctl: inspect and edit model registrations in the deployment store.

Parity: reference `launch/llmctl` (`launch/llmctl/src/main.rs`) — list the
models frontends currently discover, statically add a registration (for an
endpoint served by something other than this framework's workers, or ahead
of its workers), and remove registrations.

Usage:
    python -m dynamo_tpu.llmctl --store tcp://HOST:PORT list
    python -m dynamo_tpu.llmctl --store ... add --name m --endpoint ns.comp.ep
    python -m dynamo_tpu.llmctl --store ... remove --name m
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from dynamo_tpu.model_card import MODEL_PREFIX, ModelDeploymentCard


async def cmd_list(store, args) -> int:
    records = await store.get_prefix(f"{MODEL_PREFIX}/")
    by_name: dict[str, list[tuple[str, ModelDeploymentCard]]] = {}
    for key, value in sorted(records.items()):
        try:
            card = ModelDeploymentCard.from_bytes(value)
        except Exception:
            print(f"?? unparseable card at {key}", file=sys.stderr)
            continue
        by_name.setdefault(card.name, []).append((key, card))
    if args.json:
        print(json.dumps({
            name: [json.loads(c.to_bytes()) for _k, c in entries]
            for name, entries in by_name.items()
        }))
        return 0
    if not by_name:
        print("(no models registered)")
        return 0
    print(f"{'MODEL':<28} {'INSTANCES':>9} {'ENDPOINT':<28} {'ROUTER':<12} {'CTX':>6}")
    for name, entries in sorted(by_name.items()):
        card = entries[0][1]
        ep = ".".join(card.endpoint)
        print(f"{name:<28} {len(entries):>9} {ep:<28} {card.router_mode:<12} {card.context_length:>6}")
    return 0


async def cmd_add(store, args) -> int:
    ns, comp, ep = args.endpoint.split(".", 2)
    card = ModelDeploymentCard(
        name=args.name,
        tokenizer=args.tokenizer,
        context_length=args.context_length,
        router_mode=args.router_mode,
        endpoint=(ns, comp, ep),
        model_type=args.model_type,
    )
    # Static registration: lease id 0, no lease binding — lives until removed.
    await store.put(card.instance_key(0), card.to_bytes())
    print(f"registered {args.name} -> {args.endpoint}")
    return 0


async def cmd_remove(store, args) -> int:
    records = await store.get_prefix(f"{MODEL_PREFIX}/{args.name}/")
    if not records:
        print(f"no registrations for {args.name!r}", file=sys.stderr)
        return 1
    for key in records:
        await store.delete(key)
    print(f"removed {len(records)} registration(s) of {args.name}")
    return 0


async def cmd_deployments(store, args) -> int:
    """List/scale/delete GraphDeployment records (the operator acts on them)."""
    from dynamo_tpu.deploy.objects import STORE_PREFIX, DeploymentPhase, GraphDeployment

    if args.dep_cmd == "list":
        records = await store.get_prefix(STORE_PREFIX)
        deps = sorted(
            (GraphDeployment.from_bytes(v) for v in records.values()), key=lambda d: d.name
        )
        if args.json:
            import dataclasses

            print(json.dumps([dataclasses.asdict(d) for d in deps]))
            return 0
        if not deps:
            print("(no deployments)")
            return 0
        print(f"{'NAME':<20} {'PHASE':<10} {'GEN':>4} {'GRAPH':<40} READY")
        for d in deps:
            ready = ",".join(f"{k}={v}" for k, v in sorted(d.services_ready.items())) or "-"
            print(f"{d.name:<20} {d.phase:<10} {d.generation:>4} {d.graph:<40} {ready}")
        return 0
    raw = await store.get(STORE_PREFIX + args.name)
    if raw is None:
        print(f"no deployment {args.name!r}", file=sys.stderr)
        return 1
    dep = GraphDeployment.from_bytes(raw)
    if args.dep_cmd == "scale":
        if dep.phase == DeploymentPhase.DELETING.value:
            print(f"{args.name} is being deleted", file=sys.stderr)
            return 1
        service, sep, n = args.replicas.partition("=")
        if not service or not sep or not n.isdigit():
            print(f"replicas must be Service=N, got {args.replicas!r}", file=sys.stderr)
            return 2
        dep.config.setdefault(service, {})["replicas"] = int(n)
        dep.generation += 1
        dep.phase = DeploymentPhase.PENDING.value
        await store.put(dep.key, dep.to_bytes())
        print(f"{args.name}: {service} -> {n} replicas (gen {dep.generation})")
    elif args.dep_cmd == "delete":
        dep.phase = DeploymentPhase.DELETING.value
        await store.put(dep.key, dep.to_bytes())
        print(f"{args.name}: deleting")
    return 0


async def _amain(args: argparse.Namespace) -> int:
    from dynamo_tpu.runtime.store_server import StoreClient

    store = StoreClient.from_url(args.store)
    try:
        handlers = {
            "list": cmd_list, "add": cmd_add, "remove": cmd_remove,
            "deployment": cmd_deployments,
        }
        return await handlers[args.cmd](store, args)
    finally:
        close = getattr(store, "close", None)
        if close:
            await close()


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description="model-registration control")
    p.add_argument("--store", required=True, help="tcp://host:port of the deployment store")
    sub = p.add_subparsers(dest="cmd", required=True)
    lst = sub.add_parser("list", help="list registered models")
    lst.add_argument("--json", action="store_true")
    add = sub.add_parser("add", help="statically register a model")
    add.add_argument("--name", required=True)
    add.add_argument("--endpoint", required=True, help="namespace.component.endpoint")
    add.add_argument("--tokenizer", default="byte")
    add.add_argument("--context-length", type=int, default=4096)
    add.add_argument("--router-mode", default="round_robin")
    add.add_argument("--model-type", default="chat+completions")
    rem = sub.add_parser("remove", help="remove a model's registrations")
    rem.add_argument("--name", required=True)
    dep = sub.add_parser("deployment", help="inspect/scale/delete graph deployments")
    dep_sub = dep.add_subparsers(dest="dep_cmd", required=True)
    dl = dep_sub.add_parser("list")
    dl.add_argument("--json", action="store_true")
    ds = dep_sub.add_parser("scale")
    ds.add_argument("name")
    ds.add_argument("replicas", help="Service=N")
    dd = dep_sub.add_parser("delete")
    dd.add_argument("name")
    args = p.parse_args(argv)
    raise SystemExit(asyncio.run(_amain(args)))


if __name__ == "__main__":
    main()
