"""Pre-deployment profiling sweep: one worker -> WorkerProfile JSON.

Drives an in-process engine at increasing concurrency, measuring prefill and
decode throughput plus TTFT/ITL percentiles per level; the resulting
`planner.core.WorkerProfile` (capacities + piecewise latency surfaces) is
what the planner's SLA mode interpolates at runtime.

Parity: reference `benchmarks/profiler/profile_sla.py` (pre-deployment TP
sweep feeding `perf_interpolation.py`); here the sweep runs the first-party
engine directly — real JAX on the chip, or the timing-model mocker for
CI/planner tests.

CLI: ``python -m dynamo_tpu.profiler --model test-tiny --mock --out p.json``
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import logging
import time

import numpy as np

from dynamo_tpu.planner.core import WorkerProfile
from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions
from dynamo_tpu.runtime.engine import Context

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class LevelResult:
    concurrency: int
    prefill_tps: float
    decode_tps: float
    ttft_p50: float
    itl_p50: float
    # Tail latency: the SLA planner sizes fleets on medians, but tail
    # percentiles are what SLOs are written against — both ship in the
    # WorkerProfile JSON.
    ttft_p95: float = 0.0
    ttft_p99: float = 0.0
    itl_p95: float = 0.0
    itl_p99: float = 0.0


async def _run_level(service, *, concurrency: int, isl: int, osl: int, seed: int) -> LevelResult:
    rng = np.random.default_rng(seed)

    async def one(i: int) -> tuple[float, list[float]]:
        # Distinct prompts: no prefix-cache hits between requests.
        token_ids = [int(t) for t in rng.integers(5, 250, isl)]
        req = PreprocessedRequest(
            token_ids=token_ids,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
            request_id=f"profile-{concurrency}-{i}",
        )
        t0 = time.monotonic()
        first = None
        gaps: list[float] = []
        prev = None
        async for out in service.generate(req, Context()):
            now = time.monotonic()
            if first is None and (out.get("token_ids") or out.get("finish_reason")):
                first = now - t0
            if prev is not None:
                gaps.append(now - prev)
            prev = now
        return first or 0.0, gaps

    t0 = time.monotonic()
    results = await asyncio.gather(*(one(i) for i in range(concurrency)))
    wall = max(time.monotonic() - t0, 1e-6)
    ttfts = [r[0] for r in results]
    gaps = [g for r in results for g in r[1]]
    prefill_tokens = concurrency * isl
    decode_tokens = concurrency * osl
    # Prefill phase ends (approximately) at the last first-token time.
    prefill_wall = max(max(ttfts), 1e-6)
    return LevelResult(
        concurrency=concurrency,
        prefill_tps=prefill_tokens / prefill_wall,
        decode_tps=decode_tokens / wall,
        ttft_p50=float(np.median(ttfts)),
        itl_p50=float(np.median(gaps)) if gaps else 0.0,
        ttft_p95=float(np.percentile(ttfts, 95)),
        ttft_p99=float(np.percentile(ttfts, 99)),
        itl_p95=float(np.percentile(gaps, 95)) if gaps else 0.0,
        itl_p99=float(np.percentile(gaps, 99)) if gaps else 0.0,
    )


async def profile_service(
    service,
    *,
    levels: list[int] | None = None,
    isl: int = 128,
    osl: int = 32,
) -> tuple[WorkerProfile, list[LevelResult]]:
    """Sweep one engine service; returns (profile, per-level results)."""
    levels = levels or [1, 2, 4, 8]
    out: list[LevelResult] = []
    for i, c in enumerate(levels):
        res = await _run_level(service, concurrency=c, isl=isl, osl=osl, seed=i)
        logger.info(
            "level c=%d: prefill %.0f tok/s, decode %.0f tok/s, ttft p50 %.3fs, itl p50 %.4fs",
            c, res.prefill_tps, res.decode_tps, res.ttft_p50, res.itl_p50,
        )
        out.append(res)
    max_c = max(levels)
    profile = WorkerProfile(
        prefill_tokens_per_sec=max(r.prefill_tps for r in out),
        decode_tokens_per_sec=max(r.decode_tps for r in out),
        max_concurrent=max_c,
        ttft_curve=[(r.concurrency / max_c, r.ttft_p50) for r in out],
        itl_curve=[(r.concurrency / max_c, r.itl_p50) for r in out],
        ttft_p95_curve=[(r.concurrency / max_c, r.ttft_p95) for r in out],
        ttft_p99_curve=[(r.concurrency / max_c, r.ttft_p99) for r in out],
        itl_p95_curve=[(r.concurrency / max_c, r.itl_p95) for r in out],
        itl_p99_curve=[(r.concurrency / max_c, r.itl_p99) for r in out],
    )
    return profile, out


async def _amain(args: argparse.Namespace) -> None:
    from dynamo_tpu.launch import build_engine_service, make_worker_spec

    spec = make_worker_spec(args.model, num_pages=args.num_pages, max_batch_size=args.max_batch_size)
    spec.mock = args.mock
    service = await build_engine_service(spec)
    try:
        profile, results = await profile_service(
            service,
            levels=[int(x) for x in args.levels.split(",")],
            isl=args.isl,
            osl=args.osl,
        )
    finally:
        await service.close()
    if args.out:
        with open(args.out, "w") as f:
            f.write(profile.to_json())
        logger.info("wrote %s", args.out)
    print(json.dumps({
        "profile": json.loads(profile.to_json()),
        "levels": [dataclasses.asdict(r) for r in results],
    }))


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description="dynamo-tpu worker profiler")
    p.add_argument("--model", default="test-tiny")
    p.add_argument("--mock", action="store_true", help="profile the timing-model mocker")
    p.add_argument("--levels", default="1,2,4,8", help="concurrency sweep levels")
    p.add_argument("--isl", type=int, default=128)
    p.add_argument("--osl", type=int, default=32)
    p.add_argument("--num-pages", type=int, default=512)
    p.add_argument("--max-batch-size", type=int, default=64)
    p.add_argument("--out", default=None, help="write WorkerProfile JSON here")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s %(levelname)s %(message)s")
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()
