"""Launcher: the `dynamo-run` equivalent (reference SURVEY.md §2 row 37).

Wires the pieces into runnable topologies:

- ``serve_worker``     — build a JAX engine for a model and serve it on a
  runtime endpoint; publish the ModelDeploymentCard (lease-bound) so
  frontends discover it.
- ``serve_frontend``   — ModelManager + ModelWatcher + OpenAI HttpService.
- ``run_local``        — both in one process over the in-memory runtime
  (the `dynamo-run in=http out=<engine>` single-node path).
- CLI: ``python -m dynamo_tpu.launch --model test-tiny --http-port 8080``
  with ``--store tcp://...`` to join a multi-process deployment.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
from dataclasses import dataclass, field
from typing import Any

from dynamo_tpu.config import env_flag
from dynamo_tpu.engine.core import EngineConfig, EngineCore
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.engine.service import JaxEngineService
from dynamo_tpu.frontend.http import HttpService
from dynamo_tpu.frontend.metrics import FrontendMetrics
from dynamo_tpu.frontend.model_manager import ModelManager, ModelWatcher
from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS, ModelConfig
from dynamo_tpu.protocols.kv import KvCacheEvent
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.tokenizer import load_tokenizer

logger = logging.getLogger(__name__)


@dataclass
class WorkerSpec:
    """Everything needed to bring up one engine worker."""

    model_config: ModelConfig
    card: ModelDeploymentCard
    engine_config: EngineConfig = field(default_factory=EngineConfig)
    params: Any = None  # model params pytree; random-init if None
    model_dir: str | None = None  # HF-style checkpoint dir: real weights + tokenizer
    attn_impl: str | None = None
    block_manager_config: Any = None  # blocks.BlockManagerConfig enables G2/G3 tiers
    # GSPMD execution: a parallel.mesh.MeshPlan, or "auto" to derive one from
    # the device count and model shape (tp <= kv heads, ep for wide MoE).
    mesh_plan: Any = None
    # Timing-model engine instead of JAX (planner/router fleets in CI and the
    # planner's local connector; parity: reference mocker, SURVEY.md row 35).
    mock: bool = False
    # Weight-only quantization applied after load ("" = off, "int8"):
    # halves weight HBM reads on the decode path (models/quant.py).
    quantize: str = ""
    # VLM checkpoints: the vision tower's config (+ loaded params, filled at
    # engine build time so run_local can start a weight-sharing encode worker).
    # serve_vision=False skips loading the tower (extra workers in a fleet).
    vision_config: Any = None
    vision_params: Any = None
    serve_vision: bool = True

    @classmethod
    def from_preset(cls, preset: str, *, card: ModelDeploymentCard | None = None, **engine_kw: Any) -> "WorkerSpec":
        mc = PRESETS[preset]
        tokenizer = "byte"
        card = card or ModelDeploymentCard(
            name=preset,
            tokenizer=tokenizer,
            context_length=min(mc.max_position, 4096),
            eos_token_ids=sorted(load_tokenizer(tokenizer).eos_token_ids),
        )
        if mc.image_token_id is not None:
            card.extra.setdefault("image_token_id", mc.image_token_id)
        return cls(model_config=mc, card=card, engine_config=cls._engine_cfg(card, engine_kw))

    @classmethod
    def from_model_dir(cls, model_dir: str, *, name: str | None = None, **engine_kw: Any) -> "WorkerSpec":
        """Serve a real HF-style checkpoint directory (config.json +
        safetensors + tokenizer.json). Weights load at engine build time,
        directly onto the device/mesh.

        Parity: reference `lib/llm/src/local_model.rs:29-140` (local model
        resolution into a served card + engine)."""
        import pathlib

        p = pathlib.Path(model_dir)
        if p.is_file() and p.suffix == ".gguf":
            from dynamo_tpu.models.gguf import config_from_gguf, shared_reader

            # The shared reader serves config, card, tokenizer, and weights:
            # parsing the header eagerly decodes the full embedded vocab
            # (100k+ strings for a real model) — do it once per process.
            reader = shared_reader(p)
            mc = config_from_gguf(reader, name=name or p.stem)
            card = ModelDeploymentCard.from_gguf(name or p.stem, p, reader=reader)
        else:
            mc = ModelConfig.from_hf(p / "config.json", name=name or p.name)
            card = ModelDeploymentCard.from_model_dir(name or p.name, p)
        spec = cls(
            model_config=mc, card=card,
            engine_config=cls._engine_cfg(card, engine_kw), model_dir=str(p),
        )
        # LLaVA-class VLM checkpoint: record the tower config; the engine
        # build loads LM+tower via load_vlm and run_local starts a real
        # encode worker (models/loader.load_vlm, VERDICT r3 item 4).
        import json as _json

        if not (p.is_file() and p.suffix == ".gguf"):
            raw_cfg = _json.loads((p / "config.json").read_text())
            if "vision_config" in raw_cfg:
                if raw_cfg.get("model_type") == "qwen2_vl":
                    from dynamo_tpu.models.qwen2_vl import Qwen2VLVisionConfig

                    spec.vision_config = Qwen2VLVisionConfig.from_hf(raw_cfg)
                else:
                    from dynamo_tpu.models.vision import VisionConfig

                    spec.vision_config = VisionConfig.from_hf_llava(raw_cfg)
                if mc.image_token_id is not None:
                    card.extra.setdefault("image_token_id", mc.image_token_id)
                if mc.video_token_id is not None:
                    card.extra.setdefault("video_token_id", mc.video_token_id)
        return spec

    @staticmethod
    def _engine_cfg(card: ModelDeploymentCard, engine_kw: dict) -> EngineConfig:
        import os

        # Explicit engine_kw wins over the card-derived defaults (the bench
        # CLI overrides page_size/max_seq_len/decode_steps per run).
        defaults = dict(
            max_seq_len=card.context_length,
            eos_token_ids=tuple(card.eos_token_ids),
            page_size=card.kv_page_size,
            decode_steps=int(
                os.environ.get("DYNAMO_DECODE_STEPS")
                or os.environ.get("DYN_WORKER_DECODE_STEPS", "1")
            ),
            chunk_prefill_tokens=int(
                os.environ.get("DYNAMO_CHUNK_PREFILL_TOKENS")
                or os.environ.get("DYN_WORKER_CHUNK_PREFILL_TOKENS", "512")
            ),
            spec_k=int(
                os.environ.get("DYN_SPEC_K")
                or os.environ.get("DYN_WORKER_SPEC_K", "0")
            ),
            slo_sched=env_flag(os.environ, "DYN_SLO_SCHED"),
            cache_aware=env_flag(os.environ, "DYN_CACHE_AWARE"),
            # DYN_CACHE_AWARE implies async onboarding: residual pricing
            # assumes tier hits are cheap, which they only are pipelined.
            async_onboard=(
                env_flag(os.environ, "DYN_ASYNC_ONBOARD")
                or env_flag(os.environ, "DYN_CACHE_AWARE")
            ),
            overlap=(
                env_flag(os.environ, "DYN_OVERLAP")
                or env_flag(os.environ, "DYN_WORKER_OVERLAP")
            ),
            overlap_spec=(
                env_flag(os.environ, "DYN_OVERLAP_SPEC", default=True)
                and env_flag(os.environ, "DYN_WORKER_OVERLAP_SPEC", default=True)
            ),
            constraint_lookahead_tokens=int(
                os.environ.get("DYN_CONSTRAINT_LOOKAHEAD_TOKENS", "32")
            ),
        )
        defaults.update(engine_kw)
        return EngineConfig(**defaults)


def _kv_cache_dtype():
    """Resolve DYN_KV_CACHE_DTYPE / DYN_WORKER_KV_CACHE_DTYPE to a jnp dtype.

    'bf16' (or unset) -> None: the runner keeps its model-dtype default.
    'fp8' -> float8_e4m3fn storage; every attention path upcasts fp8 KV to
    the query dtype at the matmul, so this only changes cache HBM footprint.
    """
    import os

    name = (
        os.environ.get("DYN_KV_CACHE_DTYPE")
        or os.environ.get("DYN_WORKER_KV_CACHE_DTYPE", "")
    ).strip().lower()
    if name in ("", "bf16", "bfloat16"):
        return None
    if name in ("fp8", "float8_e4m3fn", "fp8_e4m3"):
        import jax.numpy as jnp

        return jnp.float8_e4m3fn
    raise ValueError(f"unsupported kv cache dtype: {name!r} (want bf16 or fp8)")


def _parse_mesh(spec: str | None):
    """'auto' | 'dp=2,tp=4' | None -> mesh_plan value for WorkerSpec."""
    if spec is None or spec == "":
        return None
    if spec == "auto":
        return "auto"
    from dynamo_tpu.parallel.mesh import MeshPlan

    kw = {}
    for part in spec.split(","):
        k, _, v = part.partition("=")
        kw[k.strip()] = int(v)
    return MeshPlan(**kw)


def make_worker_spec(model: str, **engine_kw: Any) -> WorkerSpec:
    """Resolve ``model``: a preset name, or a path to an HF checkpoint dir."""
    import os

    if model in PRESETS:
        return WorkerSpec.from_preset(model, **engine_kw)
    if os.path.isdir(model) or (model.endswith(".gguf") and os.path.isfile(model)):
        return WorkerSpec.from_model_dir(model, **engine_kw)
    raise ValueError(
        f"unknown model {model!r}: not a preset ({', '.join(PRESETS)}), a checkpoint directory, or a .gguf file"
    )


async def build_engine_service(spec: WorkerSpec, *, on_kv_event=None, g4_storage=None) -> JaxEngineService:
    from dynamo_tpu.tracing import maybe_trace_from_env

    maybe_trace_from_env()  # DYN_TRACE_DIR=dir captures worker bring-up + first steps
    if spec.mock:
        from dynamo_tpu.mocker import build_mock_core

        return await JaxEngineService(build_mock_core(spec.engine_config, on_kv_event=on_kv_event)).start()

    def _build() -> ModelRunner:
        # Device work (param init, cache allocation) can take seconds on a
        # remote/real chip — keep it off the event loop so lease keep-alives
        # and health endpoints stay live.
        mesh = None
        if spec.mesh_plan is not None:
            import jax

            from dynamo_tpu.parallel.mesh import MeshPlan, make_mesh

            plan = spec.mesh_plan
            if plan == "auto":
                plan = MeshPlan.auto(
                    len(jax.devices()),
                    num_kv_heads=spec.model_config.num_kv_heads,
                    num_experts=spec.model_config.num_experts,
                )
            mesh = make_mesh(plan)
        if spec.params is not None:
            params = spec.params
        elif spec.model_dir is not None and spec.model_dir.endswith(".gguf"):
            from dynamo_tpu.models.gguf import load_gguf_params, shared_reader

            # int4 serving imports the file's own Q4_0/Q4_K codes directly
            # into packed leaves (lossless repack, no bf16 round trip); the
            # quantize_params pass below converts whatever fell back.
            params = load_gguf_params(
                shared_reader(spec.model_dir), spec.model_config, mesh=mesh,
                quantize=spec.quantize,
            )
        elif spec.model_dir is not None and spec.vision_config is not None:
            from dynamo_tpu.models.loader import load_vlm

            _tc, _vc, params, spec.vision_params = load_vlm(
                spec.model_dir, mesh=mesh, load_tower=spec.serve_vision
            )
        elif spec.model_dir is not None:
            from dynamo_tpu.models.loader import load_params

            # Direct-to-mesh: each device shard reads its own checkpoint
            # slice; the runner then skips re-placement of placed params.
            params = load_params(spec.model_dir, spec.model_config, mesh=mesh)
        else:
            params = None  # random-init below, possibly directly quantized
        if spec.quantize and params is None:
            # Random-init + quantize without ever materializing the
            # full-precision tree: an 8B-class random model OOMs a 16 GB
            # chip before quantize_params could shrink it.
            from dynamo_tpu.models.quant import init_params_quantized

            params = init_params_quantized(spec.model_config, 0, mode=spec.quantize)
        elif spec.quantize:
            from dynamo_tpu.models.quant import quantize_params

            params = quantize_params(params, mode=spec.quantize)
        elif params is None:
            params = llama.init_params(spec.model_config, 0)
        return ModelRunner(
            spec.model_config,
            params,
            num_pages=spec.engine_config.num_pages,
            page_size=spec.engine_config.page_size,
            max_batch_size=spec.engine_config.max_batch_size,
            attn_impl=spec.attn_impl,
            mesh=mesh,
            cache_dtype=_kv_cache_dtype(),
        )

    runner = await asyncio.get_running_loop().run_in_executor(None, _build)
    block_manager = None
    if spec.block_manager_config is not None:
        from dynamo_tpu.blocks import KvBlockManager

        block_manager = KvBlockManager(
            spec.block_manager_config,
            read_page=runner.read_page,
            write_page=runner.write_page,
            write_pages=getattr(runner, "write_pages", None),
            g4_storage=g4_storage,
        )
    core = EngineCore(runner, spec.engine_config, on_kv_event=on_kv_event, block_manager=block_manager)
    # Constrained decoding (response_format json_object) needs token text;
    # warm the vocab piece table + hot masks on a background thread so the
    # first json_mode request doesn't stall the serving loop.
    import os
    import threading

    core.set_constraint_tokenizer_factory(lambda: load_tokenizer(spec.card.tokenizer))
    # Default-on warm-up trades a background thread at startup for never
    # paying the cold vocab walk on the serving loop; fleets that never see
    # json_mode can set DYNAMO_WARM_CONSTRAINTS=0 to skip it entirely (the
    # first constrained request then pays the build, serialized by the
    # cache's build lock).
    if os.environ.get("DYNAMO_WARM_CONSTRAINTS", "1") != "0":
        threading.Thread(target=core.warm_constraints, daemon=True,
                         name="constraint-warmup").start()
    return await JaxEngineService(core).start()


async def serve_worker(
    runtime: DistributedRuntime,
    spec: WorkerSpec,
    *,
    lease=None,
    disagg=None,  # disagg.DisaggConfig: serve as a disaggregated *decode* worker
) -> JaxEngineService:
    """Serve the engine + KV event stream + metrics and publish the model card.

    With ``disagg`` set, the worker also serves the KV transfer endpoint and
    fronts its engine with the disagg operator (remote prefill via the
    prefill queue; see dynamo_tpu.disagg).
    """
    from dynamo_tpu.router.events import KV_EVENTS_ENDPOINT, KvEventBroadcaster
    from dynamo_tpu.router.metrics import WorkerMetricsPublisher

    broadcaster = KvEventBroadcaster()
    broadcaster.bind_loop(asyncio.get_running_loop())
    service = await build_engine_service(
        spec, on_kv_event=broadcaster.publish, g4_storage=_g4_storage_for(spec, runtime)
    )
    service.spec = spec  # run_local reads vision_config/params off it (VLM)
    broadcaster.bind_snapshot(service.core.allocator.cache_snapshot)
    ns, comp, ep = spec.card.endpoint
    component = runtime.namespace(ns).component(comp)

    serve_engine: Any = service
    transfer = None
    if disagg is not None:
        from dynamo_tpu.disagg.operator import DisaggDecodeService
        from dynamo_tpu.disagg.prefill_worker import PREFILL_QUEUE
        from dynamo_tpu.disagg.queue import DistributedQueue
        from dynamo_tpu.disagg.router import DisaggRouter
        from dynamo_tpu.disagg.transfer import KV_TRANSFER_ENDPOINT, KvTransferService

        transfer = KvTransferService(service.core)
        service.aux.append(transfer.start_sweeper())
        t_inst = await component.endpoint(KV_TRANSFER_ENDPOINT).serve(
            transfer, metadata={"model": spec.card.name}, lease=lease
        )
        # Device-path short-circuit for co-located prefill workers (ICI
        # instead of the TCP host-bounce) — see disagg/device_transfer.py.
        from dynamo_tpu.disagg.device_transfer import REGISTRY

        service.aux.append(REGISTRY.register(t_inst.address, transfer))
        disagg_router = await DisaggRouter(disagg, page_size=spec.engine_config.page_size).watch(runtime, ns)
        serve_engine = DisaggDecodeService(
            service, transfer, DistributedQueue(runtime, PREFILL_QUEUE), disagg_router, t_inst.address
        )
        service.disagg_operator = serve_engine  # remote/local prefill counters
        service.aux.append(disagg_router)

    instance = await component.endpoint(ep).serve(serve_engine, metadata={"model": spec.card.name}, lease=lease)
    await component.endpoint(KV_EVENTS_ENDPOINT).serve(broadcaster, metadata={"model": spec.card.name}, lease=lease)
    service.core.config.worker_id = instance.lease_id  # same object as spec.engine_config
    # Graceful drain needs both: re-publish the record with draining=True,
    # then revoke the lease once in-flight work finishes (drain_worker).
    service.instance = instance
    service.serve_lease = lease

    def snapshot():
        m = service.metrics()
        m.worker_id = instance.lease_id
        return m

    publisher = await WorkerMetricsPublisher(
        runtime, ns, comp, instance.lease_id, snapshot, interval=0.5, lease=lease
    ).start()
    service.aux.append(publisher)  # closed with the service by callers that track it
    await _serve_worker_telemetry(
        component, service, worker_id=f"{instance.lease_id:x}", lease=lease,
        transfer=transfer,
        queue=getattr(serve_engine, "queue", None) if disagg is not None else None,
        metadata={"model": spec.card.name},
    )
    card_lease = lease or await runtime.primary_lease()
    await runtime.store.put(
        spec.card.instance_key(instance.lease_id), spec.card.to_bytes(), lease_id=card_lease.id
    )
    logger.info("worker serving %s as instance %x", spec.card.name, instance.lease_id)
    return service


async def _serve_worker_telemetry(
    component,
    service: JaxEngineService,
    *,
    worker_id: str,
    lease=None,
    transfer=None,
    queue=None,
    metadata: dict | None = None,
):
    """Attach the per-worker telemetry plane (ISSUE: observability tentpole).

    Builds the EngineMetrics registry bound to this worker's engine
    internals, installs it as the process's KV-phase sink, and serves the
    span-query + metrics-scrape endpoints next to ``generate`` so the
    frontend can federate. ``DYN_WORKER_HTTP_PORT`` additionally opens the
    direct debug HTTP surface (0 = pick a free port).
    """
    from dynamo_tpu.observability import (
        DEBUG_EXPLAIN_ENDPOINT,
        DEBUG_TRACES_ENDPOINT,
        FLIGHT_ENDPOINT,
        METRICS_SCRAPE_ENDPOINT,
        EngineMetrics,
        ExplainQueryService,
        FlightQueryService,
        MetricsScrapeService,
        SpanQueryService,
    )
    from dynamo_tpu.observability.metrics import install
    from dynamo_tpu.observability.service import (
        COST_ENDPOINT,
        DEBUG_INCIDENTS_ENDPOINT,
        PROFILE_ENDPOINT,
        CostQueryService,
        IncidentQueryService,
        ProfileCaptureService,
    )

    metrics = EngineMetrics(worker=worker_id).bind_core(service.core)
    if transfer is not None:
        metrics.bind_transfer(transfer)
    if queue is not None:
        metrics.bind_queue(queue)
    # Process-global phase sink (plus the per-core route, so several
    # in-process workers each attribute their own KV phases — run_local is
    # now exact, not just multi-process deployments).
    install(metrics)
    service.engine_metrics = metrics  # reachable for tests / direct scraping
    await component.endpoint(DEBUG_TRACES_ENDPOINT).serve(
        SpanQueryService(host=worker_id), metadata=metadata, lease=lease
    )
    await component.endpoint(METRICS_SCRAPE_ENDPOINT).serve(
        MetricsScrapeService(metrics), metadata=metadata, lease=lease
    )
    flight = getattr(service.core, "flight", None)
    if flight is not None:
        await component.endpoint(FLIGHT_ENDPOINT).serve(
            FlightQueryService(flight, worker=worker_id), metadata=metadata, lease=lease
        )
        await component.endpoint(DEBUG_EXPLAIN_ENDPOINT).serve(
            ExplainQueryService(service.core, worker=worker_id),
            metadata=metadata, lease=lease,
        )
    incidents = getattr(service.core, "incidents", None)
    if incidents is not None:
        # Bundles captured before bring-up keep the pid label; everything
        # after carries the lease id the frontend addresses workers by.
        incidents.worker = worker_id
        await component.endpoint(DEBUG_INCIDENTS_ENDPOINT).serve(
            IncidentQueryService(incidents.store, worker=worker_id),
            metadata=metadata, lease=lease,
        )
    runner = getattr(service.core, "runner", None)
    if runner is not None:
        # Served even when DYN_COST_PLANE=0 — the service answers
        # {"enabled": False}, so operators can tell "off" from "dead".
        await component.endpoint(COST_ENDPOINT).serve(
            CostQueryService(runner, worker=worker_id), metadata=metadata, lease=lease
        )
        cost_reg = getattr(runner, "cost_registry", None)
        if cost_reg is not None:
            cost_reg.worker = worker_id
    await component.endpoint(PROFILE_ENDPOINT).serve(
        ProfileCaptureService(worker=worker_id), metadata=metadata, lease=lease
    )
    port_spec = os.environ.get("DYN_WORKER_HTTP_PORT")
    if port_spec is not None:
        from dynamo_tpu.observability.http import WorkerDebugServer

        debug = WorkerDebugServer(
            metrics, flight=flight,
            incidents=incidents.store if incidents is not None else None,
            cost=getattr(runner, "cost_registry", None),
        )
        await debug.start(port=int(port_spec))
        service.aux.append(debug)
    return metrics


def _g4_storage_for(spec: WorkerSpec, runtime: DistributedRuntime):
    """RemoteStorage for the G4 tier when configured (decode AND prefill
    workers): blocks offloaded here are onboardable by every worker joined
    to the same store (shared best-effort cache, `blocks/tier.py`)."""
    bm_cfg = spec.block_manager_config
    if bm_cfg is None or getattr(bm_cfg, "g4_capacity_blocks", 0) <= 0 or bm_cfg.null_storage:
        return None
    from dynamo_tpu.blocks.storage import RemoteStorage
    from dynamo_tpu.runtime.objects import ObjectStore

    return RemoteStorage(
        ObjectStore(runtime.store), asyncio.get_running_loop(), prefix=f"kv/{spec.card.name}"
    )


async def serve_prefill_worker(runtime: DistributedRuntime, spec: WorkerSpec, *, lease=None):
    """A prefill-fleet worker: engine + queue consumer, no model card."""
    from dynamo_tpu.disagg.prefill_worker import PrefillWorker

    service = await build_engine_service(spec, g4_storage=_g4_storage_for(spec, runtime))
    conc = int(os.environ.get("DYN_PREFILL_CONCURRENCY", "2"))
    worker = await PrefillWorker(runtime, service, max_concurrency=conc).start()
    service.aux.append(worker)
    service.prefill_worker = worker  # drain_worker stops claiming before closing
    service.serve_lease = lease
    ns, comp, _ep = spec.card.endpoint
    worker_id = f"{lease.id:x}" if lease is not None else f"prefill-{os.getpid()}"
    await _serve_worker_telemetry(
        runtime.namespace(ns).component(comp), service,
        worker_id=worker_id, lease=lease, queue=worker.queue,
        metadata={"model": spec.card.name, "role": "prefill"},
    )
    logger.info("prefill worker up for %s", spec.card.name)
    return service


async def drain_worker(
    runtime: DistributedRuntime, service: JaxEngineService, *, timeout: float | None = None
) -> bool:
    """Graceful worker shutdown: announce draining, finish in-flight work
    under a deadline, revoke the lease, close.

    Order matters: (1) the instance record is re-published with
    ``metadata.draining=True`` so clients stop routing new requests here
    while the record (and in-flight streams) stay alive; (2) the engine
    finishes admitted requests (and a prefill worker its claimed tasks)
    under ``timeout`` (``DYN_DRAIN_TIMEOUT_S``, default 30); (3) the lease
    is revoked, cascade-deleting every record this worker published; (4) the
    service closes. Returns True when everything finished in time.
    """
    import dataclasses

    if timeout is None:
        timeout = float(os.environ.get("DYN_DRAIN_TIMEOUT_S", "30"))
    instance = getattr(service, "instance", None)
    lease = getattr(service, "serve_lease", None)
    if lease is None:
        lease = await runtime.primary_lease()
    if instance is not None:
        draining = dataclasses.replace(
            instance, metadata={**instance.metadata, "draining": True}
        )
        try:
            await runtime.store.put(instance.key, draining.to_bytes(), lease_id=lease.id)
        except Exception:
            logger.exception("drain announcement failed; clients will retry against us")
    done = True
    worker = getattr(service, "prefill_worker", None)
    if worker is not None:
        done = await worker.drain(timeout)
    if hasattr(service, "drain"):
        done = await service.drain(timeout) and done
    if not done:
        logger.warning("drain deadline (%.1fs) hit with work still in flight", timeout)
    try:
        await lease.revoke()
    except Exception:
        logger.exception("lease revoke during drain failed (expiry will clean up)")
    await service.close()
    logger.info("worker drained and closed (clean=%s)", done)
    return done


async def serve_frontend(
    runtime: DistributedRuntime,
    *,
    host: str = "0.0.0.0",
    port: int = 8080,
    router_factory=None,
    clear_kv_hook=None,
) -> tuple[HttpService, ModelWatcher, int]:
    from dynamo_tpu.observability import WorkerTelemetryClient

    manager = ModelManager()
    watcher = await ModelWatcher(runtime, manager, router_factory=router_factory).start()
    service = HttpService(
        manager, metrics=FrontendMetrics(), clear_kv_hook=clear_kv_hook,
        telemetry=WorkerTelemetryClient(runtime),
    )
    actual_port = await service.start(host, port)
    return service, watcher, actual_port


async def run_local(
    preset: str = "test-tiny",
    *,
    host: str = "127.0.0.1",
    port: int = 8080,
    num_workers: int = 1,
    num_prefill_workers: int = 0,
    router_mode: str = "round_robin",
    disagg=None,  # DisaggConfig: enables the disaggregated topology
    **engine_kw: Any,
) -> dict[str, Any]:
    """Single-process serving: N (decode) workers [+ M prefill workers] + frontend."""
    runtime = DistributedRuntime.detached()
    services = []
    g2_blocks = engine_kw.pop("g2_blocks", 0)
    g3_blocks = engine_kw.pop("g3_blocks", 0)
    g4_blocks = engine_kw.pop("g4_blocks", 0)
    mesh_plan = engine_kw.pop("mesh", None)
    mock = engine_kw.pop("mock", False)
    quantize = engine_kw.pop("quantize", "")
    total_workers = num_workers + num_prefill_workers

    def make_spec(i: int) -> WorkerSpec:
        spec = make_worker_spec(preset, **engine_kw)
        spec.serve_vision = i == 0  # one tower copy serves the whole fleet
        spec.card.router_mode = router_mode
        spec.mesh_plan = mesh_plan
        spec.mock = mock
        spec.quantize = quantize
        if g2_blocks or g3_blocks or g4_blocks:
            from dynamo_tpu.blocks import BlockManagerConfig

            spec.block_manager_config = BlockManagerConfig(
                g2_capacity_blocks=g2_blocks,
                g3_capacity_blocks=g3_blocks,
                g3_path=f"/tmp/dynamo_tpu_g3_w{i}",
                g4_capacity_blocks=g4_blocks,
            )
        return spec

    for i in range(num_workers):
        # Each worker needs its own lease/instance: secondary leases per worker.
        lease = await runtime.secondary_lease() if total_workers > 1 else None
        service = await serve_worker(runtime, make_spec(i), lease=lease, disagg=disagg)
        services.append(service)
    for i in range(num_prefill_workers):
        lease = await runtime.secondary_lease() if total_workers > 1 else None
        service = await serve_prefill_worker(runtime, make_spec(num_workers + i), lease=lease)
        services.append(service)
    # Vision-language models get an in-process encode worker automatically:
    # presets use the paired test tower; VLM checkpoint dirs serve the REAL
    # loaded tower (CLIP + projector weights from the checkpoint).
    from dynamo_tpu.encode import VISION_PRESETS, serve_encode_worker

    if preset in VISION_PRESETS:
        services.append(await serve_encode_worker(runtime, VISION_PRESETS[preset]))
    else:
        for svc in services:
            spec_v = getattr(svc, "spec", None)
            if spec_v is not None and spec_v.vision_config is not None:
                services.append(await serve_encode_worker(
                    runtime, spec_v.vision_config, params=spec_v.vision_params
                ))
                break

    async def clear_all() -> int:
        n = 0
        for s in services:
            core = getattr(s, "core", None)  # encode workers hold no KV
            if core is None:
                continue
            n += core.allocator.clear_cache()
            if core.block_manager is not None:
                n += core.block_manager.clear()
        return n

    http, watcher, actual_port = await serve_frontend(
        runtime, host=host, port=port, clear_kv_hook=clear_all
    )
    return {
        "runtime": runtime,
        "services": services,
        "http": http,
        "watcher": watcher,
        "port": actual_port,
    }


async def run_role(args: argparse.Namespace) -> None:
    """Multi-process deployment: one process per role, joined via the TCP
    store (``--serve-store`` in exactly one process, ``--store`` elsewhere)."""
    from dynamo_tpu.runtime.store_server import StoreClient, StoreServer
    from dynamo_tpu.runtime.tcp import TcpTransport

    store_server = None
    if args.serve_store_port is not None:
        backing = None
        if getattr(args, "store_persist", None):
            from dynamo_tpu.runtime.persist import PersistentStore

            backing = await PersistentStore.open(args.store_persist)
        store_server = await StoreServer(backing, host=args.host, port=args.serve_store_port).start()
        store = store_server.store
        replicas = [u.strip() for u in (getattr(args, "store_replicas", "") or "").split(",") if u.strip()]
        if len(replicas) > 1:
            from dynamo_tpu.config import load_store_settings
            from dynamo_tpu.runtime.replication import attach_replication

            ss = load_store_settings()
            coord = attach_replication(
                store_server, replicas, args.store_replica_index,
                promote_after_s=ss.promote_after_s, poll_s=ss.poll_s,
                epoch_grace_s=ss.epoch_grace_s,
            )
            await coord.start()
            logger.info(
                "store replica %d/%d (%s) as %s", args.store_replica_index,
                len(replicas), replicas[args.store_replica_index], coord.role,
            )
    else:
        if not args.store:
            raise SystemExit("--role requires --store tcp://host:port (or --serve-store-port)")
        store = StoreClient.from_url(args.store)
    runtime = DistributedRuntime(store, TcpTransport(host=args.host))

    if args.num_nodes > 1:
        # Multi-host worker: rendezvous through the store, then initialize
        # the global device runtime so the mesh below spans every node.
        from dynamo_tpu.parallel.multihost import MultiNodeConfig, bringup

        await bringup(
            MultiNodeConfig(
                num_nodes=args.num_nodes, node_rank=args.node_rank,
                leader_addr=args.leader_addr,
            ),
            runtime,
        )

    disagg = None
    if args.disagg_threshold is not None:
        from dynamo_tpu.disagg.router import DisaggConfig

        disagg = DisaggConfig(max_local_prefill_length=args.disagg_threshold)

    service = None  # engine-bearing roles get SIGTERM -> drain_worker below
    if args.role == "frontend":
        _, _, port = await serve_frontend(runtime, host=args.host, port=args.http_port)
        logger.info("frontend ready on port %d", port)
    elif args.role == "worker":
        spec = make_worker_spec(args.model, num_pages=args.num_pages, max_batch_size=args.max_batch_size)
        spec.card.router_mode = args.router_mode
        spec.mesh_plan = _parse_mesh(args.mesh)
        spec.mock = args.mock
        spec.quantize = args.quantize
        service = await serve_worker(runtime, spec, disagg=disagg)
        logger.info("worker ready")
    elif args.role == "prefill":
        spec = make_worker_spec(args.model, num_pages=args.num_pages, max_batch_size=args.max_batch_size)
        spec.mesh_plan = _parse_mesh(args.mesh)
        spec.mock = args.mock
        spec.quantize = args.quantize
        service = await serve_prefill_worker(runtime, spec)
        logger.info("prefill worker ready")
    elif args.role == "encode":
        from dynamo_tpu.encode import VISION_PRESETS, serve_encode_worker

        if args.model not in VISION_PRESETS:
            raise SystemExit(f"no vision tower for model {args.model!r}")
        await serve_encode_worker(runtime, VISION_PRESETS[args.model])
        logger.info("encode worker ready")
    elif args.role == "router":
        from dynamo_tpu.model_card import MODEL_PREFIX, ModelDeploymentCard
        from dynamo_tpu.router.service import serve_router

        # Router-only hosts need no checkpoint: take the block size from a
        # card already published in the store (fall back to the default).
        block_size = 16
        for value in (await runtime.store.get_prefix(f"{MODEL_PREFIX}/")).values():
            try:
                block_size = ModelDeploymentCard.from_bytes(value).kv_page_size
                break
            except Exception:
                continue
        await serve_router(runtime, namespace="dynamo", component="backend", block_size=block_size)
        logger.info("router service ready")
    elif args.role == "store":
        logger.info("store-only process")
    else:
        raise SystemExit(f"unknown role {args.role!r}")
    stop = asyncio.Event()
    if service is not None:
        import signal

        def _dump_flight(reason: str) -> None:
            # Planner scale-downs and rolling upgrades end with a signal,
            # not a crash — the flight ring's last seconds must land on
            # disk for those exits too, not only engine-loop failures.
            flight = getattr(service.core, "flight", None)
            if flight is None:
                return
            try:
                path = flight.dump_jsonl(reason=reason)
                logger.info("flight ring dumped on %s -> %s", reason, path)
            except Exception:
                logger.exception("flight dump on %s failed", reason)

        async def _drain_then_stop() -> None:
            try:
                await drain_worker(runtime, service)
            except Exception:
                logger.exception("drain on signal failed")
            finally:
                stop.set()

        def _on_signal(reason: str) -> None:
            logger.info("%s received: dumping flight ring, draining before exit", reason.upper())
            _dump_flight(reason)
            asyncio.ensure_future(_drain_then_stop())

        try:
            loop = asyncio.get_running_loop()
            loop.add_signal_handler(signal.SIGTERM, lambda: _on_signal("sigterm"))
            loop.add_signal_handler(signal.SIGINT, lambda: _on_signal("sigint"))
        except (NotImplementedError, RuntimeError):
            # Non-Unix loops (or nested-loop shims) don't support signal
            # handlers; the role then relies on lease expiry for cleanup.
            logger.debug("signal handlers unavailable; drain-on-terminate disabled")
    print(f"READY role={args.role}", flush=True)
    await stop.wait()


async def _amain(args: argparse.Namespace) -> None:
    if args.role != "local":
        await run_role(args)
        return
    if args.input not in ("http", "text") and not args.input.startswith("batch:"):
        raise SystemExit(
            f"--input must be 'http', 'text', or 'batch:FILE.jsonl' (got {args.input!r})"
        )
    disagg = None
    if args.disagg_threshold is not None:
        from dynamo_tpu.disagg.router import DisaggConfig

        disagg = DisaggConfig(max_local_prefill_length=args.disagg_threshold)
    handles = await run_local(
        args.model,
        host=args.host,
        port=args.http_port,
        num_workers=args.workers,
        num_prefill_workers=args.prefill_workers,
        router_mode=args.router_mode,
        disagg=disagg,
        mesh=_parse_mesh(args.mesh),
        num_pages=args.num_pages,
        max_batch_size=args.max_batch_size,
        g2_blocks=args.g2_blocks,
        g3_blocks=args.g3_blocks,
        g4_blocks=args.g4_blocks,
        mock=args.mock,
        quantize=args.quantize,
    )
    logger.info("serving %s on port %d", args.model, handles["port"])
    try:
        if args.input == "text":
            await run_text_input(handles["port"], args.model)
        elif args.input.startswith("batch:"):
            await run_batch_input(handles["port"], args.model, args.input[len("batch:"):])
        else:
            await asyncio.Event().wait()
    finally:
        # Full teardown: text/batch modes exit here normally, and leaving
        # engines/runtime to loop-shutdown cancellation risks the
        # shutdown-hang class the soak tests guard against. One shielded
        # task runs every step (each isolated), so a Ctrl-C arriving during
        # teardown can't skip the later closes.
        async def _teardown() -> None:
            for closer in (
                handles["http"].stop,
                handles["watcher"].close,
                *(svc.close for svc in handles["services"]),
                handles["runtime"].close,
            ):
                try:
                    await closer()
                except Exception:
                    logger.exception("teardown step %r failed", closer)

        task = asyncio.ensure_future(_teardown())
        try:
            await asyncio.shield(task)
        except asyncio.CancelledError:
            if not task.done():
                await asyncio.wait([task])
            raise


async def run_text_input(port: int, model: str) -> None:
    """Interactive stdin chat against the local stack (``in=text``).

    Parity: reference `dynamo-run in=text` (`launch/dynamo-run/src/input/text.rs`).
    """
    import aiohttp

    loop = asyncio.get_running_loop()
    history: list[dict] = []
    print("interactive mode — empty line or EOF to exit", flush=True)
    async with aiohttp.ClientSession() as session:
        while True:
            try:
                line = await loop.run_in_executor(None, input, "> ")
            except (EOFError, KeyboardInterrupt):
                break
            if not line.strip():
                break
            import json as _json

            history.append({"role": "user", "content": line})
            reply = ""
            failed = False
            async with session.post(
                f"http://127.0.0.1:{port}/v1/chat/completions",
                json={"model": model, "messages": history, "stream": True},
            ) as resp:
                if resp.status != 200:
                    print(f"[error: {(await resp.text())[:200]}]", flush=True)
                    history.pop()  # keep the conversation consistent
                    continue
                async for raw in resp.content:
                    text = raw.decode().strip()
                    if not text.startswith("data: ") or text == "data: [DONE]":
                        continue
                    doc = _json.loads(text[6:])
                    if "error" in doc:
                        print(f"\n[error: {doc['error']}]", flush=True)
                        failed = True
                        break
                    delta = doc["choices"][0].get("delta", {})
                    piece = delta.get("content") or ""
                    reply += piece
                    print(piece, end="", flush=True)
            print(flush=True)
            if failed:
                history.pop()
            else:
                history.append({"role": "assistant", "content": reply})


async def run_batch_input(port: int, model: str, input_path: str, *, concurrency: int = 64) -> None:
    """Batch completion over a JSONL file of ``{"text": ...}`` entries.

    Writes ``output.jsonl`` beside the input (response, token counts,
    latency per entry) and prints an aggregate throughput line.
    Parity: reference `dynamo-run in=batch:` (`input/batch.rs`).
    """
    import json as _json
    import pathlib
    import time

    import aiohttp

    src = pathlib.Path(input_path)
    if not src.is_file():
        raise SystemExit(f"batch input {src} is not a file")
    entries = [
        _json.loads(line) for line in src.read_text().splitlines() if line.strip()
    ]
    out_path = src.parent / "output.jsonl"
    sem = asyncio.Semaphore(concurrency)
    t0 = time.perf_counter()
    totals = {"in": 0, "out": 0}

    async def one(session: aiohttp.ClientSession, entry: dict) -> dict:
        entry = dict(entry)
        async with sem:
            start = time.perf_counter()
            try:
                async with session.post(
                    f"http://127.0.0.1:{port}/v1/completions",
                    json={"model": model, "prompt": entry.get("text", ""), "max_tokens": 256},
                ) as resp:
                    try:
                        doc = await resp.json()
                    except Exception:
                        doc = {"error": (await resp.text())[:200]}
                if resp.status != 200 or "choices" not in doc:
                    entry["response"] = None
                    entry["finish_reason"] = "error"
                    entry["error"] = str(doc.get("error", f"http {resp.status}"))
                else:
                    choice = doc["choices"][0]
                    entry["response"] = choice.get("text", "")
                    entry["finish_reason"] = choice.get("finish_reason")
                    usage = doc.get("usage", {})
                    entry["tokens_in"] = usage.get("prompt_tokens", 0)
                    entry["tokens_out"] = usage.get("completion_tokens", 0)
                    totals["in"] += entry["tokens_in"]
                    totals["out"] += entry["tokens_out"]
            except Exception as exc:
                # One dead connection must not lose the rest of the batch.
                entry["response"] = None
                entry["finish_reason"] = "error"
                entry["error"] = f"{type(exc).__name__}: {exc}"
            entry["elapsed_ms"] = int((time.perf_counter() - start) * 1e3)
            return entry

    async with aiohttp.ClientSession() as session:
        results = await asyncio.gather(*(one(session, e) for e in entries))
    with out_path.open("w") as fh:
        for entry in results:
            fh.write(_json.dumps(entry) + "\n")
    dt = time.perf_counter() - t0
    print(
        f"batch done: {len(results)} entries, {totals['in']} tokens in, "
        f"{totals['out']} tokens out, {dt:.2f}s ({totals['out'] / max(dt, 1e-9):.0f} tok/s) "
        f"-> {out_path}",
        flush=True,
    )


def main(argv: list[str] | None = None) -> None:
    # Layered defaults (reference figment cascade, `config.rs:26-143`):
    # dataclass defaults <- TOML (DYN_CONFIG) <- DYN_RUNTIME_*/DYN_WORKER_*
    # env <- CLI flags (highest).
    from dynamo_tpu.config import load_runtime_settings, load_store_settings, load_worker_settings

    rs = load_runtime_settings()
    ws = load_worker_settings()
    ss_store = load_store_settings()
    if ws.router_mode not in ("round_robin", "random", "kv"):
        # Env/TOML-seeded defaults bypass argparse choices validation.
        raise SystemExit(f"invalid router_mode from config: {ws.router_mode!r}")
    parser = argparse.ArgumentParser(description="dynamo-tpu launcher")
    parser.add_argument("--model", default=ws.model, help="model preset name or HF checkpoint directory")
    parser.add_argument("--host", default=rs.host)
    parser.add_argument("--http-port", type=int, default=rs.http_port)
    parser.add_argument("--workers", type=int, default=1)
    parser.add_argument("--num-pages", type=int, default=ws.num_pages)
    parser.add_argument("--max-batch-size", type=int, default=ws.max_batch_size)
    parser.add_argument("--router-mode", default=ws.router_mode, choices=["round_robin", "random", "kv"])
    parser.add_argument("--g2-blocks", type=int, default=0, help="host-RAM KV tier capacity (blocks); 0 disables")
    parser.add_argument("--g3-blocks", type=int, default=0, help="disk KV tier capacity (blocks); 0 disables")
    parser.add_argument("--g4-blocks", type=int, default=0, help="remote (object-store) KV tier capacity (blocks); 0 disables")
    parser.add_argument("--prefill-workers", type=int, default=0, help="disaggregated prefill fleet size")
    parser.add_argument(
        "--role", default="local", choices=["local", "frontend", "worker", "prefill", "encode", "router", "store"],
        help="multi-process deployments: run one role per process",
    )
    parser.add_argument(
        "--store", default=rs.store or None,
        help="store server url(s): tcp://host:port, or a comma list of "
        "replica urls (tcp://a,tcp://b,...) for HA failover",
    )
    parser.add_argument("--mock", action="store_true", help="timing-model engine instead of JAX (fleet tests, planner)")
    parser.add_argument(
        "--quantize", default="", choices=["", "int8", "int4"],
        help="weight-only quantization for serving (int4: packed nibbles, "
        "group scales of DYN_QUANT_GROUP_SIZE, default 128)",
    )
    parser.add_argument(
        "--input", default="http",
        help="ingress: 'http' (serve), 'text' (interactive stdin chat), or 'batch:FILE.jsonl'",
    )
    parser.add_argument("--serve-store-port", type=int, default=None, help="run the store server in this process")
    parser.add_argument(
        "--store-persist", default=None,
        help="WAL path for durable (lease-less) store state; replayed on restart",
    )
    parser.add_argument(
        "--store-replicas", default=ss_store.replicas or None,
        help="HA store: comma list of ALL replica urls (this process's own "
        "included); index 0 bootstraps as leader",
    )
    parser.add_argument(
        "--store-replica-index", type=int, default=ss_store.replica_index,
        help="this store process's position in --store-replicas",
    )
    parser.add_argument(
        "--disagg-threshold", type=int, default=None,
        help="prompts longer than this prefill remotely (enables disaggregation)",
    )
    parser.add_argument(
        "--mesh", default=ws.mesh or None,
        help="GSPMD mesh: 'auto' or 'dp=2,tp=4,sp=1,ep=1' (default: single device)",
    )
    parser.add_argument(
        "--decode-steps", type=int, default=ws.decode_steps,
        help="fused decode steps per device dispatch",
    )
    parser.add_argument(
        "--chunk-prefill-tokens", type=int, default=ws.chunk_prefill_tokens,
        help="per-step prefill chunk budget fused with decode "
        "(stall-free mixed steps); 0 = phase-exclusive prefill/decode",
    )
    parser.add_argument(
        "--spec-k", type=int, default=ws.spec_k,
        help="speculative decoding draft length (lossless n-gram "
        "self-drafting fused into mixed steps); 0 = off",
    )
    parser.add_argument(
        "--kv-cache-dtype", default=ws.kv_cache_dtype, choices=["bf16", "fp8"],
        help="KV-cache storage dtype; fp8 halves KV HBM (attention upcasts "
        "at the matmul)",
    )
    parser.add_argument(
        "--overlap", action="store_true", default=ws.overlap,
        help="overlapped execution: depth-1 decode pipeline with device-"
        "resident token feedback (DYN_OVERLAP); output streams stay "
        "bit-identical to off",
    )
    parser.add_argument("--num-nodes", type=int, default=1, help="hosts forming one worker's mesh")
    parser.add_argument("--node-rank", type=int, default=0)
    parser.add_argument(
        "--leader-addr", default=None,
        help="host:port of the rank-0 jax coordinator (default: rendezvous via the store)",
    )
    parser.add_argument(
        "--platform", default=None,
        help="force a jax platform (e.g. 'cpu'); needed because hardware "
             "plugins may override the JAX_PLATFORMS env var",
    )
    parser.add_argument(
        "--tune-profile", default=None,
        help="auto-tuner profile JSON (bench.py --tune output); applies its "
        "knob assignments as env defaults — explicit env/CLI still wins",
    )
    args = parser.parse_args(argv)
    if args.tune_profile:
        import os

        from dynamo_tpu.tuning.profile import apply_profile, load_profile

        # Precedence env > CLI > profile: a knob already in the environment
        # is untouched, and one the operator set via flag is claimed by the
        # CLI (its re-export below must not be shadowed by the profile).
        cli_set = set()
        if args.decode_steps != ws.decode_steps:
            cli_set.add("DYN_WORKER_DECODE_STEPS")
        if args.chunk_prefill_tokens != ws.chunk_prefill_tokens:
            cli_set.add("DYN_WORKER_CHUNK_PREFILL_TOKENS")
        if args.spec_k != ws.spec_k:
            cli_set.add("DYN_WORKER_SPEC_K")
        applied = apply_profile(
            load_profile(args.tune_profile), env=os.environ, cli_set=cli_set
        )
        if applied:
            print(
                "tune profile %s: %s" % (
                    args.tune_profile,
                    " ".join(f"{k}={v}" for k, v in sorted(applied.items())),
                ),
                flush=True,
            )
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    from dynamo_tpu.runtime.logging import setup_logging

    # Cascade-resolved logging settings; reference-named env toggles
    # (DYN_LOGGING_JSONL etc.) still apply when the cascade left defaults.
    setup_logging(
        jsonl=rs.log_jsonl or None,
        level=None if rs.log_level == "INFO" else rs.log_level,
    )
    if args.decode_steps != 1:
        import os

        os.environ["DYN_WORKER_DECODE_STEPS"] = str(args.decode_steps)
    if args.chunk_prefill_tokens != 512:
        import os

        os.environ["DYN_WORKER_CHUNK_PREFILL_TOKENS"] = str(args.chunk_prefill_tokens)
    if args.spec_k != 0:
        import os

        os.environ["DYN_WORKER_SPEC_K"] = str(args.spec_k)
    if args.kv_cache_dtype != "bf16":
        import os

        os.environ["DYN_WORKER_KV_CACHE_DTYPE"] = args.kv_cache_dtype
    if args.overlap:
        import os

        os.environ["DYN_WORKER_OVERLAP"] = "1"
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()
