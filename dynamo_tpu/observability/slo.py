"""SLO-conditioned accounting: goodput vs raw throughput.

The north-star metric is explicitly conditioned — "output tokens/sec/chip @
p50 TTFT <= 500 ms" — yet raw token counters can't express it: a deployment
can post record throughput while every request blows its latency target
(DistServe's core observation). This module supplies the two pieces:

- :class:`StreamingQuantile` / :class:`StreamingQuantiles` — P² (Jain &
  Chlamtac 1985) streaming estimators, O(1) memory per quantile. Prometheus
  histograms can't answer "is p50 under 500 ms" without bucket-boundary
  distortion exactly at the target; the P² markers track the true quantile
  with no fixed buckets.
- :class:`SloAccountant` — per-request attainment classification against
  the configured targets (``config.SloSettings``: ``slo.ttft_ms`` /
  ``slo.itl_p99_ms``, env ``DYN_SLO_*``) plus cumulative goodput/output
  token counters. A request attains the SLO when its TTFT met the target
  AND its own p99 inter-token gap did; only attaining, successful requests'
  tokens count as goodput.

Consumers: ``frontend/metrics.py`` feeds every finished request through an
accountant and exports ``dynamo_goodput_tokens_total`` vs
``dynamo_output_tokens_total`` (+ quantile gauges); the planner reads the
same targets with its percentile knob (``planner/core.py``); bench.py
promotes the resulting goodput keys to top-level JSON.
"""

from __future__ import annotations

import dataclasses
import logging
from collections import deque
from typing import Callable, Iterable

from dynamo_tpu.config import AlertSettings, SloSettings, load_alert_settings, load_slo_settings

logger = logging.getLogger(__name__)

__all__ = [
    "SloSettings",
    "load_slo_settings",
    "AlertSettings",
    "load_alert_settings",
    "ALERT_KINDS",
    "StreamingQuantile",
    "StreamingQuantiles",
    "SloAccountant",
    "percentile",
]

#: Burn-rate alert kinds (the dynamo_alert_active{kind} label values).
#: One per rolling window: the fast window catches sharp regressions, the
#: slow window catches sustained slow burns the fast window averages away.
ALERT_KINDS = (
    "slo_fast_burn",
    "slo_slow_burn",
)


def percentile(sorted_xs: list[float], q: float) -> float:
    """Nearest-rank percentile of an already-sorted sample (exact; used for
    per-request gap lists, which are small enough to keep)."""
    if not sorted_xs:
        return 0.0
    idx = min(len(sorted_xs) - 1, max(0, int(q * len(sorted_xs))))
    return sorted_xs[idx]


class StreamingQuantile:
    """P² single-quantile estimator: five markers, O(1) per observation.

    Exact until five observations arrive (it just sorts them); after that
    the interior markers move by the piecewise-parabolic update. Accuracy is
    ~1% of the distribution's scale on smooth distributions — far inside
    the error a fixed histogram bucket at 0.5 s introduces at a 500 ms SLO.
    """

    __slots__ = ("q", "_n", "_heights", "_positions", "_desired", "_increments", "count")

    def __init__(self, q: float) -> None:
        if not 0.0 < q < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {q}")
        self.q = q
        self._heights: list[float] = []
        self._positions = [1.0, 2.0, 3.0, 4.0, 5.0]
        self._desired = [1.0, 1.0 + 2.0 * q, 1.0 + 4.0 * q, 3.0 + 2.0 * q, 5.0]
        self._increments = [0.0, q / 2.0, q, (1.0 + q) / 2.0, 1.0]
        self.count = 0

    def observe(self, x: float) -> None:
        self.count += 1
        h = self._heights
        if len(h) < 5:
            h.append(x)
            h.sort()
            return
        # Find the cell k containing x, clamping the extreme markers.
        if x < h[0]:
            h[0] = x
            k = 0
        elif x >= h[4]:
            h[4] = x
            k = 3
        else:
            k = 0
            while k < 3 and x >= h[k + 1]:
                k += 1
        pos = self._positions
        for i in range(k + 1, 5):
            pos[i] += 1.0
        for i in range(5):
            self._desired[i] += self._increments[i]
        # Adjust interior markers toward their desired positions.
        for i in (1, 2, 3):
            d = self._desired[i] - pos[i]
            if (d >= 1.0 and pos[i + 1] - pos[i] > 1.0) or (d <= -1.0 and pos[i - 1] - pos[i] < -1.0):
                step = 1.0 if d >= 1.0 else -1.0
                candidate = self._parabolic(i, step)
                if h[i - 1] < candidate < h[i + 1]:
                    h[i] = candidate
                else:  # parabolic left the bracket: linear fallback
                    j = i + int(step)
                    h[i] = h[i] + step * (h[j] - h[i]) / (pos[j] - pos[i])
                pos[i] += step

    def _parabolic(self, i: int, d: float) -> float:
        h, n = self._heights, self._positions
        return h[i] + d / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + d) * (h[i + 1] - h[i]) / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - d) * (h[i] - h[i - 1]) / (n[i] - n[i - 1])
        )

    def value(self) -> float:
        h = self._heights
        if not h:
            return 0.0
        if len(h) < 5:
            return percentile(sorted(h), self.q)
        return h[2]


class StreamingQuantiles:
    """A bundle of P² estimators fed by one observation stream."""

    DEFAULT = (0.5, 0.95, 0.99)

    def __init__(self, quantiles: Iterable[float] = DEFAULT) -> None:
        self._est = {q: StreamingQuantile(q) for q in quantiles}

    def observe(self, x: float) -> None:
        for est in self._est.values():
            est.observe(x)

    def get(self, q: float) -> float:
        return self._est[q].value()

    @property
    def count(self) -> int:
        return next(iter(self._est.values())).count if self._est else 0

    def snapshot(self) -> dict[float, float]:
        return {q: est.value() for q, est in self._est.items()}


@dataclasses.dataclass
class SloVerdict:
    met: bool
    ttft_ok: bool
    itl_ok: bool


class SloAccountant:
    """Classifies finished requests against the SLO and keeps the goodput
    ledger, plus multi-window burn-rate alerting over attainment.
    Single-threaded use (the frontend event loop).

    Burn rate (Google-SRE multiwindow discipline, request-count windows so
    tests stay deterministic): ``miss_frac(window) / (1 - objective)`` —
    a burn of 1.0 consumes the error budget exactly at the sustainable
    rate; the fast window alerts at a high threshold (sharp regression),
    the slow window at a low one (sustained burn). Alerts follow the
    anomaly sentinel's hysteresis: a rising edge fires once (and invokes
    ``on_fire`` — the incident plane's capture trigger), then the alert
    stays active until ``alert.clear_after`` consecutive quiet requests.
    """

    def __init__(
        self,
        settings: SloSettings | None = None,
        alerts: AlertSettings | None = None,
        *,
        on_fire: Callable[[str, dict], None] | None = None,
    ) -> None:
        self.settings = settings or load_slo_settings()
        self.alerts = alerts or load_alert_settings()
        self.on_fire = on_fire
        self.ttft = StreamingQuantiles()
        self.itl = StreamingQuantiles()
        self.requests_total = 0
        self.requests_met = 0
        self.output_tokens_total = 0
        self.goodput_tokens_total = 0
        # Rolling attainment windows (True = the request earned goodput).
        self._fast: deque[bool] = deque(maxlen=max(1, self.alerts.fast_window))
        self._slow: deque[bool] = deque(maxlen=max(1, self.alerts.slow_window))
        self._quiet: dict[str, int] = {}
        #: kind -> {"value", "threshold", "since_request"} while active.
        self.alerts_active: dict[str, dict] = {}
        #: kind -> rising edges ever fired.
        self.alerts_fired: dict[str, int] = {}

    # -- live observations (fed per token, deployment-wide) ----------------

    def observe_ttft(self, seconds: float) -> None:
        self.ttft.observe(seconds)

    def observe_itl(self, seconds: float) -> None:
        self.itl.observe(seconds)

    # -- per-request classification ----------------------------------------

    def classify(self, ttft_s: float, itl_gaps: list[float]) -> SloVerdict:
        ttft_ok = ttft_s * 1e3 <= self.settings.ttft_ms
        # A 0/1-token response has no gaps: its ITL vacuously attains.
        itl_ok = (
            percentile(sorted(itl_gaps), 0.99) * 1e3 <= self.settings.itl_p99_ms
            if itl_gaps
            else True
        )
        return SloVerdict(met=ttft_ok and itl_ok, ttft_ok=ttft_ok, itl_ok=itl_ok)

    def account(self, *, ttft_s: float, itl_gaps: list[float], output_tokens: int, ok: bool) -> SloVerdict:
        """Fold one finished request into the ledger; failed requests
        (``ok=False``) never contribute goodput regardless of latency."""
        verdict = self.classify(ttft_s, itl_gaps)
        self.requests_total += 1
        self.output_tokens_total += max(0, output_tokens)
        if verdict.met and ok:
            self.requests_met += 1
            self.goodput_tokens_total += max(0, output_tokens)
        self._observe_burn(verdict.met and ok)
        return verdict

    def attainment(self) -> float:
        return self.requests_met / self.requests_total if self.requests_total else 1.0

    # -- burn-rate alerting ------------------------------------------------

    @staticmethod
    def _burn(window: deque[bool], budget: float) -> float:
        if not window:
            return 0.0
        miss_frac = sum(1 for met in window if not met) / len(window)
        return miss_frac / budget

    def burn_rates(self) -> dict[str, float]:
        """Current burn per window (dynamo_slo_burn_rate{window})."""
        budget = max(1e-9, 1.0 - self.alerts.objective)
        return {
            "fast": round(self._burn(self._fast, budget), 4),
            "slow": round(self._burn(self._slow, budget), 4),
        }

    def _observe_burn(self, met: bool) -> None:
        self._fast.append(met)
        self._slow.append(met)
        budget = max(1e-9, 1.0 - self.alerts.objective)
        armed_fast = len(self._fast) >= min(self.alerts.min_requests, self._fast.maxlen or 1)
        armed_slow = len(self._slow) >= min(self.alerts.min_requests, self._slow.maxlen or 1)
        burn_fast = self._burn(self._fast, budget)
        burn_slow = self._burn(self._slow, budget)
        self._update_alert(
            "slo_fast_burn",
            armed_fast and burn_fast >= self.alerts.fast_burn,
            value=burn_fast, threshold=self.alerts.fast_burn, window="fast",
        )
        self._update_alert(
            "slo_slow_burn",
            armed_slow and burn_slow >= self.alerts.slow_burn,
            value=burn_slow, threshold=self.alerts.slow_burn, window="slow",
        )

    def _update_alert(self, kind: str, firing: bool, *, value: float,
                      threshold: float, window: str) -> None:
        if firing:
            self._quiet[kind] = 0
            if kind not in self.alerts_active:
                self.alerts_active[kind] = {
                    "value": round(float(value), 4),
                    "threshold": round(float(threshold), 4),
                    "window": window,
                    "since_request": self.requests_total,
                }
                self.alerts_fired[kind] = self.alerts_fired.get(kind, 0) + 1
                logger.warning(
                    "SLO alert %s: burn %.4g over threshold %.4g (%s window)",
                    kind, value, threshold, window,
                )
                if self.on_fire is not None:
                    try:
                        self.on_fire(kind, dict(self.alerts_active[kind], alert=kind))
                    except Exception:
                        logger.exception("SLO alert sink failed (ignored)")
            else:
                self.alerts_active[kind]["value"] = round(float(value), 4)
        elif kind in self.alerts_active:
            self._quiet[kind] = self._quiet.get(kind, 0) + 1
            if self._quiet[kind] >= self.alerts.clear_after:
                del self.alerts_active[kind]
                del self._quiet[kind]
                logger.info("SLO alert %s cleared", kind)

    def snapshot(self) -> dict:
        return {
            "ttft_ms": {f"p{int(q * 100)}": round(v * 1e3, 3) for q, v in self.ttft.snapshot().items()},
            "itl_ms": {f"p{int(q * 100)}": round(v * 1e3, 3) for q, v in self.itl.snapshot().items()},
            "requests_total": self.requests_total,
            "requests_met": self.requests_met,
            "slo_attainment": round(self.attainment(), 4),
            "output_tokens_total": self.output_tokens_total,
            "goodput_tokens_total": self.goodput_tokens_total,
            "targets": {"ttft_ms": self.settings.ttft_ms, "itl_p99_ms": self.settings.itl_p99_ms},
            "burn_rates": self.burn_rates(),
            "alerts_active": {k: dict(v) for k, v in self.alerts_active.items()},
            "alerts_fired": dict(self.alerts_fired),
        }
