"""XLA compile observability: first-execution-per-shape detection.

``ModelRunner`` bounds the set of compiled programs with a bucket lattice
(pow2 batch/time/page buckets, see ``engine/runner.py``) — but the lattice is
data-dependent, so production traffic can still walk into shapes nothing
warmed up, and a recompile on the serving path is a silent multi-hundred-ms
stall (bench.py PR 2 had to add identical-dry-run warm-ups for exactly this
reason). No generic tool sees it: JAX compiles inside the dispatch call.

The :class:`CompileTracker` hangs off the runner and observes every dispatch
site *after* padding: the cache key is the padded bucket signature (program
kind + every static shape/arg the jit specializes on), so it tracks exactly
what XLA's own cache tracks. Detection is key-novelty; the measured dispatch
wall time then classifies the first execution:

- ``new_shape`` — first execution AND slower than the compile threshold:
  a real tracing+compilation happened on the serving path.
- ``warm_cache`` — first execution in this process but fast: the program
  came out of a persistent/jit cache (or the model is small enough not to
  matter). Counted separately so dashboards can tell warm restarts from
  true recompile storms.

Re-hits of a seen key emit nothing — by construction one event per bucket.

A warn-once storm detector flags N slow compiles inside a trailing window of
M dispatches after a warm-up grace (the lattice legitimately fills during
the first traffic); a storm after warm-up means shapes are escaping the
lattice (e.g. a mis-sized ``prefill_bucket``) and every occurrence is a
production stall.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable

logger = logging.getLogger(__name__)

_THRESHOLD_ENV = "DYN_COMPILE_THRESHOLD_MS"

#: reasons attached to compile events / the recompile counter.
REASON_NEW_SHAPE = "new_shape"
REASON_WARM_CACHE = "warm_cache"


def _default_threshold_ms() -> float:
    try:
        return float(os.environ.get(_THRESHOLD_ENV, "50"))
    except ValueError:
        return 50.0


class CompileTracker:
    """Per-runner first-execution-per-shape tracker.

    Dispatch sites call :meth:`observe` with the program kind, the padded
    bucket signature, and the measured dispatch wall time. Thread-safe (the
    runner's ``io_lock`` already serializes dispatches, but the tracker does
    not rely on it).
    """

    def __init__(
        self,
        *,
        threshold_ms: float | None = None,
        storm_window: int = 64,
        storm_threshold: int = 8,
        warmup_dispatches: int = 32,
    ) -> None:
        self.threshold_ms = threshold_ms if threshold_ms is not None else _default_threshold_ms()
        self.storm_window = storm_window
        self.storm_threshold = storm_threshold
        self.warmup_dispatches = warmup_dispatches
        self._lock = threading.Lock()
        self._seen: set[tuple] = set()
        self._counts: dict[tuple[str, str], int] = {}  # (program, reason) -> n
        self._events: list[dict] = []
        self._sink: Callable[..., Any] | None = None
        self._dispatches = 0
        # Dispatch indices of slow (new_shape) compiles, for the storm window.
        self._slow_marks: deque[int] = deque(maxlen=max(1, storm_threshold))
        self.storm_warned = False
        # Cumulative seconds spent inside runner dispatch calls — the engine
        # core diffs this across a step to attribute in-step dispatch time.
        self.dispatch_seconds_total = 0.0
        self.last_dispatch_seconds = 0.0

    def bind_sink(self, sink: Callable[..., Any] | None) -> "CompileTracker":
        """``sink(kind, **fields)`` receives compile/storm events — wired to
        the worker's :class:`~dynamo_tpu.observability.flight.FlightRecorder`
        ``record`` method at bring-up."""
        self._sink = sink
        return self

    # -- observation -------------------------------------------------------

    def observe(self, program: str, key: tuple, seconds: float) -> dict | None:
        """Record one dispatch; returns the compile event dict when this was
        the key's first execution, else None."""
        ms = seconds * 1e3
        with self._lock:
            self._dispatches += 1
            dispatch_idx = self._dispatches
            self.dispatch_seconds_total += max(0.0, seconds)
            self.last_dispatch_seconds = max(0.0, seconds)
            full_key = (program, *key)
            if full_key in self._seen:
                return None
            self._seen.add(full_key)
            reason = REASON_NEW_SHAPE if ms >= self.threshold_ms else REASON_WARM_CACHE
            self._counts[(program, reason)] = self._counts.get((program, reason), 0) + 1
            event = {
                "program": program,
                "bucket": list(key),
                "reason": reason,
                "wall_ms": round(ms, 3),
                "dispatch_index": dispatch_idx,
            }
            self._events.append(event)
            storm = self._note_slow_locked(dispatch_idx) if reason == REASON_NEW_SHAPE else None
        self._emit(COMPILE_KIND, **event)
        if storm is not None:
            logger.warning(
                "recompile storm: %d compiles within the last %d dispatches "
                "(after %d warm-up dispatches) — shapes are escaping the bucket "
                "lattice; last program %r bucket %s",
                storm["compiles"], storm["window"], self.warmup_dispatches, program, key,
            )
            self._emit("compile_storm", **storm)
        return event

    def _note_slow_locked(self, dispatch_idx: int) -> dict | None:
        """Track a slow compile; returns a storm event once, when the last
        ``storm_threshold`` slow compiles all landed within ``storm_window``
        dispatches after the warm-up grace."""
        self._slow_marks.append(dispatch_idx)
        if (
            self.storm_warned
            or dispatch_idx <= self.warmup_dispatches
            or len(self._slow_marks) < self.storm_threshold
        ):
            return None
        if dispatch_idx - self._slow_marks[0] <= self.storm_window:
            self.storm_warned = True
            return {
                "compiles": len(self._slow_marks),
                "window": self.storm_window,
                "dispatch_index": dispatch_idx,
            }
        return None

    def _emit(self, kind: str, **fields: Any) -> None:
        sink = self._sink
        if sink is None:
            return
        try:
            sink(kind, **fields)
        except Exception:
            logger.exception("compile event sink failed")

    # -- introspection -----------------------------------------------------

    def counts(self) -> dict[tuple[str, str], int]:
        """Cumulative first-executions per (program, reason) — the source of
        truth behind ``dynamo_engine_recompiles_total`` (synced on scrape)."""
        with self._lock:
            return dict(self._counts)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    @property
    def total(self) -> int:
        with self._lock:
            return sum(self._counts.values())


COMPILE_KIND = "compile"


class timed_dispatch:
    """Context manager timing one dispatch site for a tracker.

    >>> with timed_dispatch(tracker, "step", (b, t, n, h, lp_k)):
    ...     out = self._step_fn(...)

    A ``None`` tracker makes it a no-op, so call sites need no branching.
    ``cost``/``kind`` optionally forward the same (program, key, seconds)
    observation to a :class:`~dynamo_tpu.observability.cost.CostRegistry`
    on clean exit — the cost plane rides the exact bucket keys this
    tracker already sees, without a second timing wrapper.
    """

    __slots__ = ("tracker", "program", "key", "cost", "kind", "steps", "_t0")

    def __init__(self, tracker: CompileTracker | None, program: str, key: tuple,
                 *, cost: Any | None = None, kind: str | None = None,
                 steps: int = 1) -> None:
        self.tracker = tracker
        self.program = program
        self.key = key
        self.cost = cost
        self.kind = kind
        self.steps = steps
        self._t0 = 0.0

    def __enter__(self) -> "timed_dispatch":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            return
        seconds = time.perf_counter() - self._t0
        if self.tracker is not None:
            self.tracker.observe(self.program, self.key, seconds)
        if self.cost is not None:
            try:
                self.cost.observe(self.program, self.key, seconds, self.kind, steps=self.steps)
            except Exception:
                logger.exception("cost observe failed")
