"""Per-worker debug HTTP surface: GET /metrics + GET /debug/traces/{id}.

Workers normally expose telemetry only over the runtime transport
(``observability/service.py``), federated through the frontend. For direct
Prometheus scraping of a worker — or poking a worker without a frontend —
launch enables this tiny aiohttp server when ``DYN_WORKER_HTTP_PORT`` is set
(0 picks a free port; the chosen port is logged).
"""

from __future__ import annotations

import logging

from aiohttp import web

from dynamo_tpu.observability.metrics import EngineMetrics

logger = logging.getLogger(__name__)

WORKER_HTTP_ENV = "DYN_WORKER_HTTP_PORT"


class WorkerDebugServer:
    def __init__(
        self, metrics: EngineMetrics, *, flight=None, incidents=None, cost=None
    ) -> None:
        self.metrics = metrics
        self.flight = flight  # this worker's FlightRecorder, if it has one
        self.incidents = incidents  # this worker's IncidentStore, if it has one
        self.cost = cost  # this worker's CostRegistry, if the cost plane is on
        self._runner: web.AppRunner | None = None
        self.port: int | None = None
        self.app = web.Application()
        self.app.add_routes(
            [
                web.get("/metrics", self.prometheus),
                web.get("/debug/traces/{request_id}", self.traces),
                web.get("/debug/flight", self.flight_dump),
                web.get("/debug/cost", self.cost_dump),
                web.get("/debug/incidents", self.incidents_list),
                web.get("/debug/incidents/{incident_id}", self.incident_get),
            ]
        )

    async def prometheus(self, request: web.Request) -> web.Response:
        return web.Response(body=await self.metrics.render(), content_type="text/plain")

    async def traces(self, request: web.Request) -> web.Response:
        from dynamo_tpu.observability.service import assemble_timeline
        from dynamo_tpu.tracing import SPANS

        rid = request.match_info["request_id"]
        spans = SPANS.query(request_id=rid)
        if not spans:
            spans = SPANS.query(trace_id=rid)  # accept a trace_id too
        return web.json_response(assemble_timeline(rid, spans))

    async def flight_dump(self, request: web.Request) -> web.Response:
        if self.flight is None:
            return web.json_response({"error": "no flight recorder on this worker"}, status=404)
        last = request.query.get("last")
        records = self.flight.snapshot(
            last=int(last) if last else None, kind=request.query.get("kind")
        )
        return web.json_response({"records": records, "count": len(records)})

    async def cost_dump(self, request: web.Request) -> web.Response:
        if self.cost is None:
            # Distinguish "cost plane off" from a wrong URL: 200 with
            # enabled=False mirrors the telemetry-endpoint behavior.
            return web.json_response({"enabled": False})
        return web.json_response(self.cost.snapshot())

    async def incidents_list(self, request: web.Request) -> web.Response:
        if self.incidents is None:
            return web.json_response({"error": "no incident store on this worker"}, status=404)
        items = self.incidents.list()
        return web.json_response({"count": len(items), "incidents": items})

    async def incident_get(self, request: web.Request) -> web.Response:
        if self.incidents is None:
            return web.json_response({"error": "no incident store on this worker"}, status=404)
        incident_id = request.match_info["incident_id"]
        bundle = self.incidents.get(incident_id)
        if bundle is None:
            return web.json_response({"error": f"no incident {incident_id!r}"}, status=404)
        return web.json_response(bundle)

    async def start(self, host: str = "0.0.0.0", port: int = 0) -> int:
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.port = self._runner.addresses[0][1] if self._runner.addresses else port
        logger.info("worker debug HTTP on %s:%d", host, self.port)
        return self.port

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
