"""EngineMetrics: the Prometheus registry for engine-layer observables.

The frontend registry (``frontend/metrics.py``) covers the HTTP edge; this
one covers what happens *behind* it, per worker process:

- **Step composition** — the fused-dispatch shape of the last engine step
  (decode rows vs prefill chunk rows/tokens, from ``core.last_step_info``)
  plus the cumulative mixed-step / stall-violation counts that quantify the
  stall-free invariant.
- **Page pool** — utilization, fragmentation (reclaimable-but-cached share
  of idle pages), prefix-cache hit ratio, preemptions.
- **Admission** — requests waiting/running, intake rejections, and the
  disagg prefill queue depth.
- **KV transfer** — cumulative blocks/bytes and a per-phase duration
  histogram (``gather|pack|wire|scatter``) fed by the disagg wire path.

Every family carries a ``worker`` label so the frontend can federate many
workers' registries into one ``/metrics`` document without sample
collisions. Everything that has a cheap engine-side source of truth is
synced on scrape (the ``kernel_fallbacks`` idiom) rather than
double-counted; only the phase histogram is observed at record time.
``render()`` is async so the prefill queue depth (a discovery-store scan)
can be polled during the scrape.
"""

from __future__ import annotations

import logging
import weakref
from typing import Any, Awaitable, Callable

from prometheus_client import Counter, CollectorRegistry, Gauge, Histogram, generate_latest

logger = logging.getLogger(__name__)

_PHASE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)

#: KV-transfer phases tracked by the wire-path histogram.
KV_PHASES = ("gather", "pack", "wire", "scatter")


class EngineMetrics:
    """Per-worker engine telemetry registry.

    Bind engine internals after construction (``bind_core`` / ``bind_transfer``
    / ``bind_queue_depth``); unbound families simply stay at their defaults,
    so the registry is safe to serve from any worker role.
    """

    def __init__(self, registry: CollectorRegistry | None = None, *, worker: str = "local") -> None:
        self.registry = registry or CollectorRegistry()
        self.worker = worker
        ns = "dynamo_engine"

        def gauge(name: str, doc: str) -> Gauge:
            return Gauge(name, doc, ["worker"], registry=self.registry).labels(worker)

        # Step composition: the last fused dispatch's shape. Gauges, not
        # counters — the interesting signal is the *mix* per step.
        self.step_decode_rows = gauge(f"{ns}_step_decode_rows", "Decode rows in the last engine step")
        self.step_chunk_rows = gauge(f"{ns}_step_chunk_rows", "Prefill chunk rows in the last engine step")
        self.step_chunk_tokens = gauge(f"{ns}_step_chunk_tokens", "Prefill tokens in the last engine step")
        self.step_decodable = gauge(f"{ns}_step_decodable_seqs", "Sequences decodable at the last step")
        # Cumulative engine counters, synced from the core on scrape (the
        # core already counts; a prometheus Counter would double-book).
        self.mixed_steps = gauge(f"{ns}_mixed_steps_total", "Engine steps that fused prefill chunks with decodes")
        self.stall_violations = gauge(
            f"{ns}_stall_violations_total", "Prefill-only dispatches that starved decodable sequences"
        )
        self.preemptions = gauge(f"{ns}_preemptions_total", "Sequences preempted (pages reclaimed under pressure)")
        self.admission_rejections = gauge(f"{ns}_admission_rejections_total", "Requests refused at engine intake")
        self.spec_tokens_proposed = gauge(
            f"{ns}_spec_tokens_proposed_total", "Draft tokens proposed by the speculative decoder"
        )
        self.spec_tokens_accepted = gauge(
            f"{ns}_spec_tokens_accepted_total", "Draft tokens verified and emitted by the speculative decoder"
        )
        # Page pool.
        self.pages_total = gauge(f"{ns}_pages_total", "Allocatable KV pages")
        self.pages_free = gauge(f"{ns}_pages_free", "Pages on the free list")
        self.pages_cached = gauge(f"{ns}_pages_cached", "Evictable prefix-cache pages (refcount 0)")
        self.pages_active = gauge(f"{ns}_pages_active", "Pages referenced by live sequences")
        self.page_utilization = gauge(f"{ns}_page_utilization_ratio", "active_pages / total_pages")
        self.page_fragmentation = gauge(
            f"{ns}_page_fragmentation_ratio",
            "cached / (free + cached): share of idle pages reclaimable only by eviction",
        )
        self.cache_hit_ratio = gauge(f"{ns}_prefix_cache_hit_ratio", "Prefix-cache block hit ratio (cumulative)")
        # Admission / scheduler occupancy.
        self.requests_waiting = gauge(f"{ns}_requests_waiting", "Admitted requests not yet scheduled")
        self.requests_running = gauge(f"{ns}_requests_running", "Sequences in prefill or decode")
        # SLO admission-control plane (dynamo_tpu/sched). Per-tier queue
        # depth and per-tenant throttle counts are labelled clear-then-set
        # gauges (label sets change as tenants come and go); the rest sync
        # from the controller's cumulative counters on scrape.
        self._admission_queue_depth = Gauge(
            "dynamo_engine_admission_queue_depth",
            "Waiting requests per priority tier in the engine admission queue "
            "(tier 0 = most latency-sensitive; all waiting under tier 0 when "
            "the SLO plane is off)",
            ["worker", "tier"], registry=self.registry,
        )
        self.deadline_misses = gauge(
            f"{ns}_deadline_misses_total",
            "Requests admitted after their EDF deadline (arrival + stretched "
            "TTFT budget) had already passed",
        )
        self._tenant_throttled = Gauge(
            "dynamo_tenant_throttled_total",
            "Admission deferrals charged to a tenant's quota (token bucket "
            "empty or in-flight token cap reached)",
            ["worker", "tenant"], registry=self.registry,
        )
        self.chunk_budget_tokens = gauge(
            f"{ns}_chunk_budget_tokens",
            "Live per-step prefill chunk budget (the ITL-driven controller's "
            "current value; the static chunk_prefill_tokens config when the "
            "SLO plane is off)",
        )
        # XLA compile observability: first executions per (program, reason),
        # synced from the runner's CompileTracker on scrape. Labelled gauge
        # (not Counter) for the same no-double-booking reason as above; the
        # label set is cleared and re-set per scrape so stale pairs drop out.
        self._recompiles = Gauge(
            "dynamo_engine_recompiles_total",
            "First executions of a padded shape bucket per jitted program "
            "(reason: new_shape = compiled on the serving path, warm_cache = "
            "first-seen but fast, e.g. persistent-cache hit)",
            ["worker", "program", "reason"], registry=self.registry,
        )
        # Attention dispatch path per engine step, synced from the core's
        # cumulative counts on scrape. Same clear-then-set idiom as
        # recompiles so stale (phase, path) pairs drop out.
        self._attn_dispatch = Gauge(
            "dynamo_engine_attn_dispatch_steps_total",
            "Engine steps by attention phase (decode/verify/prefill) and "
            "dispatch path (pallas kernel, reference fallback, ring)",
            ["worker", "phase", "path"], registry=self.registry,
        )
        # Overlapped execution (DYN_OVERLAP): device-idle observability.
        # gap_ms is the host window between a step returning and the next
        # dispatch — the time the overlapped loop exists to hide.
        self.step_gap_ms_last = gauge(
            f"{ns}_step_gap_ms",
            "Host gap (ms) between the previous engine step completing and "
            "the latest step's dispatch (detok/stop/schedule time the device "
            "sits idle unless the overlapped loop hides it)",
        )
        self.step_gap_ms_mean = gauge(
            f"{ns}_step_gap_ms_mean",
            "Mean host gap (ms) between consecutive engine steps (cumulative)",
        )
        self._overlap_steps = Gauge(
            "dynamo_engine_overlap_steps_total",
            "Engine steps by overlapped-execution mode while DYN_OVERLAP is "
            "armed: 'overlapped' = a chained lookahead step was dispatched "
            "before harvesting the previous one, 'barrier' = the step fell "
            "back to the synchronous path (composition change, fill, spec, "
            "constraints, penalties)",
            ["worker", "mode"], registry=self.registry,
        )
        self._overlap_barriers = Gauge(
            "dynamo_engine_overlap_barrier_total",
            "Overlap barrier steps by the condition that forced them: "
            "'cancel'/'drain' (in-flight state invalidated), 'spec' (verify "
            "harvest or DYN_OVERLAP_SPEC off), 'prefill' (whole-prompt XOR "
            "mode), 'constraint' (lookahead disabled), 'constraint_miss' "
            "(mask-cache miss or successor fan-out over the lookahead cap), "
            "'runner' (runner cannot chain), 'pages' (lookahead page "
            "reservation failed), 'fill'/'idle' (nothing to chain)",
            ["worker", "reason"], registry=self.registry,
        )
        # Constrained-decode lookahead mask cache (DYN_CONSTRAINT_LOOKAHEAD_
        # TOKENS): hit/miss totals synced from the engine's TokenMaskCache on
        # scrape. The miss rate is the live predictor of 'constraint_miss'
        # barriers — a hot grammar converges to ~100% hits after warm-up.
        self.constraint_mask_cache_hits = gauge(
            f"{ns}_constraint_mask_cache_hits_total",
            "Constrained-decode token-mask cache hits (mask reused for a "
            "machine-state summary already built)",
        )
        self.constraint_mask_cache_misses = gauge(
            f"{ns}_constraint_mask_cache_misses_total",
            "Constrained-decode token-mask cache misses (mask built by "
            "scanning the vocabulary for a new machine-state summary)",
        )
        # Async tier onboarding (DYN_ASYNC_ONBOARD / DYN_CACHE_AWARE):
        # per-tier landed page counts are clear-then-set labelled gauges
        # synced from the core's cumulative dict; the wait histogram is
        # observed from drained per-session samples at scrape time (each
        # session observed exactly once).
        self._onboard_pages = Gauge(
            "dynamo_engine_prefix_onboard_pages_total",
            "KV pages onboarded from the capacity tiers into device pages, "
            "by source tier (g2 host / g3 disk / g4 remote)",
            ["worker", "tier"], registry=self.registry,
        )
        self.onboard_shortfall = gauge(
            f"{ns}_prefix_onboard_shortfall_pages_total",
            "Probed tier pages whose payload fetch came up short (evicted or "
            "faulted between probe and fetch) and fell back to recompute",
        )
        self._onboard_wait = Histogram(
            "dynamo_engine_onboard_wait_seconds",
            "Wall time from onboarding-session start (admission) to its "
            "payloads landing in device pages",
            ["worker"], buckets=_PHASE_BUCKETS, registry=self.registry,
        )
        self._constraint_build = Histogram(
            "dynamo_engine_constraint_mask_build_seconds",
            "Wall time of each cold constrained-decoding mask build (a "
            "machine summary seen for the first time; warm steps are dict "
            "lookups and are not observed)",
            ["worker"], buckets=_PHASE_BUCKETS, registry=self.registry,
        )
        # Time-loss accounting (attribution plane): cumulative seconds the
        # engine charged per loss cause (attribution.LOSS_CAUSES = the pinned
        # barrier vocabulary + queue/admission/onboard_stall/preempt/
        # recompile/gap), plus the step-time totals consumers need to derive
        # non-compute wall time (wall + gap - dispatch) and the unattributed
        # residual. True monotone Counters (so Prometheus rate()/increase()
        # are valid and the ``_total`` sample suffix is honest): each scrape
        # incs by the delta of the core's cumulative ledger since the last
        # sync (tracked in ``_lost_time_synced``/``_step_time_synced``, reset
        # by bind_core so a rebound core's full totals land once).
        self._lost_time = Counter(
            "dynamo_engine_lost_time_seconds",
            "Wall-clock seconds the engine attributes to a latency loss "
            "cause: overlap barrier reasons plus queue (pre-admission "
            "resource wait), admission (quota-gated deferral), onboard_stall "
            "(steps idled on a tier fetch), preempt, recompile (new-shape "
            "compiles on the serving path), and gap (residual host time "
            "between dispatches)",
            ["worker", "cause"], registry=self.registry,
        )
        self._step_time = Counter(
            "dynamo_engine_step_time_seconds",
            "Cumulative engine step time by kind: wall (in-step wall clock), "
            "dispatch (runner dispatch inside steps; equals wall on runners "
            "without a compile tracker), gap (host gap between steps) — "
            "non-compute wall time = wall + gap - dispatch",
            ["worker", "kind"], registry=self.registry,
        )
        self._step_kinds = Counter(
            "dynamo_engine_step_kind_steps",
            "Engine steps recorded, by step kind (mixed / prefill / decode / "
            "drain) — the step-kind histogram behind EngineCore.loss_snapshot",
            ["worker", "kind"], registry=self.registry,
        )
        self._lost_time_synced: dict[str, float] = {}
        self._step_time_synced: dict[str, float] = {}
        self._step_kinds_synced: dict[str, int] = {}
        # Device-cost plane (observability/cost.py): roofline fraction per
        # step kind from the XLA cost-analysis ledger joined with measured
        # dispatch wall, plus true monotone byte/flop Counters delta-synced
        # from the registry's cumulative totals (same watermark scheme as
        # the lost-time Counter; a retroactive downward estimate correction
        # never decrements — the watermark holds until totals regrow).
        self._roofline = Gauge(
            "dynamo_engine_roofline_frac",
            "Achieved fraction of the chip's peak on the binding resource "
            "per step kind (prefill / decode / mixed / spec_verify); the "
            "bound label names the binding side (memory = HBM bandwidth, "
            "compute = FLOP/s). Peaks come from DYN_PEAK_HBM_GBPS / "
            "DYN_PEAK_TFLOPS or the built-in per-chip table",
            ["worker", "step_kind", "bound"], registry=self.registry,
        )
        self._hbm_bytes = Counter(
            "dynamo_engine_hbm_bytes",
            "HBM bytes moved by engine dispatches per XLA cost analysis "
            "(model-derived estimate until the background extraction "
            "lands), by step kind",
            ["worker", "step_kind"], registry=self.registry,
        )
        self._flops = Counter(
            "dynamo_engine_flops",
            "Floating-point operations executed by engine dispatches per "
            "XLA cost analysis, by step kind",
            ["worker", "step_kind"], registry=self.registry,
        )
        self._cost_synced: dict[tuple[str, str], float] = {}
        # Anomaly sentinel: 1 while a rolling-window detector is active on
        # this worker (hysteresis in the sentinel, not here), keyed by the
        # detector kind; fired totals count rising edges ever.
        self._anomaly_active = Gauge(
            "dynamo_anomaly_active",
            "1 while the worker's anomaly sentinel holds this detector "
            "active (barrier_frac_spike, step_gap_regression, goodput_drop, "
            "recompile_storm, onboard_shortfall_burst)",
            ["worker", "kind"], registry=self.registry,
        )
        self._anomaly_fired = Gauge(
            "dynamo_anomaly_fired_total",
            "Anomaly-sentinel rising edges ever fired, by detector kind",
            ["worker", "kind"], registry=self.registry,
        )
        self._incidents_captured = Gauge(
            "dynamo_incidents_captured_total",
            "Incident bundles this engine wrote to the on-disk store, by "
            "trigger kind (anomaly / crash / slo_burn)",
            ["worker", "kind"], registry=self.registry,
        )
        self.prefill_queue_depth = gauge(
            f"{ns}_prefill_queue_depth", "Unclaimed tasks in the distributed prefill queue"
        )
        self.prefill_requeues = gauge(
            f"{ns}_prefill_requeues_total",
            "Prefill tasks this worker claimed that a failed peer had already been delivered "
            "(requeue-to-peer via claim release or claim-lease expiry)",
        )
        # KV transfer (disagg prefill -> decode migration).
        self.kv_blocks = gauge("dynamo_kv_transfer_blocks_total", "KV blocks ingested into the local cache")
        self.kv_bytes = gauge("dynamo_kv_transfer_bytes_total", "KV bytes received over the transfer path")
        self.kv_streams = gauge("dynamo_kv_transfer_streams_in_flight", "Open v2 chunk-stream sessions")
        self.kv_crc_failures = gauge(
            "dynamo_kv_transfer_crc_failures_total",
            "KV wire payloads that failed the receiver-side crc32 check",
        )
        self.kv_rollbacks = gauge(
            "dynamo_kv_transfer_rollbacks_total",
            "v2 chunk-stream sessions rolled back (sender death, protocol error, unrecovered corruption)",
        )
        self._kv_phase = Histogram(
            "dynamo_kv_transfer_phase_seconds",
            "Per-phase KV transfer duration (sender gather/pack/wire, receiver scatter)",
            ["worker", "phase"], buckets=_PHASE_BUCKETS, registry=self.registry,
        )
        # KV wire v3 (striped duplex data plane).
        self.kv_wire_streams = gauge(
            "dynamo_kv_wire_streams",
            "Open striped KV data-plane connections (wire v3 stripes) on this worker",
        )
        self.kv_wire_sessions = gauge(
            "dynamo_kv_wire_inflight_sessions",
            "KV transfer sessions currently in flight on this worker (v2 + v3)",
        )
        self.kv_wire_staged = gauge(
            "dynamo_kv_wire_staged_bytes",
            "Host bytes held in out-of-order reassembly staging across sessions "
            "(bounded by DYN_KV_WIRE_INFLIGHT)",
        )
        # Which path served each transfer: device_colocated / device_pull /
        # host_striped / host_chunked / host_monolithic. Clear-then-set
        # labelled gauges synced from the service's cumulative counters.
        self._kv_path_bytes = Gauge(
            "dynamo_kv_wire_path_bytes_total",
            "KV bytes ingested per transfer path (device-pull vs host-striped "
            "vs host-chunked fallback ladder)",
            ["worker", "path"], registry=self.registry,
        )
        self._kv_path_transfers = Gauge(
            "dynamo_kv_wire_path_transfers_total",
            "Completed KV transfers per transfer path",
            ["worker", "path"], registry=self.registry,
        )
        self._core: Any = None
        self._transfer: Any = None
        self._queue_depth_fn: Callable[[], Awaitable[int]] | None = None
        self._queue: Any = None

    def observe_phase(self, phase: str, seconds: float) -> None:
        self._kv_phase.labels(self.worker, phase).observe(max(0.0, seconds))

    # -- binding -----------------------------------------------------------

    def bind_core(self, core: Any) -> "EngineMetrics":
        self._core = core
        # A fresh core's cumulative ledgers restart at zero; resetting the
        # sync watermarks makes its totals land as new Counter increments
        # (process-lifetime accumulation across cores, proper monotone).
        self._lost_time_synced.clear()
        self._step_time_synced.clear()
        self._step_kinds_synced.clear()
        self._cost_synced.clear()
        return self

    def bind_transfer(self, transfer: Any) -> "EngineMetrics":
        self._transfer = transfer
        return self

    def bind_queue_depth(self, fn: Callable[[], Awaitable[int]]) -> "EngineMetrics":
        """``fn`` is awaited per scrape (e.g. ``DistributedQueue.depth``)."""
        self._queue_depth_fn = fn
        return self

    def bind_queue(self, queue: Any) -> "EngineMetrics":
        """Bind a ``DistributedQueue``: depth is polled per scrape and the
        redelivery (requeue) counter is synced per scrape."""
        self._queue = queue
        self._queue_depth_fn = queue.depth
        return self

    # -- scrape ------------------------------------------------------------

    def _sync_core(self) -> None:
        core = self._core
        if core is None:
            return
        info = getattr(core, "last_step_info", None) or {}
        self.step_decode_rows.set(info.get("decode_rows", 0))
        self.step_chunk_rows.set(info.get("chunk_rows", 0))
        self.step_chunk_tokens.set(info.get("chunk_tokens", 0))
        self.step_decodable.set(info.get("decodable", 0))
        self.mixed_steps.set(getattr(core, "mixed_steps", 0))
        self.stall_violations.set(getattr(core, "stall_violations", 0))
        self.preemptions.set(getattr(core, "num_preemptions", 0))
        self.admission_rejections.set(getattr(core, "admission_rejections", 0))
        self.spec_tokens_proposed.set(getattr(core, "spec_tokens_proposed", 0))
        self.spec_tokens_accepted.set(getattr(core, "spec_tokens_accepted", 0))
        stats = core.allocator.stats()
        self.pages_total.set(stats.total_pages)
        self.pages_free.set(stats.free_pages)
        self.pages_cached.set(stats.cached_pages)
        self.pages_active.set(stats.active_pages)
        self.page_utilization.set(stats.active_pages / stats.total_pages if stats.total_pages else 0.0)
        idle = stats.free_pages + stats.cached_pages
        self.page_fragmentation.set(stats.cached_pages / idle if idle else 0.0)
        self.cache_hit_ratio.set(stats.hit_rate)
        self.requests_waiting.set(len(getattr(core, "waiting", ())))
        self.requests_running.set(len(getattr(core, "running", ())) + len(getattr(core, "prefilling", ())))
        adm = getattr(core, "admission", None)
        self._admission_queue_depth.clear()
        if adm is not None:
            for tier, n in adm.queue_depth_by_tier(core.waiting).items():
                self._admission_queue_depth.labels(self.worker, str(tier)).set(n)
            self.deadline_misses.set(adm.deadline_misses)
            self._tenant_throttled.clear()
            for tenant, n in adm.tenants.throttled.items():
                self._tenant_throttled.labels(self.worker, tenant).set(n)
        else:
            self._admission_queue_depth.labels(self.worker, "0").set(
                len(getattr(core, "waiting", ()))
            )
            self.deadline_misses.set(0)
        cb = getattr(core, "chunk_budget_tokens", None)
        if callable(cb):
            self.chunk_budget_tokens.set(cb())
        tracker = getattr(getattr(core, "runner", None), "compile_tracker", None)
        if tracker is not None:
            self._recompiles.clear()
            for (program, reason), n in tracker.counts().items():
                self._recompiles.labels(self.worker, program, reason).set(n)
        cost_reg = getattr(getattr(core, "runner", None), "cost_registry", None)
        if cost_reg is not None:
            self._roofline.clear()
            for step_kind, row in cost_reg.ledger().items():
                self._roofline.labels(
                    self.worker, step_kind, row.get("bound") or "memory"
                ).set(float(row.get("roofline_frac", 0.0)))
            for step_kind, tot in cost_reg.totals().items():
                for fam, counter in (
                    ("bytes", self._hbm_bytes), ("flops", self._flops),
                ):
                    cur = float(tot.get(fam, 0.0))
                    prev = self._cost_synced.get((step_kind, fam), 0.0)
                    if cur > prev:
                        counter.labels(self.worker, step_kind).inc(cur - prev)
                        self._cost_synced[(step_kind, fam)] = cur
        dispatch = getattr(core, "attn_dispatch_counts", None)
        if dispatch is not None:
            self._attn_dispatch.clear()
            for (phase, path), n in dispatch.items():
                self._attn_dispatch.labels(self.worker, phase, path).set(n)
        self.step_gap_ms_last.set(getattr(core, "step_gap_ms_last", 0.0))
        gap_n = getattr(core, "step_gap_ms_count", 0)
        self.step_gap_ms_mean.set(
            getattr(core, "step_gap_ms_sum", 0.0) / gap_n if gap_n else 0.0
        )
        overlap_counts = getattr(core, "overlap_step_counts", None)
        if overlap_counts is not None:
            self._overlap_steps.clear()
            for mode, n in overlap_counts.items():
                self._overlap_steps.labels(self.worker, mode).set(n)
        barrier_counts = getattr(core, "overlap_barrier_counts", None)
        if barrier_counts is not None:
            self._overlap_barriers.clear()
            for reason, n in barrier_counts.items():
                self._overlap_barriers.labels(self.worker, reason).set(n)
        self.constraint_mask_cache_hits.set(getattr(core, "constraint_mask_cache_hits", 0))
        self.constraint_mask_cache_misses.set(getattr(core, "constraint_mask_cache_misses", 0))
        onboard_counts = getattr(core, "onboard_page_counts", None)
        if onboard_counts is not None:
            self._onboard_pages.clear()
            for tier, n in onboard_counts.items():
                self._onboard_pages.labels(self.worker, tier).set(n)
        self.onboard_shortfall.set(getattr(core, "onboard_shortfall_pages", 0))
        drain = getattr(core, "drain_onboard_waits", None)
        if callable(drain):
            for wait_s in drain():
                self._onboard_wait.labels(self.worker).observe(max(0.0, wait_s))
        drain_builds = getattr(core, "drain_constraint_build_seconds", None)
        if callable(drain_builds):
            for build_s in drain_builds():
                self._constraint_build.labels(self.worker).observe(max(0.0, build_s))
        lost = getattr(core, "lost_time_ms", None)
        if lost is not None:
            for cause, ms in lost.items():
                prev = self._lost_time_synced.get(cause, 0.0)
                if ms > prev:
                    self._lost_time.labels(self.worker, cause).inc((ms - prev) / 1e3)
                    self._lost_time_synced[cause] = ms
            step_totals = (
                ("wall", getattr(core, "step_wall_ms_total", 0.0)),
                ("dispatch", getattr(core, "step_dispatch_ms_total", 0.0)),
                ("gap", getattr(core, "step_gap_ms_sum", 0.0)),
            )
            for kind, ms in step_totals:
                prev = self._step_time_synced.get(kind, 0.0)
                if ms > prev:
                    self._step_time.labels(self.worker, kind).inc((ms - prev) / 1e3)
                    self._step_time_synced[kind] = ms
        kind_counts = getattr(core, "step_kind_counts", None)
        if kind_counts is not None:
            for kind, n in kind_counts.items():
                prev = self._step_kinds_synced.get(kind, 0)
                if n > prev:
                    self._step_kinds.labels(self.worker, kind).inc(n - prev)
                    self._step_kinds_synced[kind] = n
        sentinel = getattr(core, "sentinel", None)
        if sentinel is not None:
            self._anomaly_active.clear()
            for kind in getattr(sentinel, "active", {}):
                self._anomaly_active.labels(self.worker, kind).set(1)
            self._anomaly_fired.clear()
            for kind, n in getattr(sentinel, "fired", {}).items():
                self._anomaly_fired.labels(self.worker, kind).set(n)
        incidents = getattr(core, "incidents", None)
        if incidents is not None:
            self._incidents_captured.clear()
            for kind, n in getattr(incidents, "captured", {}).items():
                self._incidents_captured.labels(self.worker, kind).set(n)

    def _sync_transfer(self) -> None:
        if self._transfer is None:
            return
        stats = self._transfer.stats()
        self.kv_blocks.set(stats.get("blocks", 0))
        self.kv_bytes.set(stats.get("bytes", 0))
        self.kv_streams.set(stats.get("streams_in_flight", 0))
        self.kv_crc_failures.set(stats.get("crc_failures", 0))
        self.kv_rollbacks.set(stats.get("rollbacks", 0))
        self.kv_wire_streams.set(stats.get("wire_conns", 0))
        self.kv_wire_sessions.set(stats.get("streams_in_flight", 0))
        self.kv_wire_staged.set(stats.get("staged_bytes", 0))
        paths = stats.get("paths")
        if paths is not None:
            self._kv_path_bytes.clear()
            self._kv_path_transfers.clear()
            for path, d in paths.items():
                self._kv_path_bytes.labels(self.worker, path).set(d.get("bytes", 0))
                self._kv_path_transfers.labels(self.worker, path).set(d.get("transfers", 0))

    async def render(self) -> bytes:
        self._sync_core()
        self._sync_transfer()
        if self._queue is not None:
            self.prefill_requeues.set(getattr(self._queue, "requeues", 0))
        if self._queue_depth_fn is not None:
            try:
                self.prefill_queue_depth.set(await self._queue_depth_fn())
            except Exception:
                logger.exception("prefill queue depth probe failed")
        return generate_latest(self.registry)


# -- KV-phase observation hook ------------------------------------------------
#
# The wire path (disagg/transfer.py) measures phases deep inside free
# functions; threading a metrics object through every call would couple the
# transfer protocol to the telemetry plane. Instead the worker installs its
# EngineMetrics once at bring-up and the transfer code calls
# observe_kv_phase() — a no-op until something is installed.
#
# Routing is keyed per engine core: install() registers the metrics under
# its bound core (weakly — a retired core drops its route with its last
# reference), and call sites that know their core pass it so several
# in-process workers (run_local) each attribute their own phases. The
# last-installed registry remains the fallback for core-less call sites.

_installed: EngineMetrics | None = None
_by_core: "weakref.WeakKeyDictionary[Any, EngineMetrics]" = weakref.WeakKeyDictionary()


def install(metrics: EngineMetrics | None) -> None:
    global _installed
    if metrics is not None and getattr(metrics, "_core", None) is not None:
        try:
            _by_core[metrics._core] = metrics
        except TypeError:  # core type without weakref support (test doubles)
            pass
    _installed = metrics


def installed() -> EngineMetrics | None:
    return _installed


def observe_kv_phase(phase: str, seconds: float, *, core: Any = None) -> None:
    m = None
    if core is not None:
        try:
            m = _by_core.get(core)
        except TypeError:  # core type without weakref support (test doubles)
            m = None
    if m is None:
        m = _installed
    if m is not None:
        try:
            m.observe_phase(phase, seconds)
        except Exception:
            logger.exception("kv phase observation failed")


# -- federation ---------------------------------------------------------------


def federate_text(parts: list[bytes]) -> bytes:
    """Merge rendered Prometheus texts into one legal document.

    Several processes exporting the same metric family each emit their own
    ``# HELP``/``# TYPE`` headers; Prometheus rejects duplicates, so keep the
    first header per family and pass every sample line through (sample
    uniqueness comes from the per-registry ``worker`` label).
    """
    seen_headers: set[tuple[str, str]] = set()
    out: list[str] = []
    for part in parts:
        for line in part.decode().splitlines():
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                kind, _, rest = line[2:].partition(" ")
                name = rest.split(" ", 1)[0]
                if (kind, name) in seen_headers:
                    continue
                seen_headers.add((kind, name))
            elif not line:
                continue
            out.append(line)
    return ("\n".join(out) + "\n").encode()
