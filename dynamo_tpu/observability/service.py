"""Worker telemetry endpoints + the frontend-side fan-out client.

Every worker serves extra runtime endpoints next to ``generate``:

- ``debug_traces`` (:class:`SpanQueryService`) — query the process-local
  span ring (``tracing.SPANS``) by request or trace id;
- ``metrics_scrape`` (:class:`MetricsScrapeService`) — render the process's
  :class:`~dynamo_tpu.observability.metrics.EngineMetrics` registry;
- ``debug_flight`` (:class:`FlightQueryService`) — the engine flight ring;
- ``debug_explain`` (:class:`ExplainQueryService`) — windowed STEP/COMPILE
  records + lost-time totals, the worker half of
  ``GET /debug/explain/{request_id}`` (``attribution.build_explain``);
- ``debug_incidents`` (:class:`IncidentQueryService`) — the worker's
  on-disk incident bundles (``observability/incidents.py``), the worker
  half of ``GET /debug/incidents[/{id}]``;
- ``debug_cost`` (:class:`CostQueryService`) — the runner's device-cost
  registry snapshot (``observability/cost.py``), the worker half of
  ``GET /debug/cost``;
- ``debug_profile`` (:class:`ProfileCaptureService`) — arms a bounded
  ``jax.profiler`` device trace on the worker, the worker half of
  ``POST /debug/profile/{worker}``.

They ride the same discovery + stream transport as serving traffic, so the
frontend needs no extra connectivity to reach them:
:class:`WorkerTelemetryClient` scans the ``instances/`` prefix for telemetry
endpoints and fans a query out to every live worker.
:func:`assemble_timeline` merges the union of span docs (frontend-local +
every worker's) into one ordered timeline — the body of
``GET /debug/traces/{request_id}``.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any, AsyncIterator

from dynamo_tpu.runtime.component import INSTANCE_PREFIX, DistributedRuntime, Instance
from dynamo_tpu.runtime.engine import AsyncEngine, Context

logger = logging.getLogger(__name__)

DEBUG_TRACES_ENDPOINT = "debug_traces"
METRICS_SCRAPE_ENDPOINT = "metrics_scrape"
FLIGHT_ENDPOINT = "debug_flight"
DEBUG_EXPLAIN_ENDPOINT = "debug_explain"
DEBUG_INCIDENTS_ENDPOINT = "debug_incidents"
COST_ENDPOINT = "debug_cost"
PROFILE_ENDPOINT = "debug_profile"

_FANOUT_TIMEOUT = 5.0


class SpanQueryService(AsyncEngine[Any, dict]):
    """Answers ``{"request_id"?, "trace_id"?}`` with this process's spans."""

    def __init__(self, *, host: str = "") -> None:
        self.host = host or f"pid-{os.getpid()}"

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        from dynamo_tpu.tracing import SPANS

        request = request or {}
        spans = SPANS.query(
            request_id=request.get("request_id"), trace_id=request.get("trace_id")
        )
        yield {"host": self.host, "spans": spans}


class MetricsScrapeService(AsyncEngine[Any, dict]):
    """Answers any request with the worker's rendered Prometheus text."""

    def __init__(self, metrics) -> None:
        self.metrics = metrics

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        yield {"text": (await self.metrics.render()).decode()}


class FlightQueryService(AsyncEngine[Any, dict]):
    """Answers ``{"last"?: N, "kind"?: str}`` with this worker's flight ring.

    ``worker`` is the engine worker id the frontend addresses
    (``GET /debug/flight/{worker}``) — the client fans out to every flight
    endpoint and filters on this field, so no instance-id mapping is needed.
    """

    def __init__(self, flight, *, worker: str = "") -> None:
        self.flight = flight
        self.worker = worker or f"pid-{os.getpid()}"

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        request = request or {}
        last = request.get("last")
        records = self.flight.snapshot(
            last=int(last) if last is not None else None,
            kind=request.get("kind"),
        )
        yield {"worker": self.worker, "records": records}


class ExplainQueryService(AsyncEngine[Any, dict]):
    """Answers ``{"t0"?, "t1"?}`` with this worker's attribution inputs.

    Returns the flight ring's STEP/COMPILE records (optionally windowed to
    ``[t0, t1]`` wall-clock seconds — the frontend passes the request's span
    bounds so the payload stays proportional to the request, not the ring)
    plus the engine's cumulative per-cause lost-time totals. The per-request
    join happens on the frontend (``attribution.build_explain``): flight
    records carry no request ids, so windowing is the only per-request cut a
    worker can make.
    """

    def __init__(self, core, *, worker: str = "") -> None:
        self.core = core
        self.worker = worker or f"pid-{os.getpid()}"

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        from dynamo_tpu.config import load_attrib_settings
        from dynamo_tpu.observability.flight import COMPILE, STEP

        request = request or {}
        t0 = request.get("t0")
        t1 = request.get("t1")

        def in_window(rec: dict) -> bool:
            ts = rec.get("ts") or 0.0
            return (t0 is None or ts >= float(t0)) and (t1 is None or ts <= float(t1))

        max_steps = load_attrib_settings().max_steps
        steps = [r for r in self.core.flight.snapshot(kind=STEP) if in_window(r)]
        compiles = [r for r in self.core.flight.snapshot(kind=COMPILE) if in_window(r)]
        yield {
            "worker": self.worker,
            "steps": steps[-max_steps:],
            "compiles": compiles,
            "lost_time_ms": {
                k: round(v, 3)
                for k, v in (getattr(self.core, "lost_time_ms", None) or {}).items()
            },
        }


class IncidentQueryService(AsyncEngine[Any, dict]):
    """Answers ``{"id"?: str}`` with this worker's incident bundles.

    Without an id: bundle summaries (the store's ``list()`` view). With an
    id: the full bundle, or ``{"found": False}`` when it isn't here — the
    frontend fans the id out to every worker and keeps the one that has it.
    """

    def __init__(self, store, *, worker: str = "") -> None:
        self.store = store
        self.worker = worker or f"pid-{os.getpid()}"

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        request = request or {}
        incident_id = request.get("id")
        if incident_id:
            bundle = self.store.get(str(incident_id))
            yield {"worker": self.worker, "found": bundle is not None, "bundle": bundle}
        else:
            yield {"worker": self.worker, "incidents": self.store.list()}


class CostQueryService(AsyncEngine[Any, dict]):
    """Answers any request with the runner's device-cost registry snapshot.

    The snapshot is the ``GET /debug/cost`` body for one worker: chip peaks,
    the per-compiled-program cost table and the per-step-kind roofline
    ledger. A worker whose cost plane is disabled (``DYN_COST_PLANE=0``)
    answers ``{"enabled": False}`` rather than dropping off the fan-out —
    an operator must be able to tell "off" from "dead".
    """

    def __init__(self, runner, *, worker: str = "") -> None:
        self.runner = runner
        self.worker = worker or f"pid-{os.getpid()}"

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        registry = getattr(self.runner, "cost_registry", None)
        if registry is None:
            yield {"worker": self.worker, "enabled": False}
            return
        doc = registry.snapshot()
        doc["worker"] = self.worker
        yield doc


class ProfileCaptureService(AsyncEngine[Any, dict]):
    """Arms a bounded ``jax.profiler`` device trace on this worker.

    ``{"action": "status"}`` (or an empty request) reports availability and
    whether a trace is currently running. ``{"action": "capture",
    "duration_ms": N}`` traces the next N ms of device work (clamped to
    ``DYN_PROFILE_MAX_MS``) and returns the artifact directory plus a file
    summary. Single-flight is inherited from ``tracing.start_device_trace``
    — a second capture while one runs gets ``{"ok": False, "reason":
    "busy"}`` instead of queueing (profiles are operator actions; queueing
    them would silently serialize minutes of tracing). Refuses politely
    with ``reason: "profiler_unavailable"`` where ``jax.profiler`` cannot
    start a trace (e.g. stripped builds).
    """

    DEFAULT_DURATION_MS = 2000.0

    def __init__(self, *, worker: str = "") -> None:
        self.worker = worker or f"pid-{os.getpid()}"

    def _status(self) -> dict:
        from dynamo_tpu.observability.cost import (
            profile_artifact_dir,
            profile_max_ms,
            profiler_available,
        )
        from dynamo_tpu.tracing import trace_running

        return {
            "worker": self.worker,
            "available": profiler_available(),
            "running": trace_running(),
            "artifact_dir": profile_artifact_dir(),
            "max_duration_ms": profile_max_ms(),
        }

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        from dynamo_tpu.observability.cost import (
            profile_artifact_dir,
            profile_max_ms,
            profiler_available,
        )
        from dynamo_tpu.tracing import profile_for

        request = request or {}
        if request.get("action", "status") != "capture":
            yield self._status()
            return
        status = self._status()
        if not profiler_available():
            yield {**status, "ok": False, "reason": "profiler_unavailable"}
            return
        try:
            duration_ms = float(request.get("duration_ms") or self.DEFAULT_DURATION_MS)
        except (TypeError, ValueError):
            duration_ms = self.DEFAULT_DURATION_MS
        duration_ms = max(1.0, min(duration_ms, profile_max_ms()))
        log_dir = os.path.join(
            profile_artifact_dir(), f"{self.worker}-{int(time.time() * 1e3)}"
        )
        try:
            artifact = await profile_for(duration_ms / 1e3, log_dir)
        except Exception as exc:
            yield {
                **status, "ok": False, "reason": "capture_failed",
                "error": type(exc).__name__, "detail": str(exc)[:200],
            }
            return
        if artifact is None:
            yield {**status, "ok": False, "reason": "busy"}
            return
        files = []
        total_bytes = 0
        for root, _dirs, names in os.walk(artifact):
            for name in names:
                path = os.path.join(root, name)
                try:
                    total_bytes += os.path.getsize(path)
                except OSError:
                    continue
                files.append(os.path.relpath(path, artifact))
        yield {
            **status, "ok": True, "artifact": artifact,
            "duration_ms": duration_ms,
            "files": sorted(files)[:50], "file_count": len(files),
            "total_bytes": total_bytes,
        }


class WorkerTelemetryClient:
    """Frontend-side fan-out over every worker's telemetry endpoints.

    Discovery is a prefix scan per query (telemetry is off the request hot
    path; a live watch would be over-engineering): any instance record whose
    endpoint name matches is a target. Dead workers drop out with their
    lease like any other instance.
    """

    def __init__(self, runtime: DistributedRuntime, *, timeout: float = _FANOUT_TIMEOUT) -> None:
        self.runtime = runtime
        self.timeout = timeout
        #: Per-worker failed fan-out calls (dynamo_federation_scrape_failures_total).
        #: A failure here means the federated /metrics silently lost that
        #: worker's registry — which is exactly why it is counted.
        self.scrape_failures: dict[str, int] = {}
        #: The most recent failure, for the control tower: worker/error/ts.
        self.last_failure: dict[str, Any] | None = None

    async def _targets(self, endpoint: str) -> list[Instance]:
        records = await self.runtime.store.get_prefix(f"{INSTANCE_PREFIX}/")
        out = []
        for value in records.values():
            try:
                inst = Instance.from_bytes(value)
            except Exception:
                continue
            if inst.endpoint == endpoint:
                out.append(inst)
        return out

    async def _ask(self, inst: Instance, request: dict) -> dict | None:
        async def first() -> dict | None:
            stream = self.runtime.transport.generate(inst.address, request, Context())
            try:
                async for item in stream:
                    return item
                return None
            finally:
                await stream.aclose()

        try:
            return await asyncio.wait_for(first(), self.timeout)
        except Exception as exc:
            worker = f"{inst.instance_id:x}"
            self.scrape_failures[worker] = self.scrape_failures.get(worker, 0) + 1
            self.last_failure = {
                "worker": worker,
                "endpoint": inst.endpoint,
                "error": type(exc).__name__,
                "detail": str(exc)[:200],
                "ts": time.time(),
            }
            logger.warning("telemetry query to %s failed", worker, exc_info=True)
        return None

    async def collect_spans(self, *, request_id: str | None = None, trace_id: str | None = None) -> list[dict]:
        """The union of matching span docs across every live worker."""
        targets = await self._targets(DEBUG_TRACES_ENDPOINT)
        if not targets:
            return []
        results = await asyncio.gather(
            *(self._ask(t, {"request_id": request_id, "trace_id": trace_id}) for t in targets)
        )
        spans: list[dict] = []
        for inst, res in zip(targets, results):
            if res is None:
                continue
            for s in res.get("spans", []):
                s.setdefault("host", res.get("host", f"{inst.instance_id:x}"))
                spans.append(s)
        return spans

    async def collect_flight(
        self, *, worker: str | None = None, last: int | None = None, kind: str | None = None
    ) -> dict[str, list[dict]]:
        """Flight rings by worker id; ``worker`` filters to one (or ``"all"``/
        ``None`` for every worker)."""
        targets = await self._targets(FLIGHT_ENDPOINT)
        request: dict = {}
        if last is not None:
            request["last"] = last
        if kind is not None:
            request["kind"] = kind
        results = await asyncio.gather(*(self._ask(t, request) for t in targets))
        out: dict[str, list[dict]] = {}
        for inst, res in zip(targets, results):
            if res is None:
                continue
            wid = str(res.get("worker", f"{inst.instance_id:x}"))
            if worker not in (None, "all") and wid != worker:
                continue
            out[wid] = res.get("records", [])
        return out

    async def collect_explain(
        self, *, t0: float | None = None, t1: float | None = None
    ) -> list[dict]:
        """Every worker's windowed attribution inputs (steps + compiles)."""
        targets = await self._targets(DEBUG_EXPLAIN_ENDPOINT)
        request: dict = {}
        if t0 is not None:
            request["t0"] = t0
        if t1 is not None:
            request["t1"] = t1
        results = await asyncio.gather(*(self._ask(t, request) for t in targets))
        docs = []
        for inst, res in zip(targets, results):
            if res is None:
                continue
            res.setdefault("worker", f"{inst.instance_id:x}")
            docs.append(res)
        return docs

    async def collect_metrics_texts(self) -> list[bytes]:
        """Every worker's rendered registry (for /metrics federation)."""
        targets = await self._targets(METRICS_SCRAPE_ENDPOINT)
        results = await asyncio.gather(*(self._ask(t, {}) for t in targets))
        return [r["text"].encode() for r in results if r and "text" in r]

    async def collect_incidents(self) -> dict[str, list[dict]]:
        """Bundle summaries by worker id (the /debug/incidents listing)."""
        targets = await self._targets(DEBUG_INCIDENTS_ENDPOINT)
        results = await asyncio.gather(*(self._ask(t, {}) for t in targets))
        out: dict[str, list[dict]] = {}
        for inst, res in zip(targets, results):
            if res is None:
                continue
            wid = str(res.get("worker", f"{inst.instance_id:x}"))
            out[wid] = res.get("incidents", [])
        return out

    async def collect_cost(self) -> dict[str, dict]:
        """Device-cost snapshots by worker id (the /debug/cost body)."""
        targets = await self._targets(COST_ENDPOINT)
        results = await asyncio.gather(*(self._ask(t, {}) for t in targets))
        out: dict[str, dict] = {}
        for inst, res in zip(targets, results):
            if res is None:
                continue
            wid = str(res.pop("worker", f"{inst.instance_id:x}"))
            out[wid] = res
        return out

    async def profile_status(self, worker: str | None = None) -> dict[str, dict]:
        """Profile-capture availability by worker id (GET /debug/profile)."""
        targets = await self._targets(PROFILE_ENDPOINT)
        results = await asyncio.gather(
            *(self._ask(t, {"action": "status"}) for t in targets)
        )
        out: dict[str, dict] = {}
        for inst, res in zip(targets, results):
            if res is None:
                continue
            wid = str(res.pop("worker", f"{inst.instance_id:x}"))
            if worker not in (None, "all") and wid != worker:
                continue
            out[wid] = res
        return out

    async def capture_profile(self, worker: str, duration_ms: float) -> dict | None:
        """Arm a device trace on one worker; returns its capture doc.

        The capture blocks for the trace window, so the fan-out timeout is
        stretched to cover the requested duration plus generous slack: on a
        busy worker the service coroutine may not even be scheduled for
        seconds (synchronous jit dispatches block the loop), and a timeout
        here cancels the trace mid-window.
        """
        targets = await self._targets(PROFILE_ENDPOINT)
        saved_timeout = self.timeout
        self.timeout = max(saved_timeout, duration_ms / 1e3 + 60.0)
        try:
            for inst in targets:
                status = await self._ask(inst, {"action": "status"})
                if status is None:
                    continue
                wid = str(status.get("worker", f"{inst.instance_id:x}"))
                if wid != worker:
                    continue
                return await self._ask(
                    inst, {"action": "capture", "duration_ms": duration_ms}
                )
            return None
        finally:
            self.timeout = saved_timeout

    async def fetch_incident(self, incident_id: str) -> dict | None:
        """The full bundle for one id, from whichever worker holds it."""
        targets = await self._targets(DEBUG_INCIDENTS_ENDPOINT)
        results = await asyncio.gather(
            *(self._ask(t, {"id": incident_id}) for t in targets)
        )
        for res in results:
            if res and res.get("found"):
                return res.get("bundle")
        return None


def assemble_timeline(request_id: str, spans: list[dict]) -> dict:
    """One ordered timeline from the union of span docs.

    Spans from different processes share a trace_id but not a monotonic
    clock, so ordering uses the wall-clock ``start_ts``; ``offset_ms`` is
    relative to the earliest span (queue wait → router decision → prefill →
    KV phases → first decode step read top to bottom). ``children`` indexes
    restore the parent/child structure where ids link up. A span whose
    parent was evicted from the ring (span buffers are bounded) still
    surfaces at top level, flagged ``parent_evicted: true`` — orphans must
    never silently vanish from a postmortem.
    """
    spans = sorted(spans, key=lambda s: (s.get("start_ts") or 0.0, s.get("duration_ms") or 0.0))
    t0 = spans[0].get("start_ts", 0.0) if spans else 0.0
    by_id = {s.get("span_id"): i for i, s in enumerate(spans) if s.get("span_id")}
    out_spans = []
    for i, s in enumerate(spans):
        doc = dict(s)
        doc["offset_ms"] = round(((s.get("start_ts") or t0) - t0) * 1e3, 3)
        doc["children"] = [
            j for j, c in enumerate(spans) if c.get("parent_id") and c["parent_id"] == s.get("span_id")
        ]
        doc["root"] = s.get("parent_id") not in by_id or s.get("parent_id") is None
        if s.get("parent_id") is not None and s.get("parent_id") not in by_id:
            doc["parent_evicted"] = True
        out_spans.append(doc)
    trace_ids = sorted({s["trace_id"] for s in spans if s.get("trace_id")})
    return {
        "request_id": request_id,
        "trace_ids": trace_ids,
        "span_count": len(out_spans),
        "duration_ms": round(
            max(
                (s["offset_ms"] + (s.get("duration_ms") or 0.0) for s in out_spans),
                default=0.0,
            ),
            3,
        ),
        "spans": out_spans,
    }
