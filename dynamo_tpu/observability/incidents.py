"""Incident plane: capture-on-anomaly black-box bundles.

The anomaly sentinel (PR 15) can *detect* a barrier-fraction spike or a
recompile storm, but detection alone is worthless for unattended soak and
hardware campaigns: by the time a human looks, the flight ring has rotated
and the evidence is gone. This module makes detection self-preserving —
when a detector rises, a step crashes, or an SLO burn-rate alert fires, the
process snapshots a bounded **incident bundle** into a size-capped on-disk
store, so even a dead worker leaves a self-contained postmortem artifact.

A bundle is one JSON document:

- ``id`` / ``ts`` / ``kind`` / ``worker`` — identity; ``kind`` is one of
  :data:`INCIDENT_KINDS`;
- ``trigger`` — kind-specific evidence (anomaly kind/value/threshold, the
  crash exception, or the burn-rate window state);
- ``flight`` — the last ``incident.flight_last`` flight-ring records
  around the trigger (the black box);
- ``spans`` — finished request spans whose lifetime intersects the last
  ``incident.span_window_s`` seconds (from :data:`dynamo_tpu.tracing.SPANS`);
- ``loss`` — ``EngineCore.loss_snapshot()`` at capture time (engine-side
  bundles only);
- ``config`` — the active ``DYN_*`` environment plus the incident settings
  in force;
- ``device_trace`` — whether a device trace was armed and where it writes
  (``DYN_TRACE_DIR``-style profiling), so the XPlane dump can be joined.

The store (:class:`IncidentStore`) is bounded twice — bundle count and
total on-disk bytes — and evicts oldest-first, mirroring the flight ring's
discipline on disk. Capture never raises into the engine: it is
observability, not control flow. Knobs ride
:class:`~dynamo_tpu.config.IncidentSettings` (``DYN_INCIDENT_*``).

Bundles are listed/fetched remotely via the ``debug_incidents`` worker
endpoint (``observability/service.py``) and ``GET /debug/incidents[/{id}]``
on the frontend; ``python -m dynamo_tpu.top`` renders the fleet's recent
incidents live.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from typing import Any

from dynamo_tpu.config import IncidentSettings, load_incident_settings

logger = logging.getLogger(__name__)

#: Capture trigger kinds (the dynamo_incidents_captured_total{kind} labels).
INCIDENT_KINDS = ("anomaly", "crash", "slo_burn")


def default_incident_dir() -> str:
    return os.path.join(tempfile.gettempdir(), "dynamo-incidents")


class IncidentStore:
    """Size-capped on-disk bundle store (thread-safe).

    One JSON file per bundle, named ``<id>.json`` where the id embeds a
    millisecond timestamp + pid + per-process sequence — lexicographic
    filename order is capture order, which is what eviction sorts by.
    """

    def __init__(
        self,
        dir: str | None = None,
        *,
        max_bundles: int = 32,
        max_bytes: int = 16_000_000,
    ) -> None:
        self.dir = dir or default_incident_dir()
        self.max_bundles = max(1, int(max_bundles))
        self.max_bytes = max(1, int(max_bytes))
        self._lock = threading.Lock()
        self._seq = 0

    @classmethod
    def from_settings(cls, settings: IncidentSettings) -> "IncidentStore":
        return cls(
            settings.dir or None,
            max_bundles=settings.max_bundles,
            max_bytes=settings.max_bytes,
        )

    def _paths(self) -> list[str]:
        try:
            names = sorted(
                n for n in os.listdir(self.dir)
                if n.startswith("inc-") and n.endswith(".json")
            )
        except FileNotFoundError:
            return []
        return [os.path.join(self.dir, n) for n in names]

    def save(self, bundle: dict) -> str:
        """Persist one bundle; returns its id. Evicts oldest past the caps."""
        with self._lock:
            self._seq += 1
            incident_id = bundle.get("id") or (
                f"inc-{int(time.time() * 1e3):013d}-{os.getpid()}-{self._seq:04d}"
            )
            bundle = dict(bundle, id=incident_id)
            os.makedirs(self.dir, exist_ok=True)
            path = os.path.join(self.dir, f"{incident_id}.json")
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(bundle, f)
            os.replace(tmp, path)  # atomic: a reader never sees a torn bundle
            self._evict_locked()
        return incident_id

    def _evict_locked(self) -> None:
        paths = self._paths()
        sizes = {}
        for p in paths:
            try:
                sizes[p] = os.path.getsize(p)
            except OSError:
                sizes[p] = 0
        while paths and (
            len(paths) > self.max_bundles or sum(sizes[p] for p in paths) > self.max_bytes
        ):
            victim = paths.pop(0)  # oldest-first, the flight ring's discipline
            try:
                os.remove(victim)
            except OSError:
                pass
            logger.info("incident store evicted %s", os.path.basename(victim))

    def list(self) -> list[dict]:
        """Bundle summaries, oldest first: id/ts/kind/worker/trigger/bytes."""
        out: list[dict] = []
        for path in self._paths():
            try:
                with open(path) as f:
                    b = json.load(f)
                out.append(
                    {
                        "id": b.get("id", os.path.basename(path)[:-5]),
                        "ts": b.get("ts"),
                        "kind": b.get("kind"),
                        "worker": b.get("worker", ""),
                        "trigger": b.get("trigger", {}),
                        "bytes": os.path.getsize(path),
                    }
                )
            except (OSError, ValueError):
                continue  # torn/evicted mid-read: skip, never raise
        return out

    def get(self, incident_id: str) -> dict | None:
        if "/" in incident_id or incident_id.startswith("."):
            return None  # ids are filenames: refuse traversal
        path = os.path.join(self.dir, f"{incident_id}.json")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def __len__(self) -> int:
        return len(self._paths())


def _config_snapshot(settings: IncidentSettings) -> dict:
    import dataclasses

    return {
        "env": {k: v for k, v in sorted(os.environ.items()) if k.startswith("DYN_")},
        "incident": dataclasses.asdict(settings),
    }


def _device_trace_state() -> dict:
    """Live profile-capture state, not just a static env snapshot: whether a
    trace is running NOW, whether ``jax.profiler`` could start one (the
    ``POST /debug/profile/{worker}`` follow-up an operator reaches for on a
    ``recompile_storm`` / ``step_gap_regression`` bundle), and where
    artifacts land."""
    from dynamo_tpu import tracing
    from dynamo_tpu.observability.cost import profile_artifact_dir, profiler_available

    return {
        "armed": tracing.trace_running(),
        "dir": os.environ.get("DYN_TRACE_DIR"),
        "capture_available": profiler_available(),
        "artifact_dir": profile_artifact_dir(),
    }


class IncidentCapture:
    """Assembles and persists bundles; owned per engine (or per frontend).

    ``capture()`` is called from rising edges on hot-adjacent paths
    (sentinel ``_update``, the step crash handler) — it never raises, and a
    per-kind cooldown keeps a flapping detector from flooding the store.
    """

    def __init__(
        self,
        settings: IncidentSettings | None = None,
        *,
        store: IncidentStore | None = None,
        worker: str = "",
        core: Any = None,
        flight: Any = None,
    ) -> None:
        self.settings = settings or load_incident_settings()
        self.store = store or IncidentStore.from_settings(self.settings)
        self.worker = worker
        self.core = core
        self.flight = flight
        #: trigger kind -> bundles written (dynamo_incidents_captured_total).
        self.captured: dict[str, int] = {}
        self._last: dict[str, float] = {}  # cooldown key -> monotonic stamp

    def capture(self, kind: str, trigger: dict) -> str | None:
        """Snapshot one bundle; returns its id (None when skipped/failed)."""
        if not self.settings.enable:
            return None
        try:
            return self._capture(kind, trigger)
        except Exception:
            logger.exception("incident capture failed (ignored)")
            return None

    def _capture(self, kind: str, trigger: dict) -> str | None:
        cooldown_key = f"{kind}:{trigger.get('anomaly', trigger.get('alert', ''))}"
        now = time.monotonic()
        last = self._last.get(cooldown_key)
        if last is not None and now - last < self.settings.cooldown_s:
            logger.info("incident capture for %s suppressed by cooldown", cooldown_key)
            return None
        self._last[cooldown_key] = now

        bundle = self.build_bundle(kind, trigger)
        incident_id = self.store.save(bundle)
        self.captured[kind] = self.captured.get(kind, 0) + 1
        logger.warning(
            "incident %s captured (%s) -> %s",
            incident_id, kind, os.path.join(self.store.dir, f"{incident_id}.json"),
        )
        return incident_id

    def build_bundle(self, kind: str, trigger: dict) -> dict:
        from dynamo_tpu.tracing import SPANS

        now = time.time()
        flight = self.flight or getattr(self.core, "flight", None)
        records: list[dict] = []
        if flight is not None:
            records = flight.snapshot(last=self.settings.flight_last)
        horizon = now - self.settings.span_window_s
        spans = [
            s for s in SPANS.query()
            if s.get("start_ts", 0.0) + s.get("duration_ms", 0.0) / 1e3 >= horizon
        ]
        loss = None
        if self.core is not None and hasattr(self.core, "loss_snapshot"):
            loss = self.core.loss_snapshot()
        cost = None
        cost_reg = getattr(getattr(self.core, "runner", None), "cost_registry", None)
        if cost_reg is not None:
            try:
                cost = cost_reg.snapshot()
            except Exception:
                logger.exception("cost snapshot for incident bundle failed (ignored)")
        return {
            "ts": now,
            "kind": kind,
            "worker": self.worker,
            "trigger": dict(trigger),
            "window_s": self.settings.span_window_s,
            "flight": records,
            "spans": spans,
            "loss": loss,
            "cost": cost,
            "config": _config_snapshot(self.settings),
            "device_trace": _device_trace_state(),
        }
