"""Latency attribution: per-request critical-path budgets + the loss-cause
vocabulary behind fleet-wide time-loss accounting.

The telemetry planes record *what happened* — spans (``tracing.py``), flight
STEP records (``flight.py``), compile events (``compile.py``) — but none of
them answers the operator's first question: *where did this request's latency
go?* This module is the join:

- :func:`build_explain` folds one request's span timeline and the serving
  worker's flight ring into an **ordered critical-path budget** — queue,
  admission gate, onboard fetch, prefill, KV gather/pack/wire/scatter, decode
  compute vs. host gap vs. barrier-by-reason (the pinned
  :data:`~dynamo_tpu.engine.core.BARRIER_REASONS` vocabulary), recompiles —
  whose segments sum to within tolerance of the measured E2E latency. The
  residual is reported explicitly as ``unattributed``, never silently
  absorbed. Served at ``GET /debug/explain/{request_id}`` (frontend fan-out
  over the ``debug_explain`` worker endpoint, ``service.py``).
- :data:`LOSS_CAUSES` pins the label set of
  ``dynamo_engine_lost_time_seconds_total{worker,cause}`` — the fleet-wide
  aggregate the engine charges continuously (``EngineCore._charge_loss``) so
  ``/metrics`` answers "where does this fleet's time go" without a
  per-request query. The set is the barrier vocabulary plus the six
  engine-plane causes that exist outside a barrier step; a new barrier
  reason is a new loss cause by construction
  (``tools/check_barrier_reasons.py`` pins both ends).
"""

from __future__ import annotations

from typing import Any, Iterable

from dynamo_tpu.engine.core import BARRIER_REASONS

#: Loss causes that exist outside the overlap-barrier vocabulary: request
#: wait before admission ("queue": resource wait, "admission": quota gate),
#: steps that idled on a tier fetch, preemption work, XLA recompiles on the
#: serving path, and the residual host gap between dispatches.
EXTRA_LOSS_CAUSES = ("queue", "admission", "onboard_stall", "preempt", "recompile", "gap")

#: The pinned label set of dynamo_engine_lost_time_seconds_total{cause}.
LOSS_CAUSES = tuple(BARRIER_REASONS) + EXTRA_LOSS_CAUSES

#: Span names folded into each pre-decode segment of the explain budget.
_QUEUE_SPANS = ("engine_queue_wait", "prefill_queue_wait")
_ADMISSION_SPANS = ("engine_admission_wait",)
_ONBOARD_SPANS = ("engine_onboard_wait",)
_PREFILL_SPANS = ("prefill_exec",)
_KV_SPANS = ("kv_gather", "kv_pack", "kv_wire", "kv_scatter")


def _span_ms(spans: Iterable[dict], names: tuple[str, ...]) -> float:
    return sum(
        float(s.get("duration_ms") or 0.0) for s in spans if s.get("name") in names
    )


def _find_span(spans: list[dict], name: str) -> dict | None:
    hits = [s for s in spans if s.get("name") == name]
    if not hits:
        return None
    # Earliest wins: a retried hop records later duplicates.
    return min(hits, key=lambda s: s.get("start_ts") or 0.0)


def _latest_span(spans: list[dict], name: str) -> dict | None:
    hits = [s for s in spans if s.get("name") == name]
    if not hits:
        return None
    return max(hits, key=lambda s: s.get("start_ts") or 0.0)


def _steps_by_worker(step_docs: list[dict]) -> dict[str, list[dict]]:
    by_worker: dict[str, list[dict]] = {}
    for doc in step_docs:
        wid = str(doc.get("worker", ""))
        by_worker.setdefault(wid, []).extend(doc.get("steps", []))
    return by_worker


def build_explain(
    request_id: str,
    spans: list[dict],
    step_docs: list[dict] | None = None,
    *,
    tolerance_frac: float = 0.1,
) -> dict[str, Any] | None:
    """One request's ordered critical-path budget, or None without an anchor.

    ``spans`` is the deduped union of span docs for the request (frontend +
    every worker, as ``/debug/traces`` assembles); ``step_docs`` is the
    ``debug_explain`` fan-out result — per-worker
    ``{"worker", "steps", "compiles"}`` docs whose STEP/COMPILE records are
    windowed against the request's span bounds here. Decode-phase steps are
    taken from the single worker with the most steps inside the decode
    window (the engine that actually served the decode loop): flight records
    carry no request ids, so cross-worker records would double-charge the
    same wall-clock.
    """
    anchor = _find_span(spans, "http_request") or _find_span(spans, "engine_request")
    if anchor is None:
        return None
    e2e_ms = float(anchor.get("duration_ms") or 0.0)
    t_start = float(anchor.get("start_ts") or 0.0)
    t_end = t_start + e2e_ms / 1e3

    # In disagg the prefill worker serves the remote half through its OWN
    # engine, so the request's span union holds TWO engine_request /
    # engine_first_token / engine-wait sets under one id: the prefill-side
    # set nested inside remote_prefill + prefill_exec, and the decode-side
    # set after the remote window. The budget anchors on the decode engine
    # (latest start); prefill-side engine time is already covered by the
    # remote-prefill decomposition below.
    engine = _latest_span(spans, "engine_request") or anchor
    engine_ms = float(engine.get("duration_ms") or 0.0)
    first = _latest_span(spans, "engine_first_token")
    ttft_ms = min(float(first.get("duration_ms") or 0.0), engine_ms) if first else 0.0
    t_first = float(engine.get("start_ts") or t_start) + ttft_ms / 1e3

    remote_span = _find_span(spans, "remote_prefill")
    remote_ms = float(remote_span.get("duration_ms") or 0.0) if remote_span else 0.0
    r0 = float(remote_span.get("start_ts") or 0.0) if remote_span else 0.0
    r1 = r0 + remote_ms / 1e3

    def _outside_remote(s: dict) -> bool:
        if remote_span is None:
            return True
        mid = float(s.get("start_ts") or 0.0) + float(s.get("duration_ms") or 0.0) / 2e3
        return not (r0 <= mid <= r1)

    def _engine_side_ms(names: tuple[str, ...]) -> float:
        return _span_ms((s for s in spans if _outside_remote(s)), names)

    # Pre-decode segments are de-overlapped along the span hierarchy: the
    # decode operator's remote_prefill wait sits BEFORE the decode-side
    # engine_request and contains prefill_queue_wait + prefill_exec (which
    # itself contains the sender-side kv_gather/pack/wire) + kv_scatter, so
    # each nested span is charged once and only the uncovered slack of each
    # parent remains. Engine-side waits count only outside the remote window
    # (the prefill engine's own queue/admission waits ride remote compute).
    engine_queue_ms = _engine_side_ms(("engine_queue_wait",))
    prefill_queue_ms = _span_ms(spans, ("prefill_queue_wait",))
    queue_ms = engine_queue_ms + prefill_queue_ms
    admission_ms = _engine_side_ms(_ADMISSION_SPANS)
    onboard_ms = _engine_side_ms(_ONBOARD_SPANS)
    kv_ms = {name: _span_ms(spans, (name,)) for name in _KV_SPANS}
    prefill_exec_ms = _span_ms(spans, _PREFILL_SPANS)
    kv_sender_ms = kv_ms["kv_gather"] + kv_ms["kv_pack"] + kv_ms["kv_wire"]
    # Remote prefill compute = prefill_exec minus the transfer phases it
    # wraps; transfer_wait = the remote window's remaining slack (queue-task
    # pickup, KV-landed event propagation).
    remote_compute_ms = max(0.0, prefill_exec_ms - kv_sender_ms)
    remote_parts_ms = (
        prefill_queue_ms + remote_compute_ms + kv_sender_ms + kv_ms["kv_scatter"]
    )
    transfer_wait_ms = max(0.0, remote_ms - remote_parts_ms)
    # The wire path overlaps: the receiver scatters while the sender is
    # still streaming, and prefill_exec can run a beat past the remote
    # window. Concurrency must not bill twice — squeeze the remote-side
    # components proportionally into the measured remote window.
    if remote_span is not None and remote_parts_ms > remote_ms > 0.0:
        scale = remote_ms / remote_parts_ms
        prefill_queue_ms *= scale
        remote_compute_ms *= scale
        kv_ms = {k: v * scale for k, v in kv_ms.items()}
        queue_ms = engine_queue_ms + prefill_queue_ms
    # Local prefill: whatever of the engine-side TTFT the named waits don't
    # explain is time the step loop spent on prompt chunks + the first
    # decode dispatch (spans don't time local chunks individually).
    local_prefill_ms = max(
        0.0, ttft_ms - engine_queue_ms - admission_ms - onboard_ms,
    )
    prefill_ms = remote_compute_ms + local_prefill_ms

    # Decode split from the serving worker's STEP records in the decode
    # window (first token -> request end).
    decode_worker = ""
    compute_ms = 0.0
    gap_ms = 0.0
    barrier_ms: dict[str, float] = {}
    recompile_ms = 0.0
    steps_in_window = 0
    roofline_weighted = 0.0
    roofline_weight_ms = 0.0
    if step_docs:
        best: list[dict] = []
        for wid, steps in _steps_by_worker(step_docs).items():
            windowed = [
                s for s in steps if t_first <= float(s.get("ts") or 0.0) <= t_end
            ]
            if len(windowed) > len(best):
                best, decode_worker = windowed, wid
        steps_in_window = len(best)
        for s in best:
            wall = float(s.get("wall_ms") or 0.0)
            dispatch = float(s.get("dispatch_ms") or 0.0)
            # Mock/timing runners track no dispatch clock: their step wall
            # IS the model compute analog.
            compute = dispatch if dispatch > 0.0 else wall
            host = max(0.0, wall - compute)
            compute_ms += compute
            # Device-cost plane: STEP records carry the step's roofline
            # fraction; the dispatch-weighted mean annotates decode_compute
            # so a postmortem can tell "compute was the bottleneck" from
            # "we left bandwidth on the table".
            if s.get("roofline_frac") is not None:
                roofline_weight_ms += compute
                roofline_weighted += float(s["roofline_frac"]) * compute
            gap_ms += float(s.get("gap_ms") or 0.0)
            reason = s.get("barrier_reason") or ""
            if s.get("overlap_mode") == "barrier" and reason:
                barrier_ms[reason] = barrier_ms.get(reason, 0.0) + host
            else:
                gap_ms += host
        pre_compile_ms = 0.0
        post_compile_ms = 0.0
        for doc in step_docs:
            if str(doc.get("worker", "")) != decode_worker:
                continue
            for c in doc.get("compiles", []):
                if c.get("reason") == "warm_cache":
                    continue
                ts = float(c.get("ts") or 0.0)
                if t_start <= ts <= t_end:
                    if ts <= t_first:
                        pre_compile_ms += float(c.get("wall_ms") or 0.0)
                    else:
                        post_compile_ms += float(c.get("wall_ms") or 0.0)
        # Compile time happens inside a dispatch: carve it out of the window
        # it physically sat in — the decode-window share out of the measured
        # step compute, the remainder (typically the first-dispatch compile
        # riding the TTFT) out of the prefill segment — so it reports as its
        # own segment without double-charging the time it inflated.
        recompile_ms = min(post_compile_ms, compute_ms)
        compute_ms -= recompile_ms
        pre_compile_ms += post_compile_ms - recompile_ms
        recompile_prefill_ms = min(pre_compile_ms, prefill_ms)
        prefill_ms -= recompile_prefill_ms
        # Step records carry whole-step walls and inter-step gaps, which can
        # overhang the request's decode window (a window-edge step, or a
        # first step whose gap spans pre-request idle). Scale the decode
        # split down to the window so the overshoot never masquerades as
        # negative unattributed time. The prefill-side recompile share lives
        # outside the decode window and must not be squeezed with it.
        decode_window = max(0.0, engine_ms - ttft_ms)
        decode_total = compute_ms + gap_ms + recompile_ms + sum(barrier_ms.values())
        if decode_total > decode_window > 0.0:
            scale = decode_window / decode_total
            compute_ms *= scale
            gap_ms *= scale
            recompile_ms *= scale
            barrier_ms = {k: v * scale for k, v in barrier_ms.items()}
        elif decode_window == 0.0:
            compute_ms = gap_ms = recompile_ms = 0.0
            barrier_ms = {}
        recompile_ms += recompile_prefill_ms

    segments: list[dict[str, Any]] = []

    def seg(name: str, ms: float, **extra: Any) -> None:
        if ms > 0.0:
            segments.append({"name": name, "ms": round(ms, 3), **extra})

    seg("queue", queue_ms)
    seg("admission", admission_ms)
    seg("onboard", onboard_ms)
    seg("prefill", prefill_ms)
    for name in _KV_SPANS:
        seg(name, kv_ms[name])
    seg("transfer_wait", transfer_wait_ms)
    if roofline_weight_ms > 0.0:
        seg(
            "decode_compute", compute_ms,
            roofline_frac=round(roofline_weighted / roofline_weight_ms, 4),
        )
    else:
        seg("decode_compute", compute_ms)
    seg("gap", gap_ms)
    for reason in sorted(barrier_ms, key=barrier_ms.get, reverse=True):
        seg(f"barrier:{reason}", barrier_ms[reason], reason=reason)
    seg("recompile", recompile_ms)
    # Frontend-side time around the engine span and the remote-prefill wait
    # (parse, route, flush).
    if anchor is not engine:
        seg("frontend", max(0.0, e2e_ms - engine_ms - remote_ms))

    attributed_ms = sum(s["ms"] for s in segments)
    unattributed_ms = round(e2e_ms - attributed_ms, 3)
    segments.append({"name": "unattributed", "ms": unattributed_ms})
    return {
        "request_id": request_id,
        "trace_id": anchor.get("trace_id", ""),
        "e2e_ms": round(e2e_ms, 3),
        "engine_ms": round(engine_ms, 3),
        "ttft_ms": round(ttft_ms, 3),
        "decode_ms": round(max(0.0, engine_ms - ttft_ms), 3),
        "decode_worker": decode_worker,
        "steps_in_window": steps_in_window,
        "segments": segments,
        "attributed_ms": round(attributed_ms, 3),
        "unattributed_ms": unattributed_ms,
        "coverage_frac": round(attributed_ms / e2e_ms, 4) if e2e_ms > 0 else 0.0,
        "within_tolerance": abs(unattributed_ms) <= tolerance_frac * e2e_ms,
    }
