"""Unified engine telemetry plane.

Two surfaces over the same worker internals:

- :mod:`metrics` — ``EngineMetrics``: the Prometheus registry for engine
  layers (step composition, page pool, prefill queue, KV transfer), plus
  text federation so the frontend's ``/metrics`` can serve every worker's
  registry as one document.
- :mod:`service` — runtime-transport endpoints (``debug_traces``,
  ``metrics_scrape``) that make every worker's span ring and registry
  remotely queryable, the fan-out client, and the timeline assembler behind
  ``GET /debug/traces/{request_id}``.
- :mod:`http` — the optional per-worker debug HTTP surface (``/metrics``,
  ``/debug/traces/{request_id}``, ``/debug/incidents``) for scraping workers
  directly.
- :mod:`incidents` — capture-on-anomaly black-box bundles: a size-capped
  on-disk store of flight/span/loss snapshots written at anomaly rising
  edges, engine-step crashes, and SLO burn-rate alerts.
- :mod:`cost` — the device-cost plane: per-compiled-program XLA cost
  analysis (flops / bytes-accessed / peak memory) joined with measured
  dispatch wall into a live roofline ledger per step kind.
"""

from dynamo_tpu.observability.anomaly import ANOMALY_KINDS, AnomalySentinel
from dynamo_tpu.observability.compile import CompileTracker, timed_dispatch
from dynamo_tpu.observability.cost import CostRegistry, chip_peaks, cost_plane_enabled
from dynamo_tpu.observability.flight import FlightRecorder
from dynamo_tpu.observability.incidents import (
    INCIDENT_KINDS,
    IncidentCapture,
    IncidentStore,
)
from dynamo_tpu.observability.metrics import EngineMetrics, federate_text, observe_kv_phase
from dynamo_tpu.observability.service import (
    COST_ENDPOINT,
    DEBUG_EXPLAIN_ENDPOINT,
    DEBUG_INCIDENTS_ENDPOINT,
    DEBUG_TRACES_ENDPOINT,
    FLIGHT_ENDPOINT,
    METRICS_SCRAPE_ENDPOINT,
    PROFILE_ENDPOINT,
    CostQueryService,
    ExplainQueryService,
    FlightQueryService,
    IncidentQueryService,
    MetricsScrapeService,
    ProfileCaptureService,
    SpanQueryService,
    WorkerTelemetryClient,
    assemble_timeline,
)
from dynamo_tpu.observability.slo import ALERT_KINDS, SloAccountant, StreamingQuantiles

__all__ = [
    "ANOMALY_KINDS",
    "ALERT_KINDS",
    "AnomalySentinel",
    "CompileTracker",
    "timed_dispatch",
    "FlightRecorder",
    "INCIDENT_KINDS",
    "IncidentCapture",
    "IncidentStore",
    "EngineMetrics",
    "federate_text",
    "observe_kv_phase",
    "CostRegistry",
    "chip_peaks",
    "cost_plane_enabled",
    "COST_ENDPOINT",
    "PROFILE_ENDPOINT",
    "CostQueryService",
    "ProfileCaptureService",
    "DEBUG_EXPLAIN_ENDPOINT",
    "DEBUG_INCIDENTS_ENDPOINT",
    "DEBUG_TRACES_ENDPOINT",
    "FLIGHT_ENDPOINT",
    "METRICS_SCRAPE_ENDPOINT",
    "ExplainQueryService",
    "FlightQueryService",
    "IncidentQueryService",
    "MetricsScrapeService",
    "SpanQueryService",
    "WorkerTelemetryClient",
    "assemble_timeline",
    "SloAccountant",
    "StreamingQuantiles",
    "LOSS_CAUSES",
    "build_explain",
]


def __getattr__(name):
    # attribution imports engine.core (for the pinned BARRIER_REASONS), and
    # engine.core imports this package's flight module at import time — so
    # the attribution symbols resolve lazily to keep the package importable
    # from either side.
    if name in ("LOSS_CAUSES", "EXTRA_LOSS_CAUSES", "build_explain"):
        from dynamo_tpu.observability import attribution

        return getattr(attribution, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
