"""Device-cost plane: a live roofline ledger from XLA's own cost analysis.

The serving path already knows *when* every compiled program runs (the
``CompileTracker`` observes all five runner dispatch sites) but not *what*
each dispatch moves: how many HBM bytes it streams and how many flops it
executes. XLA knows — ``jit(...).lower().compile().cost_analysis()``
reports ``flops`` / ``bytes accessed`` per compiled program — but asking on
the hot path would double-compile every bucket. The :class:`CostRegistry`
closes the gap lazily:

- at each dispatch site the runner does a cheap seen-set check on the exact
  padded-bucket key the CompileTracker uses; a first-seen bucket enqueues a
  *lowering thunk* (shape/dtype avatars of the real arguments, captured
  before the call so donation can't invalidate them) to one background
  daemon thread, which re-lowers and compiles the same signature once and
  extracts the XLA numbers;
- until (or in case) extraction fails or the backend reports nothing, the
  record carries a model-derived **estimate** (weights-minus-untied-embed
  stream + page-granular KV traffic — the same accounting ``bench.py`` and
  ``tools/profile_1b_decode.py`` use, exported here as the shared helpers
  :func:`weight_stream_bytes` / :func:`decode_step_estimate`);
- every dispatch accumulates its record's bytes/flops and measured dispatch
  wall into a per-step-kind ledger (``prefill``/``decode``/``mixed``/
  ``spec_verify``), and :meth:`CostRegistry.take_step` hands the engine
  core the bytes/flops of the dispatches inside one engine step for the
  STEP flight record join.

Achieved GB/s / FLOP/s divide by per-chip peaks: auto-detected from
``jax.devices()[0].device_kind`` (v4/v5e/v5p/v6e table below), overridable
with ``DYN_PEAK_HBM_GBPS`` / ``DYN_PEAK_TFLOPS``. On CPU backends the
fallback peaks are DDR-class proxies — roofline *fractions* there are test
plumbing, not measurements (the bytes/flops themselves are still real XLA
numbers; CPU populates cost_analysis).

Wall-clock basis caveat: the ledger's wall is the ``timed_dispatch``
measurement. On the synchronous paths that spans device execution; on the
overlapped ``*_async`` paths it is enqueue wall only, so async-mode GB/s
reads high — bytes/step stays exact either way.

Everything is gated by ``DYN_COST_PLANE`` (default on): when off, the
runner never constructs a registry, no extraction runs (spy:
:data:`EXTRACTIONS`), and served tokens are bit-identical.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

logger = logging.getLogger(__name__)

COST_PLANE_ENV = "DYN_COST_PLANE"
PEAK_HBM_ENV = "DYN_PEAK_HBM_GBPS"
PEAK_FLOPS_ENV = "DYN_PEAK_TFLOPS"
#: On-demand profile capture: hard cap on one window's duration (ms) and
#: the artifact root the XPlane dumps land under.
PROFILE_MAX_MS_ENV = "DYN_PROFILE_MAX_MS"
PROFILE_DIR_ENV = "DYN_PROFILE_DIR"

#: The ledger's step-kind vocabulary (runner-side classification of each
#: dispatch; the engine core's flight records keep their own kind field).
STEP_KINDS = ("prefill", "decode", "mixed", "spec_verify")

#: device_kind substring -> (peak HBM GB/s, peak bf16 dense TFLOPS).
#: Datasheet numbers per chip: v5e 819/197, v5p 2765/459, v6e 1640/918,
#: v4 1228/275. Matched case-insensitively against jax's device_kind
#: strings ("TPU v5 lite" == v5e, "TPU v6 lite" == v6e, "TPU v5p"/"TPU v5"
#: == v5p, "TPU v4" == v4).
CHIP_PEAKS: dict[str, tuple[float, float]] = {
    "v6e": (1640.0, 918.0),
    "v6 lite": (1640.0, 918.0),
    "v5e": (819.0, 197.0),
    "v5 lite": (819.0, 197.0),
    "v5p": (2765.0, 459.0),
    "v5": (2765.0, 459.0),  # bare "TPU v5" reports the p-class part
    "v4": (1228.0, 275.0),
}

#: Documented CPU (and unknown-backend) fallback: one DDR channel-class
#: 50 GB/s and 0.5 TFLOPS — deliberately round proxies so CPU rooflines
#: read as plumbing, never as measurements.
CPU_FALLBACK_PEAKS = (50.0, 0.5)

#: Module-wide count of cost-extraction lowerings (background compiles).
#: The DYN_COST_PLANE=0 acceptance test spies on this staying flat.
EXTRACTIONS = 0


def cost_plane_enabled() -> bool:
    return os.environ.get(COST_PLANE_ENV, "1").lower() not in ("0", "false", "off")


def profile_max_ms() -> float:
    try:
        return float(os.environ.get(PROFILE_MAX_MS_ENV, "10000"))
    except ValueError:
        return 10000.0


def profile_artifact_dir() -> str:
    import tempfile

    return os.environ.get(PROFILE_DIR_ENV) or os.path.join(
        tempfile.gettempdir(), "dynamo-profiles"
    )


def profiler_available() -> bool:
    """Whether this process can arm a device trace (jax.profiler present)."""
    try:
        import jax.profiler  # noqa: F401

        return hasattr(jax.profiler, "start_trace")
    except Exception:
        return False


def chip_peaks() -> tuple[float, float, str]:
    """(peak HBM GB/s, peak TFLOPS, source) for device 0.

    Env overrides win; else the :data:`CHIP_PEAKS` table keyed on
    ``jax.devices()[0].device_kind``; else :data:`CPU_FALLBACK_PEAKS`.
    """
    kind = ""
    try:
        import jax

        kind = jax.devices()[0].device_kind
    except Exception:
        kind = ""
    hbm = flops = None
    source = f"fallback:{kind or 'unknown'}"
    low = kind.lower()
    for sub, (h, f) in CHIP_PEAKS.items():
        if sub in low:
            hbm, flops, source = h, f, f"table:{kind}"
            break
    if hbm is None:
        hbm, flops = CPU_FALLBACK_PEAKS
    env_h, env_f = os.environ.get(PEAK_HBM_ENV), os.environ.get(PEAK_FLOPS_ENV)
    try:
        if env_h:
            hbm, source = float(env_h), "env"
        if env_f:
            flops = float(env_f)
            source = "env"
    except ValueError:
        logger.warning("ignoring malformed %s/%s", PEAK_HBM_ENV, PEAK_FLOPS_ENV)
    return float(hbm), float(flops), source


# -- shared byte/flop estimate helpers ---------------------------------------
# The single source of truth for the model-derived accounting bench.py and
# tools/profile_1b_decode.py previously each re-derived.


def tree_nbytes(tree) -> int:
    """Total bytes of every array leaf (packed quantized leaves count at
    their true storage size: int8 ~1 B/elem, packed int4 ~0.5)."""
    import jax

    return sum(x.nbytes for x in jax.tree.leaves(tree))


def tree_param_count(tree) -> int:
    """Total array elements — the flop estimate's 2*N*tokens numerator.
    Packed int4 leaves undercount by 2x; estimates only, XLA numbers win."""
    import jax

    return sum(x.size for x in jax.tree.leaves(tree))


def weight_stream_bytes(params, cfg) -> int:
    """HBM bytes of weights one decode step streams: measured tree bytes
    minus the embedding table when untied (decode gathers ``batch`` rows of
    it, never the full table; a tied table IS fully read as the lm_head)."""
    total = tree_nbytes(params)
    if not getattr(cfg, "tie_embeddings", True) and "embed" in params:
        total -= tree_nbytes(params["embed"])
    return total


def kv_window_bytes(cfg, context_tokens: float, cache_itemsize: int = 2) -> int:
    """Page-granular KV read bytes for one sequence's window of
    ``context_tokens`` (already rounded to whole pages by the caller)."""
    return int(context_tokens * cfg.kv_bytes_per_token(itemsize=cache_itemsize))


def decode_step_estimate(
    params, cfg, batch: int, context_tokens: float,
    *, cache_itemsize: int = 2, new_tokens: int | None = None,
) -> dict[str, float]:
    """Model-derived {bytes, flops} for one decode-shaped step.

    ``context_tokens`` is the per-sequence page-granular KV window (pages *
    page_size); flops ≈ 2 * params * tokens-generated (matmul floor).
    """
    toks = batch if new_tokens is None else new_tokens
    return {
        "bytes": float(
            weight_stream_bytes(params, cfg)
            + batch * kv_window_bytes(cfg, context_tokens, cache_itemsize)
        ),
        "flops": float(2 * tree_param_count(params) * toks),
    }


# -- extraction ---------------------------------------------------------------


def _avatar(x):
    """ShapeDtypeStruct stand-in for an array; non-arrays pass through.

    Captured eagerly at the dispatch site — *before* the jitted call — so
    donated cache buffers can't be invalidated under us. Sharding rides
    along when the array has one, keeping the re-lowered program's cost
    analysis faithful on meshes.
    """
    import jax

    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        return x
    sharding = getattr(x, "sharding", None)
    try:
        if sharding is not None:
            return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)
    except Exception:
        pass
    return jax.ShapeDtypeStruct(shape, dtype)


def make_lower_thunk(fn, args: tuple, kwargs: dict) -> Callable[[], Any]:
    """A zero-arg closure lowering ``fn`` on avatars of the given call.

    Avatar conversion happens NOW (cheap tree-map); the expensive
    ``lower().compile()`` happens when the background thread calls it.
    """
    import jax

    av_args = tuple(jax.tree_util.tree_map(_avatar, a) for a in args)
    av_kwargs = dict(kwargs)

    def thunk():
        return fn.lower(*av_args, **av_kwargs)

    return thunk


def _parse_cost_analysis(ca) -> tuple[float, float]:
    """(flops, bytes accessed) from a cost_analysis() return value, which
    is a dict on some jax versions and a one-element list of dicts on
    others; absent keys read as 0."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return 0.0, 0.0
    return float(ca.get("flops", 0.0) or 0.0), float(ca.get("bytes accessed", 0.0) or 0.0)


@dataclass
class CostRecord:
    """Per compiled-program-bucket cost: XLA numbers once extracted, the
    model estimate until then (or forever, when the backend reports none)."""

    program: str
    key: tuple
    kind: str
    #: per-ITERATION cost: XLA's HloCostAnalysis counts a while/scan body
    #: once regardless of trip count (verified on this jax), so a
    #: multi-step burst program's numbers cover ONE decode iteration —
    #: callers scale by ``steps`` at observe time.
    bytes: float = 0.0
    flops: float = 0.0
    peak_memory_bytes: float = 0.0
    source: str = "pending"  # pending -> xla | estimate
    dispatches: int = 0
    #: iteration units accounted (== dispatches except for multi-step
    #: bursts, where one dispatch is ``num_steps`` units).
    step_units: int = 0
    wall_s: float = 0.0
    #: iteration units per observed step kind — a padded bucket is
    #: *usually* one kind, but a mixed-capable bucket may host
    #: prefill-only steps too. The retroactive XLA adjustment multiplies
    #: the per-iteration delta by these.
    kind_dispatches: dict = field(default_factory=dict)

    def to_doc(self) -> dict:
        achieved_gbps = self.bytes * self.step_units / self.wall_s / 1e9 if self.wall_s > 0 else 0.0
        return {
            "program": self.program,
            "key": list(self.key),
            "kind": self.kind,
            "bytes": int(self.bytes),
            "flops": int(self.flops),
            "peak_memory_bytes": int(self.peak_memory_bytes),
            "source": self.source,
            "dispatches": self.dispatches,
            "steps": self.step_units,
            "wall_ms": round(self.wall_s * 1e3, 3),
            "achieved_gbps": round(achieved_gbps, 3),
        }


class CostRegistry:
    """Per-runner ledger of per-program costs and per-step-kind totals.

    Hot-path surface is two O(1) calls: :meth:`seen` (set lookup) and
    :meth:`observe` (dict arithmetic under a lock). Extraction work rides
    :meth:`submit` -> one daemon thread. Never raises into the serving
    path: extraction failures degrade to the estimate and log once.
    """

    def __init__(self, *, worker: str = "", peaks: tuple[float, float] | None = None) -> None:
        self.worker = worker
        if peaks is None:
            hbm, tflops, src = chip_peaks()
        else:
            hbm, tflops, src = float(peaks[0]), float(peaks[1]), "caller"
        self.peak_hbm_gbps = hbm
        self.peak_tflops = tflops
        self.peak_source = src
        self._lock = threading.Lock()
        self._records: dict[tuple, CostRecord] = {}
        self._ledger: dict[str, dict[str, float]] = {}
        self._step_bytes = 0.0
        self._step_flops = 0.0
        self.extract_calls = 0
        self.extract_failures = 0
        self._q: queue.Queue = queue.Queue()
        self._thread: threading.Thread | None = None

    # -- hot path ------------------------------------------------------------

    def seen(self, program: str, key: tuple) -> bool:
        return (program, key) in self._records

    def submit(
        self,
        program: str,
        key: tuple,
        kind: str,
        *,
        lower: Callable[[], Any] | None = None,
        estimate: dict[str, float] | None = None,
    ) -> None:
        """Register a first-seen bucket: estimate now, XLA numbers later."""
        rid = (program, key)
        with self._lock:
            if rid in self._records:
                return
            rec = CostRecord(program=program, key=key, kind=kind)
            if estimate:
                rec.bytes = float(estimate.get("bytes", 0.0))
                rec.flops = float(estimate.get("flops", 0.0))
                rec.source = "estimate"
            self._records[rid] = rec
        if lower is not None:
            self._q.put((rid, lower))
            self._ensure_thread()

    def observe(
        self, program: str, key: tuple, seconds: float, kind: str | None = None, steps: int = 1
    ) -> None:
        """Account one dispatch of a registered bucket into the ledger.

        ``steps`` scales the record's per-iteration bytes/flops: XLA's cost
        analysis counts a while/scan body once regardless of trip count, so
        a multi-step burst dispatch passes its ``num_steps`` here to keep
        the ledger honest. Wall time stays measured — one dispatch's wall
        covers all its iterations, so GB/s math needs no correction.
        """
        rid = (program, key)
        steps = max(1, int(steps))
        with self._lock:
            rec = self._records.get(rid)
            if rec is None:  # estimate-less caller skipped submit
                rec = self._records[rid] = CostRecord(program=program, key=key, kind=kind or "decode")
            k = kind or rec.kind
            rec.dispatches += 1
            rec.step_units += steps
            rec.wall_s += max(0.0, seconds)
            rec.kind_dispatches[k] = rec.kind_dispatches.get(k, 0) + steps
            led = self._ledger.setdefault(
                k, {"bytes": 0.0, "flops": 0.0, "wall_s": 0.0, "dispatches": 0, "steps": 0}
            )
            led["bytes"] += rec.bytes * steps
            led["flops"] += rec.flops * steps
            led["wall_s"] += max(0.0, seconds)
            led["dispatches"] += 1
            led["steps"] += steps
            self._step_bytes += rec.bytes * steps
            self._step_flops += rec.flops * steps

    def take_step(self) -> tuple[float, float]:
        """(bytes, flops) accumulated since the previous take — the engine
        core calls this once per step to stamp its STEP flight record."""
        with self._lock:
            out = (self._step_bytes, self._step_flops)
            self._step_bytes = self._step_flops = 0.0
            return out

    # -- background extraction ----------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(
            target=self._extract_loop, name="dyn-cost-extract", daemon=True
        )
        self._thread.start()

    def _extract_loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            rid, lower = item
            try:
                self._extract(rid, lower)
            except Exception as exc:
                self.extract_failures += 1
                logger.debug("cost extraction failed for %s: %s", rid, exc)
            finally:
                self._q.task_done()

    def _extract(self, rid: tuple, lower: Callable[[], Any]) -> None:
        global EXTRACTIONS
        self.extract_calls += 1
        EXTRACTIONS += 1
        compiled = lower().compile()
        flops, byts = _parse_cost_analysis(compiled.cost_analysis())
        peak_mem = 0.0
        try:
            mem = compiled.memory_analysis()
            peak_mem = float(
                getattr(mem, "argument_size_in_bytes", 0)
                + getattr(mem, "output_size_in_bytes", 0)
                + getattr(mem, "temp_size_in_bytes", 0)
            )
        except Exception:
            pass
        if byts <= 0.0 and flops <= 0.0:
            return  # backend reported nothing: the estimate stands
        with self._lock:
            rec = self._records.get(rid)
            if rec is None:
                return
            db, df = byts - rec.bytes, flops - rec.flops
            rec.bytes, rec.flops = byts, flops
            rec.peak_memory_bytes = peak_mem
            rec.source = "xla"
            # Dispatches already accounted at the estimate retro-adjust to
            # the XLA numbers, per kind they were observed under.
            for k, n in rec.kind_dispatches.items():
                led = self._ledger.get(k)
                if led is not None:
                    led["bytes"] += db * n
                    led["flops"] += df * n

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until queued extractions finish (tests/tools only)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._q.unfinished_tasks == 0:
                return True
            time.sleep(0.01)
        return self._q.unfinished_tasks == 0

    # -- read side -----------------------------------------------------------

    def roofline_of(self, byts: float, flops: float, seconds: float) -> tuple[float, str]:
        """(roofline fraction, bound) for a measured window: achieved over
        peak on each axis, classified memory- vs compute-bound by which
        fraction dominates."""
        if seconds <= 0.0 or (byts <= 0.0 and flops <= 0.0):
            return 0.0, ""
        frac_mem = byts / seconds / (self.peak_hbm_gbps * 1e9) if self.peak_hbm_gbps > 0 else 0.0
        frac_comp = flops / seconds / (self.peak_tflops * 1e12) if self.peak_tflops > 0 else 0.0
        if frac_mem >= frac_comp:
            return frac_mem, "memory"
        return frac_comp, "compute"

    def ledger(self) -> dict[str, dict[str, float]]:
        """Per-step-kind achieved GB/s, FLOP/s and roofline fraction."""
        with self._lock:
            snap = {k: dict(v) for k, v in self._ledger.items()}
        out: dict[str, dict[str, float]] = {}
        for kind, led in snap.items():
            wall = led["wall_s"]
            gbps = led["bytes"] / wall / 1e9 if wall > 0 else 0.0
            tflops = led["flops"] / wall / 1e12 if wall > 0 else 0.0
            frac, bound = self.roofline_of(led["bytes"], led["flops"], wall)
            out[kind] = {
                **led,
                "gbps": round(gbps, 3),
                "tflops": round(tflops, 4),
                "roofline_frac": round(frac, 6),
                "bound": bound,
                "bytes_per_dispatch": led["bytes"] / led["dispatches"] if led["dispatches"] else 0.0,
                "bytes_per_step": led["bytes"] / led["steps"] if led.get("steps") else 0.0,
            }
        return out

    def totals(self) -> dict[str, dict[str, float]]:
        """Cumulative {kind: {bytes, flops}} — the Counter sync source."""
        with self._lock:
            return {
                k: {"bytes": v["bytes"], "flops": v["flops"]}
                for k, v in self._ledger.items()
            }

    def record_for(self, program: str, key: tuple | None = None) -> CostRecord | None:
        """The record for a program (first match when key is None)."""
        with self._lock:
            if key is not None:
                return self._records.get((program, key))
            for (prog, _), rec in self._records.items():
                if prog == program:
                    return rec
        return None

    def snapshot(self) -> dict:
        """The /debug/cost document: per-program table + ledger + peaks."""
        with self._lock:
            records = [rec.to_doc() for rec in self._records.values()]
        records.sort(key=lambda r: (-r["wall_ms"], r["program"]))
        return {
            "enabled": True,
            "worker": self.worker,
            "peaks": {
                "hbm_gbps": self.peak_hbm_gbps,
                "tflops": self.peak_tflops,
                "source": self.peak_source,
            },
            "extract_calls": self.extract_calls,
            "extract_failures": self.extract_failures,
            "programs": records,
            "ledger": self.ledger(),
        }

    def close(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            self._q.put(None)
