"""Anomaly sentinel: rolling-window self-diagnosis over the engine step stream.

The soak and hardware campaigns run unattended — nobody is watching the
dashboards when barrier fraction creeps or a mis-sized bucket lattice starts
recompiling on the serving path. The sentinel watches the same per-step
stream the flight recorder sees and raises structured ANOMALY records (into
the flight ring, next to the steps that triggered them) plus a
``dynamo_anomaly_active{kind}`` gauge when the recent window regresses
against the process's own baseline:

- ``barrier_frac_spike`` — overlap barrier fraction in the window clears an
  absolute floor AND a ratio over the long-run baseline;
- ``step_gap_regression`` — mean host gap between dispatches spikes;
- ``goodput_drop`` — tokens-out per decode-carrying step collapses;
- ``recompile_storm`` — new-shape compiles bunch inside one window;
- ``onboard_shortfall_burst`` — tier onboard shortfall pages bunch up.

Detection is deliberately conservative: relative detectors arm only after
``min_samples`` baseline steps, and every one also requires an absolute
floor, so a quiet fleet (or a cold start legitimately filling the bucket
lattice) never false-positives. An active anomaly clears after
``clear_after`` consecutive quiet steps (hysteresis — no flapping gauge).
All knobs ride :class:`~dynamo_tpu.config.AnomalySettings` (``DYN_ANOMALY_*``).
"""

from __future__ import annotations

import logging
from collections import deque
from typing import Any

from dynamo_tpu.observability.flight import ANOMALY

logger = logging.getLogger(__name__)

#: Detector kinds (the dynamo_anomaly_active{kind} label values).
ANOMALY_KINDS = (
    "barrier_frac_spike",
    "step_gap_regression",
    "goodput_drop",
    "recompile_storm",
    "onboard_shortfall_burst",
)


class AnomalySentinel:
    """Per-engine rolling-window detectors fed from ``EngineCore.step()``.

    ``observe_step`` is on the step path: everything is O(1) per call
    (window sums are maintained incrementally), and the sentinel never
    raises into the engine — it is observability, not control flow.
    """

    def __init__(self, settings=None, *, flight=None, on_fire=None) -> None:
        if settings is None:
            from dynamo_tpu.config import load_anomaly_settings

            settings = load_anomaly_settings()
        self.settings = settings
        self.flight = flight
        #: Rising-edge sink, ``on_fire(kind, info)`` — called exactly once per
        #: edge (never while a kind stays active); the incident plane hangs
        #: capture off it. Exceptions are swallowed by _observe's guard.
        self.on_fire = on_fire
        self._window: deque[dict] = deque(maxlen=max(2, settings.window))
        # Incremental window aggregates (subtract the evictee, add the new).
        self._w = {"barrier": 0, "gap_ms": 0.0, "decode_steps": 0, "outputs": 0}
        # Cumulative totals over every observed step; baseline = total - window.
        self._t = {"steps": 0, "barrier": 0, "gap_ms": 0.0, "decode_steps": 0, "outputs": 0}
        # kind -> consecutive quiet steps since the condition last held.
        self._quiet: dict[str, int] = {}
        #: kind -> {"value", "threshold", "since_step"} while active.
        self.active: dict[str, dict[str, Any]] = {}
        #: kind -> rising edges ever fired (scoreboards / tests).
        self.fired: dict[str, int] = {}

    # -- observation -------------------------------------------------------

    def observe_step(
        self,
        *,
        wall_ms: float,
        gap_ms: float,
        barrier: bool,
        outputs: int,
        decode_rows: int,
        recompiles: int,
        shortfall_pages: int,
    ) -> None:
        """Fold one recorded engine step; evaluate every detector.

        ``recompiles`` and ``shortfall_pages`` are the engine's *cumulative*
        counters — the window delta is taken against the oldest entry.
        """
        if not self.settings.enable:
            return
        try:
            self._observe(
                wall_ms=wall_ms, gap_ms=gap_ms, barrier=barrier, outputs=outputs,
                decode_rows=decode_rows, recompiles=recompiles,
                shortfall_pages=shortfall_pages,
            )
        except Exception:
            logger.exception("anomaly sentinel failed (ignored)")

    def _observe(self, *, wall_ms, gap_ms, barrier, outputs, decode_rows,
                 recompiles, shortfall_pages) -> None:
        entry = {
            "barrier": 1 if barrier else 0,
            "gap_ms": float(gap_ms),
            "decode_steps": 1 if decode_rows > 0 else 0,
            "outputs": int(outputs) if decode_rows > 0 else 0,
            "recompiles": int(recompiles),
            "shortfall_pages": int(shortfall_pages),
        }
        if len(self._window) == self._window.maxlen:
            old = self._window[0]
            for k in self._w:
                self._w[k] -= old[k]
        self._window.append(entry)
        for k in self._w:
            self._w[k] += entry[k]
        self._t["steps"] += 1
        self._t["barrier"] += entry["barrier"]
        self._t["gap_ms"] += entry["gap_ms"]
        self._t["decode_steps"] += entry["decode_steps"]
        self._t["outputs"] += entry["outputs"]
        self._evaluate()

    # -- detectors ---------------------------------------------------------

    def _evaluate(self) -> None:
        s = self.settings
        n_w = len(self._window)
        full = n_w == self._window.maxlen
        n_base = self._t["steps"] - n_w
        armed = n_base >= s.min_samples and full

        # barrier_frac_spike
        w_frac = self._w["barrier"] / n_w if n_w else 0.0
        b_frac = (self._t["barrier"] - self._w["barrier"]) / n_base if n_base else 0.0
        self._update(
            "barrier_frac_spike",
            armed and w_frac >= s.barrier_frac and w_frac >= s.ratio * max(b_frac, 0.01),
            value=w_frac, threshold=s.barrier_frac,
        )

        # step_gap_regression
        w_gap = self._w["gap_ms"] / n_w if n_w else 0.0
        b_gap = (self._t["gap_ms"] - self._w["gap_ms"]) / n_base if n_base else 0.0
        self._update(
            "step_gap_regression",
            armed and w_gap >= s.gap_floor_ms and w_gap >= s.ratio * max(b_gap, 1.0),
            value=w_gap, threshold=s.gap_floor_ms,
        )

        # goodput_drop (decode-carrying steps only: an idle tail is not a drop)
        wd, bd = self._w["decode_steps"], self._t["decode_steps"] - self._w["decode_steps"]
        w_out = self._w["outputs"] / wd if wd else 0.0
        b_out = (self._t["outputs"] - self._w["outputs"]) / bd if bd else 0.0
        self._update(
            "goodput_drop",
            bd >= s.min_samples and wd >= max(8, n_w // 4)
            and b_out >= 1.0 and w_out <= b_out / s.ratio,
            value=w_out, threshold=b_out / s.ratio if s.ratio else 0.0,
        )

        # recompile_storm (cumulative counter delta across the window)
        comp_delta = self._window[-1]["recompiles"] - self._window[0]["recompiles"]
        self._update(
            "recompile_storm",
            full and comp_delta >= s.recompile_storm,
            value=comp_delta, threshold=s.recompile_storm,
        )

        # onboard_shortfall_burst
        sf_delta = self._window[-1]["shortfall_pages"] - self._window[0]["shortfall_pages"]
        self._update(
            "onboard_shortfall_burst",
            full and sf_delta >= s.shortfall_pages,
            value=sf_delta, threshold=s.shortfall_pages,
        )

    def _update(self, kind: str, firing: bool, *, value, threshold) -> None:
        if firing:
            self._quiet[kind] = 0
            if kind not in self.active:
                self.active[kind] = {
                    "value": round(float(value), 4),
                    "threshold": round(float(threshold), 4),
                    "since_step": self._t["steps"],
                }
                self.fired[kind] = self.fired.get(kind, 0) + 1
                logger.warning(
                    "anomaly %s: value %.4g over threshold %.4g (window %d steps)",
                    kind, value, threshold, len(self._window),
                )
                if self.flight is not None:
                    self.flight.record(
                        ANOMALY, anomaly=kind,
                        value=round(float(value), 4),
                        threshold=round(float(threshold), 4),
                        window=len(self._window),
                    )
                if self.on_fire is not None:
                    self.on_fire(kind, dict(self.active[kind], anomaly=kind,
                                            window=len(self._window)))
            else:
                self.active[kind]["value"] = round(float(value), 4)
        elif kind in self.active:
            self._quiet[kind] = self._quiet.get(kind, 0) + 1
            if self._quiet[kind] >= self.settings.clear_after:
                del self.active[kind]
                del self._quiet[kind]
                logger.info("anomaly %s cleared", kind)
