"""Engine flight recorder: a bounded ring of per-step structured records.

The metrics plane (``metrics.py``) exports the *last* step's composition and
cumulative counters — enough for dashboards, useless for postmortems: by the
time a stall or crash is noticed, the interesting steps are gone. The flight
recorder keeps the last N steps verbatim, the way an aircraft FDR does:

- ``step`` records — one per ``EngineCore.step()``: step kind (mixed /
  decode / drain), decode rows, prefill chunk rows/tokens, pool free pages,
  cumulative preemptions/rejections, step wall time and in-step runner
  dispatch time, plus the overlapped-execution fields ``gap_ms`` (host gap
  since the previous step completed — the window the device idles unless
  the DYN_OVERLAP pipeline hides it) and ``overlap_mode`` ("overlapped" /
  "barrier" while the pipeline is armed, "" otherwise).
- ``compile`` records — emitted by the :class:`~dynamo_tpu.observability.
  compile.CompileTracker` when a runner dispatch hits a never-seen shape
  bucket (the XLA recompile a generic tool cannot see).
- ``crash`` records — appended by ``EngineCore.step()`` when a step raises,
  capturing the failing step's context before the exception propagates.
- ``anomaly`` records — rising edges from the
  :class:`~dynamo_tpu.observability.anomaly.AnomalySentinel` rolling-window
  detectors, landed next to the steps that tripped them.

The ring is dumpable two ways: remotely via the ``debug_flight`` worker
endpoint behind ``GET /debug/flight/{worker}`` (``service.py``), and to a
JSONL file on unhandled engine-loop exceptions (``engine/service.py`` calls
:meth:`FlightRecorder.dump_jsonl`), so a dead worker still leaves its last
seconds on disk.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any

logger = logging.getLogger(__name__)

#: Record kinds written into the ring.
STEP = "step"
COMPILE = "compile"
CRASH = "crash"
ANOMALY = "anomaly"

_DEFAULT_CAPACITY = 2048
_DUMP_DIR_ENV = "DYN_FLIGHT_DUMP_DIR"
_CAPACITY_ENV = "DYN_FLIGHT_BUFFER"


def _default_capacity() -> int:
    try:
        return int(os.environ.get(_CAPACITY_ENV, str(_DEFAULT_CAPACITY)))
    except ValueError:
        return _DEFAULT_CAPACITY


class FlightRecorder:
    """Thread-safe bounded ring of structured engine records.

    Records are plain dicts carrying a monotonically increasing ``seq`` (so
    consumers can detect ring wrap: a gap in seq means records were lost),
    a wall-clock ``ts``, and a ``kind``. The recorder never raises into the
    engine — it is observability, not control flow.
    """

    def __init__(self, capacity: int | None = None) -> None:
        cap = capacity if capacity is not None else _default_capacity()
        self._records: deque[dict] = deque(maxlen=max(1, cap))
        self._lock = threading.Lock()
        self._seq = 0

    def record(self, kind: str, **fields: Any) -> dict:
        doc = {"seq": self._seq, "ts": time.time(), "kind": kind, **fields}
        with self._lock:
            doc["seq"] = self._seq
            self._seq += 1
            self._records.append(doc)
        return doc

    def snapshot(self, *, last: int | None = None, kind: str | None = None) -> list[dict]:
        """Ordered (oldest-first) copy of the ring, optionally filtered."""
        with self._lock:
            records = list(self._records)
        if kind is not None:
            records = [r for r in records if r.get("kind") == kind]
        if last is not None and last >= 0:
            records = records[-last:]
        return records

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def clear(self) -> None:
        with self._lock:
            self._records.clear()

    # -- crash dump --------------------------------------------------------

    def dump_jsonl(self, path: str | None = None, *, reason: str = "manual") -> str:
        """Write the ring to a JSONL file (one record per line, preceded by
        a header line identifying the dump); returns the path written.

        Default location: ``$DYN_FLIGHT_DUMP_DIR`` (or ``/tmp/dynamo-flight``),
        ``flight-<pid>-<unix ms>.jsonl`` — unique enough that successive
        crashes never clobber each other.
        """
        if path is None:
            d = os.environ.get(_DUMP_DIR_ENV, "/tmp/dynamo-flight")
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"flight-{os.getpid()}-{int(time.time() * 1e3)}.jsonl")
        records = self.snapshot()
        header = {
            "kind": "dump_header",
            "reason": reason,
            "pid": os.getpid(),
            "ts": time.time(),
            "records": len(records),
        }
        with open(path, "w") as f:
            f.write(json.dumps(header, default=str) + "\n")
            for r in records:
                f.write(json.dumps(r, default=str) + "\n")
        return path
