"""Engine core: continuous-batching step loop with stall-free mixed steps.

A ``step()`` fuses decode and prefill work into ONE runner dispatch: every
running sequence contributes a 1-token decode row, and waiting/resumed
prompts are admitted as *chunks* under a per-step token budget
(``chunk_prefill_tokens``, Sarathi-style stall-free batching) so a long
prompt never stalls the decode stream — it advances a bounded chunk per
step instead. A decode row is just the degenerate final chunk (one token
that samples), so both phases share the same jitted program at different
bucket shapes (see runner.py); there is no separate prefill/decode code
path on device. ``chunk_prefill_tokens=0`` restores the legacy
phase-exclusive behavior (a step is prefill XOR decode) — kept as the
baseline the bench stall probe compares against. Policy details:
``docs/SCHEDULER.md``.

Scheduling policy (extending the engines the reference wraps, vLLM-v0-style
admission + Sarathi-Serve chunking):

- Admission: FIFO from the waiting queue under the prefill token budget and
  page availability; prefix-cache matches reduce the budget charge. Pages
  are allocated per chunk, not per prompt, so a prompt bigger than the
  current free pool admits incrementally instead of head-of-line blocking.
- Decode first: running sequences' next-token pages are reserved before any
  chunk is sized, and decode rows ride every mixed dispatch.
- Preemption: on page exhaustion during decode, the most-recently-arrived
  running sequence is evicted (pages released, tokens kept) and requeued;
  mid-prefill sequences are preferred victims over decoding ones.
  Recomputation re-matches whatever prefix survived in cache and re-chunks.
- Pages commit to the prefix cache as they fill — chunk by chunk, so a long
  prompt's early pages are shareable before its prefill finishes — emitting
  KV stored events; eviction emits removed events (allocator.py). This
  feeds the KV-aware router's global index natively, replacing the
  reference's engine->ZMQ->NATS event bridge (SURVEY.md §3 call stack D).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from collections import deque
from typing import Callable

import numpy as np

from dynamo_tpu.engine.allocator import OutOfPagesError, PageAllocator
from dynamo_tpu.engine.runner import ModelRunner, StepBatch
from dynamo_tpu.engine.sequence import SeqStatus, Sequence
from dynamo_tpu.observability.flight import CRASH, STEP, FlightRecorder
from dynamo_tpu.protocols.common import EngineOutput, FinishReason, PreprocessedRequest
from dynamo_tpu.protocols.kv import ForwardPassMetrics, KvCacheEvent
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.faults import FAULTS, DropFault
from dynamo_tpu.tokens import DEFAULT_SALT
from dynamo_tpu.tracing import annotate

logger = logging.getLogger(__name__)

# Logprobs requests always compute this many alternatives on-device (one
# compiled program; per-request top_logprobs slices host-side — a static
# per-value k would recompile the step program for every distinct request
# setting). 20 = the OpenAI top_logprobs cap.
LOGPROBS_TOP_K = 20

# Every reason an overlapped step can record for barriering (first reason
# wins within a step; "idle" is the default when none was noted). This
# vocabulary is load-bearing: docs/SCHEDULER.md documents each row and
# tools/check_barrier_reasons.py pins both the _note_barrier call sites and
# the docs table against it — the two have drifted before.
BARRIER_REASONS = (
    "cancel",  # cancellation reaped mid-pipeline: in-flight writes are stale
    "runner",  # runner has no step_async (mock timing modes, embedders)
    "prefill",  # legacy XOR mode: whole-prompt prefill steps carry no decodes
    "constraint",  # constrained rows with lookahead disabled (knob = 0)
    "constraint_miss",  # lookahead mask-cache miss or candidate-cap overflow
    "spec",  # verify in flight (harvest-first) or spec cannot chain
    "drain",  # every live row finishes inside the in-flight step
    "pages",  # sole candidate cannot extend: commit in-flight, then re-check
    "fill",  # pipeline refill: dispatched with nothing in flight
    "idle",  # barrier step with no recorded reason (nothing dispatched)
)


@dataclasses.dataclass
class EngineConfig:
    num_pages: int = 512
    page_size: int = 16
    max_batch_size: int = 64
    max_prefill_tokens: int = 2048  # token budget per prefill step (chunked-prefill cap)
    max_seq_len: int = 4096
    eos_token_ids: tuple[int, ...] = ()
    enable_prefix_caching: bool = True
    # Sliding-window models: release pages whose every token has slid out of
    # the attention window (they can never be attended again). Committed
    # pages demote to evictable prefix cache; uncommitted ones free
    # immediately. A 32k-context window-4k Mistral stream otherwise pins
    # ~28k tokens of dead KV per sequence.
    swa_free_pages: bool = True
    salt: int = DEFAULT_SALT
    worker_id: int = 0
    # Fused decode steps per dispatch. >1 amortizes host<->device round trips
    # (vital on remote/tunneled chips); trades up to decode_steps-1 wasted
    # steps per finishing sequence and K-token stream granularity.
    decode_steps: int = 1
    # Per-step prefill token budget while decodable sequences are running:
    # prompts are admitted/advanced in chunks of at most this many tokens,
    # fused with the decode rows in one dispatch, so the longest decode
    # stall is one chunk-step rather than one whole-prompt prefill.
    # Distinct from max_prefill_tokens, which still caps a step with no
    # decodes to coalesce against. 0 disables chunking (legacy
    # prefill-XOR-decode steps; the bench stall probe's baseline).
    chunk_prefill_tokens: int = 512
    # Speculative decoding (DYN_SPEC_K): max draft tokens per decode row per
    # step, verified in one multi-token dispatch. 0 = off. Lossless: output
    # streams are bit-identical to spec_k=0 (greedy and seeded) — the
    # drafter only changes how many forwards the same tokens cost. Draft
    # tokens are charged against chunk_prefill_tokens and the decode-first
    # page reserve grows to cover spec_k+1 slots, so speculation composes
    # with chunked prefill, admission, and preemption (docs/SCHEDULER.md).
    spec_k: int = 0
    # SLO-native admission control (DYN_SLO_SCHED, dynamo_tpu/sched):
    # EDF-over-predicted-TTFT ordering of the waiting queue, per-tenant
    # quotas, and an ITL-driven chunk-budget controller. Off by default —
    # FIFO intake is then bit-identical to the pre-sched scheduler.
    slo_sched: bool = False
    # Overlapped execution (DYN_OVERLAP): a depth-1 pipeline — step N+1 is
    # dispatched with its decode rows' input tokens chained from N's
    # device-resident samples before N's tokens reach the host, so the chip
    # never idles on the per-step host round-trip. Mixed steps overlap too:
    # prefill chunk rows feed from host (their tokens are known), decode
    # rows chain; penalty history and the pos_limit write clamp are applied
    # in-graph, so penalized rows and budget-final tokens are not barriers.
    # Constrained (json_mode) rows chain via one-step-lookahead mask groups
    # (constraint_lookahead_tokens); multimodal/mrope rows chain with their
    # extras threaded through the explicit-args chained program; and
    # decode_steps>1 folds into the same pipeline as K chained sub-steps
    # per dispatch. Stops are evaluated one step late; a late-detected stop
    # cancels the in-flight row (its token is discarded, its pages released
    # — output streams stay bit-identical to overlap=False). The residual
    # barriers are cancellation, a lookahead-mask cache miss/cap overflow
    # (constraint_miss), and spec without an async verify. Reasons are
    # counted in overlap_barrier_counts and flight STEP records
    # (BARRIER_REASONS is the full vocabulary). docs/SCHEDULER.md.
    overlap: bool = False
    # Allow speculative verify dispatches to participate in the overlapped
    # pipeline (DYN_OVERLAP_SPEC): verify steps chain their base token from
    # the previous dispatch and their accepted tokens stay device-resident
    # to feed the next one. Off forces a barrier on every spec step (the
    # pre-PR-11 behavior); output streams are identical either way.
    overlap_spec: bool = True
    # Pipelined tier onboarding (DYN_ASYNC_ONBOARD; DYN_CACHE_AWARE also
    # arms it): admission no longer blocks on G2/G3/G4 payload reads — a
    # background session fetches them and they land through the batched
    # write_pages scatter while other rows (and later the same row's own
    # chunks) compute. The scheduler treats the pending pages like an
    # in-flight chunk: num_cached advances only when the session lands; a
    # fetch shortfall degrades to recompute exactly like the synchronous
    # path. Off keeps onboarding synchronous inside _schedule_prefill.
    async_onboard: bool = False
    # Cache-aware scheduling (DYN_CACHE_AWARE): the admission plane prices a
    # request by its *residual* (uncached) prefill tokens — resident G1
    # match plus capacity-tier probe — so EDF slack ranks a mostly-cached
    # long prompt ahead of a cold short one and tenant buckets charge only
    # the tokens that will actually be computed. Policy-only: off is
    # bit-identical to full-cost pricing. (The router's residual-prefill
    # cost term is armed by the same knob via sched.configure_cache_aware.)
    cache_aware: bool = False
    # Constrained-decode lookahead (DYN_CONSTRAINT_LOOKAHEAD_TOKENS): max
    # distinct successor machine states a chained json_mode row may fan out
    # to per step. At compose time the row's input token is still in flight,
    # so the engine precomputes the constraint mask for every admissible
    # candidate (grouped by successor state — JSON masks collapse thousands
    # of candidate tokens into a handful of states) and the chained program
    # selects the right one in-graph from the gathered token. Overflow or a
    # cold mask cache barriers that step (reason "constraint_miss") and
    # self-warms. 0 disables lookahead: every constrained step barriers
    # (reason "constraint") — the pre-lookahead behavior, kept as the bench
    # baseline.
    constraint_lookahead_tokens: int = 32


@dataclasses.dataclass
class _InflightStep:
    """A dispatched-but-unharvested device step.

    kind "step" is a plain (possibly mixed prefill+decode) single step;
    "spec" is a speculative verify. ns/samples/drafts snapshot the
    composition the harvest needs to apply the results — sequence state may
    have moved on (preemption, cancellation) by the time the tokens land,
    so apply skips any row whose sequence is no longer RUNNING. ``extra``
    holds the chained pure-decode sub-step handles a decode_steps>1 burst
    dispatched behind the primary step — harvested in dispatch order, one
    more token per row each."""

    batch: list
    handle: object
    kind: str = "step"
    ns: list | None = None  # real token columns per row (step/spec)
    n_dec: int = 0  # leading decode rows (the rest are prefill chunks)
    samples: list | None = None  # per-row: does the engine accept a sample?
    drafts: list | None = None  # per-decode-row draft tokens (spec)
    v: int = 1  # verify width (spec)
    extra: list = dataclasses.field(default_factory=list)  # burst sub-step handles


@dataclasses.dataclass
class _OnboardSession:
    """An admitted row's in-flight tier onboarding (config.async_onboard).

    The fetch thread fills ``payloads``/``tiers`` and sets ``done``; the
    engine thread lands the session under ``step_lock`` (device write +
    prefix-cache commit + ``num_cached`` advance) from ``_poll_onboards``.
    Cancellation (preempt/finish/abort) simply removes the session from the
    engine's list — the orphaned fetch thread finishes into this object and
    nobody reads it, so stale payloads can never land in reused pages."""

    seq: Sequence
    hashes: list  # full block-hash chain of the sequence
    start: int  # first onboard block index (== resident match length)
    pages: list  # freshly-allocated G1 pages awaiting payloads
    t0: float  # session start (perf_counter) for the wait histogram
    count_at_start: bool  # fold landed pages into num_cached_at_start
    payloads: list = dataclasses.field(default_factory=list)
    tiers: list = dataclasses.field(default_factory=list)
    done: threading.Event = dataclasses.field(default_factory=threading.Event)


class EngineCore:
    """Synchronous scheduler + executor. The async service layer drives it."""

    def __init__(
        self,
        runner: ModelRunner,
        config: EngineConfig,
        *,
        on_kv_event: Callable[[KvCacheEvent], None] | None = None,
        block_manager=None,  # dynamo_tpu.blocks.KvBlockManager (G2/G3 tiers)
        admission=None,  # sched.AdmissionController (overrides the env build)
        chunk_controller=None,  # sched.ChunkBudgetController (same)
    ) -> None:
        if runner.num_pages != config.num_pages or runner.page_size != config.page_size:
            raise ValueError("runner and engine config disagree on cache geometry")
        self.runner = runner
        self.config = config
        self.block_manager = block_manager
        self.allocator = PageAllocator(config.num_pages, config.page_size, on_event=on_kv_event)
        self.waiting: deque[Sequence] = deque()
        self.running: list[Sequence] = []
        # Admitted but mid-prompt: their next chunk is scheduled each step
        # (arrival order) before new admissions; they are not decodable
        # until the final chunk samples, at which point they move to
        # ``running``. Always empty when chunk_prefill_tokens == 0.
        self.prefilling: list[Sequence] = []
        # Composition of the latest dispatch + cumulative mixed-step stats —
        # the observable form of the stall-free invariant (tests, bench
        # stall probe): with chunking on, a dispatch carrying chunk rows
        # while decodable sequences exist must also carry their decode rows.
        self.last_step_info: dict = {}
        self.mixed_steps = 0
        self.stall_violations = 0  # prefill-only dispatches that starved decodes
        self._next_seq_id = 0
        self._eos = set(config.eos_token_ids)
        self.num_preemptions = 0
        self.admission_rejections = 0  # requests refused at add_request intake
        # SLO admission-control plane (None => legacy FIFO intake; the
        # explicit kwargs let tests/bench inject configured controllers
        # without touching the environment).
        self.admission = admission
        self.chunk_controller = chunk_controller
        if config.slo_sched:
            from dynamo_tpu.sched import build_admission_controller, build_chunk_controller

            if self.admission is None:
                self.admission = build_admission_controller()
            if self.chunk_controller is None and config.chunk_prefill_tokens > 0:
                self.chunk_controller = build_chunk_controller(config.chunk_prefill_tokens)
        if config.cache_aware and self.admission is not None:
            # Residual-cost admission (DYN_CACHE_AWARE): the EDF plane
            # prices every waiting request by its uncached prefill tokens.
            self.admission.cached_tokens_fn = self._cached_prefix_tokens
        # Last _schedule_prefill's admission outcome (flight STEP record).
        self.last_admission = {
            "admitted": 0, "deferred": 0, "deadline_slack_ms": 0.0, "cached_frac": 0.0,
        }
        # Async tier onboarding (config.async_onboard): live sessions, the
        # lazy fetch pool, and the counters the metrics/bench planes read.
        self._onboards: list[_OnboardSession] = []
        self._onboard_pool = None  # ThreadPoolExecutor, built on first use
        self.onboard_sessions = 0
        self.onboard_page_counts: dict[str, int] = {}  # tier -> pages landed
        self.onboard_shortfall_pages = 0  # probed but gone at fetch: recomputed
        self._onboard_waits: list[float] = []  # seconds; metrics plane drains
        self.onboard_wait_ms_sum = 0.0
        self.onboard_wait_count = 0
        # Overlap accounting: of the steps that had a session in flight, how
        # many still dispatched fresh device work (the pipelining win) vs
        # idled waiting on the fetch. overlap_frac = overlap / (overlap+stall).
        self.onboard_overlap_steps = 0
        self.onboard_stall_steps = 0
        self._onboard_pending_step = False
        # Speculative decoding: cumulative drafting/verify counters (metrics
        # plane syncs them; acceptance rate = accepted / proposed).
        self.spec_tokens_proposed = 0
        self.spec_tokens_accepted = 0
        self.spec_steps = 0
        # Attention dispatch-path accounting: steps by (phase, path) —
        # phase in {decode, verify, prefill}, path in {pallas, fallback,
        # ring} (runner._attn_dispatch). A serving config silently riding
        # the ~5x-slower gather formulation shows up here and at /metrics.
        self.attn_dispatch_counts: dict[tuple[str, str], int] = {}
        self._proposer = None
        if config.spec_k > 0:
            from dynamo_tpu.engine.spec import build_proposer

            self._proposer = build_proposer()
        # Flight recorder: last-N-steps ring for postmortems. The compile
        # tracker (when the runner has one — mock runners don't) sinks its
        # first-execution events into the same ring, so a flight dump shows
        # recompiles interleaved with the steps that triggered them.
        self.flight = FlightRecorder()
        _tracker = getattr(runner, "compile_tracker", None)
        if _tracker is not None:
            _tracker.bind_sink(self.flight.record)
        # Time-loss accounting (attribution plane): cumulative ms charged per
        # cause (the pinned attribution.LOSS_CAUSES vocabulary — barrier
        # reasons + queue/admission/onboard_stall/preempt/recompile/gap),
        # exported as dynamo_engine_lost_time_seconds_total{cause}. The
        # step-time totals let consumers compute non-compute wall time
        # (wall + gap - dispatch) and hence the unattributed residual.
        self.lost_time_ms: dict[str, float] = {}
        self.step_wall_ms_total = 0.0
        self.step_dispatch_ms_total = 0.0
        self._recompile_events_seen = 0  # tracker events already charged
        self.recompile_count = 0  # cumulative new_shape events (sentinel feed)
        # Anomaly sentinel: rolling-window self-diagnosis over the step
        # stream, raising ANOMALY flight records + dynamo_anomaly_active.
        from dynamo_tpu.observability.anomaly import AnomalySentinel
        from dynamo_tpu.observability.incidents import IncidentCapture

        self.sentinel = AnomalySentinel(flight=self.flight)
        # Incident plane: a sentinel rising edge (or a step crash, below)
        # snapshots a black-box bundle — flight excerpt, intersecting spans,
        # loss ledger, config — into the size-capped on-disk store, so a
        # worker that dies still leaves a postmortem artifact. The worker
        # label is refined to the lease id at telemetry bring-up (launch.py).
        self.incidents = IncidentCapture(worker=f"pid-{os.getpid()}", core=self)
        self.sentinel.on_fire = lambda kind, info: self.incidents.capture("anomaly", info)
        # Cumulative counters for the metrics plane.
        self._prompt_tokens_total = 0
        self._generated_tokens_total = 0
        # Tier write-through is collected per step and flushed as one batched
        # device->host read. The async service sets ``defer_offloads`` and
        # flushes after routing outputs, so token delivery never waits on
        # offload copies; direct drivers (tests, bench) flush at end of step.
        self.pending_offloads: list[tuple[int, int]] = []  # (block_hash, page_id)
        self.defer_offloads = False
        # Serializes step()/flush_offloads() (executor thread) against
        # abort_all() (event-loop thread, on service shutdown/failure): the
        # scheduler queues and page lists have no other cross-thread guard.
        self.step_lock = threading.RLock()
        self._head_stall_steps = 0
        # The dispatch in flight on device, not yet consumed (pipelined
        # bursts and the overlapped lookahead alike).
        self._inflight: _InflightStep | None = None
        # Effective-state advance for sequences with a dispatch in flight:
        # seq_id -> (cached_delta, emit_delta). cached_delta = new KV slots
        # the in-flight step writes for the row; emit_delta = 1 iff the row
        # samples a token the host has not seen yet. The scheduler and the
        # lookahead builder reason at num_cached + cached_delta /
        # num_generated + emit_delta so in-flight work is never
        # double-scheduled. Cleared whenever the in-flight step is consumed.
        self._inflight_adv: dict[int, tuple[int, int]] = {}
        # seq_id -> flat index into the runner's device-resident sample
        # buffer from the *latest async dispatch* (plain step: row i; spec
        # verify: row*verify_width + accepted_col, filled at harvest). A
        # chained dispatch sources these rows' input tokens in-graph.
        self._chain_map: dict[int, int] = {}
        # Constrained-row lookahead plans for the step being composed:
        # seq_id -> (successor masks, token -> group map). Built by
        # _plan_constraint_lookahead during routing, consumed by
        # _run_mixed_overlapped when it assembles the la_masks/la_groups
        # device arrays. Rebuilt whenever constrained rows route overlapped.
        self._la_plan: dict[int, tuple[list, np.ndarray]] = {}
        # Overlapped execution accounting (config.overlap): per-step mode —
        # "overlapped" when the step dispatched a chained lookahead while
        # harvesting the previous one, "barrier" otherwise — plus the host
        # gap between consecutive dispatches (device-idle observability).
        self._overlap_mode: str | None = None
        self.overlap_step_counts: dict[str, int] = {"overlapped": 0, "barrier": 0}
        # Why each barrier step barriered (first reason wins within a step):
        # cumulative reason -> count, mirrored to the metrics plane as
        # dynamo_engine_overlap_barrier_total{reason}.
        self.overlap_barrier_counts: dict[str, int] = {}
        self._overlap_barrier_reason: str | None = None
        # Rows that were in flight when a dispatch crashed (CRASH record).
        self._aborted_inflight = 0
        self._prev_step_end: float | None = None
        self.step_gap_ms_sum = 0.0
        self.step_gap_ms_count = 0
        self.step_gap_ms_last = 0.0
        # Steps recorded per kind ("mixed"/"prefill"/"decode"/"drain") — the
        # step-kind histogram behind loss_snapshot() and the metrics plane.
        self.step_kind_counts: dict[str, int] = {}
        # Constrained decoding (response_format json_object): the mask cache
        # needs token TEXT, so a tokenizer (or factory) must be installed
        # before json_mode requests are admitted.
        self._constraint_tok = None
        self._constraint_tok_factory = None
        self._mask_cache = None
        import threading as _threading

        self._constraint_lock = _threading.Lock()

    # -- request intake ----------------------------------------------------

    def add_request(self, request: PreprocessedRequest, context: Context | None = None) -> Sequence:
        context = context or Context()
        # Image content is part of the prefix-cache identity: two prompts
        # with identical placeholder tokens but different images must not
        # reuse each other's KV. The router folds the same value (tokens.py).
        from dynamo_tpu.tokens import mm_salt_fold

        salt = self.config.salt ^ mm_salt_fold(request.mm_inputs)
        seq = Sequence.from_request(
            self._next_seq_id, request, context,
            page_size=self.config.page_size, salt=salt,
        )
        self._next_seq_id += 1
        if not request.token_ids:
            return self._reject(seq, FinishReason.ERROR)
        max_prompt = self.config.max_seq_len - 1
        if len(request.token_ids) > max_prompt:
            return self._reject(seq, FinishReason.LENGTH)
        if request.sampling.json_mode:
            try:
                seq.constraint = self._make_constraint()
            except ValueError as exc:
                logger.warning("rejecting json_mode request: %s", exc)
                return self._reject(seq, FinishReason.ERROR)
        if request.mm_inputs:
            try:
                seq.mm_embeds = self._decode_mm_inputs(request)
                seq.mrope = self._mrope_for(request)
            except ValueError as exc:
                logger.warning("rejecting multimodal request: %s", exc)
                return self._reject(seq, FinishReason.ERROR)
        # A prompt needing more pages than the pool holds can never be
        # scheduled; admitting it would wedge the FIFO head forever.
        usable_pages = self.config.num_pages - 1  # page 0 is the reserved null page
        pages_needed = -(-len(request.token_ids) // self.config.page_size)
        if pages_needed > usable_pages:
            logger.warning(
                "rejecting request: prompt needs %d pages, pool holds %d",
                pages_needed, usable_pages,
            )
            return self._reject(seq, FinishReason.ERROR)
        self.waiting.append(seq)
        return seq

    def _reject(self, seq: Sequence, reason: FinishReason) -> Sequence:
        self.admission_rejections += 1
        seq.status = SeqStatus.FINISHED
        seq.finish_reason = reason
        return seq

    def set_constraint_tokenizer(self, tokenizer) -> None:
        self._constraint_tok = tokenizer

    def set_constraint_tokenizer_factory(self, factory) -> None:
        """Install the tokenizer source for constrained decoding. Loaded by
        warm_constraints (launch starts it at worker bring-up unless
        DYNAMO_WARM_CONSTRAINTS=0) or, failing that, by the first json_mode
        request."""
        self._constraint_tok_factory = factory

    def _make_constraint(self):
        from dynamo_tpu.constrained import JsonConstraint, TokenMaskCache

        with self._constraint_lock:
            if self._mask_cache is None:
                tok = self._constraint_tok
                if tok is None and self._constraint_tok_factory is not None:
                    tok = self._constraint_tok = self._constraint_tok_factory()
                if tok is None:
                    raise ValueError("json_mode needs a tokenizer on the engine worker")
                self._mask_cache = TokenMaskCache(
                    tok, self.runner.cfg.vocab_size, tuple(self._eos)
                )
            return JsonConstraint(self._mask_cache)

    def warm_constraints(self) -> None:
        """Pre-build the vocab piece table and the hot mask summaries OFF
        the serving loop (a cold 128k-vocab build walks every piece through
        the machine — seconds of work that must not land inside
        add_request and stall co-resident decode). Launch calls this on a
        daemon thread at worker startup; a json_mode request racing the
        warm-up just blocks on the same lock until it finishes."""
        from dynamo_tpu.constrained import MachineState, advance_text

        try:
            c = self._make_constraint()
            for prefix in ("", "{", '{"', '{"k"', '{"k":', '{"k": 1', "["):
                c.cache.mask_for(advance_text(MachineState(), prefix))
        except Exception:
            logger.debug("constraint warm-up skipped", exc_info=True)

    @property
    def constraint_mask_cache_hits(self) -> int:
        """Cumulative TokenMaskCache hits (mask builds + lookahead plans) —
        mirrored as dynamo_engine_constraint_mask_cache_hits_total."""
        return self._mask_cache.hits if self._mask_cache is not None else 0

    @property
    def constraint_mask_cache_misses(self) -> int:
        return self._mask_cache.misses if self._mask_cache is not None else 0

    def drain_constraint_build_seconds(self) -> list[float]:
        """Cold mask-build durations since the last scrape — observed into
        the dynamo_engine_constraint_mask_build_seconds histogram."""
        if self._mask_cache is None:
            return []
        return self._mask_cache.drain_build_seconds()

    def _decode_mm_inputs(self, request: PreprocessedRequest):
        """mm_inputs wire format -> [total_image_tokens, D] embeddings.

        The placeholder count in the prompt must match the embedding rows:
        a mismatch would silently shift every image's content."""
        import base64

        mi = request.mm_inputs
        try:
            arr = np.frombuffer(
                base64.b64decode(mi["embeds_b64"]), dtype=np.dtype(mi.get("dtype", "float32"))
            ).reshape(mi["shape"])
            arr = arr.reshape(-1, arr.shape[-1])
        except Exception as exc:  # malformed wire payloads must not escape
            raise ValueError(f"malformed mm_inputs: {exc}") from exc
        img_id = getattr(self.runner.cfg, "image_token_id", None) if hasattr(self.runner, "cfg") else None
        if img_id is None:
            raise ValueError("model has no image placeholder token")
        vid_id = getattr(self.runner.cfg, "video_token_id", None)
        n_placeholders = sum(1 for t in request.token_ids if t == img_id or t == vid_id)
        if n_placeholders != arr.shape[0]:
            raise ValueError(
                f"{n_placeholders} image placeholders vs {arr.shape[0]} embedding rows"
            )
        return arr

    def _mrope_for(self, request: PreprocessedRequest):
        """(pos3, delta) for an M-RoPE model's multimodal request; None for
        standard-rope models. The encode worker ships per-image grids in
        mm_inputs — without them the 3D positions are unknowable, so their
        absence on an M-RoPE model is a rejection, not a silent 1D fallback
        (which would quietly diverge from HF on every image prompt)."""
        cfg = getattr(self.runner, "cfg", None)
        if cfg is None or not getattr(cfg, "mrope_section", None):
            return None
        from dynamo_tpu.models.qwen2_vl import mrope_position_ids

        grids = request.mm_inputs.get("grids")
        if not grids:
            raise ValueError("M-RoPE model needs per-image grids in mm_inputs")
        pos3, delta = mrope_position_ids(
            request.token_ids, [tuple(g) for g in grids],
            image_token_id=cfg.image_token_id,
            video_token_id=cfg.video_token_id,
        )
        return pos3, delta

    @property
    def has_work(self) -> bool:
        return bool(
            self.waiting or self.running or self.prefilling or self._inflight is not None
        )

    # -- stepping ----------------------------------------------------------

    def step(self) -> list[tuple[Sequence, EngineOutput]]:
        """Advance the engine by one batched forward; returns per-seq deltas.

        Every step (and any raise out of one) lands a structured record in
        ``self.flight``: the step's composition is captured per step rather
        than last-write-wins, and a crash record snapshots the failing step's
        context before the exception propagates to the service loop (which
        dumps the ring to JSONL).
        """
        with self.step_lock:
            prev_info = self.last_step_info
            tracker = getattr(self.runner, "compile_tracker", None)
            disp0 = tracker.dispatch_seconds_total if tracker is not None else 0.0
            t0 = time.perf_counter()
            # Host gap since the previous step returned: the window where the
            # device has nothing newly dispatched (detok/stop/route/schedule
            # time). The overlapped loop exists to hide exactly this.
            gap_ms = (
                (t0 - self._prev_step_end) * 1e3 if self._prev_step_end is not None else 0.0
            )
            self._overlap_mode = None
            self._overlap_barrier_reason = None
            self._aborted_inflight = 0
            preempt0 = self.num_preemptions
            try:
                out = self._step_locked()
            except Exception as exc:
                inflight_rows = self._aborted_inflight or (
                    len(self._inflight.batch) if self._inflight is not None else 0
                )
                self.flight.record(
                    CRASH,
                    error=type(exc).__name__,
                    detail=str(exc)[:500],
                    waiting=len(self.waiting),
                    running=len(self.running),
                    prefilling=len(self.prefilling),
                    free_pages=self.allocator.num_free(),
                    inflight_rows=inflight_rows,
                    last_step_info=dict(self.last_step_info),
                )
                # After the CRASH flight record, so the bundle's flight
                # excerpt ends on the crash itself.
                self.incidents.capture(
                    "crash",
                    {
                        "error": type(exc).__name__,
                        "detail": str(exc)[:500],
                        "where": "engine_step",
                        "waiting": len(self.waiting),
                        "running": len(self.running),
                        "inflight_rows": inflight_rows,
                    },
                )
                raise
            wall_ms = (time.perf_counter() - t0) * 1e3
            info = self.last_step_info
            fresh = info is not prev_info  # _run_mixed built a new dict
            onboard_stalled = False
            if self._onboard_pending_step:
                # A tier fetch was in flight across this step: did the step
                # still dispatch device work (overlapped) or idle on it?
                if fresh:
                    self.onboard_overlap_steps += 1
                else:
                    self.onboard_stall_steps += 1
                    onboard_stalled = True
                self._onboard_pending_step = False
            if not fresh and not out and not self.running:
                self._prev_step_end = time.perf_counter()
                return out  # idle drain: nothing dispatched, nothing to record
            overlap_mode = ""
            barrier_reason = ""
            if self.config.overlap:
                overlap_mode = self._overlap_mode or "barrier"
                self.overlap_step_counts[overlap_mode] = (
                    self.overlap_step_counts.get(overlap_mode, 0) + 1
                )
                if overlap_mode == "barrier":
                    barrier_reason = self._overlap_barrier_reason or "idle"
                    self.overlap_barrier_counts[barrier_reason] = (
                        self.overlap_barrier_counts.get(barrier_reason, 0) + 1
                    )
            self.step_gap_ms_sum += gap_ms
            self.step_gap_ms_count += 1
            self.step_gap_ms_last = gap_ms
            if fresh:
                decode_rows = int(info.get("decode_rows", 0))
                chunk_rows = int(info.get("chunk_rows", 0))
                chunk_tokens = int(info.get("chunk_tokens", 0))
                spec_drafted = int(info.get("spec_drafted", 0))
                spec_accepted = int(info.get("spec_accepted", 0))
                kind = (
                    "mixed" if decode_rows and chunk_rows
                    else ("prefill" if chunk_rows else "decode")
                )
            else:
                decode_rows = len(self.running)
                chunk_rows = chunk_tokens = 0
                spec_drafted = spec_accepted = 0
                kind = "decode" if self.running else "drain"
            self.step_kind_counts[kind] = self.step_kind_counts.get(kind, 0) + 1
            dispatch_ms = (
                (tracker.dispatch_seconds_total - disp0) * 1e3 if tracker is not None else 0.0
            )
            # Consume (don't just read) the runner's dispatch label: a step
            # that only drains in-flight results must not re-count the
            # previous dispatch.
            attn = getattr(self.runner, "last_attn_dispatch", None)
            if attn is not None:
                self.runner.last_attn_dispatch = None
                self.attn_dispatch_counts[attn] = self.attn_dispatch_counts.get(attn, 0) + 1
            attn_phase, attn_path = attn if attn else ("", "")
            # Feed the chunk-budget controller only steps that carried decode
            # rows: their wall time is the ITL a running request observed.
            if self.chunk_controller is not None and decode_rows:
                self.chunk_controller.observe(wall_ms)
            # Device-cost join: the registry accumulated bytes/flops for every
            # dispatch this step made; against the dispatch wall that yields
            # the step's roofline fraction. Without a tracker (mock runners)
            # the step wall stands in for the dispatch wall.
            cost_reg = getattr(self.runner, "cost_registry", None)
            cost_fields: dict = {}
            if cost_reg is not None:
                step_hbm_bytes, step_flops = cost_reg.take_step()
                disp_s = (dispatch_ms if tracker is not None else wall_ms) / 1e3
                roofline_frac, _bound = cost_reg.roofline_of(
                    step_hbm_bytes, step_flops, disp_s
                )
                cost_fields = {
                    "hbm_bytes": int(step_hbm_bytes),
                    "flops": int(step_flops),
                    "roofline_frac": round(roofline_frac, 4),
                }
            self.flight.record(
                STEP,
                step_kind=kind,
                decode_rows=decode_rows,
                chunk_rows=chunk_rows,
                chunk_tokens=chunk_tokens,
                outputs=len(out),
                waiting=len(self.waiting),
                running=len(self.running),
                prefilling=len(self.prefilling),
                free_pages=self.allocator.num_free(),
                preemptions=self.num_preemptions,
                admission_rejections=self.admission_rejections,
                mixed_steps=self.mixed_steps,
                stall_violations=self.stall_violations,
                spec_drafted=spec_drafted,
                spec_accepted=spec_accepted,
                spec_accept_rate=(
                    round(spec_accepted / spec_drafted, 4) if spec_drafted else 0.0
                ),
                wall_ms=round(wall_ms, 3),
                dispatch_ms=round(dispatch_ms, 3),
                attn_phase=attn_phase,
                attn_path=attn_path,
                admitted=int(self.last_admission.get("admitted", 0)),
                deferred=int(self.last_admission.get("deferred", 0)),
                deadline_slack_ms=self.last_admission.get("deadline_slack_ms", 0.0),
                cached_frac=self.last_admission.get("cached_frac", 0.0),
                gap_ms=round(gap_ms, 3),
                overlap_mode=overlap_mode,
                barrier_reason=barrier_reason,
                chained_rows=int(info.get("chained_rows", 0)) if fresh else 0,
                **cost_fields,
            )
            # Time-loss accounting: every millisecond of this step's wall
            # clock that was not runner dispatch, plus the host gap before
            # it, lands under exactly one cause. Without a compile tracker
            # (mock/timing runners) the step wall IS the model-compute
            # analog, so only the gap is lost time.
            self.step_wall_ms_total += wall_ms
            self.step_dispatch_ms_total += dispatch_ms if tracker is not None else wall_ms
            host_ms = max(0.0, wall_ms - dispatch_ms) if tracker is not None else 0.0
            self._charge_loss("gap", gap_ms)
            if self.num_preemptions > preempt0:
                self._charge_loss("preempt", host_ms)
            elif onboard_stalled:
                self._charge_loss("onboard_stall", host_ms)
            elif overlap_mode == "barrier" and barrier_reason:
                self._charge_loss(barrier_reason, host_ms)
            else:
                self._charge_loss("gap", host_ms)
            if tracker is not None:
                events = tracker.events()
                for ev in events[self._recompile_events_seen:]:
                    if ev.get("reason") == "new_shape":
                        self.recompile_count += 1
                        self._charge_loss("recompile", float(ev.get("wall_ms", 0.0)))
                self._recompile_events_seen = len(events)
            self.sentinel.observe_step(
                wall_ms=wall_ms, gap_ms=gap_ms,
                barrier=overlap_mode == "barrier",
                outputs=len(out), decode_rows=decode_rows,
                recompiles=self.recompile_count,
                shortfall_pages=self.onboard_shortfall_pages,
            )
            self._prev_step_end = time.perf_counter()
            return out

    def _charge_loss(self, cause: str, ms: float) -> None:
        """Accumulate lost wall time under one attribution cause (ms)."""
        if ms > 0.0:
            self.lost_time_ms[cause] = self.lost_time_ms.get(cause, 0.0) + ms

    def loss_snapshot(self) -> dict:
        """Programmatic lost-time/step-kind snapshot (stable keys).

        The structured twin of the ``dynamo_engine_lost_time_seconds_total``
        and ``dynamo_engine_step_time_seconds_total`` exports, so the tuner
        and tests never scrape Prometheus text. All times are cumulative
        milliseconds since engine construction. Keys (pinned — extend, never
        rename):

        - ``lost_time_ms``: cumulative ms per attribution cause (the pinned
          :data:`~dynamo_tpu.observability.attribution.LOSS_CAUSES`
          vocabulary; absent cause = 0 charged so far).
        - ``step_time_ms``: ``{"wall", "dispatch", "gap"}`` cumulative totals.
        - ``step_kind_counts``: steps recorded per kind
          (``mixed``/``prefill``/``decode``/``drain``).
        - ``steps_total``: sum of ``step_kind_counts``.
        - ``overlap_step_counts`` / ``overlap_barrier_counts``: the overlap
          pipeline's mode and per-reason barrier tallies.
        - ``noncompute_wall_ms``: ``max(0, wall + gap - dispatch)`` — the
          denominator the burn-down targets divide by.
        - ``loss_coverage_frac``: fraction of non-compute wall the per-cause
          ledger accounts for (1.0 when nothing is unattributed).
        """
        wall = self.step_wall_ms_total
        dispatch = self.step_dispatch_ms_total
        gap = self.step_gap_ms_sum
        noncompute = max(0.0, wall + gap - dispatch)
        attributed = sum(
            ms for cause, ms in self.lost_time_ms.items()
            if cause not in ("queue", "admission")  # pre-step waits, not step wall
        )
        return {
            "lost_time_ms": dict(self.lost_time_ms),
            "step_time_ms": {"wall": wall, "dispatch": dispatch, "gap": gap},
            "step_kind_counts": dict(self.step_kind_counts),
            "steps_total": sum(self.step_kind_counts.values()),
            "overlap_step_counts": dict(self.overlap_step_counts),
            "overlap_barrier_counts": dict(self.overlap_barrier_counts),
            "noncompute_wall_ms": noncompute,
            "loss_coverage_frac": (
                min(1.0, attributed / noncompute) if noncompute > 0.0 else 1.0
            ),
        }

    def _step_locked(self) -> list[tuple[Sequence, EngineOutput]]:
        # Pending offloads must be read before allocate() can evict their
        # pages (deferred-mode safety; no-op when the service already flushed).
        self.flush_offloads()
        cancelled = self._reap_cancelled()
        if self._inflight is not None and (
            cancelled or (not self.config.overlap and (self.waiting or self.prefilling))
        ):
            # Composition is about to change. With overlap off an in-flight
            # step only exists defensively (config flipped mid-run) and
            # drains on any admission/chunk pressure; the chained pipeline
            # drains only on cancellation — reaping released the cancelled
            # rows' pages, so the in-flight step's writes for them are stale
            # and nothing new may be composed on top of it.
            if cancelled:
                self._note_barrier("cancel")
            out = cancelled + self._drain_inflight()
            if not self.defer_offloads:
                self.flush_offloads()
            return out
        chunks = self._schedule_prefill()
        overlap_ok, reason = self._overlap_route(chunks)
        if overlap_ok:
            with annotate("engine.overlap"):
                out = cancelled + self._run_mixed_overlapped(chunks)
            if not self.defer_offloads:
                self.flush_offloads()
            return out
        if reason is not None:
            self._note_barrier(reason)
        if self.config.overlap and self._inflight is not None:
            # Barrier with work in flight: commit it before any synchronous
            # dispatch. Chunks scheduled above keep their pages and are
            # re-scheduled (idempotently) next step.
            out = cancelled + self._drain_inflight()
            if not self.defer_offloads:
                self.flush_offloads()
            return out
        fused = self.config.chunk_prefill_tokens > 0
        if chunks or (fused and self.running and self.prefilling) or (
            self._spec_active() and self.running
        ):
            # Mixed step: decode rows + prefill-chunk rows in one dispatch.
            # Also taken with zero chunks scheduled (page-starved prefills):
            # decode must not wait on them. Legacy mode (fused=False) runs
            # the scheduled whole prompts without decode rows (XOR). With
            # speculation on, pure-decode steps route here too: the verify
            # dispatch supersedes the burst/pipelined decode paths (drafts
            # already amortize the per-step host round trip).
            with annotate("engine.mixed" if fused else "engine.prefill"):
                out = cancelled + self._run_mixed(chunks)
        elif self.running:
            with annotate("engine.decode"):
                out = cancelled + self._run_decode()
        else:
            out = cancelled + self._drain_inflight()
        if not self.defer_offloads:
            self.flush_offloads()
        return out

    def _reap_cancelled(self) -> list[tuple[Sequence, EngineOutput]]:
        out: list[tuple[Sequence, EngineOutput]] = []
        for q in (self.waiting, self.prefilling, self.running):
            for seq in list(q):
                if seq.context.is_stopped and seq.status is not SeqStatus.FINISHED:
                    self._finish(seq, FinishReason.CANCELLED)
                    out.append(
                        (
                            seq,
                            EngineOutput(
                                token_ids=[],
                                finish_reason=FinishReason.CANCELLED,
                                cumulative_tokens=seq.num_generated,
                                prompt_tokens=seq.num_prompt,
                                cached_tokens=seq.num_cached_at_start,
                            ),
                        )
                    )
        return out

    # -- overlapped pipeline routing ---------------------------------------

    def _note_barrier(self, reason: str) -> None:
        """Record why this step barriered (first reason wins)."""
        if self._overlap_barrier_reason is None:
            self._overlap_barrier_reason = reason

    def _adv(self, s: Sequence) -> tuple[int, int]:
        """(cached_delta, emit_delta) the in-flight dispatch owes ``s``."""
        return self._inflight_adv.get(s.seq_id, (0, 0))

    def _eff_cached(self, s: Sequence) -> int:
        """num_cached once the in-flight step lands."""
        return s.num_cached + self._adv(s)[0]

    def _eff_remaining(self, s: Sequence) -> int:
        """remaining_tokens at effective state, WITHOUT the live-row floor:
        <= 0 means the sequence reaches its finish line inside the in-flight
        step (it is excluded from the lookahead, and the late stop check at
        harvest finishes it). Matches Sequence.remaining_tokens for rows
        with nothing in flight."""
        de = self._adv(s)[1]
        return min(
            s.request.stop.max_tokens - (s.num_generated + de),
            self.config.max_seq_len - (len(s.tokens) + de),
        )

    def _overlap_route(self, chunks) -> tuple[bool, str | None]:
        """Decide whether this step runs the chained pipeline.

        Returns (use_overlap, barrier_reason). reason is None when overlap
        is simply off/idle; otherwise it names the composition the graph
        cannot absorb. Penalties, logprobs, page-budget-final tokens,
        admission, mixed prefill+decode, multimodal/mrope rows, json_mode
        constraints, and decode_steps>1 are deliberately NOT here — they
        are all chained in-graph now."""
        cfg = self.config
        if not cfg.overlap:
            return False, None
        if not hasattr(self.runner, "step_async"):
            return False, "runner"
        if chunks and cfg.chunk_prefill_tokens <= 0:
            # Legacy XOR mode: whole-prompt prefill steps carry no decode
            # rows, so there is nothing to chain.
            return False, "prefill"
        rows = (
            self.running
            + [s for s, _ in chunks]
            + [s for s in self.prefilling if self._adv(s)[1]]
        )
        if not rows:
            # Nothing schedulable. If a step is in flight its rows are all
            # finishing — let the driver harvest it; otherwise idle.
            return (self._inflight is not None), None
        if any(s.constraint is not None for s in rows):
            if cfg.constraint_lookahead_tokens <= 0:
                return False, "constraint"
            if not self._plan_constraint_lookahead(rows):
                # Cold successor mask or candidate fan-out past the cap:
                # barrier to the sync mask path (which warms exactly the
                # states that missed) and retry the pipeline next step.
                return False, "constraint_miss"
        if self._spec_active() and not (
            self.config.overlap_spec
            and hasattr(self.runner, "spec_step_async")
        ):
            # Speculation is on but cannot chain (knob off or runner has no
            # async verify): stand down entirely — barrier to the sync
            # verify path rather than silently dropping drafts (the
            # pre-ISSUE-11 behavior).
            return False, "spec"
        return True, None

    def _plan_constraint_lookahead(self, rows) -> bool:
        """Pre-build successor masks for constrained rows whose input token
        is still in flight. Returns False (barrier "constraint_miss") when
        any plan would need a mask the cache cannot produce warm.

        Soundness: at compose time exactly one step is unharvested, so the
        host constraint state is current through the *previous* harvested
        token — which makes ``constraint.mask(remaining_tokens)`` exactly
        the mask the in-flight step is sampling under (state unchanged
        since that compose, and remaining_tokens has not advanced for the
        in-flight emit). Every token that mask admits (minus EOS, whose
        sample the late stop check discards at harvest) is a candidate;
        candidates collapse into successor machine states and each state's
        mask at the row's post-emit remaining becomes one lookahead group."""
        cap = self.config.constraint_lookahead_tokens
        cache = self._mask_cache
        plan: dict[int, tuple[list, np.ndarray]] = {}
        self._la_plan = plan
        ok = True
        for s in rows:
            if s.constraint is None or s.seq_id not in self._chain_map:
                continue  # unchained constrained rows ship a host-built mask
            allowed = s.constraint.mask(s.remaining_tokens(self.config.max_seq_len))
            la = cache.lookahead_groups(s.constraint.state, allowed, cap)
            if la is None:
                ok = False
                continue
            states, group_of = la
            rem_next = self._eff_remaining(s)
            masks = []
            for ns in states:
                m = cache.peek_mask(ns, rem_next)
                if m is None:
                    # Cold successor summary: this step barriers to the sync
                    # mask path anyway, so spend the barrier warming the
                    # summary — otherwise a successor the stream never takes
                    # would stay cold and re-miss every step it remains a
                    # candidate.
                    cache.mask_for(ns, remaining=rem_next)
                    ok = False
                masks.append(m)
            if ok:
                plan[s.seq_id] = (masks, group_of)
        return ok

    def _attach_lookahead_masks(self, sb, batch, chain_src) -> None:
        """Ship per-row constraint masks as lookahead groups on a chained
        dispatch: ``la_masks[i, la_groups[i, tok]]`` is row i's sampling mask
        once its chained input token ``tok`` materialises in-graph. Group 0
        is the all-True identity (unconstrained rows; EOS candidates, whose
        rows finish at harvest before the sampled token is ever used)."""
        vocab = self.runner.cfg.vocab_size
        rows: dict[int, list] = {}
        groups = np.zeros((len(batch), vocab), np.int32)
        g_max = 1
        for i, s in enumerate(batch):
            if s.constraint is None:
                continue
            if chain_src[i] >= 0:
                # Routed here only after _plan_constraint_lookahead succeeded
                # for every chained constrained row: a missing plan is a bug,
                # not a fallback case.
                masks, group_of = self._la_plan[s.seq_id]
                groups[i] = np.where(group_of >= 0, group_of + 1, 0)
                rows[i] = masks
            else:
                # The host knows this row's input token (fresh chunk row or a
                # chain-lost decode row): one group holding its exact mask.
                # Non-final chunk rows' samples are discarded, so masking
                # them is harmless.
                rows[i] = [s.constraint.mask(s.remaining_tokens(self.config.max_seq_len))]
                groups[i] = 1
            g_max = max(g_max, 1 + len(rows[i]))
        la = np.zeros((len(batch), g_max, vocab), bool)
        la[:, 0] = True
        for i, masks in rows.items():
            for g, m in enumerate(masks):
                la[i, g + 1] = m
        sb.la_masks = la
        sb.la_groups = groups

    # -- prefill phase -----------------------------------------------------

    def chunk_budget_tokens(self) -> int:
        """The live per-step prefill chunk budget: the ITL-driven controller's
        current value when the SLO plane runs one, else the static config.
        Never 0 when the config is nonzero (the controller floors at
        ``chunk_floor_tokens``), so chunked-vs-legacy mode never flips."""
        if self.chunk_controller is not None:
            return self.chunk_controller.budget()
        return self.config.chunk_prefill_tokens

    def _schedule_prefill(self) -> list[tuple[Sequence, int]]:
        """Schedule this step's prefill work: ``(sequence, num_tokens)`` chunks.

        Continues mid-prompt sequences first (arrival order), then admits
        from the waiting queue FIFO, all under the per-step token budget:
        ``chunk_prefill_tokens`` while decodable sequences are running
        (decode-first — their stall is bounded by one chunk), the full
        ``max_prefill_tokens`` otherwise. Pages are allocated per chunk, so
        a prompt larger than the current free pool admits incrementally
        instead of parking at the queue head. With chunking disabled every
        scheduled chunk is a whole remaining prompt (legacy admission).

        A *resumed* (preempted) sequence already carries generated tokens;
        its "prompt" for this prefill is everything generated so far — the
        forward recomputes all uncached KV and the final chunk's sampled
        token is the legitimate next token of the continuation (no
        re-emission of old tokens).
        """
        # Land any finished onboarding sessions first: their rows' num_cached
        # advances here (engine thread, under step_lock), which both unblocks
        # their next chunk and frees this step from re-probing them.
        if self._onboards:
            self._poll_onboards(wait=False)
        ps = self.config.page_size
        chunk_budget = self.chunk_budget_tokens()
        chunked = chunk_budget > 0
        if chunked and self.running:
            budget = min(chunk_budget, self.config.max_prefill_tokens)
        else:
            budget = self.config.max_prefill_tokens
        chunks: list[tuple[Sequence, int]] = []
        # Decode first: the running sequences' next-token pages are spoken
        # for before any chunk is sized against the free pool. Speculation
        # widens the reserve to spec_k+1 slots per sequence — a chunk must
        # never get pages a verify row needs this step (draft allocation is
        # opportunistic and drops drafts rather than preempting, so without
        # the reserve a full pool would silently disable speculation).
        ahead = 1 + (self.config.spec_k if self._spec_active() else 0)
        reserve = sum(
            s.pages_needed(
                ps, self._adv(s)[0] + max(0, min(ahead, self._eff_remaining(s)))
            )
            for s in self.running
        ) if chunked else 0

        def free_pages() -> int:
            return max(0, self.allocator.num_free() - reserve)

        # 1) Continue sequences already mid-prompt (arrival order).
        for seq in self.prefilling:
            if budget <= 0:
                break
            if seq.onboard_pending:
                # Tier payloads still in flight: the row's cached prefix is
                # not final, so chunking it now would recompute tokens the
                # session is about to land. Skipped exactly like a
                # page-starved row; lands via _poll_onboards.
                continue
            # A chunk already in flight counts as computed (overlap): the
            # next chunk starts where the in-flight one will leave off.
            dc = self._adv(seq)[0]
            n = min(seq.prompt_remaining - dc, budget)
            # Cap by pages: slack in already-held pages + the free pool.
            n = min(n, len(seq.pages) * ps - (seq.num_cached + dc) + free_pages() * ps)
            if n <= 0:
                continue  # page-starved this step; decode still proceeds
            need = seq.pages_needed(ps, dc + n)
            if need:
                try:
                    seq.pages.extend(self.allocator.allocate(need))
                except OutOfPagesError:
                    continue
            budget -= n
            chunks.append((seq, n))

        # 2) Admit from the waiting queue (admission appends to
        # self.prefilling, so the live-sequence cap self-counts).
        # With the SLO plane attached, prepare() reorders the queue EDF
        # (least slack first) and returns how many head entries clear their
        # tenant quotas this step; without it the deque is untouched (FIFO,
        # bit-identical to the pre-sched scheduler).
        admissible: int | None = None
        quota_deferred = 0
        if self.admission is not None and self.waiting:
            admissible = self.admission.prepare(
                self.waiting,
                running=len(self.running) + len(self.prefilling),
                slots=self.config.max_batch_size,
            )
            # Admission-plane deferrals only: waiting entries the quota gate
            # held back at prepare time. Entries later skipped for pages /
            # prefill budget / batch slots are resource-limited, not deferred
            # by the controller, and don't belong in this count.
            quota_deferred = len(self.waiting) - admissible
        n_admitted = 0
        admit_cached = 0  # admission-time cached tokens (resident + probed)
        admit_total = 0  # total prompt tokens admitted this step
        while (
            self.waiting
            and budget > 0
            and (admissible is None or n_admitted < admissible)
            and len(self.running) + len(self.prefilling) < self.config.max_batch_size
        ):
            seq = self.waiting[0]
            if FAULTS.armed:
                try:
                    if FAULTS.fire("sched.admit") == "delay":
                        break  # deferred; retried next step
                except DropFault:
                    # Leave the seq in waiting but kill its context: next
                    # step's _reap_cancelled emits CANCELLED, so the client
                    # stream terminates instead of hanging outside all queues.
                    seq.context.kill()
                    break
            total = len(seq.tokens)  # prompt + any generated-before-preemption
            matched: list[int] = []
            onboard_n = 0  # tier blocks to onboard (payloads fetched post-alloc)
            hashes: list[int] = []
            if self.config.enable_prefix_caching:
                hashes = seq.block_seq.block_hashes
                matched = self.allocator.match_prefix(hashes)
                if self.block_manager is not None:
                    # Extend the G1 match from the capacity tiers (membership
                    # probe only; payload I/O happens after allocation succeeds).
                    onboard_n = self.block_manager.probe_prefix(hashes, len(matched))
                # Must compute at least the final token's logits.
                while (len(matched) + onboard_n) * ps > total - 1:
                    if onboard_n:
                        onboard_n -= 1
                    else:
                        self.allocator.release([matched.pop()])
            cached_len = (len(matched) + onboard_n) * ps
            num_new = total - cached_len
            # Pipelined onboarding (config.async_onboard): admit the row
            # with only its onboard-region pages allocated and ZERO chunk —
            # the tier payloads are fetched on a background thread and land
            # through the batched write_pages scatter while other rows (and
            # later this row's own chunks) compute. Legacy unchunked mode
            # keeps the synchronous path: its whole-prompt admission has no
            # later chunk for the session to overlap with.
            async_ob = self.config.async_onboard and chunked and onboard_n > 0
            if async_ob:
                n = 0
                try:
                    new_pages = self.allocator.allocate(onboard_n)
                except OutOfPagesError:
                    self.allocator.release(matched)
                    if not chunks and not self.running:
                        self._note_head_stall(seq, num_new)
                    break
            elif chunked:
                # First chunk: capped by the budget and by what the free
                # pool can hold. (Onboard pages hold fully *cached* tokens,
                # so any n >= 1 allocates at least the onboard_n pages.)
                n = min(num_new, budget)
                n = min(n, (len(matched) + free_pages()) * ps - cached_len)
                if n <= 0:
                    self.allocator.release(matched)
                    if not chunks and not self.running:
                        self._note_head_stall(seq, num_new)
                    break
            else:
                n = num_new
                if chunks and n > budget:
                    self.allocator.release(matched)
                    break
            if not async_ob:
                pages_goal = -(-(cached_len + n) // ps)
                try:
                    new_pages = self.allocator.allocate(pages_goal - len(matched))
                except OutOfPagesError:
                    self.allocator.release(matched)
                    if not chunks and not self.running:
                        self._note_head_stall(seq, num_new)
                    break
            self.waiting.popleft()
            seq.admitted_time = time.monotonic()
            n_admitted += 1
            admit_cached += cached_len
            admit_total += total
            if self.admission is not None:
                self.admission.on_admit(seq, seq.admitted_time)
            if onboard_n and not async_ob:
                # Pages exist now: fetch tier payloads, copy them in, and
                # commit — they re-enter the G1 prefix cache and re-announce
                # on the KV event plane. A fetch shortfall (evicted since the
                # probe) just means those tokens get recomputed.
                onboard, tiers = self.block_manager.fetch_prefix_tiered(
                    hashes, len(matched), onboard_n
                )
                if len(onboard) < onboard_n:
                    shortfall = onboard_n - len(onboard)
                    self.onboard_shortfall_pages += shortfall
                    onboard_n = len(onboard)
                    cached_len = (len(matched) + onboard_n) * ps
                    n += min(shortfall * ps, total - cached_len - n)
                self.block_manager.onboard(new_pages[: onboard_n], onboard)
                blocks = seq.block_seq.blocks
                for i, pid in enumerate(new_pages[:onboard_n]):
                    blk = blocks[len(matched) + i]
                    self.allocator.commit(pid, blk.block_hash, blk.parent_hash, blk.tokens)
                for tier in tiers[:onboard_n]:
                    self.onboard_page_counts[tier] = (
                        self.onboard_page_counts.get(tier, 0) + 1
                    )
            seq.pages = matched + new_pages
            seq.prefill_chunks = 0
            if async_ob:
                # The onboard region is pending: cached state reflects only
                # the resident match until the session lands (shortfall
                # pages then degrade to plain compute pages).
                seq.committed_pages = len(matched)
                seq.num_cached = len(matched) * ps
                if seq.status is not SeqStatus.PREEMPTED:
                    seq.num_cached_at_start = seq.num_cached  # re-set at land
                self._start_onboard(
                    seq, hashes, len(matched), new_pages,
                    count_at_start=seq.status is not SeqStatus.PREEMPTED,
                )
            else:
                seq.committed_pages = len(matched) + onboard_n
                seq.num_cached = cached_len
                if seq.status is not SeqStatus.PREEMPTED:
                    seq.num_cached_at_start = cached_len
            seq.status = SeqStatus.RUNNING
            self.prefilling.append(seq)
            budget -= n
            if n:
                chunks.append((seq, n))
        if chunks:
            self._head_stall_steps = 0
        elif (
            chunked
            and not self.running
            and len(self.prefilling) > 1
            and self._inflight is None
            and not self._onboards
        ):
            # Nothing can move: mid-prompt sequences pin every page among
            # themselves. Preempt the most recently arrived one (its pages
            # return to the pool / prefix cache) and retry — bounded by the
            # prefilling count. A sole mid-prompt sequence always fits (its
            # whole prompt passed the pool check in add_request). With a
            # step in flight, emptiness is progress (the in-flight chunks
            # land next step), not deadlock — never preempt a row whose
            # chunk is mid-air. An onboarding session in flight is progress
            # for the same reason: its row's cached prefix lands shortly.
            self._preempt(self.prefilling[-1])
            return self._schedule_prefill()
        if self._onboards and not chunks and not self.running:
            # Nothing else to run: block briefly on the fetch instead of
            # busy-spinning the step loop. Bounded wait — a hung tier read
            # never wedges the engine; landed sessions schedule next step.
            self._poll_onboards(wait=True)
        self._onboard_pending_step = bool(self._onboards)
        self.last_admission = {
            "admitted": n_admitted,
            "deferred": quota_deferred,
            "deadline_slack_ms": (
                round(self.admission.last_slack_ms, 3) if self.admission is not None else 0.0
            ),
            "cached_frac": round(admit_cached / admit_total, 4) if admit_total else 0.0,
        }
        return chunks

    def _note_head_stall(self, seq: Sequence, num_new: int) -> None:
        self._head_stall_steps += 1
        if self._head_stall_steps % 100 == 1:
            logger.warning(
                "head-of-queue seq %d cannot allocate pages for %d tokens "
                "(free %d pages) with nothing running; stalled %d steps",
                seq.seq_id, num_new, self.allocator.num_free(), self._head_stall_steps,
            )

    # -- async tier onboarding ---------------------------------------------

    def _start_onboard(
        self, seq: Sequence, hashes: list, start: int, pages: list, *, count_at_start: bool
    ) -> None:
        from concurrent.futures import ThreadPoolExecutor

        if self._onboard_pool is None:
            # Pool width bounds how many tier fetches overlap the forward
            # pass; on hardware wider pools contend with compute for HBM
            # bandwidth, so the width is a tunable (swept by the auto-tuner).
            width = max(1, int(os.environ.get("DYN_ONBOARD_POOL_WIDTH", "2")))
            self._onboard_pool = ThreadPoolExecutor(
                max_workers=width, thread_name_prefix="kv-onboard"
            )
        sess = _OnboardSession(
            seq=seq, hashes=list(hashes), start=start, pages=list(pages),
            t0=time.perf_counter(), count_at_start=count_at_start,
        )
        seq.onboard_pending = len(pages)
        self._onboards.append(sess)
        self.onboard_sessions += 1
        self._onboard_pool.submit(self._onboard_fetch, sess)

    def _onboard_fetch(self, sess: _OnboardSession) -> None:
        """Background path: tier reads only — never touches scheduler state
        (the engine thread lands the session under step_lock). Any failure,
        including an armed store.op fault on the G4 path, degrades to an
        empty fetch: the row recomputes, the engine never sees the raise."""
        try:
            sess.payloads, sess.tiers = self.block_manager.fetch_prefix_tiered(
                sess.hashes, sess.start, len(sess.pages)
            )
        except Exception:
            logger.exception(
                "tier fetch failed for seq %d; onboarding degrades to recompute",
                sess.seq.seq_id,
            )
            sess.payloads, sess.tiers = [], []
        finally:
            sess.done.set()

    def _cancel_onboards(self, seq: Sequence) -> None:
        """Forget any session for ``seq`` (preempt/finish): its pages are
        being released, so a later landing would scatter stale payloads into
        reused pages. The orphaned fetch thread finishes into the dropped
        session object, which nothing reads."""
        if self._onboards:
            self._onboards = [s for s in self._onboards if s.seq is not seq]
        seq.onboard_pending = 0

    def _poll_onboards(self, *, wait: bool) -> None:
        """Land finished onboarding sessions (engine thread, under step_lock).

        ``wait`` blocks briefly on the oldest session when the caller has
        nothing else to schedule — bounded, so a hung tier read degrades to
        a slow poll loop rather than a wedged engine."""
        if wait and self._onboards:
            self._onboards[0].done.wait(timeout=0.05)
        rest: list[_OnboardSession] = []
        for sess in self._onboards:
            if sess.done.is_set():
                self._land_onboard(sess)
            else:
                rest.append(sess)
        self._onboards = rest

    def _land_onboard(self, sess: _OnboardSession) -> None:
        """Apply a finished session: batched device write, prefix-cache
        commit, and the row's ``num_cached`` advance. A shortfall (blocks
        evicted or a tier fault since the probe) leaves the trailing pages
        as plain compute pages — the next chunk recomputes those tokens,
        exactly like the synchronous path."""
        seq = sess.seq
        wait_s = time.perf_counter() - sess.t0
        self._onboard_waits.append(wait_s)
        self.onboard_wait_ms_sum += wait_s * 1e3
        self.onboard_wait_count += 1
        # Per-request onboard segment for /debug/explain: the fetch ran in
        # the background, so only the measured session wait is attributable
        # to this request's critical path.
        from dynamo_tpu.tracing import record_span, trace_of

        record_span(
            "engine_onboard_wait", round(wait_s * 1e3, 3),
            trace=trace_of(seq.context), request_id=seq.request.request_id,
            pages=len(sess.pages),
        )
        if seq.status is not SeqStatus.RUNNING or seq not in self.prefilling:
            seq.onboard_pending = 0  # finished/preempted while in flight
            return
        ps = self.config.page_size
        expected = len(sess.pages)
        landed = min(len(sess.payloads), expected)
        if landed:
            self.block_manager.onboard(sess.pages[:landed], sess.payloads[:landed])
            blocks = seq.block_seq.blocks
            for i, pid in enumerate(sess.pages[:landed]):
                blk = blocks[sess.start + i]
                self.allocator.commit(pid, blk.block_hash, blk.parent_hash, blk.tokens)
            for tier in sess.tiers[:landed]:
                self.onboard_page_counts[tier] = self.onboard_page_counts.get(tier, 0) + 1
        if landed < expected:
            self.onboard_shortfall_pages += expected - landed
        seq.num_cached += landed * ps
        seq.committed_pages += landed
        if sess.count_at_start:
            seq.num_cached_at_start = seq.num_cached
        seq.onboard_pending = 0

    def drain_onboard_waits(self) -> list[float]:
        """Hand the accumulated per-session wait times (seconds) to the
        metrics plane — observed into the histogram exactly once."""
        out, self._onboard_waits = self._onboard_waits, []
        return out

    def _cached_prefix_tokens(self, seq: Sequence) -> int:
        """Admission-time estimate of this prompt's reusable KV tokens:
        the resident G1 prefix (non-mutating peek — pricing must not touch
        refcounts or LRU order) extended by the capacity-tier probe (local
        membership only — prepare() must never block on a store
        round-trip). Capped at len-1: the final token always computes."""
        if not self.config.enable_prefix_caching:
            return 0
        hashes = seq.block_seq.block_hashes
        m = self.allocator.peek_prefix(hashes)
        t = (
            self.block_manager.probe_prefix(hashes, m, local_only=True)
            if self.block_manager is not None
            else 0
        )
        return max(0, min((m + t) * self.config.page_size, len(seq.tokens) - 1))

    # -- speculative decoding ----------------------------------------------

    def _spec_active(self) -> bool:
        """Speculation runs only with a proposer AND a runner that has the
        verify dispatch (mock/timing runners don't; spec_k is then inert)."""
        return (
            self.config.spec_k > 0
            and self._proposer is not None
            and hasattr(self.runner, "spec_step")
        )

    def _propose_drafts(
        self, decode_rows: list[Sequence], chunks: list[tuple[Sequence, int]]
    ) -> list[list[int]]:
        """Per decode row, up to spec_k draft tokens for this step's verify.

        Drafts are charged against the mixed step's chunk budget (whatever
        the scheduled chunks left of it) — a draft token costs the same
        forward FLOPs/bytes as a prefill-chunk token, so letting drafts
        bypass the budget would reintroduce exactly the decode stalls the
        budget bounds. Page extension is opportunistic: on exhaustion the
        row's drafts are dropped rather than preempting anyone (speculation
        is a throughput hint, never worth evicting real work).

        Rows with repetition penalties or a decoding constraint never
        draft: both sample from state that evolves per accepted token
        (history counts, grammar machine), which the per-column verify
        sample cannot replay. Their single-token column stays exact.
        """
        k = self.config.spec_k
        budget = None
        chunk_budget = self.chunk_budget_tokens()
        if chunk_budget > 0:
            budget = max(
                0,
                min(chunk_budget, self.config.max_prefill_tokens)
                - sum(n for _, n in chunks),
            )
        drafts: list[list[int]] = []
        for s in decode_rows:
            # remaining - 1: the verify step emits at most len(draft) + 1
            # tokens, which must never overrun max_tokens / the context
            # window (this is also what keeps every speculative KV write
            # inside the row's position_limit). Effective state: a chained
            # row's in-flight token already counts against the budget.
            dc, de = self._adv(s)
            cap = min(k, self._eff_remaining(s) - 1)
            if budget is not None:
                cap = min(cap, budget)
            sp = s.request.sampling
            if cap <= 0 or sp.frequency_penalty or sp.presence_penalty or s.constraint is not None:
                drafts.append([])
                continue
            # Chained rows (de=1): the host context is stale by the in-flight
            # token. Propose one extra and drop the head — the proposer's
            # first continuation guesses the in-flight token itself; the rest
            # align with the draft positions after it. Any mismatch is caught
            # (losslessly) by the exact-replay verify.
            d = [int(tok) for tok in self._proposer.propose(s.tokens, cap + de)][de:]
            if d:
                need = s.pages_needed(self.config.page_size, dc + 1 + len(d))
                if need:
                    try:
                        s.pages.extend(self.allocator.allocate(need))
                    except OutOfPagesError:
                        d = []
            if budget is not None:
                budget -= len(d)
            self.spec_tokens_proposed += len(d)
            drafts.append(d)
        return drafts

    def _lp_cols(self, seq: Sequence, lp_aux, i: int, toks: list[int]) -> list[dict] | None:
        """Logprobs entries from the verify dispatch's per-column aux arrays
        ([B, V] / [B, V, k]): one entry per emitted token, column j of row i.
        Chunk rows pass a single token (their column 0)."""
        enc = seq.request.sampling.logprobs
        if not enc or lp_aux is None:
            return None
        alts = min(enc - 1, lp_aux["top_ids"].shape[-1])
        entries = []
        for j, tok in enumerate(toks):
            top = [
                [int(t), float(lp)]
                for t, lp in zip(lp_aux["top_ids"][i, j][:alts], lp_aux["top_lps"][i, j][:alts])
            ]
            entries.append({"id": int(tok), "logprob": float(lp_aux["logprob"][i, j]), "top": top})
        return entries

    def _run_mixed(self, chunks: list[tuple[Sequence, int]]) -> list[tuple[Sequence, EngineOutput]]:
        """One fused dispatch: a 1-token decode row per running sequence plus
        an n-token prefill row per scheduled chunk.

        Every row computes ``tokens[num_cached : num_cached + n]``; a decode
        row is just the degenerate chunk whose span ends at ``len(tokens)``.
        The runner samples every row; host-side, non-final chunk rows
        *discard* the sample — their rng fold counter (``num_generated``)
        does not advance, so the final chunk samples at exactly the fold a
        whole-prompt prefill would have used (golden parity, greedy and
        seeded). With chunking disabled this runs the scheduled whole
        prompts without decode rows — the legacy phase-exclusive step."""
        fused = self.config.chunk_prefill_tokens > 0
        spec = self._spec_active()
        out: list[tuple[Sequence, EngineOutput]] = []
        decode_rows: list[Sequence] = []
        if (fused or (spec and not chunks)) and self.running:
            failed = self._ensure_burst_pages(1)
            if failed is not None:
                out.append((failed, self._final_output(failed)))
            decode_rows = list(self.running)
        # Speculative drafts per decode row (empty lists when spec is off).
        # Must run after _ensure_burst_pages: preemption there invalidates
        # the row list. A decode row with drafts becomes a verify row — its
        # span is [input token, draft_1..draft_k] at consecutive positions.
        drafts: list[list[int]] = (
            self._propose_drafts(decode_rows, chunks) if spec and decode_rows
            else [[] for _ in decode_rows]
        )
        use_spec = spec and bool(decode_rows)
        self.last_step_info = {
            "decode_rows": len(decode_rows),
            "chunk_rows": len(chunks),
            "chunk_tokens": int(sum(n for _, n in chunks)),
            "decodable": len(self.running),
        }
        if chunks and fused:
            self.mixed_steps += 1
        if chunks and self.running and not decode_rows:
            self.stall_violations += 1  # legacy XOR: this dispatch stalls decodes
        batch = decode_rows + [s for s, _ in chunks]
        if not batch:
            return out
        n_dec = len(decode_rows)
        ns = [1 + len(d) for d in drafts] + [n for _, n in chunks]
        ps = self.config.page_size
        t = max(ns)
        npg = max(len(s.pages) for s in batch)
        b = len(batch)
        tokens = np.zeros((b, t), np.int32)
        positions = np.zeros((b, t), np.int32)
        block_tables = np.zeros((b, npg), np.int32)
        slots = np.zeros((b, t), np.int32)
        last = np.zeros(b, np.int32)
        for i, (s, n) in enumerate(zip(batch, ns)):
            if i < n_dec and n > 1:
                # Verify row: the committed next input token + its drafts.
                # Drafts are NOT in s.tokens — they only join the sequence
                # (and its hash chain) if verification accepts them.
                new = [s.tokens[s.num_cached]] + drafts[i]
            else:
                new = s.tokens[s.num_cached : s.num_cached + n]
            tokens[i, :n] = new
            pos = np.arange(s.num_cached, s.num_cached + n, dtype=np.int32)
            positions[i, :n] = pos
            block_tables[i, : len(s.pages)] = s.pages
            page_arr = np.asarray(s.pages, dtype=np.int32)
            slots[i, :n] = page_arr[pos // ps] * ps + pos % ps
            last[i] = n - 1
        # A row samples iff its span reaches the end of its tokens: all
        # decode rows, and exactly the chunks that finish their prompt.
        samples = [
            i < n_dec or s.num_cached + n == len(s.tokens)
            for i, (s, n) in enumerate(zip(batch, ns))
        ]
        sb = self._sampling_batch(batch, tokens, positions, block_tables, slots, last)
        self._mm_rows(sb, batch, ns, n_dec, positions, lambda s: s.num_cached)
        sb.num_new = np.asarray(ns, np.int32)
        lp_k = LOGPROBS_TOP_K if any(
            s.request.sampling.logprobs and smp for s, smp in zip(batch, samples)
        ) else 0
        sb.logit_mask = self._constraint_masks(batch)
        targets = None
        try:
            if use_spec:
                # Verify dispatch: decode rows score every candidate column,
                # chunk rows only their last (start n-1) — so chunk sampling
                # stays bit-identical to the non-speculative step program.
                sb.spec_start = np.asarray(
                    [0] * n_dec + [n - 1 for _, n in chunks], np.int32
                )
                v = self.config.spec_k + 1
                stepped = (
                    self.runner.spec_step(sb, v, lp_k=lp_k) if lp_k
                    else self.runner.spec_step(sb, v)
                )
                targets, lp_aux = stepped if lp_k else (stepped, None)
                next_tokens = targets[:, 0]
            else:
                stepped = self.runner.step(sb, lp_k=lp_k) if lp_k else self.runner.step(sb)
                next_tokens, lp_aux = stepped if lp_k else (stepped, None)
        except Exception:
            # Chunk seqs live in self.prefilling (and decode rows in
            # self.running); _finish removes them and releases their pages.
            for s in batch:
                self._finish(s, FinishReason.ERROR)
            raise
        rec = _InflightStep(
            batch, None, kind="spec" if use_spec else "step",
            ns=ns, n_dec=n_dec, samples=samples, drafts=drafts,
            v=(self.config.spec_k + 1 if use_spec else 1),
        )
        return out + self._apply_mixed_results(rec, next_tokens, targets, lp_aux)

    def _mm_rows(self, sb: StepBatch, batch, ns, n_dec, positions, cached_of) -> None:
        """Attach multimodal extras to a (possibly mixed) step batch: packed
        image embeddings for the prefill chunk rows and explicit 3-axis
        M-RoPE coords for every row when any row needs them. ``cached_of``
        maps a sequence to its first computed index this step — num_cached
        on the sync path, the effective (in-flight-advanced) state on the
        overlapped path. Both paths produce identical arrays for the same
        row span, which is what keeps chained multimodal dispatches
        bit-identical to the synchronous step."""
        b, t = positions.shape
        if any(s.mm_embeds is not None for s in batch[n_dec:]):
            d = next(s.mm_embeds.shape[1] for s in batch if s.mm_embeds is not None)
            m = max(s.mm_embeds.shape[0] for s in batch if s.mm_embeds is not None)
            img_id = self.runner.cfg.image_token_id
            vid_id = self.runner.cfg.video_token_id
            mm = np.zeros((b, m, d), np.float32)
            off = np.full(b, -1, np.int32)  # -1: text row, no substitution
            counts = np.zeros(b, np.int32)
            for i, (s, n) in enumerate(zip(batch, ns)):
                # Decode rows keep -1 (a sampled image-token id is an
                # ordinary token there, exactly as in pure decode steps).
                if s.mm_embeds is not None and i >= n_dec:
                    mm[i, : s.mm_embeds.shape[0]] = s.mm_embeds
                    counts[i] = s.mm_embeds.shape[0]
                    # Placeholders already covered by cached/previous chunks.
                    cached = np.asarray(s.tokens[: cached_of(s)], np.int32)
                    off[i] = int(np.count_nonzero(
                        (cached == img_id) | (cached == (vid_id if vid_id is not None else -1))
                    ))
            sb.mm_embeds, sb.mm_slot_offset, sb.mm_counts = mm, off, counts
        if any(s.mrope is not None for s in batch):
            # Per-token 3D rope coords for this step's columns. Rows without
            # mrope (text prompts sharing the batch) use sequential positions
            # on all axes — exactly 1D rope. Indices past the stored prompt
            # coords (recomputed generated tokens and decode rows) sit at
            # index + delta.
            mrope3 = np.broadcast_to(positions[:, None, :], (b, 3, t)).copy()
            for i, (s, n) in enumerate(zip(batch, ns)):
                if s.mrope is None:
                    continue
                pos3, delta = s.mrope
                ec = cached_of(s)
                idx = np.arange(ec, ec + n)
                in_prompt = idx < pos3.shape[1]
                cols = np.where(
                    in_prompt[None, :], pos3[:, np.minimum(idx, pos3.shape[1] - 1)],
                    (idx + delta)[None, :],
                )
                mrope3[i, :, :n] = cols
            sb.mrope_positions = mrope3.astype(np.int32)

    def _apply_mixed_results(
        self,
        rec: _InflightStep,
        next_tokens,
        targets,
        lp_aux,
        *,
        chain_out: bool = False,
    ) -> list[tuple[Sequence, EngineOutput]]:
        """Apply a (possibly mixed / speculative) step's sampled tokens.

        Shared by the synchronous path and the overlapped harvest. Rows
        whose sequence left RUNNING while the step was in flight
        (cancelled, preempted) are skipped — their samples are discarded,
        exactly like burst overshoot. With ``chain_out`` (spec harvest in
        the overlapped pipeline) each surviving row's last accepted token
        is recorded in ``_chain_map`` as a flat index into the runner's
        device-resident ``[B*V]`` targets buffer, so the next dispatch can
        chain from it without the token ever leaving the device; plain
        dispatches record their map at dispatch time instead. When called
        from the overlapped harvest, ``last_step_info`` is the *current*
        step's dict — a harvest step's spec fields therefore describe the
        previous dispatch's acceptance, which is when it becomes known."""
        batch, ns, n_dec = rec.batch, rec.ns, rec.n_dec
        drafts, samples = rec.drafts, rec.samples
        use_spec = rec.kind == "spec"
        ps = self.config.page_size
        out: list[tuple[Sequence, EngineOutput]] = []
        spec_accepted = 0
        for i, (s, n) in enumerate(zip(batch, ns)):
            if s.status is not SeqStatus.RUNNING:
                self._chain_map.pop(s.seq_id, None)
                continue
            if use_spec and i < n_dec:
                # Verify row: accept the longest draft prefix the target
                # tokens replay exactly, plus the bonus token after it.
                # targets[i, j] is the token the non-speculative engine
                # would sample after j accepted tokens (fold counter
                # num_generated + j), so once targets[i, j] != draft[j]
                # the later columns were scored on a context the real
                # stream never reaches and are discarded.
                draft = drafts[i]
                emitted = [int(next_tokens[i])]
                while len(emitted) <= len(draft) and emitted[-1] == draft[len(emitted) - 1]:
                    emitted.append(int(targets[i, len(emitted)]))
                accepted: list[int] = []
                for tok in emitted:
                    s.num_cached += 1
                    s.append_token(tok)
                    self._generated_tokens_total += 1
                    accepted.append(tok)
                    if s.check_stop(self._eos, self.config.max_seq_len) is not None:
                        break  # overshoot past EOS/length is discarded
                spec_accepted += max(0, len(accepted) - 1)
                if chain_out and not s.is_finished:
                    # accepted[-1] == targets[i, len(accepted) - 1]: its flat
                    # index feeds the next dispatch's chained column 0.
                    self._chain_map[s.seq_id] = i * rec.v + len(accepted) - 1
                # Roll back speculative pages the accepted span didn't
                # reach: they were freshly allocated this step (commit
                # never passes num_cached), so release returns them to the
                # free pool immediately.
                if not s.is_finished:
                    keep = s.num_cached // ps + 1
                    if len(s.pages) > keep:
                        extra = [p for p in s.pages[keep:] if p != 0]
                        del s.pages[keep:]
                        if extra:
                            self.allocator.release(extra)
                self._commit_filled_pages(s)
                self._release_out_of_window(s)
                # May finish the sequence (page release) — must follow commit.
                self._accept_constrained(s, accepted)
                out.append(self._emit_many(s, accepted, self._lp_cols(s, lp_aux, i, accepted)))
                continue
            # Prompt-token accounting: only the prompt part of the span
            # (recomputed generated tokens and decode rows contribute 0).
            self._prompt_tokens_total += max(0, min(s.num_cached + n, s.num_prompt) - s.num_cached)
            s.num_cached += n
            if n > 1 or not samples[i]:
                s.prefill_chunks += 1
            if samples[i]:
                tok = int(next_tokens[i])
                s.append_token(tok)
                self._generated_tokens_total += 1
                self._commit_filled_pages(s)
                self._release_out_of_window(s)
                # May finish the sequence (page release) — must follow commit.
                self._accept_constrained(s, [tok])
                if chain_out and not s.is_finished:
                    self._chain_map[s.seq_id] = i * rec.v  # its column 0
                lp = (self._lp_cols(s, lp_aux, i, [tok]) if use_spec
                      else self._lp_entries(s, lp_aux, i))
                out.append(self._emit(s, tok, lp))
            else:
                # Non-final chunk: publish its full pages (shareable before
                # the prefill finishes) and discard the sampled token — the
                # rng fold counter stays put for the final chunk.
                self._commit_filled_pages(s)
                self._release_out_of_window(s)
        if use_spec:
            drafted = sum(len(d) for d in drafts)
            self.spec_steps += 1
            self.spec_tokens_accepted += spec_accepted
            self.last_step_info["spec_drafted"] = drafted
            self.last_step_info["spec_accepted"] = spec_accepted
            self.last_step_info["spec_accept_rate"] = (
                round(spec_accepted / drafted, 4) if drafted else 0.0
            )
        # Chunks whose final span sampled are decodable now.
        for s in batch[n_dec:]:
            if s in self.prefilling and s.prompt_remaining <= 1 and not s.is_finished:
                self.prefilling.remove(s)
                self.running.append(s)
        return out

    # -- overlapped mixed pipeline -----------------------------------------

    def _ensure_lookahead_pages(
        self, rows: list[Sequence], horizon: int = 1
    ) -> Sequence | None:
        """Give every lookahead decode row pages covering its chained writes
        (positions ``eff_cached .. eff_cached + horizon - 1``, clamped to
        the row's finish line); preempt on exhaustion. horizon > 1 is the
        decode_steps burst composing multiple chained sub-steps up front.
        Rows preempted by an earlier row's allocation are dropped from
        ``rows`` in place (the driver re-filters afterwards for victims
        already behind the cursor). A sole row that cannot fit is returned
        *unfinished* — the step in flight may hold its legitimate finish."""
        ps = self.config.page_size
        i = 0
        while i < len(rows):
            s = rows[i]
            if s.status is not SeqStatus.RUNNING:
                rows.pop(i)
                continue
            need = s.pages_needed(
                ps, self._adv(s)[0] + max(1, min(horizon, self._eff_remaining(s)))
            )
            if need:
                try:
                    s.pages.extend(self.allocator.allocate(need))
                except OutOfPagesError:
                    victim = self.running[-1] if self.running else s
                    if victim is s and len(self.running) <= 1:
                        return s
                    self._preempt(victim)
                    continue  # retry same index (rows may shrink behind us)
            i += 1
        return None

    def _abort_pipeline(self, batch: list[Sequence]) -> None:
        """A dispatch crashed mid-pipeline: fail its rows AND whatever was
        still in flight (rows finishing inside the in-flight step live only
        there), then reset the chain state so a recovering caller starts
        from a clean pipeline. No pages leak — ``_finish`` releases each
        sequence's pages exactly once."""
        failed: dict[int, Sequence] = {id(s): s for s in batch}
        if self._inflight is not None:
            self._aborted_inflight = len(self._inflight.batch)
            for s in self._inflight.batch:
                failed.setdefault(id(s), s)
            self._inflight = None
        self._inflight_adv = {}
        self._chain_map = {}
        if hasattr(self.runner, "reset_chain"):
            self.runner.reset_chain()
        for s in failed.values():
            if s.status is not SeqStatus.FINISHED:
                self._finish(s, FinishReason.ERROR)

    def _run_mixed_overlapped(
        self, chunks: list[tuple[Sequence, int]]
    ) -> list[tuple[Sequence, EngineOutput]]:
        """Depth-1 overlapped pipeline over *mixed* steps (DYN_OVERLAP).

        Generalizes PR 10's pure-decode chaining: step N+1 is composed at
        the sequences' *effective* state (``_inflight_adv``) and dispatched
        before step N's tokens reach the host. Decode rows whose input
        token is still in flight gather it in-graph from the previous
        dispatch's device buffer (``_chain_map``); prefill chunk rows feed
        from host as always (their tokens are known). Penalty history is
        restored in-graph for chained rows and the pos_limit mask clamps
        any would-be overrun write, so penalized rows and budget-final
        tokens need no barrier. Rows that finish *inside* the in-flight
        step are excluded from the lookahead (their finish is detected at
        harvest, one step late — streams stay bit-identical to
        overlap=False). A speculative verify in flight is harvested first —
        its acceptance decides every position after it — and the next
        dispatch chains out of its device-resident targets buffer, so even
        then tokens never round-trip through the host.

        Compositions the pre-lookahead pipeline barriered on now ride it
        too: constrained rows select their mask in-graph from the
        precomputed lookahead groups (_plan_constraint_lookahead),
        multimodal/mrope rows thread their extras through the explicit-args
        chained program, and decode_steps>1 issues K-1 extra pure-decode
        sub-steps chained back-to-back behind the primary dispatch (the
        whole burst is harvested one step late, exactly like a single
        chained step).
        """
        fused = self.config.chunk_prefill_tokens > 0
        out: list[tuple[Sequence, EngineOutput]] = []
        info = self.last_step_info = {
            "decode_rows": 0,
            "chunk_rows": len(chunks),
            "chunk_tokens": int(sum(n for _, n in chunks)),
            "decodable": len(self.running),
            "chained_rows": 0,
        }
        inf = self._inflight
        if inf is not None and inf.kind == "spec":
            # A verify's acceptance decides every position that follows —
            # nothing can be composed until it lands. Harvest first; the
            # accepted tokens stay device-resident (flat targets buffer)
            # and the dispatch below chains out of them via _chain_map.
            self._note_barrier("spec")
            out += self._harvest_inflight()
            inf = None
        # Decode candidates at effective state: running rows still short of
        # their finish line, plus rows whose *final* prompt chunk is in
        # flight (decodable the moment it lands — the chained dispatch
        # consumes their sample device-side). Rows finishing inside the
        # in-flight step are excluded: a chained write would have no legal
        # position; the late stop check at harvest ends them.
        decode_rows = [
            s for s in self.running
            if s.status is SeqStatus.RUNNING and self._eff_remaining(s) >= 1
        ] + [
            s for s in self.prefilling
            if self._adv(s)[1] and self._eff_remaining(s) >= 1
        ]
        if not decode_rows and not chunks:
            # Everything live is finishing inside the in-flight step (or
            # the schedule is page-starved): commit it and rebuild the
            # pipeline next step.
            if inf is not None:
                self._note_barrier("drain")
                out += self._drain_inflight()
            return out
        spec = (
            self._spec_active()
            and self.config.overlap_spec
            and hasattr(self.runner, "spec_step_async")
        )
        # decode_steps>1 folds into the pipeline as K chained pure-decode
        # sub-steps behind the primary dispatch. Only clean decode batches
        # burst: chunks change composition mid-burst; speculation already
        # amortizes the round trip; constraints need a fresh mask per token
        # (the lookahead plan is depth-1); per-step logprobs and penalty
        # history need the host between tokens.
        k_cfg = max(1, self.config.decode_steps)
        want_burst = (
            k_cfg > 1
            and not chunks
            and not spec
            and bool(decode_rows)
            and not any(
                s.constraint is not None
                or s.request.sampling.logprobs
                or s.request.sampling.frequency_penalty
                or s.request.sampling.presence_penalty
                for s in decode_rows
            )
        )
        failed = self._ensure_lookahead_pages(
            decode_rows, k_cfg if want_burst else 1
        )
        if failed is not None and want_burst:
            # The burst horizon didn't fit; a single lookahead token still
            # might — retry at depth 1 before declaring the row stuck.
            want_burst = False
            failed = self._ensure_lookahead_pages(decode_rows, 1)
        if failed is not None:
            # The sole candidate can't extend: the in-flight step may hold
            # its legitimate finish — commit that first, then re-check.
            self._note_barrier("pages")
            out += self._drain_inflight()
            if failed.status is SeqStatus.RUNNING:
                f2 = self._ensure_burst_pages(1)
                if f2 is not None:
                    out.append((f2, self._final_output(f2)))
            return out
        # _ensure_lookahead_pages may have preempted rows already behind
        # its cursor; drop them (their recompute is scheduled from waiting).
        decode_rows = [s for s in decode_rows if s.status is SeqStatus.RUNNING]
        k_burst = 1
        if want_burst and decode_rows:
            # Never burst a row past its finish line: unlike the sync fused
            # burst there is no cheap overshoot to discard — every sub-step
            # is a real dispatch — so the shortest row clamps the depth.
            k_burst = max(
                1, min(k_cfg, min(self._eff_remaining(s) for s in decode_rows))
            )
        drafts = (
            self._propose_drafts(decode_rows, chunks) if spec and decode_rows
            else [[] for _ in decode_rows]
        )
        if any(s.mrope is not None for s in decode_rows):
            # mrope decode rows chain fine (their position delta rides the
            # packed buffer) but the verify program wants explicit 3-axis
            # positions; drop the drafts — losslessly — rather than barrier.
            drafts = [[] for _ in decode_rows]
        if any(s.constraint is not None for s in decode_rows) or any(
            s.constraint is not None or s.mm_embeds is not None or s.mrope is not None
            for s, _ in chunks
        ):
            # Verify dispatches carry neither lookahead mask groups nor mm
            # extras: a batch with constrained or multimodal rows anywhere
            # downgrades to a plain chained step (drafts dropped,
            # losslessly) instead of barriering.
            drafts = [[] for _ in decode_rows]
        # All-empty drafts degrade to a plain chained step (bit-identical
        # per the PR 6 contract) — which, unlike a verify, the *next* step
        # can overlap on top of.
        use_spec = spec and any(drafts)
        batch = decode_rows + [s for s, _ in chunks]
        if not batch:
            if inf is not None:
                self._note_barrier("drain")
                out += self._drain_inflight()
            return out
        n_dec = len(decode_rows)
        info["decode_rows"] = n_dec
        if chunks and fused:
            self.mixed_steps += 1
        ns = [1 + len(d) for d in drafts] + [n for _, n in chunks]
        ps = self.config.page_size
        t = max(ns)
        npg = max(len(s.pages) for s in batch)
        b = len(batch)
        tokens = np.zeros((b, t), np.int32)
        positions = np.zeros((b, t), np.int32)
        block_tables = np.zeros((b, npg), np.int32)
        slots = np.zeros((b, t), np.int32)
        last = np.zeros(b, np.int32)
        chain_src = np.full(b, -1, np.int32)
        samples = [False] * b
        for i, (s, n) in enumerate(zip(batch, ns)):
            ec = self._eff_cached(s)
            if i < n_dec:
                src = self._chain_map.get(s.seq_id, -1)
                if src >= 0:
                    chain_src[i] = src  # column 0 gathered in-graph
                else:
                    # Host knows the input token (nothing in flight for this
                    # row — an IndexError here would mean the chain map lost
                    # an in-flight row, never silence it).
                    tokens[i, 0] = s.tokens[ec]
                if n > 1:
                    tokens[i, 1:n] = drafts[i]
                samples[i] = True
            else:
                tokens[i, :n] = s.tokens[ec : ec + n]
                samples[i] = ec + n == len(s.tokens)
            pos = np.arange(ec, ec + n, dtype=np.int32)
            positions[i, :n] = pos
            block_tables[i, : len(s.pages)] = s.pages
            page_arr = np.asarray(s.pages, dtype=np.int32)
            slots[i, :n] = page_arr[pos // ps] * ps + pos % ps
            last[i] = n - 1
        info["chained_rows"] = chained = int((chain_src >= 0).sum())
        info["chained_rows"] += b * (k_burst - 1)  # every sub-step row chains
        sb = self._sampling_batch(batch, tokens, positions, block_tables, slots, last)
        self._mm_rows(sb, batch, ns, n_dec, positions, self._eff_cached)
        sb.num_new = np.asarray(ns, np.int32)
        if any(s.constraint is not None for s in batch):
            if chained:
                # Chained dispatch: masks resolve in-graph against the
                # gathered token (la groups); a host logit_mask cannot ride.
                self._attach_lookahead_masks(sb, batch, chain_src)
            else:
                # Pipeline fill — every token host-known, exact masks ride
                # the plain logit_mask argument as on the sync path.
                sb.logit_mask = self._constraint_masks(batch)
        lp_k = LOGPROBS_TOP_K if any(
            s.request.sampling.logprobs and smp for s, smp in zip(batch, samples)
        ) else 0
        try:
            if use_spec:
                sb.spec_start = np.asarray(
                    [0] * n_dec + [n - 1 for _, n in chunks], np.int32
                )
                v = self.config.spec_k + 1
                dev = self.runner.spec_step_async(
                    sb, v, lp_k=lp_k, chain_src=chain_src if chained else None
                )
                new_inf = _InflightStep(
                    batch, dev, kind="spec", ns=ns, n_dec=n_dec,
                    samples=samples, drafts=drafts, v=v,
                )
            else:
                dev = self.runner.step_async(
                    sb, lp_k=lp_k, chain=chained > 0,
                    chain_src=chain_src if chained else None,
                )
                new_inf = _InflightStep(
                    batch, dev, kind="step", ns=ns, n_dec=n_dec,
                    samples=samples, drafts=drafts,
                )
                for j in range(1, k_burst):
                    # decode_steps burst: one extra pure-decode sub-step per
                    # depth, each chaining row i's input from the previous
                    # dispatch's row-i sample (chain_src=None, the identity
                    # map). Host tokens are placeholders; positions/slots
                    # advance by j; sample_steps += j keeps the rng fold
                    # counter on the exact sync-loop lattice.
                    tok_j = np.zeros((b, 1), np.int32)
                    pos_j = positions[:, :1] + j
                    slots_j = np.zeros((b, 1), np.int32)
                    for i, s in enumerate(batch):
                        p = int(positions[i, 0]) + j
                        slots_j[i, 0] = s.pages[p // ps] * ps + p % ps
                    sbj = self._sampling_batch(
                        batch, tok_j, pos_j, block_tables, slots_j,
                        np.zeros(b, np.int32),
                    )
                    sbj.sample_steps += j
                    sbj.num_new = np.ones(b, np.int32)
                    new_inf.extra.append(
                        self.runner.step_async(sbj, chain=True, chain_src=None)
                    )
        except Exception:
            self._abort_pipeline(batch)
            raise
        if inf is not None:
            self._overlap_mode = "overlapped"
        else:
            self._note_barrier("fill")
        # The new dispatch's chain map: a verify's is only known at its
        # harvest (acceptance decides the column); a plain step's is its
        # emitting rows. Installed *before* the harvest below so late
        # finishes prune their (now meaningless) entries.
        if use_spec:
            self._chain_map = {}
        else:
            self._chain_map = {
                s.seq_id: i
                for i, (s, smp) in enumerate(zip(batch, samples)) if smp
            }
        if inf is not None:
            out += self._harvest_inflight()
        self._inflight = new_inf
        if use_spec:
            # Verify decode rows advance 1..k+1 tokens — unknowable until
            # harvest, which is why the next step harvests first. Only the
            # chunk rows' advance is certain.
            self._inflight_adv = {
                s.seq_id: (n, 1 if smp else 0)
                for s, n, smp in zip(batch[n_dec:], ns[n_dec:], samples[n_dec:])
            }
        else:
            # A burst's sub-steps advance every row one more cached slot and
            # one more emitted token each (rows that finish mid-burst discard
            # the overshoot at harvest, same as the sync fused burst).
            self._inflight_adv = {
                s.seq_id: (n + k_burst - 1, (1 if smp else 0) + k_burst - 1)
                for s, n, smp in zip(batch, ns, samples)
            }
        return out

    # -- decode phase ------------------------------------------------------

    def _run_decode(self) -> list[tuple[Sequence, EngineOutput]]:
        k = max(1, self.config.decode_steps)
        if self.running:
            # Don't burst past the farthest finish line: the overshoot is
            # discarded compute (at decode_steps=64 and 10 tokens remaining,
            # 84% of the burst). Pow2 keeps k on the compiled bucket lattice.
            from dynamo_tpu.engine.runner import next_pow2

            rem = max(s.remaining_tokens(self.config.max_seq_len) for s in self.running)
            k = max(1, min(k, next_pow2(rem)))
        # Overlapped execution (DYN_OVERLAP) never reaches this method:
        # _step_locked routes every composition — including decode_steps>1,
        # which is now served as chained sub-dispatches inside
        # _run_mixed_overlapped — through the pipeline, and drains it before
        # any barrier falls through to the synchronous paths below.
        if self._inflight is not None:
            return self._drain_inflight()
        # Constraints need a fresh host-built mask per token, and logprobs
        # ride the single-step path because the fused burst's scan doesn't
        # surface per-step logits. (Penalized rows burst fine: the in-graph
        # scan self-counts repetitions within the burst.)
        if any(
            s.constraint is not None or s.request.sampling.logprobs
            for s in self.running
        ):
            return self._run_decode_sync(1)
        return self._run_decode_sync(k)

    def _ensure_burst_pages(self, horizon: int, *, fail_sole: bool = True) -> Sequence | None:
        """Give every running sequence pages covering the next ``horizon``
        tokens; preempt on exhaustion. If the sole remaining sequence cannot
        fit it is returned — finished with ERROR when ``fail_sole``, left
        untouched otherwise (the pipelined path must first commit the burst
        already in flight, which may contain the sequence's legitimate
        finish)."""
        i = 0
        while i < len(self.running):
            seq = self.running[i]
            # A sequence never decodes past max_tokens (or the context
            # window): demanding pages beyond that caused end-of-run
            # preemption storms when the burst horizon overshot the finish.
            # (Safe because overshoot KV writes land in the null page — see
            # the pos_limit mask in the runner's fused burst.)
            remaining = seq.remaining_tokens(self.config.max_seq_len)
            need = seq.pages_needed(self.config.page_size, min(horizon, remaining))
            if need:
                try:
                    seq.pages.extend(self.allocator.allocate(need))
                except OutOfPagesError:
                    victim = self.running[-1]
                    if victim is seq and len(self.running) == 1:
                        # Sole sequence can't fit: context outgrew the cache.
                        if fail_sole:
                            self._finish(seq, FinishReason.ERROR)
                        return seq
                    self._preempt(victim)
                    continue  # retry same index (list shrank behind us)
            i += 1
        return None

    def _decode_step_batch(self, batch: list[Sequence]) -> StepBatch:
        """Host arrays for a synchronous decode step/burst, each row starting
        at its committed state."""
        ps = self.config.page_size
        b = len(batch)
        n = max(len(s.pages) for s in batch)
        tokens = np.zeros((b, 1), np.int32)
        positions = np.zeros((b, 1), np.int32)
        block_tables = np.zeros((b, n), np.int32)
        slots = np.zeros((b, 1), np.int32)
        last = np.zeros(b, np.int32)
        for i, s in enumerate(batch):
            pos = s.num_cached
            tokens[i, 0] = s.tokens[pos]
            positions[i, 0] = pos
            block_tables[i, : len(s.pages)] = s.pages
            slots[i, 0] = s.pages[pos // ps] * ps + pos % ps
        return self._sampling_batch(batch, tokens, positions, block_tables, slots, last)

    def _process_burst_tokens(self, batch: list[Sequence], next_tokens, lp_aux=None) -> list[tuple[Sequence, EngineOutput]]:
        """Apply a burst's sampled tokens to the batch's sequences.

        Sequences that left RUNNING while the burst was in flight (cancelled,
        preempted) are skipped — their sampled tokens are discarded, exactly
        like post-stop overshoot within a burst."""
        outputs = []
        for i, s in enumerate(batch):
            if s.status is not SeqStatus.RUNNING:
                continue
            accepted: list[int] = []
            for tok in next_tokens[i]:
                s.num_cached += 1
                s.append_token(int(tok))
                self._generated_tokens_total += 1
                accepted.append(int(tok))
                if s.check_stop(self._eos, self.config.max_seq_len) is not None:
                    break  # overshoot from the burst is discarded
            self._commit_filled_pages(s)
            self._release_out_of_window(s)
            # May finish the sequence (page release) — must follow commit.
            self._accept_constrained(s, accepted)
            outputs.append(self._emit_many(s, accepted, self._lp_entries(s, lp_aux, i)))
        return outputs

    def _run_decode_sync(self, k: int) -> list[tuple[Sequence, EngineOutput]]:
        failed = self._ensure_burst_pages(k)
        if failed is not None:
            return [(failed, self._final_output(failed))]
        # Snapshot: _finish() inside _emit() mutates self.running mid-loop.
        batch = list(self.running)
        if not batch:
            return []
        step_batch = self._decode_step_batch(batch)
        lp_k = LOGPROBS_TOP_K if any(s.request.sampling.logprobs for s in batch) else 0
        if k == 1:
            step_batch.logit_mask = self._constraint_masks(batch)
        lp_aux = None
        try:
            if k == 1:
                if lp_k:
                    stepped, lp_aux = self.runner.step(step_batch, lp_k=lp_k)
                else:
                    stepped = self.runner.step(step_batch)
                next_tokens = stepped[:, None]
            else:
                next_tokens = self.runner.multi_step(step_batch, k)  # [B, k]
        except Exception:
            for s in batch:
                self._finish(s, FinishReason.ERROR)
            raise
        return self._process_burst_tokens(batch, next_tokens, lp_aux)

    def _harvest_inflight(self) -> list[tuple[Sequence, EngineOutput]]:
        """Consume the in-flight step, keeping the runner's device-resident
        sample buffer alive — a dispatch composed on top of this harvest may
        chain out of it (spec chain-out). Clears the effective-state advance:
        the host has caught up."""
        inf = self._inflight
        if inf is None:
            return []
        self._inflight = None
        self._inflight_adv = {}
        res, lp_aux = inf.handle.result()
        if inf.kind == "spec":
            return self._apply_mixed_results(inf, res[:, 0], res, lp_aux, chain_out=True)
        out = self._apply_mixed_results(inf, res[:, 0], None, lp_aux)
        for h in inf.extra:
            # decode_steps burst sub-steps: one more pure-decode token per
            # row each, applied in dispatch order. Rows that finished in an
            # earlier sub-step are skipped by the RUNNING guard inside
            # _apply_mixed_results; their overshoot KV writes land in pages
            # that are only reallocated to dispatches composed *after* these
            # sub-steps, so device program order makes the stale writes
            # harmless (same argument as preemption under overlap).
            res_j, lp_j = h.result()
            b = len(inf.batch)
            rec = _InflightStep(
                inf.batch, h, kind="step", ns=[1] * b, n_dec=b,
                samples=[True] * b, drafts=[[] for _ in inf.batch],
            )
            out += self._apply_mixed_results(rec, res_j[:, 0], None, lp_j)
        return out

    def _drain_inflight(self) -> list[tuple[Sequence, EngineOutput]]:
        """Consume the in-flight step without composing on top of it: apply
        its results, then reset the chain state (the device buffer is dead
        until the pipeline refills)."""
        out = self._harvest_inflight()
        self._chain_map = {}
        if hasattr(self.runner, "reset_chain"):
            self.runner.reset_chain()
        return out

    # -- shared helpers ----------------------------------------------------

    def _sampling_batch(self, batch, tokens, positions, block_tables, slots, last) -> StepBatch:
        b = len(batch)
        temp = np.zeros(b, np.float32)
        top_k = np.zeros(b, np.int32)
        top_p = np.ones(b, np.float32)
        seeds = np.zeros(b, np.uint32)
        steps = np.zeros(b, np.int32)
        freq = np.zeros(b, np.float32)
        pres = np.zeros(b, np.float32)
        limits = np.zeros(b, np.int32)
        mrope_delta = np.zeros(b, np.int32)
        for i, s in enumerate(batch):
            sp = s.request.sampling
            temp[i] = sp.temperature
            top_k[i] = sp.top_k
            top_p[i] = sp.top_p
            seeds[i] = np.uint32((sp.seed if sp.seed is not None else s.seq_id * 0x9E3779B9 + 1) & 0xFFFFFFFF)
            # Effective fold counter: a chained row's in-flight token has
            # already consumed fold num_generated (the in-graph history
            # write restores that token at index steps-1; sync paths see
            # an empty advance map, so this stays num_generated there).
            steps[i] = s.num_generated + self._adv(s)[1]
            freq[i] = sp.frequency_penalty
            pres[i] = sp.presence_penalty
            limits[i] = s.position_limit(self.config.max_seq_len)
            if s.mrope is not None:
                mrope_delta[i] = s.mrope[1]
        # Generated-token history feeds the sampler's repetition penalties.
        # Only shipped when some request actually set a penalty: H collapses
        # to 1 otherwise, keeping the packed step input small. Width covers
        # this dispatch's own fused decode burst (the scan appends in-graph).
        if freq.any() or pres.any():
            h = max(int(steps.max()) + self.config.decode_steps, 1)
            history = np.full((b, h), -1, np.int32)
            for i, s in enumerate(batch):
                gen = s.tokens[s.num_prompt:]
                history[i, : len(gen)] = gen
        else:
            history = np.full((b, 1), -1, np.int32)
        return StepBatch(tokens, positions, block_tables, slots, last, temp, top_k, top_p,
                         seeds, steps, freq, pres, limits, history,
                         mrope_delta=mrope_delta)

    def _release_out_of_window(self, seq: Sequence) -> None:
        """Free pages fully below the sliding-attention window.

        The block table keeps its positional shape: released entries point
        at the reserved null page 0 — the SWA mask derives key positions
        from table INDEX, not page content, so reads of page 0 there are
        masked out regardless of what another sequence later writes in it.
        Release paths (finish/preempt) skip the zeros."""
        win = getattr(self.runner.cfg, "sliding_window", 0) if hasattr(self.runner, "cfg") else 0
        if not win or not self.config.swa_free_pages:
            return
        ps = self.config.page_size
        # Tokens at absolute positions < (next_pos - win) are out of every
        # future query's window; a page is releasable once its LAST slot is.
        keep_from = max(0, len(seq.tokens) - win) // ps
        if keep_from <= 0:
            return
        drop = [pid for pid in seq.pages[:keep_from] if pid != 0]
        if not drop:
            return
        # Never release pages the commit walk hasn't published yet (caching
        # on: commit runs first each step, so this only guards odd orderings).
        if self.config.enable_prefix_caching and seq.committed_pages < keep_from:
            drop = [pid for pid in seq.pages[: seq.committed_pages] if pid != 0]
            keep_from = seq.committed_pages
        self.allocator.release(drop)
        for i in range(keep_from):
            seq.pages[i] = 0

    def _commit_filled_pages(self, seq: Sequence) -> None:
        """Publish newly-filled pages to the prefix cache (emits stored events)
        and write them through to the capacity tiers."""
        if not self.config.enable_prefix_caching:
            return
        full_pages = seq.num_cached // self.config.page_size
        blocks = seq.block_seq.blocks
        while seq.committed_pages < full_pages:
            idx = seq.committed_pages
            blk = blocks[idx]
            newly_cached = self.allocator.commit(seq.pages[idx], blk.block_hash, blk.parent_hash, blk.tokens)
            if newly_cached and self.block_manager is not None:
                # Deferred: the device->host read happens in flush_offloads(),
                # batched, after the step's outputs have been routed.
                self.pending_offloads.append((blk.block_hash, seq.pages[idx]))
            seq.committed_pages += 1

    def flush_offloads(self) -> None:
        """Write-through pending committed pages to the capacity tiers.

        Called by the service between engine steps (same single-writer
        thread ordering, so committed pages are still live); uses the
        runner's batched multi-page gather when available.
        """
        with self.step_lock:
            if self.block_manager is None or not self.pending_offloads:
                self.pending_offloads = []
                return
            items, self.pending_offloads = self.pending_offloads, []
            self.block_manager.offload_batch(
                items,
                read_pages=getattr(self.runner, "read_pages", None),
                read_pages_async=getattr(self.runner, "read_pages_async", None),
            )

    def abort_all(self, reason: FinishReason = FinishReason.ERROR) -> None:
        """Finish every in-flight sequence (releasing its pages) — used when
        a step failure leaves device state suspect. Blocks until any step
        running in another thread completes (step_lock)."""
        with self.step_lock:
            self._abort_all_locked(reason)

    def _abort_all_locked(self, reason: FinishReason) -> None:
        self._inflight = None
        self._inflight_adv = {}
        self._chain_map = {}
        self._onboards = []  # orphaned fetch threads write into dropped sessions
        if hasattr(self.runner, "reset_chain"):
            self.runner.reset_chain()
        for seq in list(self.running) + list(self.prefilling) + list(self.waiting):
            seq.context.kill()
            self._finish(seq, reason)
        self.pending_offloads = []

    def _emit(self, seq: Sequence, token: int, logprobs: list[dict] | None = None) -> tuple[Sequence, EngineOutput]:
        return self._emit_many(seq, [token], logprobs)

    def _emit_many(self, seq: Sequence, tokens: list[int], logprobs: list[dict] | None = None) -> tuple[Sequence, EngineOutput]:
        reason = seq.check_stop(self._eos, self.config.max_seq_len)
        if reason is not None and not seq.is_finished:
            self._finish(seq, reason)
        # First delta for this sequence: attach the admission wait (frontend
        # RequestTracker observes it once) and close the predictor's loop
        # with the actual TTFT.
        wait_ms = None
        if seq.admitted_time is not None and not seq.admission_reported:
            seq.admission_reported = True
            wait_ms = max(0.0, (seq.admitted_time - seq.arrival_time) * 1e3)
            # Pre-admission wait is lost time: a quota-gated deferral is the
            # admission plane's doing, anything else is plain resource wait.
            self._charge_loss("admission" if seq.quota_deferred else "queue", wait_ms)
            if self.admission is not None and tokens:
                self.admission.on_first_token(seq, time.monotonic())
        out = EngineOutput(
            token_ids=tokens,
            finish_reason=seq.finish_reason,
            cumulative_tokens=seq.num_generated,
            prompt_tokens=seq.num_prompt if seq.finish_reason else None,
            cached_tokens=seq.num_cached_at_start if seq.finish_reason else None,
            logprobs=logprobs[: len(tokens)] if logprobs else None,
            admission_wait_ms=round(wait_ms, 3) if wait_ms is not None else None,
        )
        return seq, out

    def _constraint_masks(self, batch: list[Sequence]) -> np.ndarray | None:
        """bool[B, vocab] for a step: constrained rows get their machine's
        allowed set (force-closing near the budget), others all-True."""
        if not any(s.constraint is not None for s in batch):
            return None
        vocab = self.runner.cfg.vocab_size
        mask = np.ones((len(batch), vocab), bool)
        for i, s in enumerate(batch):
            if s.constraint is not None:
                mask[i] = s.constraint.mask(s.remaining_tokens(self.config.max_seq_len))
        return mask

    def _accept_constrained(self, seq: Sequence, tokens: list[int]) -> None:
        if seq.constraint is None:
            return
        for t in tokens:
            seq.constraint.accept(int(t))
        # Vocabularies without an EOS id can't signal completion through
        # sampling: end the sequence the moment its JSON completes. (With an
        # EOS, the mask steers the model to emit it instead.)
        st = seq.constraint.state
        definitively_done = st.complete() and st.mode == "A"  # not an extendable number
        if not self._eos and definitively_done and not seq.is_finished:
            self._finish(seq, FinishReason.STOP)

    def _lp_entries(self, seq: Sequence, lp_aux, i: int) -> list[dict] | None:
        """One request's logprobs entry from a step's aux arrays (row i):
        chosen-token logprob + this request's own alternatives slice.
        SamplingOptions.logprobs uses the +1 encoding (N = N-1 alternatives);
        the step always computes the full LOGPROBS_TOP_K bucket (one
        compiled program regardless of what each request asked for)."""
        enc = seq.request.sampling.logprobs
        if not enc or lp_aux is None:
            return None
        alts = min(enc - 1, lp_aux["top_ids"].shape[1])
        top = [
            [int(t), float(lp)]
            for t, lp in zip(lp_aux["top_ids"][i][:alts], lp_aux["top_lps"][i][:alts])
        ]
        return [{"id": int(seq.tokens[-1]), "logprob": float(lp_aux["logprob"][i]), "top": top}]

    def _final_output(self, seq: Sequence) -> EngineOutput:
        return EngineOutput(
            token_ids=[],
            finish_reason=seq.finish_reason,
            cumulative_tokens=seq.num_generated,
            prompt_tokens=seq.num_prompt,
            cached_tokens=seq.num_cached_at_start,
        )

    def _preempt(self, seq: Sequence) -> None:
        logger.info("preempting seq %d (%d pages)", seq.seq_id, len(seq.pages))
        self.num_preemptions += 1
        self._cancel_onboards(seq)
        self.allocator.release([p for p in seq.pages if p != 0])
        seq.pages = []
        seq.committed_pages = 0
        seq.num_cached = 0
        seq.prefill_chunks = 0
        seq.status = SeqStatus.PREEMPTED
        # Any in-flight advance is void: on re-admission the sequence
        # restarts from num_cached=0, so stale effective-state would
        # overshoot the prompt.
        self._inflight_adv.pop(seq.seq_id, None)
        self._chain_map.pop(seq.seq_id, None)
        if seq in self.running:
            self.running.remove(seq)
        if seq in self.prefilling:  # preempted mid-prompt: re-chunks on resume
            self.prefilling.remove(seq)
        self.waiting.appendleft(seq)

    def _finish(self, seq: Sequence, reason: FinishReason) -> None:
        seq.status = SeqStatus.FINISHED
        seq.finish_reason = reason
        self._cancel_onboards(seq)
        if self.admission is not None:
            self.admission.on_finish(seq)
        if seq.pages:
            self.allocator.release([p for p in seq.pages if p != 0])
            seq.pages = []
        if seq in self.running:
            self.running.remove(seq)
        if seq in self.prefilling:
            self.prefilling.remove(seq)
        if seq in self.waiting:
            self.waiting.remove(seq)

    # -- bookkeeping -------------------------------------------------------

    def metrics(self) -> ForwardPassMetrics:
        from dynamo_tpu.parallel.moe import DROP_COUNTER

        st = self.allocator.stats()
        moe_choices, moe_dropped = DROP_COUNTER.snapshot()
        return ForwardPassMetrics(
            worker_id=self.config.worker_id,
            kv_active_blocks=st.active_pages,
            kv_total_blocks=st.total_pages,
            num_requests_waiting=len(self.waiting),
            num_requests_running=len(self.running) + len(self.prefilling),
            request_total_slots=self.config.max_batch_size,
            cache_hit_rate=st.hit_rate,
            prompt_tokens_total=self._prompt_tokens_total,
            generated_tokens_total=self._generated_tokens_total,
            moe_choices_total=moe_choices,
            moe_dropped_total=moe_dropped,
        )
