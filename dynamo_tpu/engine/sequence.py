"""Per-request runtime state inside the engine.

A sequence tracks its tokens (prompt + generated), how many of them have KV
resident in the paged cache, its page list, and its hash-chained block
identities (for prefix-cache commit + KV events). Preemption resets the
cached count to zero while keeping tokens — recomputation then re-matches
whatever prefix survives in cache.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from enum import Enum

from dynamo_tpu.protocols.common import FinishReason, PreprocessedRequest
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.tokens import TokenBlockSequence


class SeqStatus(Enum):
    WAITING = "waiting"
    RUNNING = "running"
    PREEMPTED = "preempted"
    FINISHED = "finished"


@dataclass
class Sequence:
    seq_id: int
    request: PreprocessedRequest
    context: Context
    block_seq: TokenBlockSequence  # hash-chained identity of self.tokens
    tokens: list[int] = field(default_factory=list)  # prompt + generated
    num_prompt: int = 0
    num_cached: int = 0  # tokens whose KV is in the paged cache
    num_cached_at_start: int = 0  # prefix-cache hits at admission (for usage stats)
    pages: list[int] = field(default_factory=list)
    committed_pages: int = 0  # pages already committed to the prefix cache
    # Forward chunks this (re)prefill has executed (chunked prefill
    # progress; reset on preemption along with num_cached).
    prefill_chunks: int = 0
    # Tier pages whose payload fetch is in flight (async onboarding): the
    # chunk scheduler skips the row until the session lands — num_cached
    # advances only then, exactly like an in-flight chunk. 0 once landed
    # (shortfall pages degrade to plain compute pages) or on preemption.
    onboard_pending: int = 0
    status: SeqStatus = SeqStatus.WAITING
    finish_reason: FinishReason | None = None
    # Image embeddings [total_image_tokens, D] substituted at placeholder
    # positions during prefill (multimodal; survives preemption/recompute).
    mm_embeds: "object | None" = None
    # Qwen2-VL M-RoPE: (pos3 i32[3, prompt_len], delta). Tokens past the
    # prompt (generated, incl. recompute) sit at index + delta on all axes.
    mrope: "tuple | None" = None
    # Constrained decoding state (response_format json_object); survives
    # preemption (the machine replays nothing — it tracks generated text).
    constraint: "object | None" = None
    arrival_time: float = field(default_factory=time.monotonic)
    first_token_time: float | None = None
    # SLO admission plane (dynamo_tpu/sched): when the scheduler admitted
    # this sequence into prefill (re-admission after preemption overwrites),
    # whether the admission wait has been reported downstream, and the
    # remaining TTFT the predictor estimated at the last EDF ordering (with
    # the timestamp of that estimate — the observation's time origin).
    admitted_time: float | None = None
    admission_reported: bool = False
    predicted_ttft_s: float | None = None
    predicted_at: float | None = None
    # True once the quota gate held this request back at any prepare():
    # its pre-admission wait is then charged to the "admission" loss cause
    # rather than plain "queue" (observability/attribution.py).
    quota_deferred: bool = False

    @classmethod
    def from_request(cls, seq_id: int, request: PreprocessedRequest, context: Context, *, page_size: int, salt: int) -> "Sequence":
        block_seq = TokenBlockSequence(request.token_ids, block_size=page_size, salt=salt)
        return cls(
            seq_id=seq_id,
            request=request,
            context=context,
            block_seq=block_seq,
            tokens=list(request.token_ids),
            num_prompt=len(request.token_ids),
        )

    @property
    def num_generated(self) -> int:
        return len(self.tokens) - self.num_prompt

    @property
    def is_finished(self) -> bool:
        return self.status is SeqStatus.FINISHED

    @property
    def num_computed(self) -> int:
        """Tokens already through the forward pass. KV writes land in the
        same dispatch that computes a chunk, so this coincides with
        ``num_cached``; it exists as the scheduler-facing name — chunked
        prefill reasons about compute progress, the allocator about KV
        residency."""
        return self.num_cached

    @property
    def prompt_remaining(self) -> int:
        """Uncomputed tokens of the prompt (or, after preemption, of the
        prompt + generated recompute). 0 once fully prefilled; a mid-chunk
        sequence is not decodable until this reaches 0."""
        return max(0, len(self.tokens) - self.num_cached)

    def pages_needed(self, page_size: int, num_tokens_ahead: int = 1) -> int:
        """Extra pages needed to hold KV for the next ``num_tokens_ahead`` tokens."""
        target = self.num_cached + num_tokens_ahead
        need = -(-target // page_size)  # ceil
        return max(0, need - len(self.pages))

    def append_token(self, token: int) -> None:
        self.tokens.append(int(token))
        self.block_seq.append(int(token))

    def remaining_tokens(self, max_seq_len: int) -> int:
        """Tokens this sequence may still legitimately generate (the finish
        line check_stop enforces): bounded by max_tokens and the context
        window, never below 1 for a live sequence."""
        return max(
            1,
            min(
                self.request.stop.max_tokens - self.num_generated,
                max_seq_len - len(self.tokens),
            ),
        )

    def position_limit(self, max_seq_len: int) -> int:
        """First absolute position this sequence must never write KV at."""
        return min(self.num_prompt + self.request.stop.max_tokens, max_seq_len)

    def check_stop(self, eos_token_ids: set[int], max_seq_len: int) -> FinishReason | None:
        """Evaluate token-level stop conditions after a newly appended token."""
        stop = self.request.stop
        if self.context.is_stopped:
            return FinishReason.CANCELLED
        last = self.tokens[-1]
        if self.num_generated >= stop.min_tokens:
            if not stop.ignore_eos and last in eos_token_ids:
                return FinishReason.STOP
            if last in stop.stop_token_ids:
                return FinishReason.STOP
        if self.num_generated >= stop.max_tokens:
            return FinishReason.LENGTH
        if len(self.tokens) >= max_seq_len:
            return FinishReason.LENGTH  # context window reached
        return None
