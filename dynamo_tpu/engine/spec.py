"""Draft-token proposers for speculative decoding.

The engine treats a proposer as a black box that, given a request's full
token history (prompt + generated), suggests up to ``max_k`` continuation
tokens. Proposals are *speculative*: the verify dispatch scores them against
the target model and the scheduler only commits the accepted prefix, so a
proposer can be arbitrarily wrong without affecting output correctness —
only throughput.

The default proposer is the n-gram / prompt-lookup drafter (Saxena 2023):
match the longest recent suffix of the history against an earlier
occurrence and propose whatever followed it. It is deterministic,
model-free, and costs O(len(history)) per call on the host, which makes the
whole speculative path CPU-testable. A draft-model proposer can slot in
behind the same interface later.
"""

from __future__ import annotations

from typing import Protocol, Sequence, runtime_checkable


@runtime_checkable
class Proposer(Protocol):
    """Interface the engine drafts through."""

    def propose(self, tokens: Sequence[int], max_k: int) -> list[int]:
        """Return up to ``max_k`` draft tokens continuing ``tokens``.

        ``tokens`` is the request's prompt + generated history in order.
        Must be deterministic for a given history (losslessness does not
        require it, but reproducible benchmarks do).
        """
        ...


class NgramProposer:
    """Prompt-lookup drafting: find an earlier occurrence of the history's
    trailing n-gram and propose the tokens that followed it.

    Tries match lengths from ``max_ngram`` down to ``min_ngram`` and takes
    the longest suffix that matches. Among equal-length matches, the most
    recent occurrence with a *full* ``max_k`` continuation wins (recent
    context predicts continuation best); if every match sits too close to
    the end for a full draft — the period-1 repetition case — the longest
    available continuation is used instead of giving up draft length.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError("need 1 <= min_ngram <= max_ngram")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def propose(self, tokens: Sequence[int], max_k: int) -> list[int]:
        n = len(tokens)
        if max_k <= 0 or n < self.min_ngram + 1:
            return []
        toks = list(tokens)
        for length in range(min(self.max_ngram, n - 1), self.min_ngram - 1, -1):
            suffix = toks[n - length:]
            best: list[int] = []
            # Scan right-to-left so the first full-length continuation found
            # is the most recent one; matches too close to the end only set
            # the fallback (their continuation is truncated by the history).
            for start in range(n - length - 1, -1, -1):
                if toks[start:start + length] == suffix:
                    cont = toks[start + length:start + length + max_k]
                    if len(cont) == max_k:
                        return cont
                    if len(cont) > len(best):
                        best = cont
            if best:
                return best
        return []


def build_proposer(name: str = "ngram") -> Proposer:
    """Factory keyed by proposer name (currently only ``ngram``)."""
    if name == "ngram":
        return NgramProposer()
    raise ValueError(f"unknown speculative proposer: {name!r}")
