"""HBM page allocator: free list + refcounted prefix cache + LRU eviction.

This is the G1 (device) tier of the multi-tier KV block system. Pages hold
``page_size`` tokens of KV for all layers. Completed pages gain a chained
block hash (`dynamo_tpu.tokens`) and stay resident after release as prefix
cache until evicted by demand, LRU-first — at which point a "removed" KV
event is emitted so the global router index stays truthful.

Parity: reference block manager G1 pool + registry
(`lib/llm/src/block_manager/pool.rs:156`, `block/registry.rs`) and the KV
event contract of `kv_router/publisher.rs`. Design is fresh: a flat
page-table keyed by integer page id matching the Pallas kernel's block-table
format, no typestate machinery — mutability is guarded by refcounts.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Sequence

from dynamo_tpu.protocols.kv import BlockRemoved, BlockStored, KvCacheEvent

EventCallback = Callable[[KvCacheEvent], None]


class OutOfPagesError(RuntimeError):
    pass


@dataclass
class _PageInfo:
    refcount: int = 0
    block_hash: int | None = None  # set once the page's block is complete
    parent_hash: int | None = None
    is_cache_holder: bool = False  # this page backs the prefix-cache entry for its hash


@dataclass
class AllocatorStats:
    total_pages: int = 0
    free_pages: int = 0
    cached_pages: int = 0  # evictable (refcount 0, hash registered)
    active_pages: int = 0  # referenced by live sequences
    hits: int = 0
    misses: int = 0

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0


class PageAllocator:
    """Allocator over pages ``1..num_pages-1`` (page 0 is the reserved null page)."""

    def __init__(self, num_pages: int, page_size: int, *, on_event: EventCallback | None = None) -> None:
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.num_pages = num_pages
        self.page_size = page_size
        self._on_event = on_event
        self._free: list[int] = list(range(num_pages - 1, 0, -1))  # pop() yields low ids first
        self._pages: dict[int, _PageInfo] = {}
        self._cached: dict[int, int] = {}  # block_hash -> page_id (complete, reusable)
        self._lru: OrderedDict[int, None] = OrderedDict()  # evictable page ids, LRU first
        self._hits = 0
        self._misses = 0

    # -- events ------------------------------------------------------------

    def _emit(self, event: KvCacheEvent) -> None:
        if self._on_event is not None and not event.is_empty():
            self._on_event(event)

    # -- queries -----------------------------------------------------------

    def num_free(self) -> int:
        """Pages allocatable right now (free list + evictable cache)."""
        return len(self._free) + len(self._lru)

    def stats(self) -> AllocatorStats:
        active = sum(1 for p in self._pages.values() if p.refcount > 0)
        return AllocatorStats(
            total_pages=self.num_pages - 1,
            free_pages=len(self._free),
            cached_pages=len(self._lru),
            active_pages=active,
            hits=self._hits,
            misses=self._misses,
        )

    # -- allocation --------------------------------------------------------

    def allocate(self, n: int = 1) -> list[int]:
        """Take ``n`` fresh pages (evicting prefix cache LRU-first if needed)."""
        if self.num_free() < n:
            raise OutOfPagesError(f"need {n} pages, have {self.num_free()}")
        out: list[int] = []
        removed: list[BlockRemoved] = []
        for _ in range(n):
            if self._free:
                pid = self._free.pop()
            else:
                pid, _ = self._lru.popitem(last=False)  # least recently used
                info = self._pages[pid]
                assert info.refcount == 0 and info.block_hash is not None
                if info.is_cache_holder:
                    self._cached.pop(info.block_hash, None)
                    removed.append(BlockRemoved(info.block_hash))
            self._pages[pid] = _PageInfo(refcount=1)
            out.append(pid)
        self._emit(KvCacheEvent(removed=removed))
        return out

    def match_prefix(self, block_hashes: Sequence[int]) -> list[int]:
        """Longest cached prefix: acquire and return its pages (refcount++).

        Touches matched pages to MRU. Stops at the first miss — hash chaining
        means later matches without the prefix would be a different sequence.
        """
        matched: list[int] = []
        for h in block_hashes:
            pid = self._cached.get(h)
            if pid is None:
                self._misses += 1
                break
            info = self._pages[pid]
            if info.refcount == 0:
                self._lru.pop(pid, None)
            info.refcount += 1
            matched.append(pid)
            self._hits += 1
        return matched

    def peek_prefix(self, block_hashes: Sequence[int]) -> int:
        """Longest cached prefix length WITHOUT acquiring.

        No refcount, MRU, or hit/miss effects: the admission plane's
        residual-cost estimate runs this over every waiting sequence each
        prepare(), and pricing must not perturb eviction order or pin pages
        the request may never be admitted to use."""
        n = 0
        for h in block_hashes:
            if h not in self._cached:
                break
            n += 1
        return n

    def acquire(self, page_id: int) -> None:
        """Add a reference to an already-allocated page (e.g. fork/beam)."""
        info = self._pages[page_id]
        if info.refcount == 0:
            self._lru.pop(page_id, None)
        info.refcount += 1

    # -- completion / release ---------------------------------------------

    def commit(self, page_id: int, block_hash: int, parent_hash: int | None, token_ids: Sequence[int] = ()) -> bool:
        """Mark a page's block complete and publish it to the prefix cache.

        Returns True if this page became the cache holder for its hash. If
        the hash is already cached (another sequence computed the same block
        concurrently), this page stays un-cached — a duplicate that simply
        frees on release.
        """
        info = self._pages[page_id]
        if info.block_hash is not None:
            return False  # already committed
        info.block_hash = block_hash
        info.parent_hash = parent_hash
        if block_hash not in self._cached:
            self._cached[block_hash] = page_id
            info.is_cache_holder = True
            self._emit(KvCacheEvent(stored=[BlockStored(block_hash, parent_hash, tuple(token_ids))]))
            return True
        return False

    def release(self, page_ids: Sequence[int]) -> None:
        """Drop one reference from each page; refcount-0 pages become evictable
        prefix cache (if committed + cache holder) or return to the free list."""
        for pid in page_ids:
            info = self._pages[pid]
            if info.refcount <= 0:
                raise ValueError(f"double release of page {pid}")
            info.refcount -= 1
            if info.refcount == 0:
                if info.is_cache_holder:
                    self._lru[pid] = None  # becomes MRU end
                    self._lru.move_to_end(pid)
                else:
                    del self._pages[pid]
                    self._free.append(pid)

    def page_parent_hash(self, page_id: int) -> int | None:
        """Parent hash recorded for a committed page (transfer metadata)."""
        return self._pages[page_id].parent_hash

    def acquire_cached(self, block_hash: int) -> int | None:
        """Pin the cached page backing this hash (refcount++), if present.

        Deliberately the only hit-check: pinning means the page can't be
        evicted by a later :meth:`allocate` — required when checking hits
        while also allocating in the same pass (KV transfer injection)."""
        pid = self._cached.get(block_hash)
        if pid is None:
            return None
        self.acquire(pid)
        return pid

    def cache_snapshot(self) -> KvCacheEvent:
        """All currently-known completed blocks, parents before children.

        Used to (re)announce this worker's cache to a fresh event subscriber
        (router reconnect / late join).
        """
        blocks = {
            info.block_hash: info.parent_hash
            for info in self._pages.values()
            if info.block_hash is not None and info.is_cache_holder
        }
        stored: list[BlockStored] = []
        emitted: set[int] = set()
        pending = dict(blocks)
        while pending:
            progress = False
            for h, parent in list(pending.items()):
                if parent is None or parent in emitted or parent not in blocks:
                    stored.append(BlockStored(h, parent))
                    emitted.add(h)
                    del pending[h]
                    progress = True
            if not progress:  # pragma: no cover - cycles are impossible by construction
                break
        return KvCacheEvent(stored=stored)

    def clear_cache(self) -> int:
        """Drop all evictable prefix-cache pages (the clear-kv-blocks admin op).
        Returns the number of pages freed."""
        removed: list[BlockRemoved] = []
        n = 0
        while self._lru:
            pid, _ = self._lru.popitem(last=False)
            info = self._pages.pop(pid)
            if info.is_cache_holder and info.block_hash is not None:
                self._cached.pop(info.block_hash, None)
                removed.append(BlockRemoved(info.block_hash))
            self._free.append(pid)
            n += 1
        self._emit(KvCacheEvent(removed=removed))
        return n
