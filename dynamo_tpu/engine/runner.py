"""Bucketed jit execution of the paged forward + fused sampling.

XLA traces/compiles once per distinct input shape; the runner keeps shapes
drawn from a small bucket lattice (batch and prefill-length rounded up to
powers of two, block-table width in page-count steps) so steady-state serving
touches a handful of compiled programs. The KV cache buffers are donated each
step, so cache writes are in-place in HBM; only the sampled token ids
(i32[B]) come back to the host per step.

The forward + sampling are one fused jitted program: logits never leave the
device, avoiding a [B, vocab] device->host transfer per token.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import threading

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models import llama
from dynamo_tpu.observability.compile import CompileTracker, timed_dispatch
from dynamo_tpu.observability.cost import (
    CostRegistry,
    cost_plane_enabled,
    decode_step_estimate,
    make_lower_thunk,
)
from dynamo_tpu.ops.sampling import sample_tokens

logger = logging.getLogger(__name__)


def next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def _locked(fn):
    """Serialize cache-touching entry points on the runner's ``io_lock``.

    The KV cache buffers are *donated* to every jitted step/write: a second
    thread dispatching against ``self.k_cache`` while a step is in flight
    would either double-donate (JAX "array deleted" crash) or lose one
    thread's reassignment. The engine loop is single-writer, but KV transfer
    services and tier offload run on other executor threads — this mutex is
    what makes their access safe."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self.io_lock:
            return fn(self, *args, **kwargs)

    return wrapper


def _delta_mrope(positions: jnp.ndarray, delta: jnp.ndarray | None) -> jnp.ndarray:
    """Equal-coords 3D rope positions from sequential positions + per-row
    delta: [B, T] (+ [B]) -> [B, 3, T]. Exact for decode and for text spans
    after the prompt (HF: position = seq_index + mrope_delta on all axes)."""
    b, t = positions.shape
    p = positions if delta is None else positions + delta[:, None]
    return jnp.broadcast_to(p[:, None, :], (b, 3, t))


def _pack(padded: "StepBatch") -> np.ndarray:
    """Flatten every step input into one i32 buffer (single host->device
    transfer — on a tunneled/remote chip each separate transfer costs fixed
    round-trip latency that dwarfs the bytes; measured ~90 ms per decode
    burst at batch 32 for the unpacked form)."""
    return np.concatenate(
        [
            padded.tokens.ravel(),
            padded.positions.ravel(),
            padded.block_tables.ravel(),
            padded.slot_mapping.ravel(),
            padded.last_token_index,
            padded.temperature.view(np.int32),
            padded.top_k,
            padded.top_p.view(np.int32),
            padded.seeds.view(np.int32),
            padded.sample_steps,
            padded.freq_pen.view(np.int32),
            padded.pres_pen.view(np.int32),
            padded.pos_limit,
            padded.history.ravel(),
            padded.mrope_delta,
        ]
    )


def _unpack(packed: jnp.ndarray, b: int, t: int, n: int, h: int):
    """In-graph inverse of :func:`_pack` (static offsets, free slices)."""
    sizes = [b * t, b * t, b * n, b * t, b, b, b, b, b, b, b, b, b, b * h, b]
    offs = np.concatenate([[0], np.cumsum(sizes)])
    part = [packed[offs[i] : offs[i + 1]] for i in range(len(sizes))]
    return (
        part[0].reshape(b, t),
        part[1].reshape(b, t),
        part[2].reshape(b, n),
        part[3].reshape(b, t),
        part[4],
        jax.lax.bitcast_convert_type(part[5], jnp.float32),
        part[6],
        jax.lax.bitcast_convert_type(part[7], jnp.float32),
        jax.lax.bitcast_convert_type(part[8], jnp.uint32),
        part[9],
        jax.lax.bitcast_convert_type(part[10], jnp.float32),
        jax.lax.bitcast_convert_type(part[11], jnp.float32),
        part[12],
        part[13].reshape(b, h),
        part[14],
    )


def _apply_chain(tokens, history, sample_steps, chain_buf, chain_src):
    """Per-row device-resident token sourcing for a chained dispatch.

    ``chain_src`` i32[B] holds, per row, a flat index into ``chain_buf`` (the
    previous dispatch's device-resident samples — [Bp] for plain steps,
    [Bp*V] row-major for spec verifies) or -1 for host-fed rows. Chained
    rows' column-0 input token is gathered in-graph; host-fed rows (prefill
    chunks, fresh admissions) keep their host token untouched.

    The gathered token is also appended to the penalty ``history`` at index
    ``sample_steps - 1``: a chained row's host history is stale by exactly
    the one in-flight token it is chaining, and that token IS the gathered
    value, so the write restores bit-identical penalty state. For host-fed
    rows the write re-stores the value already there (a no-op), which keeps
    the program branch-free.
    """
    src = jnp.clip(chain_src, 0, chain_buf.shape[0] - 1)
    gathered = chain_buf[src]
    chained = chain_src >= 0
    tokens = tokens.at[:, 0].set(jnp.where(chained, gathered, tokens[:, 0]))
    idx = jnp.clip(sample_steps - 1, 0, history.shape[1] - 1)
    cur = jnp.take_along_axis(history, idx[:, None], axis=1)[:, 0]
    upd = jnp.where(chained, gathered, cur)
    history = jax.vmap(lambda hrow, w, t_: hrow.at[w].set(t_))(history, idx, upd)
    return tokens, history


@dataclasses.dataclass
class StepBatch:
    """Host-side arrays describing one engine step (pre-padding)."""

    tokens: np.ndarray  # i32[B, T]
    positions: np.ndarray  # i32[B, T]
    block_tables: np.ndarray  # i32[B, N]
    slot_mapping: np.ndarray  # i32[B, T]
    last_token_index: np.ndarray  # i32[B]
    temperature: np.ndarray  # f32[B]
    top_k: np.ndarray  # i32[B]
    top_p: np.ndarray  # f32[B]
    seeds: np.ndarray  # u32[B]
    sample_steps: np.ndarray  # i32[B] — rng fold counter (monotonic per request)
    freq_pen: np.ndarray  # f32[B] — OpenAI frequency_penalty
    pres_pen: np.ndarray  # f32[B] — OpenAI presence_penalty
    pos_limit: np.ndarray  # i32[B] first absolute position KV must never be written at
    history: np.ndarray  # i32[B, H] generated tokens so far, pad -1 (H=1 when no penalties)
    # Multimodal prefill only (None on text batches / decode):
    mm_embeds: np.ndarray | None = None  # f32[B, M, D] image embeddings
    mm_slot_offset: np.ndarray | None = None  # i32[B] placeholders already cached; -1 = text row
    mm_counts: np.ndarray | None = None  # i32[B] embedding rows provided per row
    # Qwen2-VL M-RoPE. Delta rides every packed step (one i32 per row; 0 for
    # text rows — equal coords reduce to 1D rope, so zero-delta is exact);
    # explicit per-token 3D coords are prefill-only (image spans need grid
    # coords a scalar shift can't express).
    mrope_delta: np.ndarray | None = None  # i32[B]; None -> zeros at pad time
    mrope_positions: np.ndarray | None = None  # i32[B, 3, T] (mm prefill only)
    # Constrained decoding, host-known tokens: bool[B, vocab] allowed
    # tokens (sync steps and unchained overlapped dispatches).
    logit_mask: np.ndarray | None = None
    # Constrained decoding, chained dispatches: one-step-lookahead mask
    # groups. Each row carries G candidate masks; the chained program picks
    # row i's mask in-graph as la_masks[i, la_groups[i, tokens[i, 0]]] AFTER
    # the chain gather resolves the device-resident input token. Group 0 is
    # all-True by convention (unconstrained rows, EOS candidates whose
    # sample the engine discards at harvest). Mutually exclusive with
    # logit_mask; requires chain=True.
    la_masks: np.ndarray | None = None  # bool[B, G, vocab]
    la_groups: np.ndarray | None = None  # i32[B, vocab]
    # Mixed-step metadata: real token columns per row (decode rows 1,
    # prefill-chunk rows their chunk length; padding rows 0). Host-side
    # only — never shipped to device (the kernels derive the same
    # information from positions/last_token_index: a decode row in a T>1
    # batch is exact because attention masks per-token positions and its
    # padding columns write KV to the null page). Consumed by the engine's
    # step-composition telemetry, tests, and the bench stall probe.
    num_new: np.ndarray | None = None  # i32[B]
    # Speculative verify (spec_step only): first column each row scores
    # logits at. Decode rows verify every real column (start 0); prefill
    # chunk rows score only their last column (start n-1), which keeps the
    # chunk rows' sampling bit-identical to the non-speculative step.
    spec_start: np.ndarray | None = None  # i32[B]

    @property
    def batch_size(self) -> int:
        return self.tokens.shape[0]


class ModelRunner:
    """Owns device state (params + paged KV cache) and runs engine steps."""

    def __init__(
        self,
        cfg: ModelConfig,
        params: llama.Params,
        *,
        num_pages: int,
        page_size: int,
        max_batch_size: int = 64,
        prefill_bucket: int = 64,
        attn_impl: str | None = None,
        forward_fn=None,
        cache_dtype: jnp.dtype | None = None,
        mesh=None,  # jax.sharding.Mesh for TP/DP execution (see dynamo_tpu.parallel)
        embed_pooling: str = "mean",  # /v1/embeddings pooling ("mean" | "last")
    ) -> None:
        self.cfg = cfg
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_batch_size = max_batch_size
        self.prefill_bucket = prefill_bucket
        self.attn_impl = attn_impl
        self.mesh = mesh
        self._forward = forward_fn or llama.forward
        # Serializes every cache-donating/reading entry point (see _locked):
        # RLock so a locked method may call another (e.g. device transfer).
        self.io_lock = threading.RLock()
        # First-execution-per-shape observer over every dispatch site: the
        # bucket lattice bounds compiled programs, but it is data-dependent —
        # this is how a production recompile becomes visible (metrics plane
        # syncs counts(); the engine's flight recorder is its event sink).
        self.compile_tracker = CompileTracker()
        # Device-cost plane (DYN_COST_PLANE, default on): per-bucket
        # flops/bytes records joined with measured dispatch wall into the
        # live roofline ledger. None when the plane is off — the dispatch
        # sites then skip every cost call (bit-identical serving, zero
        # extraction).
        self.cost_registry = CostRegistry() if cost_plane_enabled() else None
        # Padded page-counts whose gather/scatter kernels are compiled for
        # this runner (device-transfer warm-up bookkeeping — keyed on the
        # runner object itself, so id() reuse after GC can't skip a warm-up).
        self._devxfer_warm: set[int] = set()
        # (phase, path) of the most recent dispatch — "decode"/"verify"/
        # "prefill" x "pallas"/"fallback"/"ring". The engine copies this
        # into its STEP flight records and dispatch-path counters.
        self.last_attn_dispatch: tuple[str, str] | None = None
        self.k_cache, self.v_cache = llama.init_kv_cache(cfg, num_pages, page_size, dtype=cache_dtype)
        self._dp = 1
        if mesh is not None:
            from dynamo_tpu.parallel.sharding import cache_shardings, shard_params

            params = shard_params(params, mesh)
            cs = cache_shardings(mesh, cfg.attn_type)
            self.k_cache = jax.device_put(self.k_cache, cs)
            self.v_cache = jax.device_put(self.v_cache, cs)
            self._dp = int(mesh.shape["dp"])
        self.params = params

        @functools.partial(jax.jit, static_argnames=("impl", "lp_k"), donate_argnums=(1, 2))
        def _step(params, k_cache, v_cache, tokens, positions, block_tables, slot_mapping,
                  last_idx, temperature, top_k, top_p, seeds, sample_steps,
                  freq_pen, pres_pen, pos_limit, history, mrope_delta=None,
                  mm_embeds=None, mm_slot_offset=None, mm_counts=None,
                  mrope_positions=None, logit_mask=None, *, impl, lp_k=0):
            # In-graph finish-line clamp: any column at/past a row's absolute
            # position limit writes KV to the reserved null page 0 instead of
            # a live slot. Host scheduling never dispatches such a column for
            # a live row (and pad rows carry limit 0 with slot 0 already), so
            # this is a no-op for today's callers — it is the guarantee that
            # lets the overlapped engine keep budget-clamped rows in a
            # chained dispatch instead of draining the pipeline.
            slot_mapping = jnp.where(positions < pos_limit[:, None], slot_mapping, 0)
            # mm_* None on text batches; jit specializes once per presence
            # pattern, so the text program carries no multimodal cost.
            mm_kw = {}
            if mm_embeds is not None:
                mm_kw = dict(mm_embeds=mm_embeds, mm_slot_offset=mm_slot_offset, mm_counts=mm_counts)
            if self.cfg.mrope_section:
                mm_kw["mrope_positions"] = (
                    mrope_positions if mrope_positions is not None
                    else _delta_mrope(positions, mrope_delta)
                )
            logits, k_cache, v_cache = self._forward(
                params, self.cfg, tokens, positions, k_cache, v_cache,
                block_tables, slot_mapping, last_idx, attn_impl=impl, mesh=self.mesh,
                **mm_kw,
            )
            keys = jax.vmap(lambda s, c: jax.random.fold_in(jax.random.PRNGKey(s), c))(seeds, sample_steps)
            sample_logits = logits
            if logit_mask is not None:
                # Constrained decoding: disallowed tokens can never sample.
                # Logprobs (below) stay on the RAW logits — they report the
                # model's distribution, not the constrained one.
                from dynamo_tpu.ops.attention import NEG_INF

                sample_logits = jnp.where(logit_mask, logits, NEG_INF)
            next_tokens = sample_tokens(
                sample_logits, keys, temperature, top_k, top_p,
                history=history, frequency_penalty=freq_pen, presence_penalty=pres_pen,
            )
            if lp_k:
                from dynamo_tpu.ops.sampling import token_logprobs

                chosen, top_ids, top_lps = token_logprobs(logits, next_tokens, lp_k)
                return next_tokens, k_cache, v_cache, chosen, top_ids, top_lps
            return next_tokens, k_cache, v_cache

        self._step_fn = _step

        @functools.partial(jax.jit, static_argnames=("b", "t", "n", "h", "lp_k"), donate_argnums=(1, 2))
        def _step_packed(params, k_cache, v_cache, packed, *, b, t, n, h, lp_k=0):
            args = _unpack(packed, b, t, n, h)
            return _step(params, k_cache, v_cache, *args, impl=self.attn_impl, lp_k=lp_k)

        self._step_packed_fn = _step_packed

        @functools.partial(jax.jit, static_argnames=("b", "t", "n", "h", "lp_k"), donate_argnums=(1, 2))
        def _step_chained(params, k_cache, v_cache, packed, chain_buf, chain_src, *, b, t, n, h, lp_k=0):
            """Chained (possibly mixed) step: each row's column-0 input token
            is sourced per ``chain_src`` from the previous dispatch's
            device-resident samples instead of the host (the overlapped
            engine loop dispatches step N+1 before fetching step N's tokens —
            see step_async). Rows with ``chain_src < 0`` (prefill chunks,
            fresh admissions) feed from host as usual."""
            args = list(_unpack(packed, b, t, n, h))
            # args: 0=tokens, 9=sample_steps, 13=history (see _pack order).
            args[0], args[13] = _apply_chain(args[0], args[13], args[9], chain_buf, chain_src)
            return _step(params, k_cache, v_cache, *args, impl=self.attn_impl, lp_k=lp_k)

        self._step_chained_fn = _step_chained

        @functools.partial(jax.jit, static_argnames=("impl", "lp_k"), donate_argnums=(1, 2))
        def _step_chained_explicit(params, k_cache, v_cache, chain_buf, chain_src,
                                   tokens, positions, block_tables, slot_mapping,
                                   last_idx, temperature, top_k, top_p, seeds,
                                   sample_steps, freq_pen, pres_pen, pos_limit,
                                   history, mrope_delta=None,
                                   mm_embeds=None, mm_slot_offset=None, mm_counts=None,
                                   mrope_positions=None, la_masks=None, la_groups=None,
                                   *, impl, lp_k=0):
            """Explicit-args chained step: mesh runners (the packed buffer
            cannot be row-sharded) and any chained dispatch carrying extras
            the packed buffer has no slots for — multimodal embeds, explicit
            3-axis mrope coords, or lookahead constraint-mask groups.

            The lookahead mask selection happens strictly AFTER the chain
            gather: each row's group id is looked up at its (possibly
            device-sourced) column-0 token, which is exactly the token the
            host could not know at compose time."""
            tokens, history = _apply_chain(tokens, history, sample_steps, chain_buf, chain_src)
            logit_mask = None
            if la_masks is not None:
                rows = jnp.arange(tokens.shape[0])
                g = la_groups[rows, tokens[:, 0]]
                logit_mask = la_masks[rows, g]
            return _step(
                params, k_cache, v_cache, tokens, positions, block_tables,
                slot_mapping, last_idx, temperature, top_k, top_p, seeds,
                sample_steps, freq_pen, pres_pen, pos_limit, history, mrope_delta,
                mm_embeds, mm_slot_offset, mm_counts, mrope_positions, logit_mask,
                impl=impl, lp_k=lp_k,
            )

        self._step_chained_explicit_fn = _step_chained_explicit

        @functools.partial(jax.jit, static_argnames=("impl", "lp_k"), donate_argnums=(1, 2))
        def _spec_step(params, k_cache, v_cache, tokens, positions, block_tables, slot_mapping,
                       verify_indices, temperature, top_k, top_p, seeds, sample_steps,
                       freq_pen, pres_pen, history, mrope_delta=None,
                       mm_embeds=None, mm_slot_offset=None, mm_counts=None,
                       mrope_positions=None, logit_mask=None, *, impl, lp_k=0):
            """Speculative verify: one forward scoring V candidate positions
            per row, then a target sample at every one of them.

            ``verify_indices`` i32[B, V] names the token columns to score.
            Losslessness hinges on two properties of the flat [B*V] sampling
            below: (1) every op in ``sample_tokens`` is row-independent, so
            flat row b*V+j computes exactly what a non-speculative step with
            row b's params would; (2) the rng key for column j folds in
            ``sample_steps + j`` — the fold counter the non-speculative
            engine would have reached after accepting j tokens. Acceptance
            on the host is then plain prefix comparison ("exact replay"):
            with counter-based deterministic sampling the Leviathan
            rejection-sampling correction degenerates to equality, because
            the target "draw" at each position is itself reproducible.
            """
            b, v = verify_indices.shape
            mm_kw = {}
            if mm_embeds is not None:
                mm_kw = dict(mm_embeds=mm_embeds, mm_slot_offset=mm_slot_offset, mm_counts=mm_counts)
            if self.cfg.mrope_section:
                mm_kw["mrope_positions"] = (
                    mrope_positions if mrope_positions is not None
                    else _delta_mrope(positions, mrope_delta)
                )
            logits, k_cache, v_cache = self._forward(
                params, self.cfg, tokens, positions, k_cache, v_cache,
                block_tables, slot_mapping, verify_indices[:, 0],
                attn_impl=impl, mesh=self.mesh,
                logit_indices=verify_indices, contiguous_positions=False,
                **mm_kw,
            )  # f32[B, V, vocab]
            flat = logits.reshape(b * v, logits.shape[-1])
            cnt = (sample_steps[:, None] + jnp.arange(v, dtype=sample_steps.dtype)).reshape(-1)
            keys = jax.vmap(lambda s, c: jax.random.fold_in(jax.random.PRNGKey(s), c))(
                jnp.repeat(seeds, v), cnt
            )
            sample_logits = flat
            if logit_mask is not None:
                from dynamo_tpu.ops.attention import NEG_INF

                sample_logits = jnp.where(jnp.repeat(logit_mask, v, axis=0), flat, NEG_INF)
            targets = sample_tokens(
                sample_logits, keys,
                jnp.repeat(temperature, v), jnp.repeat(top_k, v), jnp.repeat(top_p, v),
                history=jnp.repeat(history, v, axis=0),
                frequency_penalty=jnp.repeat(freq_pen, v),
                presence_penalty=jnp.repeat(pres_pen, v),
            )
            if lp_k:
                from dynamo_tpu.ops.sampling import token_logprobs

                chosen, top_ids, top_lps = token_logprobs(flat, targets, lp_k)
                return (targets.reshape(b, v), k_cache, v_cache, chosen.reshape(b, v),
                        top_ids.reshape(b, v, lp_k), top_lps.reshape(b, v, lp_k))
            return targets.reshape(b, v), k_cache, v_cache

        self._spec_step_fn = _spec_step

        @functools.partial(jax.jit, static_argnames=("impl", "lp_k"), donate_argnums=(1, 2))
        def _spec_step_chained(params, k_cache, v_cache, chain_buf, chain_src,
                               tokens, positions, block_tables, slot_mapping,
                               verify_indices, temperature, top_k, top_p, seeds,
                               sample_steps, freq_pen, pres_pen, history,
                               mrope_delta=None, *, impl, lp_k=0):
            """Chained speculative verify: decode rows' column-0 (bonus/base)
            token gathers from the previous dispatch's device-resident
            samples; draft columns 1..K and prefill-chunk rows feed from host
            (drafts are host-proposed, chunk tokens are prompt text). The
            same losslessness argument as _spec_step applies unchanged — the
            gathered token equals the token the host would have shipped."""
            tokens, history = _apply_chain(tokens, history, sample_steps, chain_buf, chain_src)
            return _spec_step(
                params, k_cache, v_cache, tokens, positions, block_tables,
                slot_mapping, verify_indices, temperature, top_k, top_p, seeds,
                sample_steps, freq_pen, pres_pen, history, mrope_delta,
                impl=impl, lp_k=lp_k,
            )

        self._spec_step_chained_fn = _spec_step_chained

        @functools.partial(jax.jit, static_argnames=("num_steps",), donate_argnums=(1, 2))
        def _multi_step(params, k_cache, v_cache, tokens, positions, block_tables,
                        temperature, top_k, top_p, seeds, sample_steps,
                        freq_pen, pres_pen, pos_limit, history, mrope_delta=None,
                        *, num_steps):
            """``num_steps`` fused decode iterations in one dispatch.

            The sampled token of step i is step i+1's input; slot mapping is
            derived in-graph from positions and block tables (pages must be
            pre-allocated to cover positions + num_steps). Returns the sampled
            tokens [num_steps, B] — one host round-trip per burst, not per
            token, which is what decode throughput on a remote/tunneled chip
            lives or dies by.
            """
            ps = self.page_size
            zeros = jnp.zeros_like(tokens)
            h_width = history.shape[1]

            def body(carry, _):
                tok, pos, kc, vc, cnt, hist = carry
                page = jnp.take_along_axis(block_tables, (pos // ps)[:, None], axis=1)[:, 0]
                slot = page * ps + pos % ps
                # Burst overshoot (host discards those tokens) must never
                # touch live pages: past each row's finish line the write
                # lands in the reserved null page 0. This is what makes
                # page allocation capped at remaining-tokens safe.
                slot = jnp.where(pos < pos_limit, slot, 0)
                mm_kw = {}
                if self.cfg.mrope_section:
                    mm_kw["mrope_positions"] = _delta_mrope(pos[:, None], mrope_delta)
                logits, kc, vc = self._forward(
                    params, self.cfg, tok[:, None], pos[:, None], kc, vc,
                    block_tables, slot[:, None], zeros, attn_impl=self.attn_impl,
                    mesh=self.mesh,
                    **mm_kw,
                )
                keys = jax.vmap(lambda s, c: jax.random.fold_in(jax.random.PRNGKey(s), c))(seeds, cnt)
                nxt = sample_tokens(
                    logits, keys, temperature, top_k, top_p,
                    history=hist, frequency_penalty=freq_pen, presence_penalty=pres_pen,
                )
                # The burst's own samples count toward later steps' penalties.
                write = jnp.minimum(cnt, h_width - 1)
                hist = jax.vmap(lambda hrow, w, t: hrow.at[w].set(t))(hist, write, nxt)
                return (nxt, pos + 1, kc, vc, cnt + 1, hist), nxt

            (_, _, k_cache, v_cache, _, _), toks = jax.lax.scan(
                body, (tokens, positions, k_cache, v_cache, sample_steps, history), None, length=num_steps
            )
            return toks, k_cache, v_cache

        self._multi_step_fn = _multi_step

        @functools.partial(jax.jit, static_argnames=("b", "t", "n", "h", "num_steps"), donate_argnums=(1, 2))
        def _multi_step_packed(params, k_cache, v_cache, packed, *, b, t, n, h, num_steps):
            (tokens, positions, block_tables, _slot, _last,
             temperature, top_k, top_p, seeds, sample_steps,
             freq_pen, pres_pen, pos_limit, history, mrope_delta) = _unpack(packed, b, t, n, h)
            return _multi_step(
                params, k_cache, v_cache, tokens[:, 0], positions[:, 0], block_tables,
                temperature, top_k, top_p, seeds, sample_steps,
                freq_pen, pres_pen, pos_limit, history, mrope_delta, num_steps=num_steps,
            )

        self._multi_step_packed_fn = _multi_step_packed

        self._chain_tokens = None  # device i32[Bp] (or [Bp*V]): latest dispatch's samples

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def _write_page(k_cache, v_cache, k, v, pid):
            return (
                k_cache.at[:, pid].set(k.astype(k_cache.dtype)),
                v_cache.at[:, pid].set(v.astype(v_cache.dtype)),
            )

        self._write_page_fn = _write_page

        @jax.jit
        def _gather_pages(k_cache, v_cache, pids):
            return k_cache[:, pids], v_cache[:, pids]

        self._gather_pages_fn = _gather_pages

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def _scatter_pages(k_cache, v_cache, ks, vs, pids):
            # ks/vs: [L, N, ps, W]; one in-place scatter along the page axis.
            return (
                k_cache.at[:, pids].set(ks.astype(k_cache.dtype)),
                v_cache.at[:, pids].set(vs.astype(v_cache.dtype)),
            )

        self._scatter_pages_fn = _scatter_pages

        @jax.jit
        def _embed(params, tokens, mask):
            return llama.encode(params, self.cfg, tokens, mask, pooling=embed_pooling)

        self._embed_fn = _embed

    # -- tier access (block manager offload/onboard) -----------------------

    @_locked
    def read_page(self, page_id: int) -> tuple[np.ndarray, np.ndarray]:
        """Device->host copy of one page: ([L, ps, kv, hd], [L, ps, kv, hd])."""
        return (
            np.asarray(self.k_cache[:, page_id]),
            np.asarray(self.v_cache[:, page_id]),
        )

    @_locked
    def read_pages(self, page_ids: list[int]) -> list[tuple[np.ndarray, np.ndarray]]:
        """Batched device->host copy: one gather + one transfer for N pages.

        Page ids are padded to a power-of-two bucket so the jitted gather
        compiles for a handful of shapes only.
        """
        return self.read_pages_async(page_ids).wait()

    @_locked
    def read_pages_async(self, page_ids: list[int]) -> "InFlightPages":
        """Dispatch a batched page gather WITHOUT blocking on the result.

        Holds ``io_lock`` only for the gather dispatch + D2H kickoff, then
        returns an :class:`InFlightPages` handle whose ``wait()`` blocks on
        the host buffers. The gather output is a fresh device array (not an
        alias of the cache), so engine steps that donate the cache buffers
        can run while the copy is in flight — this is what lets a chunked
        KV transfer overlap chunk N+1's gather with chunk N's pack + wire.
        Same pow2 bucketing as :meth:`read_pages`: no new compiled shapes.
        """
        if not page_ids:
            return InFlightPages(None, None, 0)
        n = len(page_ids)
        padded = np.zeros(next_pow2(n), np.int32)
        padded[:n] = page_ids
        k, v = self._gather_pages_fn(self.k_cache, self.v_cache, jnp.asarray(padded))
        for buf in (k, v):
            try:  # start the device->host DMA early (best-effort API)
                buf.copy_to_host_async()
            except Exception:
                pass
        return InFlightPages(k, v, n)

    @_locked
    def write_page(self, page_id: int, k: np.ndarray, v: np.ndarray) -> None:
        """Host->device copy into one page (in place via buffer donation)."""
        self.k_cache, self.v_cache = self._write_page_fn(
            self.k_cache, self.v_cache, jnp.asarray(k), jnp.asarray(v), page_id
        )

    @_locked
    def write_pages(self, page_ids: list[int], ks, vs) -> None:
        """Batched host->device write: one transfer + one in-place scatter for
        N pages (the per-page path costs a full dispatch round-trip each).

        ``ks``/``vs``: per-page arrays [L, ps, W] (stacked on axis 1 here) or
        pre-stacked [L, N, ps, W] device/host arrays.
        """
        if not page_ids:
            return
        n = len(page_ids)
        k_stack = np.stack(ks, axis=1) if isinstance(ks, (list, tuple)) else ks
        v_stack = np.stack(vs, axis=1) if isinstance(vs, (list, tuple)) else vs
        padded_n = next_pow2(n)
        pids = np.zeros(padded_n, np.int32)
        pids[:n] = page_ids
        if padded_n != n:
            pad = ((0, 0), (0, padded_n - n)) + ((0, 0),) * (k_stack.ndim - 2)
            # Device inputs (pull-transport ingestion) must stay on device:
            # np.pad would bounce the whole stack through the host, defeating
            # the no-host-bounce pull path. jnp.pad keeps it a device op and
            # still works for host ndarrays.
            xp = jnp if isinstance(k_stack, jax.Array) else np
            k_stack = xp.pad(k_stack, pad)
            v_stack = xp.pad(v_stack, pad)
            pids[n:] = 0  # padding writes land in the reserved null page
        self.k_cache, self.v_cache = self._scatter_pages_fn(
            self.k_cache, self.v_cache, jnp.asarray(k_stack), jnp.asarray(v_stack),
            jnp.asarray(pids),
        )

    # -- bucketing ---------------------------------------------------------

    def _bucket_batch(self, b: int) -> int:
        bucket = min(next_pow2(b), max(self.max_batch_size, b))
        # Batch is dp-sharded: round up to a multiple of the dp axis size.
        return -(-bucket // self._dp) * self._dp

    def _bucket_time(self, t: int) -> int:
        # Mixed steps (decode rows fused with prefill chunks) draw T from
        # the same lattice: T = the longest chunk <= chunk_prefill_tokens,
        # so chunking adds no buckets beyond what whole-prompt prefill
        # already compiles (it strictly narrows the range, since the chunk
        # budget <= max_prefill_tokens).
        if t <= 1:
            return 1
        return min(next_pow2(t), max(self.prefill_bucket * ((t + self.prefill_bucket - 1) // self.prefill_bucket), t))

    def _bucket_pages(self, n: int) -> int:
        return max(1, next_pow2(n))

    def _pad(self, batch: StepBatch) -> StepBatch:
        b, t = batch.tokens.shape
        bp = self._bucket_batch(b)
        tp = self._bucket_time(t)
        np_ = self._bucket_pages(batch.block_tables.shape[1])
        hp = next_pow2(batch.history.shape[1])  # 1 when no penalties in batch
        mm = None
        if batch.mm_embeds is not None:
            mp = next_pow2(batch.mm_embeds.shape[1])
            mm = np.zeros((bp, mp, batch.mm_embeds.shape[2]), batch.mm_embeds.dtype)
            mm[: batch.mm_embeds.shape[0], : batch.mm_embeds.shape[1]] = batch.mm_embeds
        mrope3 = None
        if batch.mrope_positions is not None:
            mrope3 = np.zeros((bp, 3, tp), np.int32)
            mrope3[: batch.mrope_positions.shape[0], :, : batch.mrope_positions.shape[2]] = batch.mrope_positions
        lmask = None
        if batch.logit_mask is not None:
            lmask = np.ones((bp, batch.logit_mask.shape[1]), bool)
            lmask[: batch.logit_mask.shape[0]] = batch.logit_mask
        la_m = la_g = None
        if batch.la_masks is not None:
            gb, g, vocab = batch.la_masks.shape
            gp = next_pow2(g)
            # Pad rows and pad groups are all-True with group id 0: padding
            # samples stay unconstrained, exactly as on the sync path.
            la_m = np.ones((bp, gp, vocab), bool)
            la_m[:gb, :g] = batch.la_masks
            la_g = np.zeros((bp, vocab), np.int32)
            la_g[: batch.la_groups.shape[0]] = batch.la_groups

        def pad2(a, rows, cols, fill=0):
            out = np.full((rows, cols), fill, a.dtype)
            out[: a.shape[0], : a.shape[1]] = a
            return out

        def pad1(a, rows, fill=0):
            out = np.full((rows,), fill, a.dtype)
            out[: a.shape[0]] = a
            return out

        return StepBatch(
            tokens=pad2(batch.tokens, bp, tp),
            positions=pad2(batch.positions, bp, tp),
            block_tables=pad2(batch.block_tables, bp, np_),
            slot_mapping=pad2(batch.slot_mapping, bp, tp),
            last_token_index=pad1(batch.last_token_index, bp),
            temperature=pad1(batch.temperature, bp),
            top_k=pad1(batch.top_k, bp),
            top_p=pad1(batch.top_p, bp, fill=1.0),
            seeds=pad1(batch.seeds, bp),
            sample_steps=pad1(batch.sample_steps, bp),
            freq_pen=pad1(batch.freq_pen, bp),
            pres_pen=pad1(batch.pres_pen, bp),
            pos_limit=pad1(batch.pos_limit, bp),  # pad rows: limit 0 -> null page
            history=pad2(batch.history, bp, hp, fill=-1),
            mm_embeds=mm,
            mm_slot_offset=None if batch.mm_slot_offset is None else pad1(batch.mm_slot_offset, bp, fill=-1),
            mm_counts=None if batch.mm_counts is None else pad1(batch.mm_counts, bp),
            mrope_delta=(np.zeros(bp, np.int32) if batch.mrope_delta is None
                         else pad1(batch.mrope_delta, bp)),
            mrope_positions=mrope3,
            logit_mask=lmask,
            la_masks=la_m,
            la_groups=la_g,
            num_new=None if batch.num_new is None else pad1(batch.num_new, bp),
            spec_start=None if batch.spec_start is None else pad1(batch.spec_start, bp),
        )

    # -- execution ---------------------------------------------------------

    def _select_impl(self, padded: StepBatch) -> str | None:
        """Pick the attention path for a (mesh-sharded) step.

        Whole-prompt prefills on a mesh with an ``sp`` axis run sequence-
        parallel ring attention (MLA included — its absorbed form rings the
        latent/rope stream, ``models/mla.py``): every sequence's context
        starts at position 0 inside this chunk, so attending only the
        in-flight K/V is exact. Chunk-continuations and decode use the
        paged path (they must read the cache)."""
        t = padded.tokens.shape[1]
        if (
            self.mesh is not None
            and int(self.mesh.shape.get("sp", 1)) > 1
            and t > 1
            and t % int(self.mesh.shape["sp"]) == 0
            and bool((padded.positions[:, 0] == 0).all())
        ):
            return "ring"
        return self.attn_impl

    def _attn_dispatch(self, padded: StepBatch, impl: str | None, *, verify: bool = False) -> tuple[str, str]:
        """(phase, path) the attention layer will take for this dispatch.

        A host-side mirror of the models/* routing predicates (pure shape
        math — no tracing), so every engine step can record whether its
        attention ran on a Pallas kernel ("pallas"), the XLA gather
        formulation ("fallback"), or the sequence-parallel ring path
        ("ring") without touching the jitted program."""
        t = int(padded.tokens.shape[1])
        phase = "verify" if (verify and t > 1) else ("decode" if t == 1 else "prefill")
        if impl == "ring":
            return phase, "ring"
        if impl != "pallas" or self.cfg.sliding_window > 0:
            return phase, "fallback"
        from dynamo_tpu.ops.pallas_paged import interpret_mode

        interp = interpret_mode()
        t_q = t if phase == "verify" else 1  # prefill kernel tiles T freely
        if self.cfg.attn_type == "mla":
            from dynamo_tpu.ops.pallas_mla import mla_decode_supported

            # MLA prefill DOES ride the multi-query kernel (T <= row cap).
            ok = mla_decode_supported(
                self.k_cache.shape[-1], self.v_cache.shape[-1],
                t if t > 1 else 1, self.cfg.num_heads, interpret=interp,
            )
        else:
            from dynamo_tpu.ops.pallas_paged import decode_kernel_supported

            ok = decode_kernel_supported(
                self.cfg.num_heads, self.cfg.head_dim, self.k_cache.shape[-1],
                t_q, interpret=interp if phase != "prefill" else False,
            )
        return phase, "pallas" if ok else "fallback"

    # -- device-cost plane -------------------------------------------------

    def _dispatch_kind(self, batch: StepBatch, *, spec: bool = False) -> str:
        """Ledger step-kind of a dispatch (cost-plane vocabulary)."""
        if spec:
            return "spec_verify"
        if batch.tokens.shape[1] == 1:
            return "decode"
        if batch.num_new is not None and bool((np.asarray(batch.num_new) == 1).any()):
            return "mixed"  # decode rows fused into a multi-column step
        return "prefill"

    def _cost_estimate(self, padded: StepBatch, kind: str) -> dict[str, float] | None:
        """Model-derived {bytes, flops} fallback for one dispatch of this
        padded bucket: weight stream + page-granular KV window."""
        try:
            b, t = padded.tokens.shape
            window_tokens = padded.block_tables.shape[1] * self.page_size
            itemsize = int(np.dtype(self.k_cache.dtype).itemsize)
            return decode_step_estimate(
                self.params, self.cfg, b, window_tokens,
                cache_itemsize=itemsize, new_tokens=b * t,
            )
        except Exception:  # estimate is best-effort; pending beats wrong
            return None

    def _cost_call(self, program: str, key: tuple, kind: str, padded: StepBatch,
                   fn, *args, **kwargs):
        """Run one jitted dispatch, registering its bucket with the cost
        registry on first sight. The lowering thunk avatars the arguments
        *before* the call (donation invalidates the cache buffers after),
        and the actual extraction runs on the registry's background thread
        — this wrapper adds one set lookup to warm dispatches."""
        reg = self.cost_registry
        if reg is not None and not reg.seen(program, key):
            try:
                reg.submit(
                    program, key, kind,
                    lower=make_lower_thunk(fn, args, kwargs),
                    estimate=self._cost_estimate(padded, kind),
                )
            except Exception:
                logger.debug("cost submit failed for %s", program, exc_info=True)
        return fn(*args, **kwargs)

    @_locked
    def step(self, batch: StepBatch, lp_k: int = 0):
        """Run one forward+sample step; returns sampled token ids i32[B_real].

        Rows may carry different real token counts (``num_new``): a mixed
        step fuses 1-token decode rows with multi-token prefill-chunk rows
        in one dispatch. Per-row ``last_token_index`` already makes the
        logit gather exact for that; a short row's padding columns attend
        nothing real (per-token position masks) and write KV to the null
        page, and only rows whose span completes their sequence have their
        sample accepted by the engine (the rest are discarded host-side).

        ``lp_k > 0`` additionally returns a logprobs dict (chosen-token
        logprob + top-``lp_k`` alternatives, OpenAI semantics):
        ``(tokens, {"logprob": f32[B], "top_ids": i32[B, k], "top_lps":
        f32[B, k]})``. A separate compiled program per lp_k presence — text
        traffic pays nothing."""
        b_real = batch.batch_size
        padded = self._pad(batch)
        impl = self._select_impl(padded) if self.mesh is not None else self.attn_impl
        self.last_attn_dispatch = self._attn_dispatch(padded, impl)
        # Everything the jitted programs specialize on, post-padding: this is
        # the compile cache key XLA sees (shapes + static args + arg presence).
        dispatch_key = (
            padded.tokens.shape[0], padded.tokens.shape[1],
            padded.block_tables.shape[1], padded.history.shape[1],
            lp_k, impl, self.mesh is not None,
            padded.mm_embeds is not None, padded.logit_mask is not None,
        )
        cost_kind = self._dispatch_kind(batch)
        with timed_dispatch(self.compile_tracker, "step", dispatch_key,
                            cost=self.cost_registry, kind=cost_kind):
            if padded.mm_embeds is not None or padded.logit_mask is not None:
                if self.mesh is not None:
                    from dynamo_tpu.parallel.sharding import batch_sharding

                    def put(a):
                        return jax.device_put(a, batch_sharding(self.mesh, a.ndim))
                else:
                    put = jnp.asarray

                def opt(a):
                    return None if a is None else put(a)

                out = self._cost_call(
                    "step", dispatch_key, cost_kind, padded, self._step_fn,
                    self.params, self.k_cache, self.v_cache,
                    put(padded.tokens), put(padded.positions),
                    put(padded.block_tables), put(padded.slot_mapping),
                    put(padded.last_token_index), put(padded.temperature),
                    put(padded.top_k), put(padded.top_p),
                    put(padded.seeds), put(padded.sample_steps),
                    put(padded.freq_pen), put(padded.pres_pen),
                    put(padded.pos_limit), put(padded.history),
                    put(padded.mrope_delta),
                    opt(padded.mm_embeds), opt(padded.mm_slot_offset), opt(padded.mm_counts),
                    opt(padded.mrope_positions), opt(padded.logit_mask),
                    impl=impl,
                    lp_k=lp_k,
                )
            elif self.mesh is not None:
                from dynamo_tpu.parallel.sharding import batch_sharding

                def put(a):
                    return jax.device_put(a, batch_sharding(self.mesh, a.ndim))

                out = self._cost_call(
                    "step", dispatch_key, cost_kind, padded, self._step_fn,
                    self.params, self.k_cache, self.v_cache,
                    put(padded.tokens), put(padded.positions),
                    put(padded.block_tables), put(padded.slot_mapping),
                    put(padded.last_token_index), put(padded.temperature),
                    put(padded.top_k), put(padded.top_p),
                    put(padded.seeds), put(padded.sample_steps),
                    put(padded.freq_pen), put(padded.pres_pen),
                    put(padded.pos_limit), put(padded.history),
                    put(padded.mrope_delta),
                    impl=impl, lp_k=lp_k,
                )
            else:
                b, t = padded.tokens.shape
                out = self._cost_call(
                    "step", dispatch_key, cost_kind, padded, self._step_packed_fn,
                    self.params, self.k_cache, self.v_cache, jnp.asarray(_pack(padded)),
                    b=b, t=t, n=padded.block_tables.shape[1], h=padded.history.shape[1],
                    lp_k=lp_k,
                )
            if lp_k:
                next_tokens, self.k_cache, self.v_cache, chosen, top_ids, top_lps = out
                return np.asarray(next_tokens)[:b_real], {
                    "logprob": np.asarray(chosen)[:b_real],
                    "top_ids": np.asarray(top_ids)[:b_real],
                    "top_lps": np.asarray(top_lps)[:b_real],
                }
            next_tokens, self.k_cache, self.v_cache = out
            return np.asarray(next_tokens)[:b_real]

    @_locked
    def spec_step(self, batch: StepBatch, verify_width: int, lp_k: int = 0):
        """Speculative verify dispatch: returns target tokens i32[B_real, V].

        ``batch`` is a mixed StepBatch whose decode rows carry draft tokens
        as extra real columns (``num_new`` = 1 + draft length) and whose
        ``spec_start`` names each row's first verify column (0 for decode
        rows — they score every column — and n-1 for prefill-chunk rows,
        which score only their last column exactly like :meth:`step`).
        Verify columns beyond a row's real span clamp to its last column;
        the engine discards those duplicates host-side.

        ``verify_width`` (V = spec_k + 1) is a static program dimension —
        keep it constant per engine so speculation adds exactly one
        compiled program per (B, T, N) bucket. Column j of the result is
        the token the non-speculative engine would sample after accepting
        j draft tokens (rng fold ``sample_steps + j``); with ``lp_k`` the
        logprobs dict carries per-column arrays [B, V] / [B, V, k].
        """
        b_real = batch.batch_size
        padded = self._pad(batch)
        bp = padded.tokens.shape[0]
        start = padded.spec_start if padded.spec_start is not None else np.zeros(bp, np.int32)
        vi = np.minimum(
            start[:, None] + np.arange(verify_width, dtype=np.int32)[None, :],
            padded.last_token_index[:, None],
        ).astype(np.int32)
        impl = self._select_impl(padded) if self.mesh is not None else self.attn_impl
        self.last_attn_dispatch = self._attn_dispatch(padded, impl, verify=True)
        dispatch_key = (
            bp, padded.tokens.shape[1], padded.block_tables.shape[1],
            padded.history.shape[1], verify_width, lp_k, impl, self.mesh is not None,
            padded.mm_embeds is not None, padded.logit_mask is not None,
        )
        with timed_dispatch(self.compile_tracker, "spec_step", dispatch_key,
                            cost=self.cost_registry, kind="spec_verify"):
            if self.mesh is not None:
                from dynamo_tpu.parallel.sharding import batch_sharding

                def put(a):
                    return jax.device_put(a, batch_sharding(self.mesh, a.ndim))
            else:
                put = jnp.asarray

            def opt(a):
                return None if a is None else put(a)

            out = self._cost_call(
                "spec_step", dispatch_key, "spec_verify", padded, self._spec_step_fn,
                self.params, self.k_cache, self.v_cache,
                put(padded.tokens), put(padded.positions),
                put(padded.block_tables), put(padded.slot_mapping),
                put(vi), put(padded.temperature), put(padded.top_k), put(padded.top_p),
                put(padded.seeds), put(padded.sample_steps),
                put(padded.freq_pen), put(padded.pres_pen), put(padded.history),
                put(padded.mrope_delta),
                opt(padded.mm_embeds), opt(padded.mm_slot_offset), opt(padded.mm_counts),
                opt(padded.mrope_positions), opt(padded.logit_mask),
                impl=impl, lp_k=lp_k,
            )
        if lp_k:
            targets, self.k_cache, self.v_cache, chosen, top_ids, top_lps = out
            return np.asarray(targets)[:b_real], {
                "logprob": np.asarray(chosen)[:b_real],
                "top_ids": np.asarray(top_ids)[:b_real],
                "top_lps": np.asarray(top_lps)[:b_real],
            }
        targets, self.k_cache, self.v_cache = out
        return np.asarray(targets)[:b_real]

    @_locked
    def multi_step(self, batch: StepBatch, num_steps: int) -> np.ndarray:
        """Fused decode burst; returns sampled tokens i32[B_real, num_steps].

        ``batch`` must be a decode batch (T == 1) whose block tables cover
        positions + num_steps.
        """
        assert batch.tokens.shape[1] == 1, "multi_step is decode-only"
        b_real = batch.batch_size
        padded = self._pad(batch)
        self.last_attn_dispatch = self._attn_dispatch(padded, self.attn_impl)
        dispatch_key = (
            padded.tokens.shape[0], padded.tokens.shape[1],
            padded.block_tables.shape[1], padded.history.shape[1],
            num_steps, self.mesh is not None,
        )
        # steps=num_steps: XLA cost analysis counts the fused loop body once,
        # so the per-record bytes/flops cover ONE decode iteration.
        with timed_dispatch(self.compile_tracker, "multi_step", dispatch_key,
                            cost=self.cost_registry, kind="decode", steps=num_steps):
            if self.mesh is not None:
                from dynamo_tpu.parallel.sharding import batch_sharding

                def put(a):
                    return jax.device_put(a, batch_sharding(self.mesh, a.ndim))

                toks, self.k_cache, self.v_cache = self._cost_call(
                    "multi_step", dispatch_key, "decode", padded, self._multi_step_fn,
                    self.params, self.k_cache, self.v_cache,
                    put(padded.tokens[:, 0]), put(padded.positions[:, 0]),
                    put(padded.block_tables), put(padded.temperature),
                    put(padded.top_k), put(padded.top_p),
                    put(padded.seeds), put(padded.sample_steps),
                    put(padded.freq_pen), put(padded.pres_pen),
                    put(padded.pos_limit), put(padded.history),
                    put(padded.mrope_delta),
                    num_steps=num_steps,
                )
            else:
                b, t = padded.tokens.shape
                toks, self.k_cache, self.v_cache = self._cost_call(
                    "multi_step", dispatch_key, "decode", padded, self._multi_step_packed_fn,
                    self.params, self.k_cache, self.v_cache, jnp.asarray(_pack(padded)),
                    b=b, t=t, n=padded.block_tables.shape[1], h=padded.history.shape[1],
                    num_steps=num_steps,
                )
            return np.asarray(toks).T[:b_real]  # [B, num_steps]

    def _chain_src_padded(self, chain_src, b_real: int, bp: int) -> np.ndarray:
        """Pad a per-row chain source vector to the batch bucket (-1 = host).

        ``chain_src=None`` with chaining requested means the legacy
        whole-batch form: row i chains from flat index i of the previous
        dispatch's buffer."""
        src = np.full(bp, -1, np.int32)
        if chain_src is None:
            src[:b_real] = np.arange(b_real, dtype=np.int32)
        else:
            src[:b_real] = np.asarray(chain_src, np.int32)
        mx = int(src.max())
        assert mx < 0 or (
            self._chain_tokens is not None and mx < self._chain_tokens.shape[0]
        ), "chain_src points past the device-resident sample buffer"
        return src

    @_locked
    def step_async(self, batch: StepBatch, lp_k: int = 0, *, chain: bool = False,
                   chain_src: np.ndarray | None = None) -> "DeviceStepTokens":
        """Dispatch ONE (possibly mixed prefill+decode) step without blocking
        on its result.

        The overlapped engine loop (``DYN_OVERLAP=1``) uses this to run a
        depth-1 pipeline at decode_steps == 1: the sampled tokens stay
        device-resident (``self._chain_tokens``, kept flat i32[Bp]), so the
        next step can be dispatched with ``chain=True`` — each row's input
        token gathered in-graph per ``chain_src`` — before this step's
        tokens ever reach the host. ``chain_src`` i32[B_real] names, per
        row, a flat index into the previous dispatch's buffer (plain step:
        its row index; spec verify: row*V + accepted-column) or -1 to feed
        that row from host (prefill chunks, fresh admissions). Rows may
        carry multiple real token columns exactly like :meth:`step` — only
        column 0 is ever chained, which is where mixed decode rows keep
        their single real token. Returns a :class:`DeviceStepTokens` handle
        whose ``result()`` blocks on the already-started device->host copy.

        Extras the packed i32 buffer has no slots for — multimodal embeds,
        explicit 3-axis mrope coords, a host-known constraint mask
        (``logit_mask``, unchained rows only) or the lookahead mask groups
        (``la_masks``/``la_groups``, chained dispatches) — route through the
        explicit-args programs; plain text steps keep the single packed
        transfer. ``lp_k`` rides along — the aux logprob arrays are fetched
        with the tokens.
        """
        assert batch.la_masks is None or chain, (
            "lookahead mask groups resolve against the chain gather; "
            "host-known tokens take logit_mask"
        )
        assert batch.logit_mask is None or not chain, (
            "chained dispatches carry constraint masks as la_masks/la_groups"
        )
        b_real = batch.batch_size
        padded = self._pad(batch)
        impl = self._select_impl(padded) if self.mesh is not None else self.attn_impl
        self.last_attn_dispatch = self._attn_dispatch(padded, impl)
        b, t = padded.tokens.shape
        n = padded.block_tables.shape[1]
        h = padded.history.shape[1]
        src = self._chain_src_padded(chain_src, b_real, b) if chain else None
        extras = (
            padded.mm_embeds is not None or padded.mrope_positions is not None
            or padded.logit_mask is not None or padded.la_masks is not None
        )
        dispatch_key = (
            b, t, n, h, lp_k, chain, impl, self.mesh is not None,
            padded.mm_embeds is not None, padded.logit_mask is not None,
            padded.la_masks is not None,
        )
        cost_kind = self._dispatch_kind(batch)
        with timed_dispatch(self.compile_tracker, "step_async", dispatch_key,
                            cost=self.cost_registry, kind=cost_kind):
            if self.mesh is not None or extras:
                if self.mesh is not None:
                    from dynamo_tpu.parallel.sharding import batch_sharding

                    def put(a):
                        return jax.device_put(a, batch_sharding(self.mesh, a.ndim))
                else:
                    put = jnp.asarray

                def opt(a):
                    return None if a is None else put(a)

                explicit = (
                    put(padded.tokens), put(padded.positions),
                    put(padded.block_tables), put(padded.slot_mapping),
                    put(padded.last_token_index), put(padded.temperature),
                    put(padded.top_k), put(padded.top_p),
                    put(padded.seeds), put(padded.sample_steps),
                    put(padded.freq_pen), put(padded.pres_pen),
                    put(padded.pos_limit), put(padded.history),
                    put(padded.mrope_delta),
                )
                if chain:
                    out = self._cost_call(
                        "step_async", dispatch_key, cost_kind, padded,
                        self._step_chained_explicit_fn,
                        self.params, self.k_cache, self.v_cache,
                        self._chain_tokens, put(src), *explicit,
                        opt(padded.mm_embeds), opt(padded.mm_slot_offset),
                        opt(padded.mm_counts), opt(padded.mrope_positions),
                        opt(padded.la_masks), opt(padded.la_groups),
                        impl=impl, lp_k=lp_k,
                    )
                else:
                    out = self._cost_call(
                        "step_async", dispatch_key, cost_kind, padded,
                        self._step_fn,
                        self.params, self.k_cache, self.v_cache, *explicit,
                        opt(padded.mm_embeds), opt(padded.mm_slot_offset),
                        opt(padded.mm_counts), opt(padded.mrope_positions),
                        opt(padded.logit_mask),
                        impl=impl, lp_k=lp_k,
                    )
            else:
                packed = jnp.asarray(_pack(padded))
                if chain:
                    out = self._cost_call(
                        "step_async", dispatch_key, cost_kind, padded,
                        self._step_chained_fn,
                        self.params, self.k_cache, self.v_cache, packed,
                        self._chain_tokens, jnp.asarray(src),
                        b=b, t=t, n=n, h=h, lp_k=lp_k,
                    )
                else:
                    out = self._cost_call(
                        "step_async", dispatch_key, cost_kind, padded,
                        self._step_packed_fn,
                        self.params, self.k_cache, self.v_cache, packed,
                        b=b, t=t, n=n, h=h, lp_k=lp_k,
                    )
        if lp_k:
            toks, self.k_cache, self.v_cache, chosen, top_ids, top_lps = out
            aux = (chosen, top_ids, top_lps)
        else:
            toks, self.k_cache, self.v_cache = out
            aux = None
        self._chain_tokens = toks
        for buf in (toks, *(aux or ())):
            try:  # start the device->host DMA early; overlaps the next step
                buf.copy_to_host_async()
            except Exception:
                pass
        return DeviceStepTokens(toks, aux, b_real)

    @_locked
    def spec_step_async(self, batch: StepBatch, verify_width: int, lp_k: int = 0, *,
                        chain_src: np.ndarray | None = None) -> "DeviceSpecTokens":
        """Dispatch a speculative verify without blocking on its result.

        Same batch contract as :meth:`spec_step`. With ``chain_src`` (see
        :meth:`step_async`) the decode rows' column-0 base token gathers
        in-graph from the previous dispatch's device-resident samples, so a
        verify can itself be the pipeline's one-step lookahead after a plain
        chained step (a plain step emits exactly one token per row, so the
        verify's positions are host-predictable even before that token
        lands). The verify's own targets become the new chain buffer, flat
        i32[Bp*V] row-major — the engine chains the NEXT dispatch from flat
        index row*V + (accepted columns - 1) once acceptance is known.
        """
        assert batch.mm_embeds is None and batch.logit_mask is None, (
            "spec_step_async does not take multimodal/constrained batches"
        )
        b_real = batch.batch_size
        padded = self._pad(batch)
        bp = padded.tokens.shape[0]
        start = padded.spec_start if padded.spec_start is not None else np.zeros(bp, np.int32)
        vi = np.minimum(
            start[:, None] + np.arange(verify_width, dtype=np.int32)[None, :],
            padded.last_token_index[:, None],
        ).astype(np.int32)
        impl = self._select_impl(padded) if self.mesh is not None else self.attn_impl
        self.last_attn_dispatch = self._attn_dispatch(padded, impl, verify=True)
        chain = chain_src is not None
        src = self._chain_src_padded(chain_src, b_real, bp) if chain else None
        dispatch_key = (
            bp, padded.tokens.shape[1], padded.block_tables.shape[1],
            padded.history.shape[1], verify_width, lp_k, chain, impl,
            self.mesh is not None,
        )
        with timed_dispatch(self.compile_tracker, "spec_step_async", dispatch_key,
                            cost=self.cost_registry, kind="spec_verify"):
            if self.mesh is not None:
                from dynamo_tpu.parallel.sharding import batch_sharding

                def put(a):
                    return jax.device_put(a, batch_sharding(self.mesh, a.ndim))
            else:
                put = jnp.asarray
            explicit = (
                put(padded.tokens), put(padded.positions),
                put(padded.block_tables), put(padded.slot_mapping),
                put(vi), put(padded.temperature), put(padded.top_k), put(padded.top_p),
                put(padded.seeds), put(padded.sample_steps),
                put(padded.freq_pen), put(padded.pres_pen), put(padded.history),
                put(padded.mrope_delta),
            )
            if chain:
                out = self._cost_call(
                    "spec_step_async", dispatch_key, "spec_verify", padded,
                    self._spec_step_chained_fn,
                    self.params, self.k_cache, self.v_cache,
                    self._chain_tokens, put(src), *explicit,
                    impl=impl, lp_k=lp_k,
                )
            else:
                out = self._cost_call(
                    "spec_step_async", dispatch_key, "spec_verify", padded,
                    self._spec_step_fn,
                    self.params, self.k_cache, self.v_cache, *explicit,
                    impl=impl, lp_k=lp_k,
                )
        if lp_k:
            targets, self.k_cache, self.v_cache, chosen, top_ids, top_lps = out
            aux = (chosen, top_ids, top_lps)
        else:
            targets, self.k_cache, self.v_cache = out
            aux = None
        self._chain_tokens = targets.reshape(-1)  # flat [Bp*V] chain buffer
        for buf in (targets, *(aux or ())):
            try:  # start the device->host DMA early; overlaps the next step
                buf.copy_to_host_async()
            except Exception:
                pass
        return DeviceSpecTokens(targets, aux, b_real)

    def embed(self, token_lists: list[list[int]]) -> np.ndarray:
        """Sentence embeddings for N token sequences; returns f32[N, D].

        Runs the cache-free encoder (`models/llama.encode`) — params are
        read-only and nothing is donated, so this deliberately does NOT take
        ``io_lock``: embedding traffic must not stall the decode loop.
        """
        if not token_lists:
            return np.zeros((0, self.cfg.hidden_size), np.float32)
        n = len(token_lists)
        t = max(1, max(len(ts) for ts in token_lists))
        bp, tp = next_pow2(n), self._bucket_time(t)
        tokens = np.zeros((bp, tp), np.int32)
        mask = np.zeros((bp, tp), bool)
        for i, ts in enumerate(token_lists):
            tokens[i, : len(ts)] = ts
            mask[i, : len(ts)] = True
        out = self._embed_fn(self.params, jnp.asarray(tokens), jnp.asarray(mask))
        return np.asarray(out)[:n]

    def can_chain(self, batch_size: int) -> bool:
        """True if a chained burst for this real batch size would line up with
        the previous burst's padded output."""
        return (
            self._chain_tokens is not None
            and self._chain_tokens.shape[0] == self._bucket_batch(batch_size)
        )

    def chain_len(self) -> int:
        """Flat length of the device-resident sample buffer (0 = no buffer).

        The engine validates its per-row ``chain_src`` indices against this
        before dispatching a chained step."""
        return 0 if self._chain_tokens is None else int(self._chain_tokens.shape[0])

    def reset_chain(self) -> None:
        self._chain_tokens = None

    def cache_memory_bytes(self) -> int:
        return int(self.k_cache.nbytes + self.v_cache.nbytes)


class InFlightPages:
    """Handle to a dispatched page gather whose device->host copy is in
    flight (``ModelRunner.read_pages_async``)."""

    def __init__(self, k: jax.Array | None, v: jax.Array | None, n: int) -> None:
        self._k = k
        self._v = v
        self._n = n

    @property
    def num_pages(self) -> int:
        return self._n

    def wait(self) -> list[tuple[np.ndarray, np.ndarray]]:
        """Block until the pages are on host; returns [(k, v), ...] per page
        ([L, ps, W] each), pow2 padding sliced off."""
        if self._n == 0:
            return []
        k_host, v_host = np.asarray(self._k), np.asarray(self._v)
        return [(k_host[:, i], v_host[:, i]) for i in range(self._n)]


class DeviceStepTokens:
    """Handle to a single dispatched decode step's sampled tokens (and
    optional logprob aux arrays), device-resident (``ModelRunner.step_async``)."""

    def __init__(self, toks: jax.Array, aux, b_real: int) -> None:
        self._toks = toks
        self._aux = aux  # (chosen, top_ids, top_lps) or None
        self._b_real = b_real

    def result(self) -> tuple[np.ndarray, dict | None]:
        """Block until on host; returns (tokens i32[B_real, 1], lp_aux|None)."""
        toks = np.asarray(self._toks)[: self._b_real, None]
        if self._aux is None:
            return toks, None
        chosen, top_ids, top_lps = self._aux
        return toks, {
            "logprob": np.asarray(chosen)[: self._b_real],
            "top_ids": np.asarray(top_ids)[: self._b_real],
            "top_lps": np.asarray(top_lps)[: self._b_real],
        }


class DeviceSpecTokens:
    """Handle to a dispatched speculative verify's target tokens (and
    optional logprob aux), device-resident (``ModelRunner.spec_step_async``)."""

    def __init__(self, targets: jax.Array, aux, b_real: int) -> None:
        self._targets = targets  # [Bp, V]
        self._aux = aux
        self._b_real = b_real

    def result(self) -> tuple[np.ndarray, dict | None]:
        """Block until on host; returns (targets i32[B_real, V], lp_aux|None)
        — the same values :meth:`ModelRunner.spec_step` returns."""
        targets = np.asarray(self._targets)[: self._b_real]
        if self._aux is None:
            return targets, None
        chosen, top_ids, top_lps = self._aux
        return targets, {
            "logprob": np.asarray(chosen)[: self._b_real],
            "top_ids": np.asarray(top_ids)[: self._b_real],
            "top_lps": np.asarray(top_lps)[: self._b_real],
        }
