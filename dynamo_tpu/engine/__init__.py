"""The first-party JAX serving engine.

Continuous batching over a paged HBM KV cache, with prefix-cache reuse keyed
by chained block hashes (``dynamo_tpu.tokens``) and native KV stored/removed
event emission for the KV-aware router.

Structure:

- :mod:`dynamo_tpu.engine.allocator` — HBM page pool: free list, refcounted
  prefix cache, LRU eviction, KV events (the G1 tier).
- :mod:`dynamo_tpu.engine.sequence` — per-request runtime state.
- :mod:`dynamo_tpu.engine.runner` — bucketed jit execution of the model's
  paged forward + fused sampling; owns the device cache arrays.
- :mod:`dynamo_tpu.engine.scheduler` — admission / decode batching /
  preemption policy.
- :mod:`dynamo_tpu.engine.core` — synchronous engine step loop tying the
  above together.
- :mod:`dynamo_tpu.engine.service` — the async AsyncEngine facade served on a
  runtime endpoint.

The reference delegates all of this to vLLM/SGLang/TRT-LLM (SURVEY.md L4);
here it is the framework's own execution layer, designed for XLA: static
bucket shapes, donated cache buffers, one traced layer per model.
"""

from dynamo_tpu.engine.allocator import PageAllocator
from dynamo_tpu.engine.core import EngineCore, EngineConfig

__all__ = ["PageAllocator", "EngineCore", "EngineConfig"]
