"""Async engine service: the AsyncEngine facade over the synchronous core.

One background loop owns the EngineCore (single-writer — no locking):
it drains the intake queue, runs engine steps in a worker thread (so the
event loop keeps serving streams while XLA executes), and fans step outputs
out to per-request asyncio queues.

This is the stage that gets served on a runtime Endpoint
(``runtime.Endpoint.serve``); with KV events and metrics wired to the
runtime's event plane it is the full equivalent of one reference "worker"
process (vLLM subprocess + publisher side-cars, SURVEY.md §3 call stacks B/D).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, AsyncIterator

from dynamo_tpu.engine.core import EngineCore
from dynamo_tpu.engine.sequence import Sequence
from dynamo_tpu.protocols.common import EngineOutput, PreprocessedRequest
from dynamo_tpu.protocols.kv import ForwardPassMetrics
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.faults import FAULTS

logger = logging.getLogger(__name__)

_SENTINEL = object()


class JaxEngineService(AsyncEngine[Any, dict]):
    """Serves PreprocessedRequest (or its dict form) -> stream of EngineOutput dicts."""

    def __init__(self, core: EngineCore) -> None:
        self.core = core
        core.defer_offloads = True  # we flush after routing outputs (below)
        self.aux: list = []  # companion tasks (metrics publisher, ...) closed with us
        self._intake: asyncio.Queue = asyncio.Queue()
        self._streams: dict[int, asyncio.Queue] = {}
        self._loop_task: asyncio.Task | None = None
        self._wake = asyncio.Event()
        self._closed = False
        self._draining = False

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> "JaxEngineService":
        if self._closed:
            raise RuntimeError("engine service is closed")
        if self._loop_task is None:
            self._loop_task = asyncio.create_task(self._engine_loop(), name="jax-engine-loop")
        return self

    async def close(self) -> None:
        self._closed = True
        self._wake.set()
        for a in self.aux:
            await a.close()
        self.aux = []
        if self._loop_task is not None:
            self._loop_task.cancel()
            try:
                await self._loop_task
            except asyncio.CancelledError:
                pass
            self._loop_task = None
        # Cancelling the loop task does NOT stop a core.step() already
        # running in the executor thread — abort_all takes the core's
        # step_lock, so running it in the executor waits that step out
        # before touching the engine state it is mutating.
        await asyncio.get_running_loop().run_in_executor(None, self.core.abort_all)
        # In-flight streams would otherwise wait forever for a sentinel the
        # dead loop can never send (their consumers hang on shutdown/crash).
        self._drain_intake_failed()
        if self._streams:
            self._notify_streams_failed()

    def _drain_intake_failed(self) -> None:
        """Fail requests queued but never admitted by the (now dead) loop."""
        from dynamo_tpu.protocols.common import FinishReason

        drained = 0
        while True:
            try:
                _req, _ctx, out_q, _t_enq = self._intake.get_nowait()
            except asyncio.QueueEmpty:
                break
            out_q.put_nowait(EngineOutput(token_ids=[], finish_reason=FinishReason.ERROR))
            out_q.put_nowait(_SENTINEL)
            drained += 1
        if drained:
            flight = getattr(self.core, "flight", None)
            if flight is not None:
                from dynamo_tpu.observability.flight import CRASH

                flight.record(CRASH, where="intake_drain", drained=drained)

    async def drain(self, timeout: float = 30.0) -> bool:
        """Stop admitting new requests and wait for in-flight ones to finish.

        Returns True if everything finished before the deadline. The engine
        loop keeps stepping throughout — draining stops *admission*, not
        progress on work already admitted.
        """
        self._draining = True
        deadline = time.monotonic() + timeout
        while (self._streams or not self._intake.empty()) and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        return not self._streams and self._intake.empty()

    # -- engine loop -------------------------------------------------------

    async def _engine_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while not self._closed:
            # Drain intake without blocking.
            admitted = False
            while True:
                try:
                    request, context, out_q, t_enq = self._intake.get_nowait()
                except asyncio.QueueEmpty:
                    break
                # Intake-to-admission gap: how long the request sat waiting
                # for the engine loop (scheduler queue wait on the timeline).
                from dynamo_tpu.tracing import record_span, trace_of

                record_span(
                    "engine_queue_wait",
                    (time.perf_counter() - t_enq) * 1e3,
                    trace=trace_of(context),
                    request_id=context.id,
                )
                try:
                    seq = self.core.add_request(request, context)
                except Exception:
                    logger.exception("add_request failed; failing that request only")
                    from dynamo_tpu.protocols.common import FinishReason

                    out_q.put_nowait(EngineOutput(token_ids=[], finish_reason=FinishReason.ERROR))
                    out_q.put_nowait(_SENTINEL)
                    admitted = True
                    continue
                self._streams[seq.seq_id] = out_q
                if seq.is_finished:  # rejected at intake (too long / empty)
                    out_q.put_nowait(
                        EngineOutput(token_ids=[], finish_reason=seq.finish_reason, prompt_tokens=seq.num_prompt)
                    )
                    out_q.put_nowait(_SENTINEL)
                    del self._streams[seq.seq_id]
                admitted = True

            if not self.core.has_work:
                if not admitted:
                    self._wake.clear()
                    await self._wake.wait()
                continue

            # One engine step off-thread: the event loop stays responsive.
            # (If this task is cancelled mid-step, the executor thread keeps
            # running — close() serializes against it via core.step_lock.)
            try:
                if FAULTS.armed:
                    FAULTS.fire("engine.step")
                outputs = await loop.run_in_executor(None, self.core.step)
            except Exception as exc:
                logger.exception("engine step failed; failing all in-flight streams")
                flight = getattr(self.core, "flight", None)
                if flight is not None:
                    try:
                        from dynamo_tpu.observability.flight import CRASH

                        flight.record(
                            CRASH, where="engine_loop",
                            error=type(exc).__name__, detail=str(exc)[:500],
                            streams=len(self._streams),
                        )
                        path = flight.dump_jsonl(reason="engine_step_failure")
                        logger.error("flight recorder dumped to %s", path)
                    except Exception:
                        logger.exception("flight recorder dump failed")
                # core.step's own except already captured a bundle for a
                # genuine step crash; the capture cooldown folds this
                # loop-level one into it, so pre-step injected faults
                # (FAULTS "engine.step") still produce exactly one bundle.
                incidents = getattr(self.core, "incidents", None)
                if incidents is not None:
                    incidents.capture("crash", {
                        "error": type(exc).__name__, "detail": str(exc)[:500],
                        "where": "engine_loop", "streams": len(self._streams),
                    })
                self._fail_all_streams()
                continue
            self._route(outputs)
            # Tier write-through happens after outputs are routed, so token
            # delivery latency never waits on device->host offload copies.
            if self.core.pending_offloads:
                try:
                    await loop.run_in_executor(None, self.core.flush_offloads)
                except Exception:
                    logger.exception("tier offload flush failed (non-fatal)")

    def _notify_streams_failed(self) -> None:
        from dynamo_tpu.protocols.common import FinishReason

        for q in self._streams.values():
            q.put_nowait(EngineOutput(token_ids=[], finish_reason=FinishReason.ERROR))
            q.put_nowait(_SENTINEL)
        self._streams.clear()

    def _fail_all_streams(self) -> None:
        self._notify_streams_failed()
        # Engine state may be inconsistent after a failed step: drop all work,
        # releasing every sequence's pages back to the allocator.
        self.core.abort_all()

    def _route(self, outputs: list[tuple[Sequence, EngineOutput]]) -> None:
        for seq, out in outputs:
            q = self._streams.get(seq.seq_id)
            if q is None:
                continue
            q.put_nowait(out)
            if out.finish_reason is not None:
                q.put_nowait(_SENTINEL)
                del self._streams[seq.seq_id]

    # -- AsyncEngine -------------------------------------------------------

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        if isinstance(request, dict):
            request = PreprocessedRequest.from_dict(request)
        if self._closed:
            # A dead engine must refuse loudly (the stream error feeds the
            # client's inhibit list), not queue into a loop that never runs.
            raise RuntimeError("engine service is closed")
        if self._draining:
            # Draining refuses the same way: the client breaker routes the
            # request to a replica while in-flight streams finish here.
            raise RuntimeError("engine service is draining")
        if request.annotations.get("embed"):
            # Embedding requests bypass the scheduler: the cache-free encoder
            # shares nothing with the paged decode state (runner.embed). The
            # request's whole input batch runs as ONE device dispatch; one
            # output per input streams back, the last carrying the finish.
            from dynamo_tpu.protocols.common import FinishReason

            inputs = request.annotations.get("embed_inputs") or [list(request.token_ids)]
            vecs = await asyncio.get_running_loop().run_in_executor(
                None, self.core.runner.embed, [list(ids) for ids in inputs]
            )
            for i, ids in enumerate(inputs):
                last = i == len(inputs) - 1
                yield EngineOutput(
                    token_ids=[], finish_reason=FinishReason.STOP if last else None,
                    prompt_tokens=len(ids), cached_tokens=0,
                    embedding=[float(x) for x in vecs[i]],
                ).to_dict()
            return
        await self.start()
        out_q: asyncio.Queue = asyncio.Queue()
        await self._intake.put((request, context, out_q, time.perf_counter()))
        self._wake.set()
        if self._closed:
            # close() may have run between the check above and the put: its
            # intake drain might have missed this entry, so unblock the
            # consumer directly (duplicate ERROR items are harmless).
            from dynamo_tpu.protocols.common import FinishReason

            out_q.put_nowait(EngineOutput(token_ids=[], finish_reason=FinishReason.ERROR))
            out_q.put_nowait(_SENTINEL)
        finished = False
        from dynamo_tpu.tracing import Span, record_span, trace_of

        span = Span(
            "engine_request",
            trace=trace_of(context),
            request_id=request.request_id,
            prompt_tokens=len(request.token_ids),
        )
        span.__enter__()
        tokens_out = 0
        saw_finish = False
        try:
            while True:
                item = await out_q.get()
                if item is _SENTINEL:
                    finished = True
                    return
                if item.admission_wait_ms is not None:
                    # Arrival -> scheduler admission, measured by the core
                    # and attached to the first delta. As a span it joins
                    # the /debug/explain budget's pre-decode segments.
                    record_span(
                        "engine_admission_wait",
                        item.admission_wait_ms,
                        trace=span.context,
                        request_id=request.request_id,
                    )
                if tokens_out == 0 and item.token_ids:
                    # TTFT as seen at the engine boundary: submit -> first
                    # token out of the step loop. Child of engine_request.
                    record_span(
                        "engine_first_token",
                        (time.perf_counter() - span.t0) * 1e3,
                        trace=span.context,
                        request_id=request.request_id,
                    )
                tokens_out += len(item.token_ids)
                saw_finish = saw_finish or item.finish_reason is not None
                yield item.to_dict()
        finally:
            span.fields["output_tokens"] = tokens_out
            # A consumer may stop at the finish item without draining the
            # sentinel — that's still a completed request for the span.
            span.fields["finished"] = finished or saw_finish
            span.__exit__(None, None, None)
            if not finished:
                # Consumer walked away (generator closed / task cancelled):
                # stop the sequence so it doesn't decode to max_tokens.
                context.stop_generating()
                self._wake.set()

    # -- introspection -----------------------------------------------------

    def metrics(self) -> ForwardPassMetrics:
        return self.core.metrics()
