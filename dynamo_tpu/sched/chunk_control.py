"""SLO-driven chunk-budget control.

The mixed-step scheduler bounds decode stalls with ``chunk_prefill_tokens``:
every prefill chunk co-dispatched with decodes costs the decode stream about
one chunk of forward time. When the live ITL tail approaches the SLO budget,
the only knob that helps *now* (without dropping work) is a smaller chunk —
prefill throughput degrades gracefully while decode latency recovers.

This controller watches the wall time of decode-carrying steps (the engine
feeds every such step) and halves/doubles the effective chunk budget with
hysteresis:

- shrink when the windowed p99 step time >= ``shrink_at`` * ITL budget,
- relax when it <= ``relax_at`` * ITL budget,
- hold otherwise (the dead band between the thresholds), and
- after any change, hold for ``cooldown_steps`` observations with a cleared
  window, so a decision is always made on post-change samples and the
  budget cannot flap between two sizes on a boundary workload.

The budget never leaves [floor_tokens, base]; it never reaches 0, so the
engine's "is chunking on" checks are unaffected.
"""

from __future__ import annotations

from collections import deque


class ChunkBudgetController:
    def __init__(
        self,
        base_tokens: int,
        itl_budget_ms: float = 50.0,
        *,
        floor_tokens: int = 64,
        shrink_at: float = 0.9,
        relax_at: float = 0.5,
        cooldown_steps: int = 8,
        window: int = 128,
        min_samples: int = 8,
    ) -> None:
        if base_tokens <= 0:
            raise ValueError("chunk controller needs chunked prefill (base_tokens > 0)")
        self.base = int(base_tokens)
        self.floor = max(1, min(int(floor_tokens), self.base))
        self.itl_budget_ms = float(itl_budget_ms)
        self.shrink_at = float(shrink_at)
        self.relax_at = float(relax_at)
        self.cooldown_steps = int(cooldown_steps)
        self.min_samples = int(min_samples)
        self.current = self.base
        self.shrinks = 0
        self.relaxes = 0
        self._gaps: deque[float] = deque(maxlen=window)
        self._cooldown = 0

    def budget(self) -> int:
        return self.current

    def tail_ms(self) -> float:
        """Windowed p99 of observed decode-step wall times (0 if empty)."""
        if not self._gaps:
            return 0.0
        s = sorted(self._gaps)
        return s[min(len(s) - 1, int(0.99 * (len(s) - 1) + 0.999))]

    def observe(self, step_wall_ms: float) -> None:
        """Feed the wall time of one decode-carrying engine step."""
        self._gaps.append(max(0.0, float(step_wall_ms)))
        if self._cooldown > 0:
            self._cooldown -= 1
            return
        if len(self._gaps) < self.min_samples:
            return
        p99 = self.tail_ms()
        if p99 >= self.shrink_at * self.itl_budget_ms and self.current > self.floor:
            self.current = max(self.floor, self.current // 2)
            self.shrinks += 1
            self._after_change()
        elif p99 <= self.relax_at * self.itl_budget_ms and self.current < self.base:
            self.current = min(self.base, self.current * 2)
            self.relaxes += 1
            self._after_change()

    def _after_change(self) -> None:
        # Decide the next move on samples taken at the new budget only.
        self._gaps.clear()
        self._cooldown = self.cooldown_steps
