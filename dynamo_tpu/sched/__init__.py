"""SLO-native admission control plane.

Converts the measurement planes (goodput accounting, profiler latency
surfaces, federated queue-depth gauges) into *control*:

- :class:`AdmissionController` — EDF-over-predicted-TTFT ordering of the
  engine's waiting queue plus per-tenant quota gating (``admission.py``).
- :class:`TenantRegistry` / :class:`TenantQuota` — token-bucket rate and
  in-flight caps keyed by the ``x-dynamo-tenant`` header (``tenants.py``).
- :class:`TtftPredictor` — profile-surface TTFT prediction with an
  online-corrected fallback (``predictor.py``).
- :class:`ChunkBudgetController` — shrinks/relaxes the mixed-step
  scheduler's ``chunk_prefill_tokens`` against the live ITL tail
  (``chunk_control.py``).

Master toggle: ``DYN_SLO_SCHED`` (default off — the engine's FIFO intake is
bit-identical to the pre-sched scheduler). Knobs: ``DYN_SLO_SCHED_*`` and
``DYN_TENANT_*`` (config.SloSchedSettings / TenantSettings). The router's
attainment-aware cost term is armed by the same toggle
(:func:`configure_attainment`).
"""

from __future__ import annotations

import logging
import os

from dynamo_tpu.sched.admission import AdmissionConfig, AdmissionController
from dynamo_tpu.sched.chunk_control import ChunkBudgetController
from dynamo_tpu.sched.predictor import TtftPredictor
from dynamo_tpu.sched.tenants import DEFAULT_TENANT, TenantQuota, TenantRegistry

logger = logging.getLogger(__name__)


def slo_sched_enabled(env=None) -> bool:
    """The master toggle: ``DYN_SLO_SCHED`` truthy."""
    from dynamo_tpu.config import env_flag

    return env_flag(os.environ if env is None else env, "DYN_SLO_SCHED", False)


def _load_profile(path: str):
    from dynamo_tpu.planner.core import WorkerProfile

    try:
        with open(path) as f:
            return WorkerProfile.from_json(f.read())
    except (OSError, ValueError) as exc:
        logger.warning("DYN_SLO_SCHED_PROFILE %s unusable (%s); using fallback predictor", path, exc)
        return None


def build_admission_controller(
    *, settings=None, tenant_settings=None, profile=None
) -> AdmissionController:
    """Assemble an AdmissionController from the config cascade
    (``[slo_sched]``/``[tenant]`` sections, ``DYN_SLO_SCHED_*`` /
    ``DYN_TENANT_*`` env). Explicit arguments override the cascade."""
    from dynamo_tpu.config import load_slo_sched_settings, load_tenant_settings

    s = settings or load_slo_sched_settings()
    ts = tenant_settings or load_tenant_settings()
    if profile is None and s.profile:
        profile = _load_profile(s.profile)
    return AdmissionController(
        AdmissionConfig(ttft_budget_s=s.ttft_budget_ms / 1e3, tier_stretch=s.tier_stretch),
        predictor=TtftPredictor(profile),
        tenants=TenantRegistry.from_settings(ts),
    )


def build_chunk_controller(base_tokens: int, *, settings=None, slo=None) -> ChunkBudgetController:
    """Assemble the ITL-driven chunk-budget controller: the SLO section
    supplies the ITL budget, the slo_sched section the hysteresis knobs."""
    from dynamo_tpu.config import load_slo_sched_settings, load_slo_settings

    s = settings or load_slo_sched_settings()
    slo = slo or load_slo_settings()
    return ChunkBudgetController(
        base_tokens,
        itl_budget_ms=slo.itl_p99_ms,
        floor_tokens=s.chunk_floor_tokens,
        shrink_at=s.chunk_shrink_at,
        relax_at=s.chunk_relax_at,
        cooldown_steps=s.chunk_cooldown_steps,
    )


def cache_aware_enabled(env=None) -> bool:
    """``DYN_CACHE_AWARE`` truthy: residual-cost admission pricing,
    cache-aware router cost, and (implicitly) async tier onboarding."""
    from dynamo_tpu.config import env_flag

    return env_flag(os.environ if env is None else env, "DYN_CACHE_AWARE", False)


def configure_cache_aware(config, env=None, *, block_tokens=None, profile=None) -> None:
    """Arm a router ``SchedulerConfig``'s cache-aware cost term from the
    environment; a no-op unless ``DYN_CACHE_AWARE`` is on (same discipline
    as :func:`configure_attainment` — off means bit-identical costs).
    ``block_tokens`` lets the caller pass the deployment's real KV block
    size so predicted residual-prefill tokens are scaled correctly.

    The rate that converts residual prefill tokens into predicted seconds
    comes from the worker's *profiled* prefill throughput when one is
    available (``profile`` argument, else the ``DYN_SLO_SCHED_PROFILE``
    surface) — the 20k-tokens/s settings default is a guess that can skew
    placement by an order of magnitude on hardware it wasn't measured on.
    An explicit ``DYN_CACHE_AWARE_RATE_TOKENS_PER_S`` still wins: an
    operator override outranks a profile."""
    if not cache_aware_enabled(env):
        return
    from dynamo_tpu.config import load_cache_aware_settings, load_slo_sched_settings

    e = os.environ if env is None else env
    s = load_cache_aware_settings(env=env) if env is not None else load_cache_aware_settings()
    config.cache_aware_weight = s.weight
    config.cache_max_staleness_s = s.max_staleness_s
    rate = s.rate_tokens_per_s
    if "DYN_CACHE_AWARE_RATE_TOKENS_PER_S" not in e:
        if profile is None:
            # configure_attainment may already have armed the config with
            # the DYN_SLO_SCHED_PROFILE surface; reuse it before re-reading.
            profile = getattr(config, "profile", None)
        if profile is None:
            ss = load_slo_sched_settings(env=env) if env is not None else load_slo_sched_settings()
            if ss.profile:
                profile = _load_profile(ss.profile)
        if profile is not None and getattr(profile, "prefill_tokens_per_sec", 0.0) > 0.0:
            rate = float(profile.prefill_tokens_per_sec)
    config.cache_rate_tokens_per_s = rate
    if block_tokens:
        config.cache_block_tokens = int(block_tokens)


def configure_attainment(config, env=None) -> None:
    """Arm a router ``SchedulerConfig``'s attainment cost term from the
    environment; a no-op unless ``DYN_SLO_SCHED`` is on. Mutates in place
    so callers that built their own config keep full control."""
    if not slo_sched_enabled(env):
        return
    from dynamo_tpu.config import load_slo_sched_settings

    s = load_slo_sched_settings(env=env) if env is not None else load_slo_sched_settings()
    config.attainment_weight = s.attainment_weight
    config.ttft_slo_s = s.ttft_budget_ms / 1e3
    if config.profile is None and s.profile:
        config.profile = _load_profile(s.profile)


__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "ChunkBudgetController",
    "DEFAULT_TENANT",
    "TenantQuota",
    "TenantRegistry",
    "TtftPredictor",
    "build_admission_controller",
    "build_chunk_controller",
    "cache_aware_enabled",
    "configure_attainment",
    "configure_cache_aware",
    "slo_sched_enabled",
]
