"""EDF admission control over predicted TTFT.

Replaces the engine's FIFO intake (behind ``DYN_SLO_SCHED``): each step,
``prepare()`` reorders the waiting queue by *deadline slack* —

    slack = (arrival + budget * stretch^tier) - (now + predicted_ttft)

— least slack first, and gates the head at the tenant quotas. Throttled
requests sink behind admissible ones but keep their EDF order among
themselves, so a released quota resumes in deadline order, and a stretched
tier's deadline still arrives eventually: priority tiers relax, they never
starve (batch-tier aging is the anti-starvation mechanism, Llumnix-style
priority isolation without a separate queue per tier).

The controller is policy only — it never allocates pages or touches runner
state. The engine's budget/page logic runs unchanged on the reordered
queue, which is what keeps ``DYN_SLO_SCHED=0`` bit-identical to the legacy
scheduler: with no controller attached the queue is never reordered.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass

from dynamo_tpu.sched.predictor import TtftPredictor
from dynamo_tpu.sched.tenants import DEFAULT_TENANT, TenantRegistry


@dataclass
class AdmissionConfig:
    ttft_budget_s: float = 0.5  # tier-0 deadline budget (the TTFT SLO)
    tier_stretch: float = 2.0  # deadline budget multiplier per priority tier
    max_tier: int = 3  # priorities clamp into [0, max_tier]


class AdmissionController:
    """EDF-over-predicted-TTFT ordering + tenant quota gating."""

    def __init__(
        self,
        config: AdmissionConfig | None = None,
        *,
        predictor: TtftPredictor | None = None,
        tenants: TenantRegistry | None = None,
        clock=time.monotonic,
    ) -> None:
        self.config = config or AdmissionConfig()
        self.predictor = predictor or TtftPredictor()
        self.tenants = tenants or TenantRegistry()
        self._clock = clock
        # seq_id -> (tenant, charged_tokens): live quota charges.
        self._charges: dict[int, tuple[str, int]] = {}
        self.deadline_misses = 0  # admitted after their deadline had passed
        self.admitted_total = 0
        self.throttle_events = 0
        self.last_slack_ms = 0.0  # min slack across waiting at the last prepare
        # Residual-cost pricing (DYN_CACHE_AWARE): the engine wires a
        # callable ``seq -> cached KV tokens`` (resident G1 match + local
        # tier probe). With it, prediction and quota charges price a request
        # by its *uncached* prefill tokens — a 95%-cached 3000-token prompt
        # stops costing the same as a cold one. None keeps the cache-blind
        # behaviour bit-identical.
        self.cached_tokens_fn = None

    def _cached_tokens(self, seq) -> int:
        """Admission-time cached-token estimate for ``seq`` (0 without a
        pricing hook). Clamped so at least one token is always charged —
        the final token computes no matter how warm the prefix is."""
        fn = self.cached_tokens_fn
        if fn is None:
            return 0
        try:
            est = int(fn(seq))
        except Exception:
            return 0  # estimate failure degrades to cache-blind pricing
        return max(0, min(est, len(seq.tokens) - 1))

    # -- identity ----------------------------------------------------------

    def tenant_of(self, seq) -> str:
        return getattr(seq.request, "tenant_id", None) or DEFAULT_TENANT

    def tier_of(self, seq) -> int:
        prio = int(getattr(seq.request, "priority", 0) or 0)
        return min(max(prio, 0), self.config.max_tier)

    def deadline(self, seq) -> float:
        budget = self.config.ttft_budget_s * self.config.tier_stretch ** self.tier_of(seq)
        return seq.arrival_time + budget

    # -- scheduling --------------------------------------------------------

    def prepare(self, waiting: deque, *, running: int, slots: int, now: float | None = None) -> int:
        """Reorder ``waiting`` in place (EDF slack order, quota-throttled
        requests last) and return how many head entries are admissible under
        the tenant quotas right now."""
        if not waiting:
            self.last_slack_ms = 0.0
            return 0
        now = self._clock() if now is None else now
        scored = []
        for seq in waiting:
            cached = self._cached_tokens(seq)
            pred = self.predictor.predict(
                queued_tokens=max(0, seq.prompt_remaining - cached),
                running=running,
                slots=slots,
            )
            seq.predicted_ttft_s = pred
            seq.predicted_at = now
            slack = self.deadline(seq) - (now + pred)
            scored.append((slack, seq.arrival_time, seq.seq_id, seq, cached))
        scored.sort(key=lambda t: (t[0], t[1], t[2]))
        self.last_slack_ms = scored[0][0] * 1e3
        admissible: list = []
        deferred: list = []
        planned_tokens: dict[str, float] = {}
        planned_inflight: dict[str, int] = {}
        for _, _, _, seq, cached in scored:
            if seq.seq_id in self._charges:
                # Preempted resume: charged at first admission, refunded only
                # at on_finish — the quota already accounts for the resources
                # it holds. Re-gating would count the request against itself
                # (a tenant whose sole live request exceeds its in-flight cap
                # could never resume: wedged forever).
                admissible.append(seq)
                continue
            tenant = self.tenant_of(seq)
            # Quota charge is the residual: cached blocks are a copy, not a
            # prefill, so the bucket pays only for compute the request will
            # actually demand (min 1 — the final token always computes).
            tokens = max(1, len(seq.tokens) - cached)
            if self.tenants.would_admit(
                tenant,
                tokens,
                planned_tokens=planned_tokens.get(tenant, 0.0),
                planned_inflight=planned_inflight.get(tenant, 0),
            ):
                planned_tokens[tenant] = planned_tokens.get(tenant, 0.0) + tokens
                planned_inflight[tenant] = planned_inflight.get(tenant, 0) + tokens
                admissible.append(seq)
            else:
                self.tenants.note_throttled(tenant)
                self.throttle_events += 1
                # Sticky marker for loss attribution: this request's eventual
                # pre-admission wait was (at least partly) the quota gate's
                # doing, not plain resource contention.
                seq.quota_deferred = True
                deferred.append(seq)
        waiting.clear()
        waiting.extend(admissible)
        waiting.extend(deferred)
        return len(admissible)

    # -- lifecycle hooks (engine calls these) ------------------------------

    def on_admit(self, seq, now: float | None = None) -> None:
        if seq.seq_id in self._charges:
            return  # preempted resume: quota already charged
        now = self._clock() if now is None else now
        tenant = self.tenant_of(seq)
        tokens = max(1, len(seq.tokens) - self._cached_tokens(seq))
        self.tenants.on_admit(tenant, tokens)
        self._charges[seq.seq_id] = (tenant, tokens)
        self.admitted_total += 1
        if now > self.deadline(seq):
            self.deadline_misses += 1

    def on_finish(self, seq) -> None:
        charge = self._charges.pop(seq.seq_id, None)
        if charge is not None:
            self.tenants.on_finish(*charge)

    def on_first_token(self, seq, now: float | None = None) -> None:
        """Close the prediction loop with the observed TTFT.

        ``predicted_ttft_s`` is the *remaining* TTFT estimated at the last
        ``prepare()``, so the observation must share that time origin —
        measuring from arrival would fold already-elapsed queue wait into
        the ratio and inflate the bias under load.
        """
        now = self._clock() if now is None else now
        origin = seq.predicted_at if seq.predicted_at is not None else seq.arrival_time
        self.predictor.observe(seq.predicted_ttft_s, now - origin)

    # -- introspection -----------------------------------------------------

    def queue_depth_by_tier(self, waiting) -> dict[int, int]:
        depth: dict[int, int] = {}
        for seq in waiting:
            tier = self.tier_of(seq)
            depth[tier] = depth.get(tier, 0) + 1
        return depth
