"""Per-tenant admission quotas: token bucket + in-flight cap.

A tenant is whatever the frontend put in ``x-dynamo-tenant`` (requests
without one share the ``default`` tenant). Two independent limits, both
optional (0 = unlimited):

- **Rate** — a token bucket refilled at ``rate_tokens_per_s`` with capacity
  ``burst_tokens``. Admission charges the request's prompt tokens; a prompt
  larger than the bucket capacity borrows (the bucket goes negative) so an
  oversized request is delayed, never wedged forever.
- **In-flight** — total prompt tokens of the tenant's live sequences. A
  tenant with nothing in flight always fits one request, so the cap can
  never deadlock a tenant outright.

The registry only *answers* and *accounts*; the admission controller decides
order. Throttle decisions are counted per tenant for the
``dynamo_tenant_throttled_total`` metric family.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass


@dataclass
class TenantQuota:
    rate_tokens_per_s: float = 0.0  # 0 = unlimited rate
    burst_tokens: float = 0.0  # bucket capacity; 0 -> 2s of rate
    max_inflight_tokens: int = 0  # 0 = unlimited in-flight
    weight: float = 1.0  # fair-share weight across tiers (informational)

    @property
    def capacity(self) -> float:
        if self.burst_tokens > 0:
            return self.burst_tokens
        return 2.0 * self.rate_tokens_per_s


DEFAULT_TENANT = "default"


class TenantRegistry:
    """Quota state per tenant; the default quota covers unknown tenants."""

    def __init__(self, default_quota: TenantQuota | None = None, *, clock=time.monotonic) -> None:
        self.default_quota = default_quota or TenantQuota()
        self._quotas: dict[str, TenantQuota] = {}
        self._buckets: dict[str, tuple[float, float]] = {}  # tenant -> (level, last_refill)
        self._inflight: dict[str, int] = {}
        self.throttled: dict[str, int] = {}  # cumulative throttle decisions
        self._clock = clock

    @classmethod
    def from_settings(cls, settings, *, clock=time.monotonic) -> "TenantRegistry":
        """Build from config.TenantSettings: the scalar fields set the
        default quota; ``quotas`` (JSON object keyed by tenant) overrides
        per tenant, e.g. ``{"heavy": {"rate_tokens_per_s": 1000}}``."""
        reg = cls(
            TenantQuota(
                rate_tokens_per_s=settings.rate_tokens_per_s,
                burst_tokens=settings.burst_tokens,
                max_inflight_tokens=settings.max_inflight_tokens,
            ),
            clock=clock,
        )
        if settings.quotas:
            for tenant, fields in json.loads(settings.quotas).items():
                base = reg.default_quota
                reg.configure(
                    tenant,
                    TenantQuota(
                        rate_tokens_per_s=float(fields.get("rate_tokens_per_s", base.rate_tokens_per_s)),
                        burst_tokens=float(fields.get("burst_tokens", base.burst_tokens)),
                        max_inflight_tokens=int(fields.get("max_inflight_tokens", base.max_inflight_tokens)),
                        weight=float(fields.get("weight", base.weight)),
                    ),
                )
        return reg

    def configure(self, tenant: str, quota: TenantQuota) -> None:
        self._quotas[tenant] = quota

    def quota(self, tenant: str) -> TenantQuota:
        return self._quotas.get(tenant, self.default_quota)

    def inflight(self, tenant: str) -> int:
        return self._inflight.get(tenant, 0)

    def _bucket_level(self, tenant: str, q: TenantQuota) -> float:
        now = self._clock()
        level, last = self._buckets.get(tenant, (q.capacity, now))
        level = min(q.capacity, level + (now - last) * q.rate_tokens_per_s)
        self._buckets[tenant] = (level, now)
        return level

    def would_admit(
        self, tenant: str, tokens: int, *, planned_tokens: float = 0.0, planned_inflight: int = 0
    ) -> bool:
        """Could ``tokens`` prompt tokens be admitted for ``tenant`` now?
        ``planned_*`` account for requests the caller already marked
        admissible in the same scheduling pass (charged only on admit)."""
        q = self.quota(tenant)
        if q.rate_tokens_per_s > 0:
            level = self._bucket_level(tenant, q) - planned_tokens
            # Borrow semantics: an oversized prompt only needs a full bucket.
            if level < min(float(tokens), q.capacity):
                return False
        if q.max_inflight_tokens > 0:
            live = self.inflight(tenant) + planned_inflight
            if live > 0 and live + tokens > q.max_inflight_tokens:
                return False
        return True

    def note_throttled(self, tenant: str) -> None:
        self.throttled[tenant] = self.throttled.get(tenant, 0) + 1

    def on_admit(self, tenant: str, tokens: int) -> None:
        q = self.quota(tenant)
        if q.rate_tokens_per_s > 0:
            level = self._bucket_level(tenant, q)
            self._buckets[tenant] = (level - tokens, self._buckets[tenant][1])
        self._inflight[tenant] = self.inflight(tenant) + tokens

    def on_finish(self, tenant: str, tokens: int) -> None:
        self._inflight[tenant] = max(0, self.inflight(tenant) - tokens)
