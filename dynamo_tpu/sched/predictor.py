"""Predicted TTFT for admission control.

The admission controller orders the waiting queue by deadline slack, which
needs a per-request TTFT estimate *before* the request runs. Two sources:

- **Profile surface** — a profiler-produced ``WorkerProfile`` interpolates
  TTFT at the current load fraction (``ttft_at``, tail percentile when the
  sweep recorded one), plus the request's own prefill service time from the
  profiled token rate. This is the same surface the SLA planner sizes with.
- **Online fallback** — with no profile loaded, the prediction is just the
  prompt's service time at an assumed prefill rate, multiplicatively
  corrected by an EWMA of observed/predicted TTFT ratios. The bias term also
  corrects a stale or wrong profile, so it always applies.

Predictions feed ordering decisions, not hard guarantees: a consistent 2x
bias shifts every slack equally and the EDF order survives it; the online
correction exists so *relative* errors across load levels shrink over time.
"""

from __future__ import annotations


class TtftPredictor:
    """Per-request TTFT estimate from a latency surface + live queue state."""

    def __init__(
        self,
        profile=None,  # dynamo_tpu.planner.core.WorkerProfile | None
        *,
        prefill_tokens_per_sec: float = 20000.0,
        pct: int = 99,
        correction_alpha: float = 0.2,
    ) -> None:
        self.profile = profile
        self.pct = pct
        self._fallback_rate = max(1.0, prefill_tokens_per_sec)
        self._alpha = correction_alpha
        # Multiplicative bias: EWMA of observed_ttft / predicted_ttft,
        # clamped so one outlier can't invert the queue order.
        self._bias = 1.0
        self.observations = 0

    @property
    def bias(self) -> float:
        return self._bias

    def predict(self, *, queued_tokens: int, running: int, slots: int) -> float:
        """Seconds until first token for a request with ``queued_tokens``
        of uncomputed prompt, given ``running`` live sequences out of
        ``slots`` batch capacity."""
        load = min(1.0, running / max(slots, 1))
        if self.profile is not None:
            base = self.profile.ttft_at(load, pct=self.pct)
            rate = self.profile.prefill_tokens_per_sec or self._fallback_rate
        else:
            # No profile: queueing delay is folded into the bias term as
            # observations arrive (load shows up as larger observed/predicted
            # ratios, which inflate every later prediction).
            base = 0.0
            rate = self._fallback_rate
        service = queued_tokens / max(rate, 1.0)
        return self._bias * (base + service)

    def observe(self, predicted_s: float | None, actual_s: float) -> None:
        """Feed back an observed TTFT against the prediction made at its
        last EDF ordering (online correction)."""
        if not predicted_s or predicted_s <= 0.0 or actual_s <= 0.0:
            return
        ratio = min(8.0, max(0.125, actual_s / predicted_s))
        self._bias = min(16.0, max(0.0625, (1.0 - self._alpha) * self._bias + self._alpha * ratio))
        self.observations += 1
