"""dynamo-tpu: a TPU-native distributed LLM inference serving framework.

A ground-up re-design of the capabilities of NVIDIA Dynamo (the reference,
see SURVEY.md) for TPU hardware:

- OpenAI-compatible HTTP frontend with SSE streaming (``dynamo_tpu.frontend``).
- A distributed runtime with hierarchical addressing
  (Namespace -> Component -> Endpoint -> Instance), lease-based liveness and
  a two-plane transport: a broker-style request plane and a direct stream
  response plane (``dynamo_tpu.runtime``).
- KV-cache-aware request routing over a global radix index
  (``dynamo_tpu.router``).
- A multi-tier KV block manager: HBM (G1) -> host RAM (G2) -> disk (G3)
  (``dynamo_tpu.blocks``).
- A first-party JAX engine: continuous batching, paged KV cache, Pallas
  paged-attention kernels, pjit/GSPMD sharding over TPU meshes
  (``dynamo_tpu.engine``, ``dynamo_tpu.ops``, ``dynamo_tpu.models``,
  ``dynamo_tpu.parallel``).
- Disaggregated prefill/decode with KV migration over ICI/DCN
  (``dynamo_tpu.engine.disagg``).

Unlike the reference, which orchestrates third-party GPU engines, the engine
layer here is first-party JAX, so intra-model parallelism (TP/EP/SP) is
implemented natively.
"""

__version__ = "0.1.0"
