"""Encode worker: images -> vision-tower embeddings, served on the runtime.

The multimodal split (reference `examples/multimodal/components/
encode_worker.py:61-179`): a dedicated worker owns the vision tower; the
frontend's preprocessor sends it the request's images and receives the
projected embeddings, which then ride the preprocessed request to the
prefill engine (`llama.forward(mm_embeds=...)` substitutes them at the
image placeholder tokens).

Request: ``{"images_b64": [<base64 image bytes>, ...]}``
Response: ``{"embeds_b64": ..., "shape": [n, patches, D], "dtype": ...,
"patches_per_image": [...]}``
"""

from __future__ import annotations

import base64
import logging
from typing import Any, AsyncIterator

import numpy as np

from dynamo_tpu.models.vision import (
    TEST_TINY_VISION,
    VisionConfig,
    encode_image,
    init_vision_params,
    preprocess_image,
)
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.engine import AsyncEngine, Context

logger = logging.getLogger(__name__)

ENCODE_COMPONENT = "encode"
ENCODE_ENDPOINT = "encode"

# Vision towers paired with the LLM presets that accept their output width.
VISION_PRESETS: dict[str, VisionConfig] = {
    "test-tiny-vl": TEST_TINY_VISION,
}


class EncodeService(AsyncEngine[Any, dict]):
    """Serves the vision tower; one request = one batched image encode."""

    def __init__(self, cfg: VisionConfig, params=None) -> None:
        import functools

        import jax

        self.cfg = cfg
        self.params = params if params is not None else init_vision_params(cfg, 0)
        self._encode = jax.jit(functools.partial(encode_image, self.params, cfg))
        self.images_encoded = 0

    def _encode_batch(self, images: list[bytes]) -> np.ndarray:
        pixels = np.stack([preprocess_image(b, self.cfg) for b in images])
        # Pow2 batch bucketing: without it every new image count compiles a
        # fresh tower program (the runner's bucket lattice, applied here).
        n = len(images)
        bucket = 1 if n <= 1 else 1 << (n - 1).bit_length()
        if bucket != n:
            pixels = np.concatenate([pixels, np.zeros((bucket - n, *pixels.shape[1:]), pixels.dtype)])
        return np.asarray(self._encode(pixels), np.float32)[:n]

    async def close(self) -> None:  # lifecycle parity with engine services
        pass

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        import asyncio

        raw = [base64.b64decode(s) for s in request.get("images_b64", [])]
        if not raw:
            yield {"error": "no images"}
            return
        embeds = await asyncio.get_running_loop().run_in_executor(None, self._encode_batch, raw)
        self.images_encoded += len(raw)
        yield {
            "embeds_b64": base64.b64encode(np.ascontiguousarray(embeds).tobytes()).decode(),
            "shape": list(embeds.shape),
            "dtype": "float32",
            "patches_per_image": [self.cfg.num_patches] * len(raw),
        }


async def serve_encode_worker(
    runtime: DistributedRuntime,
    cfg: VisionConfig,
    *,
    params=None,
    namespace: str = "dynamo",
    lease=None,
) -> EncodeService:
    service = EncodeService(cfg, params)
    await runtime.namespace(namespace).component(ENCODE_COMPONENT).endpoint(ENCODE_ENDPOINT).serve(
        service, metadata={"patches": cfg.num_patches}, lease=lease
    )
    logger.info("encode worker up (%d patches -> %d dim)", cfg.num_patches, cfg.out_dim)
    return service


def make_encoder(runtime: DistributedRuntime, namespace: str = "dynamo"):
    """Frontend-side encoder callable: images (bytes) -> (embeds, patch counts).

    Returns an async fn the preprocessor calls; it routes to any live encode
    worker instance."""
    client = runtime.namespace(namespace).component(ENCODE_COMPONENT).endpoint(ENCODE_ENDPOINT).client()

    async def encode(images: list[bytes]) -> tuple[np.ndarray, list[int]]:
        req = {"images_b64": [base64.b64encode(b).decode() for b in images]}
        async for resp in client.generate(req, Context()):
            if "error" in resp:
                raise ValueError(f"encode worker: {resp['error']}")
            arr = np.frombuffer(
                base64.b64decode(resp["embeds_b64"]), dtype=np.dtype(resp["dtype"])
            ).reshape(resp["shape"])
            return arr, list(resp["patches_per_image"])
        raise RuntimeError("encode worker returned no response")

    return encode
