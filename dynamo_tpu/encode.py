"""Encode worker: images -> vision-tower embeddings, served on the runtime.

The multimodal split (reference `examples/multimodal/components/
encode_worker.py:61-179`): a dedicated worker owns the vision tower; the
frontend's preprocessor sends it the request's images and receives the
projected embeddings, which then ride the preprocessed request to the
prefill engine (`llama.forward(mm_embeds=...)` substitutes them at the
image placeholder tokens).

Request: ``{"images_b64": [<base64 image bytes>, ...]}``
Response: ``{"embeds_b64": ..., "shape": [n, patches, D], "dtype": ...,
"patches_per_image": [...]}``
"""

from __future__ import annotations

import base64
import logging
from typing import Any, AsyncIterator

import numpy as np

from dynamo_tpu.models.vision import (
    TEST_TINY_VISION,
    VisionConfig,
    encode_image,
    init_vision_params,
    preprocess_image,
)
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.engine import AsyncEngine, Context

logger = logging.getLogger(__name__)

ENCODE_COMPONENT = "encode"
ENCODE_ENDPOINT = "encode"

# Vision towers paired with the LLM presets that accept their output width.
VISION_PRESETS: dict[str, VisionConfig] = {
    "test-tiny-vl": TEST_TINY_VISION,
}


class EncodeService(AsyncEngine[Any, dict]):
    """Serves the vision tower; one request = one batched image encode.

    Two tower flavors share the service: fixed-geometry CLIP/LLaVA towers
    (`models/vision.VisionConfig` — batched encode, constant patch count)
    and native-resolution Qwen2-VL towers
    (`models/qwen2_vl.Qwen2VLVisionConfig` — per-image grids; the response
    carries ``grids`` so the engine can build M-RoPE positions)."""

    def __init__(self, cfg, params=None) -> None:
        import functools

        import jax

        from dynamo_tpu.models.qwen2_vl import (
            Qwen2VLVisionConfig,
            init_qwen2vl_vision_params,
        )

        import os

        self.cfg = cfg
        self.is_qwen2vl = isinstance(cfg, Qwen2VLVisionConfig)
        # Video sampling: DYNAMO_VIDEO_FRAMES frames per clip, clamped for
        # fixed-geometry towers so frames * num_patches stays within
        # DYNAMO_VIDEO_EMBED_BUDGET LLM tokens (an unclamped 8-frame default
        # at LLaVA's 576 patches/frame would exceed typical contexts and
        # reject every video request).
        self.video_frames = int(os.environ.get("DYNAMO_VIDEO_FRAMES", "8"))
        self.video_embed_budget = int(os.environ.get("DYNAMO_VIDEO_EMBED_BUDGET", "2048"))
        if self.is_qwen2vl:
            self.params = params if params is not None else init_qwen2vl_vision_params(cfg, 0)
            # Per-grid compiled programs, LRU-bounded: aspect-preserving
            # resize means arbitrary client images produce many distinct
            # grids, and each compile's executable is retained by jit.
            # Params are a traced ARGUMENT (not a closure constant), so
            # executables don't each embed a copy of the tower weights.
            self._encode_by_grid: dict = {}
            self._grid_cache_cap = 32
        else:
            self.params = params if params is not None else init_vision_params(cfg, 0)
            self._encode = jax.jit(functools.partial(encode_image, self.params, cfg))
        self.images_encoded = 0

    def _encode_batch(self, media: list[tuple[str, bytes]]) -> tuple[np.ndarray, list[int], list | None]:
        """``media``: (kind, bytes) with kind "image" | "video", in prompt
        order. -> (flattened embeds [total, D], per-item LLM token counts,
        per-item grids or None)."""
        if self.is_qwen2vl:
            return self._encode_qwen2vl(media)
        from dynamo_tpu.models.vision import preprocess_video

        # Fixed-geometry tower: videos become frame stacks through the same
        # tower; an item's embedding rows = frames * num_patches (reference
        # video_prefill recipe). Frames and stills share one batched encode.
        nf = max(1, min(self.video_frames,
                        self.video_embed_budget // max(self.cfg.num_patches, 1)))
        pixels_list, frames_per_item = [], []
        for kind, data in media:
            if kind == "video":
                stack = preprocess_video(data, self.cfg, num_frames=nf)
                pixels_list.extend(stack)
                frames_per_item.append(stack.shape[0])
            else:
                pixels_list.append(preprocess_image(data, self.cfg))
                frames_per_item.append(1)
        pixels = np.stack(pixels_list)
        # Pow2 batch bucketing: without it every new frame count compiles a
        # fresh tower program (the runner's bucket lattice, applied here).
        n = pixels.shape[0]
        bucket = 1 if n <= 1 else 1 << (n - 1).bit_length()
        if bucket != n:
            pixels = np.concatenate([pixels, np.zeros((bucket - n, *pixels.shape[1:]), pixels.dtype)])
        embeds = np.asarray(self._encode(pixels), np.float32)[:n]
        counts = [f * self.cfg.num_patches for f in frames_per_item]
        return embeds.reshape(-1, embeds.shape[-1]), counts, None

    def _encode_qwen2vl(self, media: list[tuple[str, bytes]]) -> tuple[np.ndarray, list[int], list]:
        import jax

        from dynamo_tpu.models.qwen2_vl import (
            encode_qwen2vl,
            preprocess_qwen2vl,
            preprocess_qwen2vl_video,
        )

        outs, counts, grids = [], [], []
        for kind, data in media:
            if kind == "video":
                patches, grid = preprocess_qwen2vl_video(
                    data, self.cfg, num_frames=self.video_frames
                )
                per_group = grid[1] * grid[2] // self.cfg.spatial_merge_size**2
                if grid[0] * per_group > self.video_embed_budget:
                    # Native resolution can yield ~1k LLM tokens per temporal
                    # group: first drop frames; if ONE group still exceeds
                    # the budget, downscale spatially via max_pixels (each
                    # merged token covers (patch*merge)^2 pixels) so the
                    # budget actually holds.
                    import dataclasses

                    cfg = self.cfg
                    groups = max(1, self.video_embed_budget // max(per_group, 1))
                    if per_group > self.video_embed_budget:
                        px_per_tok = (cfg.patch_size * cfg.spatial_merge_size) ** 2
                        cfg = dataclasses.replace(
                            cfg, max_pixels=self.video_embed_budget * px_per_tok
                        )
                    patches, grid = preprocess_qwen2vl_video(
                        data, cfg, num_frames=groups * cfg.temporal_patch_size,
                    )
            else:
                patches, grid = preprocess_qwen2vl(data, self.cfg)
            fn = self._encode_by_grid.pop(grid, None)
            if fn is None:  # one compiled program per media geometry
                fn = jax.jit(
                    lambda p, x, _cfg=self.cfg, _g=grid: encode_qwen2vl(p, _cfg, x, _g)
                )
                if len(self._encode_by_grid) >= self._grid_cache_cap:
                    evicted = next(iter(self._encode_by_grid))
                    del self._encode_by_grid[evicted]
            self._encode_by_grid[grid] = fn  # (re)insert at LRU tail
            out = np.asarray(fn(self.params, patches), np.float32)
            outs.append(out)
            counts.append(out.shape[0])
            grids.append(list(grid))
        return np.concatenate(outs, axis=0), counts, grids

    async def close(self) -> None:  # lifecycle parity with engine services
        pass

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        import asyncio

        media = [("image", base64.b64decode(s)) for s in request.get("images_b64", [])]
        media += [
            (m.get("kind", "image"), base64.b64decode(m["b64"]))
            for m in request.get("media", [])
        ]
        if not media:
            yield {"error": "no media"}
            return
        embeds, counts, grids = await asyncio.get_running_loop().run_in_executor(
            None, self._encode_batch, media
        )
        self.images_encoded += len(media)
        resp = {
            "embeds_b64": base64.b64encode(np.ascontiguousarray(embeds).tobytes()).decode(),
            "shape": list(embeds.shape),
            "dtype": "float32",
            "patches_per_image": counts,
        }
        if grids is not None:
            resp["grids"] = grids
        yield resp


async def serve_encode_worker(
    runtime: DistributedRuntime,
    cfg: VisionConfig,
    *,
    params=None,
    namespace: str = "dynamo",
    lease=None,
) -> EncodeService:
    service = EncodeService(cfg, params)
    patches = getattr(cfg, "num_patches", "native")  # Qwen2-VL: per-image
    await runtime.namespace(namespace).component(ENCODE_COMPONENT).endpoint(ENCODE_ENDPOINT).serve(
        service, metadata={"patches": patches}, lease=lease
    )
    logger.info("encode worker up (%s patches -> %d dim)", patches, cfg.out_dim)
    return service


def make_encoder(runtime: DistributedRuntime, namespace: str = "dynamo"):
    """Frontend-side encoder callable:
    images (bytes) -> (embeds, patch counts, per-image grids | None).

    Returns an async fn the preprocessor calls; it routes to any live encode
    worker instance."""
    client = runtime.namespace(namespace).component(ENCODE_COMPONENT).endpoint(ENCODE_ENDPOINT).client()

    async def encode(media) -> tuple[np.ndarray, list[int], list | None]:
        """``media``: list of bytes (images, back-compat) or of
        (kind, bytes) tuples with kind "image" | "video"."""
        norm = [("image", m) if isinstance(m, bytes) else m for m in media]
        if all(kind == "image" for kind, _ in norm):
            # Image-only requests ride the original wire key so a new
            # frontend keeps working against a not-yet-upgraded worker.
            req = {"images_b64": [base64.b64encode(b).decode() for _k, b in norm]}
        else:
            req = {"media": [
                {"kind": kind, "b64": base64.b64encode(b).decode()} for kind, b in norm
            ]}
        async for resp in client.generate(req, Context()):
            if "error" in resp:
                raise ValueError(f"encode worker: {resp['error']}")
            arr = np.frombuffer(
                base64.b64decode(resp["embeds_b64"]), dtype=np.dtype(resp["dtype"])
            ).reshape(resp["shape"])
            return arr, list(resp["patches_per_image"]), resp.get("grids")
        raise RuntimeError("encode worker returned no response")

    return encode
