"""Vision tower: ViT image encoder + projector to the LLM's hidden space.

Multimodal serving splits into an *encode* stage (this module, run by
encode workers) and the LLM prefill that consumes the resulting embeddings
in place of image placeholder tokens (`llama.forward(mm_embeds=...)`).

Parity: reference multimodal examples
(`examples/multimodal/components/encode_worker.py:61-179`) where a separate
worker runs the HF vision tower and hands embeddings to prefill over NIXL;
here the tower is first-party JAX (patchify -> pre-LN ViT -> 2-layer MLP
projector, the LLaVA recipe) and embeddings ride the runtime's transfer
plane.

TPU notes: patchify is one conv-as-matmul reshape (MXU-friendly), attention
is dense over a few hundred patch tokens, everything static-shaped; one
image = one [num_patches, llm_hidden] bf16/f32 block.
"""

from __future__ import annotations

import dataclasses
import io

import jax
import jax.numpy as jnp
import numpy as np

Params = dict


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    image_size: int = 224
    patch_size: int = 16
    hidden_size: int = 256
    num_layers: int = 4
    num_heads: int = 4
    mlp_ratio: int = 4
    out_dim: int = 2048  # the LLM's hidden size
    # CLIP/LLaVA tower semantics (all off for the plain first-party tower):
    cls_token: bool = False  # learned class embedding prepended (CLIP)
    pre_ln: bool = False  # CLIP pre_layrnorm after embeddings
    bias: bool = False  # attention/MLP/projector biases present
    act: str = "gelu"  # "gelu" | "quick_gelu" (CLIP)
    # Which encoder output feeds the projector: 0 = all layers + final LN
    # (first-party tower); -2 = skip the LAST layer, no post-LN, drop the
    # CLS row — HF LLaVA's vision_feature_layer=-2 / "default" selection.
    feature_layer: int = 0
    mlp_dim: int = 0  # explicit intermediate size (0 = hidden * mlp_ratio)
    ln_eps: float = 1e-6  # CLIP uses 1e-5
    # Per-channel pixel normalization (defaults = the /127.5-1 recipe).
    image_mean: tuple = (0.5, 0.5, 0.5)
    image_std: tuple = (0.5, 0.5, 0.5)

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def num_tokens(self) -> int:
        return self.num_patches + (1 if self.cls_token else 0)

    @property
    def patch_dim(self) -> int:
        return 3 * self.patch_size * self.patch_size

    @property
    def mlp_hidden(self) -> int:
        return self.mlp_dim or self.hidden_size * self.mlp_ratio

    @classmethod
    def from_hf_llava(cls, config: dict) -> "VisionConfig":
        """HF ``LlavaConfig.vision_config`` (CLIP tower) -> VisionConfig."""
        v = config["vision_config"]
        t = config["text_config"]
        fl = config.get("vision_feature_layer", -2)
        if fl not in (-1, -2):
            # A silently-mishandled selection corrupts the mm-embed splice;
            # fail at load, not per request.
            raise ValueError(
                f"unsupported vision_feature_layer {fl!r} (supported: -1, -2)"
            )
        if config.get("vision_feature_select_strategy", "default") != "default":
            raise ValueError("only vision_feature_select_strategy='default' supported")
        return cls(
            image_size=v.get("image_size", 336),
            patch_size=v.get("patch_size", 14),
            hidden_size=v["hidden_size"],
            num_layers=v["num_hidden_layers"],
            num_heads=v["num_attention_heads"],
            mlp_dim=v.get("intermediate_size", 0),
            out_dim=t["hidden_size"],
            cls_token=True, pre_ln=True, bias=True, act="quick_gelu",
            feature_layer=int(fl),
            ln_eps=float(v.get("layer_norm_eps", 1e-5)),
            # CLIP image processor statistics (openai/clip-vit defaults).
            image_mean=(0.48145466, 0.4578275, 0.40821073),
            image_std=(0.26862954, 0.26130258, 0.27577711),
        )


# A tiny tower matching the test-tiny-vl preset (out_dim = 64).
TEST_TINY_VISION = VisionConfig(
    image_size=32, patch_size=8, hidden_size=32, num_layers=2, num_heads=2, out_dim=64
)


def init_vision_params(cfg: VisionConfig, rng: jax.Array | int = 0) -> Params:
    if isinstance(rng, int):
        rng = jax.random.PRNGKey(rng)
    ks = jax.random.split(rng, 8)

    def w(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * (fan_in**-0.5))

    d, p = cfg.hidden_size, cfg.patch_dim
    mlp = cfg.mlp_hidden
    layer_keys = jax.random.split(ks[7], cfg.num_layers)

    def layer(key):
        lk = jax.random.split(key, 6)
        leaves = {
            "ln1": jnp.ones(d), "ln2": jnp.ones(d),
            "wqkv": w(lk[0], (d, 3 * d), d), "wo": w(lk[1], (d, d), d),
            "w1": w(lk[2], (d, mlp), d), "w2": w(lk[3], (mlp, d), mlp),
        }
        if cfg.bias:
            leaves.update({
                "ln1_b": jnp.zeros(d), "ln2_b": jnp.zeros(d),
                "bqkv": jnp.zeros(3 * d), "bo": jnp.zeros(d),
                "b1": jnp.zeros(mlp), "b2": jnp.zeros(d),
            })
        return leaves

    layers = jax.tree.map(lambda *xs: jnp.stack(xs), *[layer(k) for k in layer_keys])
    params = {
        "patch_embed": w(ks[0], (p, d), p),
        "pos_embed": w(ks[1], (cfg.num_tokens, d), d) * 0.02,
        "ln_f": jnp.ones(d),
        # LLaVA-style 2-layer MLP projector into the LLM hidden space.
        "proj1": w(ks[2], (d, cfg.out_dim), d),
        "proj2": w(ks[3], (cfg.out_dim, cfg.out_dim), cfg.out_dim),
        "layers": layers,
    }
    if cfg.cls_token:
        params["cls"] = w(ks[4], (d,), d)
    if cfg.pre_ln:
        params["pre_ln_g"] = jnp.ones(d)
        if cfg.bias:
            params["pre_ln_b"] = jnp.zeros(d)
    if cfg.bias:
        params["b_proj1"] = jnp.zeros(cfg.out_dim)
        params["b_proj2"] = jnp.zeros(cfg.out_dim)
        params["ln_f_b"] = jnp.zeros(d)
    return params


def _ln(x: jnp.ndarray, g: jnp.ndarray, eps: float = 1e-6, b: jnp.ndarray | None = None) -> jnp.ndarray:
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    y = (x - mu) * jax.lax.rsqrt(var + eps) * g
    return y if b is None else y + b


def encode_image(params: Params, cfg: VisionConfig, pixels: jnp.ndarray) -> jnp.ndarray:
    """[B, H, W, 3] float (normalized) -> [B, num_patches, out_dim].

    One forward serves both tower flavors: the first-party minimal ViT and
    the CLIP/LLaVA geometry (CLS token, pre-LN, biases, quick_gelu,
    vision_feature_layer=-2 selection) when the config flags say so — the
    flags mirror exactly what HF's CLIPVisionTransformer + LLaVA projector
    compute, so real LLaVA checkpoints reproduce HF logits
    (tests/test_golden_vision.py).
    """
    b = pixels.shape[0]
    g = cfg.image_size // cfg.patch_size
    # HF "gelu" is the exact erf form; jax.nn.gelu defaults to the tanh
    # approximation (~1e-3 divergence — enough to fail logit parity).
    exact_gelu = lambda v: jax.nn.gelu(v, approximate=False)  # noqa: E731
    act = exact_gelu if cfg.act == "gelu" else (lambda v: v * jax.nn.sigmoid(1.702 * v))
    # Patchify as one reshape + matmul (a conv with stride == kernel).
    x = pixels.reshape(b, g, cfg.patch_size, g, cfg.patch_size, 3)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, g * g, cfg.patch_dim)
    x = x @ params["patch_embed"]
    if cfg.cls_token:
        cls = jnp.broadcast_to(params["cls"], (b, 1, cfg.hidden_size))
        x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"]
    if cfg.pre_ln:
        x = _ln(x, params["pre_ln_g"], eps=cfg.ln_eps, b=params.get("pre_ln_b"))

    h = cfg.num_heads
    hd = cfg.hidden_size // h
    scale = hd**-0.5

    def layer_step(x, lp):
        y = _ln(x, lp["ln1"], eps=cfg.ln_eps, b=lp.get("ln1_b"))
        qkv = y @ lp["wqkv"]
        if "bqkv" in lp:
            qkv = qkv + lp["bqkv"]
        qkv = qkv.reshape(b, -1, 3, h, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        att = jax.nn.softmax(jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", att, v).reshape(b, -1, cfg.hidden_size)
        o = o @ lp["wo"]
        if "bo" in lp:
            o = o + lp["bo"]
        x = x + o
        y = _ln(x, lp["ln2"], eps=cfg.ln_eps, b=lp.get("ln2_b"))
        y = y @ lp["w1"]
        if "b1" in lp:
            y = y + lp["b1"]
        y = act(y) @ lp["w2"]
        if "b2" in lp:
            y = y + lp["b2"]
        return x + y, None

    # LLaVA feature selection: [-2] is the input to the LAST layer, so skip
    # that layer entirely (its output would be discarded) instead of running
    # it and stacking every per-layer hidden state.
    layer_tree = params["layers"]
    if cfg.feature_layer == -2:
        layer_tree = jax.tree.map(lambda a: a[:-1], layer_tree)
    x, _ = jax.lax.scan(layer_step, x, layer_tree)
    if cfg.feature_layer in (-1, -2):
        # No post-LN; CLS dropped ("default" select strategy).
        x = x[:, 1:] if cfg.cls_token else x
    else:
        x = _ln(x, params["ln_f"], eps=cfg.ln_eps, b=params.get("ln_f_b"))
    y = x @ params["proj1"]
    if "b_proj1" in params:
        y = y + params["b_proj1"]
    # The LLaVA projector uses plain (exact) GELU regardless of the tower act.
    y = jax.nn.gelu(y, approximate=False) @ params["proj2"]
    if "b_proj2" in params:
        y = y + params["b_proj2"]
    return y


def preprocess_image(data: bytes, cfg: VisionConfig) -> np.ndarray:
    """Decode + resize + normalize one image -> [H, W, 3] float32 using the
    tower's per-channel statistics.

    CLIP towers follow HF's CLIPImageProcessor geometry — shortest edge to
    image_size (bicubic), then CENTER CROP — so non-square photos produce
    the same pixel tensor HF would (a squash-resize diverges everywhere
    outside the center square). The plain tower keeps the original
    squash-resize (its own historical contract)."""
    from PIL import Image

    return preprocess_pil_image(Image.open(io.BytesIO(data)), cfg)


def preprocess_pil_image(img, cfg: VisionConfig) -> np.ndarray:
    """The resize/crop/normalize tail of :func:`preprocess_image`, for
    callers that already hold a PIL Image (video frame stacks)."""
    from PIL import Image

    img = img.convert("RGB")
    if cfg.cls_token:  # CLIP geometry
        w, h = img.size
        scale = cfg.image_size / min(w, h)
        img = img.resize((max(1, round(w * scale)), max(1, round(h * scale))), Image.BICUBIC)
        w, h = img.size
        left = (w - cfg.image_size) // 2
        top = (h - cfg.image_size) // 2
        img = img.crop((left, top, left + cfg.image_size, top + cfg.image_size))
    else:
        img = img.resize((cfg.image_size, cfg.image_size), Image.BILINEAR)
    arr = np.asarray(img, np.float32) / 255.0
    mean = np.asarray(cfg.image_mean, np.float32)
    std = np.asarray(cfg.image_std, np.float32)
    return (arr - mean) / std


def extract_frames(data: bytes, num_frames: int):
    """Uniformly sample up to ``num_frames`` frames from an animated image
    container (GIF/APNG/WebP — the formats PIL decodes without ffmpeg;
    zero-egress environments have no video codecs). Returns
    ``min(available, num_frames)`` PIL Images — a still image yields one.

    Only the sampled frames are decoded (seek, not full iteration): a long
    clip must not materialize thousands of RGB frames to pick 8.

    Parity: the reference's video workers sample frames with decord/ffmpeg
    before per-frame encoding (`examples/multimodal/components/
    video_encode_worker.py`); the sampling recipe (uniform over the clip)
    is the same."""
    import io as _io

    from PIL import Image

    img = Image.open(_io.BytesIO(data))
    total = getattr(img, "n_frames", 1)
    if total <= 0:
        raise ValueError("no decodable frames in video payload")
    idx = (np.linspace(0, total - 1, num_frames).round().astype(int)
           if total > num_frames else np.arange(total))
    out = []
    for i in idx:
        img.seek(int(i))
        out.append(img.copy().convert("RGB"))
    return out


def preprocess_video(data: bytes, cfg: VisionConfig, *, num_frames: int = 8) -> np.ndarray:
    """Video bytes -> [T, H, W, 3] float32 frame stack for fixed-geometry
    (CLIP/LLaVA) towers: each sampled frame goes through the tower's own
    image geometry; the encode worker encodes the stack as a frame batch
    and concatenates the embeddings (reference video_prefill recipe)."""
    return np.stack([
        preprocess_pil_image(f, cfg) for f in extract_frames(data, num_frames)
    ])


def decode_data_url(url: str) -> bytes:
    """``data:image/...;base64,...`` -> raw image bytes (no network egress)."""
    import base64

    if not url.startswith("data:"):
        raise ValueError("only data: image URLs are supported (no network egress)")
    _, _, payload = url.partition(",")
    return base64.b64decode(payload)
