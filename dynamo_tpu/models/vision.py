"""Vision tower: ViT image encoder + projector to the LLM's hidden space.

Multimodal serving splits into an *encode* stage (this module, run by
encode workers) and the LLM prefill that consumes the resulting embeddings
in place of image placeholder tokens (`llama.forward(mm_embeds=...)`).

Parity: reference multimodal examples
(`examples/multimodal/components/encode_worker.py:61-179`) where a separate
worker runs the HF vision tower and hands embeddings to prefill over NIXL;
here the tower is first-party JAX (patchify -> pre-LN ViT -> 2-layer MLP
projector, the LLaVA recipe) and embeddings ride the runtime's transfer
plane.

TPU notes: patchify is one conv-as-matmul reshape (MXU-friendly), attention
is dense over a few hundred patch tokens, everything static-shaped; one
image = one [num_patches, llm_hidden] bf16/f32 block.
"""

from __future__ import annotations

import dataclasses
import io

import jax
import jax.numpy as jnp
import numpy as np

Params = dict


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    image_size: int = 224
    patch_size: int = 16
    hidden_size: int = 256
    num_layers: int = 4
    num_heads: int = 4
    mlp_ratio: int = 4
    out_dim: int = 2048  # the LLM's hidden size

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return 3 * self.patch_size * self.patch_size


# A tiny tower matching the test-tiny-vl preset (out_dim = 64).
TEST_TINY_VISION = VisionConfig(
    image_size=32, patch_size=8, hidden_size=32, num_layers=2, num_heads=2, out_dim=64
)


def init_vision_params(cfg: VisionConfig, rng: jax.Array | int = 0) -> Params:
    if isinstance(rng, int):
        rng = jax.random.PRNGKey(rng)
    ks = jax.random.split(rng, 8)

    def w(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * (fan_in**-0.5))

    d, p = cfg.hidden_size, cfg.patch_dim
    mlp = cfg.hidden_size * cfg.mlp_ratio
    layer_keys = jax.random.split(ks[7], cfg.num_layers)

    def layer(key):
        lk = jax.random.split(key, 6)
        return {
            "ln1": jnp.ones(d), "ln2": jnp.ones(d),
            "wqkv": w(lk[0], (d, 3 * d), d), "wo": w(lk[1], (d, d), d),
            "w1": w(lk[2], (d, mlp), d), "w2": w(lk[3], (mlp, d), mlp),
        }

    layers = jax.tree.map(lambda *xs: jnp.stack(xs), *[layer(k) for k in layer_keys])
    return {
        "patch_embed": w(ks[0], (p, d), p),
        "pos_embed": w(ks[1], (cfg.num_patches, d), d) * 0.02,
        "ln_f": jnp.ones(d),
        # LLaVA-style 2-layer MLP projector into the LLM hidden space.
        "proj1": w(ks[2], (d, cfg.out_dim), d),
        "proj2": w(ks[3], (cfg.out_dim, cfg.out_dim), cfg.out_dim),
        "layers": layers,
    }


def _ln(x: jnp.ndarray, g: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g


def encode_image(params: Params, cfg: VisionConfig, pixels: jnp.ndarray) -> jnp.ndarray:
    """[B, H, W, 3] float in [-1, 1] -> [B, num_patches, out_dim]."""
    b = pixels.shape[0]
    g = cfg.image_size // cfg.patch_size
    # Patchify as one reshape + matmul (a conv with stride == kernel).
    x = pixels.reshape(b, g, cfg.patch_size, g, cfg.patch_size, 3)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, g * g, cfg.patch_dim)
    x = x @ params["patch_embed"] + params["pos_embed"]

    h = cfg.num_heads
    hd = cfg.hidden_size // h
    scale = hd**-0.5

    def layer_step(x, lp):
        y = _ln(x, lp["ln1"])
        qkv = (y @ lp["wqkv"]).reshape(b, -1, 3, h, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        att = jax.nn.softmax(jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", att, v).reshape(b, -1, cfg.hidden_size)
        x = x + o @ lp["wo"]
        y = _ln(x, lp["ln2"])
        x = x + jax.nn.gelu(y @ lp["w1"]) @ lp["w2"]
        return x, None

    x, _ = jax.lax.scan(layer_step, x, params["layers"])
    x = _ln(x, params["ln_f"])
    x = jax.nn.gelu(x @ params["proj1"]) @ params["proj2"]
    return x


def preprocess_image(data: bytes, cfg: VisionConfig) -> np.ndarray:
    """Decode + resize + normalize one image -> [H, W, 3] float32 in [-1, 1]."""
    from PIL import Image

    img = Image.open(io.BytesIO(data)).convert("RGB").resize(
        (cfg.image_size, cfg.image_size), Image.BILINEAR
    )
    arr = np.asarray(img, np.float32) / 127.5 - 1.0
    return arr


def decode_data_url(url: str) -> bytes:
    """``data:image/...;base64,...`` -> raw image bytes (no network egress)."""
    import base64

    if not url.startswith("data:"):
        raise ValueError("only data: image URLs are supported (no network egress)")
    _, _, payload = url.partition(",")
    return base64.b64decode(payload)
