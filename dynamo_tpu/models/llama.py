"""Llama-family decoder (Llama 3.x, DeepSeek-R1-Distill-Llama) — pure-functional JAX.

Design (TPU-first, not a torch translation):

- Parameters are a pytree with **layers stacked on a leading axis** and the
  forward pass is a ``lax.scan`` over layers. One layer gets traced/compiled
  regardless of depth — compile time is O(1) in ``num_layers`` (matters at
  70B/80-layer scale) and XLA schedules identical per-layer programs.
- The KV cache is **paged** ([L, num_pages, page_size, n_kv, head_dim],
  page-major — see ``ops/attention.py``) and flows through the scan carry;
  each layer reads its slice and writes back via dynamic index updates,
  which XLA aliases in place under buffer donation.
- One forward function serves prefill (T>1) and decode (T=1); queries attend
  to the paged cache, so chunked prefill and prefix reuse need no extra code
  path (see ``dynamo_tpu/ops/attention.py``).
- All matmuls are expressed so GSPMD can shard them from param/cache sharding
  annotations alone (no explicit collectives here; see ``dynamo_tpu/parallel``).

Replaces the model execution the reference delegates to vLLM/TRT-LLM
(SURVEY.md §2 parallelism table: TP/PP "engine-internal" — first-party here).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.ops.attention import paged_attention, write_kv
from dynamo_tpu.ops.norm import rms_norm
from dynamo_tpu.models.quant import maybe_dequant as _dq, quant_matmul as _qmm
from dynamo_tpu.ops.rope import apply_mrope, apply_rope, rope_attention_factor, rope_frequencies

Params = dict


def param_dtype(cfg: ModelConfig) -> jnp.dtype:
    return jnp.dtype(cfg.dtype)


def init_params(cfg: ModelConfig, rng: jax.Array | int = 0) -> Params:
    """Random-init parameters (tests / benchmarks without checkpoint download).

    With ``cfg.first_k_dense > 0`` (DeepSeek first_k_dense_replace) the
    pytree carries two stacked subtrees: ``dense_layers`` (the first k
    layers, dense MLP) and ``layers`` (the remaining MoE layers)."""
    if isinstance(rng, int):
        rng = jax.random.PRNGKey(rng)
    dt = param_dtype(cfg)
    keys = jax.random.split(rng, 12)
    d, q, kv, f = cfg.hidden_size, cfg.q_dim, cfg.kv_dim, cfg.intermediate_size

    def w(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * (fan_in**-0.5)).astype(dt)

    def layer_stack(l: int, moe: bool, key_salt: int) -> dict:
        ks = [jax.random.fold_in(k, key_salt) for k in keys]
        layers = {
            "attn_norm": jnp.ones((l, d), dt),
            "mlp_norm": jnp.ones((l, d), dt),
        }
        if cfg.qk_norm:
            qn = cfg.head_dim if cfg.qk_norm == "head" else cfg.q_dim
            kn = cfg.head_dim if cfg.qk_norm == "head" else cfg.kv_dim
            layers["q_norm"] = jnp.ones((l, qn), dt)
            layers["k_norm"] = jnp.ones((l, kn), dt)
        if cfg.attn_type == "mla":
            from dynamo_tpu.models.mla import init_mla_params

            layers.update(init_mla_params(cfg, ks[0], dt, l))
        else:
            layers.update(
                {
                    "wq": w(ks[0], (l, d, q), d),
                    "wk": w(ks[1], (l, d, kv), d),
                    "wv": w(ks[2], (l, d, kv), d),
                    "wo": w(ks[3], (l, q, d), q),
                }
            )
        if cfg.attention_bias:
            layers.update(
                {
                    "bq": jnp.zeros((l, q), dt),
                    "bk": jnp.zeros((l, kv), dt),
                    "bv": jnp.zeros((l, kv), dt),
                }
            )
        if moe:
            e, mf = cfg.num_experts, cfg.moe_intermediate_size
            layers.update(
                {
                    "router": w(ks[4], (l, d, e), d),
                    "w_gate": w(ks[5], (l, e, d, mf), d),
                    "w_up": w(ks[6], (l, e, d, mf), d),
                    "w_down": w(ks[7], (l, e, mf, d), mf),
                }
            )
            if cfg.moe_router_bias:
                layers["router_bias"] = jnp.zeros((l, e), jnp.float32)
            if cfg.shared_expert_size:
                fs = cfg.shared_expert_size
                layers.update(
                    {
                        "w_shared_gate": w(ks[10], (l, d, fs), d),
                        "w_shared_up": w(ks[11], (l, d, fs), d),
                        "w_shared_down": w(ks[9], (l, fs, d), fs),
                    }
                )
                if cfg.shared_expert_gated:
                    layers["shared_gate"] = w(ks[8], (l, d, 1), d)
        else:
            layers.update(
                {
                    "w_gate": w(ks[5], (l, d, f), d),
                    "w_up": w(ks[6], (l, d, f), d),
                    "w_down": w(ks[7], (l, f, d), f),
                }
            )
        return layers

    k_dense = cfg.first_k_dense if cfg.is_moe else 0
    params: Params = {
        "embed": w(keys[8], (cfg.vocab_size, d), d),
        "norm_f": jnp.ones((d,), dt),
        "layers": layer_stack(cfg.num_layers - k_dense, cfg.is_moe, 0),
    }
    if k_dense:
        params["dense_layers"] = layer_stack(k_dense, False, 1)
    if not cfg.tie_embeddings:
        params["lm_head"] = w(keys[9], (d, cfg.vocab_size), d)
    return params


def init_kv_cache(cfg: ModelConfig, num_pages: int, page_size: int, dtype: jnp.dtype | None = None):
    """Allocate the paged KV cache: two [L, num_pages, page_size, n_kv * hd] arrays.

    Page-major per layer with KV heads flattened into the trailing (lane)
    dimension — one page is a single contiguous ``ps x W`` slab covering all
    KV heads, the native layout of the Pallas decode kernel (one big DMA per
    page). Keeping W = n_kv * head_dim as the physical trailing dim makes the
    array's TPU tiling padding-free even at head_dim 64, and means the
    kernel, the write scatter, and the gather all address the cache without
    relayout copies. Ops that need per-head structure reshape *gathered*
    slices (fresh intermediates XLA can fuse), never the cache itself.
    """
    dt = dtype or param_dtype(cfg)
    if cfg.attn_type == "mla":
        # MLA: k_cache holds the per-token latents, v_cache the decoupled
        # rope keys (models/mla.py) — same paged geometry, ~7x fewer bytes.
        from dynamo_tpu.models.mla import mla_cache_widths

        wk, wv = mla_cache_widths(cfg)
        return (
            jnp.zeros((cfg.num_layers, num_pages, page_size, wk), dt),
            jnp.zeros((cfg.num_layers, num_pages, page_size, wv), dt),
        )
    shape = (cfg.num_layers, num_pages, page_size, cfg.num_kv_heads * cfg.head_dim)
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


def _mlp_dense(lp: Params, x: jnp.ndarray, act: str = "silu") -> jnp.ndarray:
    gp = _qmm(x, lp["w_gate"])
    # Gemma's GeGLU uses the tanh-approximate gelu (HF gelu_pytorch_tanh).
    gate = jax.nn.gelu(gp, approximate=True) if act == "gelu_tanh" else jax.nn.silu(gp)
    return _qmm(gate * _qmm(x, lp["w_up"]), lp["w_down"])


def _routing_kwargs(cfg: ModelConfig) -> dict:
    """Family router semantics for ``parallel/moe.route_tokens``."""
    return dict(
        scoring=cfg.moe_scoring,
        norm_topk=cfg.moe_norm_topk,
        scaling=cfg.moe_routed_scaling,
        n_group=cfg.moe_n_group,
        topk_group=cfg.moe_topk_group,
        # noaux_tc (V3) ranks groups by top-2 sum of biased scores;
        # group_limited_greedy (V2) by per-group max.
        group_score="top2sum" if cfg.moe_router_bias else "max",
    )


def _mlp_moe(lp: Params, x: jnp.ndarray, cfg: ModelConfig, mesh=None) -> jnp.ndarray:
    """Top-k routed MoE (``dynamo_tpu/parallel/moe.py``).

    Without an ``ep`` mesh axis: dropless ragged-matmul dispatch — exact,
    batch-composition-independent (deterministic greedy). With experts
    sharded over ``ep``: capacity-bounded scatter dispatch, where GSPMD turns
    the buffer movement into all-to-all over the expert axis."""
    from dynamo_tpu.parallel.moe import moe_mlp, moe_mlp_dropless

    import os

    b, t, d = x.shape
    xt = x.reshape(b * t, d)
    ep = int(mesh.shape.get("ep", 1)) if mesh is not None else 1
    routing = _routing_kwargs(cfg)
    # DYNAMO_MOE_DISPATCH overrides the ragged-matmul default without an ep
    # axis — escape hatches for toolchains where the default explodes:
    #  - "capacity": GShard scatter dispatch (lax.ragged_dot crashes the
    #    axon AOT helper at 64 experts).
    #  - "dense": decode-sized batches (N*k tokens-choices <= 2048) run the
    #    dense formulation — every token through every expert, mixed by
    #    routing weight. At decode N the extra FLOPs are MXU-noise and the
    #    step stays weight-bandwidth-bound; crucially there is NO scatter
    #    feeding a batched matmul, the exact composition the axon AOT
    #    compiler fails to schedule (compile probes: scatter alone 2s,
    #    einsums alone 1s, composed > 25 min). Larger (prefill) batches fall
    #    through to the capacity dispatch.
    dispatch = os.environ.get("DYNAMO_MOE_DISPATCH", "")
    dense_ok = b * t * cfg.num_experts_per_token <= 2048
    if ep <= 1 and dispatch == "dense" and dense_ok:
        out = _routed_dense(lp, xt, cfg)
    elif ep <= 1 and dispatch not in ("capacity", "dense"):
        out = moe_mlp_dropless(
            lp, xt, num_experts_per_token=cfg.num_experts_per_token, routing=routing
        )
    else:
        cf = cfg.moe_capacity_factor
        out = moe_mlp(
            lp, xt,
            num_experts_per_token=cfg.num_experts_per_token,
            capacity_factor=cf,
            capacity=(b * t * cfg.num_experts_per_token) if cf <= 0 else None,
            routing=routing,
        )
    if cfg.shared_expert_size:
        shared = _qmm(jax.nn.silu(_qmm(xt, lp["w_shared_gate"])) * _qmm(xt, lp["w_shared_up"]), lp["w_shared_down"])
        if cfg.shared_expert_gated:
            shared = shared * jax.nn.sigmoid((xt @ lp["shared_gate"]).astype(jnp.float32)).astype(shared.dtype)
        out = out + shared
    return out.reshape(b, t, d)


def _routed_dense(lp: Params, xt: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    """Routed MoE via dense compute over flattened tokens [N, D]: every
    token through every expert, mixed by routing weight. Exact (same output
    as the dropless dispatch); O(N*E) FLOPs, so only sensible for
    decode-sized N where the step is weight-bandwidth-bound anyway."""
    from dynamo_tpu.parallel.moe import route_tokens

    weights, topi = route_tokens(
        lp, xt, k=cfg.num_experts_per_token, **_routing_kwargs(cfg)
    )
    e = lp["router"].shape[-1]
    mix = jnp.zeros((xt.shape[0], e), jnp.float32).at[
        jnp.arange(xt.shape[0])[:, None], topi
    ].set(weights)  # [N, E]
    gate = jax.nn.silu(jnp.einsum("nd,edf->nef", xt, _dq(lp["w_gate"])))
    up = jnp.einsum("nd,edf->nef", xt, _dq(lp["w_up"]))
    expert_out = jnp.einsum("nef,efd->ned", gate * up, _dq(lp["w_down"]))  # [N, E, d]
    out = jnp.einsum("ned,ne->nd", expert_out.astype(jnp.float32), mix)
    return out.astype(xt.dtype)


def _mlp_moe_dense(lp: Params, x: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    """Dense-compute MoE golden model for tests of the dispatched paths
    (and the serving decode path under DYNAMO_MOE_DISPATCH=dense)."""
    b, t, d = x.shape
    return _routed_dense(lp, x.reshape(b * t, d), cfg).reshape(b, t, d)


def forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # i32[B, T]
    positions: jnp.ndarray,  # i32[B, T]
    k_cache: jnp.ndarray,  # [L, num_pages, page_size, n_kv * hd]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # i32[B, pages_per_seq]
    slot_mapping: jnp.ndarray,  # i32[B, T]
    last_token_index: jnp.ndarray,  # i32[B] index in [0,T) of each seq's last real token
    *,
    attn_impl: str | None = None,
    mesh=None,  # required when attn_impl == "ring"
    mm_embeds: jnp.ndarray | None = None,  # [B, M, D] image embeddings (vision tower)
    mm_slot_offset: jnp.ndarray | None = None,  # i32[B] placeholders already cached; -1 = text row
    mm_counts: jnp.ndarray | None = None,  # i32[B] embedding rows provided per row
    mrope_positions: jnp.ndarray | None = None,  # i32[B, 3, T] Qwen2-VL 3D rope coords
    logit_indices: jnp.ndarray | None = None,  # i32[B, V] token columns to score (spec verify)
    contiguous_positions: bool = True,  # False: route attention via gappy-safe paths
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One forward step. Returns (logits f32[B, vocab], k_cache, v_cache).

    Works for prefill (T = padded prompt chunk) and decode (T=1) alike; the
    engine runner donates the cache buffers so updates happen in place.

    ``attn_impl="ring"`` runs sequence-parallel ring attention over the
    mesh's ``sp`` axis (``parallel/ring.py``) for whole-prompt prefills —
    valid only when every sequence's full context is inside this chunk
    (positions start at 0, no cached prefix); K/V still write through to the
    paged cache so decode continues on the paged path.

    ``mm_embeds`` substitutes the k-th image placeholder token
    (``cfg.image_token_id``) of row b with ``mm_embeds[b, k + offset]`` —
    ``mm_slot_offset`` counts placeholders in already-cached chunks, so
    chunked prefill and prefix-cache resumption stay exact (the multimodal
    prefill handoff, reference `examples/multimodal/`).

    ``logit_indices`` switches the head to multi-position scoring for
    speculative verify: instead of one logits row per sequence at
    ``last_token_index``, score the V token columns named per row and
    return f32[B, V, vocab]. ``contiguous_positions=False`` additionally
    tells the paged-attention dispatch not to assume per-row contiguous
    position runs — verify rows from the n-gram drafter *are* contiguous,
    but the proposer interface admits draft layouts that are not, and the
    prefill kernel would silently mis-attend on a gappy row.
    """
    b, t = tokens.shape
    nl, npages, ps = k_cache.shape[0], k_cache.shape[1], k_cache.shape[2]
    inv_freq = jnp.asarray(rope_frequencies(cfg.head_dim, theta=cfg.rope_theta, scaling=cfg.rope_scaling))
    attn_mscale = rope_attention_factor(cfg.rope_scaling) ** 2
    x = params["embed"][tokens]  # [B, T, D]
    if cfg.embed_scale:  # Gemma: embeddings scale by sqrt(hidden)
        x = x * jnp.asarray(cfg.hidden_size**0.5, x.dtype)
    if mm_embeds is not None and cfg.image_token_id is not None:
        is_img = tokens == jnp.int32(cfg.image_token_id)  # [B, T]
        if cfg.video_token_id is not None:
            # Video placeholders substitute from the same embedding stream,
            # rows ordered by span position (images and videos interleaved).
            is_img = is_img | (tokens == jnp.int32(cfg.video_token_id))
        slot = jnp.cumsum(is_img.astype(jnp.int32), axis=1) - 1
        if mm_slot_offset is not None:
            slot = slot + jnp.maximum(mm_slot_offset, 0)[:, None]
            # Rows without images (offset -1) keep plain token embeddings —
            # a text prompt containing the placeholder id must not change
            # meaning based on which batch it shares a prefill with.
            is_img = is_img & (mm_slot_offset >= 0)[:, None]
        if mm_counts is not None:
            # Placeholders beyond the provided rows (e.g. *sampled* image
            # tokens recomputed after preemption) stay token embeddings.
            is_img = is_img & (slot < mm_counts[:, None])
        slot = jnp.clip(slot, 0, mm_embeds.shape[1] - 1)
        gathered = jnp.take_along_axis(mm_embeds.astype(x.dtype), slot[..., None], axis=1)
        x = jnp.where(is_img[..., None], gathered, x)

    # The stacked cache is kept flat ([L*pages, ps, W]) and every layer
    # addresses its region with offset indices (page' = li*pages + page).
    # This keeps cache writes a single in-place scatter on the donated carry
    # and cache reads a gather — slicing the layer out of the carry
    # (dynamic_index/update_in_dim) would copy the full multi-MB layer cache
    # twice per layer per step, which measures ~7 ms/step at 1B scale.
    kf0 = k_cache.reshape(nl * npages, ps, k_cache.shape[3])
    vf0 = v_cache.reshape(nl * npages, ps, v_cache.shape[3])

    if attn_impl is None:
        # Resolve the backend default up front: an unresolved None on a TPU
        # mesh would skip the sharded kernel wrapper below and run the
        # pallas_call under GSPMD, which replicates the whole cache onto
        # every device.
        from dynamo_tpu.ops.attention import default_impl

        attn_impl = default_impl()
    ring = attn_impl == "ring"
    if ring and cfg.sliding_window > 0:
        # Ring attention computes full causal attention over the sp axis;
        # silently serving a windowed model through it would change logits.
        raise ValueError(
            "ring attention does not implement sliding-window masking; "
            "serve SWA models with the paged path (no sp axis)"
        )
    if ring:
        # Padding tokens (slot 0) must not act as attendable keys in the ring
        # path (the paged path excludes them structurally via the null page).
        # A far-future sentinel position hides them from every real query.
        ring_pos = jnp.where(slot_mapping == 0, jnp.int32(2**30), positions)

    mla = cfg.attn_type == "mla"
    if mla:
        inv_freq_mla = jnp.asarray(
            rope_frequencies(cfg.qk_rope_head_dim, theta=cfg.rope_theta, scaling=cfg.rope_scaling)
        )

    def make_layer_step(moe_layer: bool):
        def layer_step(carry, lp):
            x, k_full, v_full, li = carry
            h = rms_norm(x, lp["attn_norm"], eps=cfg.rms_eps, plus_one=cfg.norm_plus_one)
            if mla:
                from dynamo_tpu.models.mla import mla_attention

                attn_out, k_full, v_full = mla_attention(
                    lp, cfg, h, positions, k_full, v_full,
                    block_tables + li * npages,
                    slot_mapping + li * (npages * ps),
                    inv_freq_mla,
                    attn_mscale=attn_mscale,
                    ring=ring, mesh=mesh,
                    ring_positions=ring_pos if ring else None,
                    impl=attn_impl,
                    contiguous_positions=contiguous_positions,
                )
                x = x + attn_out
                h2 = rms_norm(x, lp["mlp_norm"], eps=cfg.rms_eps, plus_one=cfg.norm_plus_one)
                mlp = _mlp_moe(lp, h2, cfg, mesh) if moe_layer else _mlp_dense(lp, h2, cfg.mlp_act)
                return (x + mlp, k_full, v_full, li + 1), None
            qp, kp, vp = _qmm(h, lp["wq"]), _qmm(h, lp["wk"]), _qmm(h, lp["wv"])
            if cfg.attention_bias:
                qp, kp, vp = qp + lp["bq"], kp + lp["bk"], vp + lp["bv"]
            if cfg.qk_norm == "flat":  # OLMoE: norm the flat projection
                qp = rms_norm(qp, lp["q_norm"], eps=cfg.rms_eps)
                kp = rms_norm(kp, lp["k_norm"], eps=cfg.rms_eps)
            q = qp.reshape(b, t, cfg.num_heads, cfg.head_dim)
            k = kp.reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
            v = vp.reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
            if cfg.qk_norm == "head":  # Qwen3: per-head norm before rope
                q = rms_norm(q, lp["q_norm"], eps=cfg.rms_eps)
                k = rms_norm(k, lp["k_norm"], eps=cfg.rms_eps)
            if mrope_positions is not None and cfg.mrope_section:
                # Qwen2-VL 3D rope: ONLY the rotation angles change; cache
                # slots, masking, and lengths keep the sequential positions.
                q = apply_mrope(q, mrope_positions, inv_freq, cfg.mrope_section)
                k = apply_mrope(k, mrope_positions, inv_freq, cfg.mrope_section)
            else:
                q = apply_rope(q, positions, inv_freq)
                k = apply_rope(k, positions, inv_freq)
            if attn_mscale != 1.0:  # YaRN temperature: logits scale by mscale^2
                q = q * jnp.asarray(attn_mscale, q.dtype)
            k_full, v_full = write_kv(k_full, v_full, k, v, slot_mapping + li * (npages * ps))
            if ring:
                from dynamo_tpu.parallel.ring import ring_attention

                attn = ring_attention(q, k, v, ring_pos, mesh, scale=cfg.head_dim**-0.5)
            else:
                tables_l = block_tables + li * npages
                if cfg.sliding_window > 0:
                    attn = paged_attention(
                        q, k_full, v_full, tables_l, positions,
                        impl=attn_impl, sliding_window=cfg.sliding_window,
                        contiguous_positions=contiguous_positions,
                    )
                elif attn_impl == "pallas" and mesh is not None:
                    # Explicit tp/dp layout around the kernel: GSPMD would
                    # otherwise all-gather the cache and replicate the
                    # pallas_call on every device.
                    from dynamo_tpu.ops.attention import paged_attention_sharded

                    attn = paged_attention_sharded(
                        q, k_full, v_full, tables_l, positions,
                        mesh=mesh, impl=attn_impl,
                        contiguous_positions=contiguous_positions,
                    )
                else:
                    attn = paged_attention(q, k_full, v_full, tables_l, positions, impl=attn_impl,
                                           contiguous_positions=contiguous_positions)
            x = x + _qmm(attn.reshape(b, t, cfg.q_dim), lp["wo"])
            h2 = rms_norm(x, lp["mlp_norm"], eps=cfg.rms_eps, plus_one=cfg.norm_plus_one)
            mlp = _mlp_moe(lp, h2, cfg, mesh) if moe_layer else _mlp_dense(lp, h2, cfg.mlp_act)
            x = x + mlp
            return (x, k_full, v_full, li + 1), None

        return layer_step

    # Scan over layers: one layer's program is traced once — compile time is
    # O(1) in depth (matters at 70B/80-layer scale). Mixed DeepSeek stacks
    # (first_k_dense_replace) run two scans — dense layers first — with the
    # layer counter (cache offsets) carried straight through.
    carry = (x, kf0, vf0, jnp.int32(0))
    if "dense_layers" in params:
        carry, _ = jax.lax.scan(make_layer_step(False), carry, params["dense_layers"])
    (x, k_out, v_out, _), _ = jax.lax.scan(
        make_layer_step(cfg.is_moe),
        carry,
        params["layers"],
    )
    k_out = k_out.reshape(k_cache.shape)
    v_out = v_out.reshape(v_cache.shape)

    x = rms_norm(x, params["norm_f"], eps=cfg.rms_eps, plus_one=cfg.norm_plus_one)
    # bf16 operands, f32 accumulate: no f32 materialization of the (huge)
    # embedding matrix per step; quantized lm_head goes through the shared
    # scale-after-dot helper.
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    if logit_indices is not None:
        # Speculative verify: score every candidate position in one head
        # matmul — V is small (spec_k + 1), so this stays cheap relative to
        # the layer stack it amortizes.
        sel = jnp.take_along_axis(x, logit_indices[:, :, None], axis=1)  # [B, V, D]
        logits = _qmm(sel, head, preferred_element_type=jnp.float32)  # [B, V, vocab]
        return logits, k_out, v_out
    last = jnp.take_along_axis(x, last_token_index[:, None, None], axis=1)[:, 0]  # [B, D]
    logits = _qmm(last, head, preferred_element_type=jnp.float32)  # [B, vocab]
    return logits, k_out, v_out


def encode(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # i32[B, T]
    mask: jnp.ndarray,  # bool[B, T] — True on real tokens
    pooling: str = "mean",  # "mean" | "last"
) -> jnp.ndarray:
    """Sentence-embedding forward: pooled final hidden states, L2-normalized.

    Runs the same stacked-layer scan as :func:`forward` but with plain
    in-batch causal attention — no paged cache, nothing donated, so it can
    run concurrently with serving steps. Returns f32[B, D].

    BE EXPLICIT about what this is: embeddings come from the SERVING LM's
    hidden states (masked mean, or last-token with ``pooling="last"`` — the
    E5-Mistral-class recipe). Meaningful retrieval quality requires
    deploying a checkpoint actually trained for embeddings (e.g. a
    gte-Qwen2 / E5 model through the normal loader); on a plain chat
    checkpoint this endpoint is API-parity, not a quality claim.

    Parity: the reference's /v1/embeddings route + EmbeddingEngine adapter
    (`lib/llm/src/http/service/openai.rs:580`, `engines.rs:321`).
    """
    b, t = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    inv_freq = jnp.asarray(rope_frequencies(cfg.head_dim, theta=cfg.rope_theta, scaling=cfg.rope_scaling))
    attn_mscale = rope_attention_factor(cfg.rope_scaling) ** 2
    x = params["embed"][tokens]  # [B, T, D]
    if cfg.embed_scale:  # Gemma: embeddings scale by sqrt(hidden)
        x = x * jnp.asarray(cfg.hidden_size**0.5, x.dtype)

    causal = jnp.tril(jnp.ones((t, t), bool))
    if cfg.sliding_window > 0:
        causal = causal & (
            jnp.arange(t)[None, :] > jnp.arange(t)[:, None] - cfg.sliding_window
        )
    attendable = causal[None, :, :] & mask[:, None, :]  # [B, Tq, Tk]
    bias = jnp.where(attendable, 0.0, -jnp.inf).astype(jnp.float32)[:, None, :, :]
    groups = cfg.num_heads // cfg.num_kv_heads
    scale = cfg.head_dim**-0.5

    def make_layer_step(moe_layer: bool):
        def layer_step(x, lp):
            h = rms_norm(x, lp["attn_norm"], eps=cfg.rms_eps, plus_one=cfg.norm_plus_one)
            qp, kp, vp = _qmm(h, lp["wq"]), _qmm(h, lp["wk"]), _qmm(h, lp["wv"])
            if cfg.attention_bias:
                qp, kp, vp = qp + lp["bq"], kp + lp["bk"], vp + lp["bv"]
            if cfg.qk_norm == "flat":
                qp = rms_norm(qp, lp["q_norm"], eps=cfg.rms_eps)
                kp = rms_norm(kp, lp["k_norm"], eps=cfg.rms_eps)
            qh = qp.reshape(b, t, cfg.num_heads, cfg.head_dim)
            kh = kp.reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
            if cfg.qk_norm == "head":
                qh = rms_norm(qh, lp["q_norm"], eps=cfg.rms_eps)
                kh = rms_norm(kh, lp["k_norm"], eps=cfg.rms_eps)
            q = apply_rope(qh, positions, inv_freq)
            k = apply_rope(kh, positions, inv_freq)
            if attn_mscale != 1.0:  # YaRN temperature: logits scale by mscale^2
                q = q * jnp.asarray(attn_mscale, q.dtype)
            v = vp.reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
            q = q.reshape(b, t, cfg.num_kv_heads, groups, cfg.head_dim)
            scores = jnp.einsum("bqhgd,bkhd->bhgqk", q, k).astype(jnp.float32) * scale
            scores = scores + bias[:, :, None, :, :]
            probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
            attn = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v).reshape(b, t, cfg.q_dim)
            x = x + _qmm(attn, lp["wo"])
            h2 = rms_norm(x, lp["mlp_norm"], eps=cfg.rms_eps, plus_one=cfg.norm_plus_one)
            mlp = _mlp_moe(lp, h2, cfg) if moe_layer else _mlp_dense(lp, h2, cfg.mlp_act)
            return x + mlp, None

        return layer_step

    if "dense_layers" in params:
        x, _ = jax.lax.scan(make_layer_step(False), x, params["dense_layers"])
    x, _ = jax.lax.scan(make_layer_step(cfg.is_moe), x, params["layers"])
    x = rms_norm(x, params["norm_f"], eps=cfg.rms_eps, plus_one=cfg.norm_plus_one).astype(jnp.float32)
    m = mask[:, :, None].astype(jnp.float32)
    if pooling == "last":
        # Last real token's hidden state — the recipe instruction-tuned
        # embedders (E5-Mistral / gte-Qwen class) are trained with.
        last = jnp.maximum(mask.sum(1) - 1, 0)  # [B]
        pooled = jnp.take_along_axis(x, last[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    else:
        pooled = (x * m).sum(1) / jnp.maximum(m.sum(1), 1.0)
    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)
