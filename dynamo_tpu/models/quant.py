"""Weight-only quantization for serving: int8 (per-channel) and packed int4
(group-wise).

Matmul weights are stored narrow and dequantized on the fly inside the
forward — XLA fuses the dequant expression into the matmul's operand read,
so HBM traffic for weights drops to 1 byte/elem (int8) or 0.5 byte/elem
(int4). The MXU still multiplies bf16; this is a bandwidth optimization,
which is exactly what decode is bound by.

Two leaf formats, distinguished by key:

- int8: ``{"qw": int8[..., d_in, d_out], "scale": bf16[..., d_out]}``.
  Per-output-channel symmetric; the scale commutes with the contraction, so
  ``quant_matmul`` applies it to the matmul *output* and the weight operand
  stays a bare int8→bf16 convert. Error ≤ 0.4% of each channel's range.
- int4: ``{"qw4": int8[..., d_in//2, d_out], "scale": bf16[..., G, d_out]}``
  plus an optional ``"qbias"`` (same shape as scale) for asymmetric imports
  (GGUF ``Q4_K``). Two nibbles per byte (element ``2i`` in the low nibble,
  ``2i+1`` in the high), group-wise scales along the *contraction* axis
  (``G = d_in // group_size`` groups). Group scales do NOT commute with the
  dot, so ``maybe_dequant`` expresses ``unpack * scale (+ bias)`` in-graph
  and relies on XLA operand fusion — the full-width tensor never
  round-trips HBM.

``maybe_dequant`` / ``quant_matmul`` are the single read-side accessors
(`models/llama.py`, `models/mla.py`, `parallel/moe.py`). Embeddings stay
bf16 (gathers, not matmuls); norms/biases/router are tiny and
accuracy-sensitive.

Role: the weight-quantized serving modes the reference gets from its
engines (vLLM/TRT-LLM quantized checkpoints, GGUF Q4-class wrapping); here
it's a params transform, so any checkpoint (safetensors/GGUF/random) can
serve quantized: ``--quantize int8|int4`` / ``BENCH_QUANT=int8|int4``. The
int4 group width is ``DYN_QUANT_GROUP_SIZE`` (default 128; GGUF Q4 imports
keep their native 32).
"""

from __future__ import annotations

import functools
import os
from typing import Any

import jax
import jax.numpy as jnp

# Leaves that are matmul weights, by name, at any nesting depth.
_MATMUL_LEAVES = frozenset(
    {
        "wq", "wk", "wv", "wo",
        "w_gate", "w_up", "w_down",
        "w_shared_gate", "w_shared_up", "w_shared_down",
        "lm_head",
        # MLA 2D projections (models/mla.py) — ~95% of its attention weight
        # bytes. The absorbed per-head tensors (w_uk/w_uv, 3-axis einsums)
        # stay bf16: their contraction axis is not the stored-scale axis.
        "w_q_a", "w_q_b", "w_q", "w_kv_a", "wo_mla",
    }
)

#: Modes accepted by quantize_params / init_params_quantized.
QUANT_MODES = ("int8", "int4")


def default_group_size() -> int:
    """int4 group width along the contraction axis (DYN_QUANT_GROUP_SIZE)."""
    return int(os.environ.get("DYN_QUANT_GROUP_SIZE", "128"))


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "scale" in leaf and ("qw" in leaf or "qw4" in leaf)


def _pick_group_size(d_in: int, group_size: int) -> int:
    """Largest even divisor of ``d_in`` that is ≤ the requested width.

    Group boundaries must align with nibble pairs (pairs run along d_in),
    so the width must be even; it must divide d_in so every group is full.
    """
    gs = min(group_size, d_in)
    while gs > 2 and (d_in % gs or gs % 2):
        gs -= 2 if gs % 2 == 0 else 1
    return max(gs, 2)


def pack_int4(q: jnp.ndarray) -> jnp.ndarray:
    """[..., d_in, O] int4-valued int8 → [..., d_in//2, O] packed bytes.

    Element ``2i`` lands in the low nibble of byte ``i``, ``2i+1`` in the
    high nibble. Values must be in [-8, 7].
    """
    lo = q[..., 0::2, :]
    hi = q[..., 1::2, :]
    return ((hi.astype(jnp.uint8) << 4) | (lo.astype(jnp.uint8) & 0x0F)).astype(jnp.int8)


def unpack_int4(packed: jnp.ndarray) -> jnp.ndarray:
    """[..., P, O] packed bytes → [..., 2P, O] int8 values in [-8, 7].

    Arithmetic shifts sign-extend the nibbles; the stack/reshape interleaves
    (lo, hi) back into row order — all cheap elementwise/layout ops XLA
    folds into the consuming dot's operand read.
    """
    b = packed.astype(jnp.int8)
    lo = jnp.left_shift(b, 4) >> 4  # sign-extended low nibble
    hi = b >> 4
    stacked = jnp.stack([lo, hi], axis=-2)  # [..., P, 2, O]
    return stacked.reshape(*packed.shape[:-2], packed.shape[-2] * 2, packed.shape[-1])


def quantize_leaf(w: jnp.ndarray, *, scale_dtype: Any = jnp.bfloat16) -> dict[str, jnp.ndarray]:
    """Symmetric per-output-channel int8: w[..., d_in, d_out]."""
    w32 = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=-2)  # [..., d_out]
    # Round the scale to its stored width *before* quantizing so the quants
    # are optimal for the scale the dequant will actually use.
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(scale_dtype)
    q = jnp.clip(
        jnp.round(w32 / scale.astype(jnp.float32)[..., None, :]), -127, 127
    ).astype(jnp.int8)
    return {"qw": q, "scale": scale}


def quantize_leaf_int4(
    w: jnp.ndarray, *, group_size: int | None = None, scale_dtype: Any = jnp.bfloat16
) -> dict[str, jnp.ndarray]:
    """Symmetric group-wise packed int4: w[..., d_in, d_out].

    Groups of ``group_size`` consecutive input rows share one bf16 scale per
    output channel; quants clip to [-7, 7] (the -8 code is reserved for
    asymmetric imports so symmetric dequant stays sign-balanced).
    """
    d_in = w.shape[-2]
    if d_in % 2:
        raise ValueError(f"int4 packing needs an even contraction dim, got {d_in}")
    gs = _pick_group_size(d_in, group_size or default_group_size())
    groups = d_in // gs
    w32 = jnp.asarray(w, jnp.float32).reshape(*w.shape[:-2], groups, gs, w.shape[-1])
    amax = jnp.max(jnp.abs(w32), axis=-2)  # [..., G, d_out]
    scale = jnp.where(amax > 0, amax / 7.0, 1.0).astype(scale_dtype)
    q = jnp.clip(
        jnp.round(w32 / scale.astype(jnp.float32)[..., None, :]), -7, 7
    ).astype(jnp.int8)
    q = q.reshape(*w.shape[:-2], d_in, w.shape[-1])
    return {"qw4": pack_int4(q), "scale": scale}


def _dequant_int4(leaf: dict, dtype: Any) -> jnp.ndarray:
    """Packed int4 leaf → full-width expression (for XLA operand fusion)."""
    q = unpack_int4(leaf["qw4"])  # [..., d_in, O] int8
    scale = leaf["scale"]  # [..., G, O]
    groups = scale.shape[-2]
    d_in, d_out = q.shape[-2], q.shape[-1]
    qg = q.reshape(*q.shape[:-2], groups, d_in // groups, d_out).astype(dtype)
    w = qg * scale.astype(dtype)[..., :, None, :]
    if "qbias" in leaf:
        w = w + leaf["qbias"].astype(dtype)[..., :, None, :]
    return w.reshape(*q.shape[:-2], d_in, d_out)


def quantize_params(params: dict, *, mode: str = "int8") -> dict:
    """Return a params pytree with matmul weights replaced by quantized
    leaves (int8 per-channel or packed int4 group-wise)."""
    if mode in ("", "none", None):
        return params
    if mode not in QUANT_MODES:
        raise ValueError(
            f"unknown quantization mode {mode!r} (supported: {', '.join(QUANT_MODES)})"
        )
    q_leaf = quantize_leaf if mode == "int8" else quantize_leaf_int4

    def walk(tree: Any, name: str | None) -> Any:
        if isinstance(tree, dict) and not is_quantized(tree):
            return {k: walk(v, k) for k, v in tree.items()}
        if name in _MATMUL_LEAVES and not is_quantized(tree):
            return q_leaf(tree)
        return tree

    return walk(params, None)


def quant_matmul(x: jnp.ndarray, leaf: Any, *, preferred_element_type: Any | None = None) -> jnp.ndarray:
    """``x @ w`` for a possibly-quantized last-two-dims weight.

    For int8 leaves the per-output-channel scale is applied to the matmul
    *output* (it commutes with the contraction), so the weight operand is a
    bare int8→bf16 convert — which XLA fuses into the dot's operand read
    (weights stream from HBM at 1 byte/elem). Scaling the weight before the
    dot instead materializes a dequantized copy and loses the bandwidth win.

    int4 group scales vary along the contraction axis and do not commute;
    the dequant expression goes on the operand side and fuses into the read
    (0.5 byte/elem streamed).
    """
    if is_quantized(leaf):
        if "qw4" in leaf:
            return jnp.matmul(
                x, _dequant_int4(leaf, x.dtype), preferred_element_type=preferred_element_type
            )
        y = jnp.matmul(
            x, leaf["qw"].astype(x.dtype), preferred_element_type=preferred_element_type
        )
        return y * leaf["scale"].astype(y.dtype)
    return jnp.matmul(x, leaf, preferred_element_type=preferred_element_type)


def maybe_dequant(leaf: Any, dtype: Any = jnp.bfloat16) -> jnp.ndarray:
    """The read-side accessor every matmul site goes through.

    For a quantized leaf, emits the dequant expression (``qw.astype * scale``
    for int8; unpack→scale→(+bias) for packed int4) — XLA fuses this into
    the consuming dot's operand so the dequantized tensor never round-trips
    HBM. Plain arrays pass through untouched.
    """
    if is_quantized(leaf):
        if "qw4" in leaf:
            return _dequant_int4(leaf, dtype)
        return leaf["qw"].astype(dtype) * leaf["scale"].astype(dtype)[..., None, :]
    return leaf




def init_params_quantized(cfg, rng: int | jax.Array = 0, *, mode: str = "int8") -> dict:
    """Random-init parameters directly in quantized form, never
    materializing the bf16/f32 tree.

    ``init_params`` + ``quantize_params`` peaks at full-precision model size
    plus f32 transients — an 8B-class model OOMs a 16 GB chip before the
    quantization that would have made it fit. Benchmarks need only
    identically-SHAPED (and finite) weights, so matmul leaves are generated
    directly in their quantized layout (int8 draws, or packed int4 bytes —
    each nibble uniform over the code range) with a constant fan-in scale,
    chunked along the stacked layer axis to bound the RNG's int32 transient;
    everything else follows ``init_params``'s shapes via ``jax.eval_shape``.
    """
    import math

    from dynamo_tpu.models import llama

    if mode in ("", "none", None):
        return llama.init_params(cfg, rng)
    if mode not in QUANT_MODES:
        raise ValueError(
            f"unknown quantization mode {mode!r} (supported: {', '.join(QUANT_MODES)})"
        )
    if isinstance(rng, int):
        rng = jax.random.PRNGKey(rng)
    shapes = jax.eval_shape(lambda: llama.init_params(cfg, jax.random.PRNGKey(0)))
    max_chunk_elems = 2**28  # 1 GiB int32 RNG transient ceiling

    @functools.partial(jax.jit, static_argnames=("shape", "lo", "hi"))
    def _rand_int8(key, shape, lo=-127, hi=128):
        # ONE dispatch per leaf: lax.map over the stacked leading axis keeps
        # the RNG's int32 transient at one slice, and avoids the per-chunk
        # host round trips that dominate init on a tunneled chip.
        if len(shape) >= 3 and math.prod(shape) > max_chunk_elems:
            keys = jax.random.split(key, shape[0])
            return jax.lax.map(
                lambda k: jax.random.randint(k, shape[1:], lo, hi, jnp.int8),
                keys,
            )
        return jax.random.randint(key, shape, lo, hi, jnp.int8)

    def gen_int8(key, sds):
        fan_in = sds.shape[-2]
        scale = jnp.full(
            sds.shape[:-2] + sds.shape[-1:], (fan_in**-0.5) / 127.0, jnp.bfloat16
        )
        return {"qw": _rand_int8(key, tuple(sds.shape)), "scale": scale}

    def gen_int4(key, sds):
        d_in = sds.shape[-2]
        if d_in % 2:
            raise ValueError(f"int4 packing needs an even contraction dim, got {d_in}")
        gs = _pick_group_size(d_in, default_group_size())
        packed_shape = sds.shape[:-2] + (d_in // 2, sds.shape[-1])
        scale_shape = sds.shape[:-2] + (d_in // gs, sds.shape[-1])
        # Full-byte uniform draws: each nibble is uniform over [-8, 7], so
        # the packed bytes ARE a valid symmetric-ish int4 population.
        packed = _rand_int8(key, packed_shape, -128, 128)
        scale = jnp.full(scale_shape, (d_in**-0.5) / 7.0, jnp.bfloat16)
        return {"qw4": packed, "scale": scale}

    gen_quant = gen_int8 if mode == "int8" else gen_int4

    def gen_plain(key, name, sds):
        if "norm" in name:
            return jnp.ones(sds.shape, sds.dtype)
        if sds.ndim == 1:
            return jnp.zeros(sds.shape, sds.dtype)
        fan_in = sds.shape[-2]
        return (
            jax.random.normal(key, sds.shape, jnp.float32) * fan_in**-0.5
        ).astype(sds.dtype)

    idx = 0

    def walk(tree, name):
        nonlocal idx
        if isinstance(tree, dict):
            return {k: walk(v, k) for k, v in tree.items()}
        key = jax.random.fold_in(rng, idx)
        idx += 1
        if name in _MATMUL_LEAVES:
            return gen_quant(key, tree)
        return gen_plain(key, name, tree)

    return walk(shapes, None)
