"""Weight-only int8 quantization for serving.

Matmul weights are stored int8 with a per-output-channel bf16 scale and
dequantized on the fly inside the forward — XLA fuses the ``astype * scale``
into the matmul's operand read, so HBM traffic for weights halves (the MXU
still multiplies bf16; this is a bandwidth optimization, which is exactly
what decode is bound by). Per-channel symmetric quantization keeps the
error ≤ 0.4% of each channel's range — negligible against bf16 activations.

A quantized leaf is the nested pytree ``{"qw": int8[..., d_in, d_out],
"scale": bf16[..., d_out]}``; ``maybe_dequant`` is the single read-side
accessor (`models/llama.py`). Embeddings stay bf16 (gathers, not matmuls);
norms/biases/router are tiny and accuracy-sensitive.

Role: the weight-quantized serving mode the reference gets from its engines
(vLLM/TRT-LLM quantized checkpoints); here it's a params transform, so any
checkpoint (safetensors/GGUF/random) can serve quantized:
``--quantize int8`` / ``BENCH_QUANT=int8``.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

# Leaves that are matmul weights, by name, at any nesting depth.
_MATMUL_LEAVES = frozenset(
    {
        "wq", "wk", "wv", "wo",
        "w_gate", "w_up", "w_down",
        "w_shared_gate", "w_shared_up", "w_shared_down",
        "lm_head",
        # MLA 2D projections (models/mla.py) — ~95% of its attention weight
        # bytes. The absorbed per-head tensors (w_uk/w_uv, 3-axis einsums)
        # stay bf16: their contraction axis is not the stored-scale axis.
        "w_q_a", "w_q_b", "w_q", "w_kv_a", "wo_mla",
    }
)


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "qw" in leaf and "scale" in leaf


def quantize_leaf(w: jnp.ndarray, *, scale_dtype: Any = jnp.bfloat16) -> dict[str, jnp.ndarray]:
    """Symmetric per-output-channel int8: w[..., d_in, d_out]."""
    w32 = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=-2)  # [..., d_out]
    # Round the scale to its stored width *before* quantizing so the quants
    # are optimal for the scale the dequant will actually use.
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(scale_dtype)
    q = jnp.clip(
        jnp.round(w32 / scale.astype(jnp.float32)[..., None, :]), -127, 127
    ).astype(jnp.int8)
    return {"qw": q, "scale": scale}


def quantize_params(params: dict, *, mode: str = "int8") -> dict:
    """Return a params pytree with matmul weights replaced by int8 leaves."""
    if mode in ("", "none", None):
        return params
    if mode != "int8":
        raise ValueError(f"unknown quantization mode {mode!r} (supported: int8)")

    def walk(tree: Any, name: str | None) -> Any:
        if isinstance(tree, dict) and not is_quantized(tree):
            return {k: walk(v, k) for k, v in tree.items()}
        if name in _MATMUL_LEAVES and not is_quantized(tree):
            return quantize_leaf(tree)
        return tree

    return walk(params, None)


def quant_matmul(x: jnp.ndarray, leaf: Any, *, preferred_element_type: Any | None = None) -> jnp.ndarray:
    """``x @ w`` for a possibly-quantized last-two-dims weight.

    For int8 leaves the per-output-channel scale is applied to the matmul
    *output* (it commutes with the contraction), so the weight operand is a
    bare int8→bf16 convert — which XLA fuses into the dot's operand read
    (weights stream from HBM at 1 byte/elem). Scaling the weight before the
    dot instead materializes a dequantized copy and loses the bandwidth win.
    """
    if is_quantized(leaf):
        y = jnp.matmul(
            x, leaf["qw"].astype(x.dtype), preferred_element_type=preferred_element_type
        )
        return y * leaf["scale"].astype(y.dtype)
    return jnp.matmul(x, leaf, preferred_element_type=preferred_element_type)


def maybe_dequant(leaf: Any, dtype: Any = jnp.bfloat16) -> jnp.ndarray:
    """The read-side accessor every matmul site goes through.

    For a quantized leaf, emits ``qw.astype(dtype) * scale`` — XLA fuses
    this into the consuming dot's operand so the dequantized tensor never
    round-trips HBM. Plain arrays pass through untouched.
    """
    if is_quantized(leaf):
        return leaf["qw"].astype(dtype) * leaf["scale"].astype(dtype)[..., None, :]
    return leaf




def init_params_quantized(cfg, rng: int | jax.Array = 0, *, mode: str = "int8") -> dict:
    """Random-init parameters directly in quantized form, never
    materializing the bf16/f32 tree.

    ``init_params`` + ``quantize_params`` peaks at full-precision model size
    plus f32 transients — an 8B-class model OOMs a 16 GB chip before the
    quantization that would have made it fit. Benchmarks need only
    identically-SHAPED (and finite) weights, so matmul leaves are generated
    as int8 draws with a constant fan-in scale, chunked along the stacked
    layer axis to bound the RNG's int32 transient; everything else follows
    ``init_params``'s shapes via ``jax.eval_shape``.
    """
    import math

    from dynamo_tpu.models import llama

    if mode in ("", "none", None):
        return llama.init_params(cfg, rng)
    if mode != "int8":
        raise ValueError(f"unknown quantization mode {mode!r} (supported: int8)")
    if isinstance(rng, int):
        rng = jax.random.PRNGKey(rng)
    shapes = jax.eval_shape(lambda: llama.init_params(cfg, jax.random.PRNGKey(0)))
    max_chunk_elems = 2**28  # 1 GiB int32 RNG transient ceiling

    @functools.partial(jax.jit, static_argnames=("shape",))
    def _rand_int8(key, shape):
        # ONE dispatch per leaf: lax.map over the stacked leading axis keeps
        # the RNG's int32 transient at one slice, and avoids the per-chunk
        # host round trips that dominate init on a tunneled chip.
        if len(shape) >= 3 and math.prod(shape) > max_chunk_elems:
            keys = jax.random.split(key, shape[0])
            return jax.lax.map(
                lambda k: jax.random.randint(k, shape[1:], -127, 128, jnp.int8),
                keys,
            )
        return jax.random.randint(key, shape, -127, 128, jnp.int8)

    def gen_quant(key, sds):
        fan_in = sds.shape[-2]
        scale = jnp.full(
            sds.shape[:-2] + sds.shape[-1:], (fan_in**-0.5) / 127.0, jnp.bfloat16
        )
        return {"qw": _rand_int8(key, tuple(sds.shape)), "scale": scale}

    def gen_plain(key, name, sds):
        if "norm" in name:
            return jnp.ones(sds.shape, sds.dtype)
        if sds.ndim == 1:
            return jnp.zeros(sds.shape, sds.dtype)
        fan_in = sds.shape[-2]
        return (
            jax.random.normal(key, sds.shape, jnp.float32) * fan_in**-0.5
        ).astype(sds.dtype)

    idx = 0

    def walk(tree, name):
        nonlocal idx
        if isinstance(tree, dict):
            return {k: walk(v, k) for k, v in tree.items()}
        key = jax.random.fold_in(rng, idx)
        idx += 1
        if name in _MATMUL_LEAVES:
            return gen_quant(key, tree)
        return gen_plain(key, name, tree)

    return walk(shapes, None)
