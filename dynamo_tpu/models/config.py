"""Model architecture configs + presets.

``ModelConfig`` is the single architecture description consumed by model
forwards, weight loaders, the engine's cache sizing, and the planner's memory
model. Convertible from HF `config.json` (`from_hf`).
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Any


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    intermediate_size: int
    rope_theta: float = 500000.0
    rope_scaling: dict | None = None
    rms_eps: float = 1e-5
    max_position: int = 131072
    tie_embeddings: bool = False
    dtype: str = "bfloat16"
    # MoE fields (0 experts = dense).
    num_experts: int = 0
    num_experts_per_token: int = 0
    moe_intermediate_size: int = 0
    # Per-expert buffer headroom for the dispatched (expert-parallel) MoE
    # path; <= 0 means no-drop capacity (exact, memory-heavier).
    moe_capacity_factor: float = 1.25
    # Always-on shared expert alongside the routed ones (Qwen2-MoE /
    # DeepSeek): total hidden width of the shared FFN; 0 disables.
    shared_expert_size: int = 0
    # Qwen2-MoE gates the shared expert with sigmoid(x @ g); DeepSeek doesn't.
    shared_expert_gated: bool = False
    # Router semantics (parallel/moe.py:route_tokens). DeepSeek-V3:
    # sigmoid scoring + aux-free e_score_correction_bias (noaux_tc) +
    # group-limited top-k + routed scaling; Mixtral: softmax + renorm;
    # Qwen2-MoE: softmax without renorm.
    moe_scoring: str = "softmax"  # "softmax" | "sigmoid"
    moe_norm_topk: bool = True  # renormalize the top-k weights
    moe_routed_scaling: float = 1.0  # DeepSeek routed_scaling_factor
    moe_n_group: int = 0  # group-limited routing (V3 n_group); 0 = off
    moe_topk_group: int = 0
    moe_router_bias: bool = False  # e_score_correction_bias present (noaux_tc)
    # DeepSeek first_k_dense_replace: the first k layers use a dense MLP
    # (params["dense_layers"]) while the rest are MoE (params["layers"]).
    first_k_dense: int = 0
    # Biases on q/k/v projections (Qwen2 family).
    attention_bias: bool = False
    # Gemma family: GeGLU MLP ("gelu_tanh"), zero-centered norm weights
    # ((1+w) convention), sqrt(hidden) embedding scaling.
    mlp_act: str = "silu"  # "silu" | "gelu_tanh"
    norm_plus_one: bool = False
    embed_scale: bool = False
    # Q/K RMS-norm before rope: "" (none), "head" (per-head over head_dim —
    # Qwen3), "flat" (over the full projection width — OLMoE).
    qk_norm: str = ""
    # Sliding-window attention (Mistral): queries attend to the last
    # `sliding_window` positions only. 0 = full causal.
    sliding_window: int = 0
    # Multimodal: the placeholder token id image embeddings substitute for
    # (None = text-only model); vision tower geometry lives in VisionConfig.
    image_token_id: int | None = None
    # Qwen2-VL M-RoPE: frequency-dim split for (temporal, height, width)
    # coordinates, e.g. (16, 24, 24). None = standard 1D rope.
    mrope_section: tuple | None = None
    video_token_id: int | None = None
    # Attention family: "gqa" (default) or "mla" (DeepSeek latent attention,
    # models/mla.py). MLA caches one latent + rope key per token.
    attn_type: str = "gqa"
    q_lora_rank: int = 0  # 0 = direct q projection
    kv_lora_rank: int = 0
    qk_nope_head_dim: int = 0
    qk_rope_head_dim: int = 0
    v_head_dim: int = 0
    # DeepSeek-V2/V3 checkpoints store the rope dims of q_b_proj /
    # kv_a_proj_with_mqa in interleaved pair order (HF `rope_interleave`,
    # default true there); the loader permutes them to the half-split
    # convention models/ops use (models/loader.py). False for every
    # non-MLA family: their HF checkpoints are already half-split.
    rope_interleave: bool = False

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    def kv_bytes_per_token(self, itemsize: int | None = None) -> int:
        """Bytes of KV cache per token across all layers (2 = K and V; MLA
        caches one latent + rope key instead). ``itemsize`` overrides the
        dtype-derived cache element size (e.g. a bf16 cache for an f32
        model)."""
        if itemsize is None:
            itemsize = 2 if self.dtype == "bfloat16" else 4
        if self.attn_type == "mla":
            # Physical bytes: the rope stream is padded to one 128-lane tile
            # (models/mla.py:mla_cache_widths — Mosaic DMA alignment).
            rope_width = max(self.qk_rope_head_dim, 128)
            return self.num_layers * (self.kv_lora_rank + rope_width) * itemsize
        return 2 * self.num_layers * self.kv_dim * itemsize

    def param_count(self) -> int:
        embed = self.vocab_size * self.hidden_size
        attn = self.hidden_size * (self.q_dim + 2 * self.kv_dim) + self.q_dim * self.hidden_size
        mlp = 3 * self.hidden_size * self.intermediate_size
        if self.is_moe:
            mlp = self.num_experts * 3 * self.hidden_size * self.moe_intermediate_size + self.hidden_size * self.num_experts
        norms = 2 * self.hidden_size
        head = 0 if self.tie_embeddings else embed
        return embed + head + self.hidden_size + self.num_layers * (attn + mlp + norms)

    @classmethod
    def from_hf(cls, config: dict[str, Any] | str | pathlib.Path, *, name: str | None = None) -> "ModelConfig":
        """Build from an HF ``config.json`` dict or path (Llama/Qwen-style keys)."""
        if not isinstance(config, dict):
            config = json.loads(pathlib.Path(config).read_text())
        if "vision_config" in config and "text_config" not in config:
            # Original flat Qwen2-VL layout (Qwen/Qwen2-VL-*-Instruct):
            # text keys live at top level next to vision_config. Normalize
            # to the nested shape so one branch handles both.
            inner_flat = {k: v for k, v in config.items() if k != "vision_config"}
            config = {**config, "text_config": inner_flat}
        if "text_config" in config and "vision_config" in config:
            # VLM config: the LM is the nested text_config; the tower is
            # models/vision.VisionConfig.from_hf_llava (LLaVA/CLIP) or
            # models/qwen2_vl.Qwen2VLVisionConfig.from_hf (Qwen2-VL).
            import dataclasses as _dc

            inner = dict(config["text_config"])
            inner.setdefault("_name_or_path", config.get("_name_or_path", "vlm"))
            # Qwen2-VL M-RoPE rides in rope_scaling; it is a position-id
            # scheme, not a frequency modifier — extract it and neutralize
            # the scaling dict so rope_frequencies sees plain rope.
            mrope = None
            rs = inner.get("rope_scaling") or {}
            if rs.get("mrope_section"):
                mrope = tuple(rs["mrope_section"])
                rest = {k: v for k, v in rs.items() if k != "mrope_section"}
                if rest.get("rope_type", rest.get("type")) in (None, "default", "mrope"):
                    rest = None
                inner["rope_scaling"] = rest
            cfg = cls.from_hf(inner, name=name)
            return _dc.replace(
                cfg,
                image_token_id=config.get("image_token_index", config.get("image_token_id")),
                video_token_id=config.get("video_token_id"),
                mrope_section=mrope,
            )
        if config.get("model_type") in ("gemma2", "gemma3", "gemma3_text"):
            # Gemma-2/3 add logit softcapping and alternating local/global
            # attention; running them through Gemma-1 math would silently
            # produce wrong logits. Refuse loudly.
            raise ValueError(
                f"model_type {config['model_type']!r} is unsupported "
                "(Gemma-2/3 softcapping + alternating-window attention); "
                "supported Gemma family: model_type 'gemma'"
            )
        hidden = config["hidden_size"]
        heads = config["num_attention_heads"]
        # DeepSeek replaces the first k MoE layers with dense MLPs
        # (first_k_dense_replace). k >= num_layers collapses to a plain
        # dense model; mixed stacks (0 < k < layers, real V2/V3) carry
        # first_k_dense through to the dense_layers/layers subtree split
        # (models/llama.py dual scan, models/loader._leaf_specs).
        first_dense = int(config.get("first_k_dense_replace", 0) or 0)
        all_dense = first_dense >= config["num_hidden_layers"]
        return cls(
            name=name or config.get("_name_or_path", config.get("model_type", "model")),
            vocab_size=config["vocab_size"],
            hidden_size=hidden,
            num_layers=config["num_hidden_layers"],
            num_heads=heads,
            num_kv_heads=config.get("num_key_value_heads", heads),
            head_dim=config.get("head_dim") or hidden // heads,
            intermediate_size=config["intermediate_size"],
            rope_theta=config.get("rope_theta", 10000.0),
            rope_scaling=config.get("rope_scaling"),
            rms_eps=config.get("rms_norm_eps", 1e-5),
            max_position=config.get("max_position_embeddings", 8192),
            tie_embeddings=config.get("tie_word_embeddings", False),
            num_experts=(n_experts := 0 if all_dense else (
                config.get("num_experts", config.get("num_local_experts", config.get("n_routed_experts", 0))) or 0
            )),
            num_experts_per_token=(config.get("num_experts_per_tok", 0) or 0) if n_experts else 0,
            # Mixtral stores the expert width in intermediate_size itself.
            moe_intermediate_size=((config.get("moe_intermediate_size", 0) or 0) or config["intermediate_size"]) if n_experts else 0,
            # Qwen2-MoE names the width directly; DeepSeek counts experts.
            shared_expert_size=((config.get("shared_expert_intermediate_size", 0) or 0)
            or (config.get("n_shared_experts", 0) or 0) * (config.get("moe_intermediate_size", 0) or 0)) if n_experts else 0,
            shared_expert_gated=config.get("model_type") == "qwen2_moe",
            # Native transformers' DeepseekV3Config does not serialize
            # scoring_func (its modeling hardcodes sigmoid routing), so a
            # missing key on deepseek_v3 means sigmoid — same model_type
            # fallback as moe_router_bias below.
            moe_scoring=config.get(
                "scoring_func",
                "sigmoid" if config.get("model_type") == "deepseek_v3" else "softmax",
            ) if n_experts else "softmax",
            # Mixtral renormalizes unconditionally (no config key) and
            # DeepSeek-V3 defaults norm_topk_prob=True; Qwen2-MoE/V2 default
            # False (real checkpoints set the key explicitly either way).
            moe_norm_topk=bool(config.get(
                "norm_topk_prob", config.get("model_type") in ("mixtral", "deepseek_v3")
            )),
            moe_routed_scaling=float(config.get("routed_scaling_factor", 1.0) or 1.0),
            moe_n_group=(config.get("n_group", 0) or 0) if n_experts else 0,
            moe_topk_group=(config.get("topk_group", 0) or 0) if n_experts else 0,
            # noaux_tc correction bias: native transformers' DeepseekV3Config
            # doesn't serialize topk_method, but its modeling always creates
            # e_score_correction_bias — key off model_type too.
            moe_router_bias=bool(n_experts) and (
                config.get("topk_method", "") == "noaux_tc"
                or config.get("model_type") == "deepseek_v3"
            ),
            first_k_dense=0 if all_dense else first_dense,
            attention_bias=bool(config.get("attention_bias", config.get("model_type") in (
                "qwen2", "qwen2_moe", "qwen2_vl", "qwen2_vl_text"))),
            # Gemma: hidden_activation gelu_pytorch_tanh (None in older
            # configs means the same), (1+w) norms, sqrt(hidden) embeds.
            mlp_act="gelu_tanh" if config.get("model_type") == "gemma" else "silu",
            norm_plus_one=config.get("model_type") == "gemma",
            embed_scale=config.get("model_type") == "gemma",
            qk_norm={"qwen3": "head", "qwen3_moe": "head", "olmoe": "flat"}.get(
                config.get("model_type", ""), ""
            ),
            # HF gates the window: Qwen2-family configs carry sliding_window
            # together with use_sliding_window=false (full causal). Adopt the
            # key only when the gate is on (absent = on, Mistral-style) AND
            # it applies to every layer (max_window_layers partial-SWA is
            # unsupported — full attention is the conservative fallback).
            sliding_window=int(config.get("sliding_window") or 0)
            if config.get("use_sliding_window", True)
            and int(config.get("max_window_layers") or config["num_hidden_layers"])
            >= config["num_hidden_layers"]
            else 0,
            # DeepSeek-V2/V3: MLA signalled by the latent-rank keys.
            attn_type="mla" if config.get("kv_lora_rank") else "gqa",
            q_lora_rank=config.get("q_lora_rank") or 0,
            kv_lora_rank=config.get("kv_lora_rank") or 0,
            qk_nope_head_dim=config.get("qk_nope_head_dim") or 0,
            qk_rope_head_dim=config.get("qk_rope_head_dim") or 0,
            v_head_dim=config.get("v_head_dim") or 0,
            # HF defaults rope_interleave=True for DeepSeek MLA configs, so
            # a missing key means interleaved — matching every real V2/V3
            # checkpoint. save_params now always writes the key; MLA
            # checkpoints exported by THIS repo before the rope fix (no key,
            # weights half-split) load wrong under this default — re-export,
            # or add "rope_interleave": false to their config.json.
            rope_interleave=bool(config.get("rope_interleave", True))
            if config.get("kv_lora_rank")
            else False,
        )


# Presets for the tracked benchmark configs (BASELINE.md) plus tiny test models.
PRESETS: dict[str, ModelConfig] = {
    # Small enough for fast CPU unit tests, large enough to exercise GQA + paging.
    "test-tiny": ModelConfig(
        name="test-tiny", vocab_size=256, hidden_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=16, intermediate_size=128,
        rope_theta=10000.0, max_position=512, tie_embeddings=True, dtype="float32",
    ),
    # Kernel-geometry test model: shapes chosen so the Pallas paged kernels'
    # support predicate holds on the LOCAL shard at tp=2 (n_kv/tp * head_dim
    # = 2*64 = 128 lanes) — used by the sharded-kernel tests and the
    # attn_impl="pallas" multichip dryrun pass.
    "test-kernel": ModelConfig(
        name="test-kernel", vocab_size=256, hidden_size=512, num_layers=2,
        num_heads=8, num_kv_heads=4, head_dim=64, intermediate_size=256,
        rope_theta=10000.0, max_position=512, tie_embeddings=True, dtype="float32",
    ),
    # Vision-language test model: test-tiny plus an image placeholder token.
    "test-tiny-vl": ModelConfig(
        name="test-tiny-vl", vocab_size=256, hidden_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=16, intermediate_size=128,
        rope_theta=10000.0, max_position=512, tie_embeddings=True, dtype="float32",
        image_token_id=255,
    ),
    # MoE test model: 4 experts, top-2.
    "test-tiny-moe": ModelConfig(
        name="test-tiny-moe", vocab_size=256, hidden_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=16, intermediate_size=128,
        rope_theta=10000.0, max_position=512, tie_embeddings=True, dtype="float32",
        num_experts=4, num_experts_per_token=2, moe_intermediate_size=64,
    ),
    "llama-3.2-1b": ModelConfig(
        name="llama-3.2-1b", vocab_size=128256, hidden_size=2048, num_layers=16,
        num_heads=32, num_kv_heads=8, head_dim=64, intermediate_size=8192,
        rope_theta=500000.0, tie_embeddings=True,
        rope_scaling={"rope_type": "llama3", "factor": 32.0, "low_freq_factor": 1.0,
                      "high_freq_factor": 4.0, "original_max_position_embeddings": 8192},
    ),
    "llama-3-8b": ModelConfig(
        name="llama-3-8b", vocab_size=128256, hidden_size=4096, num_layers=32,
        num_heads=32, num_kv_heads=8, head_dim=128, intermediate_size=14336,
        rope_theta=500000.0, max_position=8192,
    ),
    "llama-3-70b": ModelConfig(
        name="llama-3-70b", vocab_size=128256, hidden_size=8192, num_layers=80,
        num_heads=64, num_kv_heads=8, head_dim=128, intermediate_size=28672,
        rope_theta=500000.0, max_position=8192,
    ),
    # DeepSeek-R1-Distill-Llama-8B: Llama-3.1-8B architecture (BASELINE
    # tracked config #2); distilled weights load via the standard Llama map.
    "deepseek-r1-distill-8b": ModelConfig(
        name="deepseek-r1-distill-8b", vocab_size=128256, hidden_size=4096,
        num_layers=32, num_heads=32, num_kv_heads=8, head_dim=128,
        intermediate_size=14336, rope_theta=500000.0, max_position=131072,
        rope_scaling={"rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
                      "high_freq_factor": 4.0, "original_max_position_embeddings": 8192},
    ),
    # Qwen2.5-7B: Qwen2 family (q/k/v biases, untied head, 1M-theta rope).
    "qwen2.5-7b": ModelConfig(
        name="qwen2.5-7b", vocab_size=152064, hidden_size=3584, num_layers=28,
        num_heads=28, num_kv_heads=4, head_dim=128, intermediate_size=18944,
        rope_theta=1000000.0, max_position=32768, rms_eps=1e-6,
        attention_bias=True,
    ),
    # Mistral-7B-v0.1: Llama architecture + 4096-token sliding window.
    "mistral-7b": ModelConfig(
        name="mistral-7b", vocab_size=32000, hidden_size=4096, num_layers=32,
        num_heads=32, num_kv_heads=8, head_dim=128, intermediate_size=14336,
        rope_theta=10000.0, max_position=32768, sliding_window=4096,
    ),
    # Qwen3-8B: per-head Q/K RMS norm, untied head, no attention bias.
    "qwen3-8b": ModelConfig(
        name="qwen3-8b", vocab_size=151936, hidden_size=4096, num_layers=36,
        num_heads=32, num_kv_heads=8, head_dim=128, intermediate_size=12288,
        rope_theta=1000000.0, max_position=40960, rms_eps=1e-6,
        qk_norm="head",
    ),
    # Qwen3-30B-A3B: 128 experts / top-8 MoE with per-head qk-norm; needs
    # ep>=2 on 16 GB chips (~30 GB int8).
    "qwen3-30b-a3b": ModelConfig(
        name="qwen3-30b-a3b", vocab_size=151936, hidden_size=2048, num_layers=48,
        num_heads=32, num_kv_heads=4, head_dim=128, intermediate_size=6144,
        rope_theta=1000000.0, max_position=40960, rms_eps=1e-6,
        num_experts=128, num_experts_per_token=8, moe_intermediate_size=768,
        moe_scoring="softmax", moe_norm_topk=True, qk_norm="head",
    ),
    # Mixtral-8x7B: 8 routed experts / top-2, no shared expert.
    "mixtral-8x7b": ModelConfig(
        name="mixtral-8x7b", vocab_size=32000, hidden_size=4096, num_layers=32,
        num_heads=32, num_kv_heads=8, head_dim=128, intermediate_size=14336,
        rope_theta=1000000.0, max_position=32768,
        num_experts=8, num_experts_per_token=2, moe_intermediate_size=14336,
    ),
    # DeepSeek-V3-shaped wide-EP config (BASELINE tracked config #4):
    # 256 routed experts / top-8 with real MLA (latent KV cache, absorbed
    # up-projections — models/mla.py); expert-parallel serving exercises
    # dynamo_tpu/parallel/moe.py.
    "deepseek-v3-ep": ModelConfig(
        name="deepseek-v3-ep", vocab_size=129280, hidden_size=7168,
        num_layers=61, num_heads=128, num_kv_heads=128, head_dim=64,
        intermediate_size=18432, rope_theta=10000.0, max_position=163840,
        num_experts=256, num_experts_per_token=8, moe_intermediate_size=2048,
        shared_expert_size=2048,  # n_shared_experts=1
        attn_type="mla", q_lora_rank=1536, kv_lora_rank=512,
        qk_nope_head_dim=128, qk_rope_head_dim=64, v_head_dim=128,
        rope_interleave=True,  # real V3 checkpoints ship interleaved rope dims
        # V3 router: sigmoid scores + aux-free correction bias, 8 groups
        # with the best 4 eligible, renormalized weights scaled 2.5x.
        moe_scoring="sigmoid", moe_router_bias=True, moe_norm_topk=True,
        moe_routed_scaling=2.5, moe_n_group=8, moe_topk_group=4,
        first_k_dense=3,
    ),
    # DeepSeek-V2-Lite: the real 15.7B MoE+MLA checkpoint shape — 64 routed
    # experts / top-6 + 2 shared experts, MLA without q-LoRA, one leading
    # dense layer. Expert weights dominate (~14.4 GB int8), so single-chip
    # v5e serving needs ep>=2; the single-chip MoE bench uses olmoe-1b-7b.
    "deepseek-v2-lite": ModelConfig(
        name="deepseek-v2-lite", vocab_size=102400, hidden_size=2048,
        num_layers=27, num_heads=16, num_kv_heads=16, head_dim=128,
        intermediate_size=10944, rope_theta=10000.0, max_position=163840,
        num_experts=64, num_experts_per_token=6, moe_intermediate_size=1408,
        shared_expert_size=2816,  # n_shared_experts=2
        attn_type="mla", q_lora_rank=0, kv_lora_rank=512,
        qk_nope_head_dim=128, qk_rope_head_dim=64, v_head_dim=128,
        rope_interleave=True, moe_scoring="softmax", moe_norm_topk=False,
        moe_routed_scaling=1.0, first_k_dense=1,
    ),
    # Qwen1.5-MoE-A2.7B-class: 14.3B total / 2.7B active — 60 experts /
    # top-4 + a sigmoid-gated shared expert (Qwen2-MoE semantics).
    "qwen1.5-moe-a2.7b": ModelConfig(
        name="qwen1.5-moe-a2.7b", vocab_size=151936, hidden_size=2048,
        num_layers=24, num_heads=16, num_kv_heads=16, head_dim=128,
        intermediate_size=5632, rope_theta=1000000.0, max_position=8192,
        num_experts=60, num_experts_per_token=4, moe_intermediate_size=1408,
        shared_expert_size=5632, shared_expert_gated=True,
        moe_scoring="softmax", moe_norm_topk=False, attention_bias=True,
    ),
    # OLMoE-1B-7B: real 6.9B-total / 1.3B-active MoE checkpoint shape —
    # 64 experts / top-8, no shared expert, softmax routing with top-k
    # renorm. The single-chip MoE bench config: ~7 GB int8 on v5e.
    "olmoe-1b-7b": ModelConfig(
        name="olmoe-1b-7b", vocab_size=50304, hidden_size=2048,
        num_layers=16, num_heads=16, num_kv_heads=16, head_dim=128,
        intermediate_size=1024, rope_theta=10000.0, max_position=4096,
        num_experts=64, num_experts_per_token=8, moe_intermediate_size=1024,
        moe_scoring="softmax", moe_norm_topk=True, qk_norm="flat",
    ),
    # MLA throughput proxy at 8B-class scale: DeepSeek-V3's per-layer MLA
    # geometry (kv_lora 512 + rope 64 latent cache, absorbed projections)
    # on a 32-layer/4096-hidden dense trunk, sized to one 16 GB chip at
    # int8. Answers "MLA decode throughput on hardware" (VERDICT r3 missing
    # #1) without the 671B V3 trunk; named -proxy because no public
    # checkpoint has this exact shape.
    "mla-8b-proxy": ModelConfig(
        name="mla-8b-proxy", vocab_size=128256, hidden_size=4096,
        num_layers=32, num_heads=32, num_kv_heads=32, head_dim=128,
        intermediate_size=14336, rope_theta=500000.0, max_position=8192,
        attn_type="mla", q_lora_rank=1536, kv_lora_rank=512,
        qk_nope_head_dim=128, qk_rope_head_dim=64, v_head_dim=128,
        rope_interleave=True,
    ),
    # Tiny V3-true-shape test model: MLA + sigmoid/noaux_tc routing +
    # group-limited top-k + a leading dense layer (mirrors the golden test).
    "test-tiny-v3": ModelConfig(
        name="test-tiny-v3", vocab_size=256, hidden_size=64, num_layers=3,
        num_heads=4, num_kv_heads=4, head_dim=16, intermediate_size=128,
        rope_theta=10000.0, max_position=512, tie_embeddings=True, dtype="float32",
        num_experts=4, num_experts_per_token=2, moe_intermediate_size=32,
        shared_expert_size=32,
        attn_type="mla", q_lora_rank=32, kv_lora_rank=24,
        qk_nope_head_dim=16, qk_rope_head_dim=8, v_head_dim=16,
        rope_interleave=True, moe_scoring="sigmoid", moe_router_bias=True,
        moe_norm_topk=True, moe_routed_scaling=2.5, moe_n_group=2,
        moe_topk_group=1, first_k_dense=1,
    ),
    # MLA test model (tiny): latent cache + absorbed projections.
    "test-tiny-mla": ModelConfig(
        name="test-tiny-mla", vocab_size=256, hidden_size=64, num_layers=2,
        num_heads=4, num_kv_heads=4, head_dim=16, intermediate_size=128,
        rope_theta=10000.0, max_position=512, tie_embeddings=True, dtype="float32",
        attn_type="mla", q_lora_rank=32, kv_lora_rank=24,
        qk_nope_head_dim=16, qk_rope_head_dim=8, v_head_dim=16,
    ),
}
