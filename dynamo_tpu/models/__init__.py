"""First-party JAX model implementations.

The reference wraps external engines per model family; here the model zoo is
native: dense Llama-family decoders (Llama 3.x, DeepSeek-R1-Distill), with MoE
(DeepSeek-style expert parallel) and multimodal (vision-encoder prefill)
variants layered on the same paged-cache forward contract.

The forward contract every model implements (see ``llama.py``):

    forward(params, tokens, positions, k_cache, v_cache, block_tables,
            slot_mapping, last_token_index) -> (logits, k_cache, v_cache)

so the engine's scheduler/runner is model-agnostic.
"""

from dynamo_tpu.models.config import ModelConfig, PRESETS
from dynamo_tpu.models import llama

__all__ = ["ModelConfig", "PRESETS", "llama"]
