"""GGUF checkpoint support: parser, dequantization, writer, params loading.

Reads llama.cpp-style GGUF (v2/v3) files — header, typed metadata KV pairs,
tensor index — via mmap, dequantizes the common quant formats (F32/F16/BF16/
Q8_0/Q4_0/Q4_1/Q4_K/Q5_K/Q6_K) to numpy, maps GGUF metadata onto :class:`ModelConfig`,
reconstructs the embedded tokenizer as a ``tokenizers`` object, and loads the
tensor set into the stacked-layer params pytree used by ``models/llama.py``.

A writer (`write_gguf`) round-trips params → GGUF (with optional Q8_0
quantization), which the tests use to synthesize checkpoints and which doubles
as an export tool (``python -m dynamo_tpu.models.gguf info file.gguf``).

TPU notes: quantized GGUF blocks are a CPU-side storage format here — tensors
are dequantized on host and placed on the mesh in bf16 so every matmul still
hits the MXU; block-dequant-on-chip is intentionally not emulated.

Parity: reference ``lib/llm/src/gguf/{content,gguf_metadata,gguf_tokenizer}.rs``
(metadata + embedded-tokenizer extraction), ``model_card/create.rs`` (cards
built from GGUF), ``local_model.rs`` (GGUF vs HF repo resolution).
"""

from __future__ import annotations

import mmap
import os
import pathlib
import struct
from typing import Any, BinaryIO

import numpy as np

from dynamo_tpu.models.config import ModelConfig

MAGIC = b"GGUF"

# Metadata value types (GGUF spec).
T_U8, T_I8, T_U16, T_I16, T_U32, T_I32, T_F32, T_BOOL, T_STR, T_ARR, T_U64, T_I64, T_F64 = range(13)

_SCALAR_FMT = {
    T_U8: "<B", T_I8: "<b", T_U16: "<H", T_I16: "<h", T_U32: "<I", T_I32: "<i",
    T_F32: "<f", T_U64: "<Q", T_I64: "<q", T_F64: "<d",
}

# ggml tensor types (subset we can read/write).
GGML_F32, GGML_F16 = 0, 1
GGML_Q4_0, GGML_Q4_1 = 2, 3
GGML_Q8_0 = 8
GGML_Q4_K, GGML_Q5_K, GGML_Q6_K = 12, 13, 14
GGML_BF16 = 30

_TYPE_NAMES = {GGML_F32: "F32", GGML_F16: "F16", GGML_Q4_0: "Q4_0", GGML_Q4_1: "Q4_1",
               GGML_Q8_0: "Q8_0", GGML_BF16: "BF16",
               GGML_Q4_K: "Q4_K", GGML_Q5_K: "Q5_K", GGML_Q6_K: "Q6_K"}

_BLOCK = 32  # quant block size for Q4_0/Q4_1/Q8_0
_QK_K = 256  # K-quant super-block size

# bytes per block / elements per block
_TYPE_SIZES = {
    GGML_F32: (4, 1),
    GGML_F16: (2, 1),
    GGML_BF16: (2, 1),
    GGML_Q8_0: (2 + _BLOCK, _BLOCK),
    GGML_Q4_0: (2 + _BLOCK // 2, _BLOCK),
    GGML_Q4_1: (4 + _BLOCK // 2, _BLOCK),
    GGML_Q4_K: (2 + 2 + 12 + _QK_K // 2, _QK_K),       # 144
    GGML_Q5_K: (2 + 2 + 12 + _QK_K // 8 + _QK_K // 2, _QK_K),  # 176
    GGML_Q6_K: (_QK_K // 2 + _QK_K // 4 + _QK_K // 16 + 2, _QK_K),  # 210
}


class GGUFTensorInfo:
    __slots__ = ("name", "shape", "ggml_type", "offset", "nbytes")

    def __init__(self, name: str, shape: tuple[int, ...], ggml_type: int, offset: int) -> None:
        self.name = name
        self.shape = shape  # numpy (row-major) orientation: ggml dims reversed
        self.ggml_type = ggml_type
        self.offset = offset  # relative to data section start
        n = int(np.prod(shape)) if shape else 1
        bpb, epb = _TYPE_SIZES[ggml_type]
        self.nbytes = (n // epb) * bpb


# ---------------------------------------------------------------------------
# Reader
# ---------------------------------------------------------------------------


class GGUFReader:
    """mmap-backed GGUF file: ``.metadata`` dict + tensor index + dequant reads."""

    def __init__(self, path: str | pathlib.Path) -> None:
        self.path = pathlib.Path(path)
        self._file = open(self.path, "rb")
        try:
            self._mm = mmap.mmap(self._file.fileno(), 0, access=mmap.ACCESS_READ)
        except Exception:
            self._file.close()
            raise
        try:
            self._parse_header()
        except Exception:
            self.close()
            raise

    def _parse_header(self) -> None:
        path = self.path
        self._pos = 0
        magic = self._take(4)
        if magic != MAGIC:
            raise ValueError(f"{path}: not a GGUF file (magic {magic!r})")
        self.version = self._scalar("<I")
        if self.version not in (2, 3):
            raise ValueError(f"{path}: unsupported GGUF version {self.version}")
        n_tensors = self._scalar("<Q")
        n_kv = self._scalar("<Q")
        self.metadata: dict[str, Any] = {}
        for _ in range(n_kv):
            key = self._string()
            self.metadata[key] = self._value(self._scalar("<I"))
        self.tensors: dict[str, GGUFTensorInfo] = {}
        for _ in range(n_tensors):
            name = self._string()
            n_dims = self._scalar("<I")
            dims = [self._scalar("<Q") for _ in range(n_dims)]
            ggml_type = self._scalar("<I")
            offset = self._scalar("<Q")
            if ggml_type not in _TYPE_SIZES:
                raise ValueError(f"{path}: tensor {name!r} has unsupported ggml type {ggml_type}")
            # ggml lists dims innermost-first; numpy shape is the reverse.
            self.tensors[name] = GGUFTensorInfo(name, tuple(reversed(dims)), ggml_type, offset)
        align = int(self.metadata.get("general.alignment", 32))
        self._data_start = (self._pos + align - 1) // align * align

    # -- low-level cursor reads ------------------------------------------------

    def _take(self, n: int) -> bytes:
        b = self._mm[self._pos : self._pos + n]
        self._pos += n
        return b

    def _scalar(self, fmt: str) -> int:
        (v,) = struct.unpack(fmt, self._take(struct.calcsize(fmt)))
        return v

    def _string(self) -> str:
        n = self._scalar("<Q")
        return self._take(n).decode("utf-8")

    def _value(self, vtype: int) -> Any:
        if vtype == T_STR:
            return self._string()
        if vtype == T_BOOL:
            return bool(self._scalar("<B"))
        if vtype == T_ARR:
            etype = self._scalar("<I")
            n = self._scalar("<Q")
            if etype in _SCALAR_FMT:  # bulk-read numeric arrays
                fmt = _SCALAR_FMT[etype]
                size = struct.calcsize(fmt)
                arr = np.frombuffer(self._take(n * size), dtype=np.dtype(fmt[1:]).newbyteorder("<"))
                return arr.tolist()
            return [self._value(etype) for _ in range(n)]
        return self._scalar(_SCALAR_FMT[vtype])

    # -- tensor access ---------------------------------------------------------

    def __contains__(self, name: str) -> bool:
        return name in self.tensors

    def keys(self):
        return self.tensors.keys()

    def read(self, name: str) -> np.ndarray:
        """Dequantize tensor ``name`` to float32 (or its native float dtype)."""
        info = self.tensors[name]
        start = self._data_start + info.offset
        # memoryview slice: zero-copy window into the mapping (a plain mmap
        # slice would copy the whole tensor into a bytes object first).
        raw = memoryview(self._mm)[start : start + info.nbytes]
        return _dequant(raw, info.ggml_type, info.shape)

    def read_q4(self, name: str) -> tuple[np.ndarray, np.ndarray, np.ndarray | None]:
        """Q4_0/Q4_K tensor ``name`` without dequantizing to full width:
        ``(q, scale, bias)`` with ``q`` int8 in [-8, 7] at the tensor's
        shape, and f32 ``scale``/``bias`` per 32-wide group along the
        innermost (contiguous) axis — ``shape[:-1] + (shape[-1]//32,)``.

        The decomposition is exact against ``read``'s dequant: Q4_0 is
        ``d*(q_raw-8)`` natively; Q4_K's ``d*sc*q_raw - dmin*mn`` rewrites
        to ``(d*sc)*(q_raw-8) + (8*d*sc - dmin*mn)`` (q shifted to the
        symmetric code range, the shift folded into the bias).
        """
        info = self.tensors[name]
        start = self._data_start + info.offset
        raw = memoryview(self._mm)[start : start + info.nbytes]
        shape = info.shape
        gshape = shape[:-1] + (shape[-1] // _BLOCK,)
        if info.ggml_type == GGML_Q4_0:
            rec = np.frombuffer(raw, dtype=np.dtype([("d", "<f2"), ("qs", "u1", (_BLOCK // 2,))]))
            lo = (rec["qs"] & 0x0F).astype(np.int8) - 8
            hi = (rec["qs"] >> 4).astype(np.int8) - 8
            q = np.concatenate([lo, hi], axis=1)  # [nb, 32]: elems 0..15 in low nibbles
            return q.reshape(shape), rec["d"].astype(np.float32).reshape(gshape), None
        if info.ggml_type == GGML_Q4_K:
            rec = np.frombuffer(raw, dtype=np.dtype(
                [("d", "<f2"), ("dmin", "<f2"), ("scales", "u1", (12,)), ("qs", "u1", (_QK_K // 2,))]
            ))
            nb = rec.shape[0]
            sc, mn = _k_scale_min(rec["scales"])
            qs = rec["qs"].reshape(nb, 4, 32)
            q = (np.stack([qs & 0xF, qs >> 4], axis=2).reshape(nb, 8, 32).astype(np.int8) - 8)
            d = rec["d"].astype(np.float32)[:, None]
            dmin = rec["dmin"].astype(np.float32)[:, None]
            scale = d * sc.astype(np.float32)  # [nb, 8]
            bias = 8.0 * scale - dmin * mn.astype(np.float32)
            return q.reshape(shape), scale.reshape(gshape), bias.reshape(gshape)
        raise ValueError(
            f"{name}: ggml type {_TYPE_NAMES.get(info.ggml_type, info.ggml_type)} "
            "has no packed int4 read path (Q4_0/Q4_K only)"
        )

    def close(self) -> None:
        self._mm.close()
        self._file.close()


_READER_CACHE: dict[str, tuple[float, GGUFReader]] = {}


def shared_reader(path: str | pathlib.Path) -> GGUFReader:
    """Process-wide cached reader, keyed by resolved path + mtime.

    Parsing a GGUF header eagerly decodes the embedded vocab (100k+ strings
    for a real model); the serve path touches the same file for config, card,
    tokenizer, and weights — one parse serves all. Borrowers must NOT close
    the returned reader; the cache owns it (an mmap held open for the life of
    the process, same cost as serving the weights from it).
    """
    key = str(pathlib.Path(path).resolve())
    mtime = os.path.getmtime(key)
    hit = _READER_CACHE.get(key)
    if hit is not None and hit[0] == mtime:
        return hit[1]
    # A stale entry is dropped, not closed: an in-flight borrower (e.g. a
    # weight load racing a file replacement) keeps a live mapping; the old
    # reader's fd/mmap are released when the last borrower lets go (GC).
    reader = GGUFReader(key)
    _READER_CACHE[key] = (mtime, reader)
    return reader


def _dequant(raw: bytes | memoryview, ggml_type: int, shape: tuple[int, ...]) -> np.ndarray:
    # The .copy() detaches from the mmap (no Python-bytes intermediate, one
    # owned allocation): returned arrays must outlive reader.close().
    if ggml_type == GGML_F32:
        return np.frombuffer(raw, dtype="<f4").reshape(shape).copy()
    if ggml_type == GGML_F16:
        return np.frombuffer(raw, dtype="<f2").reshape(shape).copy()
    if ggml_type == GGML_BF16:
        import ml_dtypes

        return np.frombuffer(raw, dtype=ml_dtypes.bfloat16).reshape(shape).copy()
    n = int(np.prod(shape))
    nb = n // _BLOCK
    if ggml_type == GGML_Q8_0:
        rec = np.frombuffer(raw, dtype=np.dtype([("d", "<f2"), ("qs", "i1", (_BLOCK,))]))
        out = rec["qs"].astype(np.float32) * rec["d"].astype(np.float32)[:, None]
        return out.reshape(shape)
    if ggml_type == GGML_Q4_0:
        rec = np.frombuffer(raw, dtype=np.dtype([("d", "<f2"), ("qs", "u1", (_BLOCK // 2,))]))
        lo = (rec["qs"] & 0x0F).astype(np.int8) - 8
        hi = (rec["qs"] >> 4).astype(np.int8) - 8
        q = np.concatenate([lo, hi], axis=1).astype(np.float32)  # [nb, 32]: elems 0..15 in low nibbles
        return (q * rec["d"].astype(np.float32)[:, None]).reshape(shape)
    if ggml_type == GGML_Q4_1:
        rec = np.frombuffer(raw, dtype=np.dtype([("d", "<f2"), ("m", "<f2"), ("qs", "u1", (_BLOCK // 2,))]))
        lo = (rec["qs"] & 0x0F).astype(np.float32)
        hi = (rec["qs"] >> 4).astype(np.float32)
        q = np.concatenate([lo, hi], axis=1)
        return (q * rec["d"].astype(np.float32)[:, None] + rec["m"].astype(np.float32)[:, None]).reshape(shape)
    if ggml_type == GGML_Q4_K:
        rec = np.frombuffer(raw, dtype=np.dtype(
            [("d", "<f2"), ("dmin", "<f2"), ("scales", "u1", (12,)), ("qs", "u1", (_QK_K // 2,))]
        ))
        nb = rec.shape[0]
        sc, mn = _k_scale_min(rec["scales"])
        qs = rec["qs"].reshape(nb, 4, 32)
        # Sub-block order within each 64-elem chunk: low nibbles then high.
        q = np.stack([qs & 0xF, qs >> 4], axis=2).reshape(nb, 8, 32).astype(np.float32)
        d = rec["d"].astype(np.float32)[:, None, None]
        dmin = rec["dmin"].astype(np.float32)[:, None, None]
        return (d * sc[:, :, None] * q - dmin * mn[:, :, None]).reshape(shape)
    if ggml_type == GGML_Q5_K:
        rec = np.frombuffer(raw, dtype=np.dtype(
            [("d", "<f2"), ("dmin", "<f2"), ("scales", "u1", (12,)),
             ("qh", "u1", (_QK_K // 8,)), ("qs", "u1", (_QK_K // 2,))]
        ))
        nb = rec.shape[0]
        sc, mn = _k_scale_min(rec["scales"])
        qs = rec["qs"].reshape(nb, 4, 32)
        qh = rec["qh"][:, None, :]  # [nb, 1, 32]
        shift = 2 * np.arange(4, dtype=np.uint8)[None, :, None]
        lo = (qs & 0xF) + (((qh >> shift) & 1) << 4)
        hi = (qs >> 4) + (((qh >> (shift + 1)) & 1) << 4)
        q = np.stack([lo, hi], axis=2).reshape(nb, 8, 32).astype(np.float32)
        d = rec["d"].astype(np.float32)[:, None, None]
        dmin = rec["dmin"].astype(np.float32)[:, None, None]
        return (d * sc[:, :, None] * q - dmin * mn[:, :, None]).reshape(shape)
    if ggml_type == GGML_Q6_K:
        rec = np.frombuffer(raw, dtype=np.dtype(
            [("ql", "u1", (_QK_K // 2,)), ("qh", "u1", (_QK_K // 4,)),
             ("scales", "i1", (_QK_K // 16,)), ("d", "<f2")]
        ))
        nb = rec.shape[0]
        ql = rec["ql"].reshape(nb, 2, 2, 32)  # [nb, half, {l, l+32}, 32]
        qh = rec["qh"].reshape(nb, 2, 32)
        # Quarters within a 128-elem half: (ql[l]&F|h0), (ql[l+32]&F|h1),
        # (ql[l]>>4|h2), (ql[l+32]>>4|h3) with h = 2-bit fields of qh[l].
        q = np.stack(
            [
                (ql[:, :, 0] & 0xF) | (((qh >> 0) & 3) << 4),
                (ql[:, :, 1] & 0xF) | (((qh >> 2) & 3) << 4),
                (ql[:, :, 0] >> 4) | (((qh >> 4) & 3) << 4),
                (ql[:, :, 1] >> 4) | (((qh >> 6) & 3) << 4),
            ],
            axis=2,
        ).astype(np.int16) - 32  # [nb, 2, 4, 32]
        sc = rec["scales"].reshape(nb, 2, 4, 2).astype(np.float32)
        scq = np.repeat(sc, 16, axis=3)  # scale index l // 16 within a quarter
        d = rec["d"].astype(np.float32)[:, None, None, None]
        return (d * scq * q).reshape(shape)
    raise ValueError(f"unsupported ggml type {ggml_type}")


def _k_scale_min(scales: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Unpack Q4_K/Q5_K 6-bit packed (scale, min) pairs: [nb, 12] u8 ->
    ([nb, 8], [nb, 8]) — ggml's get_scale_min_k4, vectorized."""
    s = scales.astype(np.uint8)
    sc = np.empty((s.shape[0], 8), np.uint8)
    mn = np.empty_like(sc)
    sc[:, :4] = s[:, 0:4] & 63
    mn[:, :4] = s[:, 4:8] & 63
    sc[:, 4:] = (s[:, 8:12] & 0xF) | ((s[:, 0:4] >> 6) << 4)
    mn[:, 4:] = (s[:, 8:12] >> 4) | ((s[:, 4:8] >> 6) << 4)
    return sc, mn


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------


def _quantize_q4_0(arr: np.ndarray) -> bytes:
    flat = np.ascontiguousarray(arr, dtype=np.float32).reshape(-1, _BLOCK)
    # llama.cpp convention: d = value-at-max-abs / -8, so q=0 hits the
    # negative extreme exactly; round the scale to its stored f16 width first.
    idx = np.abs(flat).argmax(axis=1)
    vmax = flat[np.arange(flat.shape[0]), idx]
    d = (vmax / -8.0).astype("<f2").astype(np.float32)
    inv = np.where(d != 0, 1.0 / np.where(d == 0, 1, d), 0.0)
    q = np.clip(np.rint(flat * inv[:, None]) + 8, 0, 15).astype(np.uint8)
    lo, hi = q[:, :16], q[:, 16:]
    rec = np.empty(flat.shape[0], dtype=np.dtype([("d", "<f2"), ("qs", "u1", (_BLOCK // 2,))]))
    rec["d"] = d.astype("<f2")
    rec["qs"] = lo | (hi << 4)
    return rec.tobytes()


def _quantize_q4_k(arr: np.ndarray) -> bytes:
    """Q4_K encoder: 256-elem superblocks, 8 sub-blocks of 32 with 6-bit
    quantized (scale, min) pairs against f16 super-scales — the exact
    layout ``_dequant``'s Q4_K branch (and ggml) decodes:
    ``x ≈ d*sc*q - dmin*mn`` with q in [0, 15]."""
    flat = np.ascontiguousarray(arr, dtype=np.float32).reshape(-1, 8, 32)
    nb = flat.shape[0]
    lo = flat.min(axis=2)  # [nb, 8]
    hi = flat.max(axis=2)
    mins = np.maximum(0.0, -lo)  # positive offset subtracted at decode
    scales = np.maximum(hi + mins, 1e-30) / 15.0
    d = (scales.max(axis=1) / 63.0).astype("<f2").astype(np.float32)  # [nb]
    dmin = (mins.max(axis=1) / 63.0).astype("<f2").astype(np.float32)
    inv_d = np.where(d > 0, 1.0 / np.where(d == 0, 1, d), 0.0)
    inv_dm = np.where(dmin > 0, 1.0 / np.where(dmin == 0, 1, dmin), 0.0)
    sc = np.clip(np.rint(scales * inv_d[:, None]), 0, 63).astype(np.uint8)
    mn = np.clip(np.rint(mins * inv_dm[:, None]), 0, 63).astype(np.uint8)
    eff_scale = d[:, None] * sc  # [nb, 8]
    eff_min = dmin[:, None] * mn
    denom = np.where(eff_scale > 0, eff_scale, 1.0)
    q = np.clip(
        np.rint((flat + eff_min[:, :, None]) / denom[:, :, None]), 0, 15
    ).astype(np.uint8)
    # Pack 6-bit (sc, mn): inverse of _k_scale_min.
    packed = np.empty((nb, 12), np.uint8)
    packed[:, 0:4] = (sc[:, :4] & 63) | ((sc[:, 4:] >> 4) << 6)
    packed[:, 4:8] = (mn[:, :4] & 63) | ((mn[:, 4:] >> 4) << 6)
    packed[:, 8:12] = (sc[:, 4:] & 0xF) | ((mn[:, 4:] & 0xF) << 4)
    # Pack nibbles: chunk c holds sub-blocks (2c, 2c+1) as (low, high).
    q4 = q.reshape(nb, 4, 2, 32)
    qs = (q4[:, :, 0] | (q4[:, :, 1] << 4)).reshape(nb, 128)
    rec = np.empty(nb, dtype=np.dtype(
        [("d", "<f2"), ("dmin", "<f2"), ("scales", "u1", (12,)), ("qs", "u1", (_QK_K // 2,))]
    ))
    rec["d"] = d.astype("<f2")
    rec["dmin"] = dmin.astype("<f2")
    rec["scales"] = packed
    rec["qs"] = qs
    return rec.tobytes()


def _quantize_q8_0(arr: np.ndarray) -> bytes:
    flat = np.ascontiguousarray(arr, dtype=np.float32).reshape(-1, _BLOCK)
    amax = np.abs(flat).max(axis=1)
    # Round the scale to its stored f16 width *before* quantizing, so the
    # quants are optimal for the scale the reader will actually use.
    d = (amax / 127.0).astype("<f2").astype(np.float32)
    inv = np.where(d > 0, 1.0 / np.where(d == 0, 1, d), 0.0)
    qs = np.clip(np.rint(flat * inv[:, None]), -127, 127).astype(np.int8)
    rec = np.empty(flat.shape[0], dtype=np.dtype([("d", "<f2"), ("qs", "i1", (_BLOCK,))]))
    rec["d"] = d.astype("<f2")
    rec["qs"] = qs
    return rec.tobytes()


def _write_string(f: BinaryIO, s: str) -> None:
    b = s.encode("utf-8")
    f.write(struct.pack("<Q", len(b)))
    f.write(b)


def _write_value(f: BinaryIO, v: Any) -> None:
    if isinstance(v, bool):
        f.write(struct.pack("<I", T_BOOL) + struct.pack("<B", int(v)))
    elif isinstance(v, int):
        f.write(struct.pack("<I", T_U32 if 0 <= v < 2**32 else T_I64))
        f.write(struct.pack("<I" if 0 <= v < 2**32 else "<q", v))
    elif isinstance(v, float):
        f.write(struct.pack("<I", T_F32) + struct.pack("<f", v))
    elif isinstance(v, str):
        f.write(struct.pack("<I", T_STR))
        _write_string(f, v)
    elif isinstance(v, (list, tuple)):
        f.write(struct.pack("<I", T_ARR))
        if not v:
            f.write(struct.pack("<IQ", T_I32, 0))
        elif all(isinstance(e, str) for e in v):
            f.write(struct.pack("<IQ", T_STR, len(v)))
            for s in v:
                _write_string(f, s)
        elif any(isinstance(e, float) for e in v):  # mixed int/float -> f32
            f.write(struct.pack("<IQ", T_F32, len(v)))
            f.write(np.asarray(v, dtype="<f4").tobytes())
        elif all(isinstance(e, (int, bool)) for e in v):
            f.write(struct.pack("<IQ", T_I32, len(v)))
            f.write(np.asarray(v, dtype="<i4").tobytes())
        else:
            raise TypeError(f"cannot serialize mixed-type metadata array: {v[:4]!r}...")
    else:
        raise TypeError(f"cannot serialize metadata value of type {type(v)}")


def write_gguf(
    path: str | pathlib.Path,
    metadata: dict[str, Any],
    tensors: dict[str, np.ndarray],
    *,
    quant: dict[str, int] | int | None = None,
    align: int = 32,
    raw_tensors: dict[str, tuple[tuple[int, ...], int, bytes]] | None = None,
) -> None:
    """Write a GGUF v3 file. ``quant`` selects ggml storage per tensor
    (a single type for all, or a per-name map); default stores float tensors
    in their native width (f32/f16/bf16). ``raw_tensors`` carries
    pre-encoded payloads as ``name -> (shape, ggml_type, bytes)`` — the
    passthrough for block formats this writer has no encoder for
    (K-quants), used by re-export tooling and fixtures."""
    import ml_dtypes

    # A caller round-tripping reader.metadata would otherwise duplicate the
    # alignment key with a conflicting value — the reader's last-wins parse
    # would then compute a data offset the writer never used.
    metadata = dict(metadata)
    align = int(metadata.pop("general.alignment", align))

    def ttype(name: str, arr: np.ndarray) -> int:
        if isinstance(quant, int):
            q = quant
        elif isinstance(quant, dict):
            q = quant.get(name, -1)
        else:
            q = -1
        if q >= 0:
            if q in (GGML_Q4_1, GGML_Q5_K, GGML_Q6_K):
                raise ValueError("writer supports Q8_0/Q4_0/Q4_K quantization; Q4_1/Q5_K/Q6_K are read-only")
            n = int(np.prod(arr.shape))
            if q in (GGML_Q8_0, GGML_Q4_0) and n % _BLOCK:
                q = GGML_F16  # not blockable; fall back
            if q == GGML_Q4_K and n % _QK_K:
                q = GGML_F16  # superblocks need 256-elem multiples
            return q
        if arr.dtype == np.float16:
            return GGML_F16
        if arr.dtype == ml_dtypes.bfloat16:
            return GGML_BF16
        return GGML_F32

    def payload(arr: np.ndarray, t: int) -> bytes:
        if t == GGML_F32:
            return np.ascontiguousarray(arr, dtype="<f4").tobytes()
        if t == GGML_F16:
            return np.ascontiguousarray(arr, dtype="<f2").tobytes()
        if t == GGML_BF16:
            return np.ascontiguousarray(arr.astype(ml_dtypes.bfloat16)).tobytes()
        if t == GGML_Q8_0:
            return _quantize_q8_0(arr)
        if t == GGML_Q4_0:
            return _quantize_q4_0(arr)
        if t == GGML_Q4_K:
            return _quantize_q4_k(arr)
        raise ValueError(f"writer does not support ggml type {t} (readable-only format)")

    blobs: list[tuple[str, tuple[int, ...], int, bytes]] = []
    for name, arr in tensors.items():
        t = ttype(name, np.asarray(arr))
        blobs.append((name, np.asarray(arr).shape, t, payload(np.asarray(arr), t)))
    for name, (shape, t, data) in (raw_tensors or {}).items():
        bpb, epb = _TYPE_SIZES[t]
        expect = int(np.prod(shape)) // epb * bpb
        if len(data) != expect:
            raise ValueError(f"raw tensor {name}: {len(data)} bytes != {expect} for shape {shape}")
        blobs.append((name, tuple(shape), t, data))

    with open(path, "wb") as f:
        f.write(MAGIC)
        f.write(struct.pack("<IQQ", 3, len(blobs), len(metadata) + 1))
        _write_string(f, "general.alignment")
        f.write(struct.pack("<II", T_U32, align))
        for key, val in metadata.items():
            _write_string(f, key)
            _write_value(f, val)
        offset = 0
        for name, shape, t, data in blobs:
            _write_string(f, name)
            dims = tuple(reversed(shape))  # ggml order: innermost first
            f.write(struct.pack("<I", len(dims)))
            for d in dims:
                f.write(struct.pack("<Q", d))
            f.write(struct.pack("<IQ", t, offset))
            offset += (len(data) + align - 1) // align * align
        pad = (-f.tell()) % align
        f.write(b"\x00" * pad)
        for _name, _shape, _t, data in blobs:
            f.write(data)
            f.write(b"\x00" * ((-len(data)) % align))


# ---------------------------------------------------------------------------
# Metadata -> ModelConfig
# ---------------------------------------------------------------------------


def config_from_gguf(reader: GGUFReader, *, name: str | None = None) -> ModelConfig:
    """Map ``{arch}.*`` GGUF metadata keys onto :class:`ModelConfig`."""
    md = reader.metadata
    arch = md.get("general.architecture")
    if not arch:
        raise ValueError("GGUF file missing required `general.architecture` metadata")

    def get(key: str, default: Any = None) -> Any:
        value = md.get(f"{arch}.{key}", default)
        # Some exports store per-layer lists for scalar-shaped keys
        # (head_count, feed_forward_length, ...); take the first layer.
        if isinstance(value, list) and value:
            return value[0]
        return value

    heads = int(get("attention.head_count", 1))
    hidden = int(get("embedding_length", 0))
    kv_heads = get("attention.head_count_kv", heads)
    vocab = get("vocab_size")
    if vocab is None:
        toks = md.get("tokenizer.ggml.tokens")
        vocab = len(toks) if toks else 32000
    head_dim = int(get("attention.key_length", hidden // max(heads, 1)))
    # Gemma GGUFs: GeGLU + scaled embeddings come from the arch; the (1+w)
    # norm convention does NOT apply — llama.cpp's converter bakes the +1
    # into the exported norm weights.
    gemma = arch == "gemma"
    tied = "output.weight" not in reader.tensors
    # Rope scaling: GGUF stores {arch}.rope.scaling.* (llama.cpp key names);
    # map onto the HF-schema dict rope_frequencies consumes. Llama-3-style
    # GGUFs don't carry the low/high freq factors, so use the published
    # Llama-3 defaults when the type asks for them.
    rope_scaling = None
    sc_type = get("rope.scaling.type")
    if sc_type and sc_type != "none":
        rope_scaling = {
            "rope_type": sc_type,
            "factor": float(get("rope.scaling.factor", 1.0)),
            "original_max_position_embeddings": int(
                get("rope.scaling.original_context_length", get("context_length", 4096))
            ),
            "low_freq_factor": float(get("rope.scaling.low_freq_factor", 1.0)),
            "high_freq_factor": float(get("rope.scaling.high_freq_factor", 4.0)),
        }
        # YaRN extras (attn_factor is llama.cpp's key; betas are ours).
        if get("rope.scaling.attn_factor") is not None:
            rope_scaling["attention_factor"] = float(get("rope.scaling.attn_factor"))
        for beta in ("beta_fast", "beta_slow"):
            if get(f"rope.scaling.{beta}") is not None:
                rope_scaling[beta] = float(get(f"rope.scaling.{beta}"))
    shared_ffn = int(get("expert_shared_feed_forward_length", 0))
    if shared_ffn == 0 and "blk.0.ffn_gate_shexp.weight" in reader.tensors:
        shared_ffn = reader.tensors["blk.0.ffn_gate_shexp.weight"].shape[0]
    return ModelConfig(
        name=name or md.get("general.name", arch),
        vocab_size=int(vocab),
        hidden_size=hidden,
        num_layers=int(get("block_count", 0)),
        num_heads=heads,
        num_kv_heads=int(kv_heads),
        head_dim=head_dim,
        intermediate_size=int(get("feed_forward_length", 0)),
        rope_theta=float(get("rope.freq_base", 10000.0)),
        rope_scaling=rope_scaling,
        rms_eps=float(get("attention.layer_norm_rms_epsilon", 1e-5)),
        max_position=int(get("context_length", 4096)),
        tie_embeddings=tied,
        mlp_act="gelu_tanh" if gemma else "silu",
        embed_scale=gemma,  # norm_plus_one deliberately NOT set (see above)
        num_experts=int(get("expert_count", 0)),
        num_experts_per_token=int(get("expert_used_count", 0)),
        moe_intermediate_size=int(get("expert_feed_forward_length", 0)),
        shared_expert_size=shared_ffn,
        shared_expert_gated="blk.0.ffn_gate_inp_shexp.weight" in reader.tensors,
        attention_bias="blk.0.attn_q.bias" in reader.tensors,
        # Q/K RMS norms: present as blk.N.attn_{q,k}_norm.weight. The WIDTH
        # distinguishes the style — per-head (Qwen3) vs full projection
        # width (OLMoE) — so detection is shape-driven, not arch-name-driven.
        qk_norm=(
            ""
            if "blk.0.attn_q_norm.weight" not in reader.tensors
            else (
                "head"
                if reader.tensors["blk.0.attn_q_norm.weight"].shape[-1] == head_dim
                else "flat"
            )
        ),
    )


# ---------------------------------------------------------------------------
# Embedded tokenizer -> tokenizers object
# ---------------------------------------------------------------------------


def tokenizer_from_gguf(reader: GGUFReader):
    """Rebuild the embedded tokenizer as a BaseTokenizer.

    GGUF stores the vocab inline (``tokenizer.ggml.*``): SentencePiece-style
    unigram for ``model=llama``, byte-level BPE for ``model=gpt2``.
    """
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers

    from dynamo_tpu.tokenizer import HfTokenizer

    md = reader.metadata
    kind = md.get("tokenizer.ggml.model", "llama")
    tokens: list[str] = md.get("tokenizer.ggml.tokens") or []
    if not tokens:
        raise ValueError("GGUF file has no embedded tokenizer (tokenizer.ggml.tokens)")
    bos = md.get("tokenizer.ggml.bos_token_id")
    eos = md.get("tokenizer.ggml.eos_token_id")
    if kind == "llama":
        scores = md.get("tokenizer.ggml.scores")
        if scores is None:
            raise ValueError("`llama` unigram tokenizer requires tokenizer.ggml.scores")
        unk = int(md.get("tokenizer.ggml.unknown_token_id", 0))
        tk = Tokenizer(models.Unigram(list(zip(tokens, map(float, scores))), unk_id=unk, byte_fallback=True))
        tk.pre_tokenizer = pre_tokenizers.Metaspace(replacement="▁", prepend_scheme="first")
        tk.decoder = decoders.Sequence(
            [decoders.Replace("▁", " "), decoders.ByteFallback(), decoders.Fuse(), decoders.Strip(" ", 1, 0)]
        )
    elif kind == "gpt2":
        merges_raw = md.get("tokenizer.ggml.merges") or []
        merges = [tuple(m.split(" ", 1)) for m in merges_raw]
        vocab = {tok: i for i, tok in enumerate(tokens)}
        tk = Tokenizer(models.BPE(vocab=vocab, merges=merges, fuse_unk=False))
        tk.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False, use_regex=True)
        tk.decoder = decoders.ByteLevel()
    else:
        raise ValueError(f"unsupported GGUF tokenizer model {kind!r}")
    # tokenizer.ggml.token_type marks CONTROL (=3) tokens — BOS/EOS/<|im_end|>
    # etc. Register them as special so `skip_special_tokens` decoding actually
    # skips them (unregistered, they'd leak into generated text).
    token_types = md.get("tokenizer.ggml.token_type")
    if token_types:
        from tokenizers import AddedToken

        control = [
            AddedToken(tok, special=True, normalized=False)
            for tok, tt in zip(tokens, token_types)
            if tt == 3
        ]
        if control:
            tk.add_special_tokens(control)
    return HfTokenizer(
        tk,
        eos_token_ids={int(eos)} if eos is not None else None,
        bos_token_id=int(bos) if bos is not None else None,
    )


# ---------------------------------------------------------------------------
# Tensor name mapping -> stacked params pytree
# ---------------------------------------------------------------------------

# leaf name -> (gguf suffix, transpose?)
_GGUF_LAYER_MAP: dict[str, tuple[str, bool]] = {
    "attn_norm": ("attn_norm.weight", False),
    "mlp_norm": ("ffn_norm.weight", False),
    "wq": ("attn_q.weight", True),
    "wk": ("attn_k.weight", True),
    "wv": ("attn_v.weight", True),
    "wo": ("attn_output.weight", True),
    "w_gate": ("ffn_gate.weight", True),
    "w_up": ("ffn_up.weight", True),
    "w_down": ("ffn_down.weight", True),
}
_GGUF_BIAS_MAP = {"bq": "attn_q.bias", "bk": "attn_k.bias", "bv": "attn_v.bias"}
# Architectures whose GGUFs use GGML NORM (interleaved-pair) rope and whose
# Q/K were therefore permuted by llama.cpp's converter. Mistral/Mixtral are
# written under arch "llama"; qwen2/deepseek2 are NEOX (unpermuted).
_NORM_ROPE_ARCHS = {"llama"}
# MoE: experts are pre-stacked 3D tensors in GGUF ([E, out, in] in numpy order).
_GGUF_MOE_MAP: dict[str, str] = {
    "w_gate": "ffn_gate_exps.weight",
    "w_up": "ffn_up_exps.weight",
    "w_down": "ffn_down_exps.weight",
}
_GGUF_SHARED_MAP: dict[str, tuple[str, bool]] = {
    "w_shared_gate": ("ffn_gate_shexp.weight", True),
    "w_shared_up": ("ffn_up_shexp.weight", True),
    "w_shared_down": ("ffn_down_shexp.weight", True),
}


def _pack_nibble_rows(q: np.ndarray) -> np.ndarray:
    """[..., d_in, O] int4-valued int8 -> [..., d_in//2, O] packed bytes,
    element ``2i`` in the low nibble of byte ``i`` — the layout
    ``models/quant.unpack_int4`` expects (numpy twin of ``pack_int4``)."""
    lo, hi = q[..., 0::2, :], q[..., 1::2, :]
    return ((hi.astype(np.uint8) << 4) | (lo.astype(np.uint8) & 0x0F)).astype(np.int8)


def load_gguf_params(
    source: str | pathlib.Path | GGUFReader,
    cfg: ModelConfig,
    *,
    mesh: Any | None = None,
    dtype: Any | None = None,
    quantize: str = "",
) -> dict:
    """GGUF file -> stacked params pytree (optionally sharded onto ``mesh``).

    Tensors are dequantized on host, layer-stacked, cast, and placed. GGUF
    checkpoints are single-file and quant-compressed, so unlike the
    safetensors path (`loader.load_params`) there is no per-shard lazy read —
    peak host memory is one dequantized leaf.

    ``quantize="int4"`` imports Q4_0/Q4_K matmul tensors DIRECTLY into
    packed int4 leaves (``{"qw4", "scale"[, "qbias"]}`` — see
    ``models/quant``) instead of round-tripping through full-width bf16:
    the checkpoint's own 4-bit codes and group scales are repacked
    losslessly, so the serve path streams 0.5 byte/elem where the dequant
    path would forfeit the checkpoint's bandwidth win. Tensors stored at
    other ggml types (and any leaf whose layers mix types) fall back to the
    dequant path; the caller's ``quantize_params`` pass picks those up.
    """
    import jax
    import jax.numpy as jnp
    import ml_dtypes

    reader = source if isinstance(source, GGUFReader) else shared_reader(source)
    want = str(dtype or cfg.dtype)
    np_dtype = ml_dtypes.bfloat16 if want == "bfloat16" else np.dtype(jnp.dtype(want).name)

    # llama.cpp's converter permutes llama-family Q/K weights (arch "llama"
    # covers Mistral/Mixtral too) into GGML NORM-rope interleaved-pair order;
    # ops/rope.apply_rope uses the half-split (NEOX/HF) convention, so invert
    # that permutation at load (qwen2 etc. are NEOX in GGUF — no permute).
    from dynamo_tpu.models.loader import rope_load_perm

    arch = reader.metadata.get("general.architecture")
    qk_perms: dict[str, np.ndarray] = {}
    if arch in _NORM_ROPE_ARCHS:
        qk_perms = {
            "wq": rope_load_perm(cfg.num_heads, cfg.head_dim, cfg.head_dim),
            "wk": rope_load_perm(cfg.num_kv_heads, cfg.head_dim, cfg.head_dim),
            "bq": rope_load_perm(cfg.num_heads, cfg.head_dim, cfg.head_dim),
            "bk": rope_load_perm(cfg.num_kv_heads, cfg.head_dim, cfg.head_dim),
        }

    def rd(name: str, transpose: bool, perm: np.ndarray | None = None) -> np.ndarray:
        arr = reader.read(name)
        if perm is not None:  # permute GGML rows (pre-transpose orientation)
            arr = arr[perm]
        return arr.T if transpose else arr

    packed_q4 = quantize == "int4"

    def rd_packed(name: str, perm: np.ndarray | None = None, moe: bool = False) -> dict | None:
        """Q4_0/Q4_K -> packed int4 leaf in model orientation, else None.

        The rope permutation applies to GGML rows = output channels, which
        become the leaf's last axis after transpose — compatible with the
        group scales, whose groups run along the contraction axis.
        """
        info = reader.tensors.get(name)
        if info is None or info.ggml_type not in (GGML_Q4_0, GGML_Q4_K):
            return None
        q, scale, bias = reader.read_q4(name)
        if perm is not None:
            q, scale = q[perm], scale[perm]
            bias = bias[perm] if bias is not None else None
        tr = (lambda a: a.transpose(0, 2, 1)) if moe else (lambda a: a.T)
        leaf = {
            "qw4": _pack_nibble_rows(np.ascontiguousarray(tr(q))),
            "scale": np.ascontiguousarray(tr(scale)),
        }
        if bias is not None:
            leaf["qbias"] = np.ascontiguousarray(tr(bias))
        return leaf

    L = cfg.num_layers
    layers: dict[str, Any] = {}

    def stack(leaf: str, suffix: str, transpose: bool) -> np.ndarray:
        perm = qk_perms.get(leaf)
        return np.stack([rd(f"blk.{li}.{suffix}", transpose, perm) for li in range(L)]).astype(np_dtype, copy=False)

    def stack_packed(leaf: str, suffix: str, moe: bool = False) -> dict | None:
        """Layer-stacked packed leaf, or None if any layer can't pack (or
        the layers mix Q4_0 with Q4_K — stacking needs uniform keys)."""
        perm = qk_perms.get(leaf)
        per_layer = []
        for li in range(L):
            d = rd_packed(f"blk.{li}.{suffix}", perm, moe=moe)
            if d is None or (per_layer and set(d) != set(per_layer[0])):
                return None
            per_layer.append(d)
        return {k: np.stack([d[k] for d in per_layer]) for k in per_layer[0]}

    for leaf, (suffix, t) in _GGUF_LAYER_MAP.items():
        if leaf in ("w_gate", "w_up", "w_down") and cfg.is_moe:
            continue
        packed = stack_packed(leaf, suffix) if packed_q4 and t else None
        layers[leaf] = packed if packed is not None else stack(leaf, suffix, t)
    if cfg.attention_bias:
        for leaf, suffix in _GGUF_BIAS_MAP.items():
            layers[leaf] = stack(leaf, suffix, False)
    if cfg.qk_norm:
        layers["q_norm"] = stack("q_norm", "attn_q_norm.weight", False)
        layers["k_norm"] = stack("k_norm", "attn_k_norm.weight", False)
    if cfg.is_moe:
        layers["router"] = stack("router", "ffn_gate_inp.weight", True)
        for leaf, suffix in _GGUF_MOE_MAP.items():
            packed = stack_packed(leaf, suffix, moe=True) if packed_q4 else None
            if packed is not None:
                layers[leaf] = packed
                continue
            # [E, out, in] per layer -> transpose within-expert to [E, in, out]
            arrs = [reader.read(f"blk.{li}.{suffix}").transpose(0, 2, 1) for li in range(L)]
            layers[leaf] = np.stack(arrs).astype(np_dtype, copy=False)
        if cfg.shared_expert_size and "blk.0.ffn_gate_shexp.weight" in reader:
            for leaf, (suffix, t) in _GGUF_SHARED_MAP.items():
                packed = stack_packed(leaf, suffix) if packed_q4 and t else None
                layers[leaf] = packed if packed is not None else stack(leaf, suffix, t)
            if cfg.shared_expert_gated and "blk.0.ffn_gate_inp_shexp.weight" in reader:
                layers["shared_gate"] = stack("shared_gate", "ffn_gate_inp_shexp.weight", True)

    params: dict[str, Any] = {
        "embed": rd("token_embd.weight", False).astype(np_dtype, copy=False),
        "norm_f": rd("output_norm.weight", False).astype(np_dtype, copy=False),
        "layers": layers,
    }
    if not cfg.tie_embeddings:
        lm = "output.weight" if "output.weight" in reader else "token_embd.weight"
        packed = rd_packed(lm) if packed_q4 else None
        params["lm_head"] = packed if packed is not None else rd(lm, True).astype(np_dtype, copy=False)

    if mesh is None:
        return jax.tree.map(jnp.asarray, params)
    from dynamo_tpu.parallel.sharding import param_shardings

    shardings = param_shardings(mesh, params)
    return jax.tree.map(lambda leaf, s: jax.device_put(leaf, s), params, shardings)


def save_params_gguf(
    path: str | pathlib.Path,
    cfg: ModelConfig,
    params: dict,
    *,
    quant: dict[str, int] | int | None = None,
    tokenizer_metadata: dict[str, Any] | None = None,
) -> None:
    """Reverse mapping: params pytree -> GGUF file (tests / export tool)."""
    import jax

    host = jax.tree.map(np.asarray, params)
    arch = "llama"
    md: dict[str, Any] = {
        "general.architecture": arch,
        "general.name": cfg.name,
        f"{arch}.embedding_length": cfg.hidden_size,
        f"{arch}.block_count": cfg.num_layers,
        f"{arch}.attention.head_count": cfg.num_heads,
        f"{arch}.attention.head_count_kv": cfg.num_kv_heads,
        f"{arch}.attention.key_length": cfg.head_dim,
        f"{arch}.feed_forward_length": cfg.intermediate_size,
        f"{arch}.rope.freq_base": float(cfg.rope_theta),
        f"{arch}.attention.layer_norm_rms_epsilon": float(cfg.rms_eps),
        f"{arch}.context_length": cfg.max_position,
        f"{arch}.vocab_size": cfg.vocab_size,
    }
    if cfg.rope_scaling:
        sc = cfg.rope_scaling
        md[f"{arch}.rope.scaling.type"] = str(sc.get("rope_type", sc.get("type", "linear")))
        md[f"{arch}.rope.scaling.factor"] = float(sc.get("factor", 1.0))
        if "original_max_position_embeddings" in sc:
            md[f"{arch}.rope.scaling.original_context_length"] = int(sc["original_max_position_embeddings"])
        for key in ("low_freq_factor", "high_freq_factor", "beta_fast", "beta_slow"):
            if key in sc:
                md[f"{arch}.rope.scaling.{key}"] = float(sc[key])
        if "attention_factor" in sc:
            md[f"{arch}.rope.scaling.attn_factor"] = float(sc["attention_factor"])
    if cfg.is_moe:
        md[f"{arch}.expert_count"] = cfg.num_experts
        md[f"{arch}.expert_used_count"] = cfg.num_experts_per_token
        md[f"{arch}.expert_feed_forward_length"] = cfg.moe_intermediate_size
        if cfg.shared_expert_size:
            md[f"{arch}.expert_shared_feed_forward_length"] = cfg.shared_expert_size
    md.update(tokenizer_metadata or {})

    tensors: dict[str, np.ndarray] = {
        "token_embd.weight": host["embed"],
        "output_norm.weight": host["norm_f"],
    }
    if "lm_head" in host:
        tensors["output.weight"] = np.ascontiguousarray(host["lm_head"].T)
    layers = host["layers"]
    # Exports are written under arch "llama": permute Q/K (and their biases)
    # from the half-split runtime convention back to GGML NORM interleaved
    # order so llama.cpp-ecosystem consumers rope them correctly.
    from dynamo_tpu.models.loader import rope_save_perm

    save_perms = {
        "wq": rope_save_perm(cfg.num_heads, cfg.head_dim, cfg.head_dim),
        "wk": rope_save_perm(cfg.num_kv_heads, cfg.head_dim, cfg.head_dim),
        "bq": rope_save_perm(cfg.num_heads, cfg.head_dim, cfg.head_dim),
        "bk": rope_save_perm(cfg.num_kv_heads, cfg.head_dim, cfg.head_dim),
    }
    for li in range(cfg.num_layers):
        if cfg.qk_norm:
            tensors[f"blk.{li}.attn_q_norm.weight"] = np.ascontiguousarray(layers["q_norm"][li])
            tensors[f"blk.{li}.attn_k_norm.weight"] = np.ascontiguousarray(layers["k_norm"][li])
        for leaf, (suffix, t) in _GGUF_LAYER_MAP.items():
            if leaf not in layers:
                continue
            arr = layers[leaf][li]
            if t:
                arr = arr.T
            if leaf in save_perms:
                arr = arr[save_perms[leaf]]
            tensors[f"blk.{li}.{suffix}"] = np.ascontiguousarray(arr)
        for leaf, suffix in _GGUF_BIAS_MAP.items():
            if leaf in layers:
                arr = layers[leaf][li]
                if leaf in save_perms:
                    arr = arr[save_perms[leaf]]
                tensors[f"blk.{li}.{suffix}"] = arr
        if "router" in layers:
            tensors[f"blk.{li}.ffn_gate_inp.weight"] = np.ascontiguousarray(layers["router"][li].T)
            for leaf, suffix in _GGUF_MOE_MAP.items():
                tensors[f"blk.{li}.{suffix}"] = np.ascontiguousarray(layers[leaf][li].transpose(0, 2, 1))
            for leaf, (suffix, t) in _GGUF_SHARED_MAP.items():
                if leaf in layers:
                    tensors[f"blk.{li}.{suffix}"] = np.ascontiguousarray(layers[leaf][li].T)
            if "shared_gate" in layers:
                tensors[f"blk.{li}.ffn_gate_inp_shexp.weight"] = np.ascontiguousarray(layers["shared_gate"][li].T)
    # Norm vectors and biases aren't blockable/meaningfully quantizable; apply
    # `quant` only to matrices.
    qmap: dict[str, int] | None = None
    if isinstance(quant, dict):
        qmap = {n: q for n, q in quant.items() if np.asarray(tensors[n]).ndim >= 2}
    elif quant is not None:
        qmap = {n: quant for n, a in tensors.items() if np.asarray(a).ndim >= 2}
    write_gguf(path, md, {n: np.asarray(a, dtype=np.float32) for n, a in tensors.items()}, quant=qmap)


def _main() -> None:  # pragma: no cover - CLI convenience
    import argparse
    import json

    ap = argparse.ArgumentParser(prog="python -m dynamo_tpu.models.gguf")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_info = sub.add_parser("info", help="print GGUF metadata + tensor index")
    p_info.add_argument("file")
    args = ap.parse_args()
    if args.cmd == "info":
        r = GGUFReader(args.file)
        meta = {k: (v if not isinstance(v, list) or len(v) <= 8 else f"[{len(v)} items]")
                for k, v in r.metadata.items()}
        print(json.dumps({"version": r.version, "metadata": meta,
                          "tensors": {n: {"shape": list(t.shape), "type": _TYPE_NAMES.get(t.ggml_type, t.ggml_type)}
                                      for n, t in r.tensors.items()}}, indent=2))


if __name__ == "__main__":  # pragma: no cover
    _main()
