"""Qwen2-VL: 2D-rope ViT vision tower, patch merger, and M-RoPE indices.

The reference's primary multimodal family
(`examples/multimodal/components/encode_worker.py:61-179` serves Qwen2-VL
through HF). Architecture (vs the CLIP/LLaVA tower in `models/vision.py`):

- **Native-resolution patching**: images resize to multiples of
  ``patch_size * spatial_merge_size`` (smart_resize) instead of a fixed
  square; the patch sequence length varies per image and a ``(t, h, w)``
  grid describes it. Patches flatten in MERGE-GROUP order (each 2x2 spatial
  group contiguous) with the temporal axis folded into the patch dim
  (temporal_patch_size=2 — a still image is duplicated).
- **2D rotary embeddings** in the tower: each patch's rope angle vector is
  ``[freqs(h_pos), freqs(w_pos)]`` over head_dim/2, applied in the
  half-split (rotate_half) convention. No learned position embeddings, no
  CLS token.
- **Patch merger**: LayerNorm then each 2x2 group's features concatenate
  ([4*D]) through a 2-layer MLP into the LLM hidden size — so the LLM sees
  ``t*h*w/4`` tokens per image.
- **M-RoPE** in the LLM: position ids are 3D (temporal, height, width).
  Text tokens carry equal coords (reduces exactly to 1D rope); image spans
  carry grid coords. :func:`mrope_position_ids` mirrors HF
  ``get_rope_index`` (modeling_qwen2_vl.py); the rope application lives in
  ``ops/rope.apply_mrope``.

TPU notes: everything below is static-shaped per (grid) — one jit
specialization per distinct image geometry; the serving encoder bounds the
per-grid program cache with LRU eviction (encode.py). Attention is dense
over one image's patches (a few hundred to a few thousand tokens) —
MXU-friendly einsums, no paging needed.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np

Params = dict


@dataclasses.dataclass(frozen=True)
class Qwen2VLVisionConfig:
    embed_dim: int = 1280
    depth: int = 32
    num_heads: int = 16
    mlp_ratio: float = 4.0
    patch_size: int = 14
    temporal_patch_size: int = 2
    spatial_merge_size: int = 2
    in_channels: int = 3
    out_dim: int = 3584  # LLM hidden size
    act: str = "quick_gelu"
    ln_eps: float = 1e-6
    # Qwen2-VL image processor statistics (OPENAI_CLIP).
    image_mean: tuple = (0.48145466, 0.4578275, 0.40821073)
    image_std: tuple = (0.26862954, 0.26130258, 0.27577711)
    min_pixels: int = 56 * 56
    max_pixels: int = 14 * 14 * 4 * 1280

    @property
    def patch_dim(self) -> int:
        return self.in_channels * self.temporal_patch_size * self.patch_size**2

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def mlp_hidden(self) -> int:
        return int(self.embed_dim * self.mlp_ratio)

    @property
    def merge_dim(self) -> int:
        return self.embed_dim * self.spatial_merge_size**2

    def merged_tokens(self, grid: tuple[int, int, int]) -> int:
        t, h, w = grid
        return t * h * w // self.spatial_merge_size**2

    @classmethod
    def from_hf(cls, config: dict) -> "Qwen2VLVisionConfig":
        """HF ``Qwen2VLConfig.vision_config`` dict -> Qwen2VLVisionConfig."""
        v = config["vision_config"]
        t = config.get("text_config", config)
        return cls(
            embed_dim=v.get("embed_dim", v.get("hidden_size", 1280)),
            depth=v.get("depth", 32),
            num_heads=v.get("num_heads", 16),
            mlp_ratio=float(v.get("mlp_ratio", 4.0)),
            patch_size=v.get("patch_size", 14),
            temporal_patch_size=v.get("temporal_patch_size", 2),
            spatial_merge_size=v.get("spatial_merge_size", 2),
            in_channels=v.get("in_channels", 3),
            # HF names the OUTPUT dim "hidden_size" on the vision config
            # when embed_dim is present (Qwen2-VL quirk).
            out_dim=t["hidden_size"],
            act=v.get("hidden_act", "quick_gelu"),
        )


TEST_TINY_QWEN2VL_VISION = Qwen2VLVisionConfig(
    embed_dim=32, depth=2, num_heads=2, patch_size=4, out_dim=64,
    min_pixels=4 * 4 * 4, max_pixels=4 * 4 * 4 * 1280,
)


def init_qwen2vl_vision_params(cfg: Qwen2VLVisionConfig, rng: jax.Array | int = 0) -> Params:
    if isinstance(rng, int):
        rng = jax.random.PRNGKey(rng)
    ks = jax.random.split(rng, 4)
    d, mlp, md = cfg.embed_dim, cfg.mlp_hidden, cfg.merge_dim

    def w(key, shape, fan_in):
        return jax.random.normal(key, shape, jnp.float32) * (fan_in**-0.5)

    def layer(key):
        lk = jax.random.split(key, 4)
        return {
            "ln1": jnp.ones(d), "ln1_b": jnp.zeros(d),
            "ln2": jnp.ones(d), "ln2_b": jnp.zeros(d),
            "wqkv": w(lk[0], (d, 3 * d), d), "bqkv": jnp.zeros(3 * d),
            "wo": w(lk[1], (d, d), d), "bo": jnp.zeros(d),
            "w1": w(lk[2], (d, mlp), d), "b1": jnp.zeros(mlp),
            "w2": w(lk[3], (mlp, d), mlp), "b2": jnp.zeros(d),
        }

    layer_keys = jax.random.split(ks[3], cfg.depth)
    return {
        "patch_embed": w(ks[0], (cfg.patch_dim, d), cfg.patch_dim),
        "merger_ln": jnp.ones(d), "merger_ln_b": jnp.zeros(d),
        "merger_w1": w(ks[1], (md, md), md), "merger_b1": jnp.zeros(md),
        "merger_w2": w(ks[2], (md, cfg.out_dim), md), "merger_b2": jnp.zeros(cfg.out_dim),
        "layers": jax.tree.map(lambda *xs: jnp.stack(xs), *[layer(k) for k in layer_keys]),
    }


def _ln(x, g, b, eps):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


def _vision_rope_angles(cfg: Qwen2VLVisionConfig, grid: tuple[int, int, int]) -> np.ndarray:
    """Per-patch rope angle vector [S, head_dim/2] = [freqs(h), freqs(w)],
    with h/w indices in the same merge-group order the patches arrive in
    (HF ``rot_pos_emb``)."""
    t, h, w = grid
    m = cfg.spatial_merge_size
    hpos = np.broadcast_to(np.arange(h)[:, None], (h, w))
    wpos = np.broadcast_to(np.arange(w)[None, :], (h, w))

    def merge_order(a):
        return a.reshape(h // m, m, w // m, m).transpose(0, 2, 1, 3).reshape(-1)

    hpos, wpos = merge_order(hpos), merge_order(wpos)
    dim = cfg.head_dim // 2  # angles per coordinate axis: dim/2 freqs each
    inv_freq = 1.0 / (10000.0 ** (np.arange(0, dim, 2, dtype=np.float64) / dim))
    angles = np.concatenate(
        [hpos[:, None] * inv_freq, wpos[:, None] * inv_freq], axis=1
    )  # [h*w, head_dim/2]
    return np.tile(angles, (t, 1)).astype(np.float32)


def _rotate(x: jnp.ndarray, angles: jnp.ndarray) -> jnp.ndarray:
    """Half-split rotation of [S, H, hd] by per-token angles [S, hd/2]."""
    cos = jnp.cos(angles)[:, None, :]
    sin = jnp.sin(angles)[:, None, :]
    x32 = x.astype(jnp.float32)
    half = x.shape[-1] // 2
    x1, x2 = x32[..., :half], x32[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], -1).astype(x.dtype)


def encode_qwen2vl(
    params: Params,
    cfg: Qwen2VLVisionConfig,
    patches: jnp.ndarray,  # [S, patch_dim] flattened patches (one image/video)
    grid: tuple[int, int, int],
) -> jnp.ndarray:
    """One image (or video clip) -> [t*h*w/4, out_dim] merged embeddings.

    Matches HF ``Qwen2VisionTransformerPretrainedModel.forward`` for a
    single grid. Attention is block-diagonal per TEMPORAL slice (HF's
    cu_seqlens repeat h*w per t): frames of a video don't attend to each
    other; a still image (t=1) is one full-attention block. Multi-image
    batches there are additional blocks, i.e. exactly a loop over this."""
    act = (lambda v: v * jax.nn.sigmoid(1.702 * v)) if cfg.act == "quick_gelu" \
        else (lambda v: jax.nn.gelu(v, approximate=False))
    x = patches @ params["patch_embed"]  # [S, D]
    angles = jnp.asarray(_vision_rope_angles(cfg, grid))
    h, hd = cfg.num_heads, cfg.head_dim
    t, hw = grid[0], grid[1] * grid[2]
    scale = hd**-0.5

    def layer_step(x, lp):
        y = _ln(x, lp["ln1"], lp["ln1_b"], cfg.ln_eps)
        qkv = (y @ lp["wqkv"] + lp["bqkv"]).reshape(-1, 3, h, hd)
        q, k, v = _rotate(qkv[:, 0], angles), _rotate(qkv[:, 1], angles), qkv[:, 2]
        q = q.reshape(t, hw, h, hd).astype(jnp.float32)
        k = k.reshape(t, hw, h, hd).astype(jnp.float32)
        v = v.reshape(t, hw, h, hd)
        att = jax.nn.softmax(jnp.einsum("tqhd,tkhd->thqk", q, k) * scale, axis=-1)
        o = jnp.einsum("thqk,tkhd->tqhd", att.astype(v.dtype), v).reshape(-1, cfg.embed_dim)
        x = x + (o @ lp["wo"] + lp["bo"])
        y = _ln(x, lp["ln2"], lp["ln2_b"], cfg.ln_eps)
        y = act(y @ lp["w1"] + lp["b1"]) @ lp["w2"] + lp["b2"]
        return x + y, None

    x, _ = jax.lax.scan(layer_step, x, params["layers"])
    # Merger: LN, then each spatial merge group's 4 patch features concat.
    y = _ln(x, params["merger_ln"], params["merger_ln_b"], cfg.ln_eps)
    y = y.reshape(-1, cfg.merge_dim)
    y = jax.nn.gelu(y @ params["merger_w1"] + params["merger_b1"], approximate=False)
    return y @ params["merger_w2"] + params["merger_b2"]


# -- image preprocessing (HF Qwen2VLImageProcessor parity) -------------------

def smart_resize(height: int, width: int, factor: int, min_pixels: int, max_pixels: int) -> tuple[int, int]:
    """HF smart_resize: dims to multiples of ``factor``, pixel count into
    [min_pixels, max_pixels], aspect ratio approximately kept."""
    if max(height, width) / min(height, width) > 200:
        raise ValueError("aspect ratio must be < 200")
    h_bar = max(factor, round(height / factor) * factor)
    w_bar = max(factor, round(width / factor) * factor)
    if h_bar * w_bar > max_pixels:
        beta = math.sqrt((height * width) / max_pixels)
        h_bar = max(factor, math.floor(height / beta / factor) * factor)
        w_bar = max(factor, math.floor(width / beta / factor) * factor)
    elif h_bar * w_bar < min_pixels:
        beta = math.sqrt(min_pixels / (height * width))
        h_bar = math.ceil(height * beta / factor) * factor
        w_bar = math.ceil(width * beta / factor) * factor
    return h_bar, w_bar


def preprocess_qwen2vl(data: bytes, cfg: Qwen2VLVisionConfig) -> tuple[np.ndarray, tuple[int, int, int]]:
    """Image bytes -> (flattened patches [S, patch_dim] f32, (t, h, w) grid),
    matching HF Qwen2VLImageProcessor: smart_resize (bicubic), normalize,
    duplicate to temporal_patch_size frames, flatten in merge-group order."""
    import io

    from PIL import Image

    img = Image.open(io.BytesIO(data))
    arr = _normalize_frame(img, cfg, _resize_target(img.size, cfg))
    frames = np.repeat(arr[None], cfg.temporal_patch_size, axis=0)  # [T, C, H, W]
    return patchify_frames(frames, cfg)


def _resize_target(size_wh: tuple[int, int], cfg: Qwen2VLVisionConfig) -> tuple[int, int]:
    w0, h0 = size_wh
    factor = cfg.patch_size * cfg.spatial_merge_size
    return smart_resize(h0, w0, factor, cfg.min_pixels, cfg.max_pixels)


def _normalize_frame(img, cfg: Qwen2VLVisionConfig, target_hw: tuple[int, int]) -> np.ndarray:
    """PIL image -> [C, H, W] float32, resized (bicubic) + normalized — the
    shared tail of the image and video paths (an HF-parity fix here fixes
    both)."""
    from PIL import Image

    h1, w1 = target_hw
    arr = np.asarray(img.convert("RGB").resize((w1, h1), Image.BICUBIC), np.float32) / 255.0
    arr = (arr - np.asarray(cfg.image_mean, np.float32)) / np.asarray(cfg.image_std, np.float32)
    return arr.transpose(2, 0, 1)


def patchify_frames(frames: np.ndarray, cfg: Qwen2VLVisionConfig) -> tuple[np.ndarray, tuple[int, int, int]]:
    """[T*tp?, C, H, W] normalized frames -> (patches [S, patch_dim], grid).

    ``T`` must be a multiple of temporal_patch_size (callers pad by
    repeating the last frame, as HF does). Mirrors the exact reshape/
    transpose of Qwen2VLImageProcessor._preprocess."""
    ps, m, tp = cfg.patch_size, cfg.spatial_merge_size, cfg.temporal_patch_size
    nt, c, hh, ww = frames.shape
    if nt % tp:
        frames = np.concatenate([frames, np.repeat(frames[-1:], tp - nt % tp, axis=0)])
        nt = frames.shape[0]
    gt, gh, gw = nt // tp, hh // ps, ww // ps
    p = frames.reshape(gt, tp, c, gh // m, m, ps, gw // m, m, ps)
    p = p.transpose(0, 3, 6, 4, 7, 2, 1, 5, 8)
    return p.reshape(gt * gh * gw, c * tp * ps * ps).astype(np.float32), (gt, gh, gw)


def preprocess_qwen2vl_video(
    data: bytes, cfg: Qwen2VLVisionConfig, *, num_frames: int = 8
) -> tuple[np.ndarray, tuple[int, int, int]]:
    """Video bytes (animated GIF/APNG/WebP) -> (patches [S, patch_dim],
    (t, h, w) grid with t = sampled_frames / temporal_patch_size).

    Uniform frame sampling (the reference's video_processor recipe:
    sample N frames, encode, stack — `examples/multimodal/utils/
    video_processor.py`), shared smart_resize target across frames so the
    grid is consistent, then the same merge-group patchify as images with
    the real temporal axis instead of frame duplication."""
    from dynamo_tpu.models.vision import extract_frames

    frames_pil = extract_frames(data, num_frames)
    target = _resize_target(frames_pil[0].size, cfg)
    return patchify_frames(
        np.stack([_normalize_frame(f, cfg, target) for f in frames_pil]), cfg
    )


# -- M-RoPE position ids (HF get_rope_index parity) --------------------------

def mrope_position_ids(
    tokens: list[int],
    grids: list[tuple[int, int, int]],
    *,
    image_token_id: int,
    video_token_id: int | None = None,
    spatial_merge_size: int = 2,
) -> tuple[np.ndarray, int]:
    """One sequence's 3D rope positions: (pos3 i32[3, T], delta).

    Text spans get equal coords continuing from the running max; each
    vision span (``grids`` consumed in order, h/w pre-merge as in HF) gets
    (t, h/m, w/m) grid coords offset by the running max. ``delta`` is
    ``max_pos + 1 - T``: decode token i (0-based from T) sits at position
    ``T + i + delta`` on all three axes. Mirrors HF ``get_rope_index``
    (modeling_qwen2_vl.py:925-1052) without needing vision_start tokens —
    spans are located by runs of the placeholder ids themselves."""
    arr = np.asarray(tokens, np.int64)
    t_len = len(arr)
    is_vis = arr == image_token_id
    if video_token_id is not None:
        is_vis |= arr == video_token_id
    pos3 = np.zeros((3, t_len), np.int64)
    gi = 0
    st = 0
    run = 0  # next position index (running max + 1)
    i = 0
    while i < t_len:
        if is_vis[i]:
            if gi >= len(grids):
                raise ValueError(f"{len(grids)} grids but more vision spans in prompt")
            gt, gh, gw = grids[gi]
            gh, gw = gh // spatial_merge_size, gw // spatial_merge_size
            n = gt * gh * gw
            if not bool(is_vis[i : i + n].all()) or i + n > t_len:
                raise ValueError("vision span shorter than its grid")
            # Text before this span.
            for c in range(3):
                pos3[c, st:i] = np.arange(i - st) + run
            run = run + (i - st)
            ti = np.repeat(np.arange(gt), gh * gw)
            hi = np.tile(np.repeat(np.arange(gh), gw), gt)
            wi = np.tile(np.arange(gw), gt * gh)
            pos3[0, i : i + n] = ti + run
            pos3[1, i : i + n] = hi + run
            pos3[2, i : i + n] = wi + run
            run = run + max(gt, gh, gw)
            gi += 1
            st = i + n
            i = i + n
        else:
            i += 1
    for c in range(3):
        pos3[c, st:] = np.arange(t_len - st) + run
    delta = int(pos3.max()) + 1 - t_len
    return pos3.astype(np.int32), delta
