"""Multi-head Latent Attention (DeepSeek-V2/V3) on the paged cache.

MLA caches a single shared **latent** per token — the KV-compressed vector
``c`` (kv_lora_rank wide) plus a decoupled rope key — instead of per-head
K/V. Per-token cache cost drops from ``2 * n_kv * head_dim`` to
``kv_lora_rank + rope_dim`` (e.g. V3: 576 values vs 32k for an equivalent
MHA), which is the architecture's whole point for long-context serving.

Implementation is the **absorbed** formulation: the per-head up-projections
``W_uk``/``W_uv`` never materialize per-head K/V. Queries are projected into
latent space (``q_nope @ W_uk``) so attention scores and the weighted sum
run directly against the cached latents; ``W_uv`` applies once to the
attention output. Prefill and decode share the path (same trick as the
dense forward), so chunked prefill/prefix reuse work unchanged.

Paged-cache mapping — no engine changes needed:

- ``k_cache`` stores the latents (width ``kv_lora_rank``)
- ``v_cache`` stores the rope keys (width ``qk_rope_head_dim``)

Both are ordinary ``[L, pages, page_size, W]`` arrays, so the allocator,
prefix cache, tier offload, and disagg transfer treat MLA pages exactly
like GQA pages. Decode attention streams pages through the Pallas MLA
kernel (``ops/pallas_mla.py`` — 6.2x the gather formulation on v5e);
prefill and non-kernel geometries use the gather formulation. The 2D
projections (w_kv_a, w_q*, wo_mla) are int8-quantizable like every other
matmul weight.

Parity: the MLA serving capability the reference gets from SGLang/vLLM's
DeepSeek support (`examples/sglang`, BASELINE config #4).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.quant import quant_matmul as _qmm
from dynamo_tpu.ops.norm import rms_norm
from dynamo_tpu.ops.rope import apply_rope

NEG_INF = -1e30

Params = dict


def init_mla_params(cfg: ModelConfig, key: jax.Array, dt, num_layers: int) -> dict[str, jnp.ndarray]:
    """MLA attention leaves, layers stacked on the leading axis."""
    d = cfg.hidden_size
    h = cfg.num_heads
    l = num_layers
    r_q, r_kv = cfg.q_lora_rank, cfg.kv_lora_rank
    dn, dr, dv = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
    keys = jax.random.split(key, 6)

    def w(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) * (fan_in**-0.5)).astype(dt)

    leaves = {
        # x -> compressed kv latent + decoupled rope key (shared, 1 "head")
        "w_kv_a": w(keys[0], (l, d, r_kv + dr), d),
        "kv_norm": jnp.ones((l, r_kv), dt),
        # latent -> per-head K_nope / V
        "w_uk": w(keys[1], (l, r_kv, h, dn), r_kv),
        "w_uv": w(keys[2], (l, r_kv, h, dv), r_kv),
        "wo_mla": w(keys[3], (l, h * dv, d), h * dv),
    }
    if r_q > 0:
        leaves["w_q_a"] = w(keys[4], (l, d, r_q), d)
        leaves["q_norm"] = jnp.ones((l, r_q), dt)
        leaves["w_q_b"] = w(keys[5], (l, r_q, h * (dn + dr)), r_q)
    else:
        leaves["w_q"] = w(keys[4], (l, d, h * (dn + dr)), d)
    return leaves


def mla_cache_widths(cfg: ModelConfig) -> tuple[int, int]:
    """(k_cache width, v_cache width): latents and rope keys.

    The rope stream is padded up to one 128-lane tile: Mosaic cannot DMA a
    sub-tile HBM slice (the decode kernel streams [page_size, width] slabs),
    and a 64-wide array would be tile-padded by the compiler anyway — the
    pad makes the physical layout explicit instead of unaddressable.
    Readers slice [..., :qk_rope_head_dim]; writers zero-fill."""
    return cfg.kv_lora_rank, max(cfg.qk_rope_head_dim, 128)


def mla_attention(
    lp: Params,
    cfg: ModelConfig,
    h: jnp.ndarray,  # [B, T, D] normed input
    positions: jnp.ndarray,  # i32[B, T]
    c_cache: jnp.ndarray,  # [P, ps, r_kv]  (the layer's k_cache slice view)
    r_cache: jnp.ndarray,  # [P, ps, dr]    (the layer's v_cache slice view)
    block_tables: jnp.ndarray,  # i32[B, pages_per_seq]
    slot_mapping: jnp.ndarray,  # i32[B, T]
    inv_freq: jnp.ndarray,  # [qk_rope_head_dim // 2] (rope-dim frequencies)
    attn_mscale: float = 1.0,  # YaRN temperature (mscale^2), applied to logits
    ring: bool = False,  # sequence-parallel ring over mesh's sp axis
    mesh=None,  # required when ring
    ring_positions: jnp.ndarray | None = None,  # [B, T] padding-hidden positions
    impl: str | None = None,  # "pallas" enables the MLA decode/verify kernel
    contiguous_positions: bool = True,  # False: gappy rows (speculative verify)
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One MLA layer: returns (attn_out [B,T,D], c_cache, r_cache).

    ``ring=True`` runs the sp-sharded ring path for whole-prompt prefills:
    in the absorbed formulation MLA *is* MQA with key ``[c; k_rope]``
    (width r_kv + dr) and value ``c`` (width r_kv), so the generic ring
    machinery (``parallel/ring.py``) applies unchanged — the latent cache
    still writes through for the decode phase. This is the long-context
    DeepSeek serving path (VERDICT r2 item 3)."""
    b, t, _ = h.shape
    n_heads = cfg.num_heads
    r_kv, dn, dr, dv = cfg.kv_lora_rank, cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim

    # -- latent + rope key, written through to the paged cache -------------
    kv_a = _qmm(h, lp["w_kv_a"])  # [B, T, r_kv + dr]
    c = rms_norm(kv_a[..., :r_kv], lp["kv_norm"], eps=cfg.rms_eps)
    k_rope = apply_rope(kv_a[..., None, r_kv:], positions, inv_freq)[:, :, 0]  # [B,T,dr]

    num_pages, ps, r_width = r_cache.shape[0], r_cache.shape[1], r_cache.shape[2]
    slots = slot_mapping.reshape(-1)
    c_flat = c_cache.reshape(num_pages * ps, r_kv).at[slots].set(
        c.reshape(-1, r_kv).astype(c_cache.dtype)
    )
    # Rope stream is lane-padded (mla_cache_widths): zero-fill the tail.
    k_rope_store = k_rope.reshape(-1, dr)
    if r_width != dr:
        k_rope_store = jnp.pad(k_rope_store, ((0, 0), (0, r_width - dr)))
    r_flat = r_cache.reshape(num_pages * ps, r_width).at[slots].set(
        k_rope_store.astype(r_cache.dtype)
    )
    c_cache = c_flat.reshape(num_pages, ps, r_kv)
    r_cache = r_flat.reshape(num_pages, ps, r_width)

    # -- queries, absorbed into latent space -------------------------------
    if "w_q_a" in lp:
        q_a = rms_norm(_qmm(h, lp["w_q_a"]), lp["q_norm"], eps=cfg.rms_eps)
        q = _qmm(q_a, lp["w_q_b"]).reshape(b, t, n_heads, dn + dr)
    else:
        q = _qmm(h, lp["w_q"]).reshape(b, t, n_heads, dn + dr)
    q_nope, q_rope = q[..., :dn], q[..., dn:]
    q_rope = apply_rope(q_rope, positions, inv_freq)
    # absorb W_uk: scores live in latent space
    q_lat = jnp.einsum("bthn,rhn->bthr", q_nope, lp["w_uk"])  # [B,T,H,r_kv]

    if ring:
        from dynamo_tpu.parallel.ring import ring_attention

        scale = (dn + dr) ** -0.5 * attn_mscale
        q_full = jnp.concatenate([q_lat.astype(h.dtype), q_rope], axis=-1)
        k_full = jnp.concatenate([c, k_rope], axis=-1)[:, :, None, :]  # MQA
        v_lat = c[:, :, None, :]
        out_lat = ring_attention(
            q_full, k_full, v_lat,
            positions if ring_positions is None else ring_positions,
            mesh, scale=scale,
        )  # [B, T, H, r_kv]
        out = jnp.einsum("bthr,rhv->bthv", out_lat.astype(h.dtype), lp["w_uv"])
        return _qmm(out.reshape(b, t, n_heads * dv), lp["wo_mla"]), c_cache, r_cache

    # -- decode: stream pages through the Pallas MLA kernel ----------------
    # The gather formulation below reads the latent cache ~4x per step
    # (gather write + score read + output read): measured 0.21x roofline at
    # V3 MLA geometry. The kernel reads each page once (6.2x measured,
    # BENCH r04). Under a mesh it runs per-device on the query-head shard
    # against the replicated latent cache (shard_map — no collectives
    # inside attention; see parallel/sharding.cache_shardings).
    if impl is None:
        from dynamo_tpu.ops.attention import default_impl

        impl = default_impl()
    if impl == "pallas":
        from dynamo_tpu.ops.pallas_mla import (
            interpret_mode,
            mla_decode_supported,
            mla_paged_decode,
            mla_paged_decode_sharded,
        )

        # The multi-query kernel's per-row causal mask is exact for ANY
        # position layout (T = 1 decode, gappy speculative-verify rows,
        # contiguous prefill windows) — the only gates are geometry and the
        # VMEM row cap on T.
        if mla_decode_supported(
            r_kv, r_width, t, n_heads, interpret=interpret_mode()
        ):
            scale = (dn + dr) ** -0.5 * attn_mscale
            q_rope_k = q_rope  # [B, T, H, dr]
            if r_width != dr:  # match the lane-padded rope stream
                q_rope_k = jnp.pad(
                    q_rope_k, ((0, 0), (0, 0), (0, 0), (0, r_width - dr))
                )
            if mesh is None:
                out_lat = mla_paged_decode(
                    q_lat, q_rope_k, c_cache, r_cache,
                    block_tables, positions,
                    scale=scale, interpret=interpret_mode(),
                )  # [B, T, H, r_kv]
            else:
                out_lat = mla_paged_decode_sharded(
                    q_lat, q_rope_k, c_cache, r_cache,
                    block_tables, positions,
                    mesh=mesh, scale=scale, interpret=interpret_mode(),
                )
            out = jnp.einsum("bthr,rhv->bthv", out_lat.astype(h.dtype), lp["w_uv"])
            return _qmm(out.reshape(b, t, n_heads * dv), lp["wo_mla"]), c_cache, r_cache
        if t == 1 or not contiguous_positions:
            # Decode/verify falling off the kernel is the ~5x downgrade
            # worth alerting on; a T-over-cap contiguous prefill is not
            # (no MLA prefill kernel exists to fall back FROM).
            from dynamo_tpu.ops.pallas_paged import _record_fallback

            _record_fallback(
                "mla_decode" if t == 1 else "mla_verify", q, c_cache
            )

    # -- gather this batch's pages and attend ------------------------------
    pages_per_seq = block_tables.shape[1]
    s = pages_per_seq * ps
    c_pages = c_cache[block_tables.reshape(-1)].reshape(b, s, r_kv)
    r_pages = r_cache[block_tables.reshape(-1)].reshape(b, s, r_width)[..., :dr]

    scale = (dn + dr) ** -0.5 * attn_mscale
    logits = (
        jnp.einsum("bthr,bsr->bhts", q_lat, c_pages, preferred_element_type=jnp.float32)
        + jnp.einsum("bthr,bsr->bhts", q_rope, r_pages, preferred_element_type=jnp.float32)
    ) * scale
    key_pos = jnp.arange(s, dtype=jnp.int32)
    mask = key_pos[None, None, :] <= positions[:, :, None]  # [B, T, S]
    logits = jnp.where(mask[:, None, :, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)

    out_lat = jnp.einsum(
        "bhts,bsr->bthr", probs.astype(c_pages.dtype), c_pages, preferred_element_type=jnp.float32
    )  # [B, T, H, r_kv]
    out = jnp.einsum("bthr,rhv->bthv", out_lat.astype(h.dtype), lp["w_uv"])  # [B,T,H,dv]
    return _qmm(out.reshape(b, t, n_heads * dv), lp["wo_mla"]), c_cache, r_cache


def mla_attention_naive(
    lp: Params,
    cfg: ModelConfig,
    h: jnp.ndarray,  # [B, T, D]
    positions: jnp.ndarray,
    inv_freq: jnp.ndarray,
    attn_mscale: float = 1.0,
) -> jnp.ndarray:
    """Golden reference: materialize per-head K/V (no cache, full self-attn).

    The absorbed paged formulation must match this on whole sequences."""
    b, t, _ = h.shape
    n_heads = cfg.num_heads
    r_kv, dn, dr, dv = cfg.kv_lora_rank, cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim

    kv_a = _qmm(h, lp["w_kv_a"])
    c = rms_norm(kv_a[..., :r_kv], lp["kv_norm"], eps=cfg.rms_eps)
    k_rope = apply_rope(kv_a[..., None, r_kv:], positions, inv_freq)  # [B,T,1,dr]
    k_nope = jnp.einsum("btr,rhn->bthn", c, lp["w_uk"])  # [B,T,H,dn]
    v = jnp.einsum("btr,rhv->bthv", c, lp["w_uv"])  # [B,T,H,dv]

    if "w_q_a" in lp:
        q_a = rms_norm(_qmm(h, lp["w_q_a"]), lp["q_norm"], eps=cfg.rms_eps)
        q = _qmm(q_a, lp["w_q_b"]).reshape(b, t, n_heads, dn + dr)
    else:
        q = _qmm(h, lp["w_q"]).reshape(b, t, n_heads, dn + dr)
    q_nope, q_rope = q[..., :dn], q[..., dn:]
    q_rope = apply_rope(q_rope, positions, inv_freq)

    k = jnp.concatenate([k_nope, jnp.broadcast_to(k_rope, (b, t, n_heads, dr))], axis=-1)
    qf = jnp.concatenate([q_nope, q_rope], axis=-1)
    scale = (dn + dr) ** -0.5 * attn_mscale
    logits = jnp.einsum("bthd,bshd->bhts", qf, k, preferred_element_type=jnp.float32) * scale
    mask = positions[:, :, None] >= positions[:, None, :]  # causal on true positions
    logits = jnp.where(mask[:, None, :, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhts,bshv->bthv", probs.astype(v.dtype), v, preferred_element_type=jnp.float32)
    return _qmm(out.astype(h.dtype).reshape(b, t, n_heads * dv), lp["wo_mla"])
