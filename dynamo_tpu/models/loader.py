"""Checkpoint loading: HF-style safetensors model dirs → stacked params pytree.

TPU-first design: the model's params pytree stacks layers on a leading axis
(``models/llama.py``), but HF checkpoints store one tensor per layer with
torch's ``[out_features, in_features]`` orientation. The loader maps names,
transposes projections to math orientation ``[in, out]``, stacks layers, and
places each leaf **directly onto the device mesh** — per-shard reads through
``jax.make_array_from_callback`` over lazy safetensors slices, so peak host
memory is one shard, not the checkpoint (required for 70B-class weights).

Supports dense Llama-family (Llama 3.x, Qwen2, DeepSeek-R1-Distill) and
routed-MoE layouts (Qwen2-MoE / DeepSeek-style ``mlp.gate`` +
``mlp.experts.{e}.*``, Mixtral ``block_sparse_moe`` aliases).

Also provides ``save_params`` (the reverse mapping) so tests and tools can
materialize an HF-compatible checkpoint from any params pytree — the same
role the reference's model-expression tooling plays for its engines.

Parity: reference ``lib/llm/src/local_model.rs:29-140`` (model resolution +
artifact discovery), ``lib/llm/src/model_card/create.rs`` (card built from
real artifacts), ``lib/llm/src/hub.rs:32`` (checkpoint acquisition — here a
local/shared-filesystem path; TPU pods mount shared storage, no download
daemon needed).
"""

from __future__ import annotations

import json
import pathlib
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models.config import ModelConfig

Params = dict


# ---------------------------------------------------------------------------
# Checkpoint index: tensor name -> (file, lazy slice handle)
# ---------------------------------------------------------------------------


class CheckpointIndex:
    """All tensors of a (possibly sharded) safetensors checkpoint, lazily.

    Handles both single-file ``model.safetensors`` and sharded checkpoints
    with ``model.safetensors.index.json``. Tensors are exposed as lazy slice
    handles — bytes are only read for the slices actually requested.
    """

    def __init__(self, model_dir: str | pathlib.Path) -> None:
        from safetensors import safe_open

        self.dir = pathlib.Path(model_dir)
        index_file = self.dir / "model.safetensors.index.json"
        if index_file.exists():
            weight_map: dict[str, str] = json.loads(index_file.read_text())["weight_map"]
            files = sorted(set(weight_map.values()))
        else:
            files = sorted(f.name for f in self.dir.glob("*.safetensors"))
            if not files:
                raise FileNotFoundError(f"no *.safetensors under {self.dir}")
        self._handles = {f: safe_open(str(self.dir / f), framework="numpy") for f in files}
        self._where: dict[str, str] = {}
        for fname, h in self._handles.items():
            for key in h.keys():
                self._where[key] = fname

    def keys(self) -> list[str]:
        return sorted(self._where)

    def __contains__(self, name: str) -> bool:
        return name in self._where

    def get_slice(self, name: str):
        return self._handles[self._where[name]].get_slice(name)

    def shape(self, name: str) -> tuple[int, ...]:
        return tuple(self.get_slice(name).get_shape())

    def read(self, name: str) -> np.ndarray:
        return self._handles[self._where[name]].get_tensor(name)


# ---------------------------------------------------------------------------
# HF name mapping
# ---------------------------------------------------------------------------

# Per-layer sources: leaf name -> (hf suffix candidates, transpose?)
_LAYER_MAP: dict[str, tuple[tuple[str, ...], bool]] = {
    "attn_norm": (("input_layernorm.weight",), False),
    "mlp_norm": (("post_attention_layernorm.weight",), False),
    "wq": (("self_attn.q_proj.weight",), True),
    "wk": (("self_attn.k_proj.weight",), True),
    "wv": (("self_attn.v_proj.weight",), True),
    "wo": (("self_attn.o_proj.weight",), True),
    "w_gate": (("mlp.gate_proj.weight",), True),
    "w_up": (("mlp.up_proj.weight",), True),
    "w_down": (("mlp.down_proj.weight",), True),
}

# Qwen2-family attention biases.
_BIAS_MAP: dict[str, tuple[tuple[str, ...], bool]] = {
    "bq": (("self_attn.q_proj.bias",), False),
    "bk": (("self_attn.k_proj.bias",), False),
    "bv": (("self_attn.v_proj.bias",), False),
}

# MoE per-layer sources. Router: [E, D] in HF -> [D, E]. Experts are stored
# one tensor per expert; the loader stacks them on an expert axis.
_MOE_ROUTER = ("mlp.gate.weight", "block_sparse_moe.gate.weight")
_MOE_EXPERT_MAP: dict[str, tuple[tuple[str, ...], bool]] = {
    "w_gate": (("mlp.experts.{e}.gate_proj.weight", "block_sparse_moe.experts.{e}.w1.weight"), True),
    "w_up": (("mlp.experts.{e}.up_proj.weight", "block_sparse_moe.experts.{e}.w3.weight"), True),
    "w_down": (("mlp.experts.{e}.down_proj.weight", "block_sparse_moe.experts.{e}.w2.weight"), True),
}

# Always-on shared expert: Qwen2-MoE (`mlp.shared_expert.*` + sigmoid gate) /
# DeepSeek (`mlp.shared_experts.*`, ungated).
_SHARED_EXPERT_MAP: dict[str, tuple[tuple[str, ...], bool]] = {
    "w_shared_gate": (("mlp.shared_expert.gate_proj.weight", "mlp.shared_experts.gate_proj.weight"), True),
    "w_shared_up": (("mlp.shared_expert.up_proj.weight", "mlp.shared_experts.up_proj.weight"), True),
    "w_shared_down": (("mlp.shared_expert.down_proj.weight", "mlp.shared_experts.down_proj.weight"), True),
}
_SHARED_GATE = ("mlp.shared_expert_gate.weight",)


def _find(index: CheckpointIndex, candidates: tuple[str, ...], li: int, e: int | None = None) -> str:
    for cand in candidates:
        name = f"model.layers.{li}." + (cand.format(e=e) if e is not None else cand)
        if name in index:
            return name
    raise KeyError(f"layer {li}: none of {candidates} in checkpoint (expert={e})")


class _LazyLeaf:
    """A stacked-leaf view over per-layer checkpoint tensors.

    ``__getitem__`` with a tuple of slices (as produced by
    ``jax.make_array_from_callback``) reads only the bytes each device shard
    needs: the layer axis selects which per-layer tensors to touch, and the
    within-layer slices are pushed down into the safetensors lazy slice (with
    transposition handled by slicing the source in swapped order).
    """

    def __init__(
        self,
        index: CheckpointIndex,
        shape: tuple[int, ...],
        per_layer: Callable[[int], list[tuple[str, bool]]],
        dtype: np.dtype,
        expert_axis: bool = False,
        row_perm: np.ndarray | None = None,
    ) -> None:
        self.index = index
        self.shape = shape
        self.per_layer = per_layer  # li -> [(tensor name, transpose?)] (len>1 = expert stack)
        self.dtype = dtype
        self.expert_axis = expert_axis
        # Source-row (torch [out, in] axis-0) permutation applied at read
        # time (rope interleaved -> half-split, see rope_load_perm). A
        # permuted leaf materializes the full per-layer tensor: a shard's
        # slice no longer maps to contiguous source rows.
        self.row_perm = row_perm

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def _read(self, name: str, transpose: bool, idx: tuple[slice, ...]) -> np.ndarray:
        sl = self.index.get_slice(name)
        if self.row_perm is not None:
            arr = np.asarray(sl[:])[self.row_perm]
            if transpose:
                arr = arr.T
            return arr[idx] if idx else arr
        if transpose:
            src = sl[idx[1], idx[0]] if len(idx) == 2 else sl[:]
            arr = np.asarray(src).T
        else:
            arr = np.asarray(sl[idx] if idx else sl[:])
        return arr

    def __getitem__(self, idx) -> np.ndarray:
        if not isinstance(idx, tuple):
            idx = (idx,)
        idx = tuple(
            i if isinstance(i, slice) else slice(i, i + 1) for i in idx
        ) + (slice(None),) * (len(self.shape) - len(idx))
        layers = range(*idx[0].indices(self.shape[0]))
        rest = idx[1:]
        out_layers = []
        for li in layers:
            sources = self.per_layer(li)
            if self.expert_axis:
                e_sl, inner = rest[0], rest[1:]
                chosen = sources[e_sl]
                arr = np.stack([self._read(n, t, inner) for n, t in chosen])
            else:
                (name, transpose), = sources
                arr = self._read(name, transpose, rest)
            out_layers.append(arr)
        return np.stack(out_layers).astype(self.dtype, copy=False)


def rope_load_perm(n_heads: int, head_size: int, rope_dim: int) -> np.ndarray:
    """Row permutation (torch ``[out, in]`` orientation) converting each
    head's trailing ``rope_dim`` rows from interleaved pair order to the
    half-split order ``ops/rope.apply_rope`` expects: ``new = old[perm]``.

    DeepSeek-V2/V3 checkpoints ship rope dims interleaved (HF
    ``rope_interleave=True``: modeling does ``view(d//2, 2).transpose`` on
    the activations before rotate_half — `modeling_deepseek_v3.py:311`);
    llama.cpp's converter likewise permutes whole Q/K heads of llama-family
    GGUFs into interleaved (GGML NORM-rope) order. Permuting the *weights*
    once at load is equivalent and keeps the runtime half-split everywhere.
    Half-split row ``p*half + d`` reads interleaved row ``2*d + p``.
    """
    half = rope_dim // 2
    idx = np.arange(n_heads * head_size)
    head, r = idx // head_size, idx % head_size
    off = head_size - rope_dim
    j = r - off
    src_r = np.where(r >= off, off + 2 * (j % max(half, 1)) + j // max(half, 1), r)
    return head * head_size + src_r


def rope_save_perm(n_heads: int, head_size: int, rope_dim: int) -> np.ndarray:
    """Inverse of :func:`rope_load_perm` (half-split -> interleaved), applied
    by the checkpoint writers so exports match the ecosystem convention."""
    return np.argsort(rope_load_perm(n_heads, head_size, rope_dim))


# MLA per-layer sources (DeepSeek-V2/V3 HF names). kv_b_proj packs per-head
# [K_nope; V] row blocks and is split by _KvBLeaf.
_MLA_MAP: dict[str, tuple[tuple[str, ...], bool]] = {
    "w_q_a": (("self_attn.q_a_proj.weight",), True),
    "q_norm": (("self_attn.q_a_layernorm.weight",), False),
    "w_q_b": (("self_attn.q_b_proj.weight",), True),
    "w_q": (("self_attn.q_proj.weight",), True),
    "w_kv_a": (("self_attn.kv_a_proj_with_mqa.weight",), True),
    "kv_norm": (("self_attn.kv_a_layernorm.weight",), False),
    "wo_mla": (("self_attn.o_proj.weight",), True),
}


class _KvBLeaf:
    """Stacked [L, r_kv, H, seg_width] view over per-layer kv_b_proj tensors.

    kv_b_proj is torch-[H*(dn+dv), r_kv]; head h's rows are
    ``h*(dn+dv) + offset .. + offset + width`` (offset 0/width dn for W_uk,
    offset dn/width dv for W_uv). Reads materialize one layer's tensor
    (~MBs) and slice — per-head lazy slicing isn't worth the complexity.
    """

    def __init__(self, index: "CheckpointIndex", num_layers: int, n_heads: int,
                 dn: int, dv: int, offset: int, width: int, dtype,
                 layer_offset: int = 0) -> None:
        self.index = index
        self.layer_offset = layer_offset
        self.shape = (
            num_layers,
            index.shape(f"model.layers.{layer_offset}.self_attn.kv_b_proj.weight")[1],
            n_heads, width,
        )
        self.n_heads, self.seg = n_heads, dn + dv
        self.offset, self.width = offset, width
        self.dtype = dtype
        self.ndim = 4

    def per_layer_name(self, li: int) -> str:
        return f"model.layers.{li + self.layer_offset}.self_attn.kv_b_proj.weight"

    def __getitem__(self, idx) -> np.ndarray:
        if not isinstance(idx, tuple):
            idx = (idx,)
        idx = tuple(i if isinstance(i, slice) else slice(i, i + 1) for i in idx)
        idx = idx + (slice(None),) * (4 - len(idx))
        out_layers = []
        for li in range(*idx[0].indices(self.shape[0])):
            full = np.asarray(self.index.get_slice(self.per_layer_name(li))[:])  # [H*seg, r_kv]
            per_head = full.reshape(self.n_heads, self.seg, -1)  # [H, dn+dv, r_kv]
            part = per_head[:, self.offset : self.offset + self.width, :]  # [H, w, r_kv]
            arr = np.transpose(part, (2, 0, 1))  # [r_kv, H, w]
            out_layers.append(arr[idx[1], :, :][:, idx[2], :][:, :, idx[3]])
        return np.stack(out_layers).astype(self.dtype, copy=False)


_MOE_ROUTER_BIAS = ("mlp.gate.e_score_correction_bias",)


def _leaf_specs(index: CheckpointIndex, cfg: ModelConfig, dtype: np.dtype) -> dict[str, Any]:
    """Build the params pytree of _LazyLeaf / lazy top-level reads.

    Mixed DeepSeek stacks (``cfg.first_k_dense``) produce two subtrees:
    ``dense_layers`` (checkpoint layers [0, k), dense MLP) and ``layers``
    (checkpoint layers [k, L), MoE)."""
    d = cfg.hidden_size

    def subtree(l0: int, count: int, moe: bool) -> dict[str, Any]:
        def simple(suffixes: tuple[str, ...], transpose: bool,
                   row_perm: np.ndarray | None = None, leaf_dtype=None):
            name0 = _find(index, suffixes, l0)
            shp = index.shape(name0)
            shp = shp[::-1] if transpose else shp
            return _LazyLeaf(
                index, (count, *shp),
                lambda li, s=suffixes, t=transpose: [(_find(index, s, li + l0), t)],
                leaf_dtype or dtype, row_perm=row_perm,
            )

        if cfg.attn_type == "mla":
            layers = {
                name: simple(suffixes, t)
                for name, (suffixes, t) in _LAYER_MAP.items()
                if name in ("attn_norm", "mlp_norm")
            }
            # DeepSeek checkpoints store rope dims interleaved: permute the
            # rope rows of the q projection (per head) and kv_a_proj (single
            # shared rope key) to half-split at load (rope_load_perm).
            q_perm = kv_perm = None
            if cfg.rope_interleave:
                q_perm = rope_load_perm(
                    cfg.num_heads, cfg.qk_nope_head_dim + cfg.qk_rope_head_dim, cfg.qk_rope_head_dim
                )
                kv_perm = rope_load_perm(
                    1, cfg.kv_lora_rank + cfg.qk_rope_head_dim, cfg.qk_rope_head_dim
                )
            for name, (suffixes, t) in _MLA_MAP.items():
                if name in ("w_q_a", "q_norm", "w_q_b") and cfg.q_lora_rank <= 0:
                    continue
                if name == "w_q" and cfg.q_lora_rank > 0:
                    continue
                perm = {"w_q_b": q_perm, "w_q": q_perm, "w_kv_a": kv_perm}.get(name)
                layers[name] = simple(suffixes, t, row_perm=perm)
            layers["w_uk"] = _KvBLeaf(
                index, count, cfg.num_heads, cfg.qk_nope_head_dim, cfg.v_head_dim,
                0, cfg.qk_nope_head_dim, dtype, layer_offset=l0,
            )
            layers["w_uv"] = _KvBLeaf(
                index, count, cfg.num_heads, cfg.qk_nope_head_dim, cfg.v_head_dim,
                cfg.qk_nope_head_dim, cfg.v_head_dim, dtype, layer_offset=l0,
            )
        else:
            layers = {
                name: simple(suffixes, t)
                for name, (suffixes, t) in _LAYER_MAP.items()
                if name not in ("w_gate", "w_up", "w_down")
            }
            if cfg.qk_norm:  # Qwen3 (per-head) / OLMoE (flat) q/k RMS norms
                layers["q_norm"] = simple(("self_attn.q_norm.weight",), False)
                layers["k_norm"] = simple(("self_attn.k_norm.weight",), False)
        if cfg.attention_bias:
            for name, (suffixes, t) in _BIAS_MAP.items():
                layers[name] = simple(suffixes, t)
        if moe:
            e = cfg.num_experts
            layers["router"] = simple(_MOE_ROUTER, True)
            if cfg.moe_router_bias:
                # The correction bias competes with sigmoid scores at O(1e-2)
                # margins: keep it fp32 (as HF does), never the compute dtype.
                layers["router_bias"] = simple(
                    _MOE_ROUTER_BIAS, False, leaf_dtype=np.float32
                )
            for name, (suffixes, t) in _MOE_EXPERT_MAP.items():
                name0 = _find(index, suffixes, l0, 0)
                shp = index.shape(name0)[::-1]
                layers[name] = _LazyLeaf(
                    index,
                    (count, e, *shp),
                    lambda li, s=suffixes, t=t: [(_find(index, s, li + l0, ei), t) for ei in range(e)],
                    dtype,
                    expert_axis=True,
                )
            if cfg.shared_expert_size:
                for name, (suffixes, t) in _SHARED_EXPERT_MAP.items():
                    layers[name] = simple(suffixes, t)
                if cfg.shared_expert_gated:
                    layers["shared_gate"] = simple(_SHARED_GATE, True)
        else:
            for name in ("w_gate", "w_up", "w_down"):
                layers[name] = simple(_LAYER_MAP[name][0], True)
        return layers

    k_dense = cfg.first_k_dense if cfg.is_moe else 0
    moe = cfg.is_moe and any(
        f"model.layers.{k_dense}.{c}" in index for c in _MOE_ROUTER
    )
    layers = subtree(k_dense, cfg.num_layers - k_dense, moe)

    class _TopLeaf:
        def __init__(self, name: str, transpose: bool) -> None:
            self.name, self.transpose = name, transpose
            shp = index.shape(name)
            self.shape = shp[::-1] if transpose else shp
            self.dtype = dtype
            self.ndim = len(self.shape)

        def __getitem__(self, idx) -> np.ndarray:
            sl = index.get_slice(self.name)
            if not isinstance(idx, tuple):
                idx = (idx,)
            idx = tuple(idx) + (slice(None),) * (len(self.shape) - len(idx))
            if self.transpose:
                arr = np.asarray(sl[idx[1], idx[0]]).T
            else:
                arr = np.asarray(sl[idx])
            return arr.astype(self.dtype, copy=False)

    params: dict[str, Any] = {
        "embed": _TopLeaf("model.embed_tokens.weight", False),
        "norm_f": _TopLeaf("model.norm.weight", False),
        "layers": layers,
    }
    if k_dense:
        params["dense_layers"] = subtree(0, k_dense, False)
    if not cfg.tie_embeddings:
        if "lm_head.weight" in index:
            params["lm_head"] = _TopLeaf("lm_head.weight", True)
        else:  # config said untied but checkpoint ties: reuse embeddings
            params["lm_head"] = _TopLeaf("model.embed_tokens.weight", True)
    return params


def _consumed_names(specs: dict, num_layers: int) -> set[str]:
    """Every checkpoint tensor the spec tree will read."""
    del num_layers  # each stacked leaf knows its own layer count (shape[0])
    names: set[str] = set()

    def walk(tree):
        for leaf in jax.tree.leaves(tree, is_leaf=lambda x: hasattr(x, "shape")):
            if isinstance(leaf, _LazyLeaf):
                for li in range(leaf.shape[0]):
                    names.update(n for n, _t in leaf.per_layer(li))
            elif isinstance(leaf, _KvBLeaf):
                names.update(leaf.per_layer_name(li) for li in range(leaf.shape[0]))
            else:
                names.add(leaf.name)

    walk(specs)
    return names


# Buffers some exporters serialize that carry no weights.
_IGNORABLE = ("rotary_emb.inv_freq", "masked_bias", ".attn.bias")


class _RenamedIndex:
    """View over a CheckpointIndex translating canonical Llama names
    (``model.X`` / ``lm_head.weight``) to a VLM checkpoint's language-model
    subtree. Handles both HF layouts: the post-refactor
    ``model.language_model.X`` (+ top-level ``lm_head.weight``) and the
    legacy ``language_model.model.X`` (+ ``language_model.lm_head.weight``).
    Vision/projector tensors are hidden from ``keys()`` so the strict
    leftover check applies to the LM subtree only."""

    def __init__(self, index: CheckpointIndex) -> None:
        self._index = index
        self._legacy = any(k.startswith("language_model.model.") for k in index.keys())

    def _translate(self, name: str) -> str:
        if self._legacy:
            if name == "lm_head.weight":
                return "language_model.lm_head.weight"
            if name.startswith("model."):
                return "language_model." + name
            return name
        if name.startswith("model."):
            return "model.language_model." + name[len("model."):]
        return name

    def keys(self) -> list[str]:
        out = []
        for k in self._index.keys():
            if self._legacy and k.startswith("language_model.model."):
                out.append("model." + k[len("language_model.model."):])
            elif self._legacy and k == "language_model.lm_head.weight":
                out.append("lm_head.weight")
            elif k.startswith("model.language_model."):
                out.append("model." + k[len("model.language_model."):])
            elif k == "lm_head.weight" and not self._legacy:
                out.append(k)
        return out

    def __contains__(self, name: str) -> bool:
        return self._translate(name) in self._index

    def get_slice(self, name: str):
        return self._index.get_slice(self._translate(name))

    def shape(self, name: str) -> tuple[int, ...]:
        return self._index.shape(self._translate(name))

    def read(self, name: str) -> np.ndarray:
        return self._index.read(self._translate(name))


def load_params(
    model_dir: str | pathlib.Path,
    cfg: ModelConfig,
    *,
    mesh: jax.sharding.Mesh | None = None,
    dtype: Any | None = None,
    strict: bool = True,
    index: Any | None = None,
) -> Params:
    """Load a params pytree from an HF-style safetensors checkpoint.

    With ``mesh``, every leaf is materialized **directly sharded**: each
    device shard is read from the checkpoint independently (lazy slices), so
    host memory stays O(largest shard). Without a mesh, leaves land on the
    default device.

    ``strict`` (default) fails on checkpoint tensors the mapping would
    silently drop — a model whose weights are partially ignored *looks* like
    a working deployment while generating garbage.
    """
    target_dtype = np.dtype(jnp.dtype(dtype or cfg.dtype).name) if str(dtype or cfg.dtype) != "bfloat16" else jnp.bfloat16
    import ml_dtypes

    np_dtype = ml_dtypes.bfloat16 if target_dtype == jnp.bfloat16 else np.dtype(target_dtype)
    index = index if index is not None else CheckpointIndex(model_dir)
    specs = _leaf_specs(index, cfg, np_dtype)
    if strict:
        consumed = _consumed_names(specs, cfg.num_layers)
        leftover = [
            n for n in index.keys()
            if n not in consumed and not any(n.endswith(sfx) for sfx in _IGNORABLE)
        ]
        if leftover:
            raise ValueError(
                f"checkpoint has {len(leftover)} tensors the {cfg.name!r} mapping would "
                f"silently drop (first few: {leftover[:6]}); the architecture config and "
                f"checkpoint disagree — pass strict=False only if this is intentional"
            )

    # _LazyLeaf/_TopLeaf are unregistered types: jax.tree.map sees them as leaves.
    if mesh is None:
        return jax.tree.map(
            lambda leaf: jnp.asarray(leaf[(slice(None),) * len(leaf.shape)]), specs
        )

    from dynamo_tpu.parallel.sharding import param_shardings

    shardings = param_shardings(mesh, specs)

    def place(leaf, sharding):
        return jax.make_array_from_callback(tuple(leaf.shape), sharding, lambda idx: leaf[idx])

    return jax.tree.map(place, specs, shardings)


# ---------------------------------------------------------------------------
# High-level entry: directory -> (config, params); plus the reverse writer
# ---------------------------------------------------------------------------


def load_model(
    model_dir: str | pathlib.Path,
    *,
    mesh: jax.sharding.Mesh | None = None,
    dtype: Any | None = None,
    name: str | None = None,
) -> tuple[ModelConfig, Params]:
    """Resolve an HF model directory: config.json -> ModelConfig, weights -> pytree."""
    p = pathlib.Path(model_dir)
    cfg = ModelConfig.from_hf(p / "config.json", name=name or p.name)
    if dtype is not None:
        import dataclasses

        cfg = dataclasses.replace(cfg, dtype=str(jnp.dtype(dtype).name))
    return cfg, load_params(p, cfg, mesh=mesh, dtype=dtype)


def load_vision_params(index: CheckpointIndex, dtype: Any = np.float32) -> Params:
    """CLIP tower + LLaVA projector weights -> the vision pytree that
    ``models/vision.encode_image`` consumes.

    Maps HF names (``[model.]vision_tower.vision_model.*`` +
    ``[model.]multi_modal_projector.*``, reference
    `examples/multimodal/components/encode_worker.py:61-179` serves exactly
    this tower via HF). Conv patch embedding becomes the patchify matmul
    weight ([d,3,ph,pw] -> [(ph,pw,c), d] matching encode_image's flatten
    order); q/k/v projections stack into one ``wqkv``."""
    names = set(index.keys())
    pre = "model." if any(n.startswith("model.vision_tower.") for n in names) else ""
    vt = pre + "vision_tower.vision_model."
    proj = pre + "multi_modal_projector."

    def rd(name: str) -> np.ndarray:
        return index.read(name).astype(dtype)

    conv = rd(vt + "embeddings.patch_embedding.weight")  # [d, 3, ph, pw]
    d = conv.shape[0]
    patch_embed = conv.transpose(2, 3, 1, 0).reshape(-1, d)

    n_layers = 1 + max(
        int(n.split("encoder.layers.")[1].split(".")[0])
        for n in names if "encoder.layers." in n
    )

    def layer(li: int) -> dict:
        p = f"{vt}encoder.layers.{li}."
        q, k, v = (rd(p + f"self_attn.{x}_proj.weight") for x in "qkv")
        bq, bk, bv = (rd(p + f"self_attn.{x}_proj.bias") for x in "qkv")
        return {
            "ln1": rd(p + "layer_norm1.weight"), "ln1_b": rd(p + "layer_norm1.bias"),
            "ln2": rd(p + "layer_norm2.weight"), "ln2_b": rd(p + "layer_norm2.bias"),
            "wqkv": np.concatenate([q.T, k.T, v.T], axis=1),
            "bqkv": np.concatenate([bq, bk, bv]),
            "wo": rd(p + "self_attn.out_proj.weight").T,
            "bo": rd(p + "self_attn.out_proj.bias"),
            "w1": rd(p + "mlp.fc1.weight").T, "b1": rd(p + "mlp.fc1.bias"),
            "w2": rd(p + "mlp.fc2.weight").T, "b2": rd(p + "mlp.fc2.bias"),
        }

    layers = jax.tree.map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
        *[layer(i) for i in range(n_layers)],
    )
    params: Params = {
        "patch_embed": jnp.asarray(patch_embed),
        "cls": jnp.asarray(rd(vt + "embeddings.class_embedding")),
        "pos_embed": jnp.asarray(rd(vt + "embeddings.position_embedding.weight")),
        "pre_ln_g": jnp.asarray(rd(vt + "pre_layrnorm.weight")),
        "pre_ln_b": jnp.asarray(rd(vt + "pre_layrnorm.bias")),
        "ln_f": jnp.asarray(rd(vt + "post_layernorm.weight")),
        "ln_f_b": jnp.asarray(rd(vt + "post_layernorm.bias")),
        "proj1": jnp.asarray(rd(proj + "linear_1.weight").T),
        "b_proj1": jnp.asarray(rd(proj + "linear_1.bias")),
        "proj2": jnp.asarray(rd(proj + "linear_2.weight").T),
        "b_proj2": jnp.asarray(rd(proj + "linear_2.bias")),
        "layers": layers,
    }
    return params


class _HiddenPrefixIndex:
    """View over a CheckpointIndex hiding non-LM subtrees (``visual.*``) so
    the strict leftover check applies to the LM only. Qwen2-VL checkpoints
    store the LM under canonical ``model.*`` names already."""

    def __init__(self, index: CheckpointIndex, hidden: tuple[str, ...]) -> None:
        self._index = index
        self._hidden = hidden

    def keys(self) -> list[str]:
        return [k for k in self._index.keys() if not k.startswith(self._hidden)]

    def __contains__(self, name: str) -> bool:
        return not name.startswith(self._hidden) and name in self._index

    def read(self, name: str) -> np.ndarray:
        return self._index.read(name)

    def __getattr__(self, attr):  # shape(), dtype(), ... — name-keyed reads
        return getattr(self._index, attr)


def load_qwen2vl_vision_params(index: CheckpointIndex, dtype: Any = np.float32) -> Params:
    """Qwen2-VL tower + merger weights -> the pytree
    ``models/qwen2_vl.encode_qwen2vl`` consumes. Maps ``[model.]visual.*``:
    the Conv3d patch embedding becomes the patchify matmul weight
    ([D, C, tp, ph, pw] -> [(c, tp, ph, pw), D], the flatten order
    ``patchify_frames`` produces); qkv stays one fused projection."""
    names = set(index.keys())
    pre = "model.visual." if any(n.startswith("model.visual.") for n in names) else "visual."

    def rd(name: str) -> np.ndarray:
        return index.read(pre + name).astype(dtype)

    conv = rd("patch_embed.proj.weight")  # [D, C, tp, ph, pw]
    d = conv.shape[0]
    n_layers = 1 + max(
        int(n.split("blocks.")[1].split(".")[0])
        for n in names if n.startswith(pre + "blocks.")
    )

    def layer(li: int) -> dict:
        p = f"blocks.{li}."
        return {
            "ln1": rd(p + "norm1.weight"), "ln1_b": rd(p + "norm1.bias"),
            "ln2": rd(p + "norm2.weight"), "ln2_b": rd(p + "norm2.bias"),
            "wqkv": rd(p + "attn.qkv.weight").T, "bqkv": rd(p + "attn.qkv.bias"),
            "wo": rd(p + "attn.proj.weight").T, "bo": rd(p + "attn.proj.bias"),
            "w1": rd(p + "mlp.fc1.weight").T, "b1": rd(p + "mlp.fc1.bias"),
            "w2": rd(p + "mlp.fc2.weight").T, "b2": rd(p + "mlp.fc2.bias"),
        }

    return {
        "patch_embed": jnp.asarray(conv.reshape(d, -1).T),
        "merger_ln": jnp.asarray(rd("merger.ln_q.weight")),
        "merger_ln_b": jnp.asarray(rd("merger.ln_q.bias")),
        "merger_w1": jnp.asarray(rd("merger.mlp.0.weight").T),
        "merger_b1": jnp.asarray(rd("merger.mlp.0.bias")),
        "merger_w2": jnp.asarray(rd("merger.mlp.2.weight").T),
        "merger_b2": jnp.asarray(rd("merger.mlp.2.bias")),
        "layers": jax.tree.map(
            lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
            *[layer(i) for i in range(n_layers)],
        ),
    }


def load_vlm(
    model_dir: str | pathlib.Path,
    *,
    mesh: jax.sharding.Mesh | None = None,
    dtype: Any | None = None,
    name: str | None = None,
    load_tower: bool = True,
):
    """LLaVA-style VLM checkpoint -> (text ModelConfig, VisionConfig,
    lm_params, vision_params). The LM half loads through the standard Llama
    mapping via a renamed-index view; the tower loads eagerly (it is small
    relative to the LM). VERDICT r3 item 4."""
    import json as _json

    from dynamo_tpu.models.vision import VisionConfig

    p = pathlib.Path(model_dir)
    config = _json.loads((p / "config.json").read_text())
    if "vision_config" not in config:
        raise ValueError(f"{model_dir}: not a VLM checkpoint (no vision_config)")
    tcfg = ModelConfig.from_hf(config, name=name or p.name)
    if dtype is not None:
        import dataclasses as _dc

        tcfg = _dc.replace(tcfg, dtype=str(jnp.dtype(dtype).name))
    index = CheckpointIndex(p)
    # The tower stays f32: it is tiny next to the LM and LayerNorm-heavy.
    # load_tower=False skips it entirely — in a multi-worker deployment only
    # the worker backing the encode service needs a tower copy.
    if config.get("model_type") == "qwen2_vl":
        from dynamo_tpu.models.qwen2_vl import Qwen2VLVisionConfig

        vcfg = Qwen2VLVisionConfig.from_hf(config)
        lm_index = _HiddenPrefixIndex(index, ("visual.", "model.visual."))
        lm_params = load_params(p, tcfg, mesh=mesh, dtype=dtype, index=lm_index)
        vision_params = load_qwen2vl_vision_params(index, dtype=np.float32) if load_tower else None
    else:
        vcfg = VisionConfig.from_hf_llava(config)
        lm_params = load_params(p, tcfg, mesh=mesh, dtype=dtype, index=_RenamedIndex(index))
        vision_params = load_vision_params(index, dtype=np.float32) if load_tower else None
    return tcfg, vcfg, lm_params, vision_params


def save_params(
    model_dir: str | pathlib.Path,
    cfg: ModelConfig,
    params: Params,
) -> None:
    """Write params as an HF-compatible checkpoint (config.json + safetensors).

    The exact inverse of ``load_params``: unstack layers, transpose back to
    torch ``[out, in]`` orientation, emit HF Llama/Qwen2(-MoE) names. Used by
    tests (round-trip) and by tooling that re-exports fine-tuned weights.
    """
    p = pathlib.Path(model_dir)
    p.mkdir(parents=True, exist_ok=True)
    hf_cfg: dict[str, Any] = {
        "architectures": ["LlamaForCausalLM"],
        "model_type": "llama",
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.hidden_size,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.num_kv_heads,
        "head_dim": cfg.head_dim,
        "intermediate_size": cfg.intermediate_size,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.rms_eps,
        "max_position_embeddings": cfg.max_position,
        "tie_word_embeddings": cfg.tie_embeddings,
        "torch_dtype": cfg.dtype,
    }
    if cfg.rope_scaling:
        hf_cfg["rope_scaling"] = cfg.rope_scaling
    hf_cfg["attention_bias"] = cfg.attention_bias
    if cfg.qk_norm:
        # qk_norm is reconstructed from model_type at load (from_hf): pin
        # the family whose modeling carries these norms so a save->load
        # round-trip keeps them (head: Qwen3; flat: OLMoE).
        if cfg.qk_norm == "head":
            hf_cfg["model_type"] = "qwen3_moe" if cfg.is_moe else "qwen3"
            hf_cfg["architectures"] = ["Qwen3MoeForCausalLM" if cfg.is_moe else "Qwen3ForCausalLM"]
        else:
            hf_cfg["model_type"] = "olmoe"
            hf_cfg["architectures"] = ["OlmoeForCausalLM"]
    # Gemma's math (GeGLU, (1+w) norms, scaled embeddings) is keyed off
    # model_type at load — a "llama"-typed save would silently reload with
    # silu/plain-norm math over Gemma weights. GGUF-sourced Gemma arrives
    # with norm_plus_one=False (llama.cpp bakes the +1 into the weights) but
    # still gelu_tanh/embed_scale, so ANY of the three marks the family.
    gemma_family = cfg.norm_plus_one or cfg.mlp_act == "gelu_tanh" or cfg.embed_scale
    if gemma_family:
        hf_cfg["model_type"] = "gemma"
        hf_cfg["architectures"] = ["GemmaForCausalLM"]
        hf_cfg["hidden_activation"] = "gelu_pytorch_tanh"
    if cfg.attn_type == "mla":
        hf_cfg.update(
            model_type="deepseek_v3",
            architectures=["DeepseekV3ForCausalLM"],
            q_lora_rank=cfg.q_lora_rank or None,
            kv_lora_rank=cfg.kv_lora_rank,
            qk_nope_head_dim=cfg.qk_nope_head_dim,
            qk_rope_head_dim=cfg.qk_rope_head_dim,
            v_head_dim=cfg.v_head_dim,
            rope_interleave=cfg.rope_interleave,
        )
    if cfg.is_moe:
        if cfg.attn_type != "mla" and not cfg.qk_norm:
            # MLA pinned deepseek_v3; qk_norm pinned qwen3_moe/olmoe above.
            hf_cfg["model_type"] = (
                "qwen2_moe" if cfg.shared_expert_gated or not cfg.shared_expert_size else "deepseek_v2"
            )
        hf_cfg.update(
            num_experts=cfg.num_experts,
            num_experts_per_tok=cfg.num_experts_per_token,
            moe_intermediate_size=cfg.moe_intermediate_size,
            scoring_func=cfg.moe_scoring,
            norm_topk_prob=cfg.moe_norm_topk,
            routed_scaling_factor=cfg.moe_routed_scaling,
        )
        if cfg.moe_n_group:
            hf_cfg.update(n_group=cfg.moe_n_group, topk_group=cfg.moe_topk_group)
        if cfg.moe_router_bias:
            hf_cfg["topk_method"] = "noaux_tc"
        if cfg.first_k_dense:
            hf_cfg["first_k_dense_replace"] = cfg.first_k_dense
        if cfg.shared_expert_size:
            if cfg.shared_expert_gated:
                hf_cfg["shared_expert_intermediate_size"] = cfg.shared_expert_size
            else:
                hf_cfg["n_shared_experts"] = cfg.shared_expert_size // cfg.moe_intermediate_size
    (p / "config.json").write_text(json.dumps(hf_cfg, indent=2))

    tensors: dict[str, np.ndarray] = {}

    def put(name: str, arr, transpose: bool, row_perm: np.ndarray | None = None) -> None:
        a = np.asarray(arr)
        if transpose:
            a = a.T
        if row_perm is not None:  # half-split -> checkpoint (interleaved) order
            a = a[row_perm]
        tensors[name] = np.ascontiguousarray(a)

    # HF Gemma checkpoints store ZERO-CENTERED norm weights (runtime adds
    # +1). GGUF-sourced params carry the +1 baked in (norm_plus_one=False),
    # so saving them under model_type=gemma must subtract it back out or the
    # reload (which re-adds 1) would double-shift every norm.
    def zero_center(a):
        a = np.asarray(a)
        return (a.astype(np.float32) - 1.0).astype(a.dtype)

    shift_norms = gemma_family and not cfg.norm_plus_one

    put("model.embed_tokens.weight", params["embed"], False)
    put("model.norm.weight",
        zero_center(params["norm_f"]) if shift_norms else params["norm_f"], False)
    if not cfg.tie_embeddings and "lm_head" in params:
        put("lm_head.weight", params["lm_head"], True)
    def write_subtree(lp, l0: int, count: int, moe: bool) -> None:
        for li in range(count):
            base = f"model.layers.{li + l0}."
            for leaf, (suffixes, transpose) in _LAYER_MAP.items():
                if moe and leaf in _MOE_EXPERT_MAP:
                    continue
                if cfg.attn_type == "mla" and leaf in ("wq", "wk", "wv", "wo"):
                    continue
                arr = lp[leaf][li]
                if shift_norms and leaf in ("attn_norm", "mlp_norm"):
                    arr = zero_center(arr)
                put(base + suffixes[0], arr, transpose)
            if cfg.qk_norm and cfg.attn_type != "mla":
                put(base + "self_attn.q_norm.weight", lp["q_norm"][li], False)
                put(base + "self_attn.k_norm.weight", lp["k_norm"][li], False)
            if cfg.attn_type == "mla":
                q_sperm = kv_sperm = None
                if cfg.rope_interleave:
                    q_sperm = rope_save_perm(
                        cfg.num_heads, cfg.qk_nope_head_dim + cfg.qk_rope_head_dim, cfg.qk_rope_head_dim
                    )
                    kv_sperm = rope_save_perm(
                        1, cfg.kv_lora_rank + cfg.qk_rope_head_dim, cfg.qk_rope_head_dim
                    )
                for leaf, (suffixes, transpose) in _MLA_MAP.items():
                    if leaf in lp:
                        sperm = {"w_q_b": q_sperm, "w_q": q_sperm, "w_kv_a": kv_sperm}.get(leaf)
                        put(base + suffixes[0], lp[leaf][li], transpose, row_perm=sperm)
                # kv_b_proj: interleave per-head [K_nope; V] row blocks
                uk = np.asarray(lp["w_uk"][li])  # [r_kv, H, dn]
                uv = np.asarray(lp["w_uv"][li])  # [r_kv, H, dv]
                per_head = np.concatenate(
                    [np.transpose(uk, (1, 2, 0)), np.transpose(uv, (1, 2, 0))], axis=1
                )  # [H, dn+dv, r_kv]
                put(base + "self_attn.kv_b_proj.weight", per_head.reshape(-1, per_head.shape[-1]), False)
            if cfg.attention_bias:
                for leaf, (suffixes, transpose) in _BIAS_MAP.items():
                    put(base + suffixes[0], lp[leaf][li], transpose)
            if moe:
                put(base + _MOE_ROUTER[0], lp["router"][li], True)
                if "router_bias" in lp:
                    put(base + _MOE_ROUTER_BIAS[0], lp["router_bias"][li], False)
                for leaf, (suffixes, transpose) in _MOE_EXPERT_MAP.items():
                    for e in range(cfg.num_experts):
                        put(base + suffixes[0].format(e=e), lp[leaf][li, e], transpose)
                if cfg.shared_expert_size:
                    src = 0 if cfg.shared_expert_gated else 1
                    for leaf, (suffixes, transpose) in _SHARED_EXPERT_MAP.items():
                        put(base + suffixes[src], lp[leaf][li], transpose)
                    if cfg.shared_expert_gated:
                        put(base + _SHARED_GATE[0], lp["shared_gate"][li], True)

    k_dense = cfg.first_k_dense if cfg.is_moe else 0
    if k_dense:
        write_subtree(params["dense_layers"], 0, k_dense, False)
    write_subtree(params["layers"], k_dense, cfg.num_layers - k_dense, cfg.is_moe)

    from safetensors.numpy import save_file

    save_file(tensors, str(p / "model.safetensors"))
    index = {"metadata": {"total_size": sum(t.nbytes for t in tensors.values())},
             "weight_map": {k: "model.safetensors" for k in tensors}}
    (p / "model.safetensors.index.json").write_text(json.dumps(index))
